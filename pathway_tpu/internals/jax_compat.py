"""Version-portable JAX surface.

The accelerator modules (``parallel/exchange``, ``ops/knn``,
``models/ring_attention``) were written against the modern top-level
``jax.shard_map`` API (``check_vma=`` keyword). Older JAX releases (the
0.4.x line baked into some environments) only ship
``jax.experimental.shard_map.shard_map`` with the keyword spelled
``check_rep=``. This shim resolves whichever exists at import time and
translates the keyword, so one call site works on both — and capability
probing (``shard_map_available()``) is a function of this module, not a
scattered try/except per caller.
"""

from __future__ import annotations

import functools
from typing import Any

__all__ = [
    "shard_map",
    "shard_map_available",
    "shard_map_unavailable_reason",
    "enable_cpu_collectives",
    "multihost_cpu_supported",
]

_IMPL: Any = None
_NEEDS_CHECK_REP = False
_REASON: str | None = None

try:  # modern API (jax >= 0.5): top-level export, check_vma keyword
    from jax import shard_map as _IMPL  # type: ignore[attr-defined]
except ImportError:
    try:  # legacy API (jax 0.4.x): experimental module, check_rep keyword
        from jax.experimental.shard_map import shard_map as _IMPL

        _NEEDS_CHECK_REP = True
    except ImportError as e:  # pragma: no cover - no shard_map at all
        _IMPL = None
        _REASON = f"jax provides no shard_map implementation: {e}"


def shard_map_available() -> bool:
    """Whether ANY shard_map implementation exists in this environment."""
    return _IMPL is not None


def shard_map_unavailable_reason() -> str:
    return _REASON or "shard_map is available"


def enable_cpu_collectives() -> bool:
    """Arm gloo TCP collectives on the CPU backend (required for ANY
    multiprocess computation there — XLA's default CPU client refuses
    them outright). Must run before the first backend/distributed-client
    creation; harmless no-op on TPU/GPU or when the config knob or gloo
    build is absent. Returns whether CPU collectives are armed."""
    import jax

    try:
        # NB: attribute-style reads of this option raise on the 0.4.x
        # line; the values mapping + update() are the portable surface
        if jax.config.values.get("jax_cpu_collectives_implementation") == "gloo":
            return True
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        return True
    except Exception:
        return False


def multihost_cpu_supported() -> tuple[bool, str]:
    """Whether this environment can run multiprocess computations on the
    CPU backend — (ok, reason). Capability probe for tests: a False here
    means 'skip with this reason', not 'xfail and hope'."""
    try:
        import jaxlib.xla_extension as xe

        if not hasattr(xe, "make_gloo_tcp_collectives"):
            return False, (
                "jaxlib built without gloo TCP collectives: multiprocess "
                "computations are unimplemented on the default CPU client"
            )
    except ImportError as e:
        return False, f"jaxlib.xla_extension unavailable: {e}"
    import jax

    if "jax_cpu_collectives_implementation" not in jax.config.values:
        return False, (
            "jax.config lacks jax_cpu_collectives_implementation: cannot "
            "arm gloo CPU collectives on this jax version"
        )
    return True, "gloo CPU collectives available"


def shard_map(f: Any = None, **kwargs: Any) -> Any:
    """``jax.shard_map`` with the keyword dialect of the installed JAX.

    Usable directly or via ``functools.partial(shard_map, mesh=...)`` the
    way every call site in this repo does."""
    if _IMPL is None:
        raise ImportError(shard_map_unavailable_reason())
    if _NEEDS_CHECK_REP and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    if f is None:
        return functools.partial(shard_map, **kwargs)
    return _IMPL(f, **kwargs)
