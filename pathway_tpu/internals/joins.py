"""Joinable / JoinResult — join desugaring.

Re-design of ``python/pathway/internals/joins.py`` (1,422 LoC; ``Joinable``
:46, ``JoinResult`` :135). A JoinResult holds both sides + equality
conditions; ``.select()``/``.reduce()`` produce concrete tables lowered to
the engine's incremental Join operator (dataflow.rs:2270).
"""

from __future__ import annotations

import copy
import enum
from typing import Any

from . import dtype as dt
from .expression import (
    ColumnBinaryOpExpression,
    ColumnExpression,
    ColumnReference,
    IdReference,
    smart_coerce,
)
from .parse_graph import Universe
from .schema import ColumnSchema, schema_from_columns
from .thisclass import ThisPlaceholder, left, right, substitute, this


class JoinMode(enum.Enum):
    INNER = "inner"
    LEFT = "left"
    RIGHT = "right"
    OUTER = "outer"


class JoinResult:
    def __init__(self, left_table, right_table, on: tuple, mode: JoinMode, id: Any = None):
        from .table import Table

        self._left = left_table
        self._right = right_table
        self._mode = mode
        self._id = id
        self._left_on: list[ColumnExpression] = []
        self._right_on: list[ColumnExpression] = []
        for cond in on:
            lexpr, rexpr = self._split_condition(cond)
            self._left_on.append(lexpr)
            self._right_on.append(rexpr)

    def _split_condition(self, cond: Any):
        if not isinstance(cond, ColumnBinaryOpExpression) or cond._op != "==":
            raise ValueError("join conditions must be equality expressions (a == b)")
        lexpr = substitute(cond._left, {left: self._left, right: self._right})
        rexpr = substitute(cond._right, {left: self._left, right: self._right})
        lside = _side_of(lexpr, self._left, self._right)
        rside = _side_of(rexpr, self._left, self._right)
        if lside == "right" or rside == "left":
            lexpr, rexpr = rexpr, lexpr
            lside, rside = rside, lside
        if lside != "left" or rside != "right":
            raise ValueError(
                "each join condition must reference the left table on one side "
                "and the right table on the other"
            )
        return lexpr, rexpr

    def _resolve(self, expr: ColumnExpression) -> ColumnExpression:
        """Rewrite pw.this/pw.left/pw.right and JoinResult refs to the
        underlying tables."""
        expr = substitute(
            smart_coerce(expr), {left: self._left, right: self._right, this: self}
        )
        return _replace_jr_refs(expr, self)

    def _lookup(self, name: str) -> ColumnReference:
        in_left = name in self._left.schema.__columns__
        in_right = name in self._right.schema.__columns__
        if in_left and in_right:
            raise ValueError(
                f"column {name!r} exists on both sides of the join; "
                "use pw.left / pw.right to disambiguate"
            )
        if in_left:
            return ColumnReference(self._left, name)
        if in_right:
            return ColumnReference(self._right, name)
        raise AttributeError(f"join result has no column {name!r}")

    def __getattr__(self, name: str) -> ColumnReference:
        if name.startswith("_"):
            raise AttributeError(name)
        return self._lookup(name)

    def __getitem__(self, name: str) -> ColumnReference:
        return self._lookup(name)

    def select(self, *args: Any, **kwargs: Any):
        from .table import Table

        from .thisclass import ThisWithout, left as left_ph, right as right_ph

        exprs: dict[str, ColumnExpression] = {}
        flat: list[Any] = []
        for arg in args:
            if isinstance(arg, ThisWithout):
                # pw.left/pw.right wildcards expand against their side;
                # bare pw.this expands against the left (reference join
                # desugaring binds this to the join's row namespace)
                side = (
                    self._right if arg.placeholder is right_ph else self._left
                )
                flat.extend(
                    ColumnReference(side, n)
                    for n in side.column_names()
                    if n not in arg.excluded
                )
            else:
                flat.append(arg)
        for arg in flat:
            resolved = self._resolve(arg)
            if not isinstance(resolved, ColumnReference):
                raise ValueError("positional select args must be column references")
            exprs[resolved.name] = resolved
        for name, e in kwargs.items():
            exprs[name] = self._resolve(e)

        schema = _join_select_schema(self, exprs)
        id_side = None
        if self._id is not None:
            id_expr = self._resolve(self._id)
            if not isinstance(id_expr, IdReference):
                raise ValueError("join id= must be pw.left.id or pw.right.id")
            id_side = "left" if id_expr.table is self._left else "right"
        # id=pw.left.id with a LEFT join emits exactly one row per left row
        # under the reference's uniqueness contract ("result.id == left.id";
        # duplicate matches are a runtime error) — so the output IS the
        # id-side universe, and downstream zips need no promise
        # (symmetrically for RIGHT joins keyed by the right side)
        if id_side == "left" and self._mode == JoinMode.LEFT:
            universe = self._left._universe
        elif id_side == "right" and self._mode == JoinMode.RIGHT:
            universe = self._right._universe
        else:
            universe = Universe()
        return Table(
            "join_select",
            [self._left, self._right],
            {
                "left_on": self._left_on,
                "right_on": self._right_on,
                "mode": self._mode.value,
                "exprs": exprs,
                "id_side": id_side,
                "asof_now": getattr(self, "_asof_now", False),
            },
            schema,
            universe,
        )

    def reduce(self, *args: Any, **kwargs: Any):
        full = self.select(
            **{
                n: self._lookup(n)
                for n in set(self._left.column_names()) ^ set(self._right.column_names())
            }
        )
        return full.reduce(*args, **kwargs)

    def groupby(self, *args: Any, **kwargs: Any):
        cols = {}
        for n in self._left.column_names():
            if n not in self._right.schema.__columns__:
                cols[n] = ColumnReference(self._left, n)
        for n in self._right.column_names():
            if n not in self._left.schema.__columns__:
                cols[n] = ColumnReference(self._right, n)
        full = self.select(**cols)
        new_args = [getattr(full, a.name) if isinstance(a, ColumnReference) else a for a in args]
        return full.groupby(*new_args, **kwargs)

    def filter(self, expression: Any):
        raise NotImplementedError("filter on JoinResult: select first, then filter")


def _side_of(expr: ColumnExpression, left_table, right_table) -> str | None:
    found: set[str] = set()

    def walk(e):
        if isinstance(e, ColumnReference):
            if e.table is left_table:
                found.add("left")
            elif e.table is right_table:
                found.add("right")
            elif isinstance(e.table, ThisPlaceholder):
                raise ValueError("unresolved placeholder in join condition")
        for d in getattr(e, "_deps", ()):
            walk(d)

    walk(expr)
    if found == {"left"}:
        return "left"
    if found == {"right"}:
        return "right"
    return None


def _replace_jr_refs(expr: ColumnExpression, jr: JoinResult) -> ColumnExpression:
    from .expression import SelfKeysExpression

    if isinstance(expr, IdReference):
        if expr.table is jr:
            return SelfKeysExpression()  # the joined row's own key
        return expr
    if isinstance(expr, ColumnReference):
        if expr.table is jr:
            return jr._lookup(expr.name)
        return expr
    if not getattr(expr, "_deps", ()):
        return expr
    clone = copy.copy(expr)
    for attr, value in list(vars(clone).items()):
        if isinstance(value, ColumnExpression):
            setattr(clone, attr, _replace_jr_refs(value, jr))
        elif isinstance(value, tuple) and any(isinstance(v, ColumnExpression) for v in value):
            setattr(clone, attr, tuple(
                _replace_jr_refs(v, jr) if isinstance(v, ColumnExpression) else v
                for v in value
            ))
    return clone


def _join_select_schema(jr: JoinResult, exprs: dict[str, ColumnExpression]):
    from .expression_compiler import ColumnEnv, infer_dtype

    env = ColumnEnv()
    env.add_table(jr._left, prefix="l.")
    env.add_table(jr._right, prefix="r.")
    env.add(jr, "id", None, dt.POINTER)
    mode = jr._mode
    l_opt = mode in (JoinMode.RIGHT, JoinMode.OUTER)
    r_opt = mode in (JoinMode.LEFT, JoinMode.OUTER)
    cols = {}
    for name, e in exprs.items():
        d = infer_dtype(_prefix_refs(e, jr), env)
        side = _side_of(e, jr._left, jr._right)
        if (side == "left" and l_opt) or (side == "right" and r_opt):
            d = dt.Optional(d)
        cols[name] = ColumnSchema(name=name, dtype=d)
    return schema_from_columns(cols, name="Joined")


def _prefix_refs(expr: ColumnExpression, jr: JoinResult) -> ColumnExpression:
    """For typing only: the env above registered prefixed names; references
    resolve by table identity so no rewrite is actually needed."""
    return expr


class Joinable:
    pass
