"""OTLP telemetry push (reference ``src/engine/telemetry.rs:63-156``).

The tracer (``tracing.py``) records spans and counter samples locally;
this module exports them over OTLP/HTTP JSON to a collector when
``PATHWAY_TELEMETRY_SERVER`` (spans + metrics, the usage-telemetry role)
or ``PATHWAY_MONITORING_SERVER`` (operator monitoring) is set — the same
two-endpoint split as the reference's TelemetryConfig
(``telemetry.rs:180-221``). OTLP/gRPC needs the opentelemetry SDK (not
baked into this environment); OTLP/HTTP JSON is part of the OTLP spec and
needs only ``urllib``, so the export path is fully local-testable against
a loopback collector. Export never raises: telemetry must not fail the
run it observes.

Resource attributes mirror ``telemetry.rs:63-74``: service.name/version,
service.instance.id, run.id.
"""

from __future__ import annotations

import json
import os
import secrets
import time
from typing import Any

__all__ = ["OtlpExporter", "export_from_env"]

_EXPORT_TIMEOUT_S = 10.0


def _hex_id(n_bytes: int) -> str:
    return secrets.token_hex(n_bytes)


class OtlpExporter:
    """Convert tracer events to OTLP/HTTP JSON and POST them.

    Spans (Chrome ``ph: X`` duration events) go to ``/v1/traces`` as one
    scope-span batch under a fresh trace id; counter samples (``ph: C``)
    go to ``/v1/metrics`` as gauge points.
    """

    def __init__(self, endpoint: str, *, service_name: str = "pathway_tpu",
                 run_id: str | None = None):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.run_id = run_id or _hex_id(8)
        self.trace_id = _hex_id(16)

    # -- payload building -------------------------------------------------

    def _resource(self) -> dict:
        from .. import __version__

        attrs = {
            "service.name": self.service_name,
            "service.version": __version__,
            "service.instance.id": f"{os.getpid()}@{os.uname().nodename}",
            "run.id": self.run_id,
        }
        return {
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in attrs.items()
            ]
        }

    @staticmethod
    def _attr_value(v: Any) -> dict:
        if isinstance(v, bool):
            return {"boolValue": v}
        if isinstance(v, int):
            return {"intValue": str(v)}
        if isinstance(v, float):
            return {"doubleValue": v}
        return {"stringValue": str(v)}

    def spans_payload(self, events: list[dict], origin_unix_ns: int) -> dict:
        """ExportTraceServiceRequest for the tracer's duration events.
        ``origin_unix_ns`` anchors the tracer's relative µs timestamps."""
        spans = []
        for ev in events:
            if ev.get("ph") != "X":
                continue
            start = origin_unix_ns + int(ev["ts"] * 1e3)
            end = start + int(ev.get("dur", 0.0) * 1e3)
            span = {
                "traceId": self.trace_id,
                "spanId": _hex_id(8),
                "name": ev["name"],
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(start),
                "endTimeUnixNano": str(end),
            }
            args = ev.get("args") or {}
            if args:
                span["attributes"] = [
                    {"key": k, "value": self._attr_value(v)}
                    for k, v in args.items()
                ]
            spans.append(span)
        return {
            "resourceSpans": [{
                "resource": self._resource(),
                "scopeSpans": [{
                    "scope": {"name": "pathway_tpu.tracing"},
                    "spans": spans,
                }],
            }]
        }

    def metrics_payload(self, events: list[dict], origin_unix_ns: int) -> dict:
        """ExportMetricsServiceRequest: counter samples become gauges."""
        series: dict[str, list[dict]] = {}
        for ev in events:
            if ev.get("ph") != "C":
                continue
            t = str(origin_unix_ns + int(ev["ts"] * 1e3))
            for field, value in (ev.get("args") or {}).items():
                name = f"{ev['name']}.{field}" if field != "value" else ev["name"]
                series.setdefault(name, []).append({
                    "timeUnixNano": t,
                    "asDouble": float(value),
                })
        metrics = [
            {"name": name, "gauge": {"dataPoints": points}}
            for name, points in series.items()
        ]
        return {
            "resourceMetrics": [{
                "resource": self._resource(),
                "scopeMetrics": [{
                    "scope": {"name": "pathway_tpu.tracing"},
                    "metrics": metrics,
                }],
            }]
        }

    def histograms_payload(
        self,
        points: list[tuple[str, dict, dict]],
        time_unix_nano: int,
    ) -> dict:
        """ExportMetricsServiceRequest for engine histogram snapshots
        (observability/histogram.py log2 buckets → OTLP explicit-bounds
        histogram data points, cumulative temporality)."""
        metrics = []
        for name, attrs, snap in points:
            counts = snap["counts"]
            nonzero = [i for i, c in enumerate(counts) if c]
            if nonzero:
                lo, hi = nonzero[0], nonzero[-1]
                # bounds in seconds; bucket i upper bound is 2^i ns
                bounds = [(1 << i) / 1e9 for i in range(lo, hi + 1)]
                bucket_counts = (
                    [str(sum(counts[: lo]) + counts[lo])]
                    + [str(counts[i]) for i in range(lo + 1, hi + 1)]
                    + ["0"]  # overflow bucket beyond the occupied range
                )
            else:
                bounds = []
                bucket_counts = [str(snap["count"])]
            point = {
                "timeUnixNano": str(time_unix_nano),
                "count": str(snap["count"]),
                "sum": snap["sum"] / 1e9,
                "bucketCounts": bucket_counts,
                "explicitBounds": bounds,
            }
            if attrs:
                point["attributes"] = [
                    {"key": k, "value": self._attr_value(v)}
                    for k, v in attrs.items()
                ]
            metrics.append({
                "name": name,
                "histogram": {
                    "aggregationTemporality": 2,  # CUMULATIVE
                    "dataPoints": [point],
                },
            })
        return {
            "resourceMetrics": [{
                "resource": self._resource(),
                "scopeMetrics": [{
                    "scope": {"name": "pathway_tpu.observability"},
                    "metrics": metrics,
                }],
            }]
        }

    # -- transport --------------------------------------------------------

    def _post(self, path: str, payload: dict) -> bool:
        import urllib.request

        req = urllib.request.Request(
            self.endpoint + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=_EXPORT_TIMEOUT_S) as r:
                return 200 <= r.status < 300
        except Exception:
            return False

    def export(self, tracer: Any) -> dict[str, bool]:
        """Push the tracer's current buffer; returns per-signal success.

        One-shot/diagnostic surface only: it ignores the ``_otlp_mark``
        cursor, so mixing it with the periodic flusher would double-export
        — incremental callers go through ``export_events`` +
        ``Tracer.events_since`` instead."""
        with tracer._lock:
            events = list(tracer._events)
            origin = tracer._origin
        # anchor relative timestamps to the wall clock NOW minus the
        # monotonic distance to each event (close enough for telemetry)
        origin_unix_ns = time.time_ns() - (time.perf_counter_ns() - origin)
        return self.export_events(events, origin_unix_ns)

    def export_events(
        self, events: list[dict], origin_unix_ns: int
    ) -> dict[str, bool]:
        """Push a specific event slice (the periodic flusher's incremental
        path — it exports only events_since the shared cursor)."""
        out = {}
        spans = self.spans_payload(events, origin_unix_ns)
        if spans["resourceSpans"][0]["scopeSpans"][0]["spans"]:
            out["traces"] = self._post("/v1/traces", spans)
        metrics = self.metrics_payload(events, origin_unix_ns)
        if metrics["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]:
            out["metrics"] = self._post("/v1/metrics", metrics)
        return out

    def export_histograms(
        self, points: list[tuple[str, dict, dict]], time_unix_nano: int
    ) -> bool:
        payload = self.histograms_payload(points, time_unix_nano)
        if not payload["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]:
            return True
        return self._post("/v1/metrics", payload)


def export_from_env(tracer: Any | None) -> None:
    """End-of-run hook: push to PATHWAY_TELEMETRY_SERVER and/or
    PATHWAY_MONITORING_SERVER when set. Idempotent per buffer state (the
    hook sits at several run exits) and never raises. Shares the
    ``_otlp_mark`` cursor with the periodic flusher
    (observability/exporter.py), so only the tail appended since the last
    periodic push goes out here."""
    if tracer is None:
        return
    endpoints = [
        os.environ.get("PATHWAY_TELEMETRY_SERVER"),
        os.environ.get("PATHWAY_MONITORING_SERVER"),
    ]
    eps = {e for e in endpoints if e}
    if not eps:
        return
    events, mark = tracer.events_since(tracer._otlp_mark)
    if not events:
        return
    tracer._otlp_mark = mark
    origin_unix_ns = time.time_ns() - (
        time.perf_counter_ns() - tracer._origin
    )
    for ep in eps:
        try:
            OtlpExporter(ep).export_events(events, origin_unix_ns)
        except Exception:
            pass
