"""Universe solver — propositional reasoning over key-set relations.

Re-design of the reference's SAT-based solver
(``python/pathway/internals/universe_solver.py``): each universe is a
propositional variable ("an arbitrary fixed key is in this set"); subset is
the implication clause ¬A∨B, disjointness ¬A∨¬B, union/intersection/
difference add their defining clauses; a query holds iff its negation is
unsatisfiable (``query_is_subset(A,B)`` ⇔ {A, ¬B} UNSAT). The reference
delegates to python-sat; this environment has no SAT library, so a small
DPLL solver with unit propagation lives here — the clause databases involved
(a few variables per Table operation) are tiny.
"""

from __future__ import annotations

from typing import Any, Iterable

__all__ = ["UniverseSolver"]


class UniverseSolver:
    def __init__(self) -> None:
        self._vars: dict[Any, int] = {}  # universe -> positive literal
        self.clauses: list[tuple[int, ...]] = []
        #: clauses derivable from table structure alone (no user promises):
        #: proofs over these need no runtime re-verification
        self.structural_clauses: list[tuple[int, ...]] = []
        self._query_cache: dict[tuple, bool] = {}

    # -- variables / clauses ----------------------------------------------

    def var(self, universe: Any) -> int:
        v = self._vars.get(universe)
        if v is None:
            v = len(self._vars) + 1
            self._vars[universe] = v
        return v

    def add_clause(self, lits: Iterable[int], *, promised: bool = False) -> None:
        clause = tuple(lits)
        self.clauses.append(clause)
        if not promised:
            self.structural_clauses.append(clause)
        self._query_cache.clear()

    # -- registration (reference universe_solver.py API) -------------------

    def register_as_subset(self, subset: Any, superset: Any,
                           *, promised: bool = False) -> None:
        a, b = self.var(subset), self.var(superset)
        self.add_clause([-a, b], promised=promised)  # A => B

    def register_as_equal(self, left: Any, right: Any,
                          *, promised: bool = False) -> None:
        self.register_as_subset(left, right, promised=promised)
        self.register_as_subset(right, left, promised=promised)

    def register_as_disjoint(self, *args: Any, promised: bool = False) -> None:
        vs = [self.var(a) for a in args]
        for i in range(len(vs)):
            for j in range(i):
                self.add_clause([-vs[i], -vs[j]], promised=promised)  # Ai => ¬Aj

    def register_as_difference(self, result: Any, left: Any, right: Any) -> None:
        """result = left - right."""
        self.register_as_subset(result, left)
        self.register_as_disjoint(result, right)
        r, a, b = self.var(result), self.var(left), self.var(right)
        self.add_clause([r, -a, b])  # (A ∧ ¬B) => R

    def register_as_intersection(self, result: Any, *args: Any) -> None:
        for arg in args:
            self.register_as_subset(result, arg)
        r = self.var(result)
        vs = [self.var(a) for a in args]
        self.add_clause([r, *[-v for v in vs]])  # (A1 ∧ A2 ∧ …) => R

    def register_as_union(self, result: Any, *args: Any) -> None:
        for arg in args:
            self.register_as_subset(arg, result)
        r = self.var(result)
        vs = [self.var(a) for a in args]
        self.add_clause([-r, *vs])  # R => (A1 ∨ A2 ∨ …)

    # -- queries -----------------------------------------------------------

    def query_is_subset(self, subset: Any, superset: Any) -> bool:
        key = ("sub", self.var(subset), self.var(superset))
        hit = self._query_cache.get(key)
        if hit is None:
            # A ⊆ B ⇔ {A, ¬B} unsatisfiable
            hit = not self._solve((key[1], -key[2]))
            self._query_cache[key] = hit
        return hit

    def query_are_equal(self, a: Any, b: Any) -> bool:
        return self.query_is_subset(a, b) and self.query_is_subset(b, a)

    def query_are_disjoint(self, *args: Any, structural_only: bool = False) -> bool:
        """``structural_only=True`` ignores promise clauses: a True result
        is then a *proof* (no runtime verification needed), not trust."""
        vs = [self.var(a) for a in args]
        for i in range(len(vs)):
            for j in range(i):
                key = ("dis", structural_only, *sorted((vs[i], vs[j])))
                hit = self._query_cache.get(key)
                if hit is None:
                    hit = not self._solve(
                        (vs[i], vs[j]), structural_only=structural_only
                    )
                    self._query_cache[key] = hit
                if not hit:
                    return False
        return True

    def query_is_empty(self, a: Any) -> bool:
        return not self._solve((self.var(a),))

    # -- the DPLL core ------------------------------------------------------

    def _solve(
        self, assumptions: tuple[int, ...], *, structural_only: bool = False
    ) -> bool:
        """Satisfiability of the clause DB under the given literal
        assumptions. DPLL: unit-propagate, then split on a variable of the
        first unresolved clause."""
        assign: dict[int, bool] = {}
        for lit in assumptions:
            val = lit > 0
            if assign.setdefault(abs(lit), val) != val:
                return False
        db = self.structural_clauses if structural_only else self.clauses
        return self._dpll(db, assign)

    def _dpll(self, clauses: list[tuple[int, ...]], assign: dict[int, bool]) -> bool:
        while True:
            unit: int | None = None
            pending: list[tuple[int, ...]] = []
            for clause in clauses:
                satisfied = False
                unassigned: list[int] = []
                for lit in clause:
                    val = assign.get(abs(lit))
                    if val is None:
                        unassigned.append(lit)
                    elif (lit > 0) == val:
                        satisfied = True
                        break
                if satisfied:
                    continue
                if not unassigned:
                    return False  # conflict
                if len(unassigned) == 1 and unit is None:
                    unit = unassigned[0]
                pending.append(clause)
            if unit is not None:
                assign[abs(unit)] = unit > 0
                clauses = pending
                continue
            if not pending:
                return True  # every clause satisfied
            # split on the first unassigned literal of the first open clause
            for lit in pending[0]:
                if abs(lit) not in assign:
                    branch = abs(lit)
                    break
            for val in (True, False):
                trial = dict(assign)
                trial[branch] = val
                if self._dpll(pending, trial):
                    return True
            return False
