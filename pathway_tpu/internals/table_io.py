"""Static / scheduled table construction (datasource layer).

Re-design of ``internals/table_io.py`` + ``datasource.py``: static tables
become engine StaticSource batches; definitions with ``__time__``/``__diff__``
columns become ScheduledSource schedules (the debug/stream-generator path).

Key derivation rules (match the reference's observable behavior):
- explicit integer ``id`` column → deterministic pointer per id
  (``unsafe_trusted_ids``, debug/__init__.py + python_api key for_value);
- ``id_from`` columns → pointer from those values (``Key::for_values``);
- otherwise content-fingerprint + row sequence, so identical definitions
  produce identical keys (what makes id-sensitive equality asserts work —
  reference caches static tables by content, debug/__init__.py:396-403).
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from ..engine import keys as K
from ..engine.delta import column_of_values
from . import dtype as dt
from .parse_graph import G, Universe
from .schema import ColumnSchema, SchemaMetaclass, schema_from_columns
from .table import Table


#: python types whose dtype depends only on the TYPE, so a column scan can
#: dedupe by set(map(type, ...)) (C-speed) instead of per-value inference —
#: datetimes excluded (naive vs utc depends on tzinfo), tuples excluded
#: (Tuple(args) depends on the content)
_TYPE_ONLY_DTYPES: dict[type, dt.DType] = {
    t: dt._FROM_PY[t]
    for t in (str, bool, int, float, bytes, type(None), dict)
}


def _infer_dtype_of_column(arr: "np.ndarray", vals: list) -> dt.DType:
    if arr.dtype == np.int64:
        return dt.INT
    if arr.dtype == np.float64:
        return dt.FLOAT
    if arr.dtype == np.bool_:
        return dt.BOOL
    if not vals:
        return dt.ANY
    types = set(map(type, vals))
    if all(t in _TYPE_ONLY_DTYPES for t in types):
        return dt.types_lca_many([_TYPE_ONLY_DTYPES[t] for t in types])
    # mixed/complex values (tuples, datetimes, arrays): per-value inference
    return dt.types_lca_many([dt.dtype_of_value(v) for v in vals])


def _infer_dtypes(
    names: list[str], data: dict[str, "np.ndarray"]
) -> dict[str, dt.DType]:
    return {
        name: _infer_dtype_of_column(
            data[name],
            list(data[name]) if data[name].dtype == object else [],
        )
        for name in names
    }


def _coerce_column(col: np.ndarray, target: dt.DType) -> np.ndarray:
    """Coerce parsed values to the declared schema dtype (reference: schema-
    driven conversion in table_from_pandas / connector parsers)."""
    u = dt.unoptionalize(target)
    conv = None
    if u == dt.STR:
        conv = str
    elif u == dt.INT:
        conv = int
    elif u == dt.FLOAT:
        conv = float
    elif u == dt.BOOL:
        conv = bool
    if conv is not None:
        if col.dtype == object or (u == dt.STR and col.dtype != object):
            out = np.empty(len(col), dtype=object)
            for i, v in enumerate(col):
                if isinstance(v, np.generic):
                    v = v.item()
                out[i] = None if v is None else conv(v)
            col = out
        elif u == dt.FLOAT and col.dtype == np.int64:
            return col.astype(np.float64)
        elif u == dt.INT and col.dtype == np.float64:
            return col.astype(np.int64)
        else:
            return col
    if col.dtype == object and not target.is_optional and target.numpy_dtype != np.dtype(object):
        try:
            return col.astype(target.numpy_dtype)
        except (ValueError, TypeError, OverflowError):
            # e.g. a python int beyond int64: the engine's general paths
            # handle big ints exactly as objects (vs the reference's hard
            # i64 Value::Int) — degrade, don't crash ingestion
            return col
    return col


def rows_to_table(
    names: list[str],
    rows: list[tuple],
    *,
    id_values: list[int] | None = None,
    id_from: Sequence[str] | None = None,
    schema: SchemaMetaclass | None = None,
    times: list[int] | None = None,
    diffs: list[int] | None = None,
) -> Table:
    """Build a static (or scheduled, when times given) table from rows."""
    if schema is not None:
        dtypes = schema.dtypes()
        names = [n for n in names if n in dtypes] + [n for n in dtypes if n not in names]
        col_order = list(dtypes.keys())
        if id_from is None:
            id_from = schema.primary_key_columns()
    else:
        col_order = names

    n = len(rows)
    _ix = {name: names.index(name) for name in col_order}
    data = {
        name: column_of_values([r[_ix[name]] for r in rows])
        for name in col_order
    }
    if schema is None:
        # infer from the BUILT columns: dense dtypes read off the array,
        # object columns dedupe by value type — O(distinct types), not
        # O(rows) python-level dtype_of_value calls
        dtypes = _infer_dtypes(col_order, data)
    for name in col_order:
        data[name] = _coerce_column(data[name], dtypes[name])

    if id_values is not None:
        keys = K.pointer_from_ints(np.asarray(id_values, dtype=np.int64))
    elif id_from:
        keys = K.mix_columns([data[c] for c in id_from], n)
    elif times is not None:
        # update streams: a __diff__=-1 row must retract the key of the
        # matching earlier insert, so keys derive from row CONTENT
        # (reference: content-fingerprint ids in table_from_pandas,
        # debug/__init__.py:380-384)
        keys = K.mix_columns([data[c] for c in col_order], n)
    else:
        # row-ordinal ids, exactly the reference's unindexed-table rule
        # (ids hash pandas' RangeIndex, debug/__init__.py:373-375): the
        # Nth row of ANY unindexed static table gets the same id as an
        # explicit integer index N. Content-independent — so a table
        # derived by select() compares index-equal to a freshly built
        # expected table, the contract the reference test corpus leans on.
        keys = K.pointer_from_ints(np.arange(n, dtype=np.int64))

    schema_obj = schema if schema is not None else schema_from_columns(
        {name: ColumnSchema(name=name, dtype=dtypes[name]) for name in col_order},
        name="Static",
    )

    if times is not None:
        diffs_arr = np.asarray(diffs if diffs is not None else [1] * n, dtype=np.int64)
        times_arr = np.asarray(times, dtype=np.int64)
        batches = []
        for t in sorted(set(times_arr.tolist())):
            idx = np.flatnonzero(times_arr == t)
            batches.append((
                int(t),
                keys[idx],
                {c: data[c][idx] for c in col_order},
                diffs_arr[idx],
            ))
        return Table(
            "scheduled",
            [],
            {"columns": col_order, "batches": batches},
            schema_obj,
            Universe(),
        )

    # the reference's static-tables universe cache (debug/__init__.py:
    # 384-401): two static tables built with the SAME id material — equal
    # explicit ids, equal id_from key columns, or equal unindexed row
    # counts — share one Universe, so columns of one are selectable into
    # the other without an explicit promise (the test-corpus contract).
    from .parse_graph import G

    if id_values is not None:
        cache_key = ("ids", tuple(id_values))
    elif id_from:
        cache_key = ("id_from", tuple(np.asarray(keys).tolist()))
    else:
        cache_key = ("ordinal", n)
    universe = G.static_tables_cache.get(cache_key)
    if universe is None:
        universe = Universe()
        G.static_tables_cache[cache_key] = universe
    return Table("static", [], {"keys": keys, "data": data}, schema_obj, universe)


def empty_table(schema: SchemaMetaclass) -> Table:
    return rows_to_table(schema.column_names(), [], schema=schema)


def table_from_datasource(datasource: Any) -> Table:
    """Source-node table: datasource.build() -> engine SourceNode."""
    return Table(
        "source",
        [],
        {"build": datasource.build, "datasource": datasource},
        datasource.schema,
        Universe(),
    )
