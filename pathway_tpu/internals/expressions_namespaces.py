"""``.str`` / ``.num`` / ``.dt`` expression method namespaces.

Re-design of ``python/pathway/internals/expressions/`` (date_time.py 1,613
LoC, string.py 931 LoC, numerical.py in the reference). Methods compile to
elementwise columnar kernels via ``compile_method``; numeric ones vectorize,
string ones run host-side (strings are irregular data and stay off the TPU —
same split the reference draws between Rust string ops and ndarray ops).
"""

from __future__ import annotations

import datetime
import math
from typing import Any, Callable

import numpy as np

from . import dtype as dt
from .expression import ColumnExpression, MethodCallExpression, smart_coerce


class _Namespace:
    def __init__(self, expression: ColumnExpression):
        self._expression = expression

    def _method(self, name: str, *args: Any, **kwargs: Any) -> MethodCallExpression:
        return MethodCallExpression(name, [self._expression, *args], **kwargs)


class StringNamespace(_Namespace):
    def lower(self):
        return self._method("str.lower")

    def upper(self):
        return self._method("str.upper")

    def strip(self, chars=None):
        return self._method("str.strip", chars)

    def len(self):
        return self._method("str.len")

    def reversed(self):
        return self._method("str.reversed")

    def swapcase(self):
        return self._method("str.swapcase")

    # pre-parity spelling kept as an alias (reference name is ``swapcase``,
    # string.py:358)
    swap_case = swapcase

    def removeprefix(self, prefix):
        return self._method("str.removeprefix", prefix)

    def removesuffix(self, suffix):
        return self._method("str.removesuffix", suffix)

    def title(self):
        return self._method("str.title")

    def count(self, sub):
        return self._method("str.count", sub)

    def find(self, sub):
        return self._method("str.find", sub)

    def rfind(self, sub):
        return self._method("str.rfind", sub)

    def startswith(self, prefix):
        return self._method("str.startswith", prefix)

    def endswith(self, suffix):
        return self._method("str.endswith", suffix)

    def replace(self, old, new, count=-1):
        return self._method("str.replace", old, new, count)

    def split(self, sep=None, maxsplit=-1):
        return self._method("str.split", sep, maxsplit)

    def slice(self, start, end):
        return self._method("str.slice", start, end)

    def parse_int(self, optional: bool = False):
        return self._method("str.parse_int", optional=optional)

    def parse_float(self, optional: bool = False):
        return self._method("str.parse_float", optional=optional)

    def parse_bool(self, true_values=("on", "true", "yes", "1"), false_values=("off", "false", "no", "0"), optional: bool = False):
        return self._method(
            "str.parse_bool",
            true_values=tuple(true_values),
            false_values=tuple(false_values),
            optional=optional,
        )


class NumericalNamespace(_Namespace):
    def abs(self):
        return self._method("num.abs")

    def round(self, decimals=0):
        return self._method("num.round", decimals)

    def fill_na(self, default_value):
        return self._method("num.fill_na", default_value)


class DateTimeNamespace(_Namespace):
    def nanosecond(self):
        return self._method("dt.nanosecond")

    def microsecond(self):
        return self._method("dt.microsecond")

    def millisecond(self):
        return self._method("dt.millisecond")

    def second(self):
        return self._method("dt.second")

    def minute(self):
        return self._method("dt.minute")

    def hour(self):
        return self._method("dt.hour")

    def day(self):
        return self._method("dt.day")

    def month(self):
        return self._method("dt.month")

    def year(self):
        return self._method("dt.year")

    def timestamp(self, unit: str | None = None):
        """Epoch timestamp. With a unit ('s'/'ms'/'us'/'ns'): float, like the
        reference (date_time.py:384). unit=None: int nanoseconds (the
        reference's deprecated default)."""
        return self._method("dt.timestamp", unit=unit)

    def weekday(self):
        return self._method("dt.weekday")

    def from_timestamp(self, unit: str):
        """INT/FLOAT epoch timestamp -> DateTimeNaive (date_time.py:1466)."""
        return self._method("dt.from_timestamp", unit=unit)

    def utc_from_timestamp(self, unit: str):
        """INT/FLOAT epoch timestamp -> DateTimeUtc (date_time.py:1525)."""
        return self._method("dt.from_timestamp", unit=unit).dt.to_utc("UTC")

    # -- Duration totals (date_time.py:1119-1465) -------------------------

    def nanoseconds(self):
        return self._method("dt.nanoseconds")

    def microseconds(self):
        return self._method("dt.microseconds")

    def milliseconds(self):
        return self._method("dt.milliseconds")

    def seconds(self):
        return self._method("dt.seconds")

    def minutes(self):
        return self._method("dt.minutes")

    def hours(self):
        return self._method("dt.hours")

    def days(self):
        return self._method("dt.days")

    def weeks(self):
        return self._method("dt.weeks")

    # -- timezone-aware arithmetic (date_time.py:840-975): compositions
    # over to_utc/to_naive_in_timezone, exactly as the reference builds them

    def add_duration_in_timezone(self, duration, timezone):
        return (self.to_utc(timezone) + duration).dt.to_naive_in_timezone(
            timezone
        )

    def subtract_duration_in_timezone(self, duration, timezone):
        return (self.to_utc(timezone) - duration).dt.to_naive_in_timezone(
            timezone
        )

    def subtract_date_time_in_timezone(self, date_time, timezone):
        return self.to_utc(timezone) - smart_coerce(date_time).dt.to_utc(
            timezone
        )

    def strftime(self, fmt):
        return self._method("dt.strftime", fmt)

    def strptime(self, fmt, contains_timezone: bool | None = None):
        if contains_timezone is None:
            # a literal fmt with %z parses zone-aware values -> UTC dtype
            # (reference infers DATE_TIME_UTC from the format string)
            contains_timezone = isinstance(fmt, str) and "%z" in fmt
        return self._method("dt.strptime", fmt, contains_timezone=contains_timezone)

    def to_naive_in_timezone(self, timezone: str):
        return self._method("dt.to_naive_in_timezone", timezone)

    def to_utc(self, from_timezone: str):
        return self._method("dt.to_utc", from_timezone)

    def round(self, duration):
        return self._method("dt.round", duration)

    def floor(self, duration):
        return self._method("dt.floor", duration)


# ---------------------------------------------------------------------------
# compilation
# ---------------------------------------------------------------------------

_UNIT_NS = {"ns": 1, "us": 1_000, "ms": 1_000_000, "s": 1_000_000_000}


def _td_ns(d: datetime.timedelta) -> int:
    """Exact total nanoseconds of a timedelta (int arithmetic throughout)."""
    return ((d.days * 86400 + d.seconds) * 1_000_000 + d.microseconds) * 1000


def _td_trunc(d: datetime.timedelta, unit_ns: int) -> int:
    """Total whole units, truncating toward zero — chrono ``num_*``
    semantics (reference Duration accessors), not floor division: -90s is
    -1 minute, not -2."""
    ns = _td_ns(d)
    q = abs(ns) // unit_ns
    return q if ns >= 0 else -q


def _dur_ns(d: Any) -> int:
    if isinstance(d, datetime.timedelta):
        return _td_ns(d)
    return int(d)


def _dt_epoch_ns(v: datetime.datetime) -> int:
    """Exact nanoseconds since the epoch (naive: 1970-01-01; aware: UTC)."""
    if v.tzinfo is None:
        epoch = datetime.datetime(1970, 1, 1)
    else:
        epoch = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
    return _td_ns(v - epoch)


def _tz(name: str):
    from zoneinfo import ZoneInfo  # module-level cache inside zoneinfo

    return ZoneInfo(name)


_METHODS: dict[str, tuple[Callable, Callable]] = {
    # name -> (scalar impl, dtype fn over arg dtypes)
    "to_string": (lambda v: str(v), lambda ts: dt.STR),
    "str.lower": (lambda s: s.lower(), lambda ts: dt.STR),
    "str.upper": (lambda s: s.upper(), lambda ts: dt.STR),
    "str.strip": (lambda s, c: s.strip(c), lambda ts: dt.STR),
    "str.len": (lambda s: len(s), lambda ts: dt.INT),
    "str.reversed": (lambda s: s[::-1], lambda ts: dt.STR),
    "str.swapcase": (lambda s: s.swapcase(), lambda ts: dt.STR),
    "str.removeprefix": (lambda s, p: s.removeprefix(p), lambda ts: dt.STR),
    "str.removesuffix": (lambda s, p: s.removesuffix(p), lambda ts: dt.STR),
    "str.title": (lambda s: s.title(), lambda ts: dt.STR),
    "str.count": (lambda s, sub: s.count(sub), lambda ts: dt.INT),
    "str.find": (lambda s, sub: s.find(sub), lambda ts: dt.INT),
    "str.rfind": (lambda s, sub: s.rfind(sub), lambda ts: dt.INT),
    "str.startswith": (lambda s, p: s.startswith(p), lambda ts: dt.BOOL),
    "str.endswith": (lambda s, p: s.endswith(p), lambda ts: dt.BOOL),
    "str.replace": (lambda s, o, n, c: s.replace(o, n, c), lambda ts: dt.STR),
    # exact Python list semantics (a lifted `s.split(...)` must be
    # cell-for-cell identical to the per-row path; the engine used to
    # wrap in tuple, which diverged on == and isinstance checks)
    "str.split": (
        lambda s, sep, m: s.split(sep, m),
        lambda ts: dt.List(dt.STR),
    ),
    "str.slice": (lambda s, a, b: s[a:b], lambda ts: dt.STR),
    "num.abs": (lambda v: abs(v), lambda ts: ts[0]),
    "num.round": (lambda v, d: round(v, d), lambda ts: ts[0]),
    # exact Python int() for lifted UDFs (udf_lift): per element, so
    # int(nan)/int(inf) raise into per-row semantics instead of the
    # dense astype path's silent INT64_MIN
    "py.int": (lambda v: int(v), lambda ts: dt.INT),
    "dt.second": (lambda v: v.second, lambda ts: dt.INT),
    "dt.minute": (lambda v: v.minute, lambda ts: dt.INT),
    "dt.hour": (lambda v: v.hour, lambda ts: dt.INT),
    "dt.day": (lambda v: v.day, lambda ts: dt.INT),
    "dt.month": (lambda v: v.month, lambda ts: dt.INT),
    "dt.year": (lambda v: v.year, lambda ts: dt.INT),
    "dt.microsecond": (lambda v: v.microsecond, lambda ts: dt.INT),
    "dt.millisecond": (lambda v: v.microsecond // 1000, lambda ts: dt.INT),
    "dt.nanosecond": (lambda v: v.microsecond * 1000, lambda ts: dt.INT),
    "dt.strftime": (lambda v, fmt: v.strftime(fmt), lambda ts: dt.STR),
    "dt.weekday": (lambda v: v.weekday(), lambda ts: dt.INT),
    # exact Python datetime.timestamp() for lifted UDFs (udf_lift): tz-
    # aware datetimes convert exactly; naive ones use the LOCAL timezone,
    # like Python — deliberately distinct from dt.timestamp(unit=...),
    # whose naive anchor is the epoch (reference date_time.py:384)
    "py.timestamp": (lambda v: v.timestamp(), lambda ts: dt.FLOAT),
    # Duration totals (reference date_time.py:1119-1465: all are *total*
    # durations as ints, truncating toward zero like chrono's num_*)
    "dt.nanoseconds": (lambda d: _td_ns(d), lambda ts: dt.INT),
    "dt.microseconds": (lambda d: _td_trunc(d, 1_000), lambda ts: dt.INT),
    "dt.milliseconds": (lambda d: _td_trunc(d, 1_000_000), lambda ts: dt.INT),
    "dt.seconds": (lambda d: _td_trunc(d, 1_000_000_000), lambda ts: dt.INT),
    "dt.minutes": (lambda d: _td_trunc(d, 60_000_000_000), lambda ts: dt.INT),
    "dt.hours": (lambda d: _td_trunc(d, 3_600_000_000_000), lambda ts: dt.INT),
    "dt.days": (lambda d: _td_trunc(d, 86_400_000_000_000), lambda ts: dt.INT),
    "dt.weeks": (
        lambda d: _td_trunc(d, 604_800_000_000_000), lambda ts: dt.INT,
    ),
    # timezone conversions (reference date_time.py:660,750; zoneinfo is the
    # chrono-tz analog)
    "dt.to_utc": (
        lambda v, tz: v.replace(tzinfo=_tz(tz)).astimezone(
            datetime.timezone.utc
        ),
        lambda ts: dt.DATE_TIME_UTC,
    ),
    "dt.to_naive_in_timezone": (
        lambda v, tz: v.astimezone(_tz(tz)).replace(tzinfo=None),
        lambda ts: dt.DATE_TIME_NAIVE,
    ),
}


def compile_method(expr: MethodCallExpression, env, build, xp_name):
    name = expr._method
    kw = expr._method_kwargs
    parts = [build(a, env, xp_name) for a in expr._args]
    arg_dtypes = [p[1] for p in parts]
    refs = set().union(*[p[3] for p in parts]) if parts else set()

    if name in ("str.parse_int", "str.parse_float", "str.parse_bool"):
        optional = kw.get("optional", False)
        if name == "str.parse_int":
            conv, out_dt = int, dt.INT
        elif name == "str.parse_float":
            conv, out_dt = float, dt.FLOAT
        else:
            tv = {s.lower() for s in kw.get("true_values", ("true",))}
            fv = {s.lower() for s in kw.get("false_values", ("false",))}

            def conv(s: str) -> bool:
                ls = s.strip().lower()
                if ls in tv:
                    return True
                if ls in fv:
                    return False
                raise ValueError(f"cannot parse {s!r} as bool")

            out_dt = dt.BOOL

        def fn(cols, keys, f=parts[0][0]):
            from .expression_compiler import _materialize

            vals = _materialize(f(cols, keys), len(keys))
            out = np.empty(len(vals), dtype=object)
            for i, s in enumerate(vals):
                if s is None:
                    out[i] = None
                    continue
                try:
                    out[i] = conv(s)
                except ValueError:
                    if optional:
                        out[i] = None
                    else:
                        raise
            if not optional and out_dt != dt.BOOL:
                return out.astype(out_dt.numpy_dtype)
            return out

        return fn, (dt.Optional(out_dt) if optional else out_dt), False, refs

    if name == "dt.timestamp":
        unit = kw.get("unit")
        as_float = unit is not None  # reference: float with a unit, int ns
        # for the deprecated no-unit form (date_time.py:384)
        div = _UNIT_NS[unit or "ns"]

        def fn(cols, keys, f=parts[0][0]):
            from .expression_compiler import _materialize

            vals = _materialize(f(cols, keys), len(keys))
            out = np.empty(
                len(vals), dtype=np.float64 if as_float else np.int64
            )
            for i, v in enumerate(vals):
                ns = _dt_epoch_ns(v)
                out[i] = ns / div if as_float else ns // div
            return out

        return fn, dt.FLOAT if as_float else dt.INT, False, refs

    if name == "dt.from_timestamp":
        mul = _UNIT_NS[kw["unit"]]

        def fn(cols, keys, f=parts[0][0]):
            from .expression_compiler import _materialize

            vals = _materialize(f(cols, keys), len(keys))
            out = np.empty(len(vals), dtype=object)
            epoch = datetime.datetime(1970, 1, 1)
            for i, v in enumerate(vals):
                if isinstance(v, (int, np.integer)):
                    # exact int path: float64 can't hold current-era ns
                    us = (int(v) * mul) // 1000
                else:
                    us = (v * mul) / 1000
                out[i] = epoch + datetime.timedelta(microseconds=us)
            return out

        return fn, dt.DATE_TIME_NAIVE, False, refs

    if name == "dt.strptime":
        contains_tz = kw.get("contains_timezone", False)

        def fn(cols, keys, f=parts[0][0], fmtf=parts[1][0]):
            from .expression_compiler import _materialize

            vals = _materialize(f(cols, keys), len(keys))
            fmts = _materialize(fmtf(cols, keys), len(keys))
            out = np.empty(len(vals), dtype=object)
            for i in range(len(vals)):
                out[i] = datetime.datetime.strptime(vals[i], fmts[i])
            return out

        return fn, dt.DATE_TIME_UTC if contains_tz else dt.DATE_TIME_NAIVE, False, refs

    if name in ("dt.round", "dt.floor"):
        def fn(cols, keys, f=parts[0][0], df=parts[1][0]):
            from .expression_compiler import _materialize

            vals = _materialize(f(cols, keys), len(keys))
            durs = _materialize(df(cols, keys), len(keys))
            out = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                step = _dur_ns(durs[i])
                epoch = datetime.datetime(1970, 1, 1, tzinfo=v.tzinfo)
                ns = int((v - epoch).total_seconds() * 1_000_000_000)
                if name == "dt.round":
                    ns = (ns + step // 2) // step * step
                else:
                    ns = ns // step * step
                out[i] = epoch + datetime.timedelta(microseconds=ns / 1000)
            return out

        return fn, arg_dtypes[0], False, refs

    if name == "num.fill_na":
        def fn(cols, keys, f=parts[0][0], dflt=parts[1][0]):
            from .expression_compiler import _materialize

            vals = _materialize(f(cols, keys), len(keys))
            dv = _materialize(dflt(cols, keys), len(keys))
            if vals.dtype != object:
                if vals.dtype == np.float64:
                    mask = np.isnan(vals)
                    if mask.any():
                        vals = vals.copy()
                        vals[mask] = dv[mask]
                return vals
            out = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                bad = v is None or (isinstance(v, float) and math.isnan(v))
                out[i] = dv[i] if bad else v
            from .expression_compiler import _densify

            return _densify(out, dt.unoptionalize(arg_dtypes[0]))

        return fn, dt.unoptionalize(arg_dtypes[0]), False, refs

    if name not in _METHODS:
        # internal invariant: every namespace method constructs a name listed
        # above (the reference's .dt/.str/.num inventory is fully mapped) —
        # reaching here means a namespace/compiler mismatch, not a user error
        raise AssertionError(f"unmapped expression method {name!r}")

    impl, dtype_fn = _METHODS[name]
    out_dt = dtype_fn(arg_dtypes)
    any_opt = any(t.is_optional for t in arg_dtypes)

    def fn(cols, keys):
        from .expression_compiler import _densify, _materialize, _unnp

        n = len(keys)
        arrs = [_materialize(p[0](cols, keys), n) for p in parts]
        out = np.empty(n, dtype=object)
        for i in range(n):
            args_i = [_unnp(a[i]) for a in arrs]
            if args_i and args_i[0] is None:
                out[i] = None
            else:
                out[i] = impl(*args_i)
        return _densify(out, out_dt)

    return fn, (dt.Optional(out_dt) if any_opt else out_dt), False, refs
