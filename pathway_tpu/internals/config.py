"""Env-first engine configuration (reference
``python/pathway/internals/config.py:35-121`` ``PathwayConfig`` +
``src/engine/dataflow/config.rs:62-128`` worker config).

All knobs come from ``PATHWAY_*`` environment variables so `spawn`-style
launchers configure workers purely through the environment, exactly like
the reference.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

__all__ = ["PathwayConfig", "get_pathway_config", "pathway_config", "MAX_WORKERS"]

#: reference free-tier cap (dataflow/config.rs:7-11)
MAX_WORKERS = 8


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v is not None else default
    except ValueError:
        return default


def _env_addresses(name: str) -> list[str] | None:
    v = os.environ.get(name)
    if not v or not v.strip():
        return None
    return [a.strip() for a in v.split(",") if a.strip()]


@dataclass
class PathwayConfig:
    ignore_asserts: bool = field(
        default_factory=lambda: _env_bool("PATHWAY_IGNORE_ASSERTS"))
    runtime_typechecking: bool = field(
        default_factory=lambda: _env_bool("PATHWAY_RUNTIME_TYPECHECKING"))
    replay_storage: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_REPLAY_STORAGE"))
    snapshot_access: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_SNAPSHOT_ACCESS"))
    persistence_mode: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_PERSISTENCE_MODE"))
    license_key: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_LICENSE_KEY"))
    monitoring_server: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_MONITORING_SERVER"))
    continue_after_replay: bool = field(
        default_factory=lambda: _env_bool("PATHWAY_CONTINUE_AFTER_REPLAY"))
    #: span tracing → Chrome-trace JSON (internals/tracing.py; the OTLP
    #: telemetry analog of src/engine/telemetry.rs for a no-egress world)
    trace_file: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_TRACE_FILE"))
    # NOTE: PATHWAY_RUN_ID / PATHWAY_FLIGHT_DIR / PATHWAY_FLIGHT_RING_KB are
    # deliberately NOT snapshotted here — the tracer must initialize even
    # when config validation refuses the worker layout, and the flight
    # recorder re-reads its env per restart generation; both read the
    # environment directly (internals/tracing.py,
    # observability/flightrecorder.py), like the PATHWAY_SUPERVISE_* knobs.
    # observability (engine/http_server.py + observability/)
    #: force the monitoring HTTP server on without a code change (the
    #: with_http_server=True analog for spawn-style deployments)
    monitoring_http_server: bool = field(
        default_factory=lambda: _env_bool("PATHWAY_MONITORING_HTTP_SERVER"))
    #: bind host for /metrics + probes; loopback unless opted into
    monitoring_http_host: str = field(
        default_factory=lambda: os.environ.get(
            "PATHWAY_MONITORING_HTTP_HOST", "127.0.0.1"))
    #: base port; process p serves on base + p (http_server.rs convention)
    monitoring_http_port: int = field(
        default_factory=lambda: _env_int(
            "PATHWAY_MONITORING_HTTP_PORT", 20000))
    #: periodic telemetry flush cadence (observability/exporter.py);
    #: 0 disables, leaving only the end-of-run export
    telemetry_flush_s: float = field(
        default_factory=lambda: _env_float("PATHWAY_TELEMETRY_FLUSH_S", 60.0))
    #: /healthz fails when an unfinished executor's heartbeat is older
    health_wedge_timeout_s: float = field(
        default_factory=lambda: _env_float("PATHWAY_HEALTH_WEDGE_S", 30.0))
    # robustness / self-healing (chaos/ + parallel/supervisor.py)
    #: declarative fault plan: inline JSON or a path to one (chaos/plan.py);
    #: unset = every injection site disarmed (one None check each)
    fault_plan: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_FAULT_PLAN"))
    #: how long a blocked cluster collective waits before declaring the
    #: mesh dead (peer-death propagation normally fires in milliseconds —
    #: this is the backstop for silent stalls)
    collective_timeout_s: float = field(
        default_factory=lambda: _env_float(
            "PATHWAY_COLLECTIVE_TIMEOUT_S", 600.0))
    #: per-peer mesh-establishment budget (jittered-backoff retries within)
    connect_timeout_s: float = field(
        default_factory=lambda: _env_float("PATHWAY_CONNECT_TIMEOUT_S", 30.0))
    #: set by `spawn --supervise` on children: enables cooperative SIGTERM
    #: wind-down so the supervisor's teardown flushes the persistence tail
    supervised: bool = field(
        default_factory=lambda: _env_bool("PATHWAY_SUPERVISED"))
    #: restart generation (0 = first boot), stamped by the supervisor;
    #: gates fault-plan entries and feeds pathway_restarts_total
    restart_count: int = field(
        default_factory=lambda: _env_int("PATHWAY_RESTART_COUNT", 0))
    #: why the supervisor last restarted the ensemble (metrics label)
    last_restart_reason: str | None = field(
        default_factory=lambda: os.environ.get("PATHWAY_LAST_RESTART_REASON"))
    # worker layout (config.rs PATHWAY_THREADS/PROCESSES/PROCESS_ID/FIRST_PORT)
    #: route dense Exchange columns over the jax device mesh (ICI) instead
    #: of host memory — parallel/meshcomm.py; needs ≥ total_workers devices
    mesh_exchange: bool = field(
        default_factory=lambda: _env_bool("PATHWAY_MESH_EXCHANGE"))
    threads: int = field(default_factory=lambda: _env_int("PATHWAY_THREADS", 1))
    processes: int = field(default_factory=lambda: _env_int("PATHWAY_PROCESSES", 1))
    process_id: int = field(default_factory=lambda: _env_int("PATHWAY_PROCESS_ID", 0))
    first_port: int = field(default_factory=lambda: _env_int("PATHWAY_FIRST_PORT", 10000))
    #: multi-host cluster address book: comma-separated host[:port], one per
    #: process (the timely hostfile analog — communication/src/initialize.rs);
    #: unset = all processes on 127.0.0.1 at first_port+pid
    addresses: list[str] | None = field(
        default_factory=lambda: _env_addresses("PATHWAY_ADDRESSES"))

    def __post_init__(self) -> None:
        if self.threads * self.processes > MAX_WORKERS:
            raise RuntimeError(
                f"too many workers: {self.threads}×{self.processes} > "
                f"{MAX_WORKERS} (reference free-tier limit, "
                "dataflow/config.rs:7-11)"
            )
        if self.addresses is not None and len(self.addresses) != self.processes:
            raise RuntimeError(
                f"PATHWAY_ADDRESSES lists {len(self.addresses)} hosts for "
                f"{self.processes} processes — one host[:port] per process"
            )

    @property
    def total_workers(self) -> int:
        return self.threads * self.processes

    @property
    def replay_mode(self) -> str | None:
        return self.persistence_mode


def get_pathway_config() -> PathwayConfig:
    """Fresh config snapshot from the current environment."""
    return PathwayConfig()


def __getattr__(name: str):
    # `pathway_config` resolves lazily: importing the package must not
    # validate (and possibly reject) worker env vars the program never uses
    if name == "pathway_config":
        return get_pathway_config()
    raise AttributeError(name)
