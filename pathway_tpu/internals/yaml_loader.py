"""YAML pipeline loader (reference
``python/pathway/internals/yaml_loader.py`` — used by the app templates).

``!pw.some.dotted.Name`` tags instantiate the referenced callable with the
mapping's entries as kwargs; ``$ref: name`` entries resolve to previously
defined top-level objects, and ``$env`` interpolates environment variables.
"""

from __future__ import annotations

import importlib
import os
from typing import Any, IO

import yaml

__all__ = ["load_yaml"]


def _resolve_dotted(path: str) -> Any:
    if path == "pw" or path.startswith("pw."):
        path = "pathway_tpu" + path[2:]
    parts = path.split(".")
    for split in range(len(parts), 0, -1):
        module_name = ".".join(parts[:split])
        try:
            obj: Any = importlib.import_module(module_name)
        except ImportError:
            continue
        for attr in parts[split:]:
            obj = getattr(obj, attr)
        return obj
    raise ImportError(f"cannot resolve {path!r}")


class _Tagged:
    def __init__(self, path: str, value: Any):
        self.path = path
        self.value = value


class _Loader(yaml.SafeLoader):
    pass


def _tag_constructor(loader: _Loader, tag_suffix: str, node: yaml.Node) -> _Tagged:
    if isinstance(node, yaml.MappingNode):
        value = loader.construct_mapping(node, deep=True)
    elif isinstance(node, yaml.SequenceNode):
        value = loader.construct_sequence(node, deep=True)
    else:
        value = loader.construct_scalar(node)
    return _Tagged(tag_suffix, value)


_Loader.add_multi_constructor("!", _tag_constructor)


def _instantiate(obj: Any, defined: dict[str, Any]) -> Any:
    if isinstance(obj, _Tagged):
        target = _resolve_dotted(obj.path)
        value = _instantiate(obj.value, defined)
        if isinstance(value, dict):
            return target(**value)
        if value is None or (isinstance(value, str) and value == ""):
            return target()
        if isinstance(value, list):
            return target(*value)
        return target(value)
    if isinstance(obj, dict):
        if set(obj.keys()) == {"$ref"}:
            return defined[obj["$ref"]]
        if set(obj.keys()) == {"$env"}:
            return os.environ[obj["$env"]]
        return {k: _instantiate(v, defined) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_instantiate(v, defined) for v in obj]
    if isinstance(obj, str) and obj.startswith("$") and len(obj) > 1:
        # $name references a defined entry; an unknown name is an error
        # (reference test_yaml.py:96), never a silent literal
        if obj[1:] not in defined:
            raise KeyError(f"undefined yaml variable {obj!r}")
        return defined[obj[1:]]
    return obj


def load_yaml(stream: str | IO) -> Any:
    """Parse a YAML pipeline description, instantiating ``!dotted.path``
    tags (top-level keys become ``$name`` references for later entries)."""
    raw = yaml.load(stream, Loader=_Loader)
    if not isinstance(raw, dict):
        return _instantiate(raw, {})
    defined: dict[str, Any] = {}
    out: dict[str, Any] = {}
    for key, value in raw.items():
        if isinstance(key, str) and key.startswith("$"):
            # ``$name:`` defines a variable — referenced as ``$name``,
            # excluded from the result (reference test_yaml.py:58)
            defined[key[1:]] = _instantiate(value, defined)
        else:
            v = _instantiate(value, defined)
            defined[key] = v
            out[key] = v
    return out
