"""Legacy class-transformer API (``@pw.transformer``).

Re-design of ``python/pathway/internals/row_transformer.py`` (294 LoC,
``ClassArgMeta``/``ClassArg`` + attribute markers) and the ``shadows``
evaluation machinery over engine ``complex_columns``
(``src/engine/dataflow.rs`` legacy transformer columns). The reference
deprecates this API in favor of expressions; it is kept for parity.

Here every output class table lowers to ONE ``GroupedRecompute`` engine
node gathering the full current rows of all argument tables — computed
attributes then evaluate as plain Python with lazy per-row memoization,
which naturally supports the API's defining feature: pointer-chasing
across rows and tables (``self.transformer.nodes[ptr].val``) with
recursive attribute references. Not incremental within a tick (the whole
transformer recomputes when any input changes), matching the reference's
own guidance that transformers are for expressiveness, not speed.

Usage (reference ``tests/test_transformers.py``)::

    @pw.transformer
    class traversal:
        class nodes(pw.ClassArg):
            next = pw.input_attribute()
            val = pw.input_attribute()

        class requests(pw.ClassArg):
            node = pw.input_attribute()

            @pw.output_attribute
            def reached(self) -> int:
                return self.transformer.nodes[self.node].val

    out = traversal(nodes_table, requests_table).requests
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

__all__ = [
    "ClassArg",
    "attribute",
    "input_attribute",
    "input_method",
    "method",
    "output_attribute",
    "transformer",
]


class _InputAttribute:
    def __init__(self) -> None:
        self.name: str | None = None


def input_attribute(type: Any = None) -> Any:  # noqa: A002 — reference name
    return _InputAttribute()


class _OutputAttribute:
    def __init__(self, fn: Callable, output_name: str | None = None):
        self.fn = fn
        self.output_name = output_name or fn.__name__
        self.name = fn.__name__


def output_attribute(fn: Callable | None = None, *, output_name: str | None = None):
    if fn is None:
        return lambda f: _OutputAttribute(f, output_name)
    return _OutputAttribute(fn, output_name)


class _Attribute:
    """Computed, memoized, NOT exported (reference ``attribute``)."""

    def __init__(self, fn: Callable):
        self.fn = fn
        self.name = fn.__name__


def attribute(fn: Callable) -> _Attribute:
    return _Attribute(fn)


def method(fn: Callable | None = None, **kwargs: Any):
    raise NotImplementedError(
        "@pw.method output columns are not supported; plain helper methods "
        "on the ClassArg work, and expressions/udfs cover exported callables"
    )


def input_method(type: Any = None) -> Any:
    raise NotImplementedError(
        "pw.input_method is not supported; pass data columns and call plain "
        "helper methods instead"
    )


class ClassArg:
    """Base for transformer argument classes. At evaluation time instances
    are per-row handles with lazy attribute resolution (reference
    ``ClassArg``, row_transformer.py:148)."""

    # populated per subclass by transformer()
    _pw_inputs: list[str]
    _pw_outputs: list[_OutputAttribute]
    _pw_attrs: dict[str, _Attribute]
    _pw_output_schema: Any = None

    def __init_subclass__(cls, output: Any = None, **kw: Any) -> None:
        super().__init_subclass__(**kw)
        cls._pw_output_schema = output


class _RowHandle:
    """One row of one class table during evaluation: input attributes read
    from the stored tuple, computed attributes evaluate lazily with
    memoization; ``self.transformer`` reaches the other tables."""

    __slots__ = ("_cls", "_ctx", "_key", "_row", "_cache")

    def __init__(self, cls, ctx, key, row):
        self._cls = cls
        self._ctx = ctx
        self._key = key
        self._row = row
        self._cache: dict[str, Any] = {}

    @property
    def id(self):
        return np.uint64(self._key)

    @property
    def transformer(self):
        return self._ctx

    def pointer_from(self, *args):
        from ..engine import keys as K

        return K.hash_values([tuple(args)])[0]

    def __getattr__(self, name: str):
        cls = object.__getattribute__(self, "_cls")
        cache = object.__getattribute__(self, "_cache")
        if name in cache:
            return cache[name]
        if name in cls._pw_inputs:
            v = self._row[cls._pw_inputs.index(name)]
            cache[name] = v
            return v
        for out in cls._pw_outputs:
            if out.name == name:
                v = out.fn(self)
                cache[name] = v
                return v
        if name in cls._pw_attrs:
            v = cls._pw_attrs[name].fn(self)
            cache[name] = v
            return v
        # plain helpers / class constants / staticmethods resolve on the
        # class; methods bind to this handle as `self`
        attr = getattr(cls, name)
        if callable(attr) and not isinstance(attr, type):
            import types

            if isinstance(
                inspect_getattr_static(cls, name), staticmethod
            ):
                return attr
            return types.MethodType(attr, self)
        return attr


def inspect_getattr_static(cls, name):
    import inspect

    return inspect.getattr_static(cls, name)


class _EvalContext:
    """``self.transformer`` — class-name → table accessor over the gathered
    row dicts of the current tick."""

    def __init__(self, classes: dict[str, type], rows: dict[str, dict]):
        self._classes = classes
        self._rows = rows
        self._handles: dict[tuple[str, int], _RowHandle] = {}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._classes:
            raise AttributeError(f"transformer has no table {name!r}")
        return _TableAccessor(self, name)

    def handle(self, tab: str, key: int) -> _RowHandle:
        key = int(key)
        hk = (tab, key)
        h = self._handles.get(hk)
        if h is None:
            row = self._rows[tab].get(key)
            if row is None:
                raise KeyError(
                    f"no row {key} in transformer table {tab!r}"
                )
            h = _RowHandle(self._classes[tab], self, key, row)
            self._handles[hk] = h
        return h


class _TableAccessor:
    __slots__ = ("_ctx", "_tab")

    def __init__(self, ctx: _EvalContext, tab: str):
        self._ctx = ctx
        self._tab = tab

    def __getitem__(self, key) -> _RowHandle:
        return self._ctx.handle(self._tab, int(key))


class _TransformerResult:
    def __init__(self, tables: dict[str, Any], input_only: set[str]):
        self._tables = tables
        self._input_only = input_only

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self._tables[name]
        except KeyError:
            if name in self._input_only:
                raise AttributeError(
                    f"transformer class {name!r} has no output attributes, "
                    "so it produces no result table"
                ) from None
            raise AttributeError(name) from None

    def __getitem__(self, name: str):
        return getattr(self, name)


class Transformer:
    def __init__(self, cls: type):
        self._name = cls.__name__
        self._classes: dict[str, type] = {}
        for name, member in vars(cls).items():
            if isinstance(member, type) and issubclass(member, ClassArg):
                member._pw_inputs = [
                    n for n, v in vars(member).items()
                    if isinstance(v, _InputAttribute)
                ]
                member._pw_outputs = [
                    v for v in vars(member).values()
                    if isinstance(v, _OutputAttribute)
                ]
                member._pw_attrs = {
                    n: v for n, v in vars(member).items()
                    if isinstance(v, _Attribute)
                }
                self._classes[name] = member

    def __call__(self, *tables, **named):
        from .table import Table
        from .schema import ColumnSchema, schema_from_columns
        from . import dtype as dt
        from ..engine import operators as ops

        names = list(self._classes)
        if len(tables) > len(names):
            raise TypeError(
                f"transformer {self._name} takes {len(names)} table(s), "
                f"got {len(tables)} positional"
            )
        unknown = sorted(set(named) - set(names))
        if unknown:
            raise TypeError(
                f"transformer {self._name} has no table(s) named {unknown}"
            )
        bound: dict[str, Table] = dict(zip(names, tables))
        double = sorted(set(bound) & set(named))
        if double:
            raise TypeError(
                f"transformer {self._name}: table(s) {double} passed both "
                "positionally and by name"
            )
        bound.update(named)
        missing = [n for n in names if n not in bound]
        if missing:
            raise TypeError(
                f"transformer {self._name} missing table(s): {missing}"
            )
        classes = self._classes

        # input projections built ONCE: the runner caches lowered nodes by
        # Table object, so multiple output classes share the input nodes
        # (each output's GroupedRecompute still gathers its own state copy
        # — acceptable for a deprecated expressiveness-oriented API)
        projections = {
            n: bound[n].select(**{
                c: getattr(bound[n], c) for c in classes[n]._pw_inputs
            })
            for n in names
        }

        out_tables: dict[str, Table] = {}
        for out_name, out_cls in classes.items():
            outputs = out_cls._pw_outputs
            if not outputs:
                continue
            declared = out_cls._pw_output_schema
            cols = {}
            for o in outputs:
                dtype = dt.ANY
                if declared is not None and o.output_name in declared.column_names():
                    dtype = declared.dtypes()[o.output_name]
                cols[o.output_name] = ColumnSchema(name=o.output_name, dtype=dtype)
            schema = schema_from_columns(cols, name=f"{self._name}_{out_name}")

            def make_lower(out_name=out_name, out_cls=out_cls, outputs=outputs):
                def lower(runner, tbl):
                    in_nodes = [runner.lower(projections[n]) for n in names]

                    def compute(gk, *rows_and_time):
                        *rows_per_tab, time = rows_and_time
                        rows = {
                            n: tab_rows
                            for n, tab_rows in zip(names, rows_per_tab)
                        }
                        ctx = _EvalContext(classes, rows)
                        out = []
                        for key in rows[out_name]:
                            h = ctx.handle(out_name, key)
                            out.append(
                                (key, tuple(
                                    getattr(h, o.name) for o in outputs
                                ))
                            )
                        return out

                    return runner._add(ops.GroupedRecompute(
                        in_nodes, [None] * len(in_nodes),
                        [o.output_name for o in outputs], compute,
                    ))
                return lower

            out_tables[out_name] = Table(
                "custom", [bound[n] for n in names],
                {"lower": make_lower()}, schema,
                bound[out_name]._universe,
            )
        return _TransformerResult(
            out_tables,
            {n for n, c in classes.items() if not c._pw_outputs},
        )


def transformer(cls: type) -> Transformer:
    """Class-transformer decorator (reference row_transformer.py)."""
    return Transformer(cls)
