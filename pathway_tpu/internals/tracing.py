"""In-process span tracing and run profiling.

Re-design of the reference's telemetry pair — Rust OTLP traces/metrics
(``src/engine/telemetry.rs:47-156``) and the Python build/run spans
(``python/pathway/internals/graph_runner/telemetry.py``,
``graph_runner/__init__.py:146-176``) — for an environment with no
network egress: instead of pushing OTLP over gRPC, the tracer records
spans in memory and writes the Chrome Trace Event format (the catapult
JSON array understood by ``chrome://tracing`` and ``ui.perfetto.dev``)
when the run finishes.

Activation is env-first like every other engine knob
(``internals/config.py``): set ``PATHWAY_TRACE_FILE=/path/run.json``.
When unset, ``get_tracer()`` returns ``None`` and every instrumentation
site is a single ``is None`` check — no timestamps are taken.

Span taxonomy (mirrors the reference's span names where it has them):

- ``graph.build`` — lowering the parse graph to engine nodes
  (reference span ``graph_runner/__init__.py:146``);
- ``engine.run`` — the whole executor run;
- ``tick`` — one logical-time sweep, with the minted timestamp attached;
- per-node events under each tick, named ``<NodeClass>#<id>``, with the
  emitted row count — the analog of timely's event logging stream
  (``DIFFERENTIAL_LOG_ADDR``, reference ``dataflow.rs:5540-5548``);
- counter samples of ``EngineStats`` totals per tick, rendered by the
  trace viewers as time series.

Multi-process runs write one file per process (``<path>.p<process_id>``,
like the per-process metrics ports of ``engine/http_server.rs:21``);
worker threads separate naturally by ``tid``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

__all__ = [
    "Tracer",
    "activate",
    "deactivate",
    "get_tracer",
    "init_from_env",
    "span",
]


class _Span:
    __slots__ = ("tracer", "name", "args", "t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self) -> "_Span":
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> None:
        self.tracer.complete(self.name, self.t0, self.args or None)


class Tracer:
    """Collects Chrome-trace events; thread-safe, append-only.

    ``path=None`` collects without writing a local trace file — the mode
    used when only an OTLP endpoint (``internals/telemetry.py``) consumes
    the spans."""

    def __init__(self, path: str | None, max_events: int | None = None):
        self.path = path
        self._events: list[dict[str, Any]] = []
        self._lock = threading.Lock()
        self._pid = os.getpid()
        #: perf_counter origin so timestamps start near zero in the viewer
        self._origin = time.perf_counter_ns()
        #: streaming pipelines run forever (run.py) — bound the buffer so
        #: tracing a long-lived run keeps the most recent window instead of
        #: growing without limit; oldest half is dropped on overflow
        if max_events is None:
            max_events = int(
                os.environ.get("PATHWAY_TRACE_MAX_EVENTS", "500000")
            )
        self._max_events = max(max_events, 2)
        self._dropped = 0
        self._appended = 0
        self._flush_mark = -1  # _appended value at the last write

    # -- recording ----------------------------------------------------

    def _ts(self, ns: int) -> float:
        return (ns - self._origin) / 1e3  # µs

    def span(self, name: str, **args: Any) -> _Span:
        """``with tracer.span("graph.build", tables=3): ...``"""
        return _Span(self, name, args)

    def complete(
        self, name: str, t0_ns: int, args: dict[str, Any] | None = None
    ) -> None:
        """A finished duration event that began at ``t0_ns``."""
        ev = {
            "name": name,
            "ph": "X",
            "ts": self._ts(t0_ns),
            "dur": (time.perf_counter_ns() - t0_ns) / 1e3,
            "pid": self._pid,
            "tid": threading.get_ident() % 2**31,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def _append(self, ev: dict[str, Any]) -> None:
        with self._lock:
            self._events.append(ev)
            self._appended += 1
            if len(self._events) > self._max_events:
                drop = len(self._events) // 2
                self._dropped += drop
                del self._events[:drop]

    def instant(self, name: str, **args: Any) -> None:
        ev = {
            "name": name,
            "ph": "i",
            "s": "t",
            "ts": self._ts(time.perf_counter_ns()),
            "pid": self._pid,
            "tid": threading.get_ident() % 2**31,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def counter(self, name: str, values: dict[str, float]) -> None:
        """A counter sample (rendered as stacked time series). Callers with
        per-worker counters must put the worker id in ``name`` — trace
        viewers key counter tracks by (pid, name), so same-named samples
        from different workers would interleave into one garbled series."""
        self._append(
            {
                "name": name,
                "ph": "C",
                "ts": self._ts(time.perf_counter_ns()),
                "pid": self._pid,
                "args": values,
            }
        )

    def events_since(self, mark: int) -> tuple[list[dict[str, Any]], int]:
        """Events appended after the ``mark`` cursor (an ``_appended``
        value), plus the new cursor — the incremental-export protocol used
        by the periodic OTLP flusher (observability/exporter.py) and the
        end-of-run push, which share one cursor so nothing double-exports.
        Events already dropped by the ring buffer are simply gone."""
        with self._lock:
            new = self._appended - mark
            if new <= 0:
                return [], self._appended
            return list(self._events[-new:]), self._appended

    # -- output -------------------------------------------------------

    def flush(self) -> str | None:
        """Write the full event buffer to the trace file. Re-flushable: a
        tracer kept alive across several ``pw.run`` calls (``activate()``)
        rewrites the file with the accumulated events each time; a flush
        with nothing new since the last write is a no-op. Never raises —
        tracing is auxiliary and must not fail (or mask the error of) the
        run it observes."""
        if self.path is None:  # OTLP-only mode: no local file
            return None
        with self._lock:
            if self._flush_mark == self._appended:
                return None
            self._flush_mark = self._appended
            events = list(self._events)
        path = self.path
        # raw env read, not PathwayConfig: config validation can refuse the
        # worker layout (e.g. over the worker cap) and flush must not raise
        try:
            n_processes = int(os.environ.get("PATHWAY_PROCESSES", "1"))
            process_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
        except ValueError:
            n_processes, process_id = 1, 0
        if n_processes > 1:
            path = f"{path}.p{process_id}"
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self._pid,
                "args": {"name": "pathway_tpu"},
            }
        ]
        if self._dropped:
            meta.append(
                {
                    "name": "trace.dropped_events",
                    "ph": "i",
                    "s": "g",
                    "ts": 0.0,
                    "pid": self._pid,
                    "tid": 0,
                    "args": {"count": self._dropped},
                }
            )
        try:
            with open(path, "w") as f:
                json.dump(
                    {"traceEvents": meta + events, "displayTimeUnit": "ms"}, f
                )
        except (OSError, TypeError, ValueError) as e:
            import warnings

            warnings.warn(
                f"could not write trace file {path!r}: {e}", RuntimeWarning
            )
            return None
        return path


_active: Tracer | None = None
_env_checked = False
_programmatic = False


def activate(path: str) -> Tracer:
    """Programmatic activation (the env var is the usual route). Survives
    ``pw.run``'s env re-read until ``deactivate()``."""
    global _active, _env_checked, _programmatic
    _active = Tracer(path)
    _env_checked = True
    _programmatic = True
    return _active


def deactivate() -> None:
    global _active, _env_checked, _programmatic
    _active = None
    _env_checked = True
    _programmatic = False


def init_from_env() -> Tracer | None:
    """Install a tracer if ``PATHWAY_TRACE_FILE`` is set (read through
    ``PathwayConfig`` so the config snapshot and the tracer agree). Called
    at the top of every run so each ``pw.run`` re-reads the environment; a
    tracer installed via ``activate()`` is kept as-is."""
    global _active, _env_checked
    if _programmatic:
        return _active
    try:
        from .config import get_pathway_config

        path = get_pathway_config().trace_file
    except (ImportError, RuntimeError):
        # config can refuse bad worker env vars; tracing still works
        path = os.environ.get("PATHWAY_TRACE_FILE")
    if path:
        _active = Tracer(path)
    elif os.environ.get("PATHWAY_TELEMETRY_SERVER") or os.environ.get(
        "PATHWAY_MONITORING_SERVER"
    ):
        # an OTLP endpoint alone still needs a span collector — file-less
        # tracer (the reference enables telemetry without local tracing,
        # telemetry.rs:215-221)
        _active = Tracer(None)
    else:
        _active = None
    _env_checked = True
    return _active


def get_tracer() -> Tracer | None:
    global _env_checked
    if not _env_checked:
        init_from_env()
    return _active


def span(name: str, **args: Any):
    """Span on the active tracer, or a no-op context when tracing is off —
    lets instrumentation sites keep a single code path."""
    import contextlib

    tracer = get_tracer()
    if tracer is None:
        return contextlib.nullcontext()
    return tracer.span(name, **args)
