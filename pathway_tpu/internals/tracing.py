"""In-process span tracing and run profiling.

Re-design of the reference's telemetry pair — Rust OTLP traces/metrics
(``src/engine/telemetry.rs:47-156``) and the Python build/run spans
(``python/pathway/internals/graph_runner/telemetry.py``,
``graph_runner/__init__.py:146-176``) — for an environment with no
network egress: instead of pushing OTLP over gRPC, the tracer records
spans in memory and writes the Chrome Trace Event format (the catapult
JSON array understood by ``chrome://tracing`` and ``ui.perfetto.dev``)
when the run finishes.

Activation is env-first like every other engine knob
(``internals/config.py``): set ``PATHWAY_TRACE_FILE=/path/run.json``.
When unset, ``get_tracer()`` returns ``None`` and every instrumentation
site is a single ``is None`` check — no timestamps are taken.

Span taxonomy (mirrors the reference's span names where it has them):

- ``graph.build`` — lowering the parse graph to engine nodes
  (reference span ``graph_runner/__init__.py:146``);
- ``engine.run`` — the whole executor run;
- ``tick`` — one logical-time sweep, with the minted timestamp attached;
- per-node events under each tick, named ``<NodeClass>#<id>``, with the
  emitted row count — the analog of timely's event logging stream
  (``DIFFERENTIAL_LOG_ADDR``, reference ``dataflow.rs:5540-5548``);
- counter samples of ``EngineStats`` totals per tick, rendered by the
  trace viewers as time series.

Multi-process runs write one file per process (``<path>.p<process_id>``,
like the per-process metrics ports of ``engine/http_server.rs:21``);
worker threads separate naturally by ``tid``. Cross-process linkage is
Dapper-style: every tracer carries a cluster-wide ``run_id``
(``PATHWAY_RUN_ID``, stamped by ``pathway-tpu spawn``), comm frames ship a
``(run_id, flow_id)`` trace context, and both ends emit Chrome flow
events (``ph: s``/``f``) bound by that id — ``pathway-tpu trace merge``
assembles the per-process files into one clock-aligned cluster timeline
(``observability/trace_merge.py``), using the per-peer clock offsets the
cluster handshake estimates (``parallel/cluster.py``) and records here via
:meth:`Tracer.set_clock_offsets`.
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time
from typing import Any

__all__ = [
    "Tracer",
    "activate",
    "deactivate",
    "get_tracer",
    "init_from_env",
    "mint_flow_tag",
    "span",
]


def mint_flow_tag() -> str:
    """Per-comm-instance disambiguator for deterministic flow ids (ids are
    ``<run_id>/<tag>/...``): several comm backends — or repeated ``pw.run``
    calls under ``activate()`` — share one tracer, and two instances
    minting ids from the same (channel, tick) coordinates must not
    collide. One shared definition so every comm layer's ids stay
    mergeable by the same scheme."""
    return secrets.token_hex(2)


def make_flow_id(tracer: "Tracer", tag: str, *coords: Any) -> str:
    """THE flow-id scheme: ``<run_id>/<tag>/<coord>/...``. Every comm
    backend builds its ids here — the run id scopes them cluster-wide,
    ``tag`` (a :func:`mint_flow_tag`) scopes them per comm instance, and
    the coordinates make them deterministic so sender and receiver can
    mint the same id without shipping context (LocalComm/MeshComm) or
    ship it once per frame (ClusterComm)."""
    return "/".join([tracer.run_id, tag, *map(str, coords)])


class _Span:
    __slots__ = ("tracer", "name", "args", "t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self) -> "_Span":
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> None:
        self.tracer.complete(self.name, self.t0, self.args or None)


class Tracer:
    """Collects Chrome-trace events; thread-safe, append-only.

    ``path=None`` collects without writing a local trace file — the mode
    used when only an OTLP endpoint (``internals/telemetry.py``) consumes
    the spans."""

    def __init__(self, path: str | None, max_events: int | None = None):
        self.path = path
        self._events: list[dict[str, Any]] = []
        self._lock = threading.Lock()
        self._pid = os.getpid()
        #: cluster-wide run identity: every process of one spawn shares it
        #: (the CLI stamps PATHWAY_RUN_ID), so flow ids minted here are
        #: unique AND recognizable across the whole ensemble's trace files
        self.run_id = os.environ.get("PATHWAY_RUN_ID") or secrets.token_hex(4)
        #: wall-clock anchor of the perf_counter origin — what lets the
        #: merge CLI (and the OTLP exporters) place this process's relative
        #: timestamps on a shared unix timeline
        unix_now = time.time_ns()
        #: perf_counter origin so timestamps start near zero in the viewer
        self._origin = time.perf_counter_ns()
        self.origin_unix_ns = unix_now
        #: peer process id -> (unix-clock offset ns, rtt ns), estimated by
        #: the cluster handshake ping (ClusterComm); written to the trace
        #: file so `trace merge` can align per-host clocks
        self._clock_offsets: dict[int, tuple[float, float]] = {}
        #: streaming pipelines run forever (run.py) — bound the buffer so
        #: tracing a long-lived run keeps the most recent window instead of
        #: growing without limit; oldest half is dropped on overflow
        if max_events is None:
            max_events = int(
                os.environ.get("PATHWAY_TRACE_MAX_EVENTS", "500000")
            )
        self._max_events = max(max_events, 2)
        self._dropped = 0
        self._appended = 0
        self._flush_mark = -1  # _appended value at the last write
        #: incremental-export cursor shared by the periodic OTLP flusher
        #: and the end-of-run push (internals/telemetry.py)
        self._otlp_mark = 0

    # -- recording ----------------------------------------------------

    def _ts(self, ns: int) -> float:
        return (ns - self._origin) / 1e3  # µs

    def span(self, name: str, **args: Any) -> _Span:
        """``with tracer.span("graph.build", tables=3): ...``"""
        return _Span(self, name, args)

    def complete(
        self,
        name: str,
        t0_ns: int,
        args: dict[str, Any] | None = None,
        counter: tuple[str, dict[str, float]] | None = None,
    ) -> None:
        """A finished duration event that began at ``t0_ns``. With
        ``counter=(name, values)`` a counter sample is appended in the SAME
        lock acquisition, so the pair is adjacent in the buffer and the
        overflow drop can never orphan the sample from its span (the
        executor's per-tick row counters use this)."""
        ev = {
            "name": name,
            "ph": "X",
            "ts": self._ts(t0_ns),
            "dur": (time.perf_counter_ns() - t0_ns) / 1e3,
            "pid": self._pid,
            "tid": threading.get_ident() % 2**31,
        }
        if args:
            ev["args"] = args
        if counter is None:
            self._append(ev)
            return
        cname, values = counter
        cev = {
            "name": cname,
            "ph": "C",
            "ts": ev["ts"] + ev["dur"],
            "pid": self._pid,
            "args": values,
        }
        self._append(ev, cev)

    def _append(self, *evs: dict[str, Any]) -> None:
        with self._lock:
            self._events.extend(evs)
            self._appended += len(evs)
            if len(self._events) > self._max_events:
                n = len(self._events)
                drop = n // 2
                # span-boundary-consistent chunking: never let the kept
                # window BEGIN with a counter sample whose owning span was
                # just dropped (complete(..., counter=...) appends the pair
                # adjacently, so skipping leading "C" events preserves it)
                while drop < n and self._events[drop].get("ph") == "C":
                    drop += 1
                if drop >= n:  # pathological all-counter buffer
                    drop = n // 2
                self._dropped += drop
                del self._events[:drop]

    def instant(self, name: str, **args: Any) -> None:
        ev = {
            "name": name,
            "ph": "i",
            "s": "t",
            "ts": self._ts(time.perf_counter_ns()),
            "pid": self._pid,
            "tid": threading.get_ident() % 2**31,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def counter(self, name: str, values: dict[str, float]) -> None:
        """A counter sample (rendered as stacked time series). Callers with
        per-worker counters must put the worker id in ``name`` — trace
        viewers key counter tracks by (pid, name), so same-named samples
        from different workers would interleave into one garbled series."""
        self._append(
            {
                "name": name,
                "ph": "C",
                "ts": self._ts(time.perf_counter_ns()),
                "pid": self._pid,
                "args": values,
            }
        )

    # -- cross-worker flow linkage ------------------------------------

    def flow_start(self, name: str, flow_id: str, **args: Any) -> None:
        """Begin a Chrome flow (``ph: s``) — the sending half of a
        cross-worker arrow. The event must fall inside a duration slice on
        this thread (comm call sites sit inside the tick span); the
        receiving side closes the flow with :meth:`flow_end` using the
        SAME id, which travels in the comm frame's trace context."""
        ev = {
            "name": name,
            "cat": "comm",
            "ph": "s",
            "id": str(flow_id),
            "ts": self._ts(time.perf_counter_ns()),
            "pid": self._pid,
            "tid": threading.get_ident() % 2**31,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def flow_end(self, name: str, flow_id: str, **args: Any) -> None:
        """Close a flow (``ph: f``) at the receiving worker; ``bp: e``
        binds the arrow to the enclosing slice."""
        ev = {
            "name": name,
            "cat": "comm",
            "ph": "f",
            "bp": "e",
            "id": str(flow_id),
            "ts": self._ts(time.perf_counter_ns()),
            "pid": self._pid,
            "tid": threading.get_ident() % 2**31,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    # -- merge/alignment metadata -------------------------------------

    def set_clock_offsets(self, offsets: dict[int, tuple[float, float]]) -> None:
        """Record per-peer unix-clock offset estimates (peer process id ->
        (offset ns, rtt ns), offset = peer clock minus ours) from the
        cluster handshake ping — flushed as ``trace.clock_sync`` metadata
        for ``pathway-tpu trace merge``."""
        with self._lock:
            self._clock_offsets = dict(offsets)

    def events_since(self, mark: int) -> tuple[list[dict[str, Any]], int]:
        """Events appended after the ``mark`` cursor (an ``_appended``
        value), plus the new cursor — the incremental-export protocol used
        by the periodic OTLP flusher (observability/exporter.py) and the
        end-of-run push, which share one cursor so nothing double-exports.
        Events already dropped by the ring buffer are simply gone: when
        more than ``new`` events were appended but the buffer holds fewer,
        the negative slice caps at the buffer — every returned event is
        still strictly after ``mark`` (the buffer always holds the newest
        ``len(_events)`` appends), so a drop can neither skip live events
        nor re-export old ones (tests/test_tracing.py drop-cursor cases)."""
        with self._lock:
            new = self._appended - mark
            if new <= 0:
                return [], self._appended
            return list(self._events[-new:]), self._appended

    # -- output -------------------------------------------------------

    def flush(self) -> str | None:
        """Write the full event buffer to the trace file. Re-flushable: a
        tracer kept alive across several ``pw.run`` calls (``activate()``)
        rewrites the file with the accumulated events each time; a flush
        with nothing new since the last write is a no-op. Never raises —
        tracing is auxiliary and must not fail (or mask the error of) the
        run it observes."""
        if self.path is None:  # OTLP-only mode: no local file
            return None
        with self._lock:
            if self._flush_mark == self._appended:
                return None
            self._flush_mark = self._appended
            events = list(self._events)
        path = self.path
        # raw env read, not PathwayConfig: config validation can refuse the
        # worker layout (e.g. over the worker cap) and flush must not raise
        try:
            n_processes = int(os.environ.get("PATHWAY_PROCESSES", "1"))
            process_id = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
        except ValueError:
            n_processes, process_id = 1, 0
        if n_processes > 1:
            path = f"{path}.p{process_id}"
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self._pid,
                "args": {"name": "pathway_tpu"},
            },
            # merge/alignment anchor: run identity, this process's place in
            # the ensemble, its unix-clock origin, and the handshake's
            # per-peer clock-offset estimates (trace_merge.py consumes it)
            {
                "name": "trace.clock_sync",
                "ph": "i",
                "s": "g",
                "ts": 0.0,
                "pid": self._pid,
                "tid": 0,
                "args": {
                    "run_id": self.run_id,
                    "process_id": process_id,
                    "origin_unix_ns": self.origin_unix_ns,
                    "clock_offsets": {
                        str(p): [off, rtt]
                        for p, (off, rtt) in sorted(
                            self._clock_offsets.items()
                        )
                    },
                },
            },
        ]
        if self._dropped:
            meta.append(
                {
                    "name": "trace.dropped_events",
                    "ph": "i",
                    "s": "g",
                    "ts": 0.0,
                    "pid": self._pid,
                    "tid": 0,
                    "args": {"count": self._dropped},
                }
            )
        try:
            # atomic rewrite: the periodic flusher rewrites this file every
            # interval, and a SIGKILL mid-write must leave the PREVIOUS
            # complete flush on disk, not a torn JSON — crashed runs are
            # exactly the ones whose trace gets read
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {"traceEvents": meta + events, "displayTimeUnit": "ms"}, f
                )
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError) as e:
            import warnings

            warnings.warn(
                f"could not write trace file {path!r}: {e}", RuntimeWarning
            )
            return None
        return path


_active: Tracer | None = None
_env_checked = False
_programmatic = False


def activate(path: str) -> Tracer:
    """Programmatic activation (the env var is the usual route). Survives
    ``pw.run``'s env re-read until ``deactivate()``."""
    global _active, _env_checked, _programmatic
    _active = Tracer(path)
    _env_checked = True
    _programmatic = True
    return _active


def deactivate() -> None:
    global _active, _env_checked, _programmatic
    _active = None
    _env_checked = True
    _programmatic = False


def init_from_env() -> Tracer | None:
    """Install a tracer if ``PATHWAY_TRACE_FILE`` is set (read through
    ``PathwayConfig`` so the config snapshot and the tracer agree). Called
    at the top of every run so each ``pw.run`` re-reads the environment; a
    tracer installed via ``activate()`` is kept as-is."""
    global _active, _env_checked
    if _programmatic:
        return _active
    try:
        from .config import get_pathway_config

        path = get_pathway_config().trace_file
    except (ImportError, RuntimeError):
        # config can refuse bad worker env vars; tracing still works
        path = os.environ.get("PATHWAY_TRACE_FILE")
    if path:
        _active = Tracer(path)
    elif os.environ.get("PATHWAY_TELEMETRY_SERVER") or os.environ.get(
        "PATHWAY_MONITORING_SERVER"
    ):
        # an OTLP endpoint alone still needs a span collector — file-less
        # tracer (the reference enables telemetry without local tracing,
        # telemetry.rs:215-221)
        _active = Tracer(None)
    else:
        _active = None
    _env_checked = True
    return _active


def get_tracer() -> Tracer | None:
    global _env_checked
    if not _env_checked:
        init_from_env()
    return _active


def span(name: str, **args: Any):
    """Span on the active tracer, or a no-op context when tracing is off —
    lets instrumentation sites keep a single code path."""
    import contextlib

    tracer = get_tracer()
    if tracer is None:
        return contextlib.nullcontext()
    return tracer.span(name, **args)
