"""``pw.global_error_log()`` — the process error log as a live table.

Reference: ``pw.global_error_log()`` (``internals/errors.py``) exposes the
engine's error-log channel as a queryable table; tests assert on
``global_error_log().select(pw.this.message)`` alongside the pipeline
output. Here the table is a realtime source draining
``engine.error.ERROR_LOG`` entries recorded after the run starts: each
sweep of the event loop picks up errors the previous tick raised, so the
final table holds exactly this run's row errors.
"""

from __future__ import annotations

import numpy as np

from ..engine.delta import Delta
from ..engine.executor import RealtimeSource
from .parse_graph import Universe
from .schema import schema_from_types
from .table import Table

__all__ = ["global_error_log", "local_error_log"]

#: build-time scope stack for ``pw.local_error_log()``: tables created
#: while a scope is active are tagged with its id, the executor restores
#: the tag around their nodes' processing, and the scoped log table
#: filters on it
_scope_seq = 0
_scope_stack: list[int] = []


def current_build_scope() -> int | None:
    return _scope_stack[-1] if _scope_stack else None


class _ErrorLogSource(RealtimeSource):
    """Emits (message, context) rows for log entries recorded since the
    run began (offset captured at build time = run start); ``scope``
    filters to one local_error_log scope."""

    def __init__(self, columns: list[str], scope: int | None = None):
        super().__init__(columns)
        from ..engine.error import ERROR_LOG

        self._log = ERROR_LOG
        self._scope = scope
        #: lifetime index of the next entry to surface — stays valid past
        #: the retention cap because the log is a ring with a monotonic
        #: base, not a frozen prefix (advisor-medium error_log_table.py)
        self._seen = ERROR_LOG.next_index

    def poll(self):
        from ..engine import keys as K

        start, new, self._seen = self._log.entries_since(self._seen)
        if not new:
            return []
        if self._scope is not None:
            new = [
                (start + i, m, c)
                for i, (m, c, sc) in enumerate(new)
                if sc == self._scope
            ]
        else:
            new = [(start + i, m, c) for i, (m, c, _) in enumerate(new)]
        if not new:
            return []
        keys = K.hash_values(
            [(ix, m, c) for ix, m, c in new],
            register=False,  # sequential identity, collision-free by index
        )
        msg = np.empty(len(new), dtype=object)
        ctx = np.empty(len(new), dtype=object)
        for i, (_, m, c) in enumerate(new):
            msg[i] = m
            ctx[i] = c
        return [Delta(keys=keys, data={"message": msg, "context": ctx})]

    def is_finished(self) -> bool:
        # nothing pending: the run ends when every OTHER source is also
        # finished (the event loop requires all-finished AND no rounds), so
        # errors raised by the final data tick still get drained first
        return self._log.next_index == self._seen


def _log_table(scope: int | None) -> Table:
    def build() -> _ErrorLogSource:
        return _ErrorLogSource(["message", "context"], scope)

    return Table(
        "source",
        [],
        {"build": build},
        schema_from_types(message=str, context=str),
        Universe(),
    )


def global_error_log() -> Table:
    """The error log of the current run as a table of
    ``(message, context)`` rows (reference ``pw.global_error_log()``)."""
    return _log_table(None)


class local_error_log:
    """``with pw.local_error_log() as log:`` — tables BUILT inside the
    block route their runtime row errors to ``log`` (a table like
    ``global_error_log()``, filtered to this scope) as well as the global
    log (reference ``pw.local_error_log``, test_errors.py:262)."""

    def __enter__(self) -> Table:
        global _scope_seq
        _scope_seq += 1
        self._scope = _scope_seq
        _scope_stack.append(self._scope)
        return _log_table(self._scope)

    def __exit__(self, *exc) -> None:
        _scope_stack.pop()
