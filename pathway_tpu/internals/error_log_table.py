"""``pw.global_error_log()`` — the process error log as a live table.

Reference: ``pw.global_error_log()`` (``internals/errors.py``) exposes the
engine's error-log channel as a queryable table; tests assert on
``global_error_log().select(pw.this.message)`` alongside the pipeline
output. Here the table is a realtime source draining
``engine.error.ERROR_LOG`` entries recorded after the run starts: each
sweep of the event loop picks up errors the previous tick raised, so the
final table holds exactly this run's row errors.
"""

from __future__ import annotations

import numpy as np

from ..engine.delta import Delta
from ..engine.executor import RealtimeSource
from .parse_graph import Universe
from .schema import schema_from_types
from .table import Table

__all__ = ["global_error_log"]


class _ErrorLogSource(RealtimeSource):
    """Emits (message, context) rows for log entries recorded since the
    run began (offset captured at build time = run start)."""

    def __init__(self, columns: list[str]):
        super().__init__(columns)
        from ..engine.error import ERROR_LOG

        self._log = ERROR_LOG
        self._seen = len(ERROR_LOG.entries())

    def poll(self):
        from ..engine import keys as K

        entries = self._log.entries()
        new = entries[self._seen :]
        if not new:
            return []
        start = self._seen
        self._seen = len(entries)
        keys = K.hash_values(
            [(start + i, m, c) for i, (m, c) in enumerate(new)],
            register=False,  # sequential identity, collision-free by index
        )
        msg = np.empty(len(new), dtype=object)
        ctx = np.empty(len(new), dtype=object)
        for i, (m, c) in enumerate(new):
            msg[i] = m
            ctx[i] = c
        return [Delta(keys=keys, data={"message": msg, "context": ctx})]

    def is_finished(self) -> bool:
        # nothing pending: the run ends when every OTHER source is also
        # finished (the event loop requires all-finished AND no rounds), so
        # errors raised by the final data tick still get drained first
        return len(self._log.entries()) == self._seen


def global_error_log() -> Table:
    """The error log of the current run as a table of
    ``(message, context)`` rows (reference ``pw.global_error_log()``)."""

    def build() -> _ErrorLogSource:
        return _ErrorLogSource(["message", "context"])

    return Table(
        "source",
        [],
        {"build": build},
        schema_from_types(message=str, context=str),
        Universe(),
    )
