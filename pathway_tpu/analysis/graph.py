"""Side-effect-free lowering + structural operator fingerprints.

The analyzer walks the COMPILED dataflow graph — the same engine-operator
nodes ``pw.run`` would execute — not the declarative parse graph: every
diagnostic then reasons about what actually runs (post expression
compilation, post groupby/join decomposition), exactly the stage the
reference engine checks whole expression DAGs at (``src/engine/
expression.rs``). :class:`AnalysisGraphRunner` reuses the real
``GraphRunner`` lowering but stubs the delivery layer (no files opened,
no connections dialed) and records sink metadata + node→table provenance
for diagnostics.

Fingerprints: every operator gets a structural hash derived from its
class, construction parameters (``Node.analysis_signature``), compiled
expression trees and its inputs' fingerprints — identity-free, so two
compiles of the same script agree bit-for-bit while any graph change
propagates downstream. This is the stable operator identity primitive
zero-downtime graph-version migration needs (ROADMAP item 4).
"""

from __future__ import annotations

import hashlib
from typing import Any

from ..engine.executor import Node, SourceNode, _topological
from ..internals.graph_runner import GraphRunner
from ..internals.parse_graph import G

__all__ = [
    "AnalysisGraphRunner",
    "expr_fingerprint",
    "fingerprint_nodes",
    "lower_current_graph",
    "node_labels",
]


class _NullDeliverySink:
    """Stands in for a DeliverySink during analysis: the Subscribe node
    gets real callables, nothing external is ever opened."""

    @staticmethod
    def on_batch(*a: Any, **k: Any) -> None:  # pragma: no cover
        return None

    @staticmethod
    def on_end(*a: Any, **k: Any) -> None:  # pragma: no cover
        return None


class AnalysisGraphRunner(GraphRunner):
    """GraphRunner that lowers WITHOUT execution side effects and records
    provenance the passes need."""

    def __init__(self) -> None:
        super().__init__()
        #: delivery sink specs in registration order (io/delivery.deliver)
        self.sink_specs: list[dict] = []
        #: plain (non-delivery) subscribe sinks seen
        self.plain_sinks: int = 0
        #: id(node) -> Table that lowered to it (diagnostic locations)
        self.node_tables: dict[int, Any] = {}

    def lower(self, table: Any) -> Node:
        before = len(self._nodes)
        node = super().lower(table)
        # every node minted while lowering THIS table inherits its
        # provenance; nested lower() calls already claimed their own
        # spans (setdefault keeps the innermost, most precise owner)
        for minted in self._nodes[before:]:
            self.node_tables.setdefault(id(minted), table)
        self.node_tables.setdefault(id(node), table)
        return node

    def lower_sink(self, sink: Any) -> None:
        if sink.get("kind") == "subscribe" and not sink.get("delivery"):
            self.plain_sinks += 1
        super().lower_sink(sink)

    def _build_delivery_sink(self, spec: dict) -> Any:
        # record, never instantiate: adapter factories open files/dial
        # connections — analysis must observe the graph, not touch the world
        self.sink_specs.append(spec)
        return _NullDeliverySink


def lower_current_graph() -> AnalysisGraphRunner:
    """Lower every sink registered on the global parse graph (what
    ``pw.run`` would execute) through the analysis runner."""
    runner = AnalysisGraphRunner()
    for sink in G.sinks:
        runner.lower_sink(sink)
    return runner


# ---------------------------------------------------------------------------
# structural fingerprints
# ---------------------------------------------------------------------------


def _const_repr(c: Any) -> str:
    """Canonical, process-independent repr of a code/default constant.
    Plain ``repr`` is NOT stable across processes for everything the
    bytecode compiler can intern: frozenset literals (``x in {"a","b"}``)
    iterate in hash-randomized order, and arbitrary objects embed memory
    addresses. Sets sort by element repr; containers recurse; anything
    without a value-based repr degrades to its type name."""
    if isinstance(c, (frozenset, set)):
        return "{" + ",".join(sorted(_const_repr(e) for e in c)) + "}"
    if isinstance(c, tuple):
        return "(" + ",".join(_const_repr(e) for e in c) + ")"
    if c is None or isinstance(c, (bool, int, float, complex, str, bytes)):
        return repr(c)
    r = repr(c)
    # value-based reprs (dtypes, enums) are stable and informative; a
    # default object repr embeds a memory address — degrade to the type
    return r if " at 0x" not in r else type(c).__name__


def _code_fp(code: Any, h: "hashlib._Hash") -> None:
    """Fold a code object into the hash, identity-free: raw bytecode +
    global/attribute names + canonicalized non-code constants (nested
    code objects recurse — their repr embeds a memory address and must
    never be hashed). Local variable names (``co_varnames``) are
    deliberately NOT hashed: bytecode addresses locals by slot, so a
    pure rename is semantically invisible — and graph-version migration
    relies on renames not moving fingerprints."""
    h.update(code.co_code)
    h.update(repr(code.co_names).encode())
    for const in code.co_consts:
        if hasattr(const, "co_code"):
            _code_fp(const, h)
        else:
            h.update(_const_repr(const).encode())


def _fn_fp(fn: Any, h: "hashlib._Hash") -> None:
    code = getattr(fn, "__code__", None)
    if code is not None:
        _code_fp(code, h)
        h.update(_const_repr(getattr(fn, "__defaults__", None) or ()).encode())
    else:
        h.update(type(fn).__name__.encode())


#: expression attributes that carry structural identity, in hash order
_SALIENT_ATTRS = (
    "_op", "_method", "_method_kwargs", "_value", "name", "_name",
    "_reducer", "_return_type", "_engine_name", "_dtype",
    "_propagate_none", "_deterministic",
)


def expr_fingerprint(expr: Any, h: "hashlib._Hash") -> None:
    """Fold one ColumnExpression tree into the hash: node type, salient
    parameters (operator symbol, method name, constant value, referenced
    column NAME — never table identity), UDF bytecode, and children via
    ``_deps``."""
    h.update(type(expr).__name__.encode())
    for attr in _SALIENT_ATTRS:
        v = getattr(expr, attr, None)
        if v is not None and not hasattr(v, "_deps"):
            h.update(attr.encode())
            if isinstance(v, dict):
                h.update(repr(sorted(
                    (k, _const_repr(x)) for k, x in v.items()
                )).encode())
            else:
                h.update(_const_repr(v).encode())
    fn = getattr(expr, "_fn", None)
    if fn is not None:
        _fn_fp(fn, h)
    for dep in getattr(expr, "_deps", ()):
        expr_fingerprint(dep, h)


def _compiled_fn_fp(fn: Any, h: "hashlib._Hash") -> None:
    """Fingerprint one compiled per-column kernel: prefer the tagged
    source expression (identity-free, survives recompiles); engine-
    internal closures (projections, join-key mixers) hash by bytecode."""
    expr = getattr(fn, "_pw_expr", None)
    if expr is not None:
        expr_fingerprint(expr, h)
        return
    key_fns = getattr(fn, "_pw_key_fns", None)
    if key_fns is not None:
        h.update(b"jk")
        for kf in key_fns:
            _compiled_fn_fp(kf, h)
        return
    _fn_fp(fn, h)


def fingerprint_nodes(nodes: list[Node]) -> dict[int, str]:
    """id(node) -> structural fingerprint hex for every node, computed in
    topological order so each fingerprint folds in its inputs'."""
    order = _topological(nodes)
    fps: dict[int, str] = {}
    for node in order:
        if getattr(node, "FINGERPRINT_TRANSPARENT", False) and node.inputs:
            # Exchange: sharding inserts it, offline lowering doesn't —
            # pass the input's fingerprint through so the manifests a
            # live sharded run and an unsharded `upgrade --plan` compile
            # write agree bit-for-bit
            fps[id(node)] = fps[id(node.inputs[0])]
            continue
        h = hashlib.sha256()
        h.update(type(node).__name__.encode())
        h.update(repr(tuple(node.column_names)).encode())
        try:
            h.update(repr(node.analysis_signature()).encode())
        except Exception:
            pass
        exprs = getattr(node, "analysis_exprs", None)
        if exprs is not None:
            for name, fn in exprs().items():
                h.update(name.encode())
                _compiled_fn_fp(fn, h)
        if isinstance(node, SourceNode):
            pid = getattr(node, "persistent_id", None)
            if pid:
                h.update(str(pid).encode())
        for inp in node.inputs:
            h.update(fps[id(inp)].encode())
        fps[id(node)] = h.hexdigest()[:16]
    return fps


def node_labels(nodes: list[Node]) -> dict[int, str]:
    """id(node) -> stable display label ("<topo index>:<class>") — NOT the
    process-global node_id, which differs between two compiles."""
    order = _topological(nodes)
    return {
        id(n): f"{i}:{type(n).__name__}" for i, n in enumerate(order)
    }
