"""Analyzer passes over the compiled dataflow graph.

Each pass is a function ``(ctx: AnalysisContext) -> list[Diagnostic]``;
:func:`run_passes` runs them all. Passes reason about the ENGINE nodes
(post expression compilation), using the introspection hooks the
operators expose (``ANALYSIS_STATE_BOUNDED``, ``analysis_forgets``,
``analysis_exprs``) plus the compile-time breadcrumbs the expression
compiler leaves on its kernels (``_pw_expr``/``_pw_dtype``/
``_pw_lift_outcome``).
"""

from __future__ import annotations

import dis
import os
from typing import Any, Callable, Iterator

from ..engine import operators as ops
from ..engine.executor import Node, RealtimeSource
from ..internals import dtype as dt
from ..internals import lintmode
from ..internals.expression import (
    ApplyExpression,
    AsyncApplyExpression,
    ColumnConstExpression,
)
from .graph import AnalysisGraphRunner, node_labels
from .report import Diagnostic

__all__ = ["AnalysisContext", "run_passes", "PASSES"]

#: the PR-8 spill budget knob — its presence downgrades unbounded-state
#: growth from a future OOM to graceful disk degradation
_SPILL_BUDGET_ENV = "PATHWAY_STATE_MEMORY_BUDGET_MB"


class AnalysisContext:
    def __init__(
        self,
        runner: AnalysisGraphRunner,
        persistence_config: Any = None,
        n_workers: int | None = None,
    ) -> None:
        self.runner = runner
        self.nodes: list[Node] = list(runner._nodes)
        self.labels = node_labels(self.nodes)
        if persistence_config is None and lintmode.ACTIVE:
            persistence_config = lintmode.CAPTURE.get("persistence_config")
        self.persistence_config = persistence_config
        if n_workers is None:
            env = os.environ.get("PATHWAY_LINT_WORKERS")
            if env:
                try:
                    n_workers = int(env)
                except ValueError:
                    n_workers = None
        if n_workers is None:
            from ..internals.config import get_pathway_config

            try:
                n_workers = get_pathway_config().total_workers
            except Exception:
                n_workers = 1
        self.n_workers = max(1, int(n_workers))
        #: consumer fan-out per node (id -> count)
        self.consumers: dict[int, int] = {}
        for n in self.nodes:
            for inp in n.inputs:
                self.consumers[id(inp)] = self.consumers.get(id(inp), 0) + 1

    # -- provenance helpers -------------------------------------------------

    def location_of(self, node: Node) -> tuple[str, int] | None:
        table = self.runner.node_tables.get(id(node))
        seq = getattr(table, "_table_seq", None)
        if seq is None:
            return None
        return lintmode.LOCATIONS.get(seq)

    def label(self, node: Node) -> str:
        return self.labels.get(id(node), f"?:{type(node).__name__}")

    @property
    def persisted(self) -> bool:
        return self.persistence_config is not None

    @property
    def transactional_sinks(self) -> list[dict]:
        return self.runner.sink_specs


# ---------------------------------------------------------------------------
# shared walkers
# ---------------------------------------------------------------------------


def _node_exprs(node: Node) -> Iterator[tuple[str, Any]]:
    """(column name, tagged source expression) for every compiled kernel
    of an expression-bearing node that carries a compile breadcrumb."""
    hook = getattr(node, "analysis_exprs", None)
    if hook is None:
        return
    for name, fn in hook().items():
        expr = getattr(fn, "_pw_expr", None)
        if expr is not None:
            yield name, expr


def _walk_expr(expr: Any) -> Iterator[Any]:
    yield expr
    for dep in getattr(expr, "_deps", ()):
        yield from _walk_expr(dep)


def _iter_applies(ctx: AnalysisContext) -> Iterator[tuple[Node, Any]]:
    """Every (node, ApplyExpression) in the graph, deduplicated by the
    UDF's code object (one diagnostic per UDF, not per re-use)."""
    seen: set[Any] = set()
    for node in ctx.nodes:
        for _name, expr in _node_exprs(node):
            for e in _walk_expr(expr):
                if isinstance(e, ApplyExpression):
                    code = getattr(e._fn, "__code__", None)
                    key = code if code is not None else id(e)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield node, e


def _udf_location(fn: Callable) -> tuple[str, int] | None:
    code = getattr(fn, "__code__", None)
    if code is None:
        return None
    return (code.co_filename, code.co_firstlineno)


def _udf_name(fn: Callable) -> str:
    return getattr(fn, "__name__", None) or repr(fn)


# ---------------------------------------------------------------------------
# pass: unbounded-state growth
# ---------------------------------------------------------------------------


def _reaches_live_source(node: Node) -> bool:
    """True when an input path from a never-ending source reaches ``node``
    without crossing a forgetting operator (ForgetAfter with
    forget_state) — the condition under which keyed state grows for as
    long as the stream runs."""
    stack = list(node.inputs)
    seen: set[int] = set()
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        if n.analysis_forgets():
            continue  # rows are retracted past the watermark: bounded below
        if isinstance(n, RealtimeSource):
            return True
        stack.extend(n.inputs)
    return False


def pass_unbounded_state(ctx: AnalysisContext) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    budget = os.environ.get(_SPILL_BUDGET_ENV)
    for node in ctx.nodes:
        if node.ANALYSIS_STATE_BOUNDED is not False:
            continue
        if not _reaches_live_source(node):
            continue
        kind = type(node).__name__
        if budget:
            out.append(Diagnostic(
                "unbounded-state",
                f"{kind} accumulates state for every distinct key of a "
                f"never-ending source; the {_SPILL_BUDGET_ENV}={budget} "
                "spill budget degrades it to disk instead of OOM, but "
                "state (and recovery time) still grows forever",
                severity="info",
                operator=ctx.label(node),
                location=ctx.location_of(node),
                mitigation=(
                    "add a temporal cutoff upstream (windowby(...) with a "
                    "cutoff behavior / ForgetAfter) so old keys retract"
                ),
            ))
        else:
            out.append(Diagnostic(
                "unbounded-state",
                f"{kind} accumulates state for every distinct key of a "
                "never-ending source with no temporal cutoff upstream — "
                "memory grows for as long as the stream runs",
                operator=ctx.label(node),
                location=ctx.location_of(node),
                mitigation=(
                    "add a temporal cutoff upstream (windowby(...) with a "
                    "cutoff behavior / ForgetAfter), or set "
                    f"{_SPILL_BUDGET_ENV} so cold state spills to disk "
                    "(PR-8 memory budget) instead of OOMing"
                ),
            ))
    return out


# ---------------------------------------------------------------------------
# pass: replay determinism
# ---------------------------------------------------------------------------

#: module globals whose mere use inside a UDF makes replay diverge
_NONDET_GLOBALS = {"random", "secrets", "time"}
#: builtins that reach outside the row (io / entropy)
_NONDET_BUILTINS = {"open", "input"}
#: module -> attributes that are nondeterministic (the module itself is
#: fine: ``datetime.datetime(2024, 1, 1)`` replays exactly and
#: ``uuid.UUID(s)``/``uuid5`` are pure parsing/hashing; ``.now()`` and
#: ``uuid4()`` are not)
_NONDET_ATTRS = {
    "datetime": {"now", "today", "utcnow"},
    # `datetime.datetime.now()` pairs through the dotted chain
    "datetime.datetime": {"now", "today", "utcnow"},
    "datetime.date": {"today"},
    "os": {"urandom", "getpid"},
    "uuid": {"uuid1", "uuid4", "getnode"},
    "np": {"random"},
    "numpy": {"random"},
}


def nondeterminism_evidence(fn: Callable) -> list[str]:
    """RNG/time/io reads visible in ``fn``'s bytecode — the same
    dis-level inspection the udf_lift gates use, pointed at replay
    hazards instead of liftability."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return []
    try:
        instructions = list(dis.get_instructions(fn))
    except TypeError:
        return []
    hits: list[str] = []
    pending: str | None = None
    #: local variable -> module it was import-bound to (a function-local
    #: `import uuid` reaches `uuid.uuid4` via STORE_FAST/LOAD_FAST, never
    #: LOAD_GLOBAL)
    local_mods: dict[str, str] = {}
    for ins in instructions:
        name = ins.opname
        if name.startswith("IMPORT_NAME"):
            mod = (ins.argval or "").split(".")[0]
            if mod in _NONDET_GLOBALS:
                hits.append(mod)
            pending = mod
        elif name.startswith("STORE_FAST"):
            if pending is not None and pending in _NONDET_ATTRS:
                local_mods[ins.argval] = pending
            pending = None
        elif name.startswith("LOAD_FAST"):
            pending = local_mods.get(ins.argval)
        elif name.startswith("LOAD_GLOBAL"):
            g = ins.argval
            if g in _NONDET_GLOBALS:
                hits.append(g)
            elif g in _NONDET_BUILTINS:
                hits.append(f"{g}()")
            pending = g
        elif name.startswith(("LOAD_ATTR", "LOAD_METHOD")):
            if pending is not None:
                allowed = _NONDET_ATTRS.get(pending)
                if allowed and ins.argval in allowed:
                    hits.append(f"{pending}.{ins.argval}")
                pending = f"{pending}.{ins.argval}"
        else:
            pending = None
    # stable order, deduplicated
    return sorted(set(hits))


def pass_replay_determinism(ctx: AnalysisContext) -> list[Diagnostic]:
    if not ctx.persisted and not ctx.transactional_sinks:
        # nothing replays and nothing is exactly-once: a wall-clock UDF
        # is a choice, not a correctness hazard
        return []
    surface = (
        "persisted (state replays after recovery)"
        if ctx.persisted
        else "feeding exactly-once sinks"
    )
    out: list[Diagnostic] = []
    for _node, expr in _iter_applies(ctx):
        evidence = nondeterminism_evidence(expr._fn)
        if not evidence:
            continue
        out.append(Diagnostic(
            "nondeterministic-udf",
            f"UDF {_udf_name(expr._fn)!r} calls {', '.join(evidence)} in a "
            f"pipeline that is {surface}: a recovery replay re-executes it "
            "and produces different values than the original run",
            location=_udf_location(expr._fn),
            mitigation=(
                "move the nondeterminism into the input (stamp rows at "
                "ingest), or make the UDF a pure function of its arguments"
            ),
        ))
    return out


# ---------------------------------------------------------------------------
# pass: per-row dispatch tax
# ---------------------------------------------------------------------------


def pass_dispatch_tax(ctx: AnalysisContext) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for _node, expr in _iter_applies(ctx):
        outcome = getattr(expr, "_pw_lift_outcome", None)
        if outcome is None or outcome.get("status") != "dynamic":
            continue
        if outcome.get("traceable"):
            continue  # the probe-row trace will compile it at runtime
        refusal = outcome.get("refusal") or "outside the liftable subset"
        out.append(Diagnostic(
            "perrow-udf",
            f"UDF {_udf_name(expr._fn)!r} runs per-row Python on every "
            f"batch (static lift refused: {refusal}; probe-trace gate "
            "refused too)",
            location=_udf_location(expr._fn),
            mitigation=(
                "rewrite within the liftable subset (pure expressions, "
                "method chains, conditionals — see README 'Writing fast "
                "UDFs'), or hoist the blocking construct out of the UDF"
            ),
        ))
    return out


# ---------------------------------------------------------------------------
# pass: fusion readiness (ROADMAP item 3's scouting report)
# ---------------------------------------------------------------------------


def pass_fusion_readiness(ctx: AnalysisContext) -> list[Diagnostic]:
    """Cross-check of the compiler's ACTUAL fusion decisions: the same
    chain walk the executor's fusion pass performs (engine/fusion.py
    ``plan_chains`` — one implementation, so analyzer and compiler can
    never disagree on chain shape), with each chain's fuse/decline
    verdict surfaced. A fused chain is an info note; a chain the
    compiler detected but DECLINED carries the verbatim decline reason
    at warning severity — the same reason-plumbing contract the
    ``_LIFT_REFUSED`` per-row diagnostics established."""
    from ..engine.fusion import plan_chains

    out: list[Diagnostic] = []
    for plan in plan_chains(ctx.nodes):
        chain = plan.members
        # every internal boundary re-enters Python dispatch and
        # materializes the upstream node's full column set
        cost = sum(len(m.column_names) for m in chain[:-1])
        shape = "→".join(type(m).__name__ for m in chain)
        if plan.fused:
            out.append(Diagnostic(
                "fusion-chain",
                f"pure linear chain {shape} ({len(chain)} operators) "
                f"fuses into one compiled kernel — ~{cost} intermediate "
                "column(s) per batch stop materializing between nodes",
                severity="info",
                operator=ctx.label(chain[0]),
                location=ctx.location_of(chain[0]),
                mitigation=None,
            ))
        else:
            out.append(Diagnostic(
                "fusion-chain",
                f"linear chain {shape} ({len(chain)} operators) "
                f"materializes ~{cost} intermediate column(s) per batch "
                f"but the compiler declined to fuse it: {plan.reason}",
                severity="warning",
                operator=ctx.label(chain[0]),
                location=ctx.location_of(chain[0]),
                mitigation=(
                    "resolve the decline reason (or unset PATHWAY_FUSION=0) "
                    "so the chain compiles into one kernel"
                ),
            ))
    return out


# ---------------------------------------------------------------------------
# pass: shard skew
# ---------------------------------------------------------------------------


def _key_cardinality(fn: Any) -> int | None:
    """Static upper bound on a key kernel's distinct values, when the
    dtype proves one (BOOL -> 2, constant -> 1); None = unknown."""
    expr = getattr(fn, "_pw_expr", None)
    if isinstance(expr, ColumnConstExpression):
        return 1
    dtype = getattr(fn, "_pw_dtype", None)
    if dtype is not None and dt.unoptionalize(dtype) == dt.BOOL:
        return 2
    return None


def _key_fns_of(node: Node) -> list[Any] | None:
    """The key kernels a keyed-state operator routes by, read off its
    input Rowwise node (the lowering always materializes keys there)."""
    if isinstance(node, ops.GroupByReduce):
        inp = node.inputs[0]
        hook = getattr(inp, "analysis_exprs", None)
        if hook is None:
            return None
        exprs = hook()
        fns = [exprs.get(c) for c in node._group_cols]
        return [f for f in fns if f is not None] or None
    if isinstance(node, ops.Join):
        fns = []
        for side in node.inputs:
            hook = getattr(side, "analysis_exprs", None)
            if hook is None:
                continue
            jk = hook().get("__jk__")
            key_fns = getattr(jk, "_pw_key_fns", None)
            if key_fns:
                fns.append(list(key_fns))
        return fns[0] if fns else None
    return None


def pass_shard_skew(ctx: AnalysisContext) -> list[Diagnostic]:
    if ctx.n_workers <= 1:
        return []
    out: list[Diagnostic] = []
    for node in ctx.nodes:
        fns = _key_fns_of(node)
        if not fns:
            continue
        cards = [_key_cardinality(f) for f in fns]
        if any(c is None for c in cards):
            continue
        total = 1
        for c in cards:
            total *= c  # type: ignore[operator]
        if total >= ctx.n_workers:
            continue
        kind = type(node).__name__
        out.append(Diagnostic(
            "shard-skew",
            f"{kind} keys take at most {total} distinct value(s) but the "
            f"pipeline targets {ctx.n_workers} workers — "
            f"{ctx.n_workers - total} worker(s) will hold no state and "
            "the rest become hot shards",
            operator=ctx.label(node),
            location=ctx.location_of(node),
            mitigation=(
                "group/join on a higher-cardinality key (or a composite "
                "key), or run fewer workers for this stage; at runtime, "
                "the keyload.* signals series and pathway_key_group_share "
                "on /metrics (observability/keyload.py) measure the "
                "realized per-key-group row distribution this pass can "
                "only predict statically"
            ),
        ))
    return out


# ---------------------------------------------------------------------------
# pass: sink / persistence misconfiguration
# ---------------------------------------------------------------------------


def _sink_location(spec: dict) -> tuple[str, int] | None:
    return spec.get("_lint_loc")


def pass_sink_misconfig(ctx: AnalysisContext) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    specs = ctx.runner.sink_specs
    if specs and not ctx.persisted:
        names = ", ".join(s["name"] for s in specs[:4])
        more = f" (+{len(specs) - 4} more)" if len(specs) > 4 else ""
        out.append(Diagnostic(
            "sink-no-persistence",
            f"{len(specs)} transactional sink(s) [{names}{more}] but the "
            "pipeline runs without persistence: no commit boundary gates "
            "delivery, so a crash re-sends whatever was in flight "
            "(at-least-once, not exactly-once)",
            location=_sink_location(specs[0]),
            mitigation=(
                "pass persistence_config to pw.run (pw.persistence."
                "Config.simple_config) — the delivery layer then acks "
                "against committed input and recovery dedupes replays"
            ),
        ))
    for spec in specs:
        if spec.get("decollided"):
            out.append(Diagnostic(
                "sink-name-collision",
                f"sink {spec['name']!r} got its name from a registration-"
                "order de-collision suffix (another sink derived the same "
                "default): reordering outputs in the program would swap "
                "their ack cursors and DLQ files",
                location=_sink_location(spec),
                mitigation="pass a distinct name= to each output connector",
            ))
    # DLQ directory overlapping a path some other component owns
    dlq_root = os.path.abspath(
        os.environ.get("PATHWAY_SINK_DLQ_DIR", "./pathway-dlq")
    )
    owned: list[tuple[str, str]] = []
    for spec in specs:
        path = (spec.get("meta") or {}).get("path")
        if path:
            owned.append((f"sink {spec['name']!r} output", os.path.abspath(path)))
    pcfg = ctx.persistence_config
    backend = getattr(pcfg, "backend", None)
    proot = (getattr(backend, "options", None) or {}).get("path")
    if proot:
        owned.append(("the persistence root", os.path.abspath(proot)))
    for what, path in owned:
        if path == dlq_root or _nested(path, dlq_root) or _nested(dlq_root, path):
            out.append(Diagnostic(
                "dlq-collision",
                f"the dead-letter directory ({dlq_root}) overlaps {what} "
                f"({path}): dead-lettered rows would interleave with "
                "data another component owns",
                mitigation=(
                    "point PATHWAY_SINK_DLQ_DIR at a directory of its own"
                ),
            ))
    return out


def _nested(inner: str, outer: str) -> bool:
    return inner.startswith(outer.rstrip(os.sep) + os.sep)


PASSES: list[Callable[[AnalysisContext], list[Diagnostic]]] = [
    pass_unbounded_state,
    pass_replay_determinism,
    pass_dispatch_tax,
    pass_fusion_readiness,
    pass_shard_skew,
    pass_sink_misconfig,
]


def run_passes(ctx: AnalysisContext) -> list[Diagnostic]:
    out: list[Diagnostic] = []
    for p in PASSES:
        out.extend(p(ctx))
    # deterministic report order: errors first, then by id, then location
    from .report import SEVERITIES

    out.sort(key=lambda d: (
        -SEVERITIES.index(d.severity),
        d.id,
        d.location or ("", 0),
        d.operator or "",
    ))
    return out
