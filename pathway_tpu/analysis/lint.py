"""``pathway-tpu lint`` driver: run a pipeline script in build-only mode
and statically analyze the graph it registers.

The script executes for real — imports, argument parsing, table
building — but ``pw.run()`` is stubbed to capture its
``persistence_config`` and return (``internals/lintmode.py``), so no
sources start and no sinks open. Diagnostics anchor to script lines
(table/sink creation sites recorded while lint mode is armed) and can be
suppressed inline:

    counts = words.groupby(pw.this.word)  # pathway: ignore[unbounded-state]

A suppression comment on a line of its own suppresses those ids for the
whole file; a trailing comment suppresses only diagnostics anchored to
that line.
"""

from __future__ import annotations

import os
import re
import runpy
import sys
from typing import Any

from ..internals import lintmode
from ..internals.parse_graph import G
from .report import CATALOG, Report

__all__ = ["collect_suppressions", "lint_script", "lint_targets"]

_SUPPRESS_RE = re.compile(r"#\s*pathway:\s*ignore\[([a-zA-Z0-9_,\s\-]+)\]")


def collect_suppressions(
    source: str,
) -> tuple[set[str], dict[int, set[str]]]:
    """(file-wide ids, line -> ids) from ``# pathway: ignore[...]``
    comments. Unknown ids are kept (forward compatibility: a script may
    carry suppressions for diagnostics a newer version ships)."""
    filewide: set[str] = set()
    by_line: dict[int, set[str]] = {}
    for lineno, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
        if line.strip().startswith("#"):
            filewide |= ids
        else:
            by_line.setdefault(lineno, set()).update(ids)
    return filewide, by_line


def _apply_suppressions(report: Report, script: str, source: str) -> None:
    filewide, by_line = collect_suppressions(source)
    if not filewide and not by_line:
        return
    kept, suppressed = [], []
    for d in report.diagnostics:
        ids_here = set(filewide)
        if (
            d.location is not None
            and os.path.abspath(d.location[0]) == os.path.abspath(script)
        ):
            ids_here |= by_line.get(d.location[1], set())
        (suppressed if d.id in ids_here else kept).append(d)
    report.diagnostics = kept
    report.suppressed.extend(suppressed)


def lint_script(
    path: str, *, n_workers: int | None = None
) -> tuple[Report, BaseException | None]:
    """Execute ``path`` in build-only mode and analyze its graph.
    Returns (report, crash) — ``crash`` is the exception the script
    itself raised (the report is then empty; exit code 3)."""
    from . import analyze

    script = os.path.abspath(path)
    saved_graph = dict(G.__dict__)
    saved_argv = list(sys.argv)
    G.clear()
    lintmode.arm(script)
    crash: BaseException | None = None
    report = Report(script=path)
    try:
        sys.argv = [script]
        try:
            runpy.run_path(script, run_name="__main__")
        except SystemExit as e:
            # argparse --help / explicit sys.exit(0) in a script is not a
            # crash; a nonzero exit is
            if e.code not in (None, 0):
                crash = e
        except BaseException as e:
            crash = e
        if crash is None:
            analyzed = analyze(
                persistence_config=lintmode.CAPTURE.get("persistence_config"),
                n_workers=n_workers,
            )
            analyzed.script = path
            report = analyzed
            try:
                with open(script, encoding="utf-8") as f:
                    source = f.read()
            except OSError:
                source = ""
            _apply_suppressions(report, script, source)
    finally:
        lintmode.disarm()
        sys.argv = saved_argv
        G.__dict__.clear()
        G.__dict__.update(saved_graph)
    return report, crash


def expand_targets(targets: list[str]) -> list[str]:
    """Scripts to lint: files stay; directories expand to every ``*.py``
    beneath them (sorted, __pycache__ excluded)."""
    out: list[str] = []
    for t in targets:
        if os.path.isdir(t):
            for dirpath, dirnames, filenames in os.walk(t):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        else:
            out.append(t)
    return sorted(dict.fromkeys(out))


def lint_targets(
    targets: list[str],
    *,
    n_workers: int | None = None,
    fail_on: str = "warning",
) -> tuple[list[dict[str, Any]], int]:
    """Lint every expanded target. Returns (per-script result docs,
    overall exit code): 0 clean, 1 warnings, 2 errors, 3 a script
    crashed while building — thresholded by ``fail_on``."""
    results: list[dict[str, Any]] = []
    worst = 0
    for script in expand_targets(targets):
        report, crash = lint_script(script, n_workers=n_workers)
        doc = report.to_dict()
        if crash is not None:
            doc["crash"] = f"{type(crash).__name__}: {crash}"
            # the same threshold contract as findings: "never" collects
            # reports non-fatally even when a script fails to build
            if fail_on != "never":
                worst = max(worst, 3)
        else:
            worst = max(worst, report.exit_code(fail_on))
        results.append({"report": report, "doc": doc, "crash": crash})
    return results, worst


def known_ids() -> list[str]:
    return sorted(CATALOG)
