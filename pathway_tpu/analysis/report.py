"""Diagnostic + report model of the static analyzer.

Every analyzer pass emits :class:`Diagnostic` records with a stable id
from :data:`CATALOG`; a :class:`Report` bundles them with the per-operator
structural fingerprints and renders to machine-readable JSON (CI) or a
human summary (terminal). Severity ordering drives the CLI exit code:
``error`` > ``warning`` > ``info``; suppressed and info-only reports are
clean (exit 0).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any

__all__ = ["CATALOG", "Diagnostic", "Report", "SEVERITIES"]

#: severity rank (exit codes: error -> 2, warning -> 1, info/clean -> 0)
SEVERITIES = ("info", "warning", "error")

#: diagnostic catalog: id -> (default severity, one-line description).
#: Ids are the suppression vocabulary (`# pathway: ignore[<id>]`) and the
#: stable key CI pipelines match on — never rename, only add.
CATALOG: dict[str, tuple[str, str]] = {
    "unbounded-state": (
        "warning",
        "groupby/join state grows without bound over a never-ending "
        "source (no temporal cutoff upstream, no spill budget set)",
    ),
    "nondeterministic-udf": (
        "error",
        "a UDF reaching a persisted/exactly-once pipeline calls RNG/"
        "time/io — replay after recovery diverges from the original run",
    ),
    "perrow-udf": (
        "warning",
        "a UDF failed the static lift AND the probe-trace gate: every "
        "row pays the Python dispatch tax",
    ),
    "fusion-chain": (
        "info",
        "a linear operator chain the compiler fuses into one kernel — "
        "or, at warning severity, one it detected but DECLINED to fuse "
        "(the message carries the compiler's verbatim decline reason)",
    ),
    "shard-skew": (
        "warning",
        "groupby/join keys have fewer distinct values than workers — "
        "some workers would sit idle while one holds the whole key space",
    ),
    "sink-no-persistence": (
        "warning",
        "transactional sinks registered but the pipeline runs without "
        "persistence — delivery degrades to at-least-once",
    ),
    "sink-name-collision": (
        "warning",
        "two sinks derived the same default name (de-collided only by "
        "registration order — ack cursors/DLQ files silently swap if the "
        "registration order changes)",
    ),
    "dlq-collision": (
        "warning",
        "the sink dead-letter directory overlaps a sink output path or "
        "the persistence root",
    ),
}


@dataclass
class Diagnostic:
    id: str
    message: str
    severity: str = ""
    #: (filename, lineno) in the linted script, when known
    location: tuple[str, int] | None = None
    #: stable operator label ("3:GroupByReduce") when node-anchored
    operator: str | None = None
    #: what to do about it — rendered under the finding
    mitigation: str | None = None

    def __post_init__(self) -> None:
        if self.id not in CATALOG:
            raise ValueError(f"unknown diagnostic id {self.id!r}")
        if not self.severity:
            self.severity = CATALOG[self.id][0]
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "id": self.id,
            "severity": self.severity,
            "message": self.message,
        }
        if self.location is not None:
            d["location"] = {"file": self.location[0], "line": self.location[1]}
        if self.operator is not None:
            d["operator"] = self.operator
        if self.mitigation is not None:
            d["mitigation"] = self.mitigation
        return d

    def render(self) -> str:
        loc = (
            f"{self.location[0]}:{self.location[1]}: "
            if self.location is not None
            else ""
        )
        op = f" [{self.operator}]" if self.operator else ""
        out = f"{loc}{self.severity}[{self.id}]{op}: {self.message}"
        if self.mitigation:
            out += f"\n    fix: {self.mitigation}"
        return out


@dataclass
class Report:
    diagnostics: list[Diagnostic] = field(default_factory=list)
    suppressed: list[Diagnostic] = field(default_factory=list)
    #: stable operator label -> structural fingerprint (hex)
    fingerprints: dict[str, str] = field(default_factory=dict)
    #: analyzed graph shape (operator/sink/source counts)
    stats: dict[str, Any] = field(default_factory=dict)
    script: str | None = None

    def worst_severity(self) -> str | None:
        worst = None
        for d in self.diagnostics:
            if worst is None or SEVERITIES.index(d.severity) > SEVERITIES.index(worst):
                worst = d.severity
        return worst

    def exit_code(self, fail_on: str = "warning") -> int:
        """0 clean/info, 1 warnings, 2 errors — thresholded by
        ``fail_on`` ('error' ignores warnings, 'never' always exits 0)."""
        worst = self.worst_severity()
        code = {None: 0, "info": 0, "warning": 1, "error": 2}[worst]
        if fail_on == "never":
            return 0
        if fail_on == "error" and code == 1:
            return 0
        return code

    def by_id(self, diag_id: str) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.id == diag_id]

    def to_dict(self) -> dict[str, Any]:
        return {
            "script": self.script,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "suppressed": [d.to_dict() for d in self.suppressed],
            "fingerprints": dict(self.fingerprints),
            "stats": dict(self.stats),
            "summary": {
                s: sum(1 for d in self.diagnostics if d.severity == s)
                for s in SEVERITIES
            },
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=False)

    def render(self, fingerprints: bool = True) -> str:
        lines: list[str] = []
        head = self.script or "<current graph>"
        lines.append(f"== pathway-tpu lint: {head} ==")
        for d in self.diagnostics:
            lines.append(d.render())
        if self.suppressed:
            lines.append(
                f"({len(self.suppressed)} finding(s) suppressed by "
                "`# pathway: ignore[...]`)"
            )
        if fingerprints and self.fingerprints:
            lines.append("operator fingerprints:")
            for label, fp in self.fingerprints.items():
                lines.append(f"  {label:<28} {fp}")
        counts = {
            s: sum(1 for d in self.diagnostics if d.severity == s)
            for s in SEVERITIES
        }
        lines.append(
            f"{counts['error']} error(s), {counts['warning']} warning(s), "
            f"{counts['info']} info — "
            f"{len(self.fingerprints)} operator(s) analyzed"
        )
        return "\n".join(lines)
