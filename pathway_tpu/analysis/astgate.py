"""Shared AST-walker framework for the repo's static gates.

Three ad-hoc checkers grew across PRs 3-10 (``scripts/check_knobs.py``,
``check_sink_paths.py``, ``check_ingest_paths.py``), each re-implementing
file walking, AST parsing and call collection. This module is the one
framework they (and new gates) ride:

- file/AST helpers: :func:`iter_py_files`, :func:`parse_file` (cached),
  :func:`calls_in`, :func:`method_defs`, :func:`import_aliases`,
  :func:`calls_inside_loops`, :func:`call_guarded`;
- a gate registry: decorate a ``() -> list[str]`` function with
  :func:`gate` and ``scripts/check_all.py`` runs every registered gate
  as one tier-1 entry;
- two repo gates that previously drifted by hand:
  :func:`chaos_sites_gate` — every chaos site declared in
  ``chaos/plan.py`` has a live injector call-site in the engine; and
  :func:`metrics_surface_gate` — every ``EngineStats`` counter/gauge is
  shipped by the hub snapshot and rendered on ``/metrics``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Callable, Iterator

__all__ = [
    "ROOT",
    "PACKAGE_DIR",
    "calls_in",
    "call_guarded",
    "calls_inside_loops",
    "async_chaos_sites_gate",
    "chaos_sites_gate",
    "fusion_metrics_gate",
    "fusion_reasons_gate",
    "gate",
    "gates",
    "latency_lineage_gate",
    "serve_metrics_gate",
    "upgrade_metrics_gate",
    "import_aliases",
    "iter_py_files",
    "metrics_surface_gate",
    "method_defs",
    "parse_file",
    "read_text",
    "run_gates",
]

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
PACKAGE_DIR = os.path.join(ROOT, "pathway_tpu")

_PARSE_CACHE: dict[str, ast.Module] = {}
_TEXT_CACHE: dict[str, str] = {}


def read_text(path: str) -> str:
    if path not in _TEXT_CACHE:
        with open(path, encoding="utf-8") as f:
            _TEXT_CACHE[path] = f.read()
    return _TEXT_CACHE[path]


def parse_file(path: str) -> ast.Module:
    if path not in _PARSE_CACHE:
        _PARSE_CACHE[path] = ast.parse(read_text(path), filename=path)
    return _PARSE_CACHE[path]


def iter_py_files(root: str | None = None) -> Iterator[str]:
    """Every ``.py`` under ``root`` (default: the package), sorted, with
    ``__pycache__`` pruned."""
    root = root or PACKAGE_DIR
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def calls_in(node: ast.AST) -> set[str]:
    """Names called anywhere under ``node`` — both ``f(...)`` and
    ``obj.f(...)`` register ``f``."""
    out: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Name):
                out.add(f.id)
            elif isinstance(f, ast.Attribute):
                out.add(f.attr)
    return out


def method_defs(tree: ast.Module, cls: str) -> dict[str, ast.FunctionDef]:
    """name -> def node for the methods of top-level class ``cls``."""
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == cls:
            return {
                n.name: n
                for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
    return {}


def import_aliases(tree: ast.Module, module_suffix: str) -> dict[str, str]:
    """local name -> imported name for every ``from X import a as b``
    where ``X`` ends with ``module_suffix`` (relative imports included:
    ``from ..chaos import wrap_backend as _chaos_wrap``)."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == module_suffix or mod.endswith("." + module_suffix) or (
                node.level > 0 and mod.split(".")[-1:] == [module_suffix]
            ):
                for alias in node.names:
                    out[alias.asname or alias.name] = alias.name
    return out


def calls_inside_loops(tree: ast.AST, attr: str) -> list[int]:
    """Line numbers of ``*.{attr}(...)`` calls lexically inside a
    for/while loop anywhere under ``tree``."""
    hits: list[int] = []

    class _W(ast.NodeVisitor):
        def __init__(self) -> None:
            self.depth = 0

        def _loop(self, node: ast.AST) -> None:
            self.depth += 1
            self.generic_visit(node)
            self.depth -= 1

        visit_For = _loop
        visit_While = _loop
        visit_AsyncFor = _loop

        def visit_Call(self, node: ast.Call) -> None:
            if (
                self.depth > 0
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == attr
            ):
                hits.append(node.lineno)
            self.generic_visit(node)

    _W().visit(tree)
    return hits


def call_guarded(fn: ast.AST, call: ast.Call) -> bool:
    """Is ``call`` nested under some ``if`` within ``fn``?"""

    class _F(ast.NodeVisitor):
        def __init__(self) -> None:
            self.guarded = False
            self.depth = 0

        def visit_If(self, node: ast.If) -> None:
            self.depth += 1
            self.generic_visit(node)
            self.depth -= 1

        def visit_Call(self, node: ast.Call) -> None:
            if node is call and self.depth > 0:
                self.guarded = True
            self.generic_visit(node)

    f = _F()
    f.visit(fn)
    return f.guarded


# ---------------------------------------------------------------------------
# gate registry
# ---------------------------------------------------------------------------

#: name -> (description, gate fn returning a list of problem strings)
gates: dict[str, tuple[str, Callable[[], list[str]]]] = {}


def gate(name: str, description: str):
    """Register a repo gate. The function returns problem strings (empty
    = green); ``scripts/check_all.py`` runs every registered gate."""

    def deco(fn: Callable[[], list[str]]):
        gates[name] = (description, fn)
        return fn

    return deco


def run_gates(names: list[str] | None = None) -> dict[str, list[str]]:
    """Run the selected (default: all) registered gates; name -> problems."""
    out: dict[str, list[str]] = {}
    for name, (_desc, fn) in sorted(gates.items()):
        if names is not None and name not in names:
            continue
        out[name] = fn()
    return out


# ---------------------------------------------------------------------------
# gate: every chaos site has a live injector call-site
# ---------------------------------------------------------------------------


def declared_chaos_sites() -> list[str]:
    """The ``_SITES`` tuple of ``chaos/plan.py``, read from source (the
    gate must see the declaration, not a possibly-shadowed import)."""
    tree = parse_file(os.path.join(PACKAGE_DIR, "chaos", "plan.py"))
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "_SITES" for t in node.targets
        ):
            value = ast.literal_eval(node.value)
            return list(value)
    raise AssertionError("chaos/plan.py: _SITES declaration not found")


def injector_accessors() -> dict[str, str]:
    """site -> ActiveFaults accessor method name, derived from
    ``chaos/injector.py``: each accessor filters ``f.site == "<site>"``."""
    tree = parse_file(os.path.join(PACKAGE_DIR, "chaos", "injector.py"))
    out: dict[str, str] = {}
    for name, fn in method_defs(tree, "ActiveFaults").items():
        for node in ast.walk(fn):
            if isinstance(node, ast.Compare) and len(node.ops) == 1 and (
                isinstance(node.ops[0], ast.Eq)
                and isinstance(node.left, ast.Attribute)
                and node.left.attr == "site"
                and len(node.comparators) == 1
                and isinstance(node.comparators[0], ast.Constant)
            ):
                out[node.comparators[0].value] = name
    return out


@gate(
    "chaos_sites",
    "every chaos site declared in chaos/plan.py has a live injector "
    "call-site in the engine",
)
def chaos_sites_gate() -> list[str]:
    sites = declared_chaos_sites()
    accessors = injector_accessors()
    problems: list[str] = []
    missing_accessor = [s for s in sites if s not in accessors]
    for s in missing_accessor:
        problems.append(
            f"site {s!r} declared in plan.py has no ActiveFaults accessor "
            "in injector.py (no way to arm it)"
        )
    # who calls each accessor outside chaos/ — both `armed.tick_fault(...)`
    # attribute calls and `from ..chaos import wrap_backend as alias` calls
    called: dict[str, list[str]] = {a: [] for a in accessors.values()}
    chaos_dir = os.path.join(PACKAGE_DIR, "chaos")
    for path in iter_py_files():
        if path.startswith(chaos_dir + os.sep):
            continue
        tree = parse_file(path)
        aliases = import_aliases(tree, "chaos")
        rel = os.path.relpath(path, ROOT)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = None
            if isinstance(f, ast.Attribute):
                name = f.attr
            elif isinstance(f, ast.Name):
                name = aliases.get(f.id, f.id if f.id in called else None)
            if name in called:
                called[name].append(rel)
    for site in sites:
        accessor = accessors.get(site)
        if accessor is None:
            continue  # already reported above
        if not called.get(accessor):
            problems.append(
                f"site {site!r}: accessor ActiveFaults.{accessor}() is "
                "never called outside chaos/ — the site is declared but "
                "nothing can ever fire it"
            )
    return problems


# ---------------------------------------------------------------------------
# gate: tick/phase-indexed chaos sites stay live on the ASYNC path
# ---------------------------------------------------------------------------


def _reachable_methods(methods: dict, start: str) -> set[str]:
    """Method names transitively reachable from ``start`` via
    ``self.<name>(...)`` calls (one class, name-based — exactly what the
    executor's loop structure needs)."""
    seen: set[str] = set()
    frontier = [start]
    while frontier:
        name = frontier.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        for node in ast.walk(methods[name]):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
            ):
                frontier.append(node.func.attr)
    return seen


def declared_phase_vocab() -> dict[str, tuple[str, ...]]:
    """site -> phase tuple, read from chaos/plan.py source (RESCALE_PHASES
    / AUTOSCALE_PHASES / UPGRADE_PHASES feeding _PHASES_BY_SITE)."""
    tree = parse_file(os.path.join(PACKAGE_DIR, "chaos", "plan.py"))
    consts: dict[str, tuple] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
            isinstance(node.targets[0], ast.Name)
        ):
            name = node.targets[0].id
            if name in ("RESCALE_PHASES", "AUTOSCALE_PHASES",
                        "UPGRADE_PHASES"):
                consts[name] = tuple(ast.literal_eval(node.value))
    return {
        "rescale": consts.get("RESCALE_PHASES", ()),
        "autoscale": consts.get("AUTOSCALE_PHASES", ()),
        "upgrade": consts.get("UPGRADE_PHASES", ()),
    }


@gate(
    "async_chaos_sites",
    "tick/autoscale/rescale chaos sites keep live call-sites under the "
    "frontier-driven async executor (no silently disarmed fault "
    "injection after the BSP refactor)",
)
def async_chaos_sites_gate() -> list[str]:
    """The BSP→async refactor moved the executor's event loop; a fault
    plan written against tick-indexed sites (``tick``, and the phased
    ``rescale``/``autoscale`` sites it composes with) must keep firing:

    - the async loop must transitively reach ``_tick``, and ``_tick``
      must still fire the bound tick fault (``self._tick_fault.fire``);
    - both async sweep shapes (source rounds AND the commit-wave settle)
      must go through ``_tick`` — a settle path with its own sweep would
      silently skip the tick site;
    - every declared rescale/autoscale/upgrade phase must still appear
      as a literal ``fire("<phase>")`` call site in its owning module
      (those fire from the resharder/controller/migrator, which the
      async executor's drain/commit protocol drives).
    """
    problems: list[str] = []
    tree = parse_file(os.path.join(PACKAGE_DIR, "engine", "executor.py"))
    methods = method_defs(tree, "Executor")
    for loop_entry in ("_stream_loop_sharded_async", "_async_settle"):
        if loop_entry not in methods:
            problems.append(
                f"executor.py: Executor.{loop_entry} not found — the "
                "async loop the gate audits is gone (rename the gate's "
                "anchor or restore the method)"
            )
            continue
        if "_tick" not in _reachable_methods(methods, loop_entry):
            problems.append(
                f"Executor.{loop_entry} never reaches _tick: async "
                "sweeps bypass the tick chaos site — fault plans with "
                "site 'tick' are silently disarmed on this path"
            )
    tick_fn = methods.get("_tick")
    fires_tick = tick_fn is not None and any(
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr == "fire"
        and isinstance(n.func.value, ast.Attribute)
        and n.func.value.attr == "_tick_fault"
        for n in ast.walk(tick_fn)
    )
    if not fires_tick:
        problems.append(
            "Executor._tick no longer fires self._tick_fault — the tick "
            "chaos site is dead in BOTH execution modes"
        )
    # phased sites: every declared phase keeps a literal fire call-site
    owners = {
        "rescale": os.path.join(PACKAGE_DIR, "rescale"),
        "autoscale": os.path.join(PACKAGE_DIR, "autoscale"),
        "upgrade": os.path.join(PACKAGE_DIR, "upgrade"),
    }
    for site, phases in declared_phase_vocab().items():
        fired: set[str] = set()
        for path in iter_py_files(owners[site]):
            for node in ast.walk(parse_file(path)):
                if (
                    isinstance(node, ast.Call)
                    and (
                        (isinstance(node.func, ast.Name)
                         and "fire" in node.func.id)
                        or (isinstance(node.func, ast.Attribute)
                            and "fire" in node.func.attr)
                    )
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    fired.add(node.args[0].value)
        for phase in phases:
            if phase not in fired:
                problems.append(
                    f"chaos site {site!r}: declared phase {phase!r} has "
                    f"no fire({phase!r}) call-site under "
                    f"{os.path.relpath(owners[site], ROOT)} — the phase "
                    "is plannable but can never fire"
                )
    return problems


# ---------------------------------------------------------------------------
# gate: every EngineStats counter/gauge reaches /metrics
# ---------------------------------------------------------------------------

#: EngineStats field -> the derived snapshot key it ships under
#: (ages/uptimes are computed at snapshot time so remote clocks never mix)
DERIVED_SNAPSHOT_KEYS = {
    "started_at": "uptime_s",
    "last_heartbeat": "heartbeat_age_s",
    "latency_updated_at": "latency_age_s",
}

#: fields that deliberately never enter the snapshot (reason recorded so
#: the exemption is auditable; anything NEW must render or be added here)
NOT_SNAPSHOTTED = {
    "detailed": "control flag (turns per-node timing on), not a metric",
    "time_by_node": (
        "raw feed of node_time_hist, which renders as "
        "pathway_operator_processing_seconds"
    ),
}

#: snapshot keys that ship to the hub but are liveness surface
#: (/healthz, /readyz, signals plane), not /metrics series
NOT_RENDERED = {
    "finished": "liveness surface: /healthz reports run completion",
    "sources_connected": "readiness surface: first half of /readyz",
    "heartbeat_age_s": "liveness surface: /healthz wedge detection",
    "e2e_ms": (
        "signals-plane gauge companion; the distribution renders as "
        "pathway_ingest_to_emit_seconds"
    ),
}


def engine_stats_fields() -> list[str]:
    """Public ``self.X = ...`` targets of ``EngineStats.__init__``."""
    tree = parse_file(os.path.join(PACKAGE_DIR, "engine", "executor.py"))
    init = method_defs(tree, "EngineStats").get("__init__")
    if init is None:
        raise AssertionError("EngineStats.__init__ not found")
    fields: list[str] = []
    for node in ast.walk(init):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and not t.attr.startswith("_")
                and t.attr not in fields
            ):
                fields.append(t.attr)
    return fields


@gate(
    "metrics_surface",
    "every EngineStats counter/gauge ships in the hub snapshot and "
    "renders on /metrics (or carries an audited exemption)",
)
def metrics_surface_gate() -> list[str]:
    hub_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "hub.py")
    )
    prom_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "prometheus.py")
    )
    problems: list[str] = []
    for field in engine_stats_fields():
        if field in NOT_SNAPSHOTTED:
            continue
        key = DERIVED_SNAPSHOT_KEYS.get(field, field)
        if not re.search(rf"[\"']{re.escape(key)}[\"']", hub_src):
            problems.append(
                f"EngineStats.{field}: snapshot key {key!r} does not "
                "appear in observability/hub.py stats_snapshot — the "
                "metric never leaves the worker (add it to the snapshot, "
                "or record an exemption in astgate.NOT_SNAPSHOTTED)"
            )
            continue
        if key in NOT_RENDERED:
            continue
        if not re.search(rf"[\"']{re.escape(key)}[\"']", prom_src):
            problems.append(
                f"EngineStats.{field}: snapshot key {key!r} is shipped "
                "by the hub but never consumed in observability/"
                "prometheus.py — it silently vanishes from /metrics "
                "(render it, or record an exemption in "
                "astgate.NOT_RENDERED)"
            )
    return problems


# ---------------------------------------------------------------------------
# gates: kernel fusion (engine/fusion.py)
# ---------------------------------------------------------------------------


def fusion_module_constants() -> tuple[dict[str, str], list[str]]:
    """(REASON_* constants, FUSION_STATS keys) parsed from the fusion
    module's AST — the single source both fusion gates check against."""
    tree = parse_file(os.path.join(PACKAGE_DIR, "engine", "fusion.py"))
    reasons: dict[str, str] = {}
    stats_keys: list[str] = []
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id.startswith("REASON_"):
                if isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, str
                ):
                    reasons[t.id] = node.value.value
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        if (
            target is not None
            and isinstance(target, ast.Name)
            and target.id == "FUSION_STATS"
            and isinstance(getattr(node, "value", None), ast.Dict)
        ):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    stats_keys.append(k.value)
    return reasons, stats_keys


@gate(
    "fusion_reasons",
    "every fusion decline reason (engine/fusion.py REASON_*) is "
    "exercised by a fusion parity test",
)
def fusion_reasons_gate() -> list[str]:
    reasons, _ = fusion_module_constants()
    problems: list[str] = []
    if not reasons:
        return ["engine/fusion.py declares no REASON_* constants"]
    test_dir = os.path.join(ROOT, "tests")
    test_src = ""
    for fn in sorted(os.listdir(test_dir)):
        if fn.startswith("test_fusion") and fn.endswith(".py"):
            test_src += read_text(os.path.join(test_dir, fn))
    if not test_src:
        return ["no tests/test_fusion*.py found to cover decline reasons"]
    for name, text in sorted(reasons.items()):
        # covered by constant name (preferred: survives rewording) or by
        # the verbatim string
        if name not in test_src and text not in test_src:
            problems.append(
                f"decline reason {name} ({text!r}) is never referenced "
                "in tests/test_fusion*.py — a declined chain with this "
                "reason has no parity test proving the per-node path "
                "still runs it correctly"
            )
    return problems


@gate(
    "fusion_metrics",
    "every FUSION_STATS counter ships in the hub snapshot and renders "
    "as pathway_fusion_* on /metrics",
)
def fusion_metrics_gate() -> list[str]:
    _, stats_keys = fusion_module_constants()
    problems: list[str] = []
    if not stats_keys:
        return ["engine/fusion.py declares no FUSION_STATS keys"]
    hub_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "hub.py")
    )
    prom_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "prometheus.py")
    )
    ts_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "timeseries.py")
    )
    if "fusion_stats_snapshot" not in hub_src or '"fusion"' not in hub_src:
        problems.append(
            "observability/hub.py does not ship the fusion counters in "
            "its snapshot/query documents"
        )
    if "pathway_fusion_" not in prom_src or "fusion_stats" not in prom_src:
        problems.append(
            "observability/prometheus.py never renders pathway_fusion_* "
            "— the counters silently vanish from /metrics"
        )
    if '"fusion.' not in ts_src and "f\"fusion." not in ts_src:
        problems.append(
            "observability/timeseries.py never records the fusion.* "
            "signals series"
        )
    # the prometheus renderer is generic over FUSION_STATS keys, so
    # per-key coverage is proven at the source: every key must be a
    # *_total counter or a gauge the renderer's suffix rule understands
    for key in stats_keys:
        if not key.endswith("_total"):
            problems.append(
                f"FUSION_STATS key {key!r} is not *_total — it would "
                "render as a gauge; rename it or extend the renderer"
            )
    return problems


# ---------------------------------------------------------------------------
# gate: serve-plane counters reach the hub, /metrics, signals and top
# ---------------------------------------------------------------------------


def serve_stats_keys() -> list[str]:
    """The ``SERVE_STATS`` keys of ``serve/stats.py``, read from source
    (same rationale as :func:`declared_chaos_sites`)."""
    tree = parse_file(os.path.join(PACKAGE_DIR, "serve", "stats.py"))
    for node in tree.body:
        targets = (
            node.targets
            if isinstance(node, ast.Assign)
            else [node.target]
            if isinstance(node, ast.AnnAssign) and node.value is not None
            else []
        )
        if any(
            isinstance(t, ast.Name) and t.id == "SERVE_STATS"
            for t in targets
        ):
            return list(ast.literal_eval(node.value))
    raise AssertionError("serve/stats.py: SERVE_STATS not found")


@gate(
    "serve_metrics",
    "every SERVE_STATS counter ships in the hub snapshot, renders as "
    "pathway_serve_* on /metrics, records as serve.* signals and shows "
    "in `pathway-tpu top`",
)
def serve_metrics_gate() -> list[str]:
    problems: list[str] = []
    keys = serve_stats_keys()
    if not keys:
        return ["serve/stats.py declares no SERVE_STATS keys"]
    hub_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "hub.py")
    )
    prom_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "prometheus.py")
    )
    ts_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "timeseries.py")
    )
    top_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "top.py")
    )
    if "serve_stats_snapshot" not in hub_src or '"serve"' not in hub_src:
        problems.append(
            "observability/hub.py does not ship the serve counters in "
            "its snapshot/query documents"
        )
    if "pathway_serve_" not in prom_src or "serve_stats" not in prom_src:
        problems.append(
            "observability/prometheus.py never renders pathway_serve_* "
            "— the counters silently vanish from /metrics"
        )
    if '"serve.' not in ts_src and 'f"serve.' not in ts_src:
        problems.append(
            "observability/timeseries.py never records the serve.* "
            "signals series — the autoscale decider flies blind on "
            "admission pressure"
        )
    if '"serve"' not in top_src:
        problems.append(
            "observability/top.py never renders a serve line — overload "
            "is invisible in the operator dashboard"
        )
    # the prometheus renderer is generic over SERVE_STATS keys: every
    # key must be *_total so it renders as a counter (live gauges come
    # from the registered providers and must NOT use the suffix)
    for key in keys:
        if not key.endswith("_total"):
            problems.append(
                f"SERVE_STATS key {key!r} is not *_total — it would "
                "render as a gauge; rename it or extend the renderer"
            )
    # the decider must consume the serve signal it scales on
    dec_src = read_text(os.path.join(PACKAGE_DIR, "autoscale", "decider.py"))
    if "serve_frac" not in dec_src:
        problems.append(
            "autoscale/decider.py never consumes the serve admission "
            "signal — 429 pressure can't trigger a scale-up"
        )
    return problems


# ---------------------------------------------------------------------------
# gate: upgrade migration counters reach the hub and /metrics
# ---------------------------------------------------------------------------


@gate(
    "upgrade_metrics",
    "graph-version upgrade counters ship in the hub snapshot and render "
    "as pathway_upgrade_* on /metrics",
)
def upgrade_metrics_gate() -> list[str]:
    """A migration that succeeds invisibly is indistinguishable from one
    that never ran: the migrator's ``_STATS`` must flow through the hub
    supervisor document and out the prometheus renderer, per verb."""
    problems: list[str] = []
    mig_src = read_text(
        os.path.join(PACKAGE_DIR, "upgrade", "migrator.py")
    )
    hub_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "hub.py")
    )
    prom_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "prometheus.py")
    )
    if "_STATS" not in mig_src:
        return ["upgrade/migrator.py declares no _STATS counters"]
    for key in ('"upgrades"', '"upgrade_duration_s"',
                '"upgrade_operators"'):
        if key not in hub_src:
            problems.append(
                f"observability/hub.py never ships the {key} key — "
                "migration outcomes never leave the supervisor"
            )
    for marker in ("pathway_upgrade_total",
                   "pathway_upgrade_duration_seconds",
                   "pathway_upgrade_operators_total"):
        if marker not in prom_src:
            problems.append(
                f"observability/prometheus.py never renders {marker} — "
                "the migration counters silently vanish from /metrics"
            )
    # every classification verb the planner can emit must be a labelled
    # series, or operators disappear from the per-verb breakdown
    for verb in ("carried", "remapped", "new", "dropped"):
        if f'"{verb}"' not in mig_src:
            problems.append(
                f"upgrade/migrator.py _STATS no longer tracks verb "
                f"{verb!r} — the per-verb operator breakdown is partial"
            )
    return problems


# ---------------------------------------------------------------------------
# gate: latency lineage (observability/critpath.py + keyload.py)
# ---------------------------------------------------------------------------


def critpath_phases() -> list[str]:
    """The ``PHASES`` tuple of ``observability/critpath.py``, read from
    source (same rationale as :func:`declared_chaos_sites`)."""
    tree = parse_file(
        os.path.join(PACKAGE_DIR, "observability", "critpath.py")
    )
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "PHASES"
            for t in node.targets
        ):
            return list(ast.literal_eval(node.value))
    raise AssertionError("observability/critpath.py: PHASES not found")


@gate(
    "latency_lineage",
    "commit-wave and key-load accounting ship end to end: hub /query "
    "docs, pathway_wave_*/pathway_key_group_* on /metrics, and the "
    "wave.*/keyload.* signals series",
)
def latency_lineage_gate() -> list[str]:
    problems: list[str] = []
    hub_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "hub.py")
    )
    prom_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "prometheus.py")
    )
    ts_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "timeseries.py")
    )
    exec_src = read_text(os.path.join(PACKAGE_DIR, "engine", "executor.py"))
    if not critpath_phases():
        problems.append("observability/critpath.py declares no PHASES")
    for key, where in (('"waves"', "hub"), ('"keyload"', "hub")):
        if key not in hub_src:
            problems.append(
                f"observability/hub.py never ships the {key} document — "
                "the lineage never leaves the process"
            )
    for marker in ("pathway_wave_", "pathway_key_group_share",
                   "pathway_ingest_to_emit_stage_seconds"):
        if marker not in prom_src:
            problems.append(
                f"observability/prometheus.py never renders {marker}* — "
                "the accounting silently vanishes from /metrics"
            )
    for marker in ('"wave.', '"keyload.'):
        if marker not in ts_src and f"f{marker}" not in ts_src:
            problems.append(
                f"observability/timeseries.py never records the "
                f"{marker[1:]}* signals series"
            )
    # the staged e2e decomposition must stay wired through note_e2e:
    # every E2E_STAGES name needs a histogram fed from the executor
    for node in parse_file(
        os.path.join(PACKAGE_DIR, "engine", "executor.py")
    ).body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "E2E_STAGES"
            for t in node.targets
        ):
            if len(ast.literal_eval(node.value)) < 4:
                problems.append(
                    "engine/executor.py E2E_STAGES lost stages — the "
                    "ingest_to_emit decomposition no longer covers the "
                    "route/dwell/settle/deliver pipeline"
                )
            break
    else:
        problems.append("engine/executor.py: E2E_STAGES not found")
    if "stage_hists" not in exec_src or "note_e2e" not in exec_src:
        problems.append(
            "engine/executor.py dropped the staged e2e histograms "
            "(stage_hists/note_e2e)"
        )
    return problems


# ---------------------------------------------------------------------------
# gate: continuous profiling plane (observability/profiler.py)
# ---------------------------------------------------------------------------


@gate(
    "profile_metrics",
    "continuous-profiler and ingest-stage counters ship end to end: hub "
    "/snapshot+/query docs, pathway_profile_*/pathway_ingest_stage_* on "
    "/metrics, and the profile.*/ingest.* signals series",
)
def profile_metrics_gate() -> list[str]:
    """A sampling profiler that only answers ``/profile`` is a debugger,
    not a plane: its health scalars (sample counts, op-tag share) and
    the ingest parse/hash/delta split must flow through the same
    snapshot → prometheus → signals path every other counter takes, or
    regressions in the profiler itself go unnoticed."""
    problems: list[str] = []
    hub_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "hub.py")
    )
    prom_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "prometheus.py")
    )
    ts_src = read_text(
        os.path.join(PACKAGE_DIR, "observability", "timeseries.py")
    )
    io_src = read_text(os.path.join(PACKAGE_DIR, "io", "python.py"))
    exec_src = read_text(os.path.join(PACKAGE_DIR, "engine", "executor.py"))
    http_src = read_text(
        os.path.join(PACKAGE_DIR, "engine", "http_server.py")
    )
    for marker, why in (
        ("profile_stats_snapshot", "profiler scalars"),
        ("ingest_stats_snapshot", "ingest stage split"),
        ('"profile"', "profile document key"),
        ('"ingest"', "ingest document key"),
    ):
        if marker not in hub_src:
            problems.append(
                f"observability/hub.py never ships the {why} "
                f"({marker}) — the profiling plane never leaves the "
                "process"
            )
    for marker in ("pathway_profile_", "pathway_ingest_stage_seconds"):
        if marker not in prom_src:
            problems.append(
                f"observability/prometheus.py never renders {marker}* — "
                "the profiling counters silently vanish from /metrics"
            )
    for marker in ('"profile.', '"ingest.'):
        if marker not in ts_src and f"f{marker}" not in ts_src:
            problems.append(
                f"observability/timeseries.py never records the "
                f"{marker[1:]}* signals series"
            )
    if "INGEST_STAGE_STATS" not in io_src:
        problems.append(
            "io/python.py dropped the INGEST_STAGE_STATS staged "
            "counters — the parse/hash/delta split has no source"
        )
    # operator tagging is what joins profiles against /attribution: the
    # executor must register a slot and label it per node sweep
    if "_op_slot" not in exec_src or "_op_label" not in exec_src:
        problems.append(
            "engine/executor.py dropped the profiler op-slot tagging "
            "(_op_slot/_op_label) — samples lose their operator labels"
        )
    if '"/profile"' not in http_src:
        problems.append(
            "engine/http_server.py no longer serves /profile — the "
            "flamegraph surface is gone"
        )
    return problems
