"""Static analysis of compiled dataflow graphs (``pathway-tpu lint``).

The reference engine compiles whole expression DAGs and rejects bad
plans before a single row flows (SURVEY §1.3); this package gives the
reproduction the same ahead-of-time discipline: :func:`analyze` lowers
the currently-registered parse graph to engine operators WITHOUT
executing anything and runs a battery of passes over it —

- ``unbounded-state``: groupby/join state growing forever over a
  never-ending source (names the ForgetAfter / spill-budget mitigation);
- ``nondeterministic-udf``: RNG/time/io inside UDFs of persisted /
  exactly-once pipelines (replay divergence);
- ``perrow-udf``: UDFs that fail both the static lift and the
  probe-trace gate, with the exact refusal reason;
- ``fusion-chain``: maximal pure linear operator chains + their
  intermediate materialization cost (ROADMAP item 3's scouting report);
- ``shard-skew``: provably low-cardinality keys vs the worker count;
- ``sink-no-persistence`` / ``sink-name-collision`` / ``dlq-collision``:
  output-plane misconfiguration.

The report also carries a stable structural fingerprint per operator —
the identity primitive graph-version migration (ROADMAP item 4) needs.

Surfaces: ``pw.analyze()`` (this function), the ``pathway-tpu lint
<script.py>`` CLI verb (``analysis/lint.py``: machine-readable JSON,
severity exit codes, ``# pathway: ignore[<id>]`` suppressions), and the
repo's own AST gate framework (``analysis/astgate.py``).
"""

from __future__ import annotations

from typing import Any

from .report import CATALOG, Diagnostic, Report

__all__ = ["CATALOG", "Diagnostic", "Report", "analyze"]


def analyze(
    *,
    persistence_config: Any = None,
    n_workers: int | None = None,
) -> Report:
    """Statically analyze the dataflow registered so far (everything
    ``pw.run()`` would execute). Lowering runs for real — expression
    compilation included — but nothing executes: no sources start, no
    sinks open, no rows flow.

    ``persistence_config``: the config the eventual ``pw.run`` will use
    (enables the replay-determinism and exactly-once checks); under
    ``pathway-tpu lint`` it is captured from the script's own stubbed
    ``pw.run`` call. ``n_workers``: the deployment's worker count for the
    shard-skew pass (default: PATHWAY_LINT_WORKERS, then the current
    config's total_workers)."""
    from .graph import fingerprint_nodes, lower_current_graph, node_labels
    from .passes import AnalysisContext, run_passes

    runner = lower_current_graph()
    ctx = AnalysisContext(
        runner,
        persistence_config=persistence_config,
        n_workers=n_workers,
    )
    report = Report()
    report.diagnostics = run_passes(ctx)
    fps = fingerprint_nodes(ctx.nodes)
    labels = node_labels(ctx.nodes)
    report.fingerprints = {
        labels[nid]: fps[nid]
        for nid in sorted(
            labels, key=lambda i: int(labels[i].split(":", 1)[0])
        )
        if nid in fps
    }
    report.stats = {
        "operators": len(ctx.nodes),
        "delivery_sinks": len(runner.sink_specs),
        "plain_sinks": runner.plain_sinks,
        "workers_modeled": ctx.n_workers,
        "persisted": ctx.persisted,
    }
    return report
