"""Central JAX configuration for the framework.

Importing this module configures JAX once:
- on CPU (tests, virtual multi-device meshes) enable x64 so INT/FLOAT columns
  keep python int64/float64 semantics;
- on TPU leave 32-bit defaults (f64 is not native on the MXU/VPU); dense
  column kernels run in f32 and the model/KNN paths pick bf16/f32 explicitly.
"""

from __future__ import annotations

import os

import jax

_platform = None


def platform() -> str:
    global _platform
    if _platform is None:
        env = os.environ.get("JAX_PLATFORMS", "")
        # avoid touching the backend (may dial a TPU tunnel) when env decides
        _platform = env.split(",")[0] if env else jax.default_backend()
    return _platform


if platform() == "cpu":
    jax.config.update("jax_enable_x64", True)
