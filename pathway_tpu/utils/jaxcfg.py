"""Central JAX configuration for the framework.

Importing this module configures JAX once:
- on CPU (tests, virtual multi-device meshes) enable x64 so INT/FLOAT columns
  keep python int64/float64 semantics;
- on TPU leave 32-bit defaults (f64 is not native on the MXU/VPU); dense
  column kernels run in f32 and the model/KNN paths pick bf16/f32 explicitly.
"""

from __future__ import annotations

import os

import jax

_platform = None


def platform() -> str:
    global _platform
    if _platform is None:
        env = os.environ.get("JAX_PLATFORMS", "")
        # avoid touching the backend (may dial a TPU tunnel) when env decides
        _platform = env.split(",")[0] if env else jax.default_backend()
    return _platform


def guard_cpu_platform(force_device_count: int | None = None) -> None:
    """When running on CPU, keep the axon TPU plugin (auto-registered by the
    image's sitecustomize) from wedging backend init by dialing its tunnel:
    scrub its path entries, deregister non-cpu backend factories, and pin
    jax_platforms. Optionally force a virtual device count (must run before
    any backend is initialized)."""
    import sys

    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return
    sys.path[:] = [p for p in sys.path if ".axon_site" not in p]
    os.environ["PYTHONPATH"] = os.pathsep.join(
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p
    )
    if force_device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={force_device_count}"
            ).strip()
    try:
        import jax._src.xla_bridge as _xb

        for _name in list(_xb._backend_factories):
            if _name != "cpu":
                _xb._backend_factories.pop(_name, None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


if platform() == "cpu":
    jax.config.update("jax_enable_x64", True)
