"""Metadata filter expressions for index queries.

The reference filters candidate documents with JMESPath boolean queries
(``src/external_integration/mod.rs:373``, via the jmespath crate). That
library isn't in this environment, so this module implements the subset the
indexing/RAG surfaces actually use, compiled to a Python predicate over the
metadata JSON dict:

    path.to.field == 'value'      (also != < <= > >=; numbers via `123`)
    contains(path, 'x')           starts_with / ends_with
    globmatch('pat', path)        glob on string fields
    expr && expr, expr || expr, !expr, parentheses
"""

from __future__ import annotations

import fnmatch
import re
from typing import Any, Callable

__all__ = ["compile_metadata_filter", "FilterSyntaxError"]


class FilterSyntaxError(ValueError):
    pass


_TOKEN = re.compile(
    r"\s*(?:(?P<op>==|!=|<=|>=|<|>|&&|\|\||!|\(|\)|,)"
    r"|(?P<str>'[^']*'|\"[^\"]*\")"
    r"|(?P<tick>`[^`]*`)"
    r"|(?P<num>-?\d+(?:\.\d+)?)"
    r"|(?P<ident>[A-Za-z_][A-Za-z0-9_.]*))"
)


def _lex(src: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if m is None:
            if src[pos:].strip() == "":
                break
            raise FilterSyntaxError(f"bad filter syntax at {src[pos:]!r}")
        pos = m.end()
        for kind in ("op", "str", "tick", "num", "ident"):
            tok = m.group(kind)
            if tok is not None:
                out.append((kind, tok))
                break
    return out


class _Parser:
    """Recursive descent: or → and → unary → comparison/primary."""

    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def take(self):
        tok = self.peek()
        self.i += 1
        return tok

    def expect(self, value: str):
        kind, tok = self.take()
        if tok != value:
            raise FilterSyntaxError(f"expected {value!r}, got {tok!r}")

    def parse(self):
        node = self.or_expr()
        if self.i != len(self.toks):
            raise FilterSyntaxError(f"trailing tokens: {self.toks[self.i:]}")
        return node

    def or_expr(self):
        node = self.and_expr()
        while self.peek() == ("op", "||"):
            self.take()
            rhs = self.and_expr()
            node = ("or", node, rhs)
        return node

    def and_expr(self):
        node = self.unary()
        while self.peek() == ("op", "&&"):
            self.take()
            rhs = self.unary()
            node = ("and", node, rhs)
        return node

    def unary(self):
        if self.peek() == ("op", "!"):
            self.take()
            return ("not", self.unary())
        if self.peek() == ("op", "("):
            self.take()
            node = self.or_expr()
            self.expect(")")
            return self.maybe_comparison(node)
        return self.comparison()

    def value(self):
        kind, tok = self.take()
        if kind == "str":
            return ("lit", tok[1:-1])
        if kind == "num":
            return ("lit", float(tok) if "." in tok else int(tok))
        if kind == "tick":
            import json

            return ("lit", json.loads(tok[1:-1]))
        if kind == "ident":
            if tok in ("contains", "starts_with", "ends_with", "globmatch"):
                if self.peek() == ("op", "("):
                    self.take()
                    a = self.value()
                    self.expect(",")
                    b = self.value()
                    self.expect(")")
                    return ("call", tok, a, b)
            if tok == "true":
                return ("lit", True)
            if tok == "false":
                return ("lit", False)
            if tok == "null":
                return ("lit", None)
            return ("path", tok.split("."))
        raise FilterSyntaxError(f"unexpected token {tok!r}")

    def comparison(self):
        return self.maybe_comparison(self.value())

    def maybe_comparison(self, lhs):
        kind, tok = self.peek()
        if kind == "op" and tok in ("==", "!=", "<", "<=", ">", ">="):
            self.take()
            rhs = self.value()
            return ("cmp", tok, lhs, rhs)
        return lhs


def _lookup(meta: Any, path: list[str]) -> Any:
    cur = meta
    for p in path:
        if isinstance(cur, dict):
            cur = cur.get(p)
        else:
            return None
    return cur


def _eval(node, meta: Any) -> Any:
    tag = node[0]
    if tag == "lit":
        return node[1]
    if tag == "path":
        return _lookup(meta, node[1])
    if tag == "and":
        return bool(_eval(node[1], meta)) and bool(_eval(node[2], meta))
    if tag == "or":
        return bool(_eval(node[1], meta)) or bool(_eval(node[2], meta))
    if tag == "not":
        return not bool(_eval(node[1], meta))
    if tag == "cmp":
        op, l, r = node[1], _eval(node[2], meta), _eval(node[3], meta)
        try:
            if op == "==":
                return l == r
            if op == "!=":
                return l != r
            if l is None or r is None:
                return False
            if op == "<":
                return l < r
            if op == "<=":
                return l <= r
            if op == ">":
                return l > r
            if op == ">=":
                return l >= r
        except TypeError:
            return False
    if tag == "call":
        fn = node[1]
        a = _eval(node[2], meta)
        b = _eval(node[3], meta)
        if fn == "globmatch":
            # jmespath-extension argument order: globmatch(pattern, field)
            return isinstance(b, str) and isinstance(a, str) and fnmatch.fnmatch(b, a)
        if not isinstance(a, str):
            if fn == "contains" and isinstance(a, (list, tuple)):
                return b in a
            return False
        b = "" if b is None else str(b)
        if fn == "contains":
            return b in a
        if fn == "starts_with":
            return a.startswith(b)
        if fn == "ends_with":
            return a.endswith(b)
    raise FilterSyntaxError(f"cannot evaluate node {node!r}")


def compile_metadata_filter(src: Any) -> Callable[[Any], bool] | None:
    """Compile a filter string to a predicate over a metadata dict.
    None (or None-valued cell) means "match everything"."""
    if src is None:
        return None
    if callable(src):
        return src
    ast = _Parser(_lex(str(src))).parse()

    def predicate(meta: Any) -> bool:
        return bool(_eval(ast, meta if meta is not None else {}))

    return predicate
