"""The closed-loop autoscale controller behind ``spawn --autoscale``.

Composes five existing subsystems into "load changes, the cluster
follows, exactly-once holds":

- the **signals plane** (``observability/timeseries.py`` served as the
  merged ``/query`` document on process 0) is the sensor;
- the :class:`~pathway_tpu.autoscale.decider.Decider` is the pure
  policy — sustained frontier lag / send-queue saturation scales up,
  sustained idleness scales down, hysteresis + cooldown + a staleness
  guard keep it from flapping;
- the **supervisor** (``parallel/supervisor.py``) is the actuator's
  safety net: the controller rides its ``poll_hook``/``planned_stop``
  seam, so worker death during or between scale events falls into the
  ordinary restart-from-snapshot path (children boot with
  ``PATHWAY_ELASTIC=1``, so even a marker left mid-sequence by a killed
  controller converges at the next supervised boot);
- the **drain** is the cooperative SIGTERM teardown the supervisor
  already performs: supervised children translate SIGTERM into
  ``request_stop()`` and their persistence managers flush exactly to
  the last delivery boundary — offsets never outrun recorded input, so
  a rescale sees a consistent prefix and rows lost is zero;
- the **resharder** (``rescale/``) repartitions that prefix N→M under
  its atomic-marker protocol, which is what makes a SIGKILL of the
  controller itself at ANY phase survivable.

The scale sequence, each boundary an ``autoscale`` chaos-site phase::

    decide -> [teardown = drain] -> reshard -> [relaunch] -> resume

The pause — SIGTERM of the old generation to launch of the new — is
measured per event (``pause_ms`` with drain/reshard parts) and appended
to the ``PATHWAY_AUTOSCALE_LOG`` JSONL event log; the latest values are
stamped into child environments so ``/metrics`` exports
``pathway_autoscale_*`` and ``pathway-tpu top`` shows the loop working.

Children are launched with ``PR_SET_PDEATHSIG=SIGTERM`` (Linux): a
controller killed mid-scale takes its ensemble down *cooperatively*
instead of leaking an orphaned cluster that would fight the next boot
for ports and the persisted store.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Callable, Sequence

from ..internals.tracing import span as _span
from .decider import Decider, DeciderConfig, Decision, load_scripted_plan

__all__ = ["AutoscaleController", "AutoscaleError", "parse_range"]


class AutoscaleError(RuntimeError):
    pass


def parse_range(spec: str) -> tuple[int, int]:
    """``"MIN..MAX"`` → (min, max); a bare ``"N"`` means N..N."""
    s = spec.strip()
    lo, sep, hi = s.partition("..")
    try:
        mn = int(lo)
        mx = int(hi) if sep else mn
    except ValueError:
        raise AutoscaleError(
            f"--autoscale expects MIN..MAX worker counts, got {spec!r}"
        ) from None
    if mn < 1 or mx < mn:
        raise AutoscaleError(
            f"--autoscale range {spec!r} needs 1 <= MIN <= MAX"
        )
    return mn, mx


def _set_pdeathsig() -> None:  # pragma: no cover — runs post-fork
    """Child-side: die (SIGTERM → cooperative flush) when the parent
    controller disappears, so a SIGKILLed controller never leaks a live
    ensemble into the next boot's ports and store."""
    try:
        import ctypes

        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(1, signal.SIGTERM)  # PR_SET_PDEATHSIG
    except Exception:
        pass  # non-Linux: orphans are the operator's problem, as before


class AutoscaleController:
    """Owns the scale loop: builds the Supervisor, polls ``/query`` on
    process 0, and executes decide → drain → reshard → resume."""

    def __init__(
        self,
        *,
        program: Sequence[str],
        min_workers: int,
        max_workers: int,
        store: str,
        backend_kind: str = "filesystem",
        base_env: dict[str, str],
        monitor_base: int,
        cfg: DeciderConfig | None = None,
        poll_s: float | None = None,
        warmup_s: float | None = None,
        log: Callable[[str], Any] | None = None,
        plan: list[dict] | None = None,
    ):
        from ..internals.config import _env_float

        self.program = list(program)
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.store = store
        self.backend_kind = backend_kind
        self.base_env = dict(base_env)
        self.monitor_base = monitor_base
        self.cfg = cfg or DeciderConfig.from_env(min_workers, max_workers)
        self.decider = Decider(self.cfg)
        self.poll_s = (
            poll_s
            if poll_s is not None
            else _env_float("PATHWAY_AUTOSCALE_POLL_S", 1.0)
        )
        # a freshly launched generation replays + re-establishes rates;
        # its signals are boot noise, not traffic
        self.warmup_s = (
            warmup_s
            if warmup_s is not None
            else _env_float("PATHWAY_AUTOSCALE_WARMUP_S", 3.0)
        )
        self._log = log or (
            lambda m: print(f"[autoscale] {m}", file=sys.stderr)
        )
        self.plan = plan if plan is not None else load_scripted_plan()
        self._plan_ix = 0
        self.workers = self._initial_workers()
        self.events: list[dict] = []
        #: /query fetch failures (dead-sensor visibility, logged in run())
        self.fetch_failures = 0
        self._fetch_fail_streak = 0
        self.log_path = self.base_env.get("PATHWAY_AUTOSCALE_LOG") or None
        self._pending: dict | None = None
        self._last_poll = 0.0
        self._started = time.monotonic()
        self._gen_started: float | None = None
        self._sup: Any = None
        from ..chaos import injector as _chaos

        armed = _chaos.current()
        self._fault = (
            armed.autoscale_faults() if armed is not None else None
        )

    # -- setup ----------------------------------------------------------

    def _initial_workers(self) -> int:
        """Persisted marker count clamped into [min, max]; min for a
        fresh store (scale up only when traffic proves the need).

        A marker READ error is NOT a fresh store: guessing min_workers
        on a transient IO hiccup would elastic-reshard a live N-worker
        layout down to MIN at the next boot. Same bug class
        tests/test_rescale.py::test_marker_io_errors_propagate pins for
        the engine — refuse loudly instead."""
        from ..persistence import layout as _layout
        from ..persistence.backends import open_backend

        try:
            root = open_backend(self._backend_spec())
        except Exception as e:
            raise AutoscaleError(
                f"cannot open the autoscale store {self.store!r}: {e}"
            ) from e
        try:
            marker = _layout.read_marker(root)
        except Exception as e:
            raise AutoscaleError(
                f"cannot read the cluster marker at {self.store!r}: {e} "
                "— refusing to guess a worker count (a wrong guess "
                "reshards the store)"
            ) from e
        finally:
            root.close()
        if marker is None:
            return self.min_workers
        return max(self.min_workers, min(self.max_workers, marker[0]))

    def _backend_spec(self) -> Any:
        from ..persistence import Backend

        return (
            Backend.filesystem(self.store)
            if self.backend_kind == "filesystem"
            else Backend.s3(self.store)
        )

    def _fire(self, phase: str) -> None:
        if self._fault is not None:
            self._fault.fire(phase)

    # -- lifecycle ------------------------------------------------------

    def run(self) -> int:
        from ..parallel.supervisor import Supervisor

        sup = Supervisor(
            self._launch,
            poll_hook=self._poll,
            planned_stop=self._planned_stop,
            flight_dir=self.base_env.get("PATHWAY_FLIGHT_DIR"),
            run_id=self.base_env.get("PATHWAY_RUN_ID"),
            log=lambda m: print(f"[autoscale] {m}", file=sys.stderr),
        )
        self._sup = sup
        self._refresh_sup()
        self._log(
            f"controller up: {self.workers} worker(s) in "
            f"[{self.min_workers}..{self.max_workers}], watching "
            f"http://127.0.0.1:{self.monitor_base}/query"
        )
        rc = sup.run()
        if self.events:
            pauses = [e["pause_ms"] for e in self.events]
            self._log(
                f"{len(self.events)} scale event(s), pause "
                f"min/max {min(pauses):.0f}/{max(pauses):.0f} ms"
            )
        if self.fetch_failures:
            self._log(
                f"sensor trouble: {self.fetch_failures} /query fetch "
                "failure(s) over the run"
            )
        return rc

    def _refresh_sup(self) -> None:
        """(Re)derive the per-generation supervision inputs from the
        current worker count — health ports, labels, flight-ring ids."""
        pids = list(range(self.workers))
        self._sup.process_ids = pids
        self._sup.labels = [f"process {p}" for p in pids]
        ports: list[int] = []
        if self.monitor_base:
            ports = [self.monitor_base + p for p in pids]
        self._sup.health_ports = ports

    # -- sensing + deciding (supervisor poll_hook) ----------------------

    def _poll(self) -> str | None:
        now = time.monotonic()
        if now - self._last_poll < self.poll_s:
            return None
        self._last_poll = now
        decision = self._scripted(now)
        if decision is None and not self.plan:
            decision = self._signal_decision(now)
        if decision is None:
            return None
        self._log(
            f"decision: {self.workers} -> {decision.target} "
            f"({decision.reason})"
        )
        from ..internals.tracing import get_tracer

        tracer = get_tracer()
        if tracer is not None:
            tracer.instant(
                "autoscale.decide",
                from_workers=self.workers,
                to_workers=decision.target,
                reason=decision.reason,
            )
        # fire the decide fault BEFORE arming _pending: a crash/exit here
        # must not leave a pending decision behind for a later budgeted
        # relaunch to record as a phantom scale event
        self._fire("decide")
        self._pending = {
            "decision": decision,
            "from": self.workers,
            "t0": time.monotonic(),
        }
        return (
            f"autoscale {self.workers}->{decision.target}: "
            f"{decision.reason}"
        )

    def _scripted(self, now: float) -> Decision | None:
        while self._plan_ix < len(self.plan):
            step = self.plan[self._plan_ix]
            if now - self._started < step["after_s"]:
                return None
            self._plan_ix += 1
            target = max(
                self.min_workers, min(self.max_workers, step["to"])
            )
            if target != self.workers:
                return Decision(
                    target,
                    "up" if target > self.workers else "down",
                    f"scripted (after {step['after_s']:.1f}s)",
                )
        return None

    def _signal_decision(self, now: float) -> Decision | None:
        if (
            self._gen_started is not None
            and now - self._gen_started < self.warmup_s
        ):
            return None
        try:
            doc = self._fetch_query()
        except Exception as e:
            # a dead sensor must be VISIBLE: an autoscaler that silently
            # never scales is worse than none. Log the first failure of
            # a streak and every 10th after (the poll cadence would spam
            # otherwise); the count surfaces in the shutdown summary.
            self.fetch_failures += 1
            self._fetch_fail_streak += 1
            if self._fetch_fail_streak == 1 or (
                self._fetch_fail_streak % 10 == 0
            ):
                self._log(
                    f"cannot read /query "
                    f"(failure #{self._fetch_fail_streak} in a row): "
                    f"{type(e).__name__}: {e}"
                )
            self.decider.note_gap(now)
            return None
        self._fetch_fail_streak = 0
        return self.decider.observe(doc, self.workers, time.time())

    def _fetch_query(self) -> dict:
        import urllib.request

        url = f"http://127.0.0.1:{self.monitor_base}/query"
        with urllib.request.urlopen(url, timeout=2.0) as r:
            return json.loads(r.read().decode())

    # -- acting (supervisor planned_stop + launch) ----------------------

    def _planned_stop(self, token: str) -> None:
        """Between the supervisor's cooperative teardown (= the drain:
        every worker flushed to its delivery boundary) and the next
        launch: reshard the persisted state to the target count.

        On ANY failure the pending decision is dropped before the error
        propagates: the supervisor falls through to its budgeted restart
        path, and that relaunch must not record a scale event that never
        happened (nor fire the ``resume`` chaos phase for it)."""
        try:
            self._planned_stop_inner()
        except BaseException:
            self._pending = None
            raise

    def _planned_stop_inner(self) -> None:
        p = self._pending
        assert p is not None, "planned stop without a pending decision"
        p["drain_ms"] = (time.monotonic() - p["t0"]) * 1000.0
        self._fire("drain")
        target = p["decision"].target
        t1 = time.monotonic()
        with _span(
            "autoscale.reshard", from_workers=self.workers,
            to_workers=target,
        ):
            from ..rescale import NoClusterMarker
            from ..rescale import rescale as _rescale

            try:
                report = _rescale(
                    self._backend_spec(), target, log=self._log
                )
            except NoClusterMarker:
                # the program never committed state yet: there is
                # nothing to reshard — the new generation simply
                # boots at the target count and writes the marker
                report = {"noop": True, "reason": "no persisted state"}
        p["reshard_ms"] = (time.monotonic() - t1) * 1000.0
        p["report"] = {
            k: report.get(k) for k in ("from", "to", "snapshot_time", "noop")
        }
        self._fire("reshard")
        self.workers = target
        self.decider.note_event(time.time())
        self._refresh_sup()

    def _launch(self, generation: int, reason: str | None):
        event = None
        if self._pending is not None:
            p, self._pending = self._pending, None
            d: Decision = p["decision"]
            event = {
                "kind": "scale",
                "t": round(time.time(), 3),
                "generation": generation,
                "from": p["from"],
                "to": self.workers,
                "direction": d.direction,
                "reason": d.reason,
                "signals": d.signals,
                "drain_ms": round(p.get("drain_ms", 0.0), 1),
                "reshard_ms": round(p.get("reshard_ms", 0.0), 1),
                "pause_ms": round(
                    (time.monotonic() - p["t0"]) * 1000.0, 1
                ),
                "report": p.get("report"),
            }
            self.events.append(event)
        env = {
            **self.base_env,
            **self._sup.child_env(generation, reason),
            "PATHWAY_PROCESSES": str(self.workers),
            # self-heal any marker/worker-count mismatch a killed
            # controller could leave behind
            "PATHWAY_ELASTIC": "1",
            "PATHWAY_AUTOSCALE": (
                f"{self.min_workers}..{self.max_workers}"
            ),
            "PATHWAY_AUTOSCALE_EVENTS": str(len(self.events)),
        }
        if self.events:
            last = self.events[-1]
            env["PATHWAY_AUTOSCALE_LAST_PAUSE_MS"] = str(last["pause_ms"])
            env["PATHWAY_AUTOSCALE_LAST_DECISION"] = (
                f"{last['from']}->{last['to']}: {last['reason']}"
            )
        preexec = _set_pdeathsig if os.name == "posix" else None
        procs = [
            subprocess.Popen(
                self.program,
                env={**env, "PATHWAY_PROCESS_ID": str(pid)},
                preexec_fn=preexec,
            )
            for pid in range(self.workers)
        ]
        self._gen_started = time.monotonic()
        self.decider.reset()
        self._append_log({
            "kind": "launch",
            "t": round(time.time(), 3),
            "generation": generation,
            "workers": self.workers,
            "pids": [pr.pid for pr in procs],
            "reason": reason,
        })
        if event is not None:
            self._append_log(event)
            self._fire("resume")
        return procs

    def _append_log(self, entry: dict) -> None:
        if not self.log_path:
            return
        try:
            with open(self.log_path, "a") as f:
                f.write(json.dumps(entry) + "\n")
        except OSError as e:  # observability must not stop the loop
            self._log(f"could not append {self.log_path}: {e}")
