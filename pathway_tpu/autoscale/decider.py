"""Traffic-driven scaling decisions — the autoscaler's pure half.

The :class:`Decider` consumes the merged ``/query`` document the
observability hub already serves (``observability/hub.py``) and answers
one question per poll: *should the cluster change size, and to what?*
It is deliberately free of processes, sockets and clocks-it-didn't-get
— every input arrives as an argument — so the flapping-resistance
properties the controller depends on are unit-testable with synthetic
documents.

Decision rules (knobs in :class:`DeciderConfig`, env-filled by
``from_env``):

- **scale up** when the worst worker's wall-anchored frontier lag stays
  above ``up_lag_ms`` for ``up_for_s`` *while input is flowing* (a lag
  that grows because the stream ended is idleness, not pressure), when
  the comm send queues stay at ``up_queue_frac`` of their bound
  for as long — the PATHWAY_COMM_QUEUE_FRAMES backpressure about to
  block the tick loop — or when the serve plane's admission queue
  (``serve.queue_depth`` vs ``serve.queue_bound``, serve/stats.py)
  stays at ``up_serve_frac`` of its bound for as long: sustained 429
  pressure at the query door is exactly the signal "add a shard
  worker";
- **scale down** when total ingest+emit falls below ``down_rows_per_s``
  for ``down_for_s``;
- **hysteresis**: a breach streak is a run of *consecutive* breaching
  samples — one non-breaching or missing sample resets it, so a
  single-sample spike can never trigger;
- **cooldown**: after any event, no decision for ``cooldown_s`` (the
  pipeline needs time to redistribute state and re-establish rates);
- **staleness**: a document older than ``stale_s``, or one whose
  roll-up marks any worker as served from a cached peer scrape
  (``stale_workers``), is *refused* — it also resets the streaks,
  because deciding from frozen numbers is how autoscalers kill
  clusters; refusals are counted, not silently dropped.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

__all__ = ["Decision", "Decider", "DeciderConfig", "load_scripted_plan"]


@dataclass(frozen=True)
class Decision:
    target: int
    direction: str  # "up" | "down"
    reason: str
    #: the signal values the decision was made from (event-log payload)
    signals: dict = field(default_factory=dict)


@dataclass
class DeciderConfig:
    min_workers: int
    max_workers: int
    #: sustained wall-anchored frontier lag that means "falling behind"
    up_lag_ms: float = 1000.0
    #: sustained send-queue occupancy (fraction of the queue bound)
    up_queue_frac: float = 0.5
    #: sustained serve admission-queue occupancy (fraction of
    #: PATHWAY_SERVE_QUEUE_BOUND) that means "queries are being shed"
    up_serve_frac: float = 0.5
    #: total input+output rows/s below which the cluster counts as idle
    down_rows_per_s: float = 1.0
    up_for_s: float = 3.0
    down_for_s: float = 10.0
    cooldown_s: float = 30.0
    #: refuse documents older than this, or with stale-marked workers
    stale_s: float = 10.0
    #: a hole between valid samples longer than this resets the streaks
    gap_s: float = 5.0
    #: workers added/removed per event
    step: int = 1

    @classmethod
    def from_env(cls, min_workers: int, max_workers: int) -> "DeciderConfig":
        from ..internals.config import _env_float, _env_int

        return cls(
            min_workers=min_workers,
            max_workers=max_workers,
            up_lag_ms=_env_float("PATHWAY_AUTOSCALE_UP_LAG_MS", 1000.0),
            up_queue_frac=_env_float("PATHWAY_AUTOSCALE_UP_QUEUE_FRAC", 0.5),
            up_serve_frac=_env_float("PATHWAY_AUTOSCALE_UP_SERVE_FRAC", 0.5),
            down_rows_per_s=_env_float(
                "PATHWAY_AUTOSCALE_DOWN_ROWS_PER_S", 1.0
            ),
            up_for_s=_env_float("PATHWAY_AUTOSCALE_UP_FOR_S", 3.0),
            down_for_s=_env_float("PATHWAY_AUTOSCALE_DOWN_FOR_S", 10.0),
            cooldown_s=_env_float("PATHWAY_AUTOSCALE_COOLDOWN_S", 30.0),
            stale_s=_env_float("PATHWAY_AUTOSCALE_STALE_S", 10.0),
            gap_s=_env_float("PATHWAY_AUTOSCALE_GAP_S", 5.0),
            step=max(1, _env_int("PATHWAY_AUTOSCALE_STEP", 1)),
        )


def _doc_signals(doc: dict) -> dict | None:
    """Extract the decision inputs from a merged ``/query`` document, or
    None when the document cannot support a decision (no worker series
    yet, signals plane off)."""
    if not doc or not doc.get("signals", True):
        return None
    workers = doc.get("workers") or {}
    if not workers:
        return None
    lags = [
        w.get("frontier_lag_ms")
        for w in workers.values()
        if w.get("frontier_lag_ms") is not None
    ]
    rate = 0.0
    saw_rate = False
    for w in workers.values():
        for key in ("input_rate", "output_rate"):
            v = w.get(key)
            if v is not None:
                rate += float(v)
                saw_rate = True
    # comm section: merged docs key by process, single-process docs are flat
    comm = doc.get("comm") or {}
    comm_by_proc = (
        comm
        if comm and all(isinstance(v, dict) for v in comm.values())
        else {"0": comm}
    )
    queue_frac = None
    for c in comm_by_proc.values():
        depth = (c or {}).get("send_queue_depth")
        cap = (c or {}).get("send_queue_capacity")
        if depth is None or not cap:
            continue
        frac = float(depth) / float(cap)
        if queue_frac is None or frac > queue_frac:
            queue_frac = frac
    # serve section: merged docs key by process, single-process docs are
    # flat; the worst process's admission-queue occupancy is the signal
    serve = doc.get("serve") or {}
    serve_by_proc = (
        serve
        if serve and all(isinstance(v, dict) for v in serve.values())
        else {"0": serve}
    )
    serve_frac = None
    for s in serve_by_proc.values():
        depth = (s or {}).get("queue_depth")
        cap = (s or {}).get("queue_bound")
        if depth is None or not cap:
            continue
        frac = float(depth) / float(cap)
        if serve_frac is None or frac > serve_frac:
            serve_frac = frac
    return {
        "lag_ms": max(lags) if lags else None,
        "rows_per_s": rate if saw_rate else None,
        "queue_frac": queue_frac,
        "serve_frac": serve_frac,
        "n_workers_reporting": len(workers),
    }


class Decider:
    def __init__(self, cfg: DeciderConfig):
        self.cfg = cfg
        self._up_since: float | None = None
        self._down_since: float | None = None
        self._last_event_t: float | None = None
        self._last_sample_t: float | None = None
        #: documents refused for staleness (observability, not control)
        self.refusals = 0

    # -- streak management --------------------------------------------

    def note_gap(self, now: float) -> None:
        """A poll produced no usable sample (endpoint unreachable, doc
        refused): the streaks lose their continuity evidence."""
        self._up_since = None
        self._down_since = None

    def note_event(self, now: float) -> None:
        """A scale event executed (or a generation [re]launched): start
        the cooldown and drop streaks built on the old topology."""
        self._last_event_t = now
        self._up_since = None
        self._down_since = None
        self._last_sample_t = None

    def reset(self) -> None:
        self._up_since = None
        self._down_since = None
        self._last_sample_t = None

    # -- the decision --------------------------------------------------

    def observe(
        self, doc: dict, current: int, now: float
    ) -> Decision | None:
        """Feed one merged ``/query`` document; returns a
        :class:`Decision` when a sustained condition crosses its
        hysteresis horizon outside the cooldown, else None."""
        cfg = self.cfg
        # staleness guard: refuse to decide from cached peer scrapes or
        # an old document — and treat the refusal as a gap
        stale = doc.get("stale_workers") or {}
        doc_age = now - float(doc.get("t", now))
        if stale or doc_age > cfg.stale_s:
            self.refusals += 1
            self.note_gap(now)
            return None
        sig = _doc_signals(doc)
        if sig is None:
            self.note_gap(now)
            return None
        if (
            self._last_sample_t is not None
            and now - self._last_sample_t > cfg.gap_s
        ):
            self.note_gap(now)  # sampler hole: streak continuity is gone
        self._last_sample_t = now

        lag, rows, queue = (
            sig["lag_ms"], sig["rows_per_s"], sig["queue_frac"]
        )
        serve = sig["serve_frac"]
        flowing = rows is not None and rows >= cfg.down_rows_per_s
        lag_hot = lag is not None and lag > cfg.up_lag_ms and flowing
        queue_hot = queue is not None and queue >= cfg.up_queue_frac
        # serve pressure needs no "flowing" guard: queries queueing at
        # the admission door IS the load, whatever the ingest rate says
        serve_hot = serve is not None and serve >= cfg.up_serve_frac
        up = lag_hot or queue_hot or serve_hot
        down = rows is not None and rows < cfg.down_rows_per_s and not up
        if up:
            self._down_since = None
            if self._up_since is None:
                self._up_since = now
        elif down:
            self._up_since = None
            if self._down_since is None:
                self._down_since = now
        else:
            self._up_since = None
            self._down_since = None

        if (
            self._last_event_t is not None
            and now - self._last_event_t < cfg.cooldown_s
        ):
            return None  # cooling down; streaks keep accruing above
        if (
            self._up_since is not None
            and now - self._up_since >= cfg.up_for_s
            and current < cfg.max_workers
        ):
            target = min(cfg.max_workers, current + cfg.step)
            if lag_hot:
                why = f"frontier lag {lag:.0f}ms > {cfg.up_lag_ms:.0f}ms"
            elif queue_hot:
                why = f"send queue {queue:.2f} >= {cfg.up_queue_frac:.2f}"
            else:
                why = (
                    f"serve queue {serve:.2f} >= {cfg.up_serve_frac:.2f}"
                )
            return Decision(
                target, "up", f"{why} for {cfg.up_for_s:.1f}s", sig
            )
        if (
            self._down_since is not None
            and now - self._down_since >= cfg.down_for_s
            and current > cfg.min_workers
        ):
            target = max(cfg.min_workers, current - cfg.step)
            return Decision(
                target,
                "down",
                f"idle ({rows:.1f} rows/s < {cfg.down_rows_per_s:.1f}) "
                f"for {cfg.down_for_s:.1f}s",
                sig,
            )
        return None


def load_scripted_plan(spec: str | None = None) -> list[dict]:
    """Parse ``PATHWAY_AUTOSCALE_PLAN`` — a scripted decision schedule
    (``[{"after_s": 2.0, "to": 3}, ...]``, inline JSON or a file path)
    that REPLACES the signal-driven decisions. The determinism hook the
    chaos suite and the pause bench stand on: a scale event at a known
    time, independent of load thresholds."""
    import json

    if spec is None:
        spec = os.environ.get("PATHWAY_AUTOSCALE_PLAN")
    if not spec or not spec.strip():
        return []
    spec = spec.strip()
    if not spec.startswith(("[", "{")):
        # anything not inline JSON is a file path; a "{...}" object is
        # inline-but-wrong and must get the expected-a-list error below,
        # not a FileNotFoundError for a file named like JSON
        with open(spec) as f:
            spec = f.read()
    steps = json.loads(spec)
    if not isinstance(steps, list):
        raise ValueError("PATHWAY_AUTOSCALE_PLAN: expected a JSON list")
    out = []
    for i, s in enumerate(steps):
        if not isinstance(s, dict) or "after_s" not in s or "to" not in s:
            raise ValueError(
                f"PATHWAY_AUTOSCALE_PLAN step #{i}: need after_s and to"
            )
        out.append({"after_s": float(s["after_s"]), "to": int(s["to"])})
    out.sort(key=lambda s: s["after_s"])
    return out
