"""Closed-loop autoscaling — traffic-driven live rescaling.

``pathway-tpu spawn --autoscale MIN..MAX --store <root>`` wraps the
process ensemble in an :class:`AutoscaleController`: it watches the
signals plane's merged ``/query`` document on process 0, decides a
target worker count (``decider.py`` — sustained frontier lag or
send-queue saturation scales up, sustained idleness scales down, with
hysteresis, cooldown and a stale-scrape refusal), and executes the live
rescale (``controller.py``): cooperative drain to the delivery
boundary, offline reshard (``rescale/``), supervised resume — zero
dropped rows, pause measured per event.
"""

from .controller import AutoscaleController, AutoscaleError, parse_range
from .decider import Decider, DeciderConfig, Decision, load_scripted_plan

__all__ = [
    "AutoscaleController",
    "AutoscaleError",
    "Decider",
    "DeciderConfig",
    "Decision",
    "load_scripted_plan",
    "parse_range",
]
