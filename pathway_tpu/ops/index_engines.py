"""Mutable index engines: TPU brute-force KNN, LSH KNN, BM25, hybrid fusion.

These implement the ``engine.external_index.IndexEngine`` protocol and
replace the reference's native index integrations
(``src/external_integration/{usearch,tantivy,brute_force_knn}_integration.rs``).
The KNN hot path is an XLA kernel: one bf16 matmul on the MXU over the whole
index block + ``lax.top_k`` (``ops/knn.py``); the index lives device-resident
in a capacity-doubling arena so shapes stay static per capacity tier and the
jit cache stays warm. BM25 is host-side (string-heavy, branchy — the wrong
shape for the MXU), mirroring the reference's Tantivy choice of CPU.
"""

from __future__ import annotations

import math
import re
from typing import Any, Callable

import numpy as np

from ..utils.filters import compile_metadata_filter

__all__ = [
    "BruteForceKnnEngine",
    "LshKnnEngine",
    "BM25Engine",
    "HybridEngine",
]


def _as_json(filter_data: Any) -> Any:
    import json as _json

    if filter_data is None:
        return None
    if isinstance(filter_data, str):
        try:
            return _json.loads(filter_data)
        except ValueError:
            return None
    from ..internals.json import Json

    if isinstance(filter_data, Json):
        return filter_data.value
    return filter_data


class _SlotArena:
    """Keyed slot allocator with a free list (host-side directory of the
    device-resident index block)."""

    def __init__(self) -> None:
        self.key_to_slot: dict[int, int] = {}
        self.slot_to_key: dict[int, int] = {}
        self.meta: dict[int, Any] = {}
        self.free: list[int] = []
        self.high = 0

    def alloc(self, key: int) -> int:
        slot = self.free.pop() if self.free else self.high
        if slot == self.high:
            self.high += 1
        self.key_to_slot[key] = slot
        self.slot_to_key[slot] = key
        return slot

    def release(self, key: int) -> int | None:
        slot = self.key_to_slot.pop(key, None)
        if slot is None:
            return None
        self.slot_to_key.pop(slot, None)
        self.meta.pop(slot, None)
        self.free.append(slot)
        return slot


class BruteForceKnnEngine:
    """Exact KNN on TPU: the index block is one [capacity, dim] device array.

    ``metric``: "cos" (inputs L2-normalized at insert/query time) or "l2"
    (negative squared distance). Capacity doubles on overflow — one recompile
    per tier, amortized.
    """

    def __init__(self, dimensions: int, *, metric: str = "cos",
                 reserved_space: int = 1024,
                 embedder: Callable[[str], np.ndarray] | None = None):
        self.dim = dimensions
        self.metric = metric
        self.embedder = embedder
        self.capacity = max(16, int(reserved_space))
        self._host = np.zeros((self.capacity, self.dim), dtype=np.float32)
        self._valid = np.zeros(self.capacity, dtype=bool)
        self._slots = _SlotArena()
        self._device = None  # lazily synced jax copy
        self._dirty = True

    # operator snapshots pickle the whole engine; the device mirror is a
    # cache rebuilt on first search after restore
    def __getstate__(self):
        st = dict(self.__dict__)
        st["_device"] = None
        st.pop("_device_valid", None)
        st["_dirty"] = True
        # the embedder may be an arbitrary closure (not picklable); the
        # restoring node grafts the freshly-constructed engine's embedder back
        st["embedder"] = None
        return st

    # -- mutation ----------------------------------------------------------
    def _vec(self, data: Any) -> np.ndarray:
        if isinstance(data, str):
            if self.embedder is None:
                raise TypeError("string data requires an embedder")
            batch = getattr(self.embedder, "embed_texts", None)
            # a models.Embedder works directly as the engine embedder
            data = batch([data])[0] if batch is not None else self.embedder(data)
        v = np.asarray(data, dtype=np.float32).reshape(-1)
        if v.shape[0] != self.dim:
            raise ValueError(f"vector dim {v.shape[0]} != index dim {self.dim}")
        if self.metric == "cos":
            # "ip" deliberately skips this: raw inner product keeps magnitude
            n = float(np.linalg.norm(v))
            if n > 0:
                v = v / n
        return v

    def add(self, key: int, data: Any, filter_data: Any) -> None:
        v = self._vec(data)
        if key in self._slots.key_to_slot:
            self._slots.release(key)
        slot = self._slots.alloc(key)
        if slot >= self.capacity:
            self._grow()
        self._host[slot] = v
        self._valid[slot] = True
        self._slots.meta[slot] = _as_json(filter_data)
        self._dirty = True

    def add_batch(self, keys: list[int], datas: list[Any], filters: list[Any]) -> None:
        """Bulk insertion: all string payloads of one tick are embedded in a
        single batched device call (one MXU forward + one roundtrip instead
        of one per document) — the ingest-path analog of the device-resident
        query fusion. Called by ExternalIndexNode when available.

        When every payload is already a vector and this is a plain
        brute-force engine (no subclass bucketing hooks), insertion is one
        vectorized slab write — normalize + slot-assign the whole tick at
        numpy speed instead of a million ``add`` calls (the 1M-doc
        north-star ingest path)."""
        batch = getattr(self.embedder, "embed_texts", None)
        text_ix = [
            i for i, d in enumerate(datas) if isinstance(d, str)
        ] if batch is not None else []
        if text_ix:
            vecs = batch([datas[i] for i in text_ix])
            datas = list(datas)
            for j, i in enumerate(text_ix):
                datas[i] = np.asarray(vecs[j], dtype=np.float32)
        if type(self).add is BruteForceKnnEngine.add and not any(
            isinstance(d, str) for d in datas
        ):
            self._bulk_add(keys, datas, filters)
            return
        for k, d, f in zip(keys, datas, filters):
            self.add(k, d, f)

    def _bulk_add(self, keys: list[int], datas: list[Any], filters: list[Any]) -> None:
        n = len(keys)
        if n == 0:
            return
        try:
            vecs = np.stack([np.asarray(d, dtype=np.float32).reshape(-1)
                             for d in datas])
        except ValueError:  # ragged dims — per-row path raises the right error
            for k, d, f in zip(keys, datas, filters):
                self.add(k, d, f)
            return
        if vecs.shape[1] != self.dim:
            raise ValueError(
                f"vector dim {vecs.shape[1]} != index dim {self.dim}"
            )
        if self.metric == "cos":
            norms = np.linalg.norm(vecs, axis=1, keepdims=True)
            np.divide(vecs, norms, out=vecs, where=norms > 0)
        ikeys = [int(k) for k in keys]
        if len(set(ikeys)) != len(ikeys):
            # duplicate keys in one tick (diff multiplicity, in-tick
            # updates): keep only the last occurrence — matching the
            # per-row path, where each add replaces the previous slot
            last = {k: i for i, k in enumerate(ikeys)}
            keep = sorted(last.values())
            ikeys = [ikeys[i] for i in keep]
            vecs = vecs[keep]
            filters = [filters[i] for i in keep]
            n = len(ikeys)
        for k in ikeys:
            if k in self._slots.key_to_slot:
                self._slots.release(k)
        if self._slots.free:
            slots = np.array([self._slots.alloc(k) for k in ikeys],
                             dtype=np.int64)
        else:  # fresh block: bulk dict updates, no per-key alloc calls
            start = self._slots.high
            slots = np.arange(start, start + n, dtype=np.int64)
            self._slots.high = start + n
            slot_list = slots.tolist()
            self._slots.key_to_slot.update(zip(ikeys, slot_list))
            self._slots.slot_to_key.update(zip(slot_list, ikeys))
        if self._slots.high > self.capacity:
            self._grow(self._slots.high)
        self._host[slots] = vecs
        self._valid[slots] = True
        for slot, f in zip(slots.tolist(), filters):
            if f is not None:
                self._slots.meta[slot] = _as_json(f)
        self._dirty = True

    def remove(self, key: int) -> None:
        slot = self._slots.release(key)
        if slot is not None:
            self._valid[slot] = False
            self._dirty = True

    def _grow(self, needed: int | None = None) -> None:
        new_cap = self.capacity * 2
        while new_cap < (needed or 0):
            new_cap *= 2
        host = np.zeros((new_cap, self.dim), dtype=np.float32)
        host[: self.capacity] = self._host
        valid = np.zeros(new_cap, dtype=bool)
        valid[: self.capacity] = self._valid
        self._host, self._valid, self.capacity = host, valid, new_cap

    # -- search ------------------------------------------------------------
    def search(self, queries: list[Any], limits: list[int], filters: list[Any]):
        n = self._slots.high
        if n == 0 or not queries:
            return [[] for _ in queries]
        import jax.numpy as jnp

        from .knn import topk_scores

        dev_embed = getattr(self.embedder, "embed_texts_device", None)
        if dev_embed is not None and all(isinstance(x, str) for x in queries):
            # device-resident query embeddings (already L2-normalized by the
            # model head) flow straight into the scorer: embed -> score ->
            # top_k pipelines as queued device work with a single blocking
            # fetch at _pack time
            q = dev_embed(list(queries))
        else:
            q = np.stack([self._vec(x) for x in queries])
        if self._dirty or self._device is None:
            self._device = jnp.asarray(self._host)
            self._device_valid = jnp.asarray(self._valid)
            self._dirty = False

        kmax = min(max(limits), int(self._valid.sum()))
        if kmax <= 0:
            return [[] for _ in queries]

        filt_fns = [compile_metadata_filter(f) for f in filters]
        if any(f is not None for f in filt_fns):
            # per-query validity: metadata filter evaluated on the host
            # directory, applied as a -inf mask before device top-k
            out = []
            for qi, (fv, lim) in enumerate(zip(filt_fns, limits)):
                mask = self._valid.copy()
                if fv is not None:
                    for slot in range(n):
                        if mask[slot] and not fv(self._slots.meta.get(slot)):
                            mask[slot] = False
                k_eff = min(lim, int(mask.sum()))
                if k_eff <= 0:
                    out.append([])
                    continue
                s, ids = topk_scores(
                    jnp.asarray(q[qi : qi + 1]), self._device, k_eff,
                    self.metric, valid=jnp.asarray(mask),
                )
                out.append(self._pack(np.asarray(s)[0], np.asarray(ids)[0], lim))
            return out

        s, ids = topk_scores(jnp.asarray(q), self._device, kmax, self.metric,
                             valid=self._device_valid)
        s, ids = np.asarray(s), np.asarray(ids)
        return [
            self._pack(s[i], ids[i], limits[i]) for i in range(len(queries))
        ]

    def _pack(self, scores: np.ndarray, slots: np.ndarray, limit: int):
        out = []
        for sc, slot in zip(scores, slots):
            if len(out) >= limit or not np.isfinite(sc):
                break
            key = self._slots.slot_to_key.get(int(slot))
            if key is not None:
                out.append((key, float(sc)))
        return out


class LshKnnEngine(BruteForceKnnEngine):
    """LSH-bucketed approximate KNN (reference ``LshKnn``,
    ``stdlib/ml/index.py`` classic impl): random-hyperplane signatures route
    vectors to buckets; queries score only their buckets' candidates — the
    exact scoring of the candidate set still runs through the TPU kernel
    path when the set is large, numpy below that.
    """

    def __init__(self, dimensions: int, *, metric: str = "cos",
                 reserved_space: int = 1024, n_or: int = 4, n_and: int = 8,
                 bucket_length: float | None = None, seed: int = 0,
                 embedder: Callable[[str], np.ndarray] | None = None):
        super().__init__(dimensions, metric=metric,
                         reserved_space=reserved_space, embedder=embedder)
        rng = np.random.default_rng(seed)
        self.n_or = n_or
        self.n_and = n_and
        self._planes = rng.standard_normal((n_or, n_and, dimensions)).astype(
            np.float32
        )
        self._buckets: list[dict[int, set[int]]] = [dict() for _ in range(n_or)]
        self._slot_sigs: dict[int, list[int]] = {}

    def _signatures(self, v: np.ndarray) -> list[int]:
        bits = (np.einsum("oad,d->oa", self._planes, v) > 0).astype(np.uint64)
        weights = (2 ** np.arange(self.n_and, dtype=np.uint64))
        return [int((bits[o] * weights).sum()) for o in range(self.n_or)]

    def add(self, key: int, data: Any, filter_data: Any) -> None:
        if key in self._slots.key_to_slot:
            # clean old bucket entries before re-slotting (plain super().add
            # would re-allocate the slot and leak the old signatures)
            self.remove(key)
        super().add(key, data, filter_data)
        slot = self._slots.key_to_slot[key]
        sigs = self._signatures(self._host[slot])
        self._slot_sigs[slot] = sigs
        for o, sig in enumerate(sigs):
            self._buckets[o].setdefault(sig, set()).add(slot)

    def remove(self, key: int) -> None:
        slot = self._slots.key_to_slot.get(key)
        super().remove(key)
        if slot is not None:
            for o, sig in enumerate(self._slot_sigs.pop(slot, [])):
                self._buckets[o].get(sig, set()).discard(slot)

    def search(self, queries: list[Any], limits: list[int], filters: list[Any]):
        if self._slots.high == 0 or not queries:
            return [[] for _ in queries]
        filt_fns = [compile_metadata_filter(f) for f in filters]
        out = []
        for qd, lim, fv in zip(queries, limits, filt_fns):
            v = self._vec(qd)
            cand: set[int] = set()
            for o, sig in enumerate(self._signatures(v)):
                cand |= self._buckets[o].get(sig, set())
            cand = {s for s in cand if self._valid[s]}
            if fv is not None:
                cand = {s for s in cand if fv(self._slots.meta.get(s))}
            if not cand:
                out.append([])
                continue
            slots = np.fromiter(cand, dtype=np.int64)
            block = self._host[slots]
            if self.metric in ("cos", "ip"):
                scores = block @ v
            else:
                scores = -((block - v[None, :]) ** 2).sum(axis=1)
            top = np.argsort(-scores)[:lim]
            out.append([
                (self._slots.slot_to_key[int(slots[i])], float(scores[i]))
                for i in top
            ])
        return out


_TOKEN_RE = re.compile(r"[A-Za-z0-9_]+")


def tokenize(text: str) -> list[str]:
    return [t.lower() for t in _TOKEN_RE.findall(text)]


class BM25Engine:
    """In-memory BM25 full-text index (replaces the reference's Tantivy
    integration, ``tantivy_integration.rs``). Host-side inverted index:
    token → {key: tf}; Okapi BM25 scoring with k1/b."""

    def __init__(self, *, ram_budget: int = 0, in_memory_index: bool = True,
                 k1: float = 1.2, b: float = 0.75):
        self.k1 = k1
        self.b = b
        self._postings: dict[str, dict[int, int]] = {}
        self._doc_len: dict[int, int] = {}
        self._doc_tokens: dict[int, list[str]] = {}
        self._meta: dict[int, Any] = {}

    def add(self, key: int, data: Any, filter_data: Any) -> None:
        if key in self._doc_len:
            self.remove(key)
        toks = tokenize(str(data))
        self._doc_tokens[key] = toks
        self._doc_len[key] = len(toks)
        self._meta[key] = _as_json(filter_data)
        for t in toks:
            self._postings.setdefault(t, {})
            self._postings[t][key] = self._postings[t].get(key, 0) + 1

    def remove(self, key: int) -> None:
        toks = self._doc_tokens.pop(key, None)
        if toks is None:
            return
        self._doc_len.pop(key, None)
        self._meta.pop(key, None)
        for t in set(toks):
            plist = self._postings.get(t)
            if plist is not None:
                plist.pop(key, None)
                if not plist:
                    del self._postings[t]

    def search(self, queries: list[Any], limits: list[int], filters: list[Any]):
        n_docs = len(self._doc_len)
        if n_docs == 0 or not queries:
            return [[] for _ in queries]
        avgdl = sum(self._doc_len.values()) / n_docs
        filt_fns = [compile_metadata_filter(f) for f in filters]
        out = []
        for q, lim, fv in zip(queries, limits, filt_fns):
            scores: dict[int, float] = {}
            for t in tokenize(str(q)):
                plist = self._postings.get(t)
                if not plist:
                    continue
                idf = math.log(1.0 + (n_docs - len(plist) + 0.5) / (len(plist) + 0.5))
                for key, tf in plist.items():
                    dl = self._doc_len[key]
                    denom = tf + self.k1 * (1 - self.b + self.b * dl / avgdl)
                    scores[key] = scores.get(key, 0.0) + idf * tf * (self.k1 + 1) / denom
            ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
            if fv is not None:
                ranked = [(k, s) for k, s in ranked if fv(self._meta.get(k))]
            out.append([(k, float(s)) for k, s in ranked[:lim] if s > 0])
        return out


class HybridEngine:
    """Reciprocal-rank fusion over sub-engines (reference ``HybridIndex``,
    ``stdlib/indexing/hybrid_index.py``): score = Σ 1/(rrf_k + rank)."""

    def __init__(self, engines: list[Any], *, rrf_k: int = 60,
                 adapters: list[Callable[[Any], Any]] | None = None):
        self.engines = engines
        self.rrf_k = rrf_k
        self.adapters = adapters or [None] * len(engines)

    def add(self, key: int, data: Any, filter_data: Any) -> None:
        for eng, ad in zip(self.engines, self.adapters):
            eng.add(key, ad(data) if ad else data, filter_data)

    def remove(self, key: int) -> None:
        for eng in self.engines:
            eng.remove(key)

    def search(self, queries: list[Any], limits: list[int], filters: list[Any]):
        # each sub-engine retrieves a deeper pool so fusion has candidates
        deep = [max(l * 2, l + 5) for l in limits]
        per_engine = [
            eng.search(
                [ad(q) if ad else q for q in queries], deep, filters
            )
            for eng, ad in zip(self.engines, self.adapters)
        ]
        out = []
        for qi in range(len(queries)):
            fused: dict[int, float] = {}
            for replies in per_engine:
                for rank, (key, _score) in enumerate(replies[qi]):
                    fused[key] = fused.get(key, 0.0) + 1.0 / (self.rrf_k + rank + 1)
            ranked = sorted(fused.items(), key=lambda kv: (-kv[1], kv[0]))
            out.append([(k, float(s)) for k, s in ranked[: limits[qi]]])
        return out
