"""TPU-native KNN kernels.

Replaces the reference's native ANN engines — USearch HNSW
(``src/external_integration/usearch_integration.rs``) and the brute-force
CPU index (``brute_force_knn_integration.rs``) — with XLA kernels: scoring
is one bf16 matmul on the MXU (batch × index), top-k via ``lax.top_k``.
A mesh-sharded variant splits the index rows across devices and merges
local top-k with an all-gather — the "sharded vector index over ICI" of
BASELINE.json's north star.

The same local-top-k → global-top-k shape exists at two scales: within a
device mesh the merge is the in-XLA all-gather below; across WORKERS the
serve plane (``serve/router.py``) carries each shard's host-side candidate
list over the wire and merges with :func:`merge_shard_topk` — the
host-side generalization of this file's gather-merge.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "topk_scores", "knn_search", "ShardedKnnIndex", "sharded_knn_search",
    "merge_shard_topk",
]


def merge_shard_topk(
    parts: "list[list[tuple[Any, float]]]", k: int
) -> "list[tuple[Any, float]]":
    """Merge per-shard best-first (key, score) candidate lists into a
    global top-k on the host — the cross-worker counterpart of
    ``sharded_knn_search``'s in-mesh all-gather merge (scores compare
    higher-is-better; duplicate keys keep their best score)."""
    from ..serve.merge import merge_topk

    return merge_topk(parts, k)


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def topk_scores(
    queries: jax.Array,
    index: jax.Array,
    k: int,
    metric: str = "cos",
    valid: jax.Array | None = None,
):
    """queries [q, d] (f32), index [n, d] -> (scores [q,k], ids [q,k]).

    cos: both sides assumed L2-normalized → dot product == cosine.
    l2: negative squared distance (higher is closer).
    valid [n] bool: rows where False are masked to -inf BEFORE top-k
    (capacity padding must never displace real documents).
    """
    qb = queries.astype(jnp.bfloat16)
    ib = index.astype(jnp.bfloat16)
    if metric in ("cos", "ip"):
        # cos assumes L2-normalized inputs; ip is the raw inner product
        scores = (qb @ ib.T).astype(jnp.float32)
    else:
        sq_i = (index.astype(jnp.float32) ** 2).sum(-1)
        dots = (qb @ ib.T).astype(jnp.float32)
        sq_q = (queries.astype(jnp.float32) ** 2).sum(-1, keepdims=True)
        scores = -(sq_q - 2 * dots + sq_i[None, :])
    if valid is not None:
        scores = jnp.where(valid[None, :], scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def knn_search(queries: np.ndarray, index: np.ndarray, k: int, metric: str = "cos"):
    s, i = topk_scores(jnp.asarray(queries), jnp.asarray(index), k, metric)
    return np.asarray(s), np.asarray(i)


def sharded_knn_search(
    mesh: Mesh,
    axis: str,
    queries: jax.Array,
    index_sharded: jax.Array,
    k: int,
    metric: str = "cos",
    valid_sharded: jax.Array | None = None,
):
    """Index rows sharded over `axis`; queries replicated. Each device scores
    its shard and takes a local top-k; an all-gather over `axis` + global
    top-k merges — the collective rides the ICI. k must be ≤ rows per shard.
    """
    n_shards = mesh.shape[axis]
    rows_per_shard = index_sharded.shape[0] // n_shards
    if k > rows_per_shard:
        raise ValueError(
            f"k={k} exceeds rows per shard ({rows_per_shard}); "
            "raise index capacity or lower k"
        )

    from ..internals.jax_compat import shard_map

    specs_in = [P(), P(axis, None)]
    args = [queries, index_sharded]
    if valid_sharded is not None:
        specs_in.append(P(axis))
        args.append(valid_sharded)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=tuple(specs_in),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def search(q, shard, *maybe_valid):
        my = jax.lax.axis_index(axis)
        v = maybe_valid[0] if maybe_valid else None
        s, i = topk_scores(q, shard, k, metric, valid=v)
        i = i + my * rows_per_shard
        # gather all shards' candidates, merge to global top-k
        all_s = jax.lax.all_gather(s, axis, axis=1).reshape(q.shape[0], -1)
        all_i = jax.lax.all_gather(i, axis, axis=1).reshape(q.shape[0], -1)
        gs, gpos = jax.lax.top_k(all_s, k)
        gi = jnp.take_along_axis(all_i, gpos, axis=1)
        return gs, gi

    return search(*args)


class ShardedKnnIndex:
    """Device-resident brute-force index with insert/query (host API).

    Capacity-padded: rows beyond ``size`` are masked by a -inf score via a
    validity column, so shapes stay static for XLA. Single-device by default;
    pass a mesh to shard rows across devices.
    """

    def __init__(
        self,
        dim: int,
        capacity: int = 1 << 20,
        metric: str = "cos",
        mesh: Mesh | None = None,
        axis: str = "data",
    ):
        self.dim = dim
        self.capacity = capacity
        self.metric = metric
        self.mesh = mesh
        self.axis = axis
        self.size = 0
        if mesh is not None:
            self._data = jax.device_put(
                jnp.zeros((capacity, dim), jnp.float32),
                NamedSharding(mesh, P(axis, None)),
            )
            self._valid_d = jax.device_put(
                jnp.zeros((capacity,), jnp.bool_), NamedSharding(mesh, P(axis))
            )
        else:
            self._data = jnp.zeros((capacity, dim), jnp.float32)
            self._valid_d = jnp.zeros((capacity,), jnp.bool_)
        self._keys: list[Any] = []

    def add(self, vectors: np.ndarray, keys: list[Any] | None = None) -> None:
        n = len(vectors)
        if self.size + n > self.capacity:
            raise ValueError("index capacity exceeded")
        self._data = jax.lax.dynamic_update_slice(
            self._data, jnp.asarray(vectors, jnp.float32), (self.size, 0)
        )
        self._valid_d = jax.lax.dynamic_update_slice(
            self._valid_d, jnp.ones((n,), jnp.bool_), (self.size,)
        )
        self._keys.extend(keys if keys is not None else range(self.size, self.size + n))
        self.size += n

    def query(self, queries: np.ndarray, k: int):
        k_eff = min(k, max(self.size, 1))
        if self.mesh is not None:
            # the sharded merge needs k candidates from every shard
            k_eff = min(k_eff, self.capacity // self.mesh.shape[self.axis])
            s, i = sharded_knn_search(
                self.mesh, self.axis, jnp.asarray(queries, jnp.float32),
                self._data, k_eff, self.metric, valid_sharded=self._valid_d,
            )
        else:
            s, i = topk_scores(
                jnp.asarray(queries, jnp.float32), self._data, k_eff,
                self.metric, valid=self._valid_d,
            )
        return np.asarray(s), np.asarray(i)

    def keys_of(self, ids: np.ndarray):
        return [
            [self._keys[j] if 0 <= j < len(self._keys) else None for j in row]
            for row in ids
        ]
