"""Supervised cluster runtime — restart-from-snapshot on worker death.

``pathway-tpu spawn --supervise`` wraps the process ensemble in a
:class:`Supervisor`: it watches every child for death (exit code) and
wedge (the PR 1 ``/healthz`` heartbeat probe), and on any failure

1. tears the surviving peers down **cooperatively** — SIGTERM first, which
   the children translate into ``request_stop()`` (``internals/run.py``)
   so their persistence managers flush the recorded input tail via
   ``close()`` before exiting; SIGKILL only after a grace period;
2. **harvests the dead workers' flight-recorder rings**
   (``observability/flightrecorder.py``, ``PATHWAY_FLIGHT_DIR``) into
   ``crash-<generation>-<process>.json`` forensic bundles — the crashed
   worker's final ticks, chaos injections fired, comm-break reasons and
   exit reason — and stamps the bundle path into the restart reason (so
   it reaches ``PATHWAY_LAST_RESTART_REASON`` and the
   ``pathway_last_restart_reason`` metric label); harvested bundle count
   is exported as ``pathway_flight_recorder_dumps_total`` via
   ``PATHWAY_FLIGHT_DUMPS``;
3. restarts the WHOLE ensemble (the engine recovers from the last
   snapshot common to every worker — ``Executor._recover``) after a
   jittered exponential backoff, stamping each generation's environment
   with ``PATHWAY_RESTART_COUNT`` / ``PATHWAY_LAST_RESTART_REASON`` so
   fault plans gate per generation and ``/metrics`` exports
   ``pathway_restarts_total`` + ``pathway_last_restart_reason``;
4. gives up when the crash-loop circuit breaker trips: more than
   ``max_restarts`` restarts inside a ``window_s`` sliding window means
   the program dies deterministically (a poisoned input, a broken
   deploy) and restarting is harm, not healing.

The reference treats restart-with-recovery as the fault-tolerance
contract (wordcount's ``run_pw_program_suddenly_terminate`` SIGKILLs the
engine and reruns it in a loop, demanding exact final output); this
module is that loop, productized.

Env knobs (CLI flags override): ``PATHWAY_SUPERVISE_MAX_RESTARTS`` (5),
``PATHWAY_SUPERVISE_WINDOW_S`` (60), ``PATHWAY_SUPERVISE_BACKOFF_S``
(0.5 initial, doubling), ``PATHWAY_SUPERVISE_BACKOFF_MAX_S`` (30),
``PATHWAY_SUPERVISE_GRACE_S`` (5).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from collections import deque
from typing import Any, Callable, Sequence

__all__ = ["Supervisor", "RestartBudgetExceeded"]

#: circuit breaker opened — the ensemble is crash-looping
EXIT_CIRCUIT_OPEN = 75  # EX_TEMPFAIL

#: ring record kinds emitted continuously during a healthy run — these
#: dominate the ring byte-for-byte and are only interesting near the
#: moment of death, so the crash bundle keeps just their recent tail
#: (rare forensic kinds — slo.alert, chaos.fired, comm.broken — are
#: kept in full regardless of age)
_FREQUENT_RECORD_KINDS = frozenset(
    {"tick", "wave.phase", "async.commit", "profile.top"}
)


class RestartBudgetExceeded(RuntimeError):
    pass


class Supervisor:
    """Run ``launch`` generations of a process ensemble until clean exit,
    restarting on failure with backoff + a sliding-window circuit breaker.

    ``launch(generation, reason)`` must return the ensemble's
    ``subprocess.Popen`` handles; ``reason`` is None for generation 0 and
    the previous generation's failure description afterwards.
    """

    def __init__(
        self,
        launch: Callable[[int, str | None], Sequence[subprocess.Popen]],
        *,
        max_restarts: int | None = None,
        window_s: float | None = None,
        backoff_s: float | None = None,
        backoff_max_s: float | None = None,
        grace_s: float | None = None,
        health_ports: Sequence[int] | None = None,
        health_interval_s: float = 1.0,
        poll_interval_s: float = 0.05,
        rng: Callable[[], float] | None = None,
        log: Callable[[str], Any] | None = None,
        labels: Sequence[str] | None = None,
        flight_dir: str | None = None,
        process_ids: Sequence[int] | None = None,
        run_id: str | None = None,
        poll_hook: Callable[[], str | None] | None = None,
        planned_stop: Callable[[str], Any] | None = None,
    ):
        from ..internals.config import _env_float, _env_int

        self.launch = launch
        self.max_restarts = (
            max_restarts
            if max_restarts is not None
            else _env_int("PATHWAY_SUPERVISE_MAX_RESTARTS", 5)
        )
        self.window_s = (
            window_s
            if window_s is not None
            else _env_float("PATHWAY_SUPERVISE_WINDOW_S", 60.0)
        )
        self.backoff_s = (
            backoff_s
            if backoff_s is not None
            else _env_float("PATHWAY_SUPERVISE_BACKOFF_S", 0.5)
        )
        self.backoff_max_s = (
            backoff_max_s
            if backoff_max_s is not None
            else _env_float("PATHWAY_SUPERVISE_BACKOFF_MAX_S", 30.0)
        )
        self.grace_s = (
            grace_s
            if grace_s is not None
            else _env_float("PATHWAY_SUPERVISE_GRACE_S", 5.0)
        )
        #: per-process /healthz ports (monitoring base + pid); empty =
        #: exit-code supervision only
        self.health_ports = list(health_ports or [])
        self.health_interval_s = health_interval_s
        self.poll_interval_s = poll_interval_s
        #: display names aligned with launch()'s Popen order — the CLI
        #: passes real process ids so failure reasons name the right
        #: worker even under a -p id subset
        self.labels = list(labels or [])
        self._rng = rng if rng is not None else __import__("random").random
        self._log = log if log is not None else (
            lambda msg: print(f"[supervisor] {msg}", file=sys.stderr)
        )
        #: where the children's flight-recorder rings (and the crash
        #: bundles harvested from them) live; None/empty = no forensics
        self.flight_dir = (
            flight_dir
            if flight_dir is not None
            else os.environ.get("PATHWAY_FLIGHT_DIR")
        )
        #: real process ids aligned with launch()'s Popen order (ring files
        #: are named flight-p<process_id>.ring); default 0..N-1 by index
        self.process_ids = list(process_ids or [])
        #: the ensemble's PATHWAY_RUN_ID: a harvested ring must carry it,
        #: or it is a stale leftover of a PREVIOUS run in the same
        #: flight dir (a child that dies before arming its recorder never
        #: overwrites the old ring) and bundling it would misattribute
        #: another run's forensics to this one
        self.run_id = (
            run_id
            if run_id is not None
            else os.environ.get("PATHWAY_RUN_ID")
        )
        #: called every watch poll while the generation is healthy; a
        #: non-None token requests a PLANNED stop: cooperative teardown
        #: (the drain-to-delivery-boundary the persistence close protocol
        #: guarantees), then ``planned_stop(token)``, then relaunch —
        #: without burning restart budget. The autoscale controller's
        #: seam into the supervision loop.
        self.poll_hook = poll_hook
        self.planned_stop = planned_stop
        self._planned: str | None = None
        self.restarts_total = 0
        self.last_restart_reason: str | None = None
        self.flight_dumps_total = 0
        #: failures inside the current circuit-breaker window at the
        #: moment the current generation launched — the CLI stamps it
        #: into child environments (PATHWAY_SUPERVISE_WINDOW_FAILURES) so
        #: /metrics shows a restart storm building BEFORE the breaker
        #: trips (pathway_circuit_open / pathway_restart_window_failures)
        self.window_failures = 0
        #: Popen indices implicated in the current generation's failure
        #: (dead exit code or served-503 wedge) — the rings worth harvesting
        self._failed_indices: list[int] = []

    # -- lifecycle -------------------------------------------------------

    def child_env(self, generation: int, reason: str | None) -> dict[str, str]:
        """The supervision stamps every launched child must carry — the
        observability hub reads exactly these keys for /metrics
        (pathway_restarts_total, pathway_flight_recorder_dumps_total,
        pathway_restart_window_failures / pathway_circuit_open,
        pathway_last_restart_reason). One source of truth for every
        launcher (cli ``spawn --supervise`` and the autoscale
        controller), so supervised and autoscaled runs cannot drift
        apart in what they export."""
        env = {
            "PATHWAY_SUPERVISED": "1",
            "PATHWAY_RESTART_COUNT": str(generation),
            # forensic-bundle count so far
            "PATHWAY_FLIGHT_DUMPS": str(self.flight_dumps_total),
            # circuit-breaker window position at launch: a restart storm
            # is visible on the children's /metrics BEFORE it trips
            "PATHWAY_SUPERVISE_WINDOW_FAILURES": str(self.window_failures),
        }
        if reason is not None:
            env["PATHWAY_LAST_RESTART_REASON"] = reason
        return env

    def run(self) -> int:
        restart_times: deque[float] = deque()
        generation = 0
        reason: str | None = None
        while True:
            now = time.monotonic()
            while restart_times and now - restart_times[0] > self.window_s:
                restart_times.popleft()
            self.window_failures = len(restart_times)
            procs = list(self.launch(generation, reason))
            reason = self._watch(procs)
            if self._planned is not None:
                # a PLANNED stop (autoscale rescale): cooperative teardown
                # drains every worker to its delivery boundary, then the
                # planned_stop hook runs (state resharding) and the next
                # generation launches immediately — no backoff, and no
                # restart-budget burn (a scale event is not a failure)
                token, self._planned = self._planned, None
                self._teardown(procs)
                try:
                    if self.planned_stop is not None:
                        self.planned_stop(token)
                except Exception as e:
                    from ..chaos.injector import ChaosInjected

                    if isinstance(e, ChaosInjected):
                        # same carve-out as the poll-hook guard: an
                        # injected crash at a drain/reshard phase must
                        # CRASH the controller, not become a budgeted
                        # restart that leaves the run exiting 0
                        raise
                    # a failed planned stop (resharder refused, store
                    # gone) IS a failure: fall through to the budgeted
                    # restart path so a broken rescale loop trips the
                    # breaker instead of spinning forever
                    reason = f"planned stop failed ({token}): {e}"
                else:
                    self._log(f"planned restart: {token}")
                    generation += 1
                    reason = token
                    continue
            if reason is None:
                return 0  # every process exited 0 — the run completed
            self._teardown(procs)
            # harvest after teardown (every ring is final) and before the
            # relaunch truncates them for the next generation
            bundles = self._harvest_flight(generation, reason)
            if bundles:
                reason = f"{reason} [flight recorder: {', '.join(bundles)}]"
            self._log(f"generation {generation} failed: {reason}")
            now = time.monotonic()
            restart_times.append(now)
            while restart_times and now - restart_times[0] > self.window_s:
                restart_times.popleft()
            if len(restart_times) > self.max_restarts:
                self._log(
                    f"circuit breaker open: {len(restart_times)} restarts "
                    f"inside {self.window_s:.0f}s (max {self.max_restarts}) "
                    "— giving up"
                )
                return EXIT_CIRCUIT_OPEN
            self.restarts_total += 1
            self.last_restart_reason = reason
            delay = min(
                self.backoff_max_s,
                self.backoff_s * (2 ** (self.restarts_total - 1)),
            ) * (0.5 + self._rng())  # jitter in [0.5, 1.5): no thundering herd
            self._log(
                f"restarting from last common snapshot in {delay:.2f}s "
                f"(restart #{self.restarts_total})"
            )
            time.sleep(delay)
            generation += 1

    def _label(self, i: int) -> str:
        return self.labels[i] if i < len(self.labels) else f"process {i}"

    def _watch(self, procs: Sequence[subprocess.Popen]) -> str | None:
        """Block until the generation resolves: None = all exited cleanly,
        else the failure reason (``_failed_indices`` names the culprits)."""
        self._failed_indices = []
        next_health = time.monotonic() + self.health_interval_s
        while True:
            codes = [p.poll() for p in procs]
            failed = [
                i for i, c in enumerate(codes) if c is not None and c != 0
            ]
            if failed:
                # settle pass: fast failure propagation can take down peers
                # within milliseconds of the first death — catch them now so
                # the ACTUAL crash victim's flight ring gets harvested, not
                # just the lowest-index casualty
                time.sleep(self.poll_interval_s)
                codes = [p.poll() for p in procs]
                self._failed_indices = [
                    i for i, c in enumerate(codes)
                    if c is not None and c != 0
                ]
                # headline the likeliest root cause: a signal death
                # (negative code, e.g. SIGKILL) over a peer that exited
                # nonzero because the mesh broke under it
                i = min(
                    self._failed_indices,
                    key=lambda j: (codes[j] >= 0, j),
                )
                reason = (
                    f"{self._label(i)} (pid {procs[i].pid}) "
                    f"exited with {codes[i]}"
                )
                others = [
                    f"{self._label(j)} exited with {codes[j]}"
                    for j in self._failed_indices
                    if j != i
                ]
                if others:
                    reason += f" (also: {'; '.join(others)})"
                return reason
            if all(c == 0 for c in codes):
                return None
            if self.health_ports and time.monotonic() >= next_health:
                wedged = self._check_health()
                if wedged is not None:
                    return wedged
                next_health = time.monotonic() + self.health_interval_s
            if self.poll_hook is not None:
                try:
                    token = self.poll_hook()
                except Exception as e:
                    from ..chaos.injector import ChaosInjected

                    if isinstance(e, ChaosInjected):
                        # an injected controller crash must CRASH the
                        # controller — absorbing it would make the
                        # autoscale chaos site's "crash" action a no-op
                        # that re-fires on every poll
                        raise
                    # an ordinary hook failure (signal fetch + decision)
                    # must never take the supervision loop down with it
                    self._log(f"poll hook failed: {e}")
                    token = None
                if token:
                    self._planned = token
                    return None
            time.sleep(self.poll_interval_s)

    def _check_health(self) -> str | None:
        """Probe each child's /healthz. Only a *served, failing* probe is
        fatal (a wedged executor thread); an unreachable port is not — the
        server may be disabled, still booting, or already shut down."""
        import urllib.error
        import urllib.request

        for i, port in enumerate(self.health_ports):
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=2.0
                ) as r:
                    if r.status != 200:  # pragma: no cover — urllib raises
                        return f"{self._label(i)} wedged (healthz {r.status})"
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    self._failed_indices = [i]
                    return (
                        f"{self._label(i)} wedged (healthz 503: "
                        f"{e.read(200).decode(errors='replace')})"
                    )
            except Exception:
                pass  # unreachable — not evidence of a wedge
        return None

    def _teardown(self, procs: Sequence[subprocess.Popen]) -> None:
        """Cooperative stop of the survivors: SIGTERM (children flush their
        persistence input tail on the way out), grace, then SIGKILL."""
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + self.grace_s
        for p in procs:
            remaining = deadline - time.monotonic()
            if remaining > 0 and p.poll() is None:
                try:
                    p.wait(timeout=remaining)
                except subprocess.TimeoutExpired:
                    pass
        for p in procs:
            if p.poll() is None:
                self._log(f"pid {p.pid} ignored SIGTERM for "
                          f"{self.grace_s:.0f}s — SIGKILL")
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()

    # -- crash forensics (flight-recorder harvest) -----------------------

    def _harvest_flight(self, generation: int, reason: str) -> list[str]:
        """Read the failed workers' flight-recorder rings into
        ``crash-<generation>-<process>.json`` bundles; returns the bundle
        paths. Never raises — forensics must not block the restart loop."""
        if not self.flight_dir:
            return []
        from ..observability import flightrecorder

        if self._failed_indices:
            targets = [
                self.process_ids[i] if i < len(self.process_ids) else i
                for i in self._failed_indices
            ]
        else:
            # failure without a named culprit (e.g. an external teardown):
            # every ring present is evidence
            targets = self.process_ids or self._discover_rings()
        bundles: list[str] = []
        for proc in targets:
            ring = flightrecorder.ring_path(self.flight_dir, proc)
            try:
                doc = flightrecorder.harvest(ring)
            except (OSError, ValueError):
                continue  # no ring (flight recorder off in the child)
            if self.run_id and doc["run_id"] != self.run_id[:16]:
                # ring header stores 16 run-id bytes; a mismatch means the
                # ring predates this run (the child died before arming its
                # recorder) — not this run's evidence
                continue
            records = doc["records"]
            # a flat tail cap would let high-frequency progress records
            # (ticks at up to 100/s, wave phases, periodic profile
            # deposits) rotate the rare forensic records — fired alerts,
            # chaos injections, comm.broken attributions — out of any
            # bundle harvested more than a few seconds after the event.
            # Keep every rare record plus the most recent tail.
            tail = records[-400:]
            rare = [
                r
                for r in records[: len(records) - len(tail)]
                if r.get("kind") not in _FREQUENT_RECORD_KINDS
            ]
            bundle = {
                "generation": generation,
                "process": proc,
                "exit_reason": reason,
                "harvested_at": time.time(),
                "run_id": doc["run_id"],
                "ring_wrapped": doc["wrapped"],
                "chaos_armed": bool(os.environ.get("PATHWAY_FAULT_PLAN")),
                "chaos_fired": [
                    r for r in records if r.get("kind") == "chaos.fired"
                ],
                "last_ticks": [
                    r for r in records if r.get("kind") == "tick"
                ][-50:],
                # the in-flight commit wave at death: the last wave-phase
                # transition stamp ("wave.phase") or completed wave record
                # ("async.commit", which names the holding worker) —
                # answers "which wave, which phase, who was it waiting on"
                "last_wave": next(
                    (
                        r for r in reversed(records)
                        if str(r.get("kind", "")).startswith("wave")
                        or r.get("kind") == "async.commit"
                    ),
                    None,
                ),
                "records": rare + tail,
            }
            path = os.path.join(
                self.flight_dir, f"crash-{generation}-{proc}.json"
            )
            try:
                with open(path, "w") as f:
                    json.dump(bundle, f)
            except OSError as e:
                self._log(f"could not write crash bundle {path}: {e}")
                continue
            self.flight_dumps_total += 1
            bundles.append(path)
        # consume every ring (harvested or not): a child of the NEXT
        # generation that dies before reaching Executor init never
        # re-creates its ring, and a later harvest would otherwise read
        # THIS generation's records and misattribute them (the bundle
        # preserves the evidence that matters)
        for proc in self._discover_rings():
            try:
                os.remove(flightrecorder.ring_path(self.flight_dir, proc))
            except OSError:
                pass
        return bundles

    def _discover_rings(self) -> list[int]:
        try:
            names = os.listdir(self.flight_dir)
        except OSError:
            return []
        out = []
        for n in names:
            if n.startswith("flight-p") and n.endswith(".ring"):
                try:
                    out.append(int(n[len("flight-p"):-len(".ring")]))
                except ValueError:
                    pass
        return sorted(out)
