"""Mesh construction helpers (the worker-pool analog of
``src/engine/dataflow/config.rs`` — PATHWAY_THREADS/PROCESSES become mesh
axes)."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axes: dict[str, int] | None = None) -> Mesh:
    """Mesh over all available devices with the given axis sizes."""
    devices = jax.devices()
    if axes is None:
        axes = {"data": len(devices)}
    sizes = list(axes.values())
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(f"axes {axes} do not cover {len(devices)} devices")
    dev_array = np.array(devices).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def data_model_mesh(n_devices: int | None = None) -> Mesh:
    """2D (data, model) mesh: model axis 2 when the device count allows,
    else pure data parallel. The default layout for embedder TP + index DP."""
    devices = jax.devices()
    n = n_devices if n_devices is not None else len(devices)
    devices = devices[:n]
    model = 2 if n % 2 == 0 and n >= 2 else 1
    data = n // model
    dev_array = np.array(devices).reshape(data, model)
    return Mesh(dev_array, ("data", "model"))
