"""Record exchange by key over a device mesh.

The reference exchanges records between timely workers through channel
allocators (``external/timely-dataflow/communication/src/allocator/``);
keys route by their low bits (``value.rs:38``). Here the same routing is a
**bucketed all-to-all**: rows are counted per destination shard, padded to a
static per-shard capacity (XLA needs static shapes), and exchanged with
``jax.lax.all_to_all`` inside ``shard_map`` so the transfer rides the ICI.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from ..internals.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..engine import keys as K


def shard_rows(keys: np.ndarray, n_shards: int) -> np.ndarray:
    """Destination shard per row (low key bits, reference SHARD_MASK)."""
    return K.shard_of(keys, n_shards)


def bucketed_all_to_all(
    mesh: Mesh,
    axis: str,
    values: jax.Array,  # global [n_shards*cap_in, d], sharded over `axis`
    dest: jax.Array,  # global [n_shards*cap_in] destination shard (-1 = empty)
    cap_out: int,  # per-device output capacity (multiple of n_shards)
):
    """Exchange rows to their destination shards.

    Every device buckets its local rows by destination into a
    [n_shards, cap_bucket] layout, all-to-all swaps buckets, and flattens
    arrivals. Returns (global [n_shards*cap_out, d] values,
    [n_shards*cap_out] validity), sharded over `axis`.
    """
    n_shards = mesh.shape[axis]
    d = values.shape[-1]
    cap_bucket = cap_out // n_shards

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), P(axis)),
        out_specs=(P(axis, None), P(axis)),
        check_vma=False,
    )
    def exchange(vals, dest):
        vals = vals.reshape(-1, d)  # this device's block
        dest = dest.reshape(-1)
        # position within destination bucket = running count per destination
        one_hot = jax.nn.one_hot(dest, n_shards, dtype=jnp.int32)  # -1 → all-zero row
        within = jnp.cumsum(one_hot, axis=0) - 1
        pos = jnp.take_along_axis(
            within, jnp.clip(dest, 0)[:, None], axis=1
        ).squeeze(-1)
        ok = (dest >= 0) & (pos < cap_bucket) & (pos >= 0)
        safe_dest = jnp.clip(dest, 0)
        safe_pos = jnp.clip(pos, 0, cap_bucket - 1)
        buckets = jnp.zeros((n_shards, cap_bucket, d), vals.dtype)
        valid = jnp.zeros((n_shards, cap_bucket), jnp.bool_)
        # scatter-add so masked-out rows (adding 0) can never clobber a slot
        # (zero must keep vals' dtype: 0.0 would promote uint32 payloads)
        buckets = buckets.at[safe_dest, safe_pos].add(
            jnp.where(ok[:, None], vals, jnp.zeros((), vals.dtype))
        )
        valid = valid.at[safe_dest, safe_pos].max(ok)
        # swap bucket b to device b over the ICI
        recv = jax.lax.all_to_all(buckets, axis, split_axis=0, concat_axis=0)
        recv_valid = jax.lax.all_to_all(valid, axis, split_axis=0, concat_axis=0)
        return recv.reshape(n_shards * cap_bucket, d), recv_valid.reshape(
            n_shards * cap_bucket
        )

    return exchange(values, dest)
