"""Zero-copy columnar wire protocol for the cluster data plane.

The reference moves exchange records between processes as raw byte
buffers through timely's ``zero_copy`` allocator
(``external/timely-dataflow/communication/src/allocator/zero_copy/``:
``bytes_exchange.rs`` hands pre-serialized regions straight to the
socket). This module is that wire format for ClusterComm's Delta
frames: a compact binary frame whose dense numpy columns are appended
**verbatim** — ``memoryview`` on encode, ``np.frombuffer`` on decode —
so a cross-process exchange never pickles a numeric column and never
copies it on receive. Object/string columns fall back to a
length-prefixed pickle section inside the same frame, so semantics are
unchanged for arbitrary python values.

Frame layout (all integers big-endian, following the 8-byte length
prefix the socket loop already speaks)::

    u8  kind      KIND_COLUMNAR (pickled control frames use KIND_PICKLE)
    u8  version
    q   tick      logical time of the exchange
    I   src       sending worker id
    I   n_dsts    destination sections that follow
    I   meta_len  + pickle((channel, trace_ctx))   # edge id + (run_id, flow_id)
    per destination:
        I   dst   destination worker id
        u8  ptype PT_PICKLE | PT_DELTA | PT_COLS
        payload

A ``PT_DELTA`` payload is ``I n_rows, H n_cols`` followed by a column
directory (name, encoding, dtype, nbytes per column — keys and diffs
are the two unnamed leading entries) and then the column buffers in
directory order. Raw buffers are padded so each starts 8-byte aligned
relative to the frame body, letting the decoder ``frombuffer`` the recv
buffer in place; both sides derive the padding from the same running
offset, so it is never transmitted. ``PT_COLS`` reuses the identical
column codec for the mesh host-boundary frames (``(src, {name: col})``
object-column swaps of MultiHostMeshComm); ``PT_PICKLE`` carries any
other payload shape unchanged.

A decoder that reads past the buffer, or a directory whose lengths
disagree with the frame, raises :class:`CorruptFrame` — the reader
thread turns that into a named ``_broken`` mark instead of feeding
garbage arrays into operator state.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any

import numpy as np

__all__ = [
    "KIND_PICKLE",
    "KIND_COLUMNAR",
    "CorruptFrame",
    "encode_frame",
    "decode_frame",
    "encode_control",
    "decodable_payload",
    "connector_frame",
    "open_connector_frame",
]

KIND_PICKLE = 0  #: body[0] of a pickled control frame (allgather/ping/bye)
KIND_COLUMNAR = 1  #: body[0] of a binary columnar exchange frame
_VERSION = 1

PT_PICKLE = 0  #: payload: arbitrary pickled object
PT_DELTA = 1  #: payload: an engine Delta (keys/diffs + named columns)
PT_COLS = 2  #: payload: (src:int, {name: ndarray}) — mesh host columns

_FRAME = struct.Struct(">BBqIII")  # kind, version, tick, src, n_dsts, meta_len
_SECTION = struct.Struct(">IB")  # dst, ptype
_COLS_HDR = struct.Struct(">IH")  # n_rows, n_cols
_COL_RAW = struct.Struct(">B")  # encoding tag
_U8 = struct.Struct(">B")
_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")
_ALIGN = 8

#: numpy dtype kinds shipped as raw buffers (fixed-width, no object refs);
#: everything else (object, str, void) rides the pickle section
_RAW_KINDS = frozenset("iufbMm")

#: column-count sanity bound, shared by encoder (fall back to pickle)
#: and decoder (reject as corrupt) so a legitimately wide payload can
#: never be refused on arrival
_MAX_COLS = 4096

_ENC_RAW = 0
_ENC_PICKLE = 1


class CorruptFrame(ValueError):
    """A wire frame failed structural validation — truncated, torn or
    corrupted in flight. The reader thread flips ``_broken`` with this
    as the named origin rather than deserializing garbage."""


class _Writer:
    """Accumulates bytes-like chunks while tracking the running frame
    offset (the alignment authority both ends share)."""

    __slots__ = ("chunks", "offset")

    def __init__(self) -> None:
        self.chunks: list[Any] = []
        self.offset = 0

    def put(self, b: Any) -> None:
        n = len(b)
        if n:
            self.chunks.append(b)
            self.offset += n

    def align(self) -> None:
        pad = -self.offset % _ALIGN
        if pad:
            self.put(b"\x00" * pad)


def _put_columns(w: _Writer, entries: list[tuple[str, np.ndarray]], n_rows: int) -> None:
    """Directory + buffers for one column set. ``entries`` order is the
    decode order; every column must hold exactly ``n_rows`` values."""
    w.put(_COLS_HDR.pack(n_rows, len(entries)))
    dirbuf = bytearray()
    bufs: list[tuple[int, Any]] = []
    for name, arr in entries:
        arr = np.asarray(arr)
        nm = name.encode("utf-8")
        dirbuf += struct.pack(">H", len(nm)) + nm
        if arr.ndim == 1 and arr.dtype.kind in _RAW_KINDS and not arr.dtype.hasobject:
            if not arr.flags.c_contiguous:
                arr = np.ascontiguousarray(arr)
            ds = arr.dtype.str.encode("ascii")
            dirbuf += _COL_RAW.pack(_ENC_RAW)
            dirbuf += struct.pack(">B", len(ds)) + ds
            dirbuf += struct.pack(">Q", arr.nbytes)
            # datetime64/timedelta64 refuse the buffer protocol — export
            # their bytes through an int64 view; the directory keeps the
            # real dtype, which frombuffer accepts on decode
            raw = arr.view(np.int64) if arr.dtype.kind in "Mm" else arr
            bufs.append((_ENC_RAW, memoryview(raw).cast("B")))
        else:
            blob = pickle.dumps(arr, protocol=pickle.HIGHEST_PROTOCOL)
            dirbuf += _COL_RAW.pack(_ENC_PICKLE)
            dirbuf += struct.pack(">B", 0)
            dirbuf += struct.pack(">Q", len(blob))
            bufs.append((_ENC_PICKLE, blob))
    w.put(bytes(dirbuf))
    for enc, data in bufs:
        if enc == _ENC_RAW:
            w.align()
        w.put(data)


def _payload_entries(payload: Any) -> tuple[int, list, int] | None:
    """Classify a payload for the columnar codec: returns
    (ptype, entries, n_rows) or None for the pickle fallback."""
    from ..engine.delta import Delta

    if isinstance(payload, Delta):
        entries = [("\x00k", payload.keys), ("\x00d", payload.diffs)]
        entries += list(payload.data.items())
        # mirror the decoder's column-count sanity bound: a wider-than-
        # plausible set ships via the pickle fallback instead of being
        # refused as corrupt on arrival
        if len(entries) > _MAX_COLS:
            return None
        return PT_DELTA, entries, len(payload)
    if (
        isinstance(payload, tuple)
        and len(payload) == 2
        and isinstance(payload[0], (int, np.integer))
        and isinstance(payload[1], dict)
        and payload[1]
        and len(payload[1]) <= _MAX_COLS
        and all(isinstance(v, np.ndarray) for v in payload[1].values())
    ):
        cols = payload[1]
        lens = {len(v) for v in cols.values()}
        if len(lens) == 1:
            return PT_COLS, list(cols.items()), lens.pop()
    return None


def encode_frame(
    channel: Any,
    tick: int,
    src: int,
    per_dst: dict[int, Any],
    ctx: tuple | None = None,
) -> tuple[list[Any], int]:
    """Encode one exchange frame → (chunks, total_bytes). Chunks are
    bytes-like (dense columns are live memoryviews of the sender's
    arrays — callers must treat them as immutable until sent, which the
    engine's column-immutability convention already guarantees)."""
    meta = pickle.dumps((channel, ctx), protocol=pickle.HIGHEST_PROTOCOL)
    w = _Writer()
    w.put(_FRAME.pack(KIND_COLUMNAR, _VERSION, tick, src, len(per_dst), len(meta)))
    w.put(meta)
    for dst, payload in per_dst.items():
        cls = _payload_entries(payload)
        if cls is None:
            blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
            w.put(_SECTION.pack(dst, PT_PICKLE))
            # u64 length: the fallback must carry anything the old all-
            # pickled plane could (a >4 GiB object graph included)
            w.put(_U64.pack(len(blob)))
            w.put(blob)
            continue
        ptype, entries, n_rows = cls
        w.put(_SECTION.pack(dst, ptype))
        if ptype == PT_COLS:
            w.put(_U32.pack(int(payload[0])))
        _put_columns(w, entries, n_rows)
    return w.chunks, w.offset


def encode_control(frame: tuple) -> bytes:
    """Pickle a control frame (allgather/barrier payloads, ping/pong,
    bye) behind the KIND_PICKLE tag byte."""
    return bytes([KIND_PICKLE]) + pickle.dumps(
        frame, protocol=pickle.HIGHEST_PROTOCOL
    )


class _Reader:
    __slots__ = ("buf", "mv", "offset")

    def __init__(self, buf: Any) -> None:
        self.buf = buf
        self.mv = memoryview(buf)
        self.offset = 0

    def take(self, n: int) -> memoryview:
        end = self.offset + n
        if n < 0 or end > len(self.mv):
            raise CorruptFrame(
                f"frame truncated: need {n} bytes at offset {self.offset}, "
                f"have {len(self.mv)}"
            )
        out = self.mv[self.offset : end]
        self.offset = end
        return out

    def unpack(self, st: struct.Struct) -> tuple:
        return st.unpack(self.take(st.size))

    def align(self) -> None:
        pad = -self.offset % _ALIGN
        if pad:
            self.take(pad)


def _read_columns(r: _Reader) -> tuple[int, list[tuple[str, np.ndarray]]]:
    n_rows, n_cols = r.unpack(_COLS_HDR)
    if n_rows > (1 << 40) or n_cols > _MAX_COLS:
        raise CorruptFrame(f"implausible column set ({n_rows} rows x {n_cols} cols)")
    directory = []
    for _ in range(n_cols):
        (nlen,) = r.unpack(_U16)
        name = bytes(r.take(nlen)).decode("utf-8")
        (enc,) = r.unpack(_COL_RAW)
        (dlen,) = r.unpack(_U8)
        dstr = bytes(r.take(dlen)).decode("ascii")
        (nbytes,) = r.unpack(_U64)
        directory.append((name, enc, dstr, nbytes))
    out: list[tuple[str, np.ndarray]] = []
    for name, enc, dstr, nbytes in directory:
        if enc == _ENC_RAW:
            try:
                dtype = np.dtype(dstr)
            except TypeError as e:
                raise CorruptFrame(f"column {name!r}: bad dtype {dstr!r}") from e
            if dtype.itemsize == 0 or nbytes % dtype.itemsize:
                raise CorruptFrame(
                    f"column {name!r}: {nbytes} bytes is not a multiple of "
                    f"dtype {dstr!r} ({dtype.itemsize}B items)"
                )
            if nbytes // dtype.itemsize != n_rows:
                raise CorruptFrame(
                    f"column {name!r}: {nbytes // dtype.itemsize} values for "
                    f"{n_rows} rows"
                )
            r.align()
            # zero-copy: the array aliases the recv buffer (a bytearray,
            # so the result is an ordinary writable array)
            arr = np.frombuffer(r.take(nbytes), dtype=dtype)
        elif enc == _ENC_PICKLE:
            try:
                arr = pickle.loads(r.take(nbytes))
            except Exception as e:
                raise CorruptFrame(f"column {name!r}: bad pickle section ({e})") from e
            if not isinstance(arr, np.ndarray) or len(arr) != n_rows:
                raise CorruptFrame(
                    f"column {name!r}: pickle section is not a {n_rows}-row column"
                )
        else:
            raise CorruptFrame(f"column {name!r}: unknown encoding {enc}")
        out.append((name, arr))
    return n_rows, out


def decode_frame(buf: Any) -> tuple:
    """Decode one columnar frame body → ``("x", channel, tick, src,
    per_dst, ctx)`` — the same tuple shape the pickled protocol used, so
    inbox delivery is codec-agnostic. Dense columns alias ``buf``."""
    from ..engine.delta import Delta

    r = _Reader(buf)
    kind, version, tick, src, n_dsts, meta_len = r.unpack(_FRAME)
    if kind != KIND_COLUMNAR or version != _VERSION:
        raise CorruptFrame(f"bad frame tag (kind={kind}, version={version})")
    if n_dsts > 1 << 20:
        raise CorruptFrame(f"implausible destination count {n_dsts}")
    try:
        channel, ctx = pickle.loads(r.take(meta_len))
    except CorruptFrame:
        raise
    except Exception as e:
        raise CorruptFrame(f"bad frame metadata ({e})") from e
    per_dst: dict[int, Any] = {}
    for _ in range(n_dsts):
        dst, ptype = r.unpack(_SECTION)
        if ptype == PT_PICKLE:
            (blen,) = r.unpack(_U64)
            try:
                per_dst[dst] = pickle.loads(r.take(blen))
            except CorruptFrame:
                raise
            except Exception as e:
                raise CorruptFrame(f"dst {dst}: bad pickled payload ({e})") from e
            continue
        if ptype == PT_COLS:
            (src_tag,) = r.unpack(_U32)
            _n_rows, cols = _read_columns(r)
            per_dst[dst] = (src_tag, dict(cols))
            continue
        if ptype != PT_DELTA:
            raise CorruptFrame(f"dst {dst}: unknown payload type {ptype}")
        _n_rows, cols = _read_columns(r)
        if len(cols) < 2 or cols[0][0] != "\x00k" or cols[1][0] != "\x00d":
            raise CorruptFrame(f"dst {dst}: delta payload missing key/diff columns")
        keys = cols[0][1]
        diffs = cols[1][1]
        if keys.dtype != np.uint64 or diffs.dtype != np.int64:
            raise CorruptFrame(
                f"dst {dst}: key/diff dtypes {keys.dtype}/{diffs.dtype}"
            )
        per_dst[dst] = Delta(
            keys=keys, data=dict(cols[2:]), diffs=diffs
        )
    if r.offset != len(r.mv):
        raise CorruptFrame(
            f"{len(r.mv) - r.offset} trailing bytes after the last section"
        )
    return ("x", channel, tick, src, per_dst, ctx)


def decodable_payload(payload: Any) -> bool:
    """True when the codec will ship this payload columnar (tests +
    LocalComm's no-serialization assertion use this to know which
    payloads the binary path covers)."""
    return _payload_entries(payload) is not None


#: channel id of in-process connector-batch frames — the ingest→engine
#: seam (a real exchange channel id is a non-negative edge id)
INGEST_CHANNEL = -1


def connector_frame(delta: Any, tick: int = -1, src: int = 0) -> tuple:
    """Wrap one ingest Delta as a wire frame: a connector batch IS an
    exchange frame, so handing it to the engine is the same operation as
    handing it to a remote worker. In process the tuple carries the Delta
    **by reference** (LocalComm.exchange's contract — never serialize on
    a local hop); across processes ``encode_frame`` ships the identical
    shape binary. Asserting decodability here means a columnar reader
    can never build a batch the cluster data plane would refuse."""
    assert decodable_payload(delta), (
        "connector batch must be frame-codec decodable"
    )
    return ("x", INGEST_CHANNEL, tick, src, {src: delta}, None)


def open_connector_frame(frame: Any) -> Any:
    """Unwrap a connector-batch frame back to its Delta. An in-process
    tuple returns the referenced Delta itself (pass-by-reference: callers
    assert identity, like LocalComm.exchange); an encoded byte frame is
    decoded through the columnar codec."""
    if isinstance(frame, (bytes, bytearray, memoryview)):
        _kind, _channel, _tick, src, per_dst, _ctx = decode_frame(frame)
        return per_dst[src]
    _kind, _channel, _tick, src, per_dst, _ctx = frame
    return per_dst[src]
