"""MeshComm — the ICI communication backend for the sharded dataflow.

Wraps a host :class:`~pathway_tpu.parallel.comm.Comm` (LocalComm threads)
and routes the dense numeric part of every Exchange frame through a
``bucketed_all_to_all`` XLA collective over a 1-D ``jax.sharding.Mesh``
(``engine/mesh_exchange.py`` → ``parallel/exchange.py``), so on TPU the
record bytes move over ICI instead of host memory. Object/string columns
ride the shared deposit and are re-zipped by source order.

Per tick + exchange channel, the fused protocol (r4 — replaces the
three-allgather/one-exchange protocol VERDICT r3 measured at 20× the host
path) is:

1. every worker deposits (signature, per-destination counts, its local
   Delta by reference, destination array) into a shared slot and hits ONE
   barrier;
2. the driver thread (worker 0) agrees dtype kinds + power-of-two caps,
   packs ALL workers' dense rows into one pinned staging buffer, ships it
   with a single sharded ``device_put``, runs the jitted collective, and
   publishes the result; second barrier;
3. every worker reads back only its own device shard and re-zips any
   host-path (object) columns straight from the deposited Deltas.

Total host synchronization: 2 barriers per channel-tick (was 8), one
device upload (was one per worker plus a result allgather).

Enable with ``PATHWAY_MESH_EXCHANGE=1`` (single-process workers only; the
multi-host variant needs ``jax.distributed`` — ``parallel/distributed.py``
— and rides DCN, not wired to the engine yet).

Reference being replaced: timely's ``zero_copy`` allocator
(``external/timely-dataflow/communication/src/allocator/zero_copy/``).
"""

from __future__ import annotations

import threading
from typing import Any, Sequence

import numpy as np

from ..engine.delta import Delta, concat_deltas
from ..engine.mesh_exchange import (
    HOST,
    MeshExchangeRunner,
    local_signature,
)
from .comm import Comm

__all__ = ["MeshComm"]


class MeshComm(Comm):
    def __init__(self, inner: Comm, mesh: Any = None):
        import jax
        from jax.sharding import Mesh

        self.inner = inner
        self.n_workers = inner.n_workers
        if mesh is None:
            devices = jax.devices()
            if len(devices) < self.n_workers:
                raise RuntimeError(
                    f"mesh exchange needs ≥{self.n_workers} devices, have "
                    f"{len(devices)} — run with "
                    "XLA_FLAGS=--xla_force_host_platform_device_count=N on "
                    "CPU, or disable PATHWAY_MESH_EXCHANGE"
                )
            mesh = Mesh(np.array(devices[: self.n_workers]), ("workers",))
        self.mesh = mesh
        self.runner = MeshExchangeRunner(mesh, "workers")
        # (channel, tick) -> {"payloads": [...], "result": ...}; entries for
        # a channel are deleted by the driver at the NEXT tick's compute
        # phase, when the post-deposit barrier proves no reader remains
        self._slots: dict[tuple, dict] = {}
        self._slot_lock = threading.Lock()

    # host-comm delegation (control plane + non-delta payloads)

    def exchange(self, channel, tick, worker_id, buckets):
        return self.inner.exchange(channel, tick, worker_id, buckets)

    def allgather(self, tag, worker_id, obj):
        return self.inner.allgather(tag, worker_id, obj)

    def barrier(self, worker_id: int):
        self.inner.barrier(worker_id)

    def abort(self):
        self.inner.abort()

    def close(self):
        # the final tick's slots have no successor tick to reclaim them
        with self._slot_lock:
            self._slots.clear()
        self.inner.close()

    # the ICI data plane

    def exchange_deltas(
        self,
        channel: int,
        tick: int,
        worker_id: int,
        buckets: Sequence[Delta | None],
        column_names: list[str],
    ) -> list[Delta]:
        """All-to-all of columnar Delta buckets; dense columns over the
        device mesh, object columns re-zipped from the shared deposit."""
        n = self.n_workers
        parts = [
            (dst, d) for dst, d in enumerate(buckets) if d is not None and len(d)
        ]
        local = concat_deltas([d for _, d in parts], column_names) if parts else None
        dest = (
            np.concatenate(
                [np.full(len(d), dst, dtype=np.int32) for dst, d in parts]
            )
            if parts
            else np.empty(0, dtype=np.int32)
        )
        counts = np.zeros(n, dtype=np.int64)
        for dst, d in parts:
            counts[dst] += len(d)
        sig = local_signature(local, column_names)

        key = (channel, tick)
        with self._slot_lock:
            slot = self._slots.setdefault(key, {"payloads": [None] * n})
            slot["payloads"][worker_id] = (sig, counts, local, dest)
        self.inner.barrier(worker_id)  # all deposits visible

        if worker_id == 0:
            with self._slot_lock:
                # all workers deposited (channel, tick) → every worker has
                # finished all earlier ticks on EVERY channel (the sweep is
                # sequential per worker); reclaim all older slots
                stale = [k for k in self._slots if k[1] < tick]
                for k in stale:
                    del self._slots[k]
                slot = self._slots[key]
            try:
                slot["result"] = self.runner.run_tick(
                    slot["payloads"], column_names
                )
            except BaseException as e:  # noqa: BLE001 — re-raised on peers
                slot["result"] = _DriverError(e)
                self.inner.barrier(worker_id)
                raise
            self.inner.barrier(worker_id)
        else:
            self.inner.barrier(worker_id)
            slot = self._slots[key]

        result = slot["result"]
        if isinstance(result, _DriverError):
            raise RuntimeError(
                "mesh exchange failed on the driver worker"
            ) from result.error
        if result is None:
            return []
        kinds, cap_bucket, gvals, gvalid = result

        per_dev = self.runner.n * cap_bucket
        my_vals = self.runner.my_shard(gvals, worker_id, per_dev)
        my_valid = self.runner.my_shard(gvalid, worker_id, per_dev)

        host_cols: dict[int, dict[str, np.ndarray]] = {}
        host_names = [c for c, k in zip(column_names, kinds) if k == HOST]
        if host_names:
            for src, payload in enumerate(slot["payloads"]):
                _, _, src_local, src_dest = payload
                if src_local is None or not len(src_local):
                    continue
                mine = src_dest == worker_id
                if mine.any():
                    ix = np.flatnonzero(mine)
                    host_cols[src] = {
                        c: src_local.data[c][ix] for c in host_names
                    }

        return self.runner.unpack_arrivals(
            vals=my_vals,
            valid=my_valid.astype(bool),
            kinds=kinds,
            column_names=column_names,
            host_cols=host_cols,
        )


class _DriverError:
    """Marks a failed driver tick so peers re-raise instead of hanging."""

    def __init__(self, error: BaseException):
        self.error = error
