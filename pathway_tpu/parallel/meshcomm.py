"""MeshComm — the ICI communication backend for the sharded dataflow.

Wraps a host :class:`~pathway_tpu.parallel.comm.Comm` (LocalComm threads)
and routes the dense numeric part of every Exchange frame through a
``bucketed_all_to_all`` XLA collective over a 1-D ``jax.sharding.Mesh``
(``engine/mesh_exchange.py`` → ``parallel/exchange.py``), so on TPU the
record bytes move over ICI instead of host memory. Object/string columns
ride the wrapped host comm and are re-zipped by source order.

Per tick + exchange channel, the protocol is:

1. every worker packs its local rows and allgathers a tiny control tuple
   (dtype signature, per-destination row counts) through the host comm;
2. workers agree on the dense column set and power-of-two bucket capacity
   (static shapes — XLA kernels are cached per shape class);
3. each worker ``device_put``s its padded block onto *its own* device; the
   driver thread (worker 0) assembles the global sharded array and runs the
   jitted collective; every worker then reads back only its own shard;
4. host-path columns swap via the wrapped comm; arrivals re-zip by
   (source, emission order), which both paths preserve.

Enable with ``PATHWAY_MESH_EXCHANGE=1`` (single-process workers only; the
multi-host variant needs ``jax.distributed`` — ``parallel/distributed.py``
— and rides DCN, not wired to the engine yet).

Reference being replaced: timely's ``zero_copy`` allocator
(``external/timely-dataflow/communication/src/allocator/zero_copy/``).
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from ..engine.delta import Delta, concat_deltas
from ..engine.mesh_exchange import (
    HOST,
    MeshExchangeRunner,
    agree_kinds,
    local_signature,
)
from .comm import Comm

__all__ = ["MeshComm"]


class MeshComm(Comm):
    def __init__(self, inner: Comm, mesh: Any = None):
        import jax
        from jax.sharding import Mesh

        self.inner = inner
        self.n_workers = inner.n_workers
        if mesh is None:
            devices = jax.devices()
            if len(devices) < self.n_workers:
                raise RuntimeError(
                    f"mesh exchange needs ≥{self.n_workers} devices, have "
                    f"{len(devices)} — run with "
                    "XLA_FLAGS=--xla_force_host_platform_device_count=N on "
                    "CPU, or disable PATHWAY_MESH_EXCHANGE"
                )
            mesh = Mesh(np.array(devices[: self.n_workers]), ("workers",))
        self.mesh = mesh
        self.runner = MeshExchangeRunner(mesh, "workers")

    # host-comm delegation (control plane + non-delta payloads)

    def exchange(self, channel, tick, worker_id, buckets):
        return self.inner.exchange(channel, tick, worker_id, buckets)

    def allgather(self, tag, worker_id, obj):
        return self.inner.allgather(tag, worker_id, obj)

    def barrier(self, worker_id: int):
        self.inner.barrier(worker_id)

    def abort(self):
        self.inner.abort()

    def close(self):
        self.inner.close()

    # the ICI data plane

    def exchange_deltas(
        self,
        channel: int,
        tick: int,
        worker_id: int,
        buckets: Sequence[Delta | None],
        column_names: list[str],
    ) -> list[Delta]:
        """All-to-all of columnar Delta buckets; dense columns over the
        device mesh, object columns over the host comm."""
        import jax

        n = self.n_workers
        parts = [
            (dst, d) for dst, d in enumerate(buckets) if d is not None and len(d)
        ]
        local = concat_deltas([d for _, d in parts], column_names)
        dest = (
            np.concatenate(
                [np.full(len(d), dst, dtype=np.int32) for dst, d in parts]
            )
            if parts
            else np.empty(0, dtype=np.int32)
        )
        counts = np.zeros(n, dtype=np.int64)
        for dst, d in parts:
            counts[dst] += len(d)

        sig = local_signature(local if len(local) else None, column_names)
        metas = self.inner.allgather(
            ("mx-meta", channel, tick), worker_id, (sig, counts.tolist())
        )
        total = sum(sum(m[1]) for m in metas)
        if total == 0:
            return []
        kinds = agree_kinds([m[0] for m in metas], len(column_names))
        from ..engine.mesh_exchange import _pow2

        cap_bucket = _pow2(max(max(m[1]) for m in metas))
        cap_in = _pow2(max(sum(m[1]) for m in metas))
        width = self.runner.width(kinds)

        vals, dst_arr = self.runner.pack_local(
            local if len(local) else None, dest, kinds, column_names, cap_in
        )
        dev = self.runner.devices[worker_id]
        shard = (
            jax.device_put(vals, dev),
            jax.device_put(dst_arr, dev),
        )
        shards = self.inner.allgather(("mx-shard", channel, tick), worker_id, shard)

        if worker_id == 0:
            out = self.runner.run_collective(shards, cap_in, cap_bucket, width)
        else:
            out = None
        outs = self.inner.allgather(("mx-out", channel, tick), worker_id, out)
        gvals, gvalid = next(o for o in outs if o is not None)

        per_dev = self.runner.n * cap_bucket
        my_vals = _my_shard(gvals, worker_id, per_dev)
        my_valid = _my_shard(gvalid, worker_id, per_dev)

        host_cols: dict[int, dict[str, np.ndarray]] = {}
        host_names = [c for c, k in zip(column_names, kinds) if k == HOST]
        if host_names:
            obj_buckets: list[Any] = [None] * n
            if parts:
                per_dst: dict[int, dict[str, list]] = {}
                for dst, d in parts:
                    cols = per_dst.setdefault(dst, {c: [] for c in host_names})
                    for c in host_names:
                        cols[c].append(d.data[c])
                for dst, cols in per_dst.items():
                    obj_buckets[dst] = (
                        worker_id,
                        {c: np.concatenate(v) for c, v in cols.items()},
                    )
            received = self.inner.exchange(
                ("mx-obj", channel), tick, worker_id, obj_buckets
            )
            for src, cols in received:
                host_cols[src] = cols

        return self.runner.unpack_arrivals(
            vals=my_vals,
            valid=my_valid.astype(bool),
            kinds=kinds,
            column_names=column_names,
            host_cols=host_cols,
        )


def _my_shard(garr: Any, worker_id: int, per_dev: int) -> np.ndarray:
    """This worker's block of a mesh-sharded global array, pulled
    device→host without materializing the other shards."""
    for s in garr.addressable_shards:
        if s.index[0].start == worker_id * per_dev:
            return np.asarray(s.data)
    # single-device fallback (tests at n=1)
    return np.asarray(garr)[worker_id * per_dev : (worker_id + 1) * per_dev]
