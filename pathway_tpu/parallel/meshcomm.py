"""MeshComm — the ICI communication backend for the sharded dataflow.

Wraps a host :class:`~pathway_tpu.parallel.comm.Comm` (LocalComm threads)
and routes the dense numeric part of every Exchange frame through a
``bucketed_all_to_all`` XLA collective over a 1-D ``jax.sharding.Mesh``
(``engine/mesh_exchange.py`` → ``parallel/exchange.py``), so on TPU the
record bytes move over ICI instead of host memory. Object/string columns
ride the shared deposit and are re-zipped by source order.

Per tick + exchange channel, the fused protocol (r4 — replaces the
three-allgather/one-exchange protocol VERDICT r3 measured at 20× the host
path) is:

1. every worker deposits (signature, per-destination counts, its local
   Delta by reference, destination array) into a shared slot and hits ONE
   barrier;
2. the driver thread (worker 0) agrees dtype kinds + power-of-two caps,
   packs ALL workers' dense rows into one pinned staging buffer, ships it
   with a single sharded ``device_put``, runs the jitted collective, and
   publishes the result; second barrier;
3. every worker reads back only its own device shard and re-zips any
   host-path (object) columns straight from the deposited Deltas.

Total host synchronization: 2 barriers per channel-tick (was 8), one
device upload (was one per worker plus a result allgather).

Enable with ``PATHWAY_MESH_EXCHANGE=1``. Single-process runs use
:class:`MeshComm` (threads over one process's devices); ``spawn -n M``
runs bootstrap ``jax.distributed`` (``parallel/distributed.py``) and use
:class:`MultiHostMeshComm`, whose collective spans every process's
devices — ICI within a pod, DCN across pods.

Host-boundary frames (the object-column swap of
:meth:`MultiHostMeshComm.exchange_deltas` and any control payloads that
ride the inner ClusterComm) reuse the columnar wire codec
(``parallel/frames.py``): the ``(src, {name: column})`` payload shape is
recognized by the encoder and ships through the same
directory-plus-buffers frame layout as Delta exchanges, so no host
boundary ever pays ``pickle.dumps`` on a dense column.

Reference being replaced: timely's ``zero_copy`` allocator
(``external/timely-dataflow/communication/src/allocator/zero_copy/``).
"""

from __future__ import annotations

import threading
from typing import Any, Sequence

import numpy as np

from ..engine.delta import Delta, concat_deltas
from ..engine.mesh_exchange import (
    HOST,
    MeshExchangeRunner,
    local_signature,
)
from .comm import Comm

__all__ = ["MeshComm", "MultiHostMeshComm"]


class MeshComm(Comm):
    def __init__(self, inner: Comm, mesh: Any = None):
        import jax
        from jax.sharding import Mesh

        self.inner = inner
        self.n_workers = inner.n_workers
        if mesh is None:
            devices = jax.devices()
            if len(devices) < self.n_workers:
                raise RuntimeError(
                    f"mesh exchange needs ≥{self.n_workers} devices, have "
                    f"{len(devices)} — run with "
                    "XLA_FLAGS=--xla_force_host_platform_device_count=N on "
                    "CPU, or disable PATHWAY_MESH_EXCHANGE"
                )
            mesh = Mesh(np.array(devices[: self.n_workers]), ("workers",))
        self.mesh = mesh
        self.runner = MeshExchangeRunner(mesh, "workers")
        # (channel, tick) -> {"payloads": [...], "result": ...}; entries for
        # a channel are deleted by the driver at the NEXT tick's compute
        # phase, when the post-deposit barrier proves no reader remains
        self._slots: dict[tuple, dict] = {}
        self._slot_lock = threading.Lock()
        # tracing: link every worker's deposit to the driver's collective
        # and the collective back to each worker's readback (flow events
        # with deterministic ids — one shared tracer, no context to ship)
        from ..internals.tracing import get_tracer, mint_flow_tag

        self._tracer = get_tracer()
        self._flow_tag = mint_flow_tag()

    def _flow_id(self, channel: int, tick: int, worker: int,
                 phase: str) -> str:
        from ..internals.tracing import make_flow_id

        return make_flow_id(
            self._tracer, self._flow_tag,
            f"mx{channel}", f"t{tick}", f"{phase}{worker}",
        )

    # host-comm delegation (control plane + non-delta payloads)

    def exchange(self, channel, tick, worker_id, buckets):
        return self.inner.exchange(channel, tick, worker_id, buckets)

    def allgather(self, tag, worker_id, obj):
        return self.inner.allgather(tag, worker_id, obj)

    def barrier(self, worker_id: int):
        self.inner.barrier(worker_id)

    # async (frontier-driven) plane: host-path delegation — the ICI
    # collective is inherently bulk-synchronous, so PATHWAY_ASYNC_EXEC=1
    # with mesh exchange routes record exchange over the host plane
    def supports_async(self) -> bool:
        return self.inner.supports_async()

    def async_attach(self, worker_id, waker):
        self.inner.async_attach(worker_id, waker)

    def async_post_exchange(self, worker_id, channel, time, buckets,
                            ingest_ns=None, seq=None, enq_ns=None):
        return self.inner.async_post_exchange(
            worker_id, channel, time, buckets, ingest_ns, seq, enq_ns
        )

    def async_broadcast(self, worker_id, payload):
        self.inner.async_broadcast(worker_id, payload)

    def async_drain(self, worker_id):
        return self.inner.async_drain(worker_id)

    def async_congested(self, worker_id):
        return self.inner.async_congested(worker_id)

    def abort(self):
        self.inner.abort()

    def close(self):
        # the final tick's slots have no successor tick to reclaim them
        with self._slot_lock:
            self._slots.clear()
        self.inner.close()

    def comm_stats(self) -> dict[str, float]:
        out = dict(self.inner.comm_stats())
        out["mesh_pending_slots"] = float(len(self._slots))
        out.update(self.runner.stats())
        return out

    # the ICI data plane

    def exchange_deltas(
        self,
        channel: int,
        tick: int,
        worker_id: int,
        buckets: Sequence[Delta | None],
        column_names: list[str],
    ) -> list[Delta]:
        """All-to-all of columnar Delta buckets; dense columns over the
        device mesh, object columns re-zipped from the shared deposit."""
        n = self.n_workers
        parts = [
            (dst, d) for dst, d in enumerate(buckets) if d is not None and len(d)
        ]
        local = concat_deltas([d for _, d in parts], column_names) if parts else None
        dest = (
            np.concatenate(
                [np.full(len(d), dst, dtype=np.int32) for dst, d in parts]
            )
            if parts
            else np.empty(0, dtype=np.int32)
        )
        counts = np.zeros(n, dtype=np.int64)
        for dst, d in parts:
            counts[dst] += len(d)
        sig = local_signature(local, column_names)

        key = (channel, tick)
        tracer = self._tracer
        if tracer is not None:
            tracer.flow_start(
                "mesh.deposit",
                self._flow_id(channel, tick, worker_id, "in"),
                channel=channel,
                tick=tick,
            )
        with self._slot_lock:
            slot = self._slots.setdefault(key, {"payloads": [None] * n})
            slot["payloads"][worker_id] = (sig, counts, local, dest)
        self.inner.barrier(worker_id)  # all deposits visible

        if worker_id == 0:
            with self._slot_lock:
                # all workers deposited (channel, tick) → every worker has
                # finished all earlier ticks on EVERY channel (the sweep is
                # sequential per worker); reclaim all older slots
                stale = [k for k in self._slots if k[1] < tick]
                for k in stale:
                    del self._slots[k]
                slot = self._slots[key]
            try:
                slot["result"] = self.runner.run_tick(
                    slot["payloads"], column_names
                )
                if tracer is not None:
                    # the driver's collective consumed every deposit and
                    # fans the result back out — close/open the flows here,
                    # inside the driver's tick slice
                    for w in range(n):
                        tracer.flow_end(
                            "mesh.deposit",
                            self._flow_id(channel, tick, w, "in"),
                        )
                        tracer.flow_start(
                            "mesh.result",
                            self._flow_id(channel, tick, w, "out"),
                        )
            except BaseException as e:  # noqa: BLE001 — re-raised on peers
                slot["result"] = _DriverError(e)
                self.inner.barrier(worker_id)
                raise
            self.inner.barrier(worker_id)
        else:
            self.inner.barrier(worker_id)
            slot = self._slots[key]

        result = slot["result"]
        if isinstance(result, _DriverError):
            raise RuntimeError(
                "mesh exchange failed on the driver worker"
            ) from result.error
        if tracer is not None:
            tracer.flow_end(
                "mesh.result", self._flow_id(channel, tick, worker_id, "out")
            )
        if result is None:
            return []
        kinds, cap_bucket, gvals, gvalid = result

        per_dev = self.runner.n * cap_bucket
        my_vals = self.runner.my_shard(gvals, worker_id, per_dev)
        my_valid = self.runner.my_shard(gvalid, worker_id, per_dev)

        host_cols: dict[int, dict[str, np.ndarray]] = {}
        host_names = [c for c, k in zip(column_names, kinds) if k == HOST]
        if host_names:
            for src, payload in enumerate(slot["payloads"]):
                _, _, src_local, src_dest = payload
                if src_local is None or not len(src_local):
                    continue
                mine = src_dest == worker_id
                if mine.any():
                    ix = np.flatnonzero(mine)
                    host_cols[src] = {
                        c: src_local.data[c][ix] for c in host_names
                    }

        return self.runner.unpack_arrivals(
            vals=my_vals,
            valid=my_valid.astype(bool),
            kinds=kinds,
            column_names=column_names,
            host_cols=host_cols,
        )


class _DriverError:
    """Marks a failed driver tick so peers re-raise instead of hanging."""

    def __init__(self, error: BaseException):
        self.error = error


class MultiHostMeshComm(Comm):
    """Cross-process mesh exchange: the DCN/ICI data plane over a
    ``jax.distributed`` multi-controller mesh (VERDICT r4 item 6).

    Processes each own ``threads`` workers and (at least) ``threads``
    local devices; the global 1-D mesh orders devices process-major so
    worker ``p*threads + t`` owns device ``t`` of process ``p``. Per
    channel-tick:

    1. every worker allgathers its tiny control tuple (dtype signature,
       per-destination counts) over the host ClusterComm, and deposits its
       local Delta in a PROCESS-local slot;
    2. each process's leader thread packs its workers' dense rows into
       process-local staging, forms its slice of the global array with
       ``jax.make_array_from_process_local_data``, and all leaders execute
       the same jitted ``bucketed_all_to_all`` simultaneously
       (multi-controller SPMD) — the record bytes ride ICI/DCN;
    3. every worker reads back its own addressable shard; object/string
       columns swap over the host ClusterComm and re-zip by source order.

    Reference: timely's cluster allocator
    (``communication/src/allocator/zero_copy/``) + bootstrap
    (``communication/src/initialize.rs``).
    """

    def __init__(self, inner: Comm, process_id: int, n_processes: int,
                 threads: int):
        import jax
        from jax.sharding import Mesh

        self.inner = inner
        self.n_workers = inner.n_workers
        self.process_id = process_id
        self.n_processes = n_processes
        self.threads = threads
        by_process: dict[int, list] = {}
        for d in jax.devices():
            by_process.setdefault(d.process_index, []).append(d)
        ordered = []
        for p in sorted(by_process):
            local = by_process[p]
            if len(local) < threads:
                raise RuntimeError(
                    f"process {p} exposes {len(local)} devices < "
                    f"{threads} workers — mesh exchange needs one device "
                    "per worker"
                )
            ordered.extend(local[:threads])
        if len(ordered) < self.n_workers:
            raise RuntimeError(
                f"mesh exchange needs ≥{self.n_workers} devices across "
                f"processes, have {len(ordered)}"
            )
        self.mesh = Mesh(np.array(ordered[: self.n_workers]), ("workers",))
        self.runner = MeshExchangeRunner(self.mesh, "workers")
        # process-local coordination among this process's worker threads
        self._local_barrier = threading.Barrier(threads)
        self._slot_lock = threading.Lock()
        self._slots: dict[tuple, dict] = {}
        # tracing: local deposit→leader flows (cross-process linkage rides
        # the inner ClusterComm's frame contexts)
        from ..internals.tracing import get_tracer, mint_flow_tag

        self._tracer = get_tracer()
        self._flow_tag = mint_flow_tag()

    def _flow_id(self, channel: int, tick: int, worker: int,
                 phase: str) -> str:
        from ..internals.tracing import make_flow_id

        return make_flow_id(
            self._tracer, self._flow_tag,
            f"mxh{channel}", f"t{tick}", f"{phase}{worker}",
        )

    # host-comm delegation

    def exchange(self, channel, tick, worker_id, buckets):
        return self.inner.exchange(channel, tick, worker_id, buckets)

    def allgather(self, tag, worker_id, obj):
        return self.inner.allgather(tag, worker_id, obj)

    def barrier(self, worker_id: int):
        self.inner.barrier(worker_id)

    # async (frontier-driven) plane delegation — see MeshComm note
    def supports_async(self) -> bool:
        return self.inner.supports_async()

    def async_attach(self, worker_id, waker):
        self.inner.async_attach(worker_id, waker)

    def async_post_exchange(self, worker_id, channel, time, buckets,
                            ingest_ns=None, seq=None, enq_ns=None):
        return self.inner.async_post_exchange(
            worker_id, channel, time, buckets, ingest_ns, seq, enq_ns
        )

    def async_broadcast(self, worker_id, payload):
        self.inner.async_broadcast(worker_id, payload)

    def async_drain(self, worker_id):
        return self.inner.async_drain(worker_id)

    def async_congested(self, worker_id):
        return self.inner.async_congested(worker_id)

    def abort(self):
        self._local_barrier.abort()
        self.inner.abort()

    def close(self):
        with self._slot_lock:
            self._slots.clear()
        self.inner.close()

    def comm_stats(self) -> dict[str, float]:
        out = dict(self.inner.comm_stats())
        out["mesh_pending_slots"] = float(len(self._slots))
        out.update(self.runner.stats())
        return out

    def _local_index(self, worker_id: int) -> int:
        return worker_id - self.process_id * self.threads

    def exchange_deltas(
        self,
        channel: int,
        tick: int,
        worker_id: int,
        buckets: Sequence[Delta | None],
        column_names: list[str],
    ) -> list[Delta]:
        from ..engine.mesh_exchange import _pow2, agree_kinds

        n = self.n_workers
        parts = [
            (dst, d) for dst, d in enumerate(buckets) if d is not None and len(d)
        ]
        local = concat_deltas([d for _, d in parts], column_names) if parts else None
        dest = (
            np.concatenate(
                [np.full(len(d), dst, dtype=np.int32) for dst, d in parts]
            )
            if parts
            else np.empty(0, dtype=np.int32)
        )
        counts = np.zeros(n, dtype=np.int64)
        for dst, d in parts:
            counts[dst] += len(d)
        sig = local_signature(local, column_names)

        key = (channel, tick)
        tracer = self._tracer
        if tracer is not None:
            tracer.flow_start(
                "mesh.deposit",
                self._flow_id(channel, tick, worker_id, "in"),
                channel=channel,
                tick=tick,
            )
        with self._slot_lock:
            slot = self._slots.setdefault(
                key, {"payloads": [None] * self.threads}
            )
            slot["payloads"][self._local_index(worker_id)] = (local, dest)
        # ONE global control allgather per channel-tick
        metas = self.inner.allgather(
            ("mxh", channel, tick), worker_id, (sig, counts.tolist())
        )
        total = sum(sum(m[1]) for m in metas)
        kinds = agree_kinds([m[0] for m in metas], len(column_names))
        cap_in = _pow2(max(sum(m[1]) for m in metas)) if total else 8
        cap_bucket = _pow2(max(max(m[1]) for m in metas)) if total else 8

        try:
            self._local_barrier.wait()  # all local deposits visible
            leader = self._local_index(worker_id) == 0
            if leader:
                with self._slot_lock:
                    stale = [k for k in self._slots if k[1] < tick]
                    for k in stale:
                        del self._slots[k]
                    slot = self._slots[key]
                try:
                    if total:
                        # count only THIS process's deposited rows — every
                        # leader runs this block, so recording the global
                        # total would inflate the fleet sum n_processes×
                        local_rows = sum(
                            sum(metas[w][1])
                            for w in range(
                                self.process_id * self.threads,
                                (self.process_id + 1) * self.threads,
                            )
                        )
                        self.runner.note_collective(local_rows)
                    slot["result"] = (
                        self._run_collective(
                            slot["payloads"], column_names, kinds,
                            cap_in, cap_bucket,
                        )
                        if total
                        else None
                    )
                    if tracer is not None:
                        base = self.process_id * self.threads
                        for w in range(base, base + self.threads):
                            tracer.flow_end(
                                "mesh.deposit",
                                self._flow_id(channel, tick, w, "in"),
                            )
                            tracer.flow_start(
                                "mesh.result",
                                self._flow_id(channel, tick, w, "out"),
                            )
                except BaseException as e:  # noqa: BLE001
                    slot["result"] = _DriverError(e)
                    self._local_barrier.wait()
                    raise
                self._local_barrier.wait()
            else:
                self._local_barrier.wait()
                slot = self._slots[key]
        except threading.BrokenBarrierError:
            raise RuntimeError(
                "a peer worker failed — aborting mesh exchange"
            ) from None

        result = slot["result"]
        if isinstance(result, _DriverError):
            raise RuntimeError(
                "mesh exchange failed on the process leader"
            ) from result.error
        if tracer is not None:
            tracer.flow_end(
                "mesh.result", self._flow_id(channel, tick, worker_id, "out")
            )

        host_names = [c for c, k in zip(column_names, kinds) if k == HOST]
        host_cols: dict[int, dict[str, np.ndarray]] = {}
        if host_names and total:
            obj_buckets: list[Any] = [None] * n
            if parts:
                per_dst: dict[int, dict[str, list]] = {}
                for dst, d in parts:
                    cols = per_dst.setdefault(dst, {c: [] for c in host_names})
                    for c in host_names:
                        cols[c].append(d.data[c])
                for dst, cols in per_dst.items():
                    obj_buckets[dst] = (
                        worker_id,
                        {c: np.concatenate(v) for c, v in cols.items()},
                    )
            received = self.inner.exchange(
                ("mxh-obj", channel), tick, worker_id, obj_buckets
            )
            for src, cols in received:
                host_cols[src] = cols

        if result is None:
            return []
        gvals, gvalid = result
        per_dev = n * cap_bucket
        my_vals = self.runner.my_shard(gvals, worker_id, per_dev)
        my_valid = self.runner.my_shard(gvalid, worker_id, per_dev)
        return self.runner.unpack_arrivals(
            vals=my_vals,
            valid=my_valid.astype(bool),
            kinds=kinds,
            column_names=column_names,
            host_cols=host_cols,
        )

    def _run_collective(self, payloads, column_names, kinds, cap_in, cap_bucket):
        """Leader thread: pack this PROCESS's workers, form the process-local
        slice of the global array, run the collective with every other
        process's leader."""
        import time as _time

        import jax

        tracer = self._tracer
        t0 = _time.perf_counter_ns() if tracer is not None else 0
        vals, dst = self.runner.pack_blocks(
            list(payloads), kinds, column_names, cap_in
        )
        sh_v, sh_d = self.runner._mesh_shardings()
        gvals = jax.make_array_from_process_local_data(sh_v, vals)
        gdest = jax.make_array_from_process_local_data(sh_d, dst)
        width = self.runner.width(kinds)
        out = self.runner._kernel(cap_in, cap_bucket, width)(gvals, gdest)
        if tracer is not None:
            tracer.complete(
                "mesh.collective",
                t0,
                {"cap_in": cap_in, "cap_bucket": cap_bucket,
                 "process": self.process_id},
            )
        return out
