"""TCP cluster communication for multi-process workers.

The ``zero_copy`` allocator analog (``external/timely-dataflow/communication/
src/allocator/zero_copy/``): processes form a full mesh of sockets
(process p listens at its address-book entry — default ``first_port + p``
on one machine, or one ``host[:port]`` per process via ``PATHWAY_ADDRESSES``
for multi-host/DCN clusters, the timely hostfile analog
(``communication/src/initialize.rs``); higher pids dial lower ones).

Data plane (``parallel/frames.py``): exchange frames are the **zero-copy
columnar wire protocol** — one binary frame per (exchange, remote
process) carries all buckets for that process's workers, dense numpy
columns appended verbatim (memoryview on encode, ``frombuffer`` on
decode) and object columns in a pickle section. Sends are **pipelined**:
``exchange`` encodes and enqueues onto a per-peer writer thread (bounded
by ``PATHWAY_COMM_QUEUE_FRAMES``) and returns to the tick loop instead
of blocking on ``sendall``; every frame queued for the same peer when
its writer wakes is coalesced into one vectored ``sendmsg`` batch — the
timely ``send_loop``/``BytesExchange`` split (zero_copy/tcp.rs). Writer
death flips ``_broken`` exactly like reader death, so the fast
failure-propagation contract is unchanged. Control frames (allgather,
ping/pong, bye) stay pickled behind a tag byte.

``pathway spawn -n M -t T program.py`` launches M processes, each hosting T
worker threads; every process runs the identical dataflow build and owns
the key shards of its workers (internals/graph_runner._run_sharded).
"""

from __future__ import annotations

import collections
import pickle
import random
import socket
import struct
import threading
import time
from typing import Any

from . import frames
from .comm import Comm

__all__ = ["ClusterComm"]

_LEN = struct.Struct(">Q")
#: defaults; per-instance values come from PATHWAY_CONNECT_TIMEOUT_S /
#: PATHWAY_COLLECTIVE_TIMEOUT_S (internals/config.py) so deployments can
#: tune how long a worker waits before declaring its peers gone
CONNECT_TIMEOUT_S = 30.0
COLLECTIVE_TIMEOUT_S = 600.0
#: default bound of each per-peer writer queue (frames); the knob is
#: PATHWAY_COMM_QUEUE_FRAMES — a full queue blocks the enqueuing worker,
#: which is the backpressure that keeps a slow peer from buffering the
#: whole stream in sender memory
QUEUE_FRAMES = 256
#: a length prefix past this is a torn/corrupt stream, not a real frame
#: (1 TiB — far above any exchange batch, far below a garbage u64)
_MAX_FRAME_BYTES = 1 << 40
#: sendmsg scatter-gather width per syscall (IOV_MAX is 1024 on linux;
#: stay under it with margin)
_IOV_MAX = 512


#: frames under this size are joined into one contiguous wire buffer and
#: written with a single send; above it, scatter-gather sendmsg avoids
#: the memcpy. Measured on this class of host a sendmsg syscall costs
#: ~300 us regardless of size while the join copies at ~10 GB/s, so the
#: crossover sits in the megabytes
_JOIN_MAX_BYTES = 4 << 20


def _send_vectored(sock: socket.socket, chunks: list) -> None:
    """sendall for a list of bytes-like chunks. Small/medium frames are
    coalesced into ONE contiguous buffer and one ``sendall`` (a single
    memcpy beats per-iovec syscall overhead by orders of magnitude at
    these sizes); only multi-megabyte batches take the zero-copy
    ``sendmsg`` scatter-gather path, chunked to ≤ _IOV_MAX iovecs with
    partial-send resume."""
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None or sum(len(c) for c in chunks) <= _JOIN_MAX_BYTES:
        sock.sendall(b"".join(chunks))
        return
    i = 0
    n = len(chunks)
    while i < n:
        try:
            sent = sendmsg(chunks[i : i + _IOV_MAX])
        except InterruptedError:  # pragma: no cover
            continue
        while sent:
            c = chunks[i]
            if sent >= len(c):
                sent -= len(c)
                i += 1
            else:
                # partial chunk: resume from a suffix view
                chunks[i] = memoryview(c)[sent:]
                sent = 0


class _PeerWriter:
    """One outbound pipeline: a bounded frame queue drained by a
    dedicated thread. ``send`` is opportunistic — a frame headed to an
    IDLE pipeline is written inline by the calling thread (in the
    bulk-synchronous exchange the sender blocks on peer frames right
    after sending, so there is nothing to overlap and the thread
    handoff would be pure latency), while any frame arriving behind
    other traffic — another worker mid-send on this link, or a backlog
    a slow peer left queued — rides the writer thread. The drain loop
    batches every queued frame into a single vectored send, which is
    where per-tick frames headed to the same peer coalesce into one
    syscall batch. An ``_io_lock`` serializes inline and drain-loop
    writes, and the FIFO rule is "inline only when nothing is queued or
    in flight", so per-thread frame order is preserved."""

    def __init__(self, comm: "ClusterComm", peer: int, sock: socket.socket,
                 max_frames: int):
        self._comm = comm
        self.peer = peer
        self._sock = sock
        self._max = max(1, max_frames)
        self._q: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._io_lock = threading.Lock()
        self._closed = False
        # per-writer counters (mutated only under _io_lock; summed by
        # comm_stats into the pathway_comm_* gauges)
        self.bytes_sent = 0
        self.frames_sent = 0
        self.frames_coalesced = 0
        self.thread = threading.Thread(
            target=self._run, name=f"pw-comm-writer-p{peer}", daemon=True
        )
        self.thread.start()

    def queue_depth(self) -> int:
        return len(self._q)

    def send(self, chunks: list, nbytes: int) -> None:
        if (
            not self._q
            and not self._closed
            and self._comm._broken is None
            and self._io_lock.acquire(blocking=False)
        ):
            # inline fast path: the pipeline is idle, so ordering is
            # trivially preserved and the thread handoff is skipped
            try:
                _send_vectored(self._sock, list(chunks))
                self.bytes_sent += nbytes
                self.frames_sent += 1
            except OSError as e:
                if not self._comm._closing:
                    self._comm._break(
                        f"send to process {self.peer} failed ({e})"
                    )
                raise RuntimeError(
                    self._comm._broken or "cluster send failed"
                ) from None
            finally:
                self._io_lock.release()
            return
        self.enqueue(chunks, nbytes)

    def enqueue(self, chunks: list, nbytes: int) -> None:
        with self._cond:
            while (
                len(self._q) >= self._max
                and not self._closed
                and self._comm._broken is None
            ):
                self._cond.wait(timeout=0.1)
            if self._closed or self._comm._broken is not None:
                raise RuntimeError(
                    self._comm._broken
                    or f"cluster send to process {self.peer} after close"
                )
            self._q.append((chunks, nbytes))
            self._cond.notify_all()

    def close(self) -> None:
        """Stop accepting frames; the drain loop exits after flushing
        everything already queued."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def join(self, timeout: float) -> None:
        self.thread.join(timeout)

    def _run(self) -> None:
        # the same must-not-die-mute contract as the reader threads: ANY
        # failure here would otherwise strand enqueuers (queue full, no
        # drain) and peers (frames never sent) until the collective
        # timeout, with no recorded cause
        try:
            self._drain_loop()
        except BaseException as e:  # noqa: BLE001
            if not self._comm._closing:
                self._comm._break(
                    f"writer thread for process {self.peer} failed: {e!r}"
                )

    def _drain_loop(self) -> None:
        comm = self._comm
        while True:
            with self._cond:
                while not self._q and not self._closed:
                    self._cond.wait()
                closed = self._closed
            # take the io lock BEFORE popping: "queue empty AND io lock
            # free" (the inline-send gate) then implies no popped-but-
            # unsent frame exists anywhere — the FIFO invariant
            with self._io_lock:
                with self._cond:
                    batch = list(self._q)
                    self._q.clear()
                    self._cond.notify_all()  # room freed: wake enqueuers
                if batch:
                    flat: list = []
                    nbytes = 0
                    for chunks, fb in batch:
                        flat.extend(chunks)
                        nbytes += fb
                    try:
                        _send_vectored(self._sock, flat)
                    except OSError as e:
                        if not comm._closing:
                            comm._break(
                                f"send to process {self.peer} failed ({e}) "
                                "(writer thread)"
                            )
                        return
                    self.bytes_sent += nbytes
                    self.frames_sent += len(batch)
                    if len(batch) > 1:
                        self.frames_coalesced += len(batch) - 1
            if closed and not self._q:
                return


class ClusterComm(Comm):
    def __init__(
        self,
        process_id: int,
        n_processes: int,
        threads_per_process: int,
        first_port: int,
        host: str = "127.0.0.1",
        addresses: list[str] | None = None,
        connect_timeout_s: float | None = None,
        collective_timeout_s: float | None = None,
    ):
        from ..internals.config import _env_float

        self.connect_timeout_s = (
            connect_timeout_s
            if connect_timeout_s is not None
            else _env_float("PATHWAY_CONNECT_TIMEOUT_S", CONNECT_TIMEOUT_S)
        )
        self.collective_timeout_s = (
            collective_timeout_s
            if collective_timeout_s is not None
            else _env_float(
                "PATHWAY_COLLECTIVE_TIMEOUT_S", COLLECTIVE_TIMEOUT_S
            )
        )
        self.process_id = process_id
        self.n_processes = n_processes
        self.threads = threads_per_process
        self.n_workers = n_processes * threads_per_process
        #: per-process (host, port) book — the timely hostfile analog
        #: (communication/src/initialize.rs); default: one machine, ports
        #: first_port..first_port+n-1
        self._addrs = _address_book(addresses, n_processes, host, first_port)
        self._local_workers = set(
            process_id * threads_per_process + i
            for i in range(threads_per_process)
        )
        self._cond = threading.Condition()
        self._barrier_seqs: dict[int, int] = {}
        #: ("x", channel, tick, dst) -> {src: payload}
        #: ("g", tag) -> {src: payload}
        self._inbox: dict[Any, dict[int, Any]] = {}
        self._gather_reads: dict[Any, int] = {}
        #: async (frontier-driven) plane: per-LOCAL-worker event inboxes.
        #: Remote arrivals ride the same columnar frames as BSP exchange
        #: (meta channel tagged ("a", ...)) and are filed here by the
        #: reader threads instead of the rendezvous inbox.
        self._async_q: dict[int, collections.deque] = {
            w: collections.deque() for w in self._local_workers
        }
        self._async_data: dict[int, int] = {w: 0 for w in self._local_workers}
        self._async_wakers: dict[int, Any] = {}
        from .comm import async_queue_bound, serve_queue_bound

        self._async_bound = async_queue_bound()
        #: serve plane (pathway_tpu/serve/): per-LOCAL-worker query
        #: event inboxes, bounded and drop-on-overflow — a lost serve
        #: event degrades one gather, never wedges the dataflow
        self._serve_q: dict[int, collections.deque] = {
            w: collections.deque() for w in self._local_workers
        }
        self._serve_bound = serve_queue_bound()
        self._serve_dropped = 0
        self._broken: str | None = None
        self._socks: dict[int, socket.socket] = {}
        self._writers: dict[int, _PeerWriter] = {}
        self._readers: list[threading.Thread] = []
        self._listener: socket.socket | None = None
        self._closing = False
        from ..internals.config import _env_int

        self._queue_frames = _env_int("PATHWAY_COMM_QUEUE_FRAMES", QUEUE_FRAMES)
        # observability counters (GIL-cheap, read by comm_stats; send-side
        # counters live on the per-peer writers — single-writer, race-free)
        self.bytes_received = 0
        self.frames_received = 0
        self.encode_ns = 0
        self._encode_lock = threading.Lock()
        # chaos site (comm.send): None unless a fault plan targets this
        # process's outbound frames — one None check per send when disarmed
        from ..chaos import injector as _chaos

        armed = _chaos.current()
        self._chaos = (
            armed.send_faults(process_id) if armed is not None else None
        )
        # tracing site: frames carry a (run_id, flow_id) context so both
        # ends of every cross-process frame emit linked flow events
        from ..internals.tracing import get_tracer, mint_flow_tag

        self._tracer = get_tracer()
        import itertools as _itertools

        self._flow_seq = _itertools.count()
        self._flow_tag = mint_flow_tag()
        #: peer process id -> (unix-clock offset ns, rtt ns), offset = peer
        #: clock minus ours; min-rtt sample of the handshake ping burst
        self.clock_offsets: dict[int, tuple[float, float]] = {}
        self._pongs_seen: dict[int, int] = {}
        self._connect_mesh()
        # only a tracer consumes the offsets — an untraced run must not pay
        # the ping burst (or its cond-wait) at every mesh establishment
        if self.n_processes > 1 and self._tracer is not None:
            self._measure_clock_offsets()
            self._tracer.set_clock_offsets(self.clock_offsets)

    # -- mesh setup ------------------------------------------------------

    def _connect_mesh(self) -> None:
        if self.n_processes == 1:
            return
        my_port = self._addrs[self.process_id][1]
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # bind all interfaces: with an address book, peers dial in over DCN
        # from other machines; the book entry is how THEY reach us
        self._listener.bind(("" if len({h for h, _ in self._addrs}) > 1
                             else self._addrs[self.process_id][0], my_port))
        self._listener.listen(self.n_processes)

        expected_inbound = self.n_processes - 1 - self.process_id

        def accept_loop() -> None:
            for _ in range(expected_inbound):
                conn, _addr = self._listener.accept()
                peer = _LEN.unpack(_recv_exact(conn, 8))[0]
                self._register_peer(int(peer), conn)

        acceptor = threading.Thread(target=accept_loop, daemon=True)
        acceptor.start()

        # dial every lower pid (they accept from us); unreachable peers are
        # retried with jittered exponential backoff until the connect
        # timeout — a restarting peer (supervised ensemble, rolling deploy)
        # needs a window to come back without synchronized reconnect storms
        for peer in range(self.process_id):
            peer_host, peer_port = self._addrs[peer]
            deadline = time.monotonic() + self.connect_timeout_s
            delay, last_err = 0.05, None
            while True:
                try:
                    s = socket.create_connection(
                        (peer_host, peer_port), timeout=2.0
                    )
                    break
                except OSError as e:
                    last_err = e
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"process {self.process_id}: peer process {peer} "
                            f"not reachable on {peer_host}:{peer_port} after "
                            f"{self.connect_timeout_s:.0f}s ({last_err})"
                        ) from e
                    time.sleep(delay * (0.5 + random.random()))
                    delay = min(delay * 2, 1.0)
            s.sendall(_LEN.pack(self.process_id))
            self._register_peer(peer, s)
        acceptor.join(self.connect_timeout_s)
        if len(self._socks) != self.n_processes - 1:
            missing = sorted(
                set(range(self.n_processes))
                - set(self._socks)
                - {self.process_id}
            )
            raise RuntimeError(
                f"process {self.process_id}: cluster mesh incomplete "
                f"({len(self._socks)}/{self.n_processes - 1} peers; "
                f"missing processes {missing})"
            )

    def _register_peer(self, peer: int, sock: socket.socket) -> None:
        # dialed sockets inherit create_connection's 2s timeout; the mesh
        # must tolerate arbitrarily long quiet periods (idle sources, slow
        # peers) — make every registered socket blocking
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._socks[peer] = sock
        self._writers[peer] = _PeerWriter(self, peer, sock, self._queue_frames)
        t = threading.Thread(target=self._read_loop, args=(peer, sock), daemon=True)
        t.start()
        self._readers.append(t)

    def _read_loop(self, peer: int, sock: socket.socket) -> None:
        try:
            while True:
                header = _recv_exact(sock, 8)
                n_body = _LEN.unpack(header)[0]
                if not 0 < n_body <= _MAX_FRAME_BYTES:
                    raise frames.CorruptFrame(
                        f"frame length {n_body} outside sanity bounds"
                    )
                body = _recv_into(sock, n_body)
                self.bytes_received += 8 + n_body
                self.frames_received += 1
                if body[0] == frames.KIND_COLUMNAR:
                    # zero-copy decode: dense columns alias `body`
                    frame = frames.decode_frame(body)
                else:
                    try:
                        frame = pickle.loads(memoryview(body)[1:])
                    except Exception as e:
                        raise frames.CorruptFrame(
                            f"bad control frame ({e})"
                        ) from e
                kind = frame[0]
                if kind == "bye":
                    # graceful: the peer finished its dataflow (all its
                    # collectives, incl. the END_TIME sweep, completed) and
                    # is shutting down — everything it owed us was already
                    # delivered in order before this frame
                    return
                if kind == "ping":
                    # clock-sync probe: echo (seq, t0) back with our recv
                    # time, straight from the reader thread so the sample
                    # measures the wire, not a collective's queueing
                    self._send_raw(
                        peer, ("pong", frame[1], frame[2], time.time_ns())
                    )
                    continue
                if kind == "pong":
                    self._note_pong(
                        peer, frame[2], frame[3], time.time_ns()
                    )
                    continue
                tracer = self._tracer
                t0 = time.perf_counter_ns() if tracer is not None else 0
                ctx = self._deliver(frame)
                if tracer is not None and ctx is not None:
                    # f before complete: the flow's binding point must fall
                    # inside the comm.recv slice on this reader thread
                    tracer.flow_end("comm.frame", ctx[1], from_process=peer)
                    tracer.complete(
                        "comm.recv",
                        t0,
                        {"from_process": peer, "bytes": 8 + n_body},
                    )
        except frames.CorruptFrame as e:
            # torn/corrupted wire bytes: refuse to deserialize garbage —
            # name the origin and fail the process's collectives fast
            if not self._closing:
                self._break(
                    f"corrupt frame from process {peer}: {e} "
                    "(reader thread refused to deserialize)"
                )
        except (OSError, EOFError) as e:
            # peer socket death: the fast-propagation path — flip _broken
            # and wake every blocked collective NOW, not at the timeout
            if not self._closing:
                self._break(
                    f"connection to process {peer} lost ({e or 'EOF'})"
                )
        except BaseException as e:  # noqa: BLE001 — reader must not die mute
            # ANY reader-thread failure (bad pickle, memory pressure, a bug)
            # would otherwise strand this process's workers in collectives
            # until the timeout with no record of why
            if not self._closing:
                self._break(f"reader thread for process {peer} failed: {e!r}")

    def _deliver(self, frame: tuple) -> tuple | None:
        """File a data/control frame into the inbox; returns the frame's
        trace context (run_id, flow_id) when the sender shipped one."""
        kind = frame[0]
        ctx = None
        wake: list[int] = []
        with self._cond:
            if kind == "x":
                _, channel, tick, src, per_dst = frame[:5]
                ctx = frame[5] if len(frame) > 5 else None
                if isinstance(channel, tuple) and channel and channel[0] == "a":
                    # async data plane: file per-worker events, never the
                    # rendezvous inbox (nothing is waiting collectively).
                    # The reader thread NEVER blocks on the inbox bound —
                    # remote backpressure is the peer-status depth the
                    # executor consults before polling sources.
                    _a, real_channel, ingest_ns, seq = channel[:4]
                    enq_ns = channel[4] if len(channel) > 4 else None
                    for dst, payload in per_dst.items():
                        q = self._async_q.get(dst)
                        if q is None:
                            continue  # stale frame for a non-local worker
                        q.append(
                            ("x", real_channel, tick, src, payload,
                             ingest_ns, seq, enq_ns)
                        )
                        self._async_data[dst] += 1
                        wake.append(dst)
                elif (
                    isinstance(channel, tuple)
                    and channel
                    and channel[0] == "s"
                ):
                    # serve plane: (meta, payload) query events into the
                    # bounded serve inboxes — overflow DROPS (counted);
                    # the origin's partial-gather timeout is the recovery
                    meta = channel[1]
                    for dst, payload in per_dst.items():
                        q = self._serve_q.get(dst)
                        if q is None:
                            continue  # stale frame for a non-local worker
                        if len(q) >= self._serve_bound:
                            self._serve_dropped += 1
                            continue
                        q.append((meta, payload))
                else:
                    for dst, payload in per_dst.items():
                        self._inbox.setdefault(("x", channel, tick, dst), {})[src] = payload
            elif kind == "ac":
                # async control broadcast: fan out to every local worker
                _, src, payload = frame[:3]
                ctx = frame[3] if len(frame) > 3 else None
                for dst, q in self._async_q.items():
                    q.append(("c", src, payload))
                    wake.append(dst)
            else:
                _, tag, src, obj = frame[:4]
                ctx = frame[4] if len(frame) > 4 else None
                self._inbox.setdefault(("g", tag), {})[src] = obj
            self._cond.notify_all()
        for dst in wake:
            waker = self._async_wakers.get(dst)
            if waker is not None:
                waker.set()
        return ctx

    # -- clock-offset estimation (mesh establishment) --------------------

    def _note_pong(self, peer: int, t0_ns: int, t1_ns: int, t2_ns: int) -> None:
        """One ping round trip: we sent at ``t0``, the peer stamped ``t1``
        on receipt, the pong landed here at ``t2``. NTP-style estimate:
        offset = t1 - (t0+t2)/2 (peer clock minus ours), error bounded by
        rtt/2 — the min-rtt sample of the burst wins."""
        rtt = t2_ns - t0_ns
        offset = t1_ns - (t0_ns + t2_ns) / 2
        with self._cond:
            best = self.clock_offsets.get(peer)
            if best is None or rtt < best[1]:
                self.clock_offsets[peer] = (float(offset), float(rtt))
            self._pongs_seen[peer] = self._pongs_seen.get(peer, 0) + 1
            self._cond.notify_all()

    def _measure_clock_offsets(
        self, n_pings: int = 4, timeout_s: float = 2.0
    ) -> None:
        """Ping every peer during mesh establishment so the per-process
        trace files can be merged onto one timeline even across hosts with
        skewed clocks (`pathway-tpu trace merge`). Best-effort: a peer that
        never answers simply has no offset estimate (merge falls back to
        raw unix origins)."""
        peers = list(self._socks)
        for _ in range(n_pings):
            for peer in peers:
                try:
                    self._send_raw(peer, ("ping", 0, time.time_ns()))
                except (RuntimeError, OSError, KeyError):
                    pass
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while (
                any(self._pongs_seen.get(p, 0) < n_pings for p in peers)
                and self._broken is None
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=min(remaining, 0.1))

    def _send(self, peer: int, frame: tuple) -> None:
        """Chaos-gated control-frame send (pickled behind the tag byte)."""
        body = frames.encode_control(frame)
        self._post(
            peer, [_LEN.pack(len(body)), body], 8 + len(body),
            chaos=frame[0] != "bye",
        )

    def _send_raw(self, peer: int, frame: tuple) -> None:
        """Control-frame send bypassing chaos (ping/pong clock probes)."""
        body = frames.encode_control(frame)
        self._post(peer, [_LEN.pack(len(body)), body], 8 + len(body),
                   chaos=False)

    def _post(self, peer: int, chunks: list, nbytes: int,
              chaos: bool = True) -> bool:
        """Enqueue one framed message (length prefix included in
        ``chunks``) onto ``peer``'s writer pipeline. All chaos comm.send
        actions fire here — on the new pipelined path, before the frame
        reaches the queue. Returns False when the frame was chaos-lost
        (drop/sever) — the async data plane's quiesce ledger needs to
        know (a counted-sent-but-never-delivered event would unbalance
        the sent/received totals forever)."""
        if chaos and self._chaos is not None:
            op = self._chaos.op_for(peer)
            if op is not None:
                action, delay_s = op
                if action == "drop":
                    return False
                if action == "delay":
                    time.sleep(delay_s)
                elif action == "sever":
                    # partition: hard-close the link and send NOTHING —
                    # both sides' read loops see EOF and flip _broken (a
                    # fall-through send would fail in the writer and
                    # mislabel the chaos as a sender crash)
                    try:
                        self._socks[peer].shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    self._socks[peer].close()
                    return False
                elif action == "duplicate":
                    self._enqueue(peer, list(chunks), nbytes)
                elif action == "corrupt":
                    chunks = _corrupt_chunks(chunks)
        self._enqueue(peer, chunks, nbytes)
        return True

    def _enqueue(self, peer: int, chunks: list, nbytes: int) -> None:
        writer = self._writers.get(peer)
        if writer is None:
            raise RuntimeError(
                self._broken or f"no connection to process {peer}"
            )
        writer.send(chunks, nbytes)

    def _process_of(self, worker: int) -> int:
        return worker // self.threads

    # -- collectives -----------------------------------------------------

    def _frame_ctx(self, peer: int, **args: Any) -> tuple | None:
        """Mint a per-frame trace context (run_id, flow_id) and emit the
        sending half of the flow; None when tracing is off (frames stay
        one element longer either way — both ends run the same version)."""
        tracer = self._tracer
        if tracer is None:
            return None
        from ..internals.tracing import make_flow_id

        flow_id = make_flow_id(
            tracer, self._flow_tag,
            f"p{self.process_id}", next(self._flow_seq),
        )
        tracer.flow_start("comm.frame", flow_id, peer_process=peer, **args)
        return (tracer.run_id, flow_id)

    def exchange(self, channel, tick, worker_id, buckets):
        per_process: dict[int, dict[int, Any]] = {}
        with self._cond:
            for dst, payload in enumerate(buckets):
                p = self._process_of(dst)
                if p == self.process_id:
                    self._inbox.setdefault(
                        ("x", channel, tick, dst), {}
                    )[worker_id] = payload
                else:
                    per_process.setdefault(p, {})[dst] = payload
            self._cond.notify_all()
        tracer = self._tracer
        for p, per_dst in per_process.items():
            ctx = self._frame_ctx(p, channel=channel, tick=tick)
            # columnar wire codec: dense columns ride as raw buffers;
            # frames behind a backlog enqueue and return, so the tick
            # loop never blocks on a slow peer here
            t0 = time.perf_counter_ns()
            chunks, body_len = frames.encode_frame(
                channel, int(tick), worker_id, per_dst, ctx
            )
            with self._encode_lock:
                # counter shared by all worker threads: an unlocked += is
                # a lost-update race (the per-writer send counters are
                # single-owner and need none)
                self.encode_ns += time.perf_counter_ns() - t0
            if tracer is not None:
                tracer.complete(
                    "comm.encode", t0,
                    {"peer_process": p, "bytes": body_len, "channel": channel},
                )
            self._post(p, [_LEN.pack(body_len)] + chunks, 8 + body_len)
        # remote processes always send a frame (even all-None buckets), so
        # completion = contributions from every worker id
        key = ("x", channel, tick, worker_id)
        payloads = self._wait(key, self.n_workers)
        with self._cond:
            self._inbox.pop(key, None)
        return [
            payloads[src]
            for src in range(self.n_workers)
            if payloads.get(src) is not None
        ]

    def allgather(self, tag, worker_id, obj):
        key = ("g", tag)
        with self._cond:
            self._inbox.setdefault(key, {})[worker_id] = obj
            self._cond.notify_all()
        # one frame per remote process, sent by each local worker for itself
        for p in range(self.n_processes):
            if p != self.process_id:
                ctx = self._frame_ctx(p, worker=worker_id)
                self._send(p, ("g", tag, worker_id, obj, ctx))
        payloads = self._wait(key, self.n_workers)
        out = [payloads[src] for src in range(self.n_workers)]
        with self._cond:
            self._gather_reads[key] = self._gather_reads.get(key, 0) + 1
            if self._gather_reads[key] >= self.threads:
                self._inbox.pop(key, None)
                self._gather_reads.pop(key, None)
        return out

    def barrier(self, worker_id: int):
        # barrier is a collective: every worker calls it the same number of
        # times, so a per-worker sequence number is a globally agreed tag
        # (a process-local counter shared by threads would diverge — the
        # threads of one process would race for tags; advisor finding r2)
        with self._cond:
            seq = self._barrier_seqs.get(worker_id, 0)
            self._barrier_seqs[worker_id] = seq + 1
        self.allgather(("b", seq), worker_id, None)

    # -- async plane (frontier-driven execution) ------------------------

    def supports_async(self) -> bool:
        return True

    def async_attach(self, worker_id: int, waker: Any) -> None:
        self._async_wakers[worker_id] = waker

    def _async_deliver_local(self, dest: int, event: tuple,
                             is_data: bool) -> None:
        # never blocks — backpressure is async_congested (see Comm)
        with self._cond:
            if self._broken is not None:
                raise RuntimeError(self._broken)
            self._async_q[dest].append(event)
            if is_data:
                self._async_data[dest] += 1
            self._cond.notify_all()
        waker = self._async_wakers.get(dest)
        if waker is not None:
            waker.set()

    def async_congested(self, worker_id: int) -> bool:
        # local thread-peers at the inbox bound, or an outbound pipeline
        # to a slow peer process at the writer-queue bound — both mean
        # "stop ingesting, let the backlog drain"
        if any(
            n >= self._async_bound
            for w, n in self._async_data.items()
            if w != worker_id
        ):
            return True
        return any(
            w.queue_depth() >= self._queue_frames
            for w in self._writers.values()
        )

    def async_post_exchange(self, worker_id, channel, time, buckets,
                            ingest_ns=None, seq=None, enq_ns=None):
        import time as time_mod  # the logical-time param shadows the module

        delivered = 0
        per_process: dict[int, dict[int, Any]] = {}
        for dst, payload in enumerate(buckets):
            if payload is None or dst == worker_id:
                continue
            p = self._process_of(dst)
            if p == self.process_id:
                self._async_deliver_local(
                    dst,
                    ("x", channel, time, worker_id, payload, ingest_ns, seq,
                     enq_ns),
                    is_data=True,
                )
                delivered += 1
            else:
                per_process.setdefault(p, {})[dst] = payload
        tracer = self._tracer
        for p, per_dst in per_process.items():
            ctx = self._frame_ctx(p, channel=channel, tick=time)
            t0 = time_mod.perf_counter_ns()
            # the async marker rides the frame metadata: same columnar
            # codec, same chaos gate (_post), different delivery side —
            # the enqueue stamp travels with the frame so the receiver's
            # drain can measure the enqueue->drain inbox dwell
            chunks, body_len = frames.encode_frame(
                ("a", channel, ingest_ns, seq, enq_ns), int(time), worker_id,
                per_dst, ctx,
            )
            with self._encode_lock:
                self.encode_ns += time_mod.perf_counter_ns() - t0
            if tracer is not None:
                tracer.complete(
                    "comm.encode", t0,
                    {"peer_process": p, "bytes": body_len, "channel": channel},
                )
            if self._post(p, [_LEN.pack(body_len)] + chunks, 8 + body_len):
                delivered += len(per_dst)
        return delivered

    def async_broadcast(self, worker_id, payload):
        for dst in self._local_workers:
            if dst != worker_id:
                self._async_deliver_local(
                    dst, ("c", worker_id, payload), is_data=False
                )
        for p in range(self.n_processes):
            if p != self.process_id:
                # rides the same chaos-gated _send as the BSP control
                # plane, so comm.send faults stay honest under async
                self._send(p, ("ac", worker_id, payload, None))

    def async_drain(self, worker_id: int) -> list:
        with self._cond:
            if self._broken is not None:
                raise RuntimeError(
                    f"process {self.process_id}: a peer worker failed: "
                    f"{self._broken}"
                )
            q = self._async_q[worker_id]
            out = list(q)
            q.clear()
            self._async_data[worker_id] = 0
            self._cond.notify_all()
        return out

    # -- serve plane (query scatter/gather) -----------------------------

    def supports_serve(self) -> bool:
        return True

    def serve_post(self, dst_worker, meta, payload):
        import time as time_mod

        p = self._process_of(dst_worker)
        if p == self.process_id:
            with self._cond:
                if self._broken is not None:
                    return False
                q = self._serve_q.get(dst_worker)
                if q is None or len(q) >= self._serve_bound:
                    self._serve_dropped += 1
                    return False
                q.append((meta, payload))
                self._cond.notify_all()
            return True
        ctx = self._frame_ctx(p, channel="serve")
        t0 = time_mod.perf_counter_ns()
        # serve events ride the same columnar codec and the same
        # chaos-gated _post as exchange frames (comm.send faults apply);
        # the ("s", meta) channel tag routes them into the serve inbox
        # on the receiving side instead of the rendezvous/async inboxes
        chunks, body_len = frames.encode_frame(
            ("s", meta), 0, self.process_id * self.threads,
            {dst_worker: payload}, ctx,
        )
        with self._encode_lock:
            self.encode_ns += time_mod.perf_counter_ns() - t0
        try:
            return self._post(p, [_LEN.pack(body_len)] + chunks, 8 + body_len)
        except (RuntimeError, OSError):
            # dead peer / torn mesh: a lost serve event degrades one
            # gather; the caller flags the shard missing
            return False

    def serve_recv(self, worker_id, timeout_s=None):
        with self._cond:
            if self._broken is not None:
                raise RuntimeError(
                    f"process {self.process_id}: a peer worker failed: "
                    f"{self._broken}"
                )
            q = self._serve_q[worker_id]
            if not q:
                self._cond.wait(timeout=timeout_s)
            if self._broken is not None:
                raise RuntimeError(
                    f"process {self.process_id}: a peer worker failed: "
                    f"{self._broken}"
                )
            out = list(q)
            q.clear()
        return out

    def _wait(self, key: Any, n: int) -> dict[int, Any]:
        deadline = time.monotonic() + self.collective_timeout_s
        with self._cond:
            while True:
                if self._broken:
                    # _break() notify_all'd this condition, so every blocked
                    # collective in the process unwinds in milliseconds —
                    # never waiting out the collective timeout
                    raise RuntimeError(
                        f"process {self.process_id}: a peer worker failed: "
                        f"{self._broken} (reference cross-worker panic "
                        "propagation, dataflow.rs:5674)"
                    )
                got = self._inbox.get(key)
                if got is not None and len(got) >= n:
                    return dict(got)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    missing = sorted(
                        set(range(self.n_workers)) - set(got or ())
                    )
                    raise RuntimeError(
                        f"process {self.process_id}: cluster collective "
                        f"timed out after {self.collective_timeout_s:.0f}s "
                        f"waiting on {key!r} (no contribution from workers "
                        f"{missing}; set PATHWAY_COLLECTIVE_TIMEOUT_S to "
                        "tune)"
                    )
                self._cond.wait(timeout=min(remaining, 1.0))

    @property
    def bytes_sent(self) -> int:
        return sum(w.bytes_sent for w in self._writers.values())

    @property
    def frames_sent(self) -> int:
        return sum(w.frames_sent for w in self._writers.values())

    def comm_stats(self) -> dict[str, float]:
        # inbox depth = frames delivered by peers but not yet consumed by
        # a local worker's collective — the exchange-queue backpressure
        # signal (a worker falling behind lets its inbox grow); send queue
        # depth = frames encoded but not yet on the wire (a slow PEER or
        # saturated link lets the writer queues grow until the
        # PATHWAY_COMM_QUEUE_FRAMES bound blocks the tick loop)
        bytes_sent = float(self.bytes_sent)
        return {
            "cluster_bytes_sent": bytes_sent,
            "cluster_frames_sent": float(self.frames_sent),
            "cluster_bytes_received": float(self.bytes_received),
            "cluster_frames_received": float(self.frames_received),
            "bytes_total": bytes_sent + float(self.bytes_received),
            "frames_coalesced_total": float(
                sum(w.frames_coalesced for w in self._writers.values())
            ),
            "send_queue_depth": float(
                sum(w.queue_depth() for w in self._writers.values())
            ),
            # the depth's denominator: PATHWAY_COMM_QUEUE_FRAMES per
            # outbound pipeline — depth/capacity is the saturation
            # fraction the autoscaler's scale-up rule watches
            "send_queue_capacity": float(
                self._queue_frames * max(1, len(self._writers))
            ),
            "encode_seconds_total": self.encode_ns / 1e9,
            "cluster_inbox_depth": float(len(self._inbox)),
            "cluster_broken": float(self._broken is not None),
            # frontier-driven plane: events delivered but not yet drained
            # by a local worker — the per-operator input-queue
            # backpressure signal of async execution
            "async_inbox_depth": float(
                sum(len(q) for q in self._async_q.values())
            ),
            "async_inbox_capacity": float(
                self._async_bound * max(1, len(self._async_q))
            ),
            # serve plane: query events delivered but not yet picked up
            # by a responder dispatcher, and events dropped at the bound
            "serve_inbox_depth": float(
                sum(len(q) for q in self._serve_q.values())
            ),
            "serve_dropped_total": float(self._serve_dropped),
        }

    def _break(self, reason: str) -> None:
        """Mark the mesh dead and wake EVERY waiter on the shared condition
        — the one notify_all that turns a 10-minute collective timeout into
        millisecond failure propagation."""
        first = False
        with self._cond:
            if self._broken is None:
                self._broken = reason
                first = True
            self._cond.notify_all()
        # async-plane parks wait on wake events, not the condition — set
        # them all so a frontier-driven loop sees the break immediately
        for waker in self._async_wakers.values():
            waker.set()
        if first:
            # black-box evidence: the crash bundle of a worker that died
            # *because a peer died* should name the peer, not look idle
            from ..observability.flightrecorder import get_recorder

            recorder = get_recorder()
            if recorder is not None:
                recorder.record(
                    "comm.broken", process=self.process_id, reason=reason
                )

    def abort(self) -> None:
        self._break(f"worker on process {self.process_id} failed")
        # peers unblock when their read loops see the closed sockets
        self._shutdown_sockets()

    def close(self) -> None:
        self._closing = True
        for p in list(self._socks):
            try:
                self._send(p, ("bye",))
            except (RuntimeError, OSError, KeyError):
                pass
        # drain the writer pipelines before tearing sockets down: queued
        # frames (including the byes) must reach peers still blocked in
        # their final collectives
        for w in self._writers.values():
            w.close()
        for w in self._writers.values():
            w.join(5.0)
        self._shutdown_sockets()

    def _shutdown_sockets(self) -> None:
        self._closing = True
        for s in self._socks.values():
            # shutdown() before close(): close() alone neither interrupts a
            # recv in flight on this socket nor sends the FIN that would
            # wake the PEER's reader — shutdown does both, which is what
            # makes failure propagation immediate instead of timeout-bound
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass


def _address_book(
    addresses: list[str] | None, n: int, host: str, first_port: int
) -> list[tuple[str, int]]:
    """Resolve per-process (host, port). ``addresses`` entries are
    ``host[:port]``; a bare host gets ``first_port + pid`` (so a hostfile of
    machine names works unchanged, like timely's)."""
    if addresses is None:
        return [(host, first_port + p) for p in range(n)]
    if len(addresses) != n:
        raise ValueError(
            f"address book lists {len(addresses)} hosts for {n} processes"
        )
    book: list[tuple[str, int]] = []
    for p, entry in enumerate(addresses):
        h, port = _parse_address(entry, first_port + p)
        book.append((h, port))
    return book


def _parse_address(entry: str, default_port: int) -> tuple[str, int]:
    """``host``, ``host:port``, ``[v6]:port``, or a bare IPv6 literal."""
    if entry.startswith("["):  # [::1]:port
        h, bracket, rest = entry[1:].partition("]")
        if not bracket or not h:
            raise ValueError(f"malformed address {entry!r}")
        if not rest:
            return h, default_port
        if not rest.startswith(":"):
            raise ValueError(f"malformed address {entry!r}")
        port_s = rest[1:]
    elif entry.count(":") > 1:  # bare IPv6 literal, no port
        return entry, default_port
    elif ":" in entry:
        h, _, port_s = entry.rpartition(":")
    else:
        return entry, default_port
    if not h:
        raise ValueError(f"address {entry!r} has an empty host")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(f"address {entry!r} has a non-numeric port") from None
    if not 0 < port < 65536:
        raise ValueError(f"address {entry!r} port out of range")
    return h, port


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    return bytes(_recv_into(sock, n))


#: frames up to this size recv into ONE preallocated buffer (every sane
#: exchange frame); past it, memory grows only as bytes actually arrive,
#: so a corrupt length prefix under the sanity cap can never OOM the
#: process with a giant zero-filled allocation
_RECV_PREALLOC_MAX = 64 << 20


def _recv_into(sock: socket.socket, n: int) -> bytearray:
    """Read exactly ``n`` bytes into one buffer — the recv buffer the
    columnar decoder's ``frombuffer`` arrays alias (a bytearray, so
    decoded columns stay ordinary writable arrays)."""
    if n <= _RECV_PREALLOC_MAX:
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            r = sock.recv_into(view[got:])
            if not r:
                raise EOFError("socket closed")
            got += r
        return buf
    # huge frame (or a garbage length that slipped the sanity bound):
    # grow with the data, one bounded scratch buffer at a time
    buf = bytearray()
    scratch = bytearray(_RECV_PREALLOC_MAX)
    sv = memoryview(scratch)
    remaining = n
    while remaining:
        r = sock.recv_into(sv[: min(_RECV_PREALLOC_MAX, remaining)])
        if not r:
            raise EOFError("socket closed")
        buf += sv[:r]
        remaining -= r
    return buf


def _corrupt_chunks(chunks: list) -> list:
    """Chaos ``corrupt`` action: keep the length prefix honest but flip
    bytes in the middle of the frame body — the peer's reader must
    detect the damage (CorruptFrame → named ``_broken``), never feed
    garbage into operator state."""
    prefix, body = chunks[0], bytearray().join(
        bytes(c) for c in chunks[1:]
    )
    # mangle the frame HEADER (tag byte onward): structural damage is
    # detected deterministically; a flip deep inside a raw float column
    # would be undetectable without per-column checksums
    for i in range(min(8, len(body))):
        body[i] ^= 0xA5
    return [prefix, bytes(body)]


