"""Worker communication backends for the sharded dataflow engine.

The reference exchanges records between timely workers through channel
allocators — ``thread`` (in-process), ``process`` (shared memory) and
``zero_copy`` (TCP) under
``external/timely-dataflow/communication/src/allocator/``. Here the same
roles are:

- :class:`LocalComm` — N worker threads in one process; exchange is direct
  in-memory handoff behind a barrier (the ``thread``/``process`` allocator
  analog; numpy batches make shared memory copies cheap).
- :class:`ClusterComm` (``parallel/cluster.py``) — full-mesh TCP between
  processes, pickled columnar frames (the ``zero_copy`` analog).
- :class:`MeshComm` (``parallel/meshcomm.py``) — wraps LocalComm; dense
  numeric columns of Exchange frames ride a ``bucketed_all_to_all`` XLA
  collective over a ``jax.sharding.Mesh`` (the ICI path,
  ``engine/mesh_exchange.py``); object columns fall back to the host path.
  Enabled by ``PATHWAY_MESH_EXCHANGE=1``.

The progress protocol degenerates to bulk-synchronous lock-step: every
worker sweeps the same node order for the same tick sequence, and every
exchange is a blocking all-to-all — so when a tick's sweep finishes on all
workers, that logical time is complete everywhere (the role of timely's
frontier tracking under a total order).
"""

from __future__ import annotations

import threading
from typing import Any, Sequence

__all__ = ["Comm", "LocalComm", "WorkerContext", "single_worker_context"]


class Comm:
    """Blocking collectives among ``n_workers`` equal participants."""

    n_workers: int

    def exchange(self, channel: int, tick: int, worker_id: int,
                 buckets: Sequence[Any]) -> list[Any]:
        """All-to-all: ``buckets[w]`` is this worker's payload destined for
        worker ``w`` (None = nothing). Returns the payloads every worker
        destined for *this* worker, in sender order. Blocks until all
        workers contributed to (channel, tick)."""
        raise NotImplementedError

    def allgather(self, tag: Any, worker_id: int, obj: Any) -> list[Any]:
        """Every worker contributes ``obj``; all receive the full list."""
        raise NotImplementedError

    def barrier(self, worker_id: int) -> None:
        raise NotImplementedError

    def abort(self) -> None:
        """Unblock peers waiting in a collective after a local failure."""

    def close(self) -> None:
        pass

    def comm_stats(self) -> dict[str, float]:
        """Backpressure/throughput gauges for the /metrics endpoint
        (rendered as ``pathway_comm_<key>``). Best-effort reads of live
        structures — no locks the data plane would contend on."""
        return {}


class LocalComm(Comm):
    """In-process comm for worker threads (timely ``thread`` allocator)."""

    def __init__(self, n_workers: int):
        self.n_workers = n_workers
        self._barrier = threading.Barrier(n_workers)
        self._lock = threading.Lock()
        self._slots: dict[Any, list] = {}
        # chaos site (comm.local): None unless a plan targets in-process
        # collectives — one None check per rendezvous when disarmed
        from ..chaos import injector as _chaos

        armed = _chaos.current()
        self._chaos = armed.local_faults() if armed is not None else None
        # tracing site: None unless span tracing is on — worker threads
        # share one tracer, so exchange flows link sender/receiver tick
        # spans across tids with deterministic ids (no context to ship)
        from ..internals.tracing import get_tracer, mint_flow_tag

        self._tracer = get_tracer()
        self._flow_tag = mint_flow_tag()

    def _flow_id(self, channel: int, tick: int, src: int, dst: int) -> str:
        from ..internals.tracing import make_flow_id

        return make_flow_id(
            self._tracer, self._flow_tag,
            f"x{channel}", f"t{tick}", f"{src}>{dst}",
        )

    def _rendezvous(self, key: Any, worker_id: int, payload: Any) -> list[Any]:
        if self._chaos is not None:
            payload = self._chaos.apply(worker_id, key, payload)
        try:
            with self._lock:
                slot = self._slots.setdefault(key, [None] * self.n_workers)
                slot[worker_id] = payload
            self._barrier.wait()
            out = self._slots[key]
            # second barrier before cleanup so no worker reads a reused slot
            self._barrier.wait()
        except threading.BrokenBarrierError:
            raise RuntimeError(
                "a peer worker failed — aborting this worker's dataflow "
                "(reference cross-worker panic propagation, dataflow.rs:5674)"
            ) from None
        with self._lock:
            self._slots.pop(key, None)
        return out

    def abort(self) -> None:
        """Break all barriers so peers blocked in a collective unwind
        instead of deadlocking (worker panic propagation)."""
        self._barrier.abort()

    def exchange(self, channel, tick, worker_id, buckets):
        """In-process all-to-all. Frames pass **by reference** — the
        returned payloads are the very objects peers deposited (asserted
        below): the thread allocator's contract is zero serialization,
        zero copies, so the columnar wire codec is only ever paid at a
        process boundary (ClusterComm)."""
        buckets = list(buckets)
        tracer = self._tracer
        if tracer is not None:
            # both ends compute the same deterministic id, so each sender's
            # tick span links to every receiver's — the in-process analog of
            # the frame trace context ClusterComm ships over TCP
            for dst, payload in enumerate(buckets):
                if dst != worker_id and payload is not None:
                    tracer.flow_start(
                        "comm.local",
                        self._flow_id(channel, tick, worker_id, dst),
                        channel=channel,
                        tick=tick,
                    )
        all_buckets = self._rendezvous(("x", channel, tick), worker_id, buckets)
        # no-serialization invariant: our own deposit must come back as
        # the identical list object (debug builds only; chaos 'drop' may
        # null the whole slot, which is the one lawful substitution)
        assert (
            all_buckets[worker_id] is None or all_buckets[worker_id] is buckets
        ), "LocalComm must pass frames by reference, never serialize"
        if tracer is not None:
            for src in range(self.n_workers):
                if (
                    src != worker_id
                    and all_buckets[src] is not None
                    and all_buckets[src][worker_id] is not None
                ):
                    tracer.flow_end(
                        "comm.local",
                        self._flow_id(channel, tick, src, worker_id),
                    )
        return [
            all_buckets[src][worker_id]
            for src in range(self.n_workers)
            # a whole-slot None is a chaos-dropped contribution (the
            # in-process analog of a lost frame): that worker's rows for
            # this tick silently vanish, exactly what the plan asked for
            if all_buckets[src] is not None
            and all_buckets[src][worker_id] is not None
        ]

    def allgather(self, tag, worker_id, obj):
        return list(self._rendezvous(("g", tag), worker_id, obj))

    def barrier(self, worker_id: int):
        self._barrier.wait()

    def comm_stats(self) -> dict[str, float]:
        # slots outstanding = collectives some worker entered but not all
        # left — a sustained nonzero depth means a straggler worker
        return {"pending_collectives": float(len(self._slots))}


class WorkerContext:
    """Identity + comm handle handed to each worker's Executor."""

    def __init__(self, worker_id: int, n_workers: int, comm: Comm | None):
        self.worker_id = worker_id
        self.n_workers = n_workers
        self.comm = comm

    @property
    def is_sharded(self) -> bool:
        return self.n_workers > 1


def single_worker_context() -> WorkerContext:
    return WorkerContext(0, 1, None)
