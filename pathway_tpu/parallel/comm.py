"""Worker communication backends for the sharded dataflow engine.

The reference exchanges records between timely workers through channel
allocators — ``thread`` (in-process), ``process`` (shared memory) and
``zero_copy`` (TCP) under
``external/timely-dataflow/communication/src/allocator/``. Here the same
roles are:

- :class:`LocalComm` — N worker threads in one process; exchange is direct
  in-memory handoff behind a barrier (the ``thread``/``process`` allocator
  analog; numpy batches make shared memory copies cheap).
- :class:`ClusterComm` (``parallel/cluster.py``) — full-mesh TCP between
  processes, pickled columnar frames (the ``zero_copy`` analog).
- :class:`MeshComm` (``parallel/meshcomm.py``) — wraps LocalComm; dense
  numeric columns of Exchange frames ride a ``bucketed_all_to_all`` XLA
  collective over a ``jax.sharding.Mesh`` (the ICI path,
  ``engine/mesh_exchange.py``); object columns fall back to the host path.
  Enabled by ``PATHWAY_MESH_EXCHANGE=1``.

Two progress protocols share these backends:

- **Bulk-synchronous lock-step** (``PATHWAY_ASYNC_EXEC=0``): every worker
  sweeps the same node order for the same tick sequence, and every
  exchange is a blocking all-to-all — when a tick's sweep finishes on
  all workers, that logical time is complete everywhere (the role of
  timely's frontier tracking under a total order).
- **Frontier-driven asynchronous execution** (the default for sharded
  streaming): exchanges become fire-and-forget *posts* into bounded
  per-worker inboxes (``async_post_exchange``/``async_drain``), workers
  advance on data availability, and consistency comes from frontier
  broadcasts riding the same wire (``async_broadcast``) — the
  timely/differential model proper (SURVEY §0/§2.5). The blocking
  collectives above remain in use for recovery replay, the END_TIME
  flush sweep, and ``PATHWAY_ASYNC_EXEC=0``.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Sequence

__all__ = ["Comm", "LocalComm", "WorkerContext", "single_worker_context"]

#: default bound of each worker's async DATA inbox, in posted batches;
#: the knob is PATHWAY_ASYNC_QUEUE_BATCHES. Posts themselves NEVER block
#: (two workers mid-sweep posting into each other's full inboxes would
#: deadlock) — the bound is enforced by the executor pausing its source
#: polls while any destination sits at it (Comm.async_congested locally,
#: peer-status inbox depths across processes), which keeps a fast
#: worker from buffering a slow peer's whole backlog in memory
ASYNC_QUEUE_BATCHES = 256


def async_queue_bound() -> int:
    from ..internals.config import _env_int

    return max(1, _env_int("PATHWAY_ASYNC_QUEUE_BATCHES", ASYNC_QUEUE_BATCHES))


class Comm:
    """Blocking collectives among ``n_workers`` equal participants."""

    n_workers: int

    def exchange(self, channel: int, tick: int, worker_id: int,
                 buckets: Sequence[Any]) -> list[Any]:
        """All-to-all: ``buckets[w]`` is this worker's payload destined for
        worker ``w`` (None = nothing). Returns the payloads every worker
        destined for *this* worker, in sender order. Blocks until all
        workers contributed to (channel, tick)."""
        raise NotImplementedError

    def allgather(self, tag: Any, worker_id: int, obj: Any) -> list[Any]:
        """Every worker contributes ``obj``; all receive the full list."""
        raise NotImplementedError

    def barrier(self, worker_id: int) -> None:
        raise NotImplementedError

    def abort(self) -> None:
        """Unblock peers waiting in a collective after a local failure."""

    def close(self) -> None:
        pass

    def comm_stats(self) -> dict[str, float]:
        """Backpressure/throughput gauges for the /metrics endpoint
        (rendered as ``pathway_comm_<key>``). Best-effort reads of live
        structures — no locks the data plane would contend on."""
        return {}

    # -- asynchronous (frontier-driven) plane ---------------------------
    #
    # Events are plain tuples:
    #   ("x", channel, time, src_worker, delta, ingest_ns, seq, enq_ns)
    #                                                           — data
    #   ("c", src_worker, payload)                              — control
    # ``enq_ns`` is the sender's wall-clock enqueue stamp (time_ns at
    # post): the receiver's drain measures the enqueue→drain inbox dwell
    # from it — the per-frame meta behind the commit-wave ``inbox_dwell``
    # phase (observability/critpath.py). Same-host clocks; the reader
    # clamps negatives so skew can only shrink a dwell, never fake one.
    # ``seq`` is the sender's per-post counter: the receiver dedupes
    # chaos-duplicated frames by (src, seq), the async analog of the BSP
    # rendezvous inbox where a duplicate overwrote its own slot. Control
    # broadcasts are never bounded (the progress protocol must not
    # deadlock behind the data it is trying to drain); data backpressure
    # is async_congested below.

    def supports_async(self) -> bool:
        return False

    def async_attach(self, worker_id: int, waker: Any) -> None:
        """Register ``worker_id``'s inbox + wake event (set on every
        delivery so the executor's idle park ends at data arrival)."""
        raise NotImplementedError

    def async_post_exchange(
        self, worker_id: int, channel: int, time: int,
        buckets: Sequence[Any], ingest_ns: "int | None" = None,
        seq: "int | None" = None, enq_ns: "int | None" = None,
    ) -> int:
        """Fire-and-forget exchange: ``buckets[w]`` goes to worker ``w``'s
        async inbox (None/own slot skipped). Never waits for peers.
        Returns the number of data events that WILL be delivered — chaos
        ``drop``/``sever`` actions lose events here, and the quiesce
        ledger (sent/received totals) must account them as never-sent or
        it can never balance again (a wedged termination)."""
        raise NotImplementedError

    def async_broadcast(self, worker_id: int, payload: Any) -> None:
        """Deliver a control event to every OTHER worker's inbox."""
        raise NotImplementedError

    def async_drain(self, worker_id: int) -> list:
        """Everything delivered to ``worker_id`` since the last drain, in
        arrival order. Raises RuntimeError once the mesh is broken —
        the async path's failure-propagation hook."""
        raise NotImplementedError

    def async_congested(self, worker_id: int) -> bool:
        """True when some destination's data backlog sits at the
        PATHWAY_ASYNC_QUEUE_BATCHES bound. Posts themselves never block
        (two workers mid-sweep posting to each other's full inboxes
        would deadlock); instead the executor checks this BEFORE polling
        its sources — ingestion pauses, queued work drains, and the
        backlog stays bounded by what was already in flight."""
        return False

    # -- serve plane (query scatter/gather, pathway_tpu/serve/) ---------
    #
    # A THIRD seam beside the BSP collectives and the async exchange
    # plane: serve queries are fire-and-forget posts with correlation
    # ids, no tick to wait for — but they must NOT ride the async
    # exchange inboxes, whose sent/received totals feed the quiesce
    # ledger (a query event in that ledger could wedge termination).
    # Events are (meta, payload) pairs; meta is a small picklable tuple
    # (the serve router's protocol), payload is whatever the columnar
    # wire codec can carry. Posts never block: a full serve inbox DROPS
    # the event and returns False (the gather's partial-result timeout
    # is the recovery path, same as a lost frame).

    def supports_serve(self) -> bool:
        return False

    def serve_post(self, dst_worker: int, meta: tuple, payload: Any) -> bool:
        """Deliver one serve event to ``dst_worker``'s serve inbox.
        Returns False when the event was dropped (bounded inbox full,
        broken mesh, dead peer) — never raises, never blocks."""
        raise NotImplementedError

    def serve_recv(
        self, worker_id: int, timeout_s: float | None = None
    ) -> list:
        """Block up to ``timeout_s`` for serve events addressed to
        ``worker_id``; returns them in arrival order (possibly empty on
        timeout). Raises RuntimeError once the mesh is broken so
        dispatcher threads unwind instead of spinning."""
        raise NotImplementedError


def serve_queue_bound() -> int:
    from ..internals.config import _env_int

    return max(1, _env_int("PATHWAY_SERVE_QUEUE_BOUND", 256))


class LocalComm(Comm):
    """In-process comm for worker threads (timely ``thread`` allocator)."""

    def __init__(self, n_workers: int):
        self.n_workers = n_workers
        self._barrier = threading.Barrier(n_workers)
        self._lock = threading.Lock()
        self._slots: dict[Any, list] = {}
        # chaos site (comm.local): None unless a plan targets in-process
        # collectives — one None check per rendezvous when disarmed
        from ..chaos import injector as _chaos

        armed = _chaos.current()
        self._chaos = armed.local_faults() if armed is not None else None
        # tracing site: None unless span tracing is on — worker threads
        # share one tracer, so exchange flows link sender/receiver tick
        # spans across tids with deterministic ids (no context to ship)
        from ..internals.tracing import get_tracer, mint_flow_tag

        self._tracer = get_tracer()
        self._flow_tag = mint_flow_tag()

    def _flow_id(self, channel: int, tick: int, src: int, dst: int) -> str:
        from ..internals.tracing import make_flow_id

        return make_flow_id(
            self._tracer, self._flow_tag,
            f"x{channel}", f"t{tick}", f"{src}>{dst}",
        )

    def _rendezvous(self, key: Any, worker_id: int, payload: Any) -> list[Any]:
        if self._chaos is not None:
            payload = self._chaos.apply(worker_id, key, payload)
        try:
            with self._lock:
                slot = self._slots.setdefault(key, [None] * self.n_workers)
                slot[worker_id] = payload
            self._barrier.wait()
            out = self._slots[key]
            # second barrier before cleanup so no worker reads a reused slot
            self._barrier.wait()
        except threading.BrokenBarrierError:
            raise RuntimeError(
                "a peer worker failed — aborting this worker's dataflow "
                "(reference cross-worker panic propagation, dataflow.rs:5674)"
            ) from None
        with self._lock:
            self._slots.pop(key, None)
        return out

    def abort(self) -> None:
        """Break all barriers so peers blocked in a collective unwind
        instead of deadlocking (worker panic propagation) — and poison
        the async plane so drains/posts raise instead of parking."""
        self._barrier.abort()
        msg = (
            "a peer worker failed — aborting this worker's "
            "dataflow (cross-worker panic propagation)"
        )
        st = self._async_state()
        if st is not None:
            with st["cond"]:
                if st["broken"] is None:
                    st["broken"] = msg
                st["cond"].notify_all()
            for waker in st["wakers"].values():
                waker.set()
        sv = getattr(self, "_serve", None)
        if sv is not None:
            with sv["cond"]:
                if sv["broken"] is None:
                    sv["broken"] = msg
                sv["cond"].notify_all()

    def exchange(self, channel, tick, worker_id, buckets):
        """In-process all-to-all. Frames pass **by reference** — the
        returned payloads are the very objects peers deposited (asserted
        below): the thread allocator's contract is zero serialization,
        zero copies, so the columnar wire codec is only ever paid at a
        process boundary (ClusterComm)."""
        buckets = list(buckets)
        tracer = self._tracer
        if tracer is not None:
            # both ends compute the same deterministic id, so each sender's
            # tick span links to every receiver's — the in-process analog of
            # the frame trace context ClusterComm ships over TCP
            for dst, payload in enumerate(buckets):
                if dst != worker_id and payload is not None:
                    tracer.flow_start(
                        "comm.local",
                        self._flow_id(channel, tick, worker_id, dst),
                        channel=channel,
                        tick=tick,
                    )
        all_buckets = self._rendezvous(("x", channel, tick), worker_id, buckets)
        # no-serialization invariant: our own deposit must come back as
        # the identical list object (debug builds only; chaos 'drop' may
        # null the whole slot, which is the one lawful substitution)
        assert (
            all_buckets[worker_id] is None or all_buckets[worker_id] is buckets
        ), "LocalComm must pass frames by reference, never serialize"
        if tracer is not None:
            for src in range(self.n_workers):
                if (
                    src != worker_id
                    and all_buckets[src] is not None
                    and all_buckets[src][worker_id] is not None
                ):
                    tracer.flow_end(
                        "comm.local",
                        self._flow_id(channel, tick, src, worker_id),
                    )
        return [
            all_buckets[src][worker_id]
            for src in range(self.n_workers)
            # a whole-slot None is a chaos-dropped contribution (the
            # in-process analog of a lost frame): that worker's rows for
            # this tick silently vanish, exactly what the plan asked for
            if all_buckets[src] is not None
            and all_buckets[src][worker_id] is not None
        ]

    def allgather(self, tag, worker_id, obj):
        return list(self._rendezvous(("g", tag), worker_id, obj))

    def barrier(self, worker_id: int):
        self._barrier.wait()

    # -- async plane (frontier-driven execution) ------------------------

    def supports_async(self) -> bool:
        return True

    def _async_state(self):
        # lazy (BSP runs never pay for the structures), created under the
        # slot lock so concurrent workers agree on ONE state dict
        st = getattr(self, "_async", None)
        if st is None:
            with self._lock:
                st = getattr(self, "_async", None)
                if st is None:
                    st = self._async = {
                        "cond": threading.Condition(),
                        "q": {
                            w: collections.deque()
                            for w in range(self.n_workers)
                        },
                        "data": {w: 0 for w in range(self.n_workers)},
                        "wakers": {},
                        "broken": None,
                        "bound": async_queue_bound(),
                    }
        return st

    def async_attach(self, worker_id: int, waker: Any) -> None:
        self._async_state()["wakers"][worker_id] = waker

    def _async_deliver(self, dest: int, event: tuple, is_data: bool) -> None:
        # never blocks: backpressure is the executor's async_congested
        # check before source polls (a blocking post here could deadlock
        # two workers mid-sweep posting into each other's full inboxes)
        st = self._async_state()
        with st["cond"]:
            if st["broken"] is not None:
                raise RuntimeError(st["broken"])
            st["q"][dest].append(event)
            if is_data:
                st["data"][dest] += 1
            st["cond"].notify_all()
        waker = st["wakers"].get(dest)
        if waker is not None:
            waker.set()

    def async_congested(self, worker_id: int) -> bool:
        st = self._async_state()
        return any(
            n >= st["bound"] for w, n in st["data"].items() if w != worker_id
        )

    def async_post_exchange(self, worker_id, channel, time, buckets,
                            ingest_ns=None, seq=None, enq_ns=None):
        if self._chaos is not None:
            # the comm.local chaos site stays live on the async data
            # plane: 'drop' vanishes this worker's rows for this post —
            # reported as 0 delivered so the quiesce ledger stays honest
            buckets = self._chaos.apply(
                worker_id, ("x", channel, time), list(buckets)
            )
            if buckets is None:
                return 0
        delivered = 0
        for dest, payload in enumerate(buckets):
            if payload is None or dest == worker_id:
                continue
            self._async_deliver(
                dest,
                ("x", channel, time, worker_id, payload, ingest_ns, seq,
                 enq_ns),
                is_data=True,
            )
            delivered += 1
        return delivered

    def async_broadcast(self, worker_id, payload):
        for dest in range(self.n_workers):
            if dest != worker_id:
                self._async_deliver(
                    dest, ("c", worker_id, payload), is_data=False
                )

    def async_drain(self, worker_id: int) -> list:
        st = self._async_state()
        with st["cond"]:
            if st["broken"] is not None:
                raise RuntimeError(st["broken"])
            q = st["q"][worker_id]
            out = list(q)
            q.clear()
            st["data"][worker_id] = 0
            st["cond"].notify_all()
        return out

    # -- serve plane ----------------------------------------------------

    def supports_serve(self) -> bool:
        return True

    def _serve_state(self):
        # lazy like _async_state: pipelines that never serve pay nothing
        sv = getattr(self, "_serve", None)
        if sv is None:
            with self._lock:
                sv = getattr(self, "_serve", None)
                if sv is None:
                    sv = self._serve = {
                        "cond": threading.Condition(),
                        "q": {
                            w: collections.deque()
                            for w in range(self.n_workers)
                        },
                        "dropped": 0,
                        "broken": None,
                        "bound": serve_queue_bound(),
                    }
        return sv

    def serve_post(self, dst_worker, meta, payload):
        sv = self._serve_state()
        with sv["cond"]:
            if sv["broken"] is not None:
                return False
            q = sv["q"].get(dst_worker)
            if q is None or len(q) >= sv["bound"]:
                sv["dropped"] += 1
                return False
            q.append((meta, payload))
            sv["cond"].notify_all()
        return True

    def serve_recv(self, worker_id, timeout_s=None):
        sv = self._serve_state()
        with sv["cond"]:
            if sv["broken"] is not None:
                raise RuntimeError(sv["broken"])
            q = sv["q"][worker_id]
            if not q:
                sv["cond"].wait(timeout=timeout_s)
            if sv["broken"] is not None:
                raise RuntimeError(sv["broken"])
            out = list(q)
            q.clear()
        return out

    def comm_stats(self) -> dict[str, float]:
        # slots outstanding = collectives some worker entered but not all
        # left — a sustained nonzero depth means a straggler worker
        out = {"pending_collectives": float(len(self._slots))}
        st = getattr(self, "_async", None)
        if st is not None:
            out["async_inbox_depth"] = float(
                sum(len(q) for q in st["q"].values())
            )
            out["async_inbox_capacity"] = float(
                st["bound"] * self.n_workers
            )
        sv = getattr(self, "_serve", None)
        if sv is not None:
            out["serve_inbox_depth"] = float(
                sum(len(q) for q in sv["q"].values())
            )
            out["serve_dropped_total"] = float(sv["dropped"])
        return out


class WorkerContext:
    """Identity + comm handle handed to each worker's Executor."""

    def __init__(self, worker_id: int, n_workers: int, comm: Comm | None):
        self.worker_id = worker_id
        self.n_workers = n_workers
        self.comm = comm
        #: set by the executor while the frontier-driven streaming loop is
        #: live (parallel/asyncplane.AsyncPlane); None = blocking BSP
        #: collectives (batch mode, recovery replay, END_TIME flush,
        #: PATHWAY_ASYNC_EXEC=0). Exchange nodes consult this per call.
        self.async_plane: Any = None

    @property
    def is_sharded(self) -> bool:
        return self.n_workers > 1


def single_worker_context() -> WorkerContext:
    return WorkerContext(0, 1, None)
