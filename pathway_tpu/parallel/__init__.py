"""Multi-device distribution layer.

Re-designs the reference's worker/exchange model (timely workers + hash
sharding, SURVEY §2.9) onto ``jax.sharding``: a Mesh replaces the worker
pool; record exchange by key becomes a bucketed all-to-all over ICI; dense
model/index state shards with NamedSharding annotations.
"""

from .distributed import global_mesh, init_from_env
from .exchange import bucketed_all_to_all, shard_rows
from .mesh import data_model_mesh, make_mesh

__all__ = [
    "make_mesh",
    "data_model_mesh",
    "shard_rows",
    "bucketed_all_to_all",
    "init_from_env",
    "global_mesh",
]
