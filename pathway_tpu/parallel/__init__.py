"""Multi-device distribution layer.

Re-designs the reference's worker/exchange model (timely workers + hash
sharding, SURVEY §2.9) onto ``jax.sharding``: a Mesh replaces the worker
pool; record exchange by key becomes a bucketed all-to-all over ICI; dense
model/index state shards with NamedSharding annotations.

Submodule attributes resolve lazily: the host comm path (``comm.py``,
``cluster.py``) must be importable without pulling jax — eager jax import
added ~3s of startup to every spawned worker process.
"""

from typing import Any

__all__ = [
    "make_mesh",
    "data_model_mesh",
    "shard_rows",
    "bucketed_all_to_all",
    "init_from_env",
    "global_mesh",
]

_LAZY = {
    "make_mesh": "mesh",
    "data_model_mesh": "mesh",
    "shard_rows": "exchange",
    "bucketed_all_to_all": "exchange",
    "init_from_env": "distributed",
    "global_mesh": "distributed",
}


def __getattr__(name: str) -> Any:
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
