"""Per-worker hub of the frontier-driven execution mode.

One :class:`AsyncPlane` is owned by each worker's executor while the
asynchronous sharded streaming loop is live (``ctx.async_plane``). It
glues three things together:

- the **data plane**: Exchange nodes call :meth:`post` (fire-and-forget
  bucket delivery through ``Comm.async_post_exchange``) and
  :meth:`take` (arrivals queued for their channel — delivered eagerly
  on arrival, the timely model where *data* moves asynchronously and
  only *notifications* follow the frontier);
- the **progress protocol**: :meth:`drain` files incoming events,
  merges peer frontier broadcasts into the
  :class:`~pathway_tpu.engine.frontier.FrontierTracker`, and keeps the
  latest per-peer status document (finished/stop flags, commit-wave
  state, quiesce votes);
- **observability**: arrival-queue latency is accumulated as the REAL
  ``exchange wait`` (time rows sat queued between arrival and
  delivery), replacing the BSP artifact where Exchange time measured
  blocked-in-collective peers.

The plane is deliberately thin — protocol *decisions* live in the
executor loop and the pure components (``engine/frontier.py``), so they
stay unit-testable without threads or sockets.
"""

from __future__ import annotations

import collections
import threading
import time as _time
from typing import Any

from ..engine.frontier import FrontierTracker

__all__ = ["AsyncPlane"]


def _min_opt(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


class AsyncPlane:
    def __init__(self, comm: Any, worker_id: int, n_workers: int):
        self.comm = comm
        self.worker_id = worker_id
        self.n_workers = n_workers
        self.tracker = FrontierTracker(n_workers, worker_id)
        self.waker = threading.Event()
        comm.async_attach(worker_id, self.waker)
        #: channel -> deque[(time, delta, ingest_ns, recv_perf_ns,
        #: route_ns, dwell0_ns)] — route_ns is the sender-side
        #: ingest→post latency carried in the frame meta, dwell0_ns the
        #: enqueue→drain inbox dwell measured on arrival; take() adds the
        #: drain→delivery queue wait to complete the dwell
        self._arrivals: dict[int, collections.deque] = {}
        self._arrivals_pending = 0
        #: running min of queued arrivals' ingest stamps, maintained on
        #: append and invalidated only when the minimum itself departs —
        #: pending_ingest_ns() is asked before every sweep, and a full
        #: rescan of a held backlog would go quadratic over commit-wave
        #: settles
        self._ingest_min: int | None = None
        self._ingest_min_dirty = False
        #: hold boundary during a commit wave: arrivals with time > hold
        #: stay queued (they belong to the NEXT commit window and must
        #: not enter operator state before this wave's snapshot)
        self.hold_above: int | None = None
        #: latest status document per peer worker (merged by drain)
        self.peer_status: dict[int, dict] = {}
        #: phase -> [(src, vote payload)] — quiesce votes awaiting their
        #: consumer (see drain)
        self._votes: dict[str, list] = {}
        #: per-post sequence (rides data events; receivers dedup by it)
        self._post_seq = 0
        #: src worker -> highest data seq seen (chaos-duplicate dedup —
        #: the async analog of the BSP rendezvous slot overwrite)
        self._seen_seq: dict[int, int] = {}
        from .comm import async_queue_bound

        self._queue_bound = async_queue_bound()
        #: quiesce counters: data events posted / delivered-to-operators
        self.sent_events = 0
        self.recv_events = 0
        #: activity marker consumed by quiesce voting (any post or take)
        self.activity = False
        #: ingest stamp of the CURRENT local sweep (set by the executor's
        #: _tick so Exchange posts forward the origin's stamp, keeping the
        #: ingest→emit histogram honest across workers)
        self.cur_ingest_ns: int | None = None
        # wait accounting: ns arrivals spent queued before delivery —
        # the genuine per-operator exchange wait of the async mode
        self.arrival_wait_ns = 0
        #: cumulative enqueue→drain→delivery dwell across ALL arrivals
        #: (commit waves read deltas of this for the inbox_dwell phase)
        self.dwell_total_ns = 0
        #: (ingest_ns, route_ns, dwell_ns) of the OLDEST arrival taken
        #: during the current sweep — the stamps behind the staged
        #: ingest→emit decomposition; _tick resets it per sweep
        self.sweep_oldest: "tuple[int, int, int] | None" = None
        self.last_broadcast = 0.0

    # -- data plane ------------------------------------------------------

    def post(self, channel: int, time: int, buckets: list) -> int:
        """Route ``buckets`` to peers (own slot is the caller's business).
        Returns the number of data events that will be delivered.

        The sent counter records what the comm layer says will actually
        arrive — a chaos drop is 0, so the quiesce ledger (global sent ==
        received) still balances after injected row loss; a duplicated
        frame is deduped receiver-side by ``seq``, so it stays 1."""
        n = sum(
            1 for i, b in enumerate(buckets)
            if b is not None and i != self.worker_id
        )
        if not n:
            return 0
        seq = self._post_seq
        self._post_seq += 1
        delivered = self.comm.async_post_exchange(
            self.worker_id, channel, time, buckets, self.cur_ingest_ns, seq,
            _time.time_ns(),
        )
        self.sent_events += delivered
        self.activity = True
        return delivered

    def take(self, channel: int) -> tuple[list, "int | None"]:
        """Arrivals released for delivery on ``channel`` (respecting the
        commit-wave hold) -> (deltas, oldest ingest stamp)."""
        q = self._arrivals.get(channel)
        if not q:
            return [], None
        out: list = []
        ingest: int | None = None
        hold = self.hold_above
        now = _time.perf_counter_ns()
        while q:
            t, delta, ing, recv_ns, route_ns, dwell0_ns = q[0]
            if hold is not None and t > hold:
                break  # FIFO per sender; later entries are >= t anyway
            q.popleft()
            out.append(delta)
            ingest = _min_opt(ingest, ing)
            if ing is not None and ing == self._ingest_min:
                self._ingest_min_dirty = True  # the minimum departed
            wait_ns = now - recv_ns
            self.arrival_wait_ns += wait_ns
            dwell_ns = dwell0_ns + wait_ns
            self.dwell_total_ns += dwell_ns
            if ing is not None and (
                self.sweep_oldest is None or ing < self.sweep_oldest[0]
            ):
                self.sweep_oldest = (ing, route_ns, dwell_ns)
            self.recv_events += 1
            self._arrivals_pending -= 1
        if out:
            self.activity = True
        return out, ingest

    def releasable(self) -> bool:
        hold = self.hold_above
        if hold is None:
            return self._arrivals_pending > 0
        return any(q and q[0][0] <= hold for q in self._arrivals.values())

    def pending_ingest_ns(self) -> "int | None":
        """Oldest ingest stamp among queued arrivals (sweep stamping).
        O(1) from the running min unless the minimum was consumed since
        the last query (then one rescan of what remains queued)."""
        if self._arrivals_pending == 0:
            self._ingest_min = None
            self._ingest_min_dirty = False
            return None
        if self._ingest_min_dirty:
            out: int | None = None
            for q in self._arrivals.values():
                for item in q:
                    out = _min_opt(out, item[2])
            self._ingest_min = out
            self._ingest_min_dirty = False
        return self._ingest_min

    # -- control plane ---------------------------------------------------

    def drain(self) -> bool:
        """Pull everything the comm delivered since the last drain; file
        data arrivals, merge statuses/frontiers. Raises when the mesh is
        broken (failure propagation). Returns True if anything arrived."""
        events = self.comm.async_drain(self.worker_id)
        if not events:
            return False
        now_ns = _time.perf_counter_ns()
        now_wall = _time.time_ns()
        now = _time.monotonic()
        for ev in events:
            if ev[0] == "x":
                _, channel, t, src, delta, ingest_ns, seq = ev[:7]
                enq_ns = ev[7] if len(ev) > 7 else None
                if seq is not None:
                    # FIFO per sender link: a seq at or below the highest
                    # seen is a chaos-duplicated frame — drop the copy
                    if seq <= self._seen_seq.get(src, -1):
                        continue
                    self._seen_seq[src] = seq
                # frame-meta stamps: sender-side ingest→post (route) and
                # post→drain inbox dwell, both wall-clock and clamped so
                # cross-process skew can only shrink them
                route_ns = dwell0_ns = 0
                if enq_ns is not None:
                    dwell0_ns = max(0, now_wall - enq_ns)
                    if ingest_ns is not None:
                        route_ns = max(0, enq_ns - ingest_ns)
                self._arrivals.setdefault(
                    channel, collections.deque()
                ).append(
                    (t, delta, ingest_ns, now_ns, route_ns, dwell0_ns)
                )
                self._arrivals_pending += 1
                if ingest_ns is not None and (
                    self._ingest_min is None or ingest_ns < self._ingest_min
                ):
                    self._ingest_min = ingest_ns
            else:
                _, src, payload = ev
                cur = self.peer_status.setdefault(src, {})
                cur.update(payload)
                f = payload.get("f")
                if f is not None:
                    self.tracker.observe(src, f, now=now)
                v = payload.get("vote")
                if v is not None:
                    # votes must not overwrite each other (a peer can cast
                    # two rounds between my drains) and must survive being
                    # delivered while a DIFFERENT phase is consuming — a
                    # per-phase log holds every vote until its consumer
                    # takes it (commit-wave settle vs termination)
                    self._votes.setdefault(v[0], []).append((src, tuple(v)))
        return True

    def take_votes(self, phase: str) -> list:
        """Unconsumed peer votes for ``phase`` (quiesce protocol)."""
        return self._votes.pop(phase, [])

    def broadcast_status(self, payload: dict, min_interval_s: float = 0.0,
                        ) -> bool:
        """Broadcast this worker's status document (frontier piggybacked
        under ``"f"``), throttled to ``min_interval_s``. Forced when the
        interval is 0."""
        now = _time.monotonic()
        if min_interval_s and now - self.last_broadcast < min_interval_s:
            return False
        payload = dict(payload)
        payload["f"] = self.tracker.local()
        # inbox depth rides every status: remote senders consult it in
        # congested() — the cross-process half of the async queue bound
        # (in-process depth is visible directly; a reader thread must
        # never block, so the remote bound is this advisory loop)
        payload["q"] = self._arrivals_pending
        self.comm.async_broadcast(self.worker_id, payload)
        # own status is merged locally so protocol code can treat
        # peer_status[worker_id] uniformly
        self.peer_status.setdefault(self.worker_id, {}).update(payload)
        self.last_broadcast = now
        return True

    def congested(self) -> bool:
        """Should this worker pause ingesting? True when any destination
        sits at the PATHWAY_ASYNC_QUEUE_BATCHES bound — same-process
        inboxes and outbound pipelines via the comm's direct view, remote
        workers via the inbox depth their status broadcasts carry
        (advisory: stale by at most a frontier-cadence interval, so the
        effective remote bound is the knob plus one broadcast window)."""
        if self.comm.async_congested(self.worker_id):
            return True
        return any(
            st.get("q", 0) >= self._queue_bound
            for w, st in self.peer_status.items()
            if w != self.worker_id
        )

    def take_activity(self) -> bool:
        a = self.activity
        self.activity = False
        return a

    def stats(self) -> dict[str, float]:
        return {
            "arrivals_pending": float(self._arrivals_pending),
            "sent_events": float(self.sent_events),
            "recv_events": float(self.recv_events),
            "arrival_wait_ms": self.arrival_wait_ns / 1e6,
            "dwell_total_ms": self.dwell_total_ns / 1e6,
            "frontier": float(self.tracker.local()),
            "global_frontier": float(self.tracker.global_frontier()),
        }
