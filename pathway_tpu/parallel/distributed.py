"""Multi-host initialization (the DCN control plane).

The reference clusters over TCP with ``PATHWAY_PROCESSES``/``PROCESS_ID``/
``FIRST_PORT`` (``dataflow/config.rs:70-86``); here the same environment
bootstraps ``jax.distributed`` so a multi-host mesh spans all processes —
collectives then ride ICI within a pod and DCN across pods, with the host
side (connectors, persistence, progress) staying per-process exactly like
the reference workers.
"""

from __future__ import annotations

import os

import jax

__all__ = ["init_from_env", "global_mesh"]

_initialized = False


def init_from_env(coordinator_host: str = "127.0.0.1") -> None:
    """Initialize jax.distributed from PATHWAY_* env (idempotent; no-op for
    single-process runs). Launch with ``pathway-tpu spawn -n M ...``."""
    global _initialized
    if _initialized:
        return
    from ..internals.config import get_pathway_config

    cfg = get_pathway_config()
    if cfg.processes <= 1:
        _initialized = True
        return
    # default coordinator port offset: first_port itself belongs to the
    # ClusterComm TCP mesh listeners
    coordinator = os.environ.get(
        "PATHWAY_COORDINATOR", f"{coordinator_host}:{cfg.first_port + 1000}"
    )
    from ..internals.jax_compat import enable_cpu_collectives

    # XLA's default CPU client refuses multiprocess computations; jaxlib
    # ships gloo TCP collectives for exactly this case — arm them before
    # the distributed client is created (no-op on TPU/GPU)
    enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=cfg.processes,
        process_id=cfg.process_id,
    )
    _initialized = True


def global_mesh(axes: dict[str, int] | None = None):
    """Mesh over every device of every participating process."""
    from .mesh import make_mesh

    init_from_env()
    return make_mesh(axes)
