"""pathway_tpu — a TPU-native stream-processing / live-data framework.

A ground-up re-design of the capabilities of Pathway (reference mounted at
/root/reference): declarative Table/expression API over an incremental
dataflow engine, built on JAX/XLA for dense compute with host-side
arrangements for irregular state. See SURVEY.md for the layer map.

Import as ``import pathway_tpu as pw`` — the API surface mirrors
``python/pathway/__init__.py``.
"""

from __future__ import annotations

import os as _os

if _os.environ.get("PATHWAY_PROCESSES", "1") not in ("", "0", "1") and _os.environ.get(
    "PATHWAY_MESH_EXCHANGE", ""
).strip().lower() in ("1", "true", "yes", "on"):
    # multiprocess mesh child: gloo CPU collectives must be armed BEFORE
    # the first jax backend client exists (XLA's default CPU client
    # refuses multiprocess computations; the config knob is read at
    # client creation, so doing it at mesh-establishment time is too
    # late). Plain TCP-cluster children never run jax collectives and
    # skip the multi-second jax import at startup.
    from .internals.jax_compat import enable_cpu_collectives as _ecc

    _ecc()
    del _ecc

from . import reducers, udfs
from .internals import dtype as _dt
from .internals.custom_reducers import BaseCustomAccumulator
from .internals.expression import (
    ColumnExpression,
    ColumnReference,
    apply,
    apply_async,
    apply_with_type,
    cast,
    coalesce,
    declare_type,
    fill_error,
    if_else,
    make_tuple,
    require,
    unwrap,
)
from .internals.json import Json
from .internals.error_log_table import global_error_log, local_error_log
from .internals.py_object_wrapper import PyObjectWrapper
from .internals.parse_graph import G, Universe
from .internals.run import MonitoringLevel, request_stop, run, run_all
from .internals.sql import sql
from .internals.config import PathwayConfig, get_pathway_config
from .internals.yaml_loader import load_yaml
from .internals.schema import (
    Schema,
    SchemaProperties,
    assert_table_has_schema,
    column_definition,
    schema_builder,
    schema_from_dict,
    schema_from_types,
)
from .internals.table import (
    Table,
    TableLike,
    groupby,
    join,
    join_inner,
    join_left,
    join_outer,
    join_right,
)
from .internals.groupbys import GroupedTable
from .internals.joins import Joinable, JoinMode, JoinResult
from .internals.thisclass import left, right, this
from .udfs import UDF, udf, udf_async

from . import debug, demo, io, persistence, stdlib, universes  # noqa: E402
from .stdlib import graphs, indexing, ml, ordered, stateful, statistical, temporal, utils, viz  # noqa: E402

__version__ = "0.1.0"


class Type:
    """Engine-level type tags (reference ``PathwayType``)."""

    ANY = _dt.ANY
    STRING = _dt.STR
    INT = _dt.INT
    BOOL = _dt.BOOL
    FLOAT = _dt.FLOAT
    POINTER = _dt.POINTER
    DATE_TIME_NAIVE = _dt.DATE_TIME_NAIVE
    DATE_TIME_UTC = _dt.DATE_TIME_UTC
    DURATION = _dt.DURATION
    ARRAY = _dt.Array()
    JSON = _dt.JSON
    BYTES = _dt.BYTES


Pointer = _dt.Pointer  # pointer typehint (engine keys are 64-bit ints)
DateTimeNaive = _dt.DATE_TIME_NAIVE
DateTimeUtc = _dt.DATE_TIME_UTC
Duration = _dt.DURATION


from .internals.iterate import iterate, iterate_universe  # noqa: E402
from .internals.interactive import (  # noqa: E402
    LiveTable,
    enable_interactive_mode,
    is_interactive_mode_enabled,
)
from .stdlib.utils.async_transformer import AsyncTransformer  # noqa: E402
from .internals.row_transformer import (  # noqa: E402
    ClassArg,
    attribute,
    input_attribute,
    input_method,
    method,
    output_attribute,
    transformer,
)


from .analysis import analyze  # noqa: E402


def set_license_key(key: str | None) -> None:  # compatibility no-op
    pass


def set_monitoring_config(*args, **kwargs) -> None:
    pass


__all__ = [
    "AsyncTransformer",
    "BaseCustomAccumulator",
    "ClassArg",
    "ColumnExpression",
    "ColumnReference",
    "GroupedTable",
    "JoinMode",
    "JoinResult",
    "Joinable",
    "Json",
    "MonitoringLevel",
    "Pointer",
    "Schema",
    "SchemaProperties",
    "Table",
    "TableLike",
    "Type",
    "UDF",
    "Universe",
    "analyze",
    "apply",
    "apply_async",
    "attribute",
    "apply_with_type",
    "assert_table_has_schema",
    "cast",
    "coalesce",
    "column_definition",
    "debug",
    "declare_type",
    "demo",
    "fill_error",
    "global_error_log",
    "local_error_log",
    "PyObjectWrapper",
    "graphs",
    "groupby",
    "if_else",
    "indexing",
    "input_attribute",
    "input_method",
    "io",
    "iterate",
    "iterate_universe",
    "LiveTable",
    "enable_interactive_mode",
    "is_interactive_mode_enabled",
    "join",
    "method",
    "output_attribute",
    "transformer",
    "join_inner",
    "join_left",
    "join_outer",
    "join_right",
    "left",
    "make_tuple",
    "ml",
    "ordered",
    "persistence",
    "reducers",
    "require",
    "right",
    "request_stop",
    "run",
    "run_all",
    "schema_builder",
    "sql",
    "universes",
    "viz",
    "PathwayConfig",
    "get_pathway_config",
    "load_yaml",
    "schema_from_dict",
    "schema_from_types",
    "stateful",
    "statistical",
    "stdlib",
    "temporal",
    "this",
    "udf",
    "udf_async",
    "udfs",
    "unwrap",
    "utils",
]
