"""The offline state resharder behind ``pathway-tpu rescale``.

Checkpoint resharding for a streaming engine — the analog of resharding
model checkpoints across device meshes in JAX training stacks: persisted
operator state is hash-sharded by worker count (``keys.shard_of``, the
reference SHARD_MASK routing), so changing the cluster size means
repartitioning every keyed container and every live input chunk.

Protocol (all phases traced as ``rescale.*`` spans, each boundary a
``rescale`` chaos site):

1. **plan** — read the ``cluster`` marker, every worker's newest
   metadata, and pick the snapshot time ``T``: the newest
   operator-snapshot time present on EVERY worker (the same choice
   recovery makes). Falls back to full-tail replay (``T = -1``) only
   when no input chunk was ever truncated.
2. **stage** — for each stateful-operator rank, read the N per-worker
   state pieces, ``split_state`` each by destination key-shard,
   ``merge_states`` per destination, and write M complete
   ``worker-{j}/`` namespaces (operator blobs at time ``T``, one input
   chunk holding the post-``T`` tail rows routed by ``shard_rows``, a
   single metadata version) under ``rescale-tmp/``.
3. **promote** — copy the staged keys to the next epoch's namespaces
   (fresh keys: the old layout is never touched), then rewrite the
   ``cluster`` marker in ONE put. The marker write is the commit point:
   a crash at any earlier moment leaves the old marker pointing at the
   old, intact layout.
4. **cleanup** — delete the staging keys and the superseded layout.

Offset carry-over: per-source offsets are unioned across workers and the
union is replicated into every destination's metadata (the post-rescale
owner — source index mod M — is not derivable from a pid name offline,
so every candidate owner must find the offset). The union is exact for
state written by this engine: each source's offset is recorded only by
its owner worker (``Executor._recover`` hands ``begin_recording`` the
owned subset) and — via the delivery-boundary close protocol — never
covers input that was not recorded. When copies conflict (legacy layouts
that recorded every source everywhere, or replicas left by a previous
rescale that a worker never overwrote with a commit), the LARGEST offset
under a structural numeric-aware order wins: offsets advance
monotonically and only on the owner, so the max copy IS the owner's and
exactly covers the recorded input — a smaller stale copy would
re-deliver rows already incorporated into the snapshot/tail.
"""

from __future__ import annotations

import json
import pickle
import sys
import time as _time
from typing import Any, Callable

import numpy as np

from ..internals.tracing import span as _span
from ..persistence import layout as _layout
from ..persistence.backends import (
    PersistenceBackend,
    PrefixBackend,
    open_backend,
)
from ..persistence.snapshots import (
    MetadataAccessor,
    OperatorSnapshots,
    SnapshotReader,
    _delta_parts,
)

__all__ = ["rescale", "stats", "RescaleError", "NoClusterMarker"]


class RescaleError(RuntimeError):
    pass


class NoClusterMarker(RescaleError):
    """The store has no cluster marker: nothing was ever persisted, so
    there is nothing to rescale. Consumers that can proceed without
    state (the autoscale controller: the next generation simply boots at
    the target count and writes the marker) catch THIS, not a message
    substring."""


#: process-local counters surfaced as ``pathway_rescale_total`` /
#: ``pathway_rescale_duration_seconds`` on /metrics (observability/hub.py)
_STATS: dict[str, Any] = {"total": 0, "duration_s": 0.0, "last": None}


def stats() -> dict[str, Any]:
    return dict(_STATS)


def _default_log(msg: str) -> None:
    print(f"[rescale] {msg}", file=sys.stderr)


def rescale(
    backend: Any, to_workers: int, *,
    log: Callable[[str], Any] | None = None, dry_run: bool = False,
) -> dict:
    """Repartition the persisted state in ``backend`` to ``to_workers``
    workers. ``backend`` is a ``PersistenceBackend`` instance or a
    ``pw.persistence.Backend`` descriptor. Returns a report dict.

    ``dry_run`` stops after the plan phase: the report carries the
    per-operator split/merge plan (rank, class, reshard mode, source
    chunk counts) and nothing — not even staging keys — is written."""
    log = log or _default_log
    t0 = _time.monotonic()
    close_after = False
    if isinstance(backend, PersistenceBackend):
        root = backend
    else:
        root = open_backend(backend)
        close_after = True
    try:
        report = _rescale_root(root, int(to_workers), log, dry_run=dry_run)
    finally:
        if close_after:
            root.close()
    dt = _time.monotonic() - t0
    report["duration_s"] = round(dt, 6)
    if not report.get("noop") and not dry_run:
        _STATS["total"] += 1
        _STATS["duration_s"] += dt
        _STATS["last"] = report
    return report


def _worker_view(root: PersistenceBackend, ns: str) -> PersistenceBackend:
    return PrefixBackend(root, ns) if ns else root


def _node_class(name: str):
    """Resolve a snapshot descriptor's operator class name against every
    loaded ``Node`` subclass (engine operators, iterate/external-index
    composites, stateful io scanners)."""
    from ..engine import external_index as _ei  # noqa: F401
    from ..engine import iterate as _it  # noqa: F401
    from ..engine import operators as _ops  # noqa: F401
    from ..engine.executor import Node

    for mod in ("deltalake", "_object_scanner", "sqlite", "airbyte"):
        try:  # stateful scanner sources; dep-gated modules may be absent
            __import__(f"pathway_tpu.io.{mod}")
        except Exception:
            pass
    stack = [Node]
    while stack:
        c = stack.pop()
        if c.__name__ == name:
            return c
        stack.extend(c.__subclasses__())
    raise RescaleError(
        f"persisted snapshot names stateful operator class {name!r}, which "
        "this build does not define — cannot reshard its state"
    )


def _offset_sort_key(off: Any):
    """Deterministic structural total order over offset states, with
    NUMBERS compared numerically — a lexicographic JSON comparison would
    rank {"rows": 1000} below {"rows": 999}. Larger key = later resume
    position."""
    if isinstance(off, bool):
        return ("b", off)
    if isinstance(off, (int, float)):
        return ("n", off)
    if isinstance(off, str):
        return ("s", off)
    if isinstance(off, (list, tuple)):
        return ("l", tuple(_offset_sort_key(v) for v in off))
    if isinstance(off, dict):
        return (
            "d",
            tuple(
                (k, _offset_sort_key(v)) for k, v in sorted(off.items())
            ),
        )
    return ("x", repr(off))


def _merge_offsets(metas: list[dict], log: Callable[[str], Any]) -> dict:
    merged: dict = {}
    conflicts: set[str] = set()
    for m in metas:
        for pid, off in (m.get("offsets") or {}).items():
            if off is None:
                continue
            if pid not in merged:
                merged[pid] = off
            elif merged[pid] != off:
                conflicts.add(pid)
                if _offset_sort_key(off) > _offset_sort_key(merged[pid]):
                    merged[pid] = off
    if conflicts:
        log(
            f"offset conflict for source(s) {sorted(conflicts)}: kept the "
            "LARGEST offset — a source's offset advances monotonically and "
            "only on its owner worker, so the max copy is the owner's, "
            "which exactly covers the recorded input (a smaller stale copy "
            "would re-deliver rows already in the snapshot/tail)"
        )
    return merged


def _pick_snapshot_time(metas: list[dict]) -> int:
    snap_sets = [
        {int(e["time"]) for e in (m.get("op_snapshots") or [])} for m in metas
    ]
    if all(not s for s in snap_sets):
        return -1
    common = set.intersection(*snap_sets)
    if common:
        return max(common)
    # no common snapshot (a crash mid-commit-wave with retention 1):
    # full-tail replay is sound only while no chunk was ever truncated
    if any(int(m.get("first_chunk", 0)) > 0 for m in metas):
        raise RescaleError(
            "no operator-snapshot time is common to every worker and the "
            "input history was already truncated — boot once with the "
            "original worker count (recovery will re-establish a common "
            "snapshot), then rescale"
        )
    return -1


def _op_chunk_bytes(view: PersistenceBackend, rank: int, desc: dict) -> int:
    """Size of one operator's persisted snapshot (stat-only where the
    backend can): resident AND spilled state — the spill tier
    materializes into snapshots, so this is the full per-operator state
    footprint the target workers must absorb."""
    from ..persistence.snapshots import OperatorSnapshots

    total = 0
    at = int(desc.get("at", desc.get("time", 0)))
    for c in range(int(desc["chunks"])):
        try:
            total += view.size_of(OperatorSnapshots._key(rank, at, c))
        except (OSError, KeyError):
            pass  # chunk pruned mid-report; keep the estimate partial
    return total


def _dry_run_report(
    report: dict, metas: list[dict], snap_time: int,
    n_from: int, to_workers: int, views: list[PersistenceBackend],
) -> dict:
    """Fill the plan-only report: per-operator split/merge actions by
    reshard mode, per-operator persisted state bytes (so an operator can
    size the target worker count before committing), plus the input-tail
    chunks each worker would replay.

    Refuses exactly what the real run refuses (per-worker operator-count
    mismatch): a dry run that prints a confident plan for a store the
    real rescale would reject defeats its preview purpose."""
    from ..persistence.manager import MANIFEST_KEY

    # the store's fingerprint manifest (graph/manifest, written at boot):
    # lets the report name operators by structural identity, not just
    # rank — the same identities `pathway-tpu upgrade --plan` prints
    ident_by_rank: dict[int, dict] = {}
    try:
        manifest = json.loads(views[0].get_value(MANIFEST_KEY))
        for e in manifest.get("stateful", []):
            ident_by_rank[int(e["rank"])] = e
    except Exception:
        pass  # pre-manifest store: rows render without identities
    ops_plan: list[dict] = []
    if snap_time >= 0:
        entries = [
            next(
                e["ops"] for e in (m.get("op_snapshots") or [])
                if int(e["time"]) == snap_time
            )
            for m in metas
        ]
        rank_counts = {len(e) for e in entries}
        if len(rank_counts) > 1:
            raise RescaleError(
                f"workers disagree on the stateful-operator count at "
                f"snapshot time {snap_time}: {sorted(rank_counts)} — the "
                "dataflow changed between workers?"
            )
        n_ranks = max(len(e) for e in entries)
        for rank in range(n_ranks):
            descs = [e.get(str(rank)) or e.get(rank) for e in entries]
            present = [d for d in descs if d is not None]
            cls_name = present[0]["cls"] if present else "?"
            try:
                mode = getattr(_node_class(cls_name), "RESHARD", "keyed")
            except RescaleError:
                mode = "unresolved"
            action = {
                "keyed": (
                    f"split {n_from} piece(s) by key shard, merge into "
                    f"{to_workers} worker(s)"
                ),
                "pinned": "keep worker-0 piece (single-owner composite)",
                "replicate": (
                    f"field-wise union replicated to all {to_workers} "
                    "worker(s)"
                ),
            }.get(mode, f"cannot plan (mode {mode})")
            bytes_per_source = [
                _op_chunk_bytes(views[i], rank, d) if d is not None else None
                for i, d in enumerate(descs)
            ]
            ident = ident_by_rank.get(rank, {})
            ops_plan.append({
                "rank": rank,
                "cls": cls_name,
                "fingerprint": ident.get("fingerprint"),
                "name": ident.get("name"),
                "mode": mode,
                "action": action,
                "chunks_per_source": [
                    int(d["chunks"]) if d is not None else None
                    for d in descs
                ],
                "state_bytes_per_source": bytes_per_source,
                "state_bytes": sum(b or 0 for b in bytes_per_source),
            })
    report["ranks"] = len(ops_plan)
    report["operators"] = ops_plan
    report["state_bytes_total"] = sum(o["state_bytes"] for o in ops_plan)
    report["tail_chunks_per_source"] = [
        max(0, int(m.get("n_chunks", 0)) - int(m.get("first_chunk", 0)))
        for m in metas
    ]
    report["dry_run"] = True
    return report


def _rescale_root(
    root: PersistenceBackend, to_workers: int, log: Callable[[str], Any],
    dry_run: bool = False,
) -> dict:
    from ..chaos import injector as _chaos

    try:
        # the canonical routing hash (identical to the live exchange's)
        from ..parallel.exchange import shard_rows
    except ImportError:
        # parallel.exchange needs jax.shard_map; shard_rows is a pure
        # delegation to the key shard — fall back on hosts without it
        from ..engine.keys import shard_of as shard_rows

    if to_workers < 1:
        raise RescaleError(f"cannot rescale to {to_workers} workers")
    armed = _chaos.current()
    fault = armed.rescale_faults() if armed is not None else None

    def fire(phase: str) -> None:
        if fault is not None:
            fault.fire(phase)

    marker = _layout.read_marker(root)
    if marker is None:
        raise NoClusterMarker(
            f"no cluster marker at {root.describe()}: nothing to rescale"
        )
    n_from, epoch = marker
    report: dict[str, Any] = {
        "from": n_from, "to": to_workers, "snapshot_time": None,
        "ranks": 0, "tail_entries": 0, "epoch": epoch,
    }
    if n_from == to_workers:
        report["noop"] = True
        return report

    with _span("rescale.plan", from_workers=n_from, to_workers=to_workers):
        views: list[PersistenceBackend] = []
        metas: list[dict] = []
        missing: list[int] = []
        for i in range(n_from):
            ns = _layout.worker_namespace(epoch, n_from, i)
            view = _worker_view(root, ns)
            views.append(view)
            cur = MetadataAccessor(view).current
            if cur is None:
                missing.append(i)
            metas.append(cur or {})
        if len(missing) == n_from:
            # marker without any committed state: adopt the new count
            # (a dry run must not write even this)
            if not dry_run:
                _layout.write_marker(root, to_workers, epoch)
            report["noop"] = True
            return report
        if missing:
            raise RescaleError(
                f"worker(s) {missing} have no committed metadata while "
                "others do — the store is torn mid-first-commit; boot with "
                f"the original count ({n_from}) once, then rescale"
            )
        snap_time = _pick_snapshot_time(metas)
        report["snapshot_time"] = snap_time
    if dry_run:
        # plan only: name what the real run WOULD do per operator, write
        # nothing (no staging keys, no marker, no chaos protocol)
        return _dry_run_report(
            report, metas, snap_time, n_from, to_workers, views
        )
    fire("plan")

    # stale staging from a previously crashed attempt is garbage — clear it
    for key in root.list_keys():
        if key.startswith(_layout.STAGING_PREFIX):
            root.remove_key(key)

    new_epoch = epoch + 1
    staged = [
        _worker_view(
            root,
            _layout.STAGING_PREFIX
            + _layout.worker_namespace(new_epoch, to_workers, j),
        )
        for j in range(to_workers)
    ]

    def mask_for(j: int):
        def mask(keys: np.ndarray) -> np.ndarray:
            return shard_rows(np.asarray(keys, dtype=np.uint64), to_workers) == j

        return mask

    fire("stage")
    ops_per_dest: list[dict] = [{} for _ in range(to_workers)]
    if snap_time >= 0:
        entries = []
        for i, m in enumerate(metas):
            entry = next(
                (
                    e for e in m["op_snapshots"]
                    if int(e["time"]) == snap_time
                ),
                None,
            )
            assert entry is not None  # snap_time came from the intersection
            entries.append(entry["ops"])
        n_ranks = {len(e) for e in entries}
        if len(n_ranks) != 1:
            raise RescaleError(
                f"workers disagree on the stateful-operator count at "
                f"snapshot time {snap_time}: {sorted(n_ranks)} — the "
                "dataflow changed between workers?"
            )
        report["ranks"] = n_ranks = n_ranks.pop()
        with _span("rescale.operators", ranks=n_ranks, at=snap_time):
            for rank in range(n_ranks):
                descs = [
                    e.get(str(rank)) or e.get(rank) for e in entries
                ]
                if any(d is None for d in descs):
                    raise RescaleError(
                        f"operator snapshot is missing rank {rank} on some "
                        "worker"
                    )
                cls_names = {d["cls"] for d in descs}
                if len(cls_names) != 1:
                    raise RescaleError(
                        f"rank {rank} names different operator classes "
                        f"across workers: {sorted(cls_names)}"
                    )
                cls = _node_class(descs[0]["cls"])
                from ..persistence.snapshots import read_op_state

                pieces = [
                    read_op_state(OperatorSnapshots(view), rank, d, cls)
                    for view, d in zip(views, descs)
                ]
                for j in range(to_workers):
                    mask = mask_for(j)
                    merged = cls.merge_states(
                        [cls.split_state(p, mask) for p in pieces]
                    )
                    n_chunks = OperatorSnapshots(staged[j]).write(
                        rank, snap_time, merged
                    )
                    ops_per_dest[j][str(rank)] = {
                        "cls": descs[0]["cls"],
                        "at": snap_time,
                        "chunks": n_chunks,
                    }

    # live input tail: rows recorded after the chosen snapshot, re-routed
    # to their destination shard by row key (the same hash the exchange
    # uses, so replay re-enters the dataflow exactly as live rows would)
    tails: list[list] = [[] for _ in range(to_workers)]
    with _span("rescale.chunks", after=snap_time):
        for view, m in zip(views, metas):
            reader = SnapshotReader(
                view, int(m.get("n_chunks", 0)), int(m.get("first_chunk", 0))
            )
            for t, pid, delta in reader.batches(after_time=snap_time):
                shards = shard_rows(delta.keys, to_workers)
                for j in range(to_workers):
                    ix = np.flatnonzero(shards == j)
                    if len(ix):
                        tails[j].append(
                            (t, pid, _delta_parts(delta.take(ix)))
                        )
        for j in range(to_workers):
            tails[j].sort(key=lambda e: e[0])  # stable: commit order kept
        report["tail_entries"] = sum(len(t) for t in tails)

    offsets = _merge_offsets(metas, log)
    last_time = max(int(m.get("last_time", -1)) for m in metas)
    for j in range(to_workers):
        if tails[j]:
            staged[j].put_value(
                "chunks/chunk-00000000",
                pickle.dumps(tails[j], protocol=pickle.HIGHEST_PROTOCOL),
            )
        meta = {
            "last_time": last_time,
            "n_chunks": 1 if tails[j] else 0,
            "first_chunk": 0,
            "chunk_spans": (
                {"0": max(int(e[0]) for e in tails[j])} if tails[j] else {}
            ),
            "offsets": offsets,
            "n_workers": to_workers,
            "op_snapshots": (
                [{"time": snap_time, "ops": ops_per_dest[j]}]
                if snap_time >= 0
                else []
            ),
        }
        staged[j].put_value("meta/meta-00000000", json.dumps(meta).encode())

    # carry the output plane's ack cursors (io/delivery.py delivery/<sink>
    # keys): sinks gather to worker 0 in every layout, so destination
    # worker 0 inherits each sink's cursor — dropping them would reset the
    # recovery floor to -1 and re-deliver the whole replayed tail after
    # every rescale (duplicate external output). If several source workers
    # carry a cursor for one sink (residue of an older layout), the
    # highest acked_time wins — cursors only ever advance, on the single
    # delivering worker, exactly like offsets.
    delivery_cursors: dict[str, tuple[int, bytes]] = {}
    for view in views:
        for key in view.list_keys():
            if not key.startswith("delivery/"):
                continue
            blob = view.get_value(key)
            try:
                acked = int(json.loads(blob).get("acked_time", -1))
            except (ValueError, TypeError):
                continue  # torn cursor: the other copies (if any) win
            cur = delivery_cursors.get(key)
            if cur is None or acked > cur[0]:
                delivery_cursors[key] = (acked, blob)
    for key, (_acked, blob) in sorted(delivery_cursors.items()):
        staged[0].put_value(key, blob)
    if delivery_cursors:
        report["delivery_cursors"] = len(delivery_cursors)

    # carry the graph's fingerprint manifest: the dataflow is unchanged
    # by a rescale, and `pathway-tpu upgrade --plan` must keep working on
    # the new layout before its first boot rewrites the manifest
    from ..persistence.manager import MANIFEST_KEY

    for view in views:
        try:
            staged[0].put_value(MANIFEST_KEY, view.get_value(MANIFEST_KEY))
            break
        except (KeyError, FileNotFoundError):
            continue

    fire("copy")
    staged_keys = [
        k for k in root.list_keys() if k.startswith(_layout.STAGING_PREFIX)
    ]
    with _span("rescale.promote", staged_keys=len(staged_keys)):
        # leftovers of a crashed attempt under the target epoch would
        # otherwise survive next to the fresh copy as unreferenced orphans
        tgt = _layout.epoch_prefix(new_epoch)
        for key in root.list_keys():
            if tgt and key.startswith(tgt):
                root.remove_key(key)
        for key in staged_keys:
            root.put_value(
                key[len(_layout.STAGING_PREFIX):], root.get_value(key)
            )
        fire("promote")
        # THE commit point: one atomic marker rewrite flips the cluster to
        # the new layout; everything before this line left the old layout
        # untouched
        _layout.write_marker(root, to_workers, new_epoch)
    fire("cleanup")
    # sweep staging plus EVERY superseded layout — including orphans a
    # previously crashed cleanup left behind (epochs older than the one
    # just promoted)
    tgt = _layout.epoch_prefix(new_epoch)
    for key in root.list_keys():
        if key == _layout.MARKER_KEY or key.startswith(tgt):
            continue
        if key.startswith(
            (_layout.STAGING_PREFIX, _layout.UPGRADE_STAGING_PREFIX)
        ) or key.startswith(
            ("epoch-", "meta/", "chunks/", "ops/", "worker-", "delivery/",
             "graph/")
        ):
            root.remove_key(key)
    report["epoch"] = new_epoch
    log(
        f"rescaled {n_from} -> {to_workers} workers at {root.describe()} "
        f"(snapshot time {snap_time}, {report['ranks']} stateful operator"
        f"(s), {report['tail_entries']} tail entries, epoch {new_epoch})"
    )
    return report
