"""Elastic rescaling — repartition persisted cluster state N → M workers.

``pathway-tpu rescale --to M <store>`` (or an elastic boot via
``spawn --supervise --elastic -n M`` / ``PATHWAY_ELASTIC=1``) runs the
offline resharder in :mod:`resharder`: it opens every ``worker-{i}/``
namespace of the persisted layout, picks the newest operator-snapshot
time common to all workers, splits each stateful operator's state and
each live input chunk by row key with the engine's own ``shard_rows``
hash, merges the per-destination pieces, and writes a complete layout
for M workers under the next epoch's namespaces — staged under
``rescale-tmp/`` and promoted by one atomic ``cluster``-marker rewrite.
"""

from .resharder import NoClusterMarker, RescaleError, rescale, stats

__all__ = ["rescale", "stats", "RescaleError", "NoClusterMarker"]
