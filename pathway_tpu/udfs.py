"""``pw.udfs`` / ``@pw.udf`` — user-defined functions over columns.

Re-design of ``python/pathway/internals/udfs/`` (``__init__.py:68-461``):
sync and async UDFs with optional caching and retry policies. Async UDFs are
gathered per batch on an event loop (the reference ships rows to a Python
event loop via ``async_apply_table``, graph.rs:744).
"""

from __future__ import annotations

import asyncio
import functools
import time as _time
import typing
from typing import Any, Callable

from .internals import dtype as dt
from .internals.expression import ApplyExpression, AsyncApplyExpression

__all__ = [
    "UDF",
    "udf",
    "udf_async",
    "CacheStrategy",
    "InMemoryCache",
    "DiskCache",
    "DefaultCache",
    "AsyncRetryStrategy",
    "ExponentialBackoffRetryStrategy",
    "FixedDelayRetryStrategy",
    "NoRetryStrategy",
    "async_executor",
    "coerce_async",
    "with_cache_strategy",
    "with_retry_strategy",
    "with_capacity",
    "with_timeout",
]


class CacheStrategy:
    def wrap(self, fn: Callable) -> Callable:
        return fn


class InMemoryCache(CacheStrategy):
    """Memoize UDF results in process memory (reference caches.py:23-91)."""

    def wrap(self, fn: Callable) -> Callable:
        cache: dict = {}
        if asyncio.iscoroutinefunction(fn):
            @functools.wraps(fn)
            async def awrapper(*args):
                key = args
                if key not in cache:
                    # cache the TASK, not the value: concurrent async calls
                    # for one key must coalesce into a single execution
                    # (reference caches.py in-flight dedup)
                    cache[key] = asyncio.ensure_future(fn(*args))
                try:
                    return await cache[key]
                except BaseException:
                    cache.pop(key, None)  # do not cache failures
                    raise

            return awrapper

        @functools.wraps(fn)
        def wrapper(*args):
            key = args
            if key not in cache:
                cache[key] = fn(*args)
            return cache[key]

        return wrapper


class DiskCache(CacheStrategy):
    """Persist UDF results on disk (reference uses diskcache; here a simple
    shelve-backed store under PATHWAY_PERSISTENT_STORAGE)."""

    def __init__(self, name: str | None = None):
        self._name = name

    #: one open shelf per path per process: gdbm holds an exclusive lock, so
    #: a second wrap() of the same cache (engine restart in-process, two
    #: UDFs sharing a name) must reuse the handle instead of re-opening
    _open_stores: dict[str, Any] = {}

    def wrap(self, fn: Callable) -> Callable:
        import hashlib
        import os
        import pickle
        import shelve

        root = os.environ.get("PATHWAY_PERSISTENT_STORAGE", "/tmp/pathway_tpu_cache")
        os.makedirs(root, exist_ok=True)
        path = os.path.join(root, self._name or fn.__name__)
        store = DiskCache._open_stores.get(path)
        if store is None:
            store = shelve.open(path)
            DiskCache._open_stores[path] = store

        # the function identity is part of the key: two UDFs resolving to
        # the same store path (shared __name__, no explicit cache name)
        # must never serve each other's results; the line number separates
        # same-scope lambdas, and is stable across restarts of one source
        code = getattr(fn, "__code__", None)
        fn_id = (
            getattr(fn, "__module__", ""),
            getattr(fn, "__qualname__", ""),
            getattr(code, "co_firstlineno", 0),
        )

        def key_of(args):
            return hashlib.blake2b(
                pickle.dumps((fn_id, args)), digest_size=16
            ).hexdigest()

        if asyncio.iscoroutinefunction(fn):
            @functools.wraps(fn)
            async def awrapper(*args):
                k = key_of(args)
                if k not in store:
                    store[k] = await fn(*args)
                    store.sync()  # durable without close (process may be killed)
                return store[k]

            return awrapper

        @functools.wraps(fn)
        def wrapper(*args):
            k = key_of(args)
            if k not in store:
                store[k] = fn(*args)
                store.sync()  # durable without close (process may be killed)
            return store[k]

        return wrapper


class DefaultCache(DiskCache):
    pass


class AsyncRetryStrategy:
    async def invoke(self, fn: Callable, *args, **kwargs):
        return await fn(*args, **kwargs)


class NoRetryStrategy(AsyncRetryStrategy):
    pass


class FixedDelayRetryStrategy(AsyncRetryStrategy):
    def __init__(self, max_retries: int = 3, delay_ms: int = 1000):
        self._max_retries = max_retries
        self._delay = delay_ms / 1000

    async def invoke(self, fn: Callable, *args, **kwargs):
        last: Exception | None = None
        for attempt in range(self._max_retries):
            try:
                return await fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 — retry everything like the reference
                last = e
                if attempt + 1 < self._max_retries:
                    await asyncio.sleep(self._next_delay(attempt))
        assert last is not None
        raise last

    def _next_delay(self, attempt: int) -> float:
        return self._delay


class ExponentialBackoffRetryStrategy(FixedDelayRetryStrategy):
    def __init__(self, max_retries: int = 3, initial_delay: int = 1000, backoff_factor: float = 2.0):
        super().__init__(max_retries, initial_delay)
        self._factor = backoff_factor

    def _next_delay(self, attempt: int) -> float:
        return self._delay * self._factor**attempt


def coerce_async(fn: Callable) -> Callable:
    if asyncio.iscoroutinefunction(fn):
        return fn

    @functools.wraps(fn)
    async def wrapper(*args, **kwargs):
        return fn(*args, **kwargs)

    return wrapper


def with_cache_strategy(fn: Callable, cache_strategy: CacheStrategy) -> Callable:
    return cache_strategy.wrap(fn)


def with_retry_strategy(fn: Callable, retry_strategy: AsyncRetryStrategy) -> Callable:
    fn = coerce_async(fn)

    @functools.wraps(fn)
    async def wrapper(*args, **kwargs):
        return await retry_strategy.invoke(fn, *args, **kwargs)

    return wrapper


def with_capacity(fn: Callable, capacity: int) -> Callable:
    fn = coerce_async(fn)
    semaphore = asyncio.Semaphore(capacity)

    @functools.wraps(fn)
    async def wrapper(*args, **kwargs):
        async with semaphore:
            return await fn(*args, **kwargs)

    return wrapper


def with_timeout(fn: Callable, timeout: float) -> Callable:
    fn = coerce_async(fn)

    @functools.wraps(fn)
    async def wrapper(*args, **kwargs):
        return await asyncio.wait_for(fn(*args, **kwargs), timeout=timeout)

    return wrapper


class Executor:
    pass


class AutoExecutor(Executor):
    pass


class AsyncExecutor(Executor):
    def __init__(self, capacity: int | None = None, timeout: float | None = None,
                 retry_strategy: AsyncRetryStrategy | None = None):
        self.capacity = capacity
        self.timeout = timeout
        self.retry_strategy = retry_strategy


def async_executor(capacity: int | None = None, timeout: float | None = None,
                   retry_strategy: AsyncRetryStrategy | None = None) -> AsyncExecutor:
    return AsyncExecutor(capacity, timeout, retry_strategy)


class UDF:
    """Base class for user-defined functions (reference udfs/__init__.py:68).

    Subclass and override ``__wrapped__``, or use the ``@pw.udf`` decorator.
    """

    def __init__(
        self,
        *,
        return_type: Any = None,
        propagate_none: bool = False,
        deterministic: bool = False,
        executor: Executor | None = None,
        cache_strategy: CacheStrategy | None = None,
    ):
        self._return_type = return_type
        self._propagate_none = propagate_none
        self._deterministic = deterministic
        self._executor = executor
        self._cache_strategy = cache_strategy

    def __wrapped__(self, *args, **kwargs):
        raise NotImplementedError

    def _prepare(self) -> Callable:
        fn = self.__wrapped__
        if self._cache_strategy is not None:
            fn = self._cache_strategy.wrap(fn)
        if isinstance(self._executor, AsyncExecutor):
            ex = self._executor
            if ex.retry_strategy is not None:
                fn = with_retry_strategy(fn, ex.retry_strategy)
            if ex.timeout is not None:
                fn = with_timeout(fn, ex.timeout)
            if ex.capacity is not None:
                fn = with_capacity(fn, ex.capacity)
        return fn

    def _ret_type(self) -> Any:
        if self._return_type is not None:
            return self._return_type
        hints = typing.get_type_hints(self.__wrapped__)
        return hints.get("return", dt.ANY)

    def __call__(self, *args: Any, **kwargs: Any):
        fn = self._prepare()
        if asyncio.iscoroutinefunction(self.__wrapped__) or isinstance(self._executor, AsyncExecutor):
            return AsyncApplyExpression(
                coerce_async(fn), self._ret_type(), args, kwargs,
                propagate_none=self._propagate_none,
                deterministic=self._deterministic,
            )
        return ApplyExpression(
            fn, self._ret_type(), args, kwargs,
            propagate_none=self._propagate_none,
            deterministic=self._deterministic,
        )


class _FunctionUDF(UDF):
    def __init__(self, fn: Callable, **kwargs: Any):
        super().__init__(**kwargs)
        self._fn = fn
        self.__name__ = getattr(fn, "__name__", "udf")
        self.__doc__ = getattr(fn, "__doc__", None)

    @property
    def __wrapped__(self):  # type: ignore[override]
        return self._fn

    def func(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def udf(
    fn: Callable | None = None,
    /,
    *,
    return_type: Any = None,
    propagate_none: bool = False,
    deterministic: bool = False,
    executor: Executor | None = None,
    cache_strategy: CacheStrategy | None = None,
):
    """Decorator turning a python function into a column UDF."""

    def wrap(f: Callable) -> _FunctionUDF:
        return _FunctionUDF(
            f,
            return_type=return_type,
            propagate_none=propagate_none,
            deterministic=deterministic,
            executor=executor,
            cache_strategy=cache_strategy,
        )

    if fn is None:
        return wrap
    return wrap(fn)


def udf_async(fn: Callable | None = None, **kwargs: Any):
    kwargs.setdefault("executor", async_executor())  # caller's executor wins
    if fn is None:
        return lambda f: udf(f, **kwargs)
    return udf(fn, **kwargs)


UDFSync = UDF
UDFAsync = UDF
