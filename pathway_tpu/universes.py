"""``pw.universes`` — key-set (universe) promises (reference
``python/pathway/internals/universes.py``: ``promise_is_subset_of``,
``promise_are_equal``, ``promise_are_pairwise_disjoint``). Promises feed
the universe solver consulted when columns of different tables are mixed.
"""

from __future__ import annotations

from .internals.parse_graph import G
from .internals.table import Table

__all__ = [
    "promise_is_subset_of",
    "promise_are_equal",
    "promise_are_pairwise_disjoint",
]


def promise_is_subset_of(subset: Table, superset: Table) -> Table:
    G.promise_subset(subset._universe, superset._universe)
    return subset


def promise_are_equal(*tables: Table) -> None:
    for other in tables[1:]:
        G.promise_equal(tables[0]._universe, other._universe)


def promise_are_pairwise_disjoint(*tables: Table) -> None:
    """Disjointness lets ``concat`` keep original keys safely. The promise
    feeds the universe solver (consulted by Table.concat at build time);
    the engine additionally errors at runtime if colliding keys show up
    (reference `_concat` + engine key-uniqueness check)."""
    G.promise_disjoint(*[t._universe for t in tables])

