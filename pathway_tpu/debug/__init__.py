"""``pw.debug`` — markdown tables, compute_and_print, pandas round-trips.

Re-design of ``python/pathway/debug/__init__.py`` (table_from_markdown :429,
compute_and_print :207, compute_and_print_update_stream :235, pandas
round-trips :270,343, StreamGenerator :496).
"""

from __future__ import annotations

import re
from typing import Any, Iterable

import numpy as np

from ..internals import dtype as dt
from ..internals.graph_runner import GraphRunner
from ..internals.schema import SchemaMetaclass
from ..internals.table import Table
from ..internals.table_io import rows_to_table

__all__ = [
    "table_from_markdown",
    "parse_to_table",
    "table_from_rows",
    "table_from_pandas",
    "table_to_pandas",
    "table_from_parquet",
    "table_to_parquet",
    "table_from_dicts",
    "compute_and_print",
    "compute_and_print_update_stream",
    "table_to_dicts",
    "StreamGenerator",
]

_SPECIAL = ("__time__", "__diff__")


def _parse_value(tok: str) -> Any:
    if tok in ("", "None", "NA", "NULL", "NaN", "nan"):
        return None
    if tok == "True" or tok == "true":
        return True
    if tok == "False" or tok == "false":
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    return tok


def table_from_markdown(
    table_def: str,
    id_from: Any = None,
    unsafe_trusted_ids: bool = False,
    schema: SchemaMetaclass | None = None,
    *,
    _stacklevel: int = 1,
    split_on_whitespace: bool = True,
) -> Table:
    """Markdown/whitespace table definition → static (or scheduled) table.

    Special columns: ``__time__`` batches rows by timestamp, ``__diff__``
    (+1/-1) marks insert/delete — together they define an update stream.
    An empty-named or ``id`` first column provides trusted integer ids.
    """
    lines = [ln for ln in table_def.strip("\n").splitlines() if ln.strip()]
    sep = r"\s*\|\s*|\s+" if split_on_whitespace else r"\s*\|\s*"

    def split(line: str) -> list[str]:
        if split_on_whitespace and "|" in line:
            # pipe-delimited: EMPTY cells are meaningful (None values) —
            # "1 |  5  |" is id=1, next=5, prev=None (reference prev/next
            # tables); a leading empty header cell marks the id column
            return [t.strip() for t in line.strip().split("|")]
        toks = re.split(sep, line.strip())
        if split_on_whitespace:
            return [t for t in toks if t != ""]
        if toks and toks[0] == "":
            toks = toks[1:]
        if toks and toks[-1] == "":
            toks = toks[:-1]
        return toks

    names = split(lines[0])
    # leading empty / "id" header column = trusted integer ids
    has_id_col = bool(names) and names[0] in ("id", "")
    if has_id_col:
        names = names[1:]

    body_lines = [ln for ln in lines[1:] if not set(ln.strip()) <= set("-| ")]
    if not has_id_col and body_lines:
        # unnamed index column: rows have one extra leading value
        if all(len(split(ln)) == len(names) + 1 for ln in body_lines):
            probe = [split(ln)[0] for ln in body_lines]
            if all(re.fullmatch(r"-?\d+", t) for t in probe):
                has_id_col = True

    body_rows: list[list[Any]] = []
    id_values: list[int] = []
    for ln in body_lines:
        toks = split(ln)
        if has_id_col:
            id_values.append(int(toks[0]))
            toks = toks[1:]
        vals = [_parse_value(t) for t in toks]
        if len(vals) != len(names):
            raise ValueError(f"row {ln!r} has {len(vals)} values, expected {len(names)}")
        body_rows.append(vals)

    times = diffs = None
    if "__time__" in names:
        ti = names.index("__time__")
        times = [int(r[ti]) for r in body_rows]
    if "__diff__" in names:
        di = names.index("__diff__")
        diffs = [int(r[di]) for r in body_rows]
        if times is None:
            times = [0] * len(body_rows)
    keep = [i for i, nm in enumerate(names) if nm not in _SPECIAL]
    clean_names = [names[i] for i in keep]
    clean_rows = [tuple(r[i] for i in keep) for r in body_rows]

    if isinstance(id_from, str):
        id_from = [id_from]
    return rows_to_table(
        clean_names,
        clean_rows,
        id_values=id_values if has_id_col else None,
        id_from=id_from,
        schema=schema,
        times=times,
        diffs=diffs,
    )


def parse_to_table(*args: Any, **kwargs: Any) -> Table:
    return table_from_markdown(*args, **kwargs)


def table_from_rows(
    schema: SchemaMetaclass,
    rows: list[tuple],
    unsafe_trusted_ids: bool = False,
    is_stream: bool = False,
) -> Table:
    names = schema.column_names()
    if is_stream:
        times = [r[len(names)] for r in rows]
        diffs = [r[len(names) + 1] for r in rows] if len(rows) and len(rows[0]) > len(names) + 1 else None
        clean = [tuple(r[: len(names)]) for r in rows]
        return rows_to_table(names, clean, schema=schema, times=times, diffs=diffs)
    return rows_to_table(names, [tuple(r) for r in rows], schema=schema)


def table_from_pandas(
    df: Any,
    id_from: Any = None,
    unsafe_trusted_ids: bool = False,
    schema: SchemaMetaclass | None = None,
    _stacklevel: int = 1,
) -> Table:
    names = [str(c) for c in df.columns if str(c) not in _SPECIAL]
    # columnar extraction (iterrows is ~100x slower and upcasts dtypes)
    cols = []
    for c in names:
        arr = df[c].to_numpy()
        if arr.dtype.kind in ("i", "u", "b"):
            cols.append([v.item() for v in arr])
        elif arr.dtype.kind == "f":
            cols.append([None if np.isnan(v) else v.item() for v in arr])
        elif arr.dtype.kind in ("M", "m"):
            # datetime64/timedelta64: iterate the Series so pandas yields
            # Timestamp/Timedelta (.item() on ns precision returns raw ints)
            cols.append([_from_pandas_value(v) for v in df[c]])
        else:
            cols.append([_from_pandas_value(v) for v in arr])
    rows = list(zip(*cols)) if names else [() for _ in range(len(df))]
    times = [int(t) for t in df["__time__"]] if "__time__" in df.columns else None
    diffs = [int(d) for d in df["__diff__"]] if "__diff__" in df.columns else None
    id_values = None
    if df.index.name in ("id",) or (id_from is None and _looks_like_ids(df.index)):
        try:
            id_values = [int(i) for i in df.index]
        except (TypeError, ValueError):
            id_values = None
    if isinstance(id_from, str):
        id_from = [id_from]
    return rows_to_table(
        names, rows, id_values=id_values, id_from=id_from, schema=schema,
        times=times, diffs=diffs,
    )


def _looks_like_ids(index: Any) -> bool:
    try:
        arr = np.asarray(index)
        if arr.dtype.kind not in "iu":
            arr = arr.astype(np.int64)
        return not np.array_equal(arr, np.arange(len(arr)))
    except (TypeError, ValueError, KeyError, OverflowError):
        # e.g. python ints beyond int64 in the index: treat as opaque
        return False


def _from_pandas_value(v: Any) -> Any:
    if v is None:
        return None
    if isinstance(v, float) and np.isnan(v):
        return None
    if isinstance(v, np.generic):
        if isinstance(v, np.floating) and np.isnan(v):
            return None
        return v.item()
    return v


def _run_capture(table: Table):
    (cap,) = GraphRunner().run_tables(table)
    return cap


def _format_pointer(key: int) -> str:
    return "^" + format(int(key), "016X")


def compute_and_print(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    squash_updates: bool = True,
    terminate_on_error: bool = True,
) -> None:
    """Run the graph and print the consolidated table (reference :207)."""
    cap = _run_capture(table)
    names = table.column_names()
    items = sorted(cap.state.iter_items(), key=lambda kv: kv[0])
    if n_rows is not None:
        items = items[:n_rows]
    header = (["id"] if include_id else []) + names
    rows = []
    for key, row in items:
        cells = [_format_pointer(key)] if include_id else []
        cells += [_format_cell(v, short_pointers) for v in row]
        rows.append(cells)
    _print_table(header, rows)


def compute_and_print_update_stream(
    table: Table,
    *,
    include_id: bool = True,
    short_pointers: bool = True,
    n_rows: int | None = None,
    **kwargs: Any,
) -> None:
    """Run the graph and print the full (time, diff) update stream."""
    cap = _run_capture(table)
    names = table.column_names()
    header = (["id"] if include_id else []) + names + ["__time__", "__diff__"]
    rows = []
    stream = cap.stream if n_rows is None else cap.stream[:n_rows]
    for time, key, row, diff in stream:
        cells = [_format_pointer(key)] if include_id else []
        cells += [_format_cell(v, short_pointers) for v in row]
        cells += [str(time), str(diff)]
        rows.append(cells)
    _print_table(header, rows)


def _format_cell(v: Any, short_pointers: bool) -> str:
    if isinstance(v, (np.uint64,)) and short_pointers:
        return _format_pointer(int(v))
    if isinstance(v, np.generic):
        v = v.item()
    return repr(v) if isinstance(v, str) else str(v)


def _render_table(header: list[str], rows: list[list[str]]) -> str:
    widths = [len(h) for h in header]
    for r in rows:
        widths = [max(w, len(c)) for w, c in zip(widths, r)]
    lines = [" | ".join(h.ljust(w) for h, w in zip(header, widths))]
    lines += [" | ".join(c.ljust(w) for c, w in zip(r, widths)) for r in rows]
    return "\n".join(lines)


def _print_table(header: list[str], rows: list[list[str]]) -> None:
    print(_render_table(header, rows))


def table_to_dicts(table: Table):
    cap = _run_capture(table)
    names = table.column_names()
    keys = []
    cols: dict[str, dict] = {n: {} for n in names}
    for key, row in cap.state.iter_items():
        keys.append(key)
        for n, v in zip(names, row):
            cols[n][key] = v
    return keys, cols


def table_from_dicts(data: dict[str, dict], schema: SchemaMetaclass | None = None) -> Table:
    names = list(data.keys())
    all_keys = sorted({k for col in data.values() for k in col})
    rows = [tuple(data[n][k] for n in names) for k in all_keys]
    return rows_to_table(names, rows, id_values=list(all_keys), schema=schema)


def table_to_pandas(table: Table, *, include_id: bool = True):
    import pandas as pd

    import datetime as _datetime

    cap = _run_capture(table)
    names = table.column_names()
    items = sorted(cap.state.iter_items(), key=lambda kv: kv[0])
    data: dict[str, Any] = {}
    for i, n in enumerate(names):
        col = [row[i] for _, row in items]
        # keep datetime cells as python objects: pandas would coerce them
        # to datetime64[ns], and numpy 2 renders ns-precision items back
        # as raw integer nanoseconds under .values.tolist() — the
        # reference hands out Timestamp-like objects here, so tests (and
        # users) call .hour/.year on the cells
        if any(isinstance(v, _datetime.datetime) for v in col):
            data[n] = pd.Series(col, dtype=object, index=[k for k, _ in items])
        else:
            data[n] = col
    if include_id:
        return pd.DataFrame(data, index=[k for k, _ in items])
    return pd.DataFrame(
        {
            n: (c.reset_index(drop=True) if isinstance(c, pd.Series) else c)
            for n, c in data.items()
        }
    )


class StreamGenerator:
    """Deterministic artificial timestamped streams (reference :496)."""

    def __init__(self) -> None:
        self._time = 0

    def table_from_list_of_batches_by_workers(
        self, batches: list[dict[int, list[dict[str, Any]]]], schema: SchemaMetaclass
    ) -> Table:
        rows: list[tuple] = []
        times: list[int] = []
        names = schema.column_names()
        for t, batch in enumerate(batches):
            for _worker, entries in batch.items():
                for entry in entries:
                    rows.append(tuple(entry[n] for n in names))
                    times.append(2 * (t + 1))
        return rows_to_table(names, rows, schema=schema, times=times)

    def table_from_list_of_batches(
        self, batches: list[list[dict[str, Any]]], schema: SchemaMetaclass
    ) -> Table:
        return self.table_from_list_of_batches_by_workers(
            [{0: b} for b in batches], schema
        )


def _format_snapshot(names: list[str], rows: dict[int, tuple], frontier: int) -> str:
    """Render a LiveTable snapshot (internals/interactive.py) in the same
    table format compute_and_print uses, returned as a string."""
    header = ["id"] + names
    lines = [
        [_format_pointer(key)] + [_format_cell(v, True) for v in row]
        for key, row in sorted(rows.items())
    ]
    return _render_table(header, lines) + f"\n[frontier {frontier}]"


def table_from_parquet(path, id_from=None, unsafe_trusted_ids=False):
    """Static table from a parquet file (reference debug/__init__.py
    table_from_parquet — pandas/pyarrow round-trip)."""
    import pandas as pd

    df = pd.read_parquet(path)
    return table_from_pandas(
        df, id_from=id_from, unsafe_trusted_ids=unsafe_trusted_ids
    )


def table_to_parquet(table, path):
    """Write a (finite) table to a parquet file (reference
    table_to_parquet)."""
    df = table_to_pandas(table, include_id=False)
    df = df.reset_index(drop=True)
    return df.to_parquet(path)
