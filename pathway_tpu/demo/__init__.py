"""pw.demo — synthetic streams (reference python/pathway/demo:28-240)."""

from __future__ import annotations

from typing import Any, Callable

from ..internals.schema import SchemaMetaclass, schema_from_types
from ..internals.table import Table
from ..internals.table_io import rows_to_table


def generate_custom_stream(
    value_generators: dict[str, Callable[[int], Any]],
    *,
    schema: SchemaMetaclass,
    nb_rows: int | None = 10,
    autocommit_duration_ms: int = 1000,
    input_rate: float = 1.0,
    persistent_storage: Any = None,
) -> Table:
    names = schema.column_names()
    rows = []
    times = []
    n = nb_rows if nb_rows is not None else 10
    for i in range(n):
        rows.append(tuple(value_generators[name](i) for name in names))
        times.append(2 * (i + 1))
    return rows_to_table(names, rows, schema=schema, times=times)


def range_stream(nb_rows: int = 30, offset: int = 0, **kwargs) -> Table:
    # reference demo/__init__.py range_stream: FLOAT values
    schema = schema_from_types(value=float)
    return generate_custom_stream(
        {"value": lambda i: float(i + offset)}, schema=schema, nb_rows=nb_rows
    )


def noisy_linear_stream(nb_rows: int = 10, **kwargs) -> Table:
    import random

    rng = random.Random(0)
    schema = schema_from_types(x=float, y=float)
    return generate_custom_stream(
        {"x": lambda i: float(i), "y": lambda i: i + rng.uniform(-1, 1)},
        schema=schema,
        nb_rows=nb_rows,
    )


def replay_csv(path: str, *, schema: SchemaMetaclass, input_rate: float = 1.0) -> Table:
    from ..io import csv as io_csv

    return io_csv.read(path, schema=schema, mode="static")
