"""Frontier tracker + quiesce votes as pure components
(``engine/frontier.py``) — the progress protocol of the asynchronous
executor, tested without threads, comm, or an engine:

- monotonic local advance (regression raises, equal re-advance no-ops);
- broadcast merge across workers (max-merge, stale broadcasts ignored);
- stall detection when a peer stops advancing while others progress;
- the frontier-derived commit boundary equals the old tick-derived
  boundary on a synchronous (lock-step) schedule;
- quiesce: two clean rounds with balanced, stable totals — and the
  single-round forgery (in-flight message masked by a recv counted
  before its send) is correctly rejected.
"""

from __future__ import annotations

import pytest

from pathway_tpu.engine.frontier import FrontierTracker, QuiesceVotes


# -- FrontierTracker ---------------------------------------------------------


def test_local_advance_is_monotone():
    ft = FrontierTracker(2, 0)
    assert ft.local() == -1
    ft.advance_local(10)
    ft.advance_local(10)  # equal re-advance: lawful no-op
    assert ft.local() == 10
    with pytest.raises(ValueError):
        ft.advance_local(8)


def test_broadcast_merge_across_workers():
    ft = FrontierTracker(3, 0)
    ft.advance_local(100)
    assert ft.observe(1, 50)
    assert ft.observe(2, 80)
    assert ft.frontiers() == [100, 50, 80]
    assert ft.global_frontier() == 50
    # stale/duplicate broadcasts (status rebroadcasts) are ignored
    assert not ft.observe(1, 50)
    assert not ft.observe(1, 40)
    assert ft.frontiers()[1] == 50
    assert ft.observe(1, 120)
    assert ft.global_frontier() == 80


def test_global_frontier_requires_every_worker():
    ft = FrontierTracker(2, 0)
    ft.advance_local(1000)
    # peer never broadcast: nothing can be considered complete
    assert ft.global_frontier() == -1
    assert ft.commit_boundary() == -1


def test_stall_detection_when_peer_stops_advancing():
    ft = FrontierTracker(2, 0)
    ft.advance_local(100, now=0.0)
    ft.observe(1, 100, now=0.0)
    # both idle: parked, not stalled
    assert ft.stalled(now=100.0, timeout_s=30.0) == []
    # worker 0 keeps advancing, worker 1 goes quiet and falls behind
    ft.advance_local(500, now=95.0)
    assert ft.stalled(now=100.0, timeout_s=30.0) == [1]
    # worker 1 resumes: no longer stalled
    ft.observe(1, 600, now=99.0)
    assert ft.stalled(now=100.0, timeout_s=30.0) == []


def test_commit_boundary_matches_tick_boundary_on_synchronous_schedule():
    """On a lock-step schedule (every worker sweeps the same even tick
    sequence, as the BSP loop does) the frontier-derived commit boundary
    is exactly the tick-derived one: the last tick completed
    everywhere."""
    n = 3
    trackers = [FrontierTracker(n, w) for w in range(n)]
    ticks = [1000, 1002, 1004, 1006]
    for t in ticks:
        for w, ft in enumerate(trackers):
            ft.advance_local(t) if w == ft.worker_id else None
        # broadcast wave after the tick completes on every worker
        for w, ft in enumerate(trackers):
            for peer, pft in enumerate(trackers):
                if peer != w:
                    ft.observe(peer, trackers[peer].local())
        for ft in trackers:
            assert ft.global_frontier() == t
            assert ft.commit_boundary() == t  # == the agreed BSP tick
    # a straggler mid-tick drags the boundary back to the last COMPLETE one
    trackers[0].advance_local(1008)
    trackers[1].observe(0, 1008)
    assert trackers[1].commit_boundary() == 1006


def test_commit_boundary_rounds_to_even():
    ft = FrontierTracker(1, 0)
    ft.advance_local(1001)  # idle promise between even mints
    assert ft.commit_boundary() == 1000


# -- QuiesceVotes ------------------------------------------------------------


def _exchange_all(voters, payloads):
    for w, p in payloads.items():
        for v, qv in enumerate(voters):
            if v != w:
                qv.observe(w, p)


def test_quiesce_two_clean_rounds():
    voters = [QuiesceVotes(2, w, "term") for w in range(2)]
    # round 0: balanced and inactive... but ONE clean round is not enough
    p = {w: voters[w].cast(3, 3, False) for w in range(2)}
    _exchange_all(voters, p)
    assert not any(v.step() for v in voters)
    # round 1: still clean with the same totals -> quiesced, everywhere
    p = {w: voters[w].cast(3, 3, False) for w in range(2)}
    _exchange_all(voters, p)
    assert all(v.step() for v in voters)


def test_quiesce_rejects_single_round_forgery():
    """The classic asymmetry: totals balance at round k while a message
    is in flight (a recv counted whose send was cast after the sender's
    vote). The second round exposes it as activity / changed totals."""
    voters = [QuiesceVotes(2, w, "term") for w in range(2)]
    # round 0: balanced (worker 0 sent 2/recv 1, worker 1 sent 1/recv 2)
    # but a 3rd message is in flight from w0, sent AFTER w0's vote
    p = {0: voters[0].cast(2, 1, False), 1: voters[1].cast(1, 2, False)}
    _exchange_all(voters, p)
    assert not any(v.step() for v in voters)
    # round 1: w1 processed the in-flight message -> active + totals moved
    p = {0: voters[0].cast(3, 1, False), 1: voters[1].cast(1, 3, True)}
    _exchange_all(voters, p)
    assert not any(v.step() for v in voters)
    # rounds 2+3: genuinely drained now
    for _ in range(2):
        p = {0: voters[0].cast(3, 1, False), 1: voters[1].cast(1, 3, False)}
        _exchange_all(voters, p)
        done = [v.step() for v in voters]
    assert all(done)


def test_quiesce_unbalanced_totals_never_complete():
    voters = [QuiesceVotes(2, w, "term") for w in range(2)]
    for _ in range(4):
        p = {w: voters[w].cast(5, 4, False) for w in range(2)}
        _exchange_all(voters, p)
        assert not any(v.step() for v in voters)


def test_quiesce_rounds_stay_aligned_across_skew():
    """A worker that starts voting late catches up through the kept
    per-round votes — rounds advance in lock-step, max skew one."""
    a, b = QuiesceVotes(2, 0, "term"), QuiesceVotes(2, 1, "term")
    pa = a.cast(1, 1, False)
    assert not a.step()  # b has not voted: round 0 incomplete for a
    assert a.round == 0
    # b arrives late, receives a's round-0 vote, casts, both advance
    b.observe(0, pa)
    pb = b.cast(1, 1, False)
    a.observe(1, pb)
    assert not a.step() and not b.step()
    assert a.round == b.round == 1
    pa, pb = a.cast(1, 1, False), b.cast(1, 1, False)
    a.observe(1, pb)
    b.observe(0, pa)
    assert a.step() and b.step()


def test_quiesce_phase_isolation():
    term = QuiesceVotes(2, 0, "term")
    term.observe(1, ("cw3", 0, 5, 5, False))  # a commit wave's vote
    assert 1 not in term._votes.get(0, {})
