"""Ported from the reference's Json-value suite.

Source: ``/root/reference/python/pathway/tests/test_json.py`` (VERDICT r4
item 7). Porting contract as in ``tests/test_ported_common_1.py``;
manifest in ``PORTED_TESTS.md``.
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.testing import T


def _json_table(values: list) -> pw.Table:
    return pw.debug.table_from_rows(
        pw.schema_from_types(data=pw.Json),
        [(pw.Json(v),) for v in values],
    )


def _vals(res, name="result"):
    out = []
    for v in pw.debug.table_to_pandas(res)[name].tolist():
        out.append(v.value if isinstance(v, pw.Json) else v)
    return out


def test_json_get_item_degrades_to_null():  # ref :185
    inp = _json_table([
        {"a": {"b": 1}},
        {"a": {"b": None}},
        {},
        {"a": {}},
        {"a": [1, 2, 3]},
        {"a": 42},
        {"a": None},
    ])
    res = inp.select(result=pw.this.data["a"]["b"])
    assert sorted(_vals(res), key=repr) == sorted(
        [1, None, None, None, None, None, None], key=repr
    )


def test_json_get_array_index():  # ref :206
    inp = pw.debug.table_from_rows(
        pw.schema_from_types(index=int, data=pw.Json),
        [
            (0, pw.Json({"field": [1, 2, 3]})),
            (1, pw.Json({"field": [4, 5, 6]})),
            (2, pw.Json({"field": [7, 8, 9]})),
        ],
    )
    res = inp.select(result=pw.this.data["field"][pw.this.index.as_int()])
    assert sorted(_vals(res)) == [1, 5, 9]


@pytest.mark.parametrize("index", [-1, -4, 3])
def test_json_get_array_index_out_of_bounds(index):  # ref :221
    inp = _json_table([{"field": [0, 1, 2]}])
    res = inp.select(result=pw.this.data["field"][index])
    assert _vals(res) == [None]


def test_json_get_default():  # ref :79
    inp = _json_table([
        {"a": {"b": 1}},
        {"a": [1, 2, 3]},
        {"a": 42},
        {"a": None},
        {},
        [1, 2, 3],
        None,
        1,
        "foo",
    ])

    @pw.udf
    def get_a(d: pw.Json) -> pw.Json:
        return d.get("a", default={"b": 42})

    res = inp.select(result=get_a(pw.this.data))
    assert sorted(_vals(res), key=repr) == sorted(
        [
            {"b": 1}, [1, 2, 3], 42, None,
            {"b": 42}, {"b": 42}, {"b": 42}, {"b": 42}, {"b": 42},
        ],
        key=repr,
    )


def test_json_udf_as_type_wrong_values_raise():  # ref :560
    j = pw.Json("foo")
    with pytest.raises(ValueError):
        j.as_int()
    with pytest.raises(ValueError):
        j.as_float()
    with pytest.raises(ValueError):
        pw.Json(1).as_str()
    with pytest.raises(ValueError):
        pw.Json(1).as_bool()
    # bools are NOT ints/floats in json-land
    with pytest.raises(ValueError):
        pw.Json(True).as_int()


def test_json_udf_as_type():  # ref :522
    assert pw.Json(5).as_int() == 5
    assert pw.Json(5).as_float() == 5.0
    assert pw.Json(1.5).as_float() == 1.5
    assert pw.Json("x").as_str() == "x"
    assert pw.Json(True).as_bool() is True
    with pytest.raises(ValueError):
        pw.Json(1.5).as_int()


def test_json_flatten():  # ref :412
    inp = _json_table([{"field": [1, 2]}, {"field": [3]}])
    parts = inp.select(xs=pw.apply_with_type(
        lambda d: tuple(d["field"].as_list()), tuple, pw.this.data
    ))
    res = parts.flatten(pw.this.xs)
    assert sorted(pw.debug.table_to_pandas(res)["xs"].tolist()) == [1, 2, 3]


def test_json_flatten_wrong_values_skip_with_error():  # ref :438
    inp = _json_table([{"field": [1]}, {"field": 42}])
    parts = inp.select(xs=pw.apply_with_type(
        lambda d: tuple(d["field"].as_list()), tuple, pw.this.data
    ))
    res = parts.flatten(pw.this.xs)
    # the 42 row errors in as_list -> Error -> flatten skips it, run survives
    assert sorted(pw.debug.table_to_pandas(res)["xs"].tolist()) == [1]


def test_json_apply():  # ref :389
    inp = _json_table([{"a": 1}, {"a": 2}])

    @pw.udf
    def incr(d: pw.Json) -> int:
        return d["a"].as_int() + 1

    res = inp.select(result=incr(pw.this.data))
    assert sorted(_vals(res)) == [2, 3]


def test_json_recursive_equality():  # ref :600
    a = pw.Json({"x": [1, {"y": "z"}], "w": None})
    b = pw.Json({"w": None, "x": [1, {"y": "z"}]})
    assert a == b
    assert hash(a) == hash(b)
    assert a != pw.Json({"x": [1, {"y": "q"}], "w": None})


def test_json_nested_select():  # ref :631
    inp = _json_table([{"outer": {"inner": {"deep": 7}}}])
    res = inp.select(result=pw.this.data["outer"]["inner"]["deep"])
    assert _vals(res) == [7]


def test_json_type_column():  # ref :578
    t = _json_table([{"a": 1}])
    assert "JSON" in repr(t.schema.dtypes()["data"]).upper()
