"""``pathway-tpu lint`` CLI: severity exit codes, suppressions, JSON
output — and the tier-1 gate that every shipped example lints clean.
"""

from __future__ import annotations

import json
import os
import textwrap

import pytest
from click.testing import CliRunner

from pathway_tpu.cli import main as cli_main
from pathway_tpu.internals.parse_graph import G

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_graph(monkeypatch):
    monkeypatch.delenv("PATHWAY_STATE_MEMORY_BUDGET_MB", raising=False)
    monkeypatch.delenv("PATHWAY_LINT_WORKERS", raising=False)
    G.clear()
    yield
    G.clear()


def _lint(*args):
    return CliRunner().invoke(cli_main, ["lint", *args])


CLEAN = """
import pathway_tpu as pw
from pathway_tpu.testing import T

t = T("a\\n1\\n2")
res = t.select(b=pw.this.a + 1)
pw.io.subscribe(res, on_change=lambda **kw: None)
pw.run()
"""

WARNING = """
import pathway_tpu as pw

class S(pw.io.python.ConnectorSubject):
    def run(self):
        pass

t = pw.io.python.read(S(), schema=pw.schema_from_types(word=str), name="w")
res = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
pw.io.subscribe(res, on_change=lambda **kw: None)
pw.run()
"""

ERROR = """
import pathway_tpu as pw
from pathway_tpu.testing import T

def udf(x):
    import random
    return x + random.random()

t = T("a\\n1\\n2")
res = t.select(c=pw.apply_with_type(udf, float, pw.this.a))
pw.io.subscribe(res, on_change=lambda **kw: None)
pw.run(persistence_config=pw.persistence.Config.simple_config(
    pw.persistence.Backend.memory("lint-cli-test")))
"""

CRASH = """
raise ValueError("broken pipeline script")
"""


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_clean_script_exits_zero(tmp_path):
    r = _lint(_write(tmp_path, "clean.py", CLEAN))
    assert r.exit_code == 0, r.output
    assert "0 error(s), 0 warning(s)" in r.output


def test_warning_script_exits_one(tmp_path):
    r = _lint(_write(tmp_path, "warn.py", WARNING))
    assert r.exit_code == 1, r.output
    assert "unbounded-state" in r.output


def test_fail_on_error_ignores_warnings(tmp_path):
    r = _lint("--fail-on", "error", _write(tmp_path, "warn.py", WARNING))
    assert r.exit_code == 0, r.output


def test_error_script_exits_two(tmp_path):
    r = _lint(_write(tmp_path, "err.py", ERROR))
    assert r.exit_code == 2, r.output
    assert "nondeterministic-udf" in r.output


def test_crashing_script_exits_three(tmp_path):
    r = _lint(_write(tmp_path, "crash.py", CRASH))
    assert r.exit_code == 3, r.output
    assert "crashed" in r.output


def test_fail_on_never_covers_crashes_too(tmp_path):
    # "never" means never: a non-building script still reports, but the
    # run collects non-fatally
    r = _lint("--fail-on", "never", _write(tmp_path, "crash.py", CRASH))
    assert r.exit_code == 0, r.output


def test_filewide_suppression_cleans_exit(tmp_path):
    body = "# pathway: ignore[unbounded-state]\n" + WARNING
    r = _lint(_write(tmp_path, "sup.py", body))
    assert r.exit_code == 0, r.output
    assert "suppressed" in r.output


def test_line_suppression_is_line_scoped(tmp_path):
    # suppressing on the WRONG line leaves the finding alive
    body = WARNING.replace(
        'name="w")', 'name="w")  # pathway: ignore[unbounded-state]'
    )
    r = _lint(_write(tmp_path, "wrongline.py", body))
    assert r.exit_code == 1, r.output


def test_json_output_parses(tmp_path):
    r = _lint("--json", _write(tmp_path, "warn.py", WARNING))
    docs = json.loads(r.output)
    assert len(docs) == 1
    assert any(
        d["id"] == "unbounded-state" for d in docs[0]["diagnostics"]
    )
    assert docs[0]["fingerprints"]
    assert docs[0]["summary"]["warning"] >= 1


def test_directory_target_expands(tmp_path):
    _write(tmp_path, "one.py", CLEAN)
    _write(tmp_path, "two.py", CLEAN)
    r = _lint(str(tmp_path))
    assert r.exit_code == 0, r.output
    assert r.output.count("== pathway-tpu lint:") == 2


def test_workers_flag_drives_shard_skew(tmp_path):
    body = """
    import pathway_tpu as pw
    from pathway_tpu.testing import T

    t = T("a\\n1\\n2")
    flagged = t.select(flag=pw.this.a > 1)
    res = flagged.groupby(pw.this.flag).reduce(
        pw.this.flag, c=pw.reducers.count())
    pw.io.subscribe(res, on_change=lambda **kw: None)
    pw.run()
    """
    path = _write(tmp_path, "skew.py", body)
    assert "shard-skew" in _lint("--workers", "4", path).output
    assert "shard-skew" not in _lint("--workers", "1", path).output


def test_fingerprints_stable_across_cli_runs(tmp_path):
    path = _write(tmp_path, "fp.py", CLEAN)
    a = _lint("--json", path)
    b = _lint("--json", path)
    fa = json.loads(a.output)[0]["fingerprints"]
    fb = json.loads(b.output)[0]["fingerprints"]
    assert fa == fb and fa


# ---------------------------------------------------------------------------
# tier-1: every shipped example lints clean (or carries an explicit
# suppression) — the CI wiring the ISSUE asks for
# ---------------------------------------------------------------------------


def test_wordcount_example_lints_clean():
    r = _lint(os.path.join(REPO, "examples", "wordcount"))
    assert r.exit_code == 0, r.output


def test_rag_server_example_lints_clean():
    r = _lint(os.path.join(REPO, "examples", "rag_server"))
    assert r.exit_code == 0, r.output
