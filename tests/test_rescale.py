"""Elastic rescaling unit + integration tests (rescale/resharder.py).

Covers the layout/epoch marker protocol, the operator split/merge API,
rescale atomicity under injected crashes at every phase boundary, the
O(chunk) generator replay satellite, and the torn-metadata fallback
satellite (direct + via the persistence.put chaos site).
"""

from __future__ import annotations

import json
import os
import time
from collections import Counter

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.persistence import Backend, Config
from pathway_tpu.persistence.backends import MemoryBackend
from pathway_tpu.rescale import RescaleError, rescale, stats


# -- harness ----------------------------------------------------------------

WORDS = ["a", "b", "a", "c"] * 3 + ["a", "c", "d"] * 4 + ["d", "b"] * 2


def _run_wordcount(upto: int, threads: int, cfg, monkeypatch) -> dict:
    """Run the flagship wordcount over WORDS[:upto] (a replayable source:
    each run re-emits from the start, recovery seeks past the persisted
    offset) and return {word: last emitted count}."""
    G.clear()
    monkeypatch.setenv("PATHWAY_THREADS", str(threads))
    final: dict = {}

    class S(pw.io.python.ConnectorSubject):
        def run(self):
            for w in WORDS[:upto]:
                self.next(word=w)
                self.commit()
                time.sleep(0.002)

    t = pw.io.python.read(
        S(), schema=pw.schema_from_types(word=str), name="words",
        autocommit_ms=None,
    )
    counts = t.groupby(pw.this.word).reduce(
        pw.this.word, c=pw.reducers.count()
    )

    def on_change(key, row, time, is_addition):
        if is_addition:
            final[row["word"]] = int(row["c"])

    pw.io.subscribe(counts, on_change=on_change)
    try:
        pw.run(persistence_config=cfg)
    finally:
        monkeypatch.setenv("PATHWAY_THREADS", "1")
        G.clear()
    return final


def _mem_cfg(name: str):
    MemoryBackend.drop(name)
    return Config.simple_config(Backend.memory(name), snapshot_interval_ms=5)


# -- layout marker / epochs -------------------------------------------------


def test_layout_namespaces():
    from pathway_tpu.persistence.layout import epoch_prefix, worker_namespace

    assert worker_namespace(0, 1, 0) == ""
    assert worker_namespace(0, 4, 2) == "worker-2/"
    assert worker_namespace(3, 1, 0) == "epoch-3/"
    assert worker_namespace(3, 4, 2) == "epoch-3/worker-2/"
    assert epoch_prefix(0) == ""


def test_rescale_refuses_empty_store():
    with pytest.raises(RescaleError, match="no cluster marker"):
        rescale(MemoryBackend(), 3)


def test_rescale_noop_same_count(monkeypatch):
    cfg = _mem_cfg("resc-noop")
    _run_wordcount(12, 1, cfg, monkeypatch)
    report = rescale(MemoryBackend("resc-noop"), 1)
    assert report["noop"] is True


# -- the core resharding round trip ----------------------------------------


def test_rescale_1_to_3_to_1_exact_counts(monkeypatch):
    cfg = _mem_cfg("resc-core")
    root = MemoryBackend("resc-core")

    seg1 = _run_wordcount(12, 1, cfg, monkeypatch)
    assert seg1 == {"a": 6, "b": 3, "c": 3}

    report = rescale(root, 3)
    assert report["from"] == 1 and report["to"] == 3
    marker = json.loads(root.get_value("cluster"))
    assert marker == {"n_workers": 3, "epoch": report["epoch"]}
    # a complete worker-{j} layout exists for every destination
    for j in range(3):
        assert any(
            k.startswith(f"epoch-{report['epoch']}/worker-{j}/meta/")
            for k in root.list_keys()
        )

    seg2 = _run_wordcount(24, 3, cfg, monkeypatch)
    assert seg2 == {"a": 10, "c": 7, "d": 4}  # new words only (skip_until)

    rescale(root, 1)
    seg3 = _run_wordcount(28, 1, cfg, monkeypatch)
    expected = Counter(WORDS)
    merged = {**seg1, **seg2, **seg3}
    assert merged == dict(expected)


def test_elastic_boot_reshards_in_process(monkeypatch):
    """PATHWAY_ELASTIC=1 + a worker-count mismatch runs the resharder
    inside worker 0's PersistenceManager construction instead of
    refusing; without it the classic refusal (now naming the remedies)
    stays."""
    cfg = _mem_cfg("resc-elastic")
    _run_wordcount(12, 2, cfg, monkeypatch)

    with pytest.raises(RuntimeError, match="pathway-tpu rescale"):
        _run_wordcount(24, 4, cfg, monkeypatch)

    monkeypatch.setenv("PATHWAY_ELASTIC", "1")
    before = stats()["total"]
    seg2 = _run_wordcount(24, 4, cfg, monkeypatch)
    assert stats()["total"] == before + 1
    assert seg2 == {"a": 10, "c": 7, "d": 4}
    marker = json.loads(MemoryBackend("resc-elastic").get_value("cluster"))
    assert marker["n_workers"] == 4


# -- crash-mid-rescale atomicity (the `rescale` chaos site) -----------------


def test_rescale_crash_at_any_phase_leaves_bootable_layout(monkeypatch):
    from pathway_tpu.chaos import injector as chaos
    from pathway_tpu.chaos.plan import FaultPlan

    cfg = _mem_cfg("resc-chaos")
    root = MemoryBackend("resc-chaos")
    _run_wordcount(12, 1, cfg, monkeypatch)
    marker_before = root.get_value("cluster")

    # a crash at every pre-promotion boundary leaves the OLD layout
    # untouched (marker byte-identical)
    for phase in ("plan", "stage", "copy", "promote"):
        chaos.arm(FaultPlan.from_dict({"faults": [
            {"site": "rescale", "phase": phase, "action": "crash"},
        ]}))
        try:
            with pytest.raises(chaos.ChaosInjected):
                rescale(root, 3)
        finally:
            chaos.disarm()
        assert root.get_value("cluster") == marker_before, phase
        # the old layout still boots and finishes the stream exactly
    seg = _run_wordcount(16, 1, cfg, monkeypatch)
    assert seg == {"a": 8, "c": 4, "d": 1}  # WORDS[12:16] == a,c,d,a

    # a crash AFTER the marker flip (cleanup) leaves the NEW layout live
    chaos.arm(FaultPlan.from_dict({"faults": [
        {"site": "rescale", "phase": "cleanup", "action": "crash"},
    ]}))
    try:
        with pytest.raises(chaos.ChaosInjected):
            rescale(root, 3)
    finally:
        chaos.disarm()
    assert json.loads(root.get_value("cluster"))["n_workers"] == 3
    seg = _run_wordcount(24, 3, cfg, monkeypatch)
    assert seg == {"a": 10, "c": 7, "d": 4}

    # the next clean rescale sweeps the crashed attempt's leftovers
    rescale(root, 2)
    leftovers = [
        k for k in root.list_keys()
        if k.startswith(("rescale-tmp/", "meta/", "chunks/", "ops/"))
    ]
    assert leftovers == []


# -- operator split/merge API ----------------------------------------------


def test_split_merge_preserves_groupby_state_multiset(monkeypatch):
    """split_state over M shards followed by merge_states reconstitutes
    the exact operator state (general + dense paths both ride the dense
    arena here: count/sum over numerics)."""
    from pathway_tpu.engine import keys as K
    from pathway_tpu.engine.operators import GroupByReduce

    rng = np.random.default_rng(0)
    gks = K.mix_columns([np.arange(50, dtype=np.int64)], 50)
    state = {
        "_state": {
            int(gk): [2, (int(i),), [2, int(i) * 10], None]
            for i, gk in enumerate(gks)
        },
        "dense": False,
        "gerrs": {},
    }
    masks = [
        (lambda keys, j=j: K.shard_of(np.asarray(keys, np.uint64), 4) == j)
        for j in range(4)
    ]
    pieces = [GroupByReduce.split_state(state, m) for m in masks]
    sizes = [len(p["_state"]) for p in pieces]
    assert sum(sizes) == 50 and all(s > 0 for s in sizes)
    merged = GroupByReduce.merge_states(pieces)
    assert merged["_state"] == state["_state"]


def test_split_merge_pinned_state_keeps_worker0_piece():
    from pathway_tpu.engine.operators import Capture

    assert Capture.RESHARD == "pinned"
    real, pristine = {"state": "full"}, {"state": "empty"}
    mask = lambda keys: np.ones(len(keys), dtype=bool)  # noqa: E731
    assert Capture.split_state(real, mask) is real
    assert Capture.merge_states([real, pristine]) is real


def test_replicated_source_state_unions():
    from pathway_tpu.engine.executor import RealtimeSource

    owner = {"_seen": {"a.txt", "b.txt"}, "_last": {"k": 4}}
    fresh = {"_seen": set(), "_last": {}}
    merged = RealtimeSource.merge_states([fresh, owner])
    assert merged == owner
    # dict-valued progress markers resolve conflicts NUMERICALLY (a prior
    # rescale replicates the owner's state everywhere; only the new
    # owner's copy advances afterwards) — repr ordering would keep 999
    stale = {"_seen": {"a.txt"}, "_last": {}, "_file_rows": {"f": 999}}
    advanced = {"_seen": {"a.txt"}, "_last": {}, "_file_rows": {"f": 1500}}
    merged = RealtimeSource.merge_states([stale, advanced])
    assert merged["_file_rows"] == {"f": 1500}


# -- satellite: generator replay (O(chunk) memory) --------------------------


def test_snapshot_reader_batches_is_a_generator(monkeypatch):
    import types

    from pathway_tpu.persistence import PersistenceManager

    cfg = _mem_cfg("resc-gen")
    _run_wordcount(8, 1, cfg, monkeypatch)
    m = PersistenceManager(cfg)
    out = m.replay_batches(after_time=-1)
    assert isinstance(out, types.GeneratorType)
    for t, pid, delta in out:
        assert pid == "words" and len(delta) >= 1
        break  # lazily consumable
    m.close()


# -- satellite: torn-metadata fallback --------------------------------------


def test_metadata_accessor_falls_back_from_torn_newest():
    from pathway_tpu.persistence.snapshots import MetadataAccessor

    b = MemoryBackend()
    b.put_value("meta/meta-00000000", json.dumps({"last_time": 4}).encode())
    b.put_value("meta/meta-00000001", b'{"last_time": 9')  # torn mid-write
    acc = MetadataAccessor(b)
    assert acc.current == {"last_time": 4}
    assert acc.fell_back_from == 1
    # healing: the next commit rewrites the torn version number
    acc.commit({"last_time": 12})
    acc2 = MetadataAccessor(b)
    assert acc2.current == {"last_time": 12}
    assert acc2.fell_back_from is None


def test_torn_meta_write_via_chaos_site_recovers(monkeypatch):
    """persistence.put `torn` on the 2nd metadata commit, then `fail` on
    the next one (the close()-flush commit; a firing fault short-circuits
    the later faults' counters, so both select nth=2): the run dies with
    the torn blob as the NEWEST version; recovery falls back one version
    with a warning and the resumed run finishes the stream with exact
    counts."""
    from pathway_tpu.chaos import injector as chaos
    from pathway_tpu.persistence.snapshots import MetadataAccessor

    cfg = _mem_cfg("resc-torn")
    monkeypatch.setenv("PATHWAY_FAULT_PLAN", json.dumps({"faults": [
        {"site": "persistence.put", "key_prefix": "meta/", "nth": 2,
         "action": "torn"},
        {"site": "persistence.put", "key_prefix": "meta/", "nth": 2,
         "action": "fail"},
    ]}))
    try:
        with pytest.raises(chaos.ChaosInjected):
            _run_wordcount(12, 1, cfg, monkeypatch)
    finally:
        monkeypatch.delenv("PATHWAY_FAULT_PLAN", raising=False)
        chaos.disarm()

    acc = MetadataAccessor(MemoryBackend("resc-torn"))
    assert acc.fell_back_from is not None

    final = _run_wordcount(12, 1, cfg, monkeypatch)
    assert final == {"a": 6, "b": 3, "c": 3}


# -- observability ----------------------------------------------------------


def test_rescale_metrics_without_restart_series(monkeypatch):
    """A completed rescale surfaces pathway_rescale_total on /metrics —
    without minting pathway_restarts_total outside supervision."""
    from pathway_tpu import chaos
    from pathway_tpu.observability import ObservabilityHub
    from pathway_tpu.observability.prometheus import parse_exposition

    chaos.disarm()
    for k in ("PATHWAY_SUPERVISED", "PATHWAY_RESTART_COUNT",
              "PATHWAY_LAST_RESTART_REASON", "PATHWAY_FLIGHT_DUMPS"):
        monkeypatch.delenv(k, raising=False)
    cfg = _mem_cfg("resc-metrics")
    _run_wordcount(8, 1, cfg, monkeypatch)
    rescale(MemoryBackend("resc-metrics"), 2)

    body = ObservabilityHub().render_metrics()
    series = parse_exposition(body)
    totals = {k[0]: v for k, v in series.items()}
    assert totals.get("pathway_rescale_total", 0) >= 1
    assert "pathway_rescale_duration_seconds" in totals
    assert "pathway_restarts_total" not in totals


# -- offsets ----------------------------------------------------------------


def test_offset_union_prefers_replay_more_on_legacy_conflict():
    from pathway_tpu.rescale.resharder import _merge_offsets

    logs: list[str] = []
    merged = _merge_offsets(
        [
            {"offsets": {"s": {"rows": 12}, "t": {"rows": 3}}},
            # the LARGEST copy is the owner's (offsets advance only on the
            # owner) and exactly covers the recorded input; comparison is
            # NUMERIC, not lexicographic JSON ("999" > "1000" as strings)
            {"offsets": {"s": {"rows": 40}, "u": {"rows": 999}}},
            {"offsets": {"u": {"rows": 1000}}},
        ],
        logs.append,
    )
    assert merged == {
        "s": {"rows": 40}, "t": {"rows": 3}, "u": {"rows": 1000},
    }
    assert logs and "conflict" in logs[0]


# -- CLI hardening: refuse nonsense targets, clear no-op, --dry-run ---------


def test_rescale_cli_refuses_nonpositive_target(tmp_path):
    from click.testing import CliRunner

    from pathway_tpu.cli import main as cli_main

    runner = CliRunner()
    for bad in ("0", "-2"):
        res = runner.invoke(
            cli_main, ["rescale", "--to", bad, str(tmp_path / "nowhere")]
        )
        assert res.exit_code != 0
        assert f"refusing --to {bad}" in res.output
        assert "must be >= 1" in res.output
        # refused BEFORE touching the store: no marker/backend complaint
        assert "no cluster marker" not in res.output


def test_rescale_cli_noop_and_dry_run(tmp_path, monkeypatch):
    from click.testing import CliRunner

    from pathway_tpu.cli import main as cli_main
    from pathway_tpu.rescale import stats as rescale_stats

    store = str(tmp_path / "pstate")
    cfg = Config.simple_config(
        Backend.filesystem(store), snapshot_interval_ms=5
    )
    _run_wordcount(12, 1, cfg, monkeypatch)
    runner = CliRunner()

    # M == current: a clear no-op, not an error and not a rewrite
    res = runner.invoke(cli_main, ["rescale", "--to", "1", store])
    assert res.exit_code == 0, res.output
    assert "already laid out for 1 worker(s)" in res.output

    def snap(d: str) -> dict:
        out = {}
        for dirpath, _dirs, files in os.walk(d):
            for fn in files:
                p = os.path.join(dirpath, fn)
                st = os.stat(p)
                out[p] = (st.st_mtime_ns, st.st_size)
        return out

    before = snap(store)
    totals_before = rescale_stats()["total"]
    res = runner.invoke(cli_main, ["rescale", "--to", "3", "--dry-run", store])
    assert res.exit_code == 0, res.output
    assert "dry run: would rescale 1 -> 3 worker(s)" in res.output
    # the plan names each stateful operator's split/merge action
    assert "split 1 piece(s) by key shard, merge into 3 worker(s)" in res.output
    assert "input tail chunks to re-route" in res.output
    # ...and sizes the state the target workers must absorb (ISSUE 8
    # satellite: estimated per-operator bytes, resident + spilled)
    assert "incl. spilled" in res.output
    assert "total stateful-operator bytes to redistribute" in res.output
    assert "MB/worker" in res.output
    assert snap(store) == before, "--dry-run must write NOTHING"
    assert rescale_stats()["total"] == totals_before, (
        "a dry run is not a rescale: the /metrics counter must not move"
    )
    # ...and the store still rescales for real afterwards
    res = runner.invoke(cli_main, ["rescale", "--to", "3", store])
    assert res.exit_code == 0, res.output
    with open(os.path.join(store, "cluster")) as f:
        assert json.load(f)["n_workers"] == 3


def test_rescale_dry_run_library_reports_plan(monkeypatch):
    cfg = _mem_cfg("resc-dry")
    _run_wordcount(12, 1, cfg, monkeypatch)
    root = MemoryBackend("resc-dry")
    keys_before = set(root.list_keys())
    report = rescale(root, 2, dry_run=True)
    assert report["dry_run"] is True
    assert report["from"] == 1 and report["to"] == 2
    assert set(root.list_keys()) == keys_before, "no staging keys on dry run"
    assert report["operators"], "the plan must name the stateful operators"
    for op in report["operators"]:
        assert op["mode"] in ("keyed", "pinned", "replicate", "unresolved")
        assert op["action"]
        assert len(op["chunks_per_source"]) == 1
        # per-operator state sizing: every present snapshot measures > 0
        # bytes (pickle headers alone are nonzero), and the rollup agrees
        assert len(op["state_bytes_per_source"]) == 1
        assert op["state_bytes"] == sum(
            b or 0 for b in op["state_bytes_per_source"]
        )
        if op["chunks_per_source"][0]:
            assert op["state_bytes"] > 0
    modes = {op["mode"] for op in report["operators"]}
    assert "keyed" in modes  # the groupby arena splits by key shard
    assert report["state_bytes_total"] == sum(
        op["state_bytes"] for op in report["operators"]
    )
    assert report["state_bytes_total"] > 0


def test_marker_io_errors_propagate():
    """A transient read error on the cluster marker must FAIL the boot,
    never be mistaken for an empty store (which would mount blank
    namespaces over a live layout)."""
    from pathway_tpu.persistence.layout import read_marker

    class FlakyBackend(MemoryBackend):
        def get_value(self, key):
            raise OSError("connection reset")

    with pytest.raises(OSError):
        read_marker(FlakyBackend())
    assert read_marker(MemoryBackend()) is None  # genuinely missing -> None
