"""Ported from the reference error-propagation suite
(`/root/reference/python/pathway/tests/test_errors.py`): table data and
expected outputs kept as the behavioral contract; harness adapted (output
table and `pw.global_error_log()` asserted separately — our
assert_table_equality takes one pair)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.error import ERROR_LOG
from pathway_tpu.internals.graph_runner import GraphRunner
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T, assert_table_equality_wo_index


@pytest.fixture(autouse=True)
def _clean():
    G.clear()
    ERROR_LOG.clear()
    yield
    G.clear()
    ERROR_LOG.clear()


def _run_with_log(table):
    log = pw.global_error_log().select(pw.this.message)
    caps = GraphRunner().run_tables(table, log)
    rows = sorted(tuple(r) for _, r in caps[0].state.iter_items())
    msgs = sorted(r[0] for _, r in caps[1].state.iter_items())
    return rows, msgs


def test_division_by_zero():
    # reference test_errors.py:22
    t1 = T(
        """
        a | b | c
        3 | 3 | 1
        4 | 0 | 2
        5 | 5 | 0
        6 | 2 | 3
        """
    )
    t2 = t1.select(x=pw.this.a // pw.this.b)
    t3 = t1.select(y=pw.this.a // pw.this.c)
    t4 = t1.select(pw.this.a, x=pw.fill_error(t2.x, -1), y=pw.fill_error(t3.y, -1))
    rows, msgs = _run_with_log(t4)
    assert rows == [(3, 1, 3), (4, -1, 2), (5, 1, -1), (6, 3, 2)]
    assert msgs == ["division by zero", "division by zero"]


def test_removal_of_error():
    # reference test_errors.py:62 — the error row is retracted later; the
    # log keeps the incident, the table does not keep the row
    t1 = T(
        """
          | a | b | __time__ | __diff__
        1 | 6 | 2 |     2    |     1
        2 | 5 | 0 |     4    |     1
        3 | 4 | 2 |     6    |     1
        2 | 5 | 0 |     8    |    -1
        """
    )
    t2 = t1.with_columns(c=pw.this.a // pw.this.b)
    rows, msgs = _run_with_log(t2)
    assert rows == [(4, 2, 2), (6, 2, 3)]
    assert msgs.count("division by zero") == 2


def test_filter_with_error_in_condition():
    # reference test_errors.py:98
    t1 = pw.debug.table_from_markdown(
        """
        a | b
        6 | 2
        5 | 5
        4 | 0
        3 | 3
        """
    )
    t2 = t1.with_columns(x=pw.this.a // pw.this.b)
    res = t2.filter(pw.this.x > 0)
    rows, msgs = _run_with_log(res)
    assert rows == [(3, 3, 1), (5, 5, 1), (6, 2, 3)]
    assert msgs == [
        "Error value encountered in filter condition, skipping the row",
        "division by zero",
    ]


def test_inner_join_with_error_in_condition():
    # reference test_errors.py:175
    t1 = pw.debug.table_from_markdown(
        """
        a | c
        1 | 1
        2 | 0
        3 | 1
        """
    ).with_columns(a=pw.this.a // pw.this.c)
    t2 = pw.debug.table_from_markdown("b\n1\n1\n2")
    res = t1.join(t2, pw.left.a == pw.right.b).select(
        pw.left.a, pw.left.c, pw.right.b
    )
    rows, msgs = _run_with_log(res)
    assert rows == [(1, 1, 1), (1, 1, 1)]
    assert msgs == [
        "Error value encountered in join condition, skipping the row",
        "division by zero",
    ]


def test_left_join_with_error_in_condition():
    # reference test_errors.py:216 — the error row still emits a PAD (its
    # key matched nothing), with the Error kept in the left column
    t1 = pw.debug.table_from_markdown(
        """
        a | c
        1 | 1
        2 | 0
        3 | 1
        """
    ).with_columns(a=pw.this.a // pw.this.c)
    t2 = pw.debug.table_from_markdown("b\n1\n1\n1\n2")
    res = t1.join_left(t2, pw.left.a == pw.right.b).select(
        a=pw.fill_error(pw.left.a, -1), c=pw.left.c, b=pw.right.b
    )
    rows, msgs = _run_with_log(res)
    assert rows == [
        (-1, 0, None), (1, 1, 1), (1, 1, 1), (1, 1, 1), (3, 1, None)
    ]
    assert "division by zero" in msgs


def test_left_join_preserving_id_duplicate_key():
    # reference test_errors.py:483 — two matches for one id-side row
    # degrade to Error in the right columns + a "duplicate key" log entry
    t1 = pw.debug.table_from_markdown("a\n1\n2\n3")
    t2 = pw.debug.table_from_markdown("b\n1\n1\n1\n2")
    res = (
        t1.join_left(t2, pw.left.a == pw.right.b, id=pw.left.id)
        .select(pw.left.a, pw.right.b)
        .with_columns(b=pw.fill_error(pw.this.b, -1))
    )
    rows, msgs = _run_with_log(res)
    assert rows == [(1, -1), (2, 2), (3, None)]
    assert any(m.startswith("duplicate key") for m in msgs)


def test_remove_errors():
    # reference test_errors.py:620
    t1 = T(
        """
        a | b | c
        3 | 3 | 1
        4 | 0 | 2
        5 | 5 | 0
        6 | 2 | 3
        """
    )
    t2 = t1.select(x=pw.this.a // pw.this.b)
    t3 = t1.select(y=pw.this.a // pw.this.c)
    t4 = t1.select(pw.this.a, x=t2.x, y=t3.y)
    res = t4.remove_errors()
    expected = T(
        """
        a | x | y
        3 | 1 | 3
        6 | 3 | 2
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_remove_errors_identity():
    # reference test_errors.py:651 — no errors: remove_errors is identity
    t1 = T(
        """
        a | b | c
        3 | 3 | 1
        4 | 1 | 2
        5 | 5 | 1
        6 | 2 | 3
        """
    )
    t2 = t1.select(pw.this.a, x=pw.this.a // pw.this.b, y=pw.this.a // pw.this.c)
    res = t2.remove_errors()
    expected = T(
        """
        a | x | y
        3 | 1 | 3
        4 | 4 | 2
        5 | 1 | 5
        6 | 3 | 2
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_groupby_with_error_in_grouping_column():
    # reference test_errors.py:717 — error group keys skip with a log
    t1 = T(
        """
        a | b | c
        3 | 3 | 1
        4 | 0 | 2
        5 | 5 | 0
        6 | 2 | 3
        6 | 6 | 2
        """
    )
    t2 = t1.select(x=pw.this.a // pw.this.b, y=pw.this.a // pw.this.c)
    res = t2.groupby(pw.this.x, pw.this.y).reduce(
        pw.this.x, pw.this.y, cnt=pw.reducers.count()
    )
    rows, msgs = _run_with_log(res)
    assert rows == [(1, 3, 2), (3, 2, 1)]
    assert msgs.count("division by zero") == 2
    assert (
        msgs.count(
            "Error value encountered in grouping columns, skipping the row"
        )
        == 2
    )


def test_global_error_log_clear_scopes_runs():
    # reference test_errors.py:1331 (clear) — a later run's log table only
    # carries that run's errors
    t = T("a | b\n1 | 0")
    r1 = t.select(x=pw.fill_error(pw.this.a // pw.this.b, -1))
    rows, msgs = _run_with_log(r1)
    assert msgs == ["division by zero"]
    G.clear()
    t2 = T("a | b\n4 | 2")
    r2 = t2.select(x=pw.this.a // pw.this.b)
    rows2, msgs2 = _run_with_log(r2)
    assert rows2 == [(2,)] and msgs2 == []


def test_groupby_skip_errors():
    # reference test_errors.py:794 — the groupby DEFAULT skips error cells
    @pw.reducers.stateful_single
    def stateful_sum(state, val):
        if state is None:
            return val
        return state + val

    t = T(
        """
        a | b |  c  | d | e
        1 | 1 | 1.5 | 1 | 1
        1 | 2 | 2.5 | 0 | 1
        1 | 3 | 3.5 | 1 | 0
        2 | 4 | 4.5 | 1 | 1
        2 | 5 | 5.5 | 1 | 0
        """
    ).with_columns(b=pw.this.b // pw.this.d, c=pw.this.c / pw.this.e)
    res = t.groupby(pw.this.a, _skip_errors=True).reduce(
        pw.this.a,
        i_sum=pw.reducers.sum(pw.this.b),
        i_min=pw.reducers.min(pw.this.b),
        f_sum=pw.reducers.sum(pw.this.c),
        cnt=pw.reducers.count(),
        st_sum=stateful_sum(pw.this.b),
    )
    rec = res.select(
        pw.this.a, pw.this.i_sum, pw.this.i_min, pw.this.f_sum,
        pw.this.cnt, pw.this.st_sum,
    )
    rows, _ = _run_with_log(rec)
    assert rows == [(1, 4, 1, 4.0, 3, 4), (2, 9, 4, 4.5, 2, 9)]


def test_groupby_propagate_errors():
    # reference test_errors.py:840 — _skip_errors=False: aggregates of a
    # group holding an error read Error (fill_error recovers them)
    t = T(
        """
        a | b |  c  | d | e
        1 | 1 | 1.5 | 1 | 1
        1 | 2 | 2.5 | 0 | 1
        1 | 3 | 3.5 | 1 | 0
        2 | 4 | 4.5 | 1 | 1
        2 | 5 | 5.5 | 1 | 0
        """
    ).with_columns(b=pw.this.b // pw.this.d, c=pw.this.c / pw.this.e)
    res = t.groupby(pw.this.a, _skip_errors=False).reduce(
        pw.this.a,
        i_sum=pw.fill_error(pw.reducers.sum(pw.this.b), -1),
        i_min=pw.fill_error(pw.reducers.min(pw.this.b), -1),
        f_sum=pw.fill_error(pw.reducers.sum(pw.this.c), -1),
        cnt=pw.reducers.count(),
    )
    rows, _ = _run_with_log(res)
    assert rows == [(1, -1, -1, -1, 3), (2, 9, 4, -1, 2)]


def test_local_logs():
    # reference test_errors.py:262 — errors route to the local log whose
    # scope BUILT the failing expression, and to the global log
    t1 = T(
        """
        a | b | c
        3 | 3 | 9
        4 | 0 | 2
        5 | 5 | 0
        6 | 2 | 3
        """
    )
    with pw.local_error_log() as error_log_1:
        t2 = t1.select(x=pw.this.a // pw.this.b)
    with pw.local_error_log() as error_log_2:
        t3 = t1.select(y=pw.this.a // pw.this.c)

    t4 = t1.select(
        pw.this.a,
        x=pw.fill_error(t2.x, -1),
        y=pw.fill_error(t3.y, -1),
    )
    g = pw.global_error_log().select(pw.this.message)
    l1 = error_log_1.select(pw.this.message)
    l2 = error_log_2.select(pw.this.message)
    caps = GraphRunner().run_tables(t4, g, l1, l2)
    rows = sorted(tuple(r) for _, r in caps[0].state.iter_items())
    assert rows == [(3, 1, 0), (4, -1, 2), (5, 1, -1), (6, 3, 2)]
    gmsgs = sorted(r[0] for _, r in caps[1].state.iter_items())
    l1msgs = [r[0] for _, r in caps[2].state.iter_items()]
    l2msgs = [r[0] for _, r in caps[3].state.iter_items()]
    assert gmsgs == ["division by zero", "division by zero"]
    assert l1msgs == ["division by zero"]  # t2's b==0 row
    assert l2msgs == ["division by zero"]  # t3's c==0 row


def test_deduplicate_with_error_in_instance():
    # reference test_errors.py:756
    t1 = T(
        """
        a | b | __time__
        2 | 1 |     2
        2 | 2 |     4
        5 | 0 |     6
        3 | 2 |     8
        1 | 1 |    10
        """
    )

    def acceptor(new_value, old_value) -> bool:
        return new_value > old_value

    res = t1.deduplicate(
        value=pw.this.a, instance=2 / pw.this.b, acceptor=acceptor
    )
    rows, msgs = _run_with_log(res)
    assert sorted(r[:2] for r in rows) == [(2, 1), (3, 2)]
    assert "division by zero" in msgs
    assert (
        "Error value encountered in deduplicate instance, skipping the row"
        in msgs
    )


def test_deduplicate_with_error_in_value():
    # reference test_errors.py:979 — the error row neither replaces the
    # accepted value nor reaches the acceptor
    t1 = T(
        """
        a | b | __time__
        2 | 1 |     2
        4 | 0 |     4
        3 | 1 |     6
        """
    ).select(a=pw.this.a // pw.this.b)

    def acceptor(new_value, old_value) -> bool:
        return new_value > old_value

    res = t1.deduplicate(value=pw.this.a, acceptor=acceptor)
    rows, _ = _run_with_log(res)
    assert rows == [(3,)]


def test_deduplicate_with_error_in_acceptor():
    # reference test_errors.py:1004 — a raising acceptor skips the row
    t1 = T(
        """
        a | __time__
        2 |     2
        4 |     4
        3 |     6
        """
    )

    def acceptor(new_value, old_value) -> bool:
        if new_value == 4:
            raise ValueError("encountered 4")
        return new_value > old_value

    res = t1.deduplicate(value=pw.this.a, acceptor=acceptor)
    rows, msgs = _run_with_log(res)
    assert rows == [(3,)]
    assert "ValueError: encountered 4" in msgs
