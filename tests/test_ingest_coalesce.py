"""Backpressure coalescing of backlogged commit windows
(``PATHWAY_INGEST_COALESCE_WINDOWS``, io/python.py) + the plain-chunk
fast flag on the rowwise ingest path."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io.python import ConnectorSubject, PythonSubjectSource


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def _drain_windows(n_windows: int, rows_per: int = 3) -> list:
    subj = ConnectorSubject()
    src = PythonSubjectSource(subj, ["x"], {}, None, None, dtypes={})
    v = 0
    for _ in range(n_windows):
        for _ in range(rows_per):
            subj.next(x=v)
            v += 1
        subj.commit()
    return src.poll()


def test_backlog_beyond_threshold_merges_into_one_delta(monkeypatch):
    deltas = _drain_windows(12)
    assert len(deltas) == 1  # default threshold 8: backlog coalesced
    assert len(deltas[0]) == 36  # every row survives the merge


def test_small_backlog_keeps_per_window_ticks(monkeypatch):
    deltas = _drain_windows(5)
    assert len(deltas) == 5  # at-or-under threshold: one delta per commit


def test_knob_zero_disables_coalescing(monkeypatch):
    monkeypatch.setenv("PATHWAY_INGEST_COALESCE_WINDOWS", "0")
    deltas = _drain_windows(12)
    assert len(deltas) == 12


def test_merged_window_keeps_oldest_ingest_stamp(monkeypatch):
    subj = ConnectorSubject()
    src = PythonSubjectSource(subj, ["x"], {}, None, None, dtypes={})
    for w in range(10):
        subj.next(x=w)
        subj.commit()
    deltas = src.poll()
    stamps = src.take_ingest_stamps()
    assert len(deltas) == len(stamps) == 1
    assert stamps[0] is not None  # the backlog's oldest row anchors e2e


def test_persistence_disables_coalescing(tmp_path):
    """With persistence on, commit windows are part of the recorded
    replay contract: every pre-queued commit must keep its own tick even
    when the backlog exceeds the coalesce threshold."""
    from pathway_tpu.persistence import Backend, Config

    class Feed(ConnectorSubject):
        def run(self):
            for w in range(12):
                self.next(x=w)
                self.commit()

    t = pw.io.python.read(
        Feed(), schema=pw.schema_from_types(x=int),
        autocommit_duration_ms=None, name="coalesce-src",
    )
    times: list[int] = []
    pw.io.subscribe(t, on_time_end=lambda time: times.append(time))
    pw.run(persistence_config=Config(Backend.filesystem(str(tmp_path))))
    assert len(times) == 12  # one tick per commit window, none merged


def test_coalesced_stream_multiset_equal_with_retractions():
    """End-to-end: a backlog with mixed plain/retraction chunks coalesces
    without losing or ghosting rows (key derivation is content-based, so
    merged windows net out exactly like per-window processing)."""

    class Feed(ConnectorSubject):
        def run(self):
            for i in range(20):
                self.next(a=i)
                if i % 5 == 0:
                    self.commit()
            self._remove(a=3)
            self._remove(a=17)
            self.commit()

    t = pw.io.python.read(
        Feed(), schema=pw.schema_from_types(a=int),
        autocommit_duration_ms=10,
    )
    live: dict[int, int] = {}

    def on_change(key, row, time, is_addition):
        live[row["a"]] = live.get(row["a"], 0) + (1 if is_addition else -1)

    pw.io.subscribe(t, on_change=on_change)
    pw.run()
    got = sorted(k for k, n in live.items() if n > 0)
    assert got == [i for i in range(20) if i not in (3, 17)]
