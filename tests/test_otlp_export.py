"""OTLP export paths (internals/telemetry.py + observability/exporter.py):
loopback collector payload shapes, the idempotent ``_otlp_mark`` re-export
guard shared by the periodic flusher and the end-of-run hook, histogram
data points, and the never-raises contract against a refusing collector."""

from __future__ import annotations

import http.server
import json
import threading
import time

import pytest

from pathway_tpu.internals import telemetry, tracing
from pathway_tpu.internals.telemetry import OtlpExporter, export_from_env
from pathway_tpu.internals.tracing import Tracer
from pathway_tpu.observability.exporter import PeriodicFlusher
from pathway_tpu.observability.histogram import LogHistogram


class Collector:
    """Loopback OTLP/HTTP collector; ``mode`` = ok | refuse | hang-free
    error (connection reset via closing early)."""

    def __init__(self, mode: str = "ok"):
        collector = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n)) if n else {}
                collector.received.append((self.path, body))
                if collector.mode == "refuse":
                    self.send_response(503)
                    self.end_headers()
                    self.wfile.write(b"no")
                    return
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        self.mode = mode
        self.received: list = []
        self.server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        self.endpoint = f"http://127.0.0.1:{self.server.server_address[1]}"
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()

    def stop(self):
        self.server.shutdown()
        self.server.server_close()

    def paths(self):
        return [p for p, _ in self.received]


@pytest.fixture
def collector():
    c = Collector()
    yield c
    c.stop()


def _traced_tracer() -> Tracer:
    tracer = Tracer(None)
    with tracer.span("engine.run", worker=0):
        with tracer.span("tick", time=42):
            pass
    tracer.counter("engine_rows.w0", {"input": 5.0, "output": 3.0})
    return tracer


def test_traces_and_metrics_payload_shape(collector, monkeypatch):
    monkeypatch.setenv("PATHWAY_TELEMETRY_SERVER", collector.endpoint)
    monkeypatch.delenv("PATHWAY_MONITORING_SERVER", raising=False)
    tracer = _traced_tracer()
    export_from_env(tracer)
    assert "/v1/traces" in collector.paths()
    assert "/v1/metrics" in collector.paths()
    _, traces = next(x for x in collector.received if x[0] == "/v1/traces")
    scope_spans = traces["resourceSpans"][0]["scopeSpans"][0]
    names = {s["name"] for s in scope_spans["spans"]}
    assert {"engine.run", "tick"} <= names
    for s in scope_spans["spans"]:
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
        assert len(s["traceId"]) == 32 and len(s["spanId"]) == 16
    _, metrics = next(x for x in collector.received if x[0] == "/v1/metrics")
    m = metrics["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
    by_name = {x["name"]: x for x in m}
    assert by_name["engine_rows.w0.input"]["gauge"]["dataPoints"][0][
        "asDouble"
    ] == 5.0


def test_otlp_mark_guard_is_idempotent(collector, monkeypatch):
    monkeypatch.setenv("PATHWAY_TELEMETRY_SERVER", collector.endpoint)
    monkeypatch.delenv("PATHWAY_MONITORING_SERVER", raising=False)
    tracer = _traced_tracer()
    export_from_env(tracer)
    n_first = len(collector.received)
    assert n_first > 0
    # re-export with no new events: the mark guard suppresses the push
    export_from_env(tracer)
    assert len(collector.received) == n_first
    # new events → only the tail is exported
    with tracer.span("graph.build"):
        pass
    export_from_env(tracer)
    assert len(collector.received) > n_first
    _, traces = next(
        x for x in collector.received[n_first:] if x[0] == "/v1/traces"
    )
    tail_names = [
        s["name"]
        for s in traces["resourceSpans"][0]["scopeSpans"][0]["spans"]
    ]
    assert tail_names == ["graph.build"], "tail export must not resend"


def test_refusing_collector_never_raises(monkeypatch):
    refusing = Collector(mode="refuse")
    try:
        monkeypatch.setenv("PATHWAY_TELEMETRY_SERVER", refusing.endpoint)
        monkeypatch.delenv("PATHWAY_MONITORING_SERVER", raising=False)
        tracer = _traced_tracer()
        export_from_env(tracer)  # 503s swallowed
        assert refusing.received, "payload was still attempted"
        # flusher path also swallows refusals
        flusher = PeriodicFlusher(
            interval_s=3600, endpoints=[refusing.endpoint]
        )
        flusher.flush_once()
        assert flusher.flushes == 1
    finally:
        refusing.stop()


def test_unreachable_collector_never_raises(monkeypatch):
    monkeypatch.setenv("PATHWAY_TELEMETRY_SERVER", "http://127.0.0.1:9")
    tracer = _traced_tracer()
    export_from_env(tracer)  # connection refused swallowed
    exp = OtlpExporter("http://127.0.0.1:9")
    assert exp._post("/v1/traces", {"resourceSpans": []}) is False


def test_histogram_payload_shape():
    h = LogHistogram()
    for v in [1_000, 2_000, 1_000_000]:
        h.observe(v)
    exp = OtlpExporter("http://127.0.0.1:1", run_id="r9")
    payload = exp.histograms_payload(
        [("pathway.tick_duration", {"worker": 0}, h.snapshot())],
        1_000_000_000,
    )
    m = payload["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
    assert m[0]["name"] == "pathway.tick_duration"
    hist = m[0]["histogram"]
    assert hist["aggregationTemporality"] == 2
    pt = hist["dataPoints"][0]
    assert pt["count"] == "3"
    assert float(pt["sum"]) == pytest.approx(1_003_000 / 1e9)
    # OTLP invariant: len(bucketCounts) == len(explicitBounds) + 1
    assert len(pt["bucketCounts"]) == len(pt["explicitBounds"]) + 1
    assert sum(int(c) for c in pt["bucketCounts"]) == 3
    assert pt["explicitBounds"] == sorted(pt["explicitBounds"])


def test_periodic_flusher_exports_spans_and_histograms(collector, tmp_path):
    from pathway_tpu.observability.hub import ObservabilityHub
    from pathway_tpu.engine.executor import EngineStats

    tracer = Tracer(str(tmp_path / "t.json"))
    tracing._active = tracer
    tracing._env_checked = True
    tracing._programmatic = True
    try:
        with tracer.span("engine.run"):
            pass
        stats = EngineStats()
        stats.tick_duration.observe(5_000_000)
        hub = ObservabilityHub()
        hub.register_worker(0, stats)
        flusher = PeriodicFlusher(
            interval_s=3600, hub=hub, endpoints=[collector.endpoint]
        )
        flusher.flush_once()
        # crash-durable local trace file written mid-run
        assert (tmp_path / "t.json").exists()
        assert "/v1/traces" in collector.paths()
        hist_posts = [
            body
            for path, body in collector.received
            if path == "/v1/metrics"
            and any(
                "histogram" in m
                for m in body["resourceMetrics"][0]["scopeMetrics"][0][
                    "metrics"
                ]
            )
        ]
        assert hist_posts, "histogram snapshots not exported"
        n = len(collector.received)
        flusher.flush_once()  # no new spans → only histograms re-post
        trace_posts = [p for p, _ in collector.received[n:] if p == "/v1/traces"]
        assert trace_posts == []
    finally:
        tracing.deactivate()


def test_flusher_runs_on_interval(collector):
    flusher = PeriodicFlusher(interval_s=0.05, endpoints=[collector.endpoint])
    flusher.start()
    try:
        deadline = time.monotonic() + 5
        while flusher.flushes < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert flusher.flushes >= 2
    finally:
        flusher.stop()


def test_start_periodic_flusher_env_gating(monkeypatch):
    from pathway_tpu.observability.exporter import start_periodic_flusher

    monkeypatch.delenv("PATHWAY_TELEMETRY_SERVER", raising=False)
    monkeypatch.delenv("PATHWAY_MONITORING_SERVER", raising=False)
    monkeypatch.delenv("PATHWAY_TRACE_FILE", raising=False)
    tracing.deactivate()
    try:
        # nothing to flush → no thread
        assert start_periodic_flusher() is None
        # endpoint set but interval 0 → disabled
        monkeypatch.setenv("PATHWAY_TELEMETRY_SERVER", "http://127.0.0.1:9")
        monkeypatch.setenv("PATHWAY_TELEMETRY_FLUSH_S", "0")
        assert start_periodic_flusher() is None
        # endpoint + positive interval → running flusher
        monkeypatch.setenv("PATHWAY_TELEMETRY_FLUSH_S", "3600")
        flusher = start_periodic_flusher()
        assert flusher is not None
        flusher.stop()
    finally:
        tracing.deactivate()
