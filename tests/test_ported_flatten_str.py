"""Ported from the reference's flatten and string-namespace suites.

Sources: ``/root/reference/python/pathway/tests/test_flatten.py`` and
``.../expressions/test_string.py`` (VERDICT r4 item 7). Porting contract
as in ``tests/test_ported_common_1.py``; manifest in ``PORTED_TESTS.md``.
"""

from __future__ import annotations

import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.testing import T, assert_table_equality_wo_index


# -- flatten (test_flatten.py) -----------------------------------------------


def test_flatten_simple():  # ref :14
    tab = pw.debug.table_from_pandas(
        pd.DataFrame.from_dict({"col": [[1, 2, 3, 4]]})
    )
    res = tab.flatten(pw.this.col, origin_id="origin_id")
    df = pw.debug.table_to_pandas(res)
    assert sorted(df["col"].tolist()) == [1, 2, 3, 4]
    # every exploded row points back at its ONE parent
    assert len(set(df["origin_id"].tolist())) == 1


def test_flatten_no_origin():  # ref :31
    tab = pw.debug.table_from_pandas(
        pd.DataFrame.from_dict({"col": [[1, 2, 3, 4]]})
    )
    res = tab.flatten(pw.this.col)
    assert sorted(pw.debug.table_to_pandas(res)["col"].tolist()) == [1, 2, 3, 4]


def test_flatten_inner_repeats():  # ref :48 (repeated values keep distinct rows)
    tab = pw.debug.table_from_pandas(
        pd.DataFrame.from_dict({"col": [[1, 1, 1, 3]]})
    )
    res = tab.flatten(pw.this.col)
    assert sorted(pw.debug.table_to_pandas(res)["col"].tolist()) == [1, 1, 1, 3]


def test_flatten_more_repeats():  # ref :65
    tab = pw.debug.table_from_pandas(
        pd.DataFrame.from_dict({"col": [[1, 1, 1, 3], [1]]})
    )
    res = tab.flatten(pw.this.col, origin_id="origin_id")
    df = pw.debug.table_to_pandas(res)
    assert sorted(df["col"].tolist()) == [1, 1, 1, 1, 3]
    assert len(set(df["origin_id"].tolist())) == 2


def test_flatten_empty_lists():  # ref :83
    tab = pw.debug.table_from_pandas(
        pd.DataFrame.from_dict({"col": [[], []]})
    )
    res = tab.flatten(pw.this.col)
    assert len(pw.debug.table_to_pandas(res)) == 0


# -- .str namespace (expressions/test_string.py) -----------------------------


def _col(res, name="c"):
    return pw.debug.table_to_pandas(res)[name].tolist()


def test_strip():  # ref :11
    t = pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [("  pad  ",), ("x",)]
    )
    res = t.select(c=pw.this.s.str.strip())
    assert sorted(_col(res)) == ["pad", "x"]


def test_count():  # ref :22
    t = pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [("banana",)]
    )
    res = t.select(c=pw.this.s.str.count("an"))
    assert _col(res) == [2]


def test_find_and_rfind():  # ref :87/:165
    t = pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [("abcabc",)]
    )
    res = t.select(
        f=pw.this.s.str.find("bc"),
        rf=pw.this.s.str.rfind("bc"),
        miss=pw.this.s.str.find("zz"),
    )
    df = pw.debug.table_to_pandas(res)
    assert df[["f", "rf", "miss"]].values.tolist() == [[1, 4, -1]]


def test_parse_int():  # ref :249
    t = pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [("42",), ("-7",)]
    )
    res = t.select(c=pw.this.s.str.parse_int())
    assert sorted(_col(res)) == [-7, 42]


def test_parse_float():  # ref :259
    t = pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [("1.5",), ("-0.25",)]
    )
    res = t.select(c=pw.this.s.str.parse_float())
    assert sorted(_col(res)) == [-0.25, 1.5]


def test_parse_bool():  # ref :285
    t = pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [("true",), ("false",)]
    )
    res = t.select(c=pw.this.s.str.parse_bool())
    assert sorted(_col(res), key=repr) == sorted([True, False], key=repr)


def test_parse_int_bad_value_is_error():  # ref :326 family
    t = pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [("42",), ("nope",)]
    )
    res = t.select(c=pw.fill_error(pw.this.s.str.parse_int(), -1))
    assert sorted(_col(res)) == [-1, 42]


def test_slice_upper_lower_len():  # string namespace basics used everywhere
    t = pw.debug.table_from_rows(
        pw.schema_from_types(s=str), [("Hello",)]
    )
    res = t.select(
        u=pw.this.s.str.upper(),
        lo=pw.this.s.str.lower(),
        n=pw.this.s.str.len(),
        sub=pw.this.s.str.slice(1, 3),
    )
    df = pw.debug.table_to_pandas(res)
    assert df[["u", "lo", "n", "sub"]].values.tolist() == [
        ["HELLO", "hello", 5, "el"]
    ]
