"""Streaming runtime: python ConnectorSubject sources, commit ticks,
rest_connector request/response over the live engine (reference test model:
python/pathway/tests/test_io.py + integration_tests/webserver)."""

from __future__ import annotations

import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    from pathway_tpu.io.http._server import terminate_all

    terminate_all()
    G.clear()


def test_python_subject_streaming_counts():
    class S(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(6):
                self.next(word="foo" if i % 2 == 0 else "bar")
                self.commit()

    t = pw.io.python.read(S(), schema=pw.schema_from_types(word=str))
    counts = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
    seen = []
    pw.io.subscribe(
        counts,
        on_change=lambda key, row, time, is_addition: seen.append(
            (row["word"], int(row["c"]), is_addition)
        ),
    )
    pw.run()
    # final state: foo=3, bar=3 — last addition per word wins
    final = {}
    for word, c, add in seen:
        if add:
            final[word] = c
    assert final == {"foo": 3, "bar": 3}
    # incremental: count for foo must have passed through 1, 2, 3
    foo_adds = [c for w, c, add in seen if w == "foo" and add]
    assert foo_adds == [1, 2, 3]


def test_python_subject_retraction():
    class S(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k="a", v=1)
            self.commit()
            self._remove(k="a", v=1)
            self.commit()

    t = pw.io.python.read(S(), schema=pw.schema_from_types(k=str, v=int))
    events = []
    pw.io.subscribe(
        t,
        on_change=lambda key, row, time, is_addition: events.append(
            (row["k"], is_addition)
        ),
    )
    pw.run()
    assert events == [("a", True), ("a", False)]


def test_rest_connector_roundtrip():
    queries, writer = pw.io.http.rest_connector(
        host="127.0.0.1",
        port=18412,
        schema=pw.schema_from_types(query=str),
    )
    results = queries.select(result=pw.apply(lambda q: q[::-1], pw.this.query))
    writer(results)

    answers = []

    def client():
        import requests

        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                r = requests.post(
                    "http://127.0.0.1:18412/", json={"query": "abc"}, timeout=10
                )
                answers.append((r.status_code, r.json()))
                break
            except Exception:
                time.sleep(0.1)
        from pathway_tpu.io.http._server import terminate_all

        terminate_all()

    th = threading.Thread(target=client, daemon=True)
    th.start()
    pw.run()
    th.join(timeout=10)
    assert answers == [(200, "cba")]


def test_rest_connector_missing_field_400():
    queries, writer = pw.io.http.rest_connector(
        host="127.0.0.1",
        port=18413,
        schema=pw.schema_from_types(query=str),
    )
    writer(queries.select(result=pw.this.query))

    status = []

    def client():
        import requests

        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                r = requests.post(
                    "http://127.0.0.1:18413/", json={"wrong": 1}, timeout=10
                )
                status.append(r.status_code)
                break
            except Exception:
                time.sleep(0.1)
        from pathway_tpu.io.http._server import terminate_all

        terminate_all()

    th = threading.Thread(target=client, daemon=True)
    th.start()
    pw.run()
    th.join(timeout=10)
    assert status == [400]


def test_next_batch_reused_buffer_is_copied():
    """A subject refilling ONE preallocated ndarray across next_batch calls
    must not corrupt engine keys (the per-array hash memo assumes the
    engine owns its columns — review finding)."""
    import numpy as np

    G.clear()
    buf = np.empty(1500, dtype=object)

    class Feed(pw.io.python.ConnectorSubject):
        def run(self):
            for tag in ("a", "b"):
                buf[:] = [f"{tag}{i}" for i in range(1500)]
                self.next_batch({"word": buf})
                self.commit()

    t = pw.io.python.read(
        Feed(), schema=pw.schema_from_types(word=str),
        autocommit_duration_ms=None,
    )
    counts = t.groupby(pw.this.word).reduce(
        pw.this.word, c=pw.reducers.count()
    )
    acc = {}
    pw.io.subscribe(
        counts,
        on_change=lambda key, row, time, is_addition: (
            acc.__setitem__(row["word"], row["c"]) if is_addition else None
        ),
    )
    pw.run()
    # 3000 distinct words, each counted exactly once
    assert len(acc) == 3000
    assert all(v == 1 for v in acc.values())


def test_fs_streaming_object_semantics(tmp_path):
    """with_metadata=True routes fs streaming through the object scanner
    (reference posix_like.rs): a modified file retracts its old version's
    rows, a deleted file retracts everything it contributed."""
    p = tmp_path / "log.csv"
    p.write_text("word\nalpha\n")
    extra = tmp_path / "extra.csv"

    t = pw.io.csv.read(
        str(tmp_path), schema=pw.schema_from_types(word=str),
        mode="streaming", with_metadata=True,
        autocommit_duration_ms=100,
    )
    assert "_metadata" in t.column_names()
    counts = t.groupby(pw.this.word).reduce(
        pw.this.word, c=pw.reducers.count()
    )
    acc = {}
    pw.io.subscribe(
        counts,
        on_change=lambda key, row, time, is_addition: (
            acc.__setitem__(row["word"], row["c"])
            if is_addition
            else acc.pop(row["word"], None)
        ),
    )

    def writer():
        time.sleep(1.6)
        p.write_text("word\ngamma\nbeta\n")
        extra.write_text("word\ndelta\n")
        time.sleep(2.2)
        extra.unlink()
        time.sleep(2.2)
        from pathway_tpu.internals.run import request_stop

        request_stop()

    threading.Thread(target=writer, daemon=True).start()
    pw.run()
    assert sorted(acc.items()) == [("beta", 1), ("gamma", 1)]


class _FakeWebserver:
    """Just enough surface for rest_connector's unit tests: route
    registry + terminate hook, no sockets."""

    def __init__(self):
        self._routes = {}
        self._loop = None

    def _add_route(self, route, methods, handler):
        pass

    def terminate(self):
        pass


class TestServeQuiescent:
    def _capture_writer(self, monkeypatch):
        """Build a rest (queries, writer) pair on a fake webserver, route
        the writer's subscribe() into captured closures and _complete()
        into a recorded list."""
        import pathway_tpu.io as pwio
        from pathway_tpu.io.http._server import _RestSubject

        captured = {}

        def fake_subscribe(table, **kwargs):
            captured.update(kwargs)

        monkeypatch.setattr(pwio, "subscribe", fake_subscribe)
        completed = []
        monkeypatch.setattr(
            _RestSubject,
            "_complete",
            lambda self, key, value: completed.append((key, value)),
        )
        queries, writer = pw.io.http.rest_connector(
            webserver=_FakeWebserver(),
            schema=pw.schema_from_types(query=str),
        )
        writer(queries.select(result=pw.this.query))
        return captured, completed

    def test_quiescent_holds_until_frontier(self, monkeypatch):
        """Frontier-quiescent respond(): with the knob on (default), the
        HTTP future resolves only at on_time_end — a later wave in the
        same commit tick that retracts + replaces the first emission wins,
        and the client never sees the partial value."""
        monkeypatch.delenv("PATHWAY_SERVE_QUIESCENT", raising=False)
        captured, completed = self._capture_writer(monkeypatch)
        on_change = captured["on_change"]
        on_time_end = captured["on_time_end"]

        # wave 1: an early operator emits a partial answer
        on_change(7, {"result": "partial"}, 1, True)
        assert completed == []  # held — frontier has not passed
        # wave 2 (same tick): downstream retracts it and emits the full one
        on_change(7, {"result": "partial"}, 1, False)
        on_change(7, {"result": "full"}, 1, True)
        assert completed == []
        # frontier passes every operator on the path: respond now
        on_time_end(1)
        assert completed == [(7, "full")]
        # the buffer drained — a later tick does not re-complete
        on_time_end(2)
        assert completed == [(7, "full")]

    def test_legacy_first_emission_resolves_immediately(self, monkeypatch):
        """PATHWAY_SERVE_QUIESCENT=0 restores the legacy first-emission
        behavior: the partial value goes out the moment it appears."""
        monkeypatch.setenv("PATHWAY_SERVE_QUIESCENT", "0")
        captured, completed = self._capture_writer(monkeypatch)
        assert "on_time_end" not in captured  # legacy arm never buffers
        captured["on_change"](7, {"result": "partial"}, 1, True)
        assert completed == [(7, "partial")]

    def test_quiescent_rest_over_collapsed_index_join(self):
        """End-to-end serve smoke on the collapsed DataIndex join: the
        quiescent default answers the SETTLED top-k row — the cascade
        query → BM25 index join → collapse → select all quiesces before
        the HTTP future resolves."""
        from pathway_tpu import indexing
        from pathway_tpu.internals.table_io import rows_to_table

        queries, writer = pw.io.http.rest_connector(
            host="127.0.0.1",
            port=18414,
            schema=pw.schema_from_types(query=str),
        )
        docs = rows_to_table(
            ["name", "text"],
            [
                ("a", "the quick brown fox jumps over the lazy dog"),
                ("b", "pack my box with five dozen liquor jugs"),
                ("c", "the brown dog sleeps by the fire"),
            ],
        )
        inner = indexing.TantivyBM25(data_column=docs.text)
        jr = indexing.DataIndex(docs, inner).query_as_of_now(
            queries.query, number_of_matches=2
        )
        writer(jr.select(result=pw.right.name))

        answers = []

        def client():
            import requests

            deadline = time.time() + 10
            while time.time() < deadline:
                try:
                    r = requests.post(
                        "http://127.0.0.1:18414/",
                        json={"query": "brown dog"},
                        timeout=10,
                    )
                    answers.append((r.status_code, r.json()))
                    break
                except Exception:
                    time.sleep(0.1)
            from pathway_tpu.io.http._server import terminate_all

            terminate_all()

        th = threading.Thread(target=client, daemon=True)
        th.start()
        pw.run()
        th.join(timeout=10)
        assert len(answers) == 1
        code, body = answers[0]
        assert code == 200
        assert sorted(body) == ["a", "c"]
