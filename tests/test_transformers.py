"""Legacy @pw.transformer class API (internals/row_transformer.py —
reference ``python/pathway/internals/row_transformer.py`` +
``tests/test_transformers.py``)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T, run_table


@pytest.fixture(autouse=True)
def _fresh():
    G.clear()
    yield
    G.clear()


def rows(table):
    state, _ = run_table(table)
    return dict(state)


def test_simple_transformer():
    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            arg = pw.input_attribute()

            @pw.output_attribute
            def ret(self) -> int:
                return self.arg + 1

    t = T("arg\n1\n2\n3")
    got = rows(foo_transformer(t).table)
    # keyed by the INPUT rows' ids, values incremented
    src = rows(t.select(pw.this.arg))
    assert {k: (v[0] + 1,) for k, v in src.items()} == got


def test_aux_objects_and_attribute():
    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            arg = pw.input_attribute()

            const = 10

            def fun(self, a) -> int:
                return a * self.arg + self.const

            @staticmethod
            def sfun(b) -> int:
                return b * 100

            @pw.attribute
            def attr(self) -> int:
                return self.arg / 2

            @pw.output_attribute
            def ret(self) -> int:
                return (
                    self.arg + self.const + self.fun(1)
                    + self.sfun(self.arg) + self.attr
                )

    t = T("arg\n10\n20\n30")
    got = sorted(v[0] for v in rows(foo_transformer(t).table).values())
    # reference test_aux_objects expects 1045/2070/3095
    assert got == [1045.0, 2070.0, 3095.0]


def test_pointer_chasing_across_tables():
    @pw.transformer
    class list_traversal:
        class nodes(pw.ClassArg):
            next = pw.input_attribute()
            val = pw.input_attribute()

        class requests(pw.ClassArg):
            node = pw.input_attribute()
            steps = pw.input_attribute()

            @pw.output_attribute
            def reached_value(self) -> int:
                node = self.transformer.nodes[self.node]
                for _ in range(self.steps):
                    node = self.transformer.nodes[node.next]
                return node.val

    raw = T("k | nxt | val\n1 | 2 | 11\n2 | 3 | 12\n3 | 3 | 13").with_id_from(
        pw.this.k
    )
    nodes = raw.select(next=raw.pointer_from(raw.nxt), val=raw.val)
    req0 = T("node | steps\n1 | 1\n3 | 0")
    requests = req0.select(
        node=raw.pointer_from(req0.node), steps=req0.steps
    )
    out = list_traversal(nodes, requests).requests
    assert sorted(v[0] for v in rows(out).values()) == [12, 13]


def test_output_attribute_rename():
    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            arg = pw.input_attribute()

            @pw.output_attribute(output_name="foo")
            def ret(self) -> int:
                return self.arg + 1

    t = T("arg\n1")
    out = foo_transformer(t).table
    assert out.column_names() == ["foo"]
    assert sorted(v[0] for v in rows(out).values()) == [2]


def test_output_attributes_reference_each_other():
    @pw.transformer
    class chain:
        class table(pw.ClassArg):
            a = pw.input_attribute()

            @pw.output_attribute
            def b(self) -> int:
                return self.a * 2

            @pw.output_attribute
            def c(self) -> int:
                return self.b + 1  # depends on another output attribute

    t = T("a\n3")
    assert list(rows(chain(t).table).values()) == [(6, 7)]


def test_transformer_is_incremental_across_ticks():
    @pw.transformer
    class doubler:
        class table(pw.ClassArg):
            v = pw.input_attribute()

            @pw.output_attribute
            def d(self) -> int:
                return self.v * 2

    t = T(
        """
        v | __time__ | __diff__
        1 | 2        | 1
        5 | 4        | 1
        1 | 6        | -1
        """
    )
    assert sorted(v[0] for v in rows(doubler(t).table).values()) == [10]


def test_method_markers_refused():
    with pytest.raises(NotImplementedError):
        pw.method(lambda self: 1)
    with pytest.raises(NotImplementedError):
        pw.input_method(int)


def test_call_signature_validation():
    @pw.transformer
    class one:
        class table(pw.ClassArg):
            a = pw.input_attribute()

            @pw.output_attribute
            def b(self):
                return self.a

    t = T("a\n1")
    with pytest.raises(TypeError, match="takes 1 table"):
        one(t, t)
    with pytest.raises(TypeError, match="no table"):
        one(tabel=t)
    with pytest.raises(TypeError, match="both"):
        one(t, table=t)


def test_input_only_class_error_is_helpful():
    @pw.transformer
    class tf:
        class src(pw.ClassArg):
            a = pw.input_attribute()

        class out(pw.ClassArg):
            b = pw.input_attribute()

            @pw.output_attribute
            def c(self):
                return self.b

    with pytest.raises(AttributeError, match="no output attributes"):
        tf(T("a\n1"), T("b\n2")).src


def test_output_attribute_rename_non_decorator():
    def fn(self):
        return self.a + 1

    @pw.transformer
    class tf:
        class table(pw.ClassArg):
            a = pw.input_attribute()
            ret = pw.output_attribute(fn, output_name="foo")

    out = tf(T("a\n1")).table
    assert out.column_names() == ["foo"]
