"""UDF machinery: sync/async executors, caching, retries, wrappers
(reference ``python/pathway/internals/udfs/`` + ``test_udf.py``)."""

from __future__ import annotations

import asyncio
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T, assert_table_equality
from pathway_tpu.udfs import (
    DiskCache,
    ExponentialBackoffRetryStrategy,
    FixedDelayRetryStrategy,
    InMemoryCache,
    async_executor,
    coerce_async,
    udf,
    udf_async,
    with_cache_strategy,
    with_capacity,
    with_retry_strategy,
    with_timeout,
)


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def _col(table, col):
    cap = pw.internals.graph_runner.GraphRunner().run_tables(table)[0]
    names = table.column_names()
    return sorted(r[names.index(col)] for _, r in cap.state.iter_items())


def test_sync_udf_decorator():
    @udf
    def double(x: int) -> int:
        return 2 * x

    t = T("a\n1\n2\n3")
    assert _col(t.select(b=double(pw.this.a)), "b") == [2, 4, 6]


def test_async_udf_runs_on_event_loop():
    calls = []

    @udf_async
    async def slow_double(x: int) -> int:
        calls.append(x)
        await asyncio.sleep(0.01)
        return 2 * x

    t = T("a\n1\n2\n3")
    assert _col(t.select(b=slow_double(pw.this.a)), "b") == [2, 4, 6]
    assert sorted(calls) == [1, 2, 3]


def test_async_udf_capacity_limits_concurrency():
    live = {"now": 0, "max": 0}

    @udf_async(executor=async_executor(capacity=2))
    async def probe(x: int) -> int:
        live["now"] += 1
        live["max"] = max(live["max"], live["now"])
        await asyncio.sleep(0.03)
        live["now"] -= 1
        return x

    t = T("a\n" + "\n".join(str(i) for i in range(6)))
    assert _col(t.select(b=probe(pw.this.a)), "b") == list(range(6))
    assert live["max"] <= 2


def test_udf_in_memory_cache_dedupes_calls():
    calls = []

    @udf(cache_strategy=InMemoryCache())
    def tracked(x: int) -> int:
        calls.append(x)
        return x + 10

    t = T("a\n5\n5\n5\n7")
    assert _col(t.select(b=tracked(pw.this.a)), "b") == [15, 15, 15, 17]
    assert sorted(calls) == [5, 7]  # one evaluation per distinct argument


def test_disk_cache_survives_restart(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_PERSISTENT_STORAGE", str(tmp_path))
    calls = []

    def make():
        @udf(cache_strategy=DiskCache(name="f"))
        def costly(x: int) -> int:
            calls.append(x)
            return x * x

        t = T("a\n3\n4")
        return _col(t.select(b=costly(pw.this.a)), "b")

    assert make() == [9, 16]
    G.clear()
    # simulate a process restart: the in-process shelf handle is dropped,
    # forcing the second run to actually read back from disk
    for store in DiskCache._open_stores.values():
        store.close()
    DiskCache._open_stores.clear()
    assert make() == [9, 16]
    assert sorted(calls) == [3, 4]  # second run served from disk


def test_disk_cache_shared_path_does_not_cross_contaminate(tmp_path, monkeypatch):
    """Two different functions landing on the same store file (same name)
    must not serve each other's cached results."""
    monkeypatch.setenv("PATHWAY_PERSISTENT_STORAGE", str(tmp_path))
    cache = DiskCache(name="shared")
    f = cache.wrap(lambda x: x + 1)
    g = cache.wrap(lambda x: x * 100)
    assert f(5) == 6
    assert g(5) == 500  # not f's cached 6


def test_retry_strategy_retries_until_success():
    attempts = {"n": 0}

    @udf_async(executor=async_executor(
        retry_strategy=FixedDelayRetryStrategy(max_retries=5, delay_ms=1)
    ))
    async def flaky(x: int) -> int:
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise RuntimeError("transient")
        return x

    t = T("a\n42")
    assert _col(t.select(b=flaky(pw.this.a)), "b") == [42]
    assert attempts["n"] == 3


def test_retry_strategy_exhaustion_propagates_as_error_row():
    @udf_async(executor=async_executor(
        retry_strategy=FixedDelayRetryStrategy(max_retries=2, delay_ms=1)
    ))
    async def always_fails(x: int) -> int:
        raise RuntimeError("permanent")

    t = T("a\n1")
    res = t.select(b=always_fails(pw.this.a))
    recovered = res.select(b=pw.fill_error(pw.this.b, -1))
    assert _col(recovered, "b") == [-1]


def test_wrapper_combinators():
    calls = []

    async def base(x):
        calls.append(x)
        await asyncio.sleep(0.001)
        return x * 3

    fn = with_cache_strategy(
        with_retry_strategy(
            with_capacity(with_timeout(base, timeout=5.0), capacity=4),
            ExponentialBackoffRetryStrategy(max_retries=2),
        ),
        InMemoryCache(),
    )

    async def drive():
        return [await fn(2), await fn(2), await fn(5)]

    assert asyncio.run(drive()) == [6, 6, 15]
    assert sorted(calls) == [2, 5]


def test_with_timeout_raises():
    async def sleepy(x):
        await asyncio.sleep(1.0)
        return x

    fn = with_timeout(sleepy, timeout=0.02)
    with pytest.raises(asyncio.TimeoutError):
        asyncio.run(fn(1))


def test_coerce_async_wraps_sync_fn():
    fn = coerce_async(lambda x: x + 1)

    async def drive():
        return await fn(41)

    assert asyncio.run(drive()) == 42


def test_udf_with_error_values():
    """A raising sync UDF produces per-row Error values, not a crashed run
    (reference Value::Error semantics)."""
    @udf
    def maybe_fail(x: int) -> int:
        if x == 2:
            raise ValueError("bad row")
        return x * 10

    t = T("a\n1\n2\n3")
    res = t.select(b=maybe_fail(pw.this.a))
    recovered = res.select(b=pw.fill_error(pw.this.b, 0))
    assert _col(recovered, "b") == [0, 10, 30]
