"""UDF machinery: sync/async executors, caching, retries, wrappers
(reference ``python/pathway/internals/udfs/`` + ``test_udf.py``)."""

from __future__ import annotations

import asyncio
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T, assert_table_equality
from pathway_tpu.udfs import (
    DiskCache,
    ExponentialBackoffRetryStrategy,
    FixedDelayRetryStrategy,
    InMemoryCache,
    async_executor,
    coerce_async,
    udf,
    udf_async,
    with_cache_strategy,
    with_capacity,
    with_retry_strategy,
    with_timeout,
)


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def _col(table, col):
    cap = pw.internals.graph_runner.GraphRunner().run_tables(table)[0]
    names = table.column_names()
    return sorted(r[names.index(col)] for _, r in cap.state.iter_items())


def test_sync_udf_decorator():
    @udf
    def double(x: int) -> int:
        return 2 * x

    t = T("a\n1\n2\n3")
    assert _col(t.select(b=double(pw.this.a)), "b") == [2, 4, 6]


def test_async_udf_runs_on_event_loop():
    calls = []

    @udf_async
    async def slow_double(x: int) -> int:
        calls.append(x)
        await asyncio.sleep(0.01)
        return 2 * x

    t = T("a\n1\n2\n3")
    assert _col(t.select(b=slow_double(pw.this.a)), "b") == [2, 4, 6]
    assert sorted(calls) == [1, 2, 3]


def test_async_udf_capacity_limits_concurrency():
    live = {"now": 0, "max": 0}

    @udf_async(executor=async_executor(capacity=2))
    async def probe(x: int) -> int:
        live["now"] += 1
        live["max"] = max(live["max"], live["now"])
        await asyncio.sleep(0.03)
        live["now"] -= 1
        return x

    t = T("a\n" + "\n".join(str(i) for i in range(6)))
    assert _col(t.select(b=probe(pw.this.a)), "b") == list(range(6))
    assert live["max"] <= 2


def test_udf_in_memory_cache_dedupes_calls():
    calls = []

    @udf(cache_strategy=InMemoryCache())
    def tracked(x: int) -> int:
        calls.append(x)
        return x + 10

    t = T("a\n5\n5\n5\n7")
    assert _col(t.select(b=tracked(pw.this.a)), "b") == [15, 15, 15, 17]
    assert sorted(calls) == [5, 7]  # one evaluation per distinct argument


def test_disk_cache_survives_restart(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_PERSISTENT_STORAGE", str(tmp_path))
    calls = []

    def make():
        @udf(cache_strategy=DiskCache(name="f"))
        def costly(x: int) -> int:
            calls.append(x)
            return x * x

        t = T("a\n3\n4")
        return _col(t.select(b=costly(pw.this.a)), "b")

    assert make() == [9, 16]
    G.clear()
    # simulate a process restart: the in-process shelf handle is dropped,
    # forcing the second run to actually read back from disk
    for store in DiskCache._open_stores.values():
        store.close()
    DiskCache._open_stores.clear()
    assert make() == [9, 16]
    assert sorted(calls) == [3, 4]  # second run served from disk


def test_disk_cache_shared_path_does_not_cross_contaminate(tmp_path, monkeypatch):
    """Two different functions landing on the same store file (same name)
    must not serve each other's cached results."""
    monkeypatch.setenv("PATHWAY_PERSISTENT_STORAGE", str(tmp_path))
    cache = DiskCache(name="shared")
    f = cache.wrap(lambda x: x + 1)
    g = cache.wrap(lambda x: x * 100)
    assert f(5) == 6
    assert g(5) == 500  # not f's cached 6


def test_retry_strategy_retries_until_success():
    attempts = {"n": 0}

    @udf_async(executor=async_executor(
        retry_strategy=FixedDelayRetryStrategy(max_retries=5, delay_ms=1)
    ))
    async def flaky(x: int) -> int:
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise RuntimeError("transient")
        return x

    t = T("a\n42")
    assert _col(t.select(b=flaky(pw.this.a)), "b") == [42]
    assert attempts["n"] == 3


def test_retry_strategy_exhaustion_propagates_as_error_row():
    @udf_async(executor=async_executor(
        retry_strategy=FixedDelayRetryStrategy(max_retries=2, delay_ms=1)
    ))
    async def always_fails(x: int) -> int:
        raise RuntimeError("permanent")

    t = T("a\n1")
    res = t.select(b=always_fails(pw.this.a))
    recovered = res.select(b=pw.fill_error(pw.this.b, -1))
    assert _col(recovered, "b") == [-1]


def test_wrapper_combinators():
    calls = []

    async def base(x):
        calls.append(x)
        await asyncio.sleep(0.001)
        return x * 3

    fn = with_cache_strategy(
        with_retry_strategy(
            with_capacity(with_timeout(base, timeout=5.0), capacity=4),
            ExponentialBackoffRetryStrategy(max_retries=2),
        ),
        InMemoryCache(),
    )

    async def drive():
        return [await fn(2), await fn(2), await fn(5)]

    assert asyncio.run(drive()) == [6, 6, 15]
    assert sorted(calls) == [2, 5]


def test_with_timeout_raises():
    async def sleepy(x):
        await asyncio.sleep(1.0)
        return x

    fn = with_timeout(sleepy, timeout=0.02)
    with pytest.raises(asyncio.TimeoutError):
        asyncio.run(fn(1))


def test_coerce_async_wraps_sync_fn():
    fn = coerce_async(lambda x: x + 1)

    async def drive():
        return await fn(41)

    assert asyncio.run(drive()) == 42


def test_udf_with_error_values():
    """A raising sync UDF produces per-row Error values, not a crashed run
    (reference Value::Error semantics)."""
    @udf
    def maybe_fail(x: int) -> int:
        if x == 2:
            raise ValueError("bad row")
        return x * 10

    t = T("a\n1\n2\n3")
    res = t.select(b=maybe_fail(pw.this.a))
    recovered = res.select(b=pw.fill_error(pw.this.b, 0))
    assert _col(recovered, "b") == [0, 10, 30]


# -- apply AST-lift (traced pure-operator lambdas -> columnar kernels) -----


def test_apply_lift_matches_per_row_semantics():
    import pathway_tpu.debug as dbg

    t = T("a | b\n3 | 4\n5 | 0")
    out = t.select(
        c=pw.apply_with_type(lambda a, b: a * 2 + b, int, pw.this.a, pw.this.b)
    )
    assert sorted(dbg.table_to_pandas(out)["c"].tolist()) == [10, 10]


def test_apply_lift_preserves_error_semantics():
    import pathway_tpu.debug as dbg

    t = T("a | b\n8 | 2\n9 | 0")
    out = t.select(c=pw.fill_error(
        pw.apply_with_type(lambda a, b: a // b, int, pw.this.a, pw.this.b), -1
    ))
    assert sorted(dbg.table_to_pandas(out)["c"].tolist()) == [-1, 4]


def test_apply_impure_lambda_not_lifted():
    import pathway_tpu.debug as dbg

    seen = []

    def note(x):
        seen.append(x)
        return x + 1

    t = T("a\n1\n2\n3")
    out = t.select(c=pw.apply_with_type(note, int, pw.this.a))
    assert sorted(dbg.table_to_pandas(out)["c"].tolist()) == [2, 3, 4]
    # the side effect MUST run once per row — lifting would run it once
    assert len(seen) == 3


def test_apply_closure_lambda_not_lifted_late_binding():
    import pathway_tpu.debug as dbg

    # closure cells are late-binding in the per-row path; the bytecode gate
    # (LOAD_DEREF) must refuse to freeze them into a traced constant
    factor = [2]

    def fn(x):
        return x * factor[0]

    t = T("a\n10")
    out = t.select(c=pw.apply_with_type(fn, int, pw.this.a))
    assert dbg.table_to_pandas(out)["c"].tolist() == [20]


def test_apply_value_branching_falls_back():
    import pathway_tpu.debug as dbg

    t = T("a\n-2\n5")
    out = t.select(
        c=pw.apply_with_type(lambda a: a if a > 0 else 0, int, pw.this.a)
    )
    assert sorted(dbg.table_to_pandas(out)["c"].tolist()) == [0, 5]


def test_apply_lift_declared_float_over_int_args():
    import pathway_tpu.debug as dbg

    t = T("a\n3")
    out = t.select(c=pw.apply_with_type(lambda a: a * 2, float, pw.this.a))
    [v] = dbg.table_to_pandas(out)["c"].tolist()
    assert v == 6.0 and isinstance(v, float)


def test_apply_loop_lambda_not_lifted():
    import pathway_tpu.debug as dbg

    # iterating the argument must NOT be traced (a ColumnExpression has
    # __getitem__ but no __iter__ — legacy iteration would spin forever)
    def total(t):
        s = 0
        for v in t:
            s = s + v
        return s

    tt = pw.debug.table_from_rows(
        pw.schema_from_types(t=tuple), [((1, 2, 3),)]
    )
    out = tt.select(c=pw.apply_with_type(total, int, pw.this.t))
    assert dbg.table_to_pandas(out)["c"].tolist() == [6]


def test_apply_global_store_lambda_not_lifted():
    import pathway_tpu.debug as dbg

    def fn(x):
        global _lift_probe_last
        _lift_probe_last = x
        return x * 2

    t = T("a\n4")
    out = t.select(c=pw.apply_with_type(fn, int, pw.this.a))
    assert dbg.table_to_pandas(out)["c"].tolist() == [8]
    # the per-row store must have run with the row VALUE, not a placeholder
    assert _lift_probe_last == 4


def test_subject_tail_rows_flushed_without_commit():
    # run() returning without commit()/close() must not strand buffered rows
    class Feed(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(300):  # 256 chunk + 44 tail
                self.next(a=i)

    from pathway_tpu.internals.parse_graph import G as _G

    _G.clear()
    t = pw.io.python.read(
        Feed(), schema=pw.schema_from_types(a=int), autocommit_duration_ms=10
    )
    got = []
    pw.io.subscribe(
        t, on_change=lambda key, row, time, is_addition: got.append(row["a"])
    )
    pw.run()
    assert sorted(got) == list(range(300))
