"""Connector breadth: sqlite (static + CDC), debezium parsing, gated
connectors' error surface (reference test model: python/pathway/tests/test_io.py
+ tests/integration/test_sqlite.rs)."""

from __future__ import annotations

import sqlite3
import threading
import time

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def _make_db(path, rows):
    con = sqlite3.connect(path)
    con.execute("CREATE TABLE IF NOT EXISTS users (id INTEGER PRIMARY KEY, name TEXT)")
    con.executemany("INSERT OR REPLACE INTO users (id, name) VALUES (?, ?)", rows)
    con.commit()
    con.close()


def test_sqlite_static_read(tmp_path):
    db = tmp_path / "t.db"
    _make_db(db, [(1, "alice"), (2, "bob")])
    t = pw.io.sqlite.read(
        str(db), "users",
        pw.schema_builder({
            "id": pw.column_definition(dtype=int, primary_key=True),
            "name": pw.column_definition(dtype=str),
        }),
        mode="static",
    )
    df = pw.debug.table_to_pandas(t)
    assert sorted(zip(df["id"], df["name"])) == [(1, "alice"), (2, "bob")]


def test_sqlite_streaming_cdc(tmp_path):
    db = tmp_path / "t.db"
    _make_db(db, [(1, "alice")])
    schema = pw.schema_builder({
        "id": pw.column_definition(dtype=int, primary_key=True),
        "name": pw.column_definition(dtype=str),
    })
    t = pw.io.sqlite.read(str(db), "users", schema, mode="streaming")
    seen = []
    done = threading.Event()

    def on_change(key, row, time, is_addition):
        seen.append((row["id"], row["name"], is_addition))
        if len([e for e in seen if e[2]]) >= 3:
            done.set()

    pw.io.subscribe(t, on_change=on_change)

    def mutate():
        time.sleep(0.4)
        _make_db(db, [(2, "bob")])  # insert
        time.sleep(0.4)
        _make_db(db, [(1, "alicia")])  # update -> retract + insert
        done.wait(timeout=10)
        time.sleep(0.2)
        pw.request_stop()

    th = threading.Thread(target=mutate, daemon=True)
    th.start()
    pw.run()
    th.join()
    assert (1, "alice", True) in seen
    assert (2, "bob", True) in seen
    assert (1, "alice", False) in seen  # retraction of the old value
    assert (1, "alicia", True) in seen


def test_debezium_parse_and_read(tmp_path):
    from pathway_tpu.io.debezium import parse_debezium_message

    create = {"payload": {"op": "c", "after": {"id": 1, "v": "a"}}}
    update = {"payload": {"op": "u", "before": {"id": 1, "v": "a"},
                          "after": {"id": 1, "v": "b"}}}
    delete = {"payload": {"op": "d", "before": {"id": 1, "v": "b"}}}
    assert parse_debezium_message(create) == [(1, {"id": 1, "v": "a"})]
    assert parse_debezium_message(update) == [
        (-1, {"id": 1, "v": "a"}), (1, {"id": 1, "v": "b"})
    ]
    assert parse_debezium_message(delete) == [(-1, {"id": 1, "v": "b"})]

    import json

    cap = tmp_path / "cdc.jsonl"
    cap.write_text("\n".join(json.dumps(m) for m in [create, update, delete]))
    t = pw.io.debezium.read(
        input_file=str(cap),
        schema=pw.schema_builder({
            "id": pw.column_definition(dtype=int, primary_key=True),
            "v": pw.column_definition(dtype=str),
        }),
    )
    events = []
    pw.io.subscribe(t, on_change=lambda key, row, time, is_addition:
                    events.append((row["v"], is_addition)))
    pw.run()
    # final state empty: create a, update to b, delete b (intra-commit
    # ordering of a retract+insert under one key is not significant)
    from collections import Counter

    assert Counter(events) == Counter(
        [("a", True), ("a", False), ("b", True), ("b", False)]
    )


def test_gated_connectors_raise_importerror():
    t = pw.debug.table_from_rows(pw.schema_from_types(x=int), [(1,)])
    with pytest.raises(ImportError, match="confluent-kafka"):
        pw.io.kafka.read({"bootstrap.servers": "x"}, "topic")
    with pytest.raises(ImportError, match="psycopg"):
        pw.io.postgres.write(t, {}, "tbl")
    with pytest.raises(ImportError, match="elasticsearch"):
        pw.io.elasticsearch.write(t, host="x", index_name="i")
    with pytest.raises(ImportError, match="pymongo"):
        pw.io.mongodb.write(t, "mongodb://x", "db", "coll")
    with pytest.raises(ImportError, match="boto3"):
        pw.io.s3.read("s3://bucket/x")
    with pytest.raises(ImportError, match="nats-py"):
        pw.io.nats.read("nats://x:4222", "topic", format="plaintext")
    # deltalake needs no client library anymore: it implements the Delta
    # protocol over pyarrow (see test_connectors_destubbed.py)


def test_sqlite_streaming_recovery_no_double_count(tmp_path):
    """Restart with persistence must not re-emit pre-existing rows: the
    source rebuilds its diff state from the replayed snapshot
    (advisor finding r1: counts doubled after restart)."""
    from pathway_tpu.persistence import Backend, Config

    db = tmp_path / "t.db"
    _make_db(db, [(1, "foo"), (2, "bar"), (3, "foo")])
    pdir = tmp_path / "pstate"
    cfg = Config.simple_config(Backend.filesystem(str(pdir)))
    schema = pw.schema_builder({
        "id": pw.column_definition(dtype=int, primary_key=True),
        "name": pw.column_definition(dtype=str),
    })

    def run_until(n_adds, mutate=None):
        seen = []
        done = threading.Event()
        t = pw.io.sqlite.read(str(db), "users", schema, mode="streaming",
                              name="users")
        counts = t.groupby(pw.this.name).reduce(
            pw.this.name, c=pw.reducers.count()
        )

        def on_change(key, row, time, is_addition):
            seen.append((row["name"], int(row["c"]), is_addition))
            if sum(1 for *_, add in seen if add) >= n_adds:
                done.set()

        pw.io.subscribe(counts, on_change=on_change)

        def driver():
            if mutate is not None:
                time.sleep(0.4)
                mutate()
            done.wait(timeout=15)
            time.sleep(0.3)
            pw.request_stop()

        th = threading.Thread(target=driver, daemon=True)
        th.start()
        pw.run(persistence_config=cfg)
        th.join()
        return seen

    seen1 = run_until(2)
    assert {(w, c) for w, c, add in seen1 if add} >= {("foo", 2), ("bar", 1)}

    # engine is down; a new row arrives
    G.clear()
    _make_db(db, [(4, "baz")])
    seen2 = run_until(1)
    final2 = {w: c for w, c, add in seen2 if add}
    # only the new row's update appears; counts continue (no {foo:4, bar:2})
    assert final2 == {"baz": 1}
