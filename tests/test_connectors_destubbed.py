"""De-stubbed connectors, driven by fakes — only the client library import
is gated; all connector logic runs here.

- s3/minio/gdrive/pyfilesystem: the shared object scanner over a
  filesystem-backed fake endpoint (new/changed/deleted object detection).
- deltalake: real Delta protocol over pyarrow — full local round-trip.
- nats: in-process fake client; read drains subscription, write publishes
  time/diff messages.
- pubsub/bigquery: fake publisher/client sinks.
- airbyte: fake protocol runner with RECORD/STATE messages.
"""

from __future__ import annotations

import json
import os
import threading

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io._object_scanner import ObjectMeta, ObjectScanSource


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


class DirBackedS3(object):
    """Filesystem-backed fake S3 endpoint: objects are files under root."""

    def __init__(self, root):
        self.root = root

    def list_objects(self):
        for dirpath, _, files in os.walk(self.root):
            for f in sorted(files):
                p = os.path.join(dirpath, f)
                st = os.stat(p)
                yield ObjectMeta(
                    key=os.path.relpath(p, self.root),
                    version=f"{st.st_size}:{st.st_mtime_ns}",
                    size=st.st_size,
                    modified_at=st.st_mtime,
                )

    def read_object(self, key: str) -> bytes:
        with open(os.path.join(self.root, key), "rb") as f:
            return f.read()


def _drain(src):
    out = []
    src._next_poll = 0.0
    for d in src.poll():
        for key, row, diff in d.iter_rows():
            out.append((row, diff))
    return out


def test_object_scanner_add_change_delete(tmp_path):
    (tmp_path / "a.txt").write_text("hello\nworld\n")
    client = DirBackedS3(os.fspath(tmp_path))
    src = ObjectScanSource(client, "plaintext", None, ["data"])
    assert sorted(_drain(src)) == [(("hello",), 1), (("world",), 1)]
    assert _drain(src) == []  # unchanged listing: no re-emission

    (tmp_path / "b.txt").write_text("new\n")
    assert _drain(src) == [(("new",), 1)]

    # changed object: old rows retracted, new inserted
    os.utime(tmp_path / "a.txt", ns=(1, 1))  # force version change detection
    (tmp_path / "a.txt").write_text("hello\nthere\n")
    changes = sorted(_drain(src))
    assert (("world",), -1) in changes and (("there",), 1) in changes
    assert (("hello",), 1) not in dict((r, d) for r, d in changes if d < 0)

    (tmp_path / "b.txt").unlink()
    assert _drain(src) == [(("new",), -1)]


def test_s3_static_read_and_metadata(tmp_path):
    (tmp_path / "x.csv").write_text("word,n\nfoo,1\nbar,2\n")
    client = DirBackedS3(os.fspath(tmp_path))
    t = pw.io.s3.read(
        "s3://bucket/prefix", _client=client, format="csv",
        schema=pw.schema_from_types(word=str, n=int), mode="static",
    )
    cap = pw.internals.graph_runner.GraphRunner().run_tables(t)[0]
    rows = sorted(tuple(r) for _, r in cap.state.iter_items())
    assert rows == [("bar", 2), ("foo", 1)]

    # streaming source with metadata column exposes path/size
    t2 = pw.io.s3.read(
        "s3://b/p", _client=client, format="plaintext", with_metadata=True,
    )
    src = t2._params["build"]()
    got = _drain(src)
    assert len(got) == 3  # plaintext: every line of x.csv incl. header
    md = json.loads(got[0][0][1])
    assert md["path"] == "x.csv" and md["size"] > 0


def test_minio_delegates_to_s3(tmp_path):
    (tmp_path / "o.txt").write_text("payload")
    settings = pw.io.minio.MinIOSettings(
        endpoint="http://127.0.0.1:1", bucket_name="b",
        access_key="k", secret_access_key="s",
    )
    t = pw.io.minio.read(
        "path", minio_settings=settings, mode="static",
        format="plaintext_by_object",
        _client=DirBackedS3(os.fspath(tmp_path)),
    )
    cap = pw.internals.graph_runner.GraphRunner().run_tables(t)[0]
    assert [r for _, r in cap.state.iter_items()] == [("payload",)]


def test_pyfilesystem_fake_fs():
    class Info:
        def __init__(self, size):
            self.size = size
            self.modified = None

    class FakeFS:
        """Minimal PyFilesystem surface (walk/getinfo/readbytes)."""

        files = {"/docs/a.txt": b"alpha", "/docs/b.txt": b"beta"}

        def walk(self, path):
            class E:
                def __init__(self, name):
                    self.name = name

            yield "/docs", [], [E("a.txt"), E("b.txt")]

        def getinfo(self, path, namespaces=()):
            return Info(len(self.files[path]))

        def readbytes(self, path):
            return self.files[path]

    t = pw.io.pyfilesystem.read(FakeFS(), path="/docs", mode="static",
                                format="plaintext_by_object")
    cap = pw.internals.graph_runner.GraphRunner().run_tables(t)[0]
    assert sorted(r for _, r in cap.state.iter_items()) == [("alpha",), ("beta",)]


def test_gdrive_read_with_injected_client(tmp_path):
    (tmp_path / "doc1").write_bytes(b"contents-1")
    t = pw.io.gdrive.read(
        "folder-id", _client=DirBackedS3(os.fspath(tmp_path)), mode="static",
    )
    cap = pw.internals.graph_runner.GraphRunner().run_tables(t)[0]
    assert [r for _, r in cap.state.iter_items()] == [(b"contents-1",)]


# ---------------------------------------------------------------------------
# deltalake: real protocol round-trip over pyarrow


def test_deltalake_write_read_roundtrip(tmp_path):
    uri = os.fspath(tmp_path / "dtable")
    t = pw.debug.table_from_markdown(
        """
        word | n
        foo  | 1
        bar  | 2
        """
    )
    pw.io.deltalake.write(t, uri, min_commit_frequency=None)
    pw.run()

    # valid Delta layout: version-0 metaData + a data commit
    log = sorted(os.listdir(os.path.join(uri, "_delta_log")))
    assert log[0] == f"{0:020d}.json"
    v0 = [json.loads(line) for line in open(
        os.path.join(uri, "_delta_log", log[0])
    )]
    assert any("metaData" in a for a in v0) and any("protocol" in a for a in v0)
    assert len(log) >= 2  # data commit happened
    parquets = [f for f in os.listdir(uri) if f.endswith(".parquet")]
    assert parquets

    G.clear()
    back = pw.io.deltalake.read(uri, mode="static")
    cap = pw.internals.graph_runner.GraphRunner().run_tables(back)[0]
    rows = sorted(tuple(r) for _, r in cap.state.iter_items())
    assert rows == [("bar", 2), ("foo", 1)]


def test_deltalake_streaming_source_picks_up_new_versions(tmp_path):
    from pathway_tpu.io.deltalake import DeltaStreamSource, DeltaTableWriter

    uri = os.fspath(tmp_path / "dstream")
    writer = DeltaTableWriter(uri, ["w"], None, min_commit_frequency_ms=None)

    class B:
        def __init__(self, rows, diffs):
            self.data = {"w": [r[0] for r in rows]}
            self.diffs = diffs

    writer.add_batch(2, B([("x",), ("y",)], [1, 1]))
    writer.flush()

    src = DeltaStreamSource(uri, ["w"], poll_interval_s=0)
    got = []
    for d in src.poll():
        got.extend((row, diff) for _, row, diff in d.iter_rows())
    assert sorted(got) == [(("x",), 1), (("y",), 1)]
    assert src.poll() == []  # no new versions

    writer.add_batch(4, B([("x",)], [-1]))  # retraction rides diff column
    writer.flush()
    src._next_poll = 0.0
    (d,) = src.poll()
    assert [(row, diff) for _, row, diff in d.iter_rows()] == [(("x",), -1)]
    # offset resume: a fresh source seeked past everything sees nothing
    src2 = DeltaStreamSource(uri, ["w"], poll_interval_s=0)
    src2.seek(src.offset_state())
    assert src2.poll() == []


def test_deltalake_remove_actions_retract(tmp_path):
    """DELETE/OPTIMIZE-style `remove` actions drop the file's rows in both
    static and streaming modes."""
    from pathway_tpu.io.deltalake import (
        DeltaStreamSource, DeltaTableWriter, _list_versions, _version_actions,
    )

    uri = os.fspath(tmp_path / "drm")
    writer = DeltaTableWriter(uri, ["w"], None, min_commit_frequency_ms=None)

    class B:
        def __init__(self, rows):
            self.data = {"w": [r[0] for r in rows]}
            self.diffs = [1] * len(rows)

    writer.add_batch(2, B([("x",), ("y",)]))
    writer.flush()
    src = DeltaStreamSource(uri, ["w"], poll_interval_s=0)
    assert len(src.poll()) == 1

    # emulate a DELETE: remove the data file via a remove action
    (added, _) = _version_actions(uri, _list_versions(uri)[-1])
    writer._commit_actions([{"remove": {"path": added[0], "dataChange": True}}])

    src._next_poll = 0.0
    (d,) = src.poll()
    assert sorted((row, diff) for _, row, diff in d.iter_rows()) == [
        (("x",), -1), (("y",), -1)
    ]
    G.clear()
    back = pw.io.deltalake.read(uri, mode="static")
    cap = pw.internals.graph_runner.GraphRunner().run_tables(back)[0]
    assert list(cap.state.iter_items()) == []


def test_static_with_metadata_matches_streaming(tmp_path):
    (tmp_path / "f.txt").write_text("hi")
    t = pw.io.s3.read(
        "s3://b/p", _client=DirBackedS3(os.fspath(tmp_path)), mode="static",
        format="plaintext_by_object", with_metadata=True,
    )
    assert t.column_names() == ["data", "_metadata"]
    cap = pw.internals.graph_runner.GraphRunner().run_tables(t)[0]
    ((_, row),) = list(cap.state.iter_items())
    assert row[0] == "hi" and json.loads(row[1])["path"] == "f.txt"


def test_scanner_malformed_object_not_redownloaded(tmp_path):
    (tmp_path / "bad.jsonl").write_text("{not json")
    client = DirBackedS3(os.fspath(tmp_path))
    reads = []
    orig = client.read_object
    client.read_object = lambda k: (reads.append(k), orig(k))[1]
    src = ObjectScanSource(
        client, "json", None, ["word"]
    )
    assert _drain(src) == []  # bad object contributes nothing...
    assert _drain(src) == []
    assert reads == ["bad.jsonl"]  # ...and is not re-downloaded every poll


# ---------------------------------------------------------------------------
# nats


class FakeNats:
    def __init__(self):
        self.subs: dict[str, list] = {}
        self.published: list[tuple[str, bytes]] = []
        self.closed = False

    def subscribe(self, topic, callback):
        self.subs.setdefault(topic, []).append(callback)

    def publish(self, topic, payload):
        self.published.append((topic, payload))
        for cb in self.subs.get(topic, []):
            cb(payload)

    def close(self):
        self.closed = True


def test_nats_read_write_roundtrip():
    fake = FakeNats()
    t = pw.io.nats.read(
        "nats://fake:4222", "in.topic",
        schema=pw.schema_from_types(word=str), _client=fake,
    )
    counts = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
    pw.io.nats.write(counts, "nats://fake:4222", "out.topic", _client=fake)

    def feed():
        import time

        time.sleep(0.15)
        fake.publish("in.topic", b"{not json")  # must be dropped, not crash
        for w in ("foo", "bar", "foo"):
            fake.publish("in.topic", json.dumps({"word": w}).encode())
        time.sleep(0.6)
        pw.request_stop()

    th = threading.Thread(target=feed, daemon=True)
    th.start()
    pw.run()
    th.join()

    out = [json.loads(p) for topic, p in fake.published if topic == "out.topic"]
    final = {}
    for msg in out:
        assert msg["diff"] in (1, -1) and "time" in msg
        if msg["diff"] == 1:
            final[msg["word"]] = msg["c"]
        elif final.get(msg["word"]) == msg["c"]:
            del final[msg["word"]]
    assert final == {"foo": 2, "bar": 1}
    assert fake.closed


# ---------------------------------------------------------------------------
# pubsub / bigquery sinks


class FakePublisher:
    def __init__(self):
        self.messages = []

    def topic_path(self, project, topic):
        return f"projects/{project}/topics/{topic}"

    def publish(self, topic_path, data, **attrs):
        self.messages.append((topic_path, data, attrs))


def test_pubsub_write_binary_column():
    t = pw.debug.table_from_markdown(
        """
        payload
        alpha
        beta
        """
    ).select(payload=pw.apply(lambda s: s.encode(), pw.this.payload))
    pub = FakePublisher()
    pw.io.pubsub.write(t, pub, "proj", "top")
    pw.run()
    assert sorted(m[1] for m in pub.messages) == [b"alpha", b"beta"]
    topic, _, attrs = pub.messages[0]
    assert topic == "projects/proj/topics/top"
    assert attrs["pathway_diff"] == "1" and "pathway_time" in attrs


def test_pubsub_rejects_multicolumn():
    t = pw.debug.table_from_markdown("a | b\n1 | 2")
    with pytest.raises(ValueError, match="single-column"):
        pw.io.pubsub.write(t, FakePublisher(), "p", "t")


class FakeBigQuery:
    def __init__(self):
        self.rows = []

    def insert_rows_json(self, table_ref, rows):
        self.rows.extend((table_ref, r) for r in rows)
        return []


def test_bigquery_write():
    t = pw.debug.table_from_markdown(
        """
        word | n
        foo  | 3
        """
    )
    client = FakeBigQuery()
    pw.io.bigquery.write(t, "ds", "tbl", _client=client)
    pw.run()
    assert len(client.rows) == 1
    ref, row = client.rows[0]
    assert ref == "ds.tbl"
    assert row["word"] == "foo" and row["n"] == 3
    assert row["diff"] == 1 and "time" in row


# ---------------------------------------------------------------------------
# airbyte


class FakeAirbyteRunner:
    def __init__(self):
        self.states_seen = []
        self.round = 0

    def extract(self, state):
        self.states_seen.append(state)
        self.round += 1
        if self.round == 1:
            return [
                {"type": "RECORD",
                 "record": {"stream": "users", "data": {"id": 1, "name": "a"}}},
                {"type": "RECORD",
                 "record": {"stream": "other", "data": {"id": 9}}},
                {"type": "STATE", "state": {"cursor": 17}},
            ]
        return [
            {"type": "RECORD",
             "record": {"stream": "users", "data": {"id": 2, "name": "b"}}},
        ]


def test_airbyte_records_and_state():
    runner = FakeAirbyteRunner()
    t = pw.io.airbyte.read(
        "cfg.yaml", ["users"], _runner=runner, refresh_interval_ms=0,
    )
    src = t._params["build"]()
    (d,) = src.poll()
    rows = [json.loads(r[0]) for _, r, _ in d.iter_rows()]
    assert rows == [{"id": 1, "name": "a"}]  # 'other' stream filtered out
    src._next_poll = 0.0
    (d2,) = src.poll()
    assert [json.loads(r[0]) for _, r, _ in d2.iter_rows()] == [
        {"id": 2, "name": "b"}
    ]
    # the STATE message feeds the next incremental extract
    assert runner.states_seen == [None, {"cursor": 17}]
    # offset resume carries the airbyte state (legacy blob = global)
    assert src.offset_state()["global"] == {"cursor": 17}


def test_sharepoint_read_with_injected_client(tmp_path):
    from pathway_tpu.xpacks.connectors import sharepoint

    (tmp_path / "Shared Documents").mkdir()
    (tmp_path / "Shared Documents" / "report.bin").write_bytes(b"\x01\x02")
    t = sharepoint.read(
        "https://example.sharepoint.com/sites/x", tenant="t", client_id="c",
        cert_path="p", thumbprint="tp", root_path="Shared Documents",
        mode="static", _client=DirBackedS3(os.fspath(tmp_path)),
    )
    cap = pw.internals.graph_runner.GraphRunner().run_tables(t)[0]
    assert [r for _, r in cap.state.iter_items()] == [(b"\x01\x02",)]


class StreamStateRunner:
    """Modern Airbyte protocol: per-stream STATE descriptors + GLOBAL."""

    def __init__(self):
        self.states_seen = []
        self.round = 0

    def extract(self, state):
        self.states_seen.append(state)
        self.round += 1
        if self.round == 1:
            return [
                {"type": "RECORD",
                 "record": {"stream": "users",
                            "data": {"id": 1, "name": "a"}}},
                {"type": "STATE", "state": {
                    "type": "STREAM",
                    "stream": {"stream_descriptor": {"name": "users"},
                               "stream_state": {"cursor": 5}}}},
                {"type": "RECORD",
                 "record": {"stream": "orders", "data": {"id": 7, "amt": 3}}},
                {"type": "STATE", "state": {
                    "type": "STREAM",
                    "stream": {"stream_descriptor": {"name": "orders"},
                               "stream_state": {"cursor": 9}}}},
                {"type": "STATE",
                 "state": {"type": "GLOBAL", "global": {"epoch": 2}}},
            ]
        return []


def test_airbyte_per_stream_state_roundtrip():
    runner = StreamStateRunner()
    t = pw.io.airbyte.read(
        "cfg.yaml", ["users", "orders"], _runner=runner,
        refresh_interval_ms=0,
    )
    src = t._params["build"]()
    (d,) = src.poll()
    rows = sorted(
        (r[0], json.loads(r[1])["id"]) for _, r, _ in d.iter_rows()
    )
    # multi-stream reads carry the stream column
    assert rows == [("orders", 7), ("users", 1)]
    src._next_poll = 0.0
    assert src.poll() == []
    # the next extract received the composite per-stream + global state
    assert runner.states_seen[1] == {
        "streams": {"users": {"cursor": 5}, "orders": {"cursor": 9}},
        "global": {"epoch": 2},
    }
    # and the offset snapshot round-trips through seek()
    st = src.offset_state()
    src2 = t._params["build"]()
    src2.seek(st)
    assert src2._state_for_extract() == runner.states_seen[1]


class FullRefreshRunner:
    """Each run returns the CURRENT full table; run 2 drops id=1, adds id=3."""

    def __init__(self):
        self.round = 0

    def extract(self, state):
        self.round += 1
        current = (
            [{"id": 1}, {"id": 2}] if self.round == 1
            else [{"id": 2}, {"id": 3}]
        )
        return [
            {"type": "RECORD", "record": {"stream": "t", "data": d}}
            for d in current
        ]


def test_airbyte_full_refresh_replace_diffs():
    runner = FullRefreshRunner()
    t = pw.io.airbyte.read(
        "cfg.yaml", ["t"], _runner=runner, refresh_interval_ms=0,
        sync_mode="full_refresh",
    )
    src = t._params["build"]()
    (d1,) = src.poll()
    first = sorted(
        (json.loads(r[0])["id"], diff) for _, r, diff in d1.iter_rows()
    )
    assert first == [(1, 1), (2, 1)]
    src._next_poll = 0.0
    (d2,) = src.poll()
    second = sorted(
        (json.loads(r[0])["id"], diff) for _, r, diff in d2.iter_rows()
    )
    # replace semantics: id=1 retracted, id=3 inserted, id=2 untouched
    assert second == [(1, -1), (3, 1)]
    src._next_poll = 0.0
    assert src.poll() == []  # steady state: no diffs


def test_airbyte_schema_projection():
    class UserSchema(pw.Schema):
        id: int
        name: str

    runner = StreamStateRunner()
    t = pw.io.airbyte.read(
        "cfg.yaml", ["users"], _runner=runner, refresh_interval_ms=0,
        schema=UserSchema, mode="static",
    )
    assert t.column_names() == ["id", "name"]
    cap = pw.internals.graph_runner.GraphRunner().run_tables(t)[0]
    assert [r for _, r in cap.state.iter_items()] == [(1, "a")]


def test_airbyte_legacy_seek_shape_still_restores():
    runner = FakeAirbyteRunner()
    t = pw.io.airbyte.read(
        "cfg.yaml", ["users"], _runner=runner, refresh_interval_ms=0,
    )
    src = t._params["build"]()
    src.seek({"state": {"cursor": 41}, "emitted": 3})
    assert src._state_for_extract() == {"cursor": 41}
    assert src._emitted == 3


class EmptySecondRunRunner:
    def __init__(self):
        self.round = 0

    def extract(self, state):
        self.round += 1
        if self.round == 1:
            return [
                {"type": "RECORD", "record": {"stream": "t", "data": {"id": 1}}},
            ]
        return []  # the table upstream was emptied


def test_airbyte_full_refresh_empty_run_retracts_all():
    runner = EmptySecondRunRunner()
    t = pw.io.airbyte.read(
        "cfg.yaml", ["t"], _runner=runner, refresh_interval_ms=0,
        sync_mode="full_refresh",
    )
    src = t._params["build"]()
    (d1,) = src.poll()
    assert [int(diff) for _, _, diff in d1.iter_rows()] == [1]
    src._next_poll = 0.0
    (d2,) = src.poll()
    # zero records this run = empty table: the old row must retract
    assert [
        (json.loads(r[0])["id"], diff) for _, r, diff in d2.iter_rows()
    ] == [(1, -1)]


def test_airbyte_snapshot_state_survives_json_roundtrip():
    runner = FullRefreshRunner()
    t = pw.io.airbyte.read(
        "cfg.yaml", ["t"], _runner=runner, refresh_interval_ms=0,
        sync_mode="full_refresh",
    )
    src = t._params["build"]()
    src.poll()
    # offsets persist as json (persistence metadata): int keys -> str,
    # tuples -> lists; a restored source must NOT churn unchanged rows
    st = json.loads(json.dumps(src.offset_state()))
    src2 = t._params["build"]()
    src2.seek(st)
    src2.runner.round = 0  # replay run 1: identical record set
    assert src2.poll() == []  # identical snapshot => zero diffs


# ---------------------------------------------------------------------------
# airbyte executable protocol (discovery -> records -> state checkpoints)

FAKE_CONNECTOR = r'''#!/usr/bin/env python3
import argparse, json, sys

ROWS = [  # (cursor, record)
    (1, {"id": 1, "name": "ann"}),
    (2, {"id": 2, "name": "bob"}),
    (3, {"id": 3, "name": "cid"}),
]

def emit(msg):
    sys.stdout.write(json.dumps(msg) + "\n")

p = argparse.ArgumentParser()
p.add_argument("command", choices=["spec", "check", "discover", "read"])
p.add_argument("--config")
p.add_argument("--catalog")
p.add_argument("--state")
a = p.parse_args()

if a.command == "spec":
    emit({"type": "SPEC", "spec": {"connectionSpecification": {}}})
elif a.command == "check":
    emit({"type": "CONNECTION_STATUS", "connectionStatus": {"status": "SUCCEEDED"}})
elif a.command == "discover":
    cfg = json.load(open(a.config))
    assert cfg.get("token") == "t0k", "config file must reach the connector"
    emit({"type": "CATALOG", "catalog": {"streams": [
        {"name": "users", "json_schema": {}, "supported_sync_modes": ["full_refresh", "incremental"]},
        {"name": "hidden", "json_schema": {}, "supported_sync_modes": ["full_refresh"]},
    ]}})
elif a.command == "read":
    catalog = json.load(open(a.catalog))
    names = [s["stream"]["name"] for s in catalog["streams"]]
    assert "users" in names and "hidden" not in names, names
    assert catalog["streams"][0]["sync_mode"] == "incremental"
    cursor = 0
    if a.state:
        st = json.load(open(a.state))
        cursor = ((st or {}).get("streams", {}).get("users") or {}).get("cursor", 0)
    sys.stderr.write("connector log noise\n")
    print("non-json line the parser must skip")
    for cur, rec in ROWS:
        if cur <= cursor:
            continue
        emit({"type": "RECORD", "record": {"stream": "users", "data": rec}})
        emit({"type": "STATE", "state": {"type": "STREAM", "stream": {
            "stream_descriptor": {"name": "users"},
            "stream_state": {"cursor": cur}}}})
'''


def _write_fake_connector(tmp_path):
    import stat
    import sys

    exe = tmp_path / "source-faker.py"
    exe.write_text(FAKE_CONNECTOR)
    exe.chmod(exe.stat().st_mode | stat.S_IXUSR)
    return [sys.executable, os.fspath(exe)]


def test_airbyte_executable_protocol_end_to_end(tmp_path):
    from pathway_tpu.io.airbyte import ExecutableAirbyteRunner

    argv = _write_fake_connector(tmp_path)
    runner = ExecutableAirbyteRunner(argv, {"token": "t0k"}, streams=["users"])
    # discovery
    catalog = runner.discover()
    assert [s["name"] for s in catalog["streams"]] == ["users", "hidden"]
    assert runner.spec() is not None
    # records + state checkpoints from a cold start
    msgs = list(runner.extract(None))
    recs = [m["record"]["data"] for m in msgs if m["type"] == "RECORD"]
    assert [r["id"] for r in recs] == [1, 2, 3]
    states = [m for m in msgs if m["type"] == "STATE"]
    assert states[-1]["state"]["stream"]["stream_state"] == {"cursor": 3}
    # resuming from a mid-stream checkpoint re-reads only the tail
    msgs2 = list(runner.extract({"streams": {"users": {"cursor": 2}}}))
    recs2 = [m["record"]["data"] for m in msgs2 if m["type"] == "RECORD"]
    assert [r["id"] for r in recs2] == [3]


def test_airbyte_executable_unknown_stream_rejected(tmp_path):
    from pathway_tpu.io.airbyte import ExecutableAirbyteRunner

    argv = _write_fake_connector(tmp_path)
    runner = ExecutableAirbyteRunner(argv, {"token": "t0k"}, streams=["nope"])
    with pytest.raises(ValueError, match="nope"):
        runner.configured_catalog()


def test_airbyte_read_through_executable_config(tmp_path):
    """pw.io.airbyte.read driving the connector exe from the yaml config:
    the full path discovery -> configured catalog -> read -> rows in a
    table, with the engine absorbing the state checkpoints."""
    argv = _write_fake_connector(tmp_path)
    cfg = tmp_path / "connection.yaml"
    cfg.write_text(
        "source:\n"
        f"  exec_path: [{argv[0]!r}, {argv[1]!r}]\n"
        "  config:\n"
        "    token: t0k\n"
    )
    t = pw.io.airbyte.read(
        os.fspath(cfg), ["users"], mode="static", refresh_interval_ms=0,
        schema=pw.schema_from_types(id=int, name=str),
    )
    cap = pw.internals.graph_runner.GraphRunner().run_tables(t)[0]
    rows = sorted(r for _, r in cap.state.iter_items())
    assert rows == [(1, "ann"), (2, "bob"), (3, "cid")]
