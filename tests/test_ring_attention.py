"""Ring attention (sequence parallelism): exact agreement with full
attention on the 8-device CPU mesh, and the long-context embedder forward."""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pathway_tpu.internals.jax_compat import (  # noqa: E402
    shard_map_available,
    shard_map_unavailable_reason,
)

# env-capability gate with an explicit reason (ISSUE 8 satellite): ring
# attention needs SOME shard_map implementation; the jax_compat shim
# accepts both the modern top-level API and the 0.4.x experimental one
pytestmark = pytest.mark.skipif(
    not shard_map_available(), reason=shard_map_unavailable_reason()
)

from pathway_tpu.models.embedder import EmbedderConfig, init_params  # noqa: E402
from pathway_tpu.models.ring_attention import (  # noqa: E402
    embed_tokens_long,
    full_attention,
    ring_attention,
)
from pathway_tpu.parallel.mesh import make_mesh  # noqa: E402


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh (conftest XLA_FLAGS)")
    return make_mesh({"seq": 8})


def test_ring_matches_full_attention(mesh):
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 64, 4, 16
    q = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    mask = jnp.asarray(rng.random((b, s)) > 0.2)
    # at least one valid key per row
    mask = mask.at[:, 0].set(True)
    scale = 1.0 / np.sqrt(d)
    expected = full_attention(q, k, v, mask, scale)
    got = ring_attention(q, k, v, mask, mesh, "seq", scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)
    # masked-out queries still produce finite values (normalizer floor)
    assert np.isfinite(np.asarray(got)).all()


def test_long_context_embedding(mesh):
    cfg = EmbedderConfig(
        vocab_size=512, dim=32, n_layers=2, n_heads=4, max_len=64,
        dtype=jnp.float32,
    )
    params = init_params(cfg, 0)
    rng = np.random.default_rng(1)
    # sequence 4x longer than max_len — impossible for the dense forward
    s = 256
    tokens = rng.integers(1, cfg.vocab_size, (2, s)).astype(np.int32)
    tokens[:, s // 2:] = 0  # long padded tail exercises the mask
    emb = embed_tokens_long(params, jnp.asarray(tokens), cfg, mesh, "seq")
    emb = np.asarray(emb)
    assert emb.shape == (2, cfg.dim)
    np.testing.assert_allclose(np.linalg.norm(emb, axis=-1), 1.0, rtol=1e-5)

    # sequence parallelism must not change the math: compare against the
    # same ring forward on a trivial 1-device mesh
    mesh1 = make_mesh({"seq": 1}) if len(jax.devices()) == 1 else None
    if mesh1 is None:
        from jax.sharding import Mesh

        mesh1 = Mesh(np.array(jax.devices()[:1]), ("seq",))
    emb1 = np.asarray(embed_tokens_long(params, jnp.asarray(tokens), cfg, mesh1, "seq"))
    np.testing.assert_allclose(emb, emb1, rtol=5e-5, atol=5e-5)
