"""groupby/reduce behavior — mirrors reference test_common.py reduce suites."""

import pytest

import pathway_tpu as pw
from pathway_tpu.testing import (
    T,
    assert_table_equality_wo_index,
)


def _t():
    return T(
        """
        k | v
        a | 1
        a | 2
        b | 3
        b | 4
        b | 5
        """
    )


def test_count():
    res = _t().groupby(pw.this.k).reduce(pw.this.k, c=pw.reducers.count())
    expected = T(
        """
        k | c
        a | 2
        b | 3
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_sum_min_max():
    res = _t().groupby(pw.this.k).reduce(
        pw.this.k,
        s=pw.reducers.sum(pw.this.v),
        mn=pw.reducers.min(pw.this.v),
        mx=pw.reducers.max(pw.this.v),
    )
    expected = T(
        """
        k | s  | mn | mx
        a | 3  | 1  | 2
        b | 12 | 3  | 5
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_avg():
    res = _t().groupby(pw.this.k).reduce(pw.this.k, a=pw.reducers.avg(pw.this.v))
    expected = T(
        """
        k | a
        a | 1.5
        b | 4.0
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_reduce_expression_over_reducers():
    res = _t().groupby(pw.this.k).reduce(
        pw.this.k,
        r=pw.reducers.sum(pw.this.v) * 10 + pw.reducers.count(),
    )
    expected = T(
        """
        k | r
        a | 32
        b | 123
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_reducer_arg_expression():
    res = _t().groupby(pw.this.k).reduce(
        pw.this.k, s=pw.reducers.sum(pw.this.v * 2)
    )
    expected = T(
        """
        k | s
        a | 6
        b | 24
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_global_reduce():
    res = _t().reduce(s=pw.reducers.sum(pw.this.v), c=pw.reducers.count())
    expected = T(
        """
        s  | c
        15 | 5
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_sorted_tuple_and_tuple():
    res = _t().groupby(pw.this.k).reduce(
        pw.this.k, st=pw.reducers.sorted_tuple(pw.this.v)
    )
    got = pw.debug.table_to_dicts(res)[1]
    vals = sorted(tuple(v) for v in got["st"].values())
    assert vals == [(1, 2), (3, 4, 5)]


def test_unique_and_any():
    t = T(
        """
        k | u
        a | x
        a | x
        b | y
        """
    )
    res = t.groupby(pw.this.k).reduce(pw.this.k, u=pw.reducers.unique(pw.this.u))
    expected = T(
        """
        k | u
        a | x
        b | y
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_unique_raises_on_multiple():
    t = T(
        """
        k | u
        a | x
        a | y
        """
    )
    res = t.groupby(pw.this.k).reduce(pw.this.k, u=pw.reducers.unique(pw.this.u))
    with pytest.raises(ValueError, match="unique"):
        pw.debug.table_to_dicts(res)


def test_argmin_argmax():
    t = T(
        """
        id | k | v
        1  | a | 10
        2  | a | 5
        3  | b | 7
        """
    )
    res = t.groupby(pw.this.k).reduce(
        pw.this.k,
        lo=pw.reducers.argmin(pw.this.v),
        hi=pw.reducers.argmax(pw.this.v),
    )
    # argmin of group a is row id 2, argmax row id 1
    ids, cols = pw.debug.table_to_dicts(t)
    rids, rcols = pw.debug.table_to_dicts(res)
    by_k = {rcols["k"][k]: k for k in rids}
    id_by_v = {cols["v"][k]: k for k in ids}
    assert int(rcols["lo"][by_k["a"]]) == int(id_by_v[5])
    assert int(rcols["hi"][by_k["a"]]) == int(id_by_v[10])
    assert int(rcols["lo"][by_k["b"]]) == int(id_by_v[7])


def test_groupby_incremental_with_retractions():
    """Streamed input with deletions: final state reflects retraction-correct
    min/max/sum (the reference's differential reduce semantics)."""
    t = T(
        """
        k | v | __time__ | __diff__
        a | 1 | 2        | 1
        a | 2 | 2        | 1
        a | 3 | 4        | 1
        a | 3 | 6        | -1
        a | 1 | 8        | -1
        """
    )
    res = t.groupby(pw.this.k).reduce(
        pw.this.k,
        s=pw.reducers.sum(pw.this.v),
        mn=pw.reducers.min(pw.this.v),
        mx=pw.reducers.max(pw.this.v),
        c=pw.reducers.count(),
    )
    expected = T(
        """
        k | s | mn | mx | c
        a | 2 | 2  | 2  | 1
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_group_disappears_on_full_retraction():
    t = T(
        """
        k | v | __time__ | __diff__
        a | 1 | 2        | 1
        b | 2 | 2        | 1
        a | 1 | 4        | -1
        """
    )
    res = t.groupby(pw.this.k).reduce(pw.this.k, c=pw.reducers.count())
    expected = T(
        """
        k | c
        b | 1
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_groupby_multiple_keys():
    t = T(
        """
        a | b | v
        1 | x | 1
        1 | y | 2
        1 | x | 3
        2 | x | 4
        """
    )
    res = t.groupby(pw.this.a, pw.this.b).reduce(
        pw.this.a, pw.this.b, s=pw.reducers.sum(pw.this.v)
    )
    expected = T(
        """
        a | b | s
        1 | x | 4
        1 | y | 2
        2 | x | 4
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_earliest_latest():
    t = T(
        """
        k | v | __time__
        a | 1 | 2
        a | 2 | 4
        a | 3 | 6
        """
    )
    res = t.groupby(pw.this.k).reduce(
        pw.this.k,
        first=pw.reducers.earliest(pw.this.v),
        last=pw.reducers.latest(pw.this.v),
    )
    expected = T(
        """
        k | first | last
        a | 1     | 3
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_ndarray_reducer():
    import numpy as np

    res = _t().groupby(pw.this.k).reduce(
        pw.this.k, arr=pw.reducers.ndarray(pw.this.v)
    )
    _, cols = pw.debug.table_to_dicts(res)
    arrays = {sorted(a.tolist())[0]: a for a in cols["arr"].values()}
    assert sorted(arrays[1].tolist()) == [1, 2]
    assert sorted(arrays[3].tolist()) == [3, 4, 5]


def test_custom_stateful_reducer():
    def combine(state, values, diff):
        (v,) = values
        return (state or 0) + v * v * diff

    res = _t().groupby(pw.this.k).reduce(
        pw.this.k, ss=pw.reducers.stateful_single(combine, pw.this.v)
    )
    expected = T(
        """
        k | ss
        a | 5
        b | 50
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_custom_accumulator():
    class SumAcc(pw.BaseCustomAccumulator):
        def __init__(self, s):
            self.s = s

        @classmethod
        def from_row(cls, row):
            return cls(row[0])

        def update(self, other):
            self.s += other.s

        def retract(self, other):
            self.s -= other.s

        def compute_result(self):
            return self.s

    sum_red = pw.reducers.udf_reducer(SumAcc)
    res = _t().groupby(pw.this.k).reduce(pw.this.k, s=sum_red(pw.this.v))
    expected = T(
        """
        k | s
        a | 3
        b | 12
        """
    )
    assert_table_equality_wo_index(res, expected)
