"""``.dt`` / ``.str`` / ``.num`` namespace parity with the reference.

The method inventory mirrors
``python/pathway/internals/expressions/{date_time,string,numerical}.py``;
the timezone tests reuse the reference's own docstring examples (DST
transitions in Europe/Warsaw) as oracles.
"""

from __future__ import annotations

import datetime

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def _rows(table, *cols):
    cap = pw.internals.graph_runner.GraphRunner().run_tables(table)[0]
    out = {}
    for key, row in cap.state.iter_items():
        d = dict(zip(table.column_names(), row))
        out[tuple(d[c] for c in cols[:-1]) if len(cols) > 2 else d[cols[0]]] = d[cols[-1]]
    return out


def _col(table, col):
    cap = pw.internals.graph_runner.GraphRunner().run_tables(table)[0]
    names = table.column_names()
    return sorted(
        row[names.index(col)] for _, row in cap.state.iter_items()
    )


def test_reference_method_inventory_resolves():
    """Every method the reference exposes exists and constructs an
    expression (the round-2 catch-all hole is closed)."""
    e = pw.this.x
    dt_methods = [
        "nanosecond", "microsecond", "millisecond", "second", "minute",
        "hour", "day", "month", "year", "weekday",
        "nanoseconds", "microseconds", "milliseconds", "seconds", "minutes",
        "hours", "days", "weeks",
    ]
    for m in dt_methods:
        assert getattr(e.dt, m)() is not None, m
    assert e.dt.timestamp(unit="s") is not None
    assert e.dt.strftime("%Y") is not None
    assert e.dt.strptime("%Y") is not None
    assert e.dt.to_utc("UTC") is not None
    assert e.dt.to_naive_in_timezone("UTC") is not None
    assert e.dt.from_timestamp(unit="s") is not None
    assert e.dt.utc_from_timestamp(unit="s") is not None
    assert e.dt.round(datetime.timedelta(hours=1)) is not None
    assert e.dt.floor(datetime.timedelta(hours=1)) is not None
    assert e.dt.add_duration_in_timezone(
        datetime.timedelta(hours=1), "UTC") is not None
    assert e.dt.subtract_duration_in_timezone(
        datetime.timedelta(hours=1), "UTC") is not None
    assert e.dt.subtract_date_time_in_timezone(pw.this.y, "UTC") is not None
    str_methods = [
        "lower", "upper", "reversed", "len", "swapcase", "title",
    ]
    for m in str_methods:
        assert getattr(e.str, m)() is not None, m
    assert e.str.replace("a", "b") is not None
    assert e.str.startswith("a") is not None
    assert e.str.endswith("a") is not None
    assert e.str.strip() is not None
    assert e.str.count("a") is not None
    assert e.str.find("a") is not None
    assert e.str.rfind("a") is not None
    assert e.str.removeprefix("a") is not None
    assert e.str.removesuffix("a") is not None
    assert e.str.slice(0, 2) is not None
    assert e.str.parse_int() is not None
    assert e.str.parse_float() is not None
    assert e.str.parse_bool() is not None
    assert e.num.abs() is not None
    assert e.num.round(2) is not None
    assert e.num.fill_na(0) is not None


def test_str_remove_prefix_suffix_swapcase():
    t = T(
        """
        s
        pathway
        PathWay
        away
        """
    )
    res = t.select(
        np=pw.this.s.str.removeprefix("path"),
        ns=pw.this.s.str.removesuffix("way"),
        sc=pw.this.s.str.swapcase(),
    )
    cap = pw.internals.graph_runner.GraphRunner().run_tables(res)[0]
    rows = sorted(tuple(r) for _, r in cap.state.iter_items())
    assert rows == sorted([
        ("way", "path", "PATHWAY"),
        ("PathWay", "PathWay", "pATHwAY"),  # case-sensitive: no match
        ("away", "a", "AWAY"),
    ])


def test_duration_totals():
    t = T(
        """
        a | b
        1 | 2
        """
    )
    d = datetime.timedelta(days=9, hours=5, minutes=30, seconds=7)
    res = t.select(
        ns=pw.cast(datetime.timedelta, d).dt.nanoseconds(),
        us=pw.cast(datetime.timedelta, d).dt.microseconds(),
        ms=pw.cast(datetime.timedelta, d).dt.milliseconds(),
        s=pw.cast(datetime.timedelta, d).dt.seconds(),
        m=pw.cast(datetime.timedelta, d).dt.minutes(),
        h=pw.cast(datetime.timedelta, d).dt.hours(),
        days=pw.cast(datetime.timedelta, d).dt.days(),
        w=pw.cast(datetime.timedelta, d).dt.weeks(),
    )
    cap = pw.internals.graph_runner.GraphRunner().run_tables(res)[0]
    ((_, row),) = list(cap.state.iter_items())
    total_s = d.total_seconds()
    assert tuple(row) == (
        int(total_s * 1e9), int(total_s * 1e6), int(total_s * 1e3),
        int(total_s), int(total_s // 60), int(total_s // 3600),
        int(total_s // 86400), int(total_s // 604800),
    )


def test_weekday_matches_reference_doc_example():
    t = T(
        """
        t1
        1970-02-03T10:13:00
        2023-03-25T10:13:00
        2023-03-26T12:13:00
        2023-05-15T14:13:23
        """
    )
    res = t.select(
        w=pw.this.t1.dt.strptime(fmt="%Y-%m-%dT%H:%M:%S").dt.weekday()
    )
    assert _col(res, "w") == [0, 1, 5, 6]


def test_timestamp_float_units_and_roundtrip():
    t = T(
        """
        t1
        2023-01-01T00:00:00
        1970-01-01T00:00:00
        """
    )
    parsed = t.select(d=pw.this.t1.dt.strptime(fmt="%Y-%m-%dT%H:%M:%S"))
    res = parsed.select(
        s=pw.this.d.dt.timestamp(unit="s"),
        ms=pw.this.d.dt.timestamp(unit="ms"),
        back=pw.this.d.dt.timestamp(unit="s").dt.from_timestamp(unit="s"),
    )
    cap = pw.internals.graph_runner.GraphRunner().run_tables(res)[0]
    rows = sorted((tuple(r) for _, r in cap.state.iter_items()))
    assert rows[0] == (0.0, 0.0, datetime.datetime(1970, 1, 1))
    assert rows[1] == (
        1672531200.0, 1672531200000.0, datetime.datetime(2023, 1, 1)
    )
    assert isinstance(rows[1][0], float)


def test_add_duration_in_timezone_dst_reference_example():
    """The reference's own DST example (date_time.py:840): adding 2h across
    the Europe/Warsaw spring-forward / fall-back transitions."""
    t = T(
        """
        date
        2023-03-26T01:23:00
        2023-03-27T01:23:00
        2023-10-29T01:23:00
        2023-10-30T01:23:00
        """
    )
    parsed = t.select(date=pw.this.date.dt.strptime(fmt="%Y-%m-%dT%H:%M:%S"))
    res = parsed.select(
        new_date=pw.this.date.dt.add_duration_in_timezone(
            datetime.timedelta(hours=2), timezone="Europe/Warsaw"
        ),
    )
    assert _col(res, "new_date") == [
        datetime.datetime(2023, 3, 26, 4, 23),   # spring forward: 01:23+2h=04:23
        datetime.datetime(2023, 3, 27, 3, 23),
        datetime.datetime(2023, 10, 29, 2, 23),  # fall back: extra hour
        datetime.datetime(2023, 10, 30, 3, 23),
    ]


def test_subtract_date_time_in_timezone_reference_example():
    t = T(
        """
        d1                  | d2
        2023-03-26T03:20:00 | 2023-03-26T01:20:00
        2023-03-27T03:20:00 | 2023-03-27T01:20:00
        2023-10-29T03:20:00 | 2023-10-29T01:20:00
        2023-10-30T03:20:00 | 2023-10-30T01:20:00
        """
    )
    fmt = "%Y-%m-%dT%H:%M:%S"
    parsed = t.select(
        d1=pw.this.d1.dt.strptime(fmt=fmt), d2=pw.this.d2.dt.strptime(fmt=fmt)
    )
    res = parsed.select(
        diff=pw.this.d1.dt.subtract_date_time_in_timezone(
            pw.this.d2, timezone="Europe/Warsaw"
        )
    )
    assert _col(res, "diff") == sorted([
        datetime.timedelta(hours=1),  # spring forward: 02:00 skipped
        datetime.timedelta(hours=2),
        datetime.timedelta(hours=3),  # fall back: 02:00 happened twice
        datetime.timedelta(hours=2),
    ])


def test_utc_from_timestamp():
    t = T(
        """
        ts
        10
        0
        """
    )
    res = t.select(d=pw.this.ts.dt.utc_from_timestamp(unit="s"))
    vals = _col(res, "d")
    assert vals == [
        datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc),
        datetime.datetime(1970, 1, 1, 0, 0, 10, tzinfo=datetime.timezone.utc),
    ]
