"""Ported from the reference's temporal window suite (boundary semantics).

Source: ``/root/reference/python/pathway/tests/temporal/test_windows.py``
(VERDICT r4 item 7). Porting contract as in ``tests/test_ported_common_1.py``;
manifest in ``PORTED_TESTS.md``.
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.testing import T, assert_table_equality_wo_index


def test_session_simple():  # ref :23
    t = T(
        """
            | instance |  t |  v
        1   | 0        |  1 |  10
        2   | 0        |  2 |  1
        3   | 0        |  4 |  3
        4   | 0        |  8 |  2
        5   | 0        |  9 |  4
        6   | 0        |  10|  8
        7   | 1        |  1 |  9
        8   | 1        |  2 |  16
        """
    )

    def should_merge(a, b):
        return abs(a - b) <= 1

    gb = t.windowby(
        t.t, window=pw.temporal.session(predicate=should_merge), instance=t.instance
    )
    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_v=pw.reducers.max(pw.this.v),
        count=pw.reducers.count(),
    )
    assert_table_equality_wo_index(
        result,
        T(
            """
            _pw_instance | _pw_window_start | _pw_window_end | min_t | max_v | count
            0            | 1                | 2              | 1     | 10    | 2
            0            | 4                | 4              | 4     | 3     | 1
            0            | 8                | 10             | 8     | 8     | 3
            1            | 1                | 2              | 1     | 16    | 2
            """
        ),
    )


def test_session_max_gap():  # ref :187
    t = T(
        """
            | t
        1   | 1.0
        2   | 1.5
        3   | 3.0
        4   | 3.4
        5   | 7.0
        """
    )
    gb = t.windowby(t.t, window=pw.temporal.session(max_gap=1.0))
    result = gb.reduce(
        pw.this._pw_window_start,
        count=pw.reducers.count(),
    )
    assert_table_equality_wo_index(
        result,
        T(
            """
            _pw_window_start | count
            1.0              | 2
            3.0              | 2
            7.0              | 1
            """
        ),
    )


def test_session_window_creation():  # ref :245
    with pytest.raises(ValueError):
        pw.temporal.session()
    with pytest.raises(ValueError):
        pw.temporal.session(predicate=lambda a, b: True, max_gap=1)


def test_sliding():  # ref :255
    t = T(
        """
            | instance | t
        1   | 0        |  12
        2   | 0        |  13
        3   | 0        |  14
        4   | 0        |  15
        5   | 0        |  16
        6   | 0        |  17
        7   | 1        |  10
        8   | 1        |  11
        """
    )
    gb = t.windowby(
        t.t, window=pw.temporal.sliding(duration=10, hop=3), instance=t.instance
    )
    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )
    assert_table_equality_wo_index(
        result,
        T(
            """
            _pw_instance | _pw_window_start | _pw_window_end | min_t | max_t | count
                0        |     3            |     13         | 12    | 12    | 1
                0        |     6            |     16         | 12    | 15    | 4
                0        |     9            |     19         | 12    | 17    | 6
                0        |     12           |     22         | 12    | 17    | 6
                0        |     15           |     25         | 15    | 17    | 3
                1        |     3            |     13         | 10    | 11    | 2
                1        |     6            |     16         | 10    | 11    | 2
                1        |     9            |     19         | 10    | 11    | 2
            """
        ),
    )


def test_sliding_origin():  # ref :430
    t = T(
        """
            | t
        1   |  12
        2   |  13
        3   |  14
        4   |  15
        5   |  16
        6   |  17
        """
    )
    gb = t.windowby(t.t, window=pw.temporal.sliding(duration=10, hop=3, origin=13))
    result = gb.reduce(
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )
    assert_table_equality_wo_index(
        result,
        T(
            """
            _pw_window_start | _pw_window_end | min_t | max_t | count
                13           |     23         | 13    | 17    | 5
                16           |     26         | 16    | 17    | 2
            """
        ),
    )


def test_sliding_larger_hop():  # ref :462
    t = T(
        """
            | t
        0   |  11
        1   |  12
        2   |  13
        3   |  14
        4   |  15
        5   |  16
        6   |  17
        """
    )
    gb = t.windowby(t.t, window=pw.temporal.sliding(duration=4, hop=6))
    result = gb.reduce(
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )
    assert_table_equality_wo_index(
        result,
        T(
            """
            _pw_window_start | _pw_window_end | min_t | max_t | count
                12           |     16         | 12    | 15    | 4
            """
        ),
    )


def test_sliding_larger_hop_mixed():  # ref :495
    t = T(
        """
            | t
        0   |  11.3
        1   |  12.1
        2   |  13.3
        3   |  14.7
        4   |  15.3
        5   |  16.1
        6   |  17.8
        """
    )
    gb = t.windowby(t.t, window=pw.temporal.sliding(duration=4, hop=6))
    result = gb.reduce(
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )
    assert_table_equality_wo_index(
        result,
        T(
            """
            _pw_window_start | _pw_window_end | min_t | max_t | count
                12           |     16         | 12.1  | 15.3  | 4
            """
        ).update_types(_pw_window_start=float, _pw_window_end=float),
    )


def test_tumbling():  # ref :528
    t = T(
        """
            | instance | t
        1   | 0        |  12
        2   | 0        |  13
        3   | 0        |  14
        4   | 0        |  15
        5   | 0        |  16
        6   | 0        |  17
        7   | 1        |  12
        8   | 1        |  13
        """
    )
    gb = t.windowby(t.t, window=pw.temporal.tumbling(duration=5), instance=t.instance)
    result = gb.reduce(
        pw.this._pw_instance,
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        min_t=pw.reducers.min(pw.this.t),
        max_t=pw.reducers.max(pw.this.t),
        count=pw.reducers.count(),
    )
    assert_table_equality_wo_index(
        result,
        T(
            """
            _pw_instance | _pw_window_start | _pw_window_end | min_t | max_t | count
                0        |     10           |     15         | 12    | 14    | 3
                0        |     15           |     20         | 15    | 17    | 3
                1        |     10           |     15         | 12    | 13    | 2
            """
        ),
    )


def test_tumbling_origin():  # ref :618
    t = T(
        """
            | t
        1   |  12
        2   |  13
        3   |  14
        4   |  15
        5   |  16
        6   |  17
        """
    )
    gb = t.windowby(t.t, window=pw.temporal.tumbling(duration=5, origin=11))
    result = gb.reduce(
        pw.this._pw_window_start,
        pw.this._pw_window_end,
        count=pw.reducers.count(),
    )
    assert_table_equality_wo_index(
        result,
        T(
            """
            _pw_window_start | _pw_window_end | count
                11           |     16         | 4
                16           |     21         | 2
            """
        ),
    )


def test_tumbling_floats():  # ref :653
    t = T(
        """
            | t
        1   |  12.1
        2   |  12.9
        3   |  13.0
        4   |  17.2
        """
    )
    gb = t.windowby(t.t, window=pw.temporal.tumbling(duration=5.0, origin=10.0))
    result = gb.reduce(
        pw.this._pw_window_start,
        count=pw.reducers.count(),
    )
    assert_table_equality_wo_index(
        result,
        T(
            """
            _pw_window_start | count
                10.0         | 3
                15.0         | 1
            """
        ),
    )


def test_intervals_over():  # ref :961
    t = T(
        """
            | t |  v
        1   | 1 |  10
        2   | 2 |  1
        3   | 4 |  3
        4   | 8 |  2
        5   | 9 |  4
        6   | 10|  8
        7   | 1 |  9
        8   | 2 |  16
        """
    )
    probes = T(
        """
        t
        2
        6
        10
        """
    )
    result = pw.temporal.windowby(
        t,
        t.t,
        window=pw.temporal.intervals_over(
            at=probes.t, lower_bound=-2, upper_bound=1
        ),
    ).reduce(
        pw.this._pw_window_location,
        v=pw.reducers.tuple(pw.this.v),
    )
    got = {
        int(loc): sorted(vs)
        for loc, vs in pw.debug.table_to_pandas(result)[
            ["_pw_window_location", "v"]
        ].values.tolist()
    }
    # probe p gathers rows with t in [p-2, p+1], both ends inclusive
    assert got == {
        2: sorted([10, 1, 9, 16]),
        6: sorted([3]),
        10: sorted([2, 4, 8]),
    }


def test_windows_boundary_inclusive_exclusive():
    # boundary pinning: a point exactly at window start belongs to the
    # window; a point exactly at the end does not ([start, end) semantics,
    # reference sliding windows)
    t = T(
        """
            | t
        1   |  10
        2   |  15
        """
    )
    gb = t.windowby(t.t, window=pw.temporal.tumbling(duration=5, origin=10))
    result = gb.reduce(
        pw.this._pw_window_start,
        count=pw.reducers.count(),
    )
    assert_table_equality_wo_index(
        result,
        T(
            """
            _pw_window_start | count
                10           | 1
                15           | 1
            """
        ),
    )
