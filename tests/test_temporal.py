"""Temporal stdlib: windows, interval/window/asof joins, behaviors —
mirrors reference temporal/test_windows.py, test_interval_joins.py style."""

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import (
    T,
    assert_table_equality_wo_index,
    run_table,
)


def test_tumbling_window_reduce():
    t = T(
        """
        t  | v
        1  | 1
        3  | 2
        4  | 3
        11 | 4
        """
    )
    res = t.windowby(pw.this.t, window=pw.temporal.tumbling(duration=10)).reduce(
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
        c=pw.reducers.count(),
    )
    expected = T(
        """
        start | s | c
        0     | 6 | 3
        10    | 4 | 1
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_sliding_window_reduce():
    t = T(
        """
        t | v
        4 | 1
        9 | 2
        """
    )
    res = t.windowby(
        pw.this.t, window=pw.temporal.sliding(hop=5, duration=10)
    ).reduce(
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )
    # t=4 in windows starting 0, -5; t=9 in windows starting 0, 5
    expected = T(
        """
        start | s
        -5    | 1
        0     | 3
        5     | 2
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_tumbling_window_instance():
    t = T(
        """
        k | t | v
        a | 1 | 1
        a | 2 | 2
        b | 1 | 5
        """
    )
    res = t.windowby(
        pw.this.t, window=pw.temporal.tumbling(duration=10), instance=pw.this.k
    ).reduce(
        k=pw.this._pw_instance,
        s=pw.reducers.sum(pw.this.v),
    )
    expected = T(
        """
        k | s
        a | 3
        b | 5
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_session_window():
    t = T(
        """
        t  | v
        1  | 1
        2  | 2
        3  | 3
        10 | 4
        11 | 5
        """
    )
    res = t.windowby(
        pw.this.t, window=pw.temporal.session(max_gap=2)
    ).reduce(
        start=pw.this._pw_window_start,
        end=pw.this._pw_window_end,
        s=pw.reducers.sum(pw.this.v),
    )
    expected = T(
        """
        start | end | s
        1     | 3   | 6
        10    | 11  | 9
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_session_window_streaming_merge():
    """Two sessions merge when a bridging row arrives later."""
    t = T(
        """
        t  | v | __time__
        1  | 1 | 2
        5  | 2 | 2
        3  | 9 | 4
        """
    )
    res = t.windowby(
        pw.this.t, window=pw.temporal.session(max_gap=2)
    ).reduce(
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )
    expected = T(
        """
        start | s
        1     | 12
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_windowby_window_tuple():
    t = T(
        """
        t | v
        1 | 1
        """
    )
    res = t.windowby(pw.this.t, window=pw.temporal.tumbling(duration=4)).reduce(
        w=pw.this._pw_window, c=pw.reducers.count()
    )
    _, cols = pw.debug.table_to_dicts(res)
    assert list(cols["w"].values()) == [(0, 4)]


def test_interval_join_inner():
    l = T(
        """
        t | a
        0 | 1
        5 | 2
        """
    )
    r = T(
        """
        t | b
        1 | 10
        4 | 20
        9 | 30
        """
    )
    res = l.interval_join(
        r, l.t, r.t, pw.temporal.interval(-2, 2)
    ).select(a=pw.left.a, b=pw.right.b)
    expected = T(
        """
        a | b
        1 | 10
        2 | 20
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_interval_join_with_eq_condition():
    l = T(
        """
        k | t | a
        x | 0 | 1
        y | 0 | 2
        """
    )
    r = T(
        """
        k | t | b
        x | 1 | 10
        y | 3 | 20
        """
    )
    res = l.interval_join(
        r, l.t, r.t, pw.temporal.interval(0, 2), pw.left.k == pw.right.k
    ).select(a=pw.left.a, b=pw.right.b)
    expected = T(
        """
        a | b
        1 | 10
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_interval_join_left():
    l = T(
        """
        t | a
        0 | 1
        9 | 2
        """
    )
    r = T(
        """
        t | b
        1 | 10
        """
    )
    res = l.interval_join_left(
        r, l.t, r.t, pw.temporal.interval(-2, 2)
    ).select(a=pw.left.a, b=pw.right.b)
    expected = T(
        """
        a | b
        1 | 10
        2 | None
        """
    )
    assert_table_equality_wo_index(res, expected, check_types=False)


def test_window_join():
    l = T(
        """
        t | a
        1 | 1
        6 | 2
        """
    )
    r = T(
        """
        t | b
        2 | 10
        7 | 20
        """
    )
    res = l.window_join(
        r, l.t, r.t, pw.temporal.tumbling(duration=5)
    ).select(a=pw.left.a, b=pw.right.b)
    expected = T(
        """
        a | b
        1 | 10
        2 | 20
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_asof_join_backward():
    trades = T(
        """
        t  | price
        2  | 100
        5  | 101
        9  | 102
        """
    )
    quotes = T(
        """
        t  | bid
        1  | 99
        4  | 100
        8  | 101
        """
    )
    res = trades.asof_join(quotes, trades.t, quotes.t).select(
        price=pw.left.price, bid=pw.right.bid
    )
    expected = T(
        """
        price | bid
        100   | 99
        101   | 100
        102   | 101
        """
    )
    assert_table_equality_wo_index(res, expected, check_types=False)


def test_asof_join_forward_and_unmatched():
    l = T(
        """
        t | a
        1 | 1
        9 | 2
        """
    )
    r = T(
        """
        t | b
        5 | 50
        """
    )
    res = l.asof_join(
        r, l.t, r.t, direction=pw.temporal.Direction.FORWARD
    ).select(a=pw.left.a, b=pw.right.b)
    expected = T(
        """
        a | b
        1 | 50
        2 | None
        """
    )
    assert_table_equality_wo_index(res, expected, check_types=False)


def test_asof_join_incremental_update():
    """A late right row re-matches existing left rows (retraction path)."""
    l = T(
        """
        t | a
        5 | 1
        """
    )
    r = T(
        """
        t | b | __time__
        1 | 10 | 2
        4 | 40 | 6
        """
    )
    res = l.asof_join(r, l.t, r.t).select(a=pw.left.a, b=pw.right.b)
    expected = T(
        """
        a | b
        1 | 40
        """
    )
    assert_table_equality_wo_index(res, expected, check_types=False)


def test_asof_now_join_does_not_retract():
    queries = T(
        """
        q | __time__
        1 | 2
        2 | 6
        """
    )
    state = T(
        """
        k | v | __time__
        0 | 10 | 0
        0 | 10 | 4
        0 | 20 | 4
        """,
        split_on_whitespace=True,
    )
    # state: v=10 at t0; at t4 retract...? build explicitly with diffs
    state = T(
        """
        k | v  | __time__ | __diff__
        0 | 10 | 0        | 1
        0 | 10 | 4        | -1
        0 | 20 | 4        | 1
        """
    )
    queries = queries.with_columns(k=0)
    res = queries.asof_now_join(state, pw.left.k == pw.right.k).select(
        q=pw.left.q, v=pw.right.v
    )
    # query 1 (t=2) saw v=10 and must NOT be retracted; query 2 (t=6) sees 20
    expected = T(
        """
        q | v
        1 | 10
        2 | 20
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_exactly_once_behavior_single_emission():
    t = T(
        """
        t | v | __time__
        1 | 1 | 2
        2 | 2 | 4
        11 | 5 | 6
        12 | 6 | 20
        """
    )
    res = t.windowby(
        pw.this.t,
        window=pw.temporal.tumbling(duration=10),
        behavior=pw.temporal.exactly_once_behavior(),
    ).reduce(
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )
    from pathway_tpu.internals.graph_runner import GraphRunner

    (cap,) = GraphRunner().run_tables(res)
    # window [0,10) closes at time>=10 → emitted once with both rows;
    # window [10,20): row at t=11 buffered to time 20, late row t=12
    # (arriving at 20) still within cutoff tick? it arrives exactly at
    # release → included or dropped per cutoff; assert single emission per
    # window (no retractions ever reach the output)
    diffs = [d for (_, _, _, d) in cap.stream]
    assert all(d == 1 for d in diffs), cap.stream
    rows = {row[0]: row[1] for _, _, row, d in cap.stream}
    assert rows[0] == 3


def test_common_behavior_keep_results_false():
    t = T(
        """
        t  | v | __time__
        1  | 1 | 2
        15 | 2 | 16
        30 | 3 | 32
        """
    )
    res = t.windowby(
        pw.this.t,
        window=pw.temporal.tumbling(duration=10),
        behavior=pw.temporal.common_behavior(cutoff=2, keep_results=False),
    ).reduce(
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    )
    from pathway_tpu.internals.graph_runner import GraphRunner

    (cap,) = GraphRunner().run_tables(res)
    final = {row[0]: row[1] for _, row in cap.state.iter_items()}
    # windows [0,10) and [10,20) are past cutoff by the final time → dropped
    assert final == {30: 3}, final


def test_temporal_joins_desugar_this():
    """pw.this in interval/asof/window join select desugars by column-name
    side lookup, like the plain-join result (reference desugaring)."""
    G.clear()
    l = T("t | a\n1 | x\n5 | y")
    r = T("t | b\n2 | p\n9 | q")
    j = l.interval_join(r, l.t, r.t, pw.temporal.interval(-2, 2)).select(
        pw.this.a, pw.this.b
    )
    assert sorted(run_table(j)[0].values()) == [("x", "p")]
    with pytest.raises(ValueError, match="both sides"):
        l.interval_join(r, l.t, r.t, pw.temporal.interval(-2, 2)).select(
            pw.this.t
        )
    G.clear()
    l = T("t | a\n1 | 10\n5 | 50")
    r = T("t | b\n0 | 1\n4 | 2")
    j = l.asof_join(r, l.t, r.t).select(pw.this.a, pw.this.b)
    assert sorted(run_table(j)[0].values()) == [(10, 1), (50, 2)]


def test_table_interpolate_method():
    """Table.interpolate (stdlib statistical attached as a method,
    reference table.py:75)."""
    G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(t=int, v=float | None),
        [(1, 1.0), (2, None), (3, 3.0)],
    )
    r = t.interpolate(pw.this.t, pw.this.v)
    assert sorted(run_table(r)[0].values()) == [(1, 1.0), (2, 2.0), (3, 3.0)]


def test_interval_join_left_pads_keep_this_columns():
    """Pad rows of outer modes must keep own-side pw.this values (review:
    the pad path used to null them while pw.left kept them)."""
    G.clear()
    l = T("t | a\n1 | x\n9 | y")
    r = T("t | b\n2 | p")
    j = l.interval_join_left(r, l.t, r.t, pw.temporal.interval(-2, 2)).select(
        pw.this.a, pw.this.b
    )
    assert sorted(run_table(j)[0].values(), key=repr) == [
        ("x", "p"), ("y", None)
    ]


def test_behavior_cutoff_drops_late_rows_event_time():
    """Lateness is judged against the max EVENT time seen (reference
    time_column.rs frontier), not the engine's processing time — the old
    processing-time comparison kept this late row."""
    G.clear()
    t = T(
        """
        t  | v | __time__
        1  | 1 | 2
        2  | 2 | 2
        20 | 3 | 4
        3  | 9 | 6
        """
    )
    r = t.windowby(
        pw.this.t, window=pw.temporal.tumbling(duration=10),
        behavior=pw.temporal.common_behavior(cutoff=5),
    ).reduce(start=pw.this._pw_window_start, s=pw.reducers.sum(pw.this.v))
    assert sorted(run_table(r)[0].values()) == [(0, 3), (20, 3)]


def test_behavior_keep_results_false_retracts_closed_windows():
    G.clear()
    t = T(
        """
        t  | v | __time__
        1  | 1 | 2
        25 | 3 | 4
        """
    )
    r = t.windowby(
        pw.this.t, window=pw.temporal.tumbling(duration=10),
        behavior=pw.temporal.common_behavior(cutoff=2, keep_results=False),
    ).reduce(start=pw.this._pw_window_start, s=pw.reducers.sum(pw.this.v))
    assert sorted(run_table(r)[0].values()) == [(20, 3)]


def test_behavior_under_wall_clock_streaming():
    """Behaviors must work when engine timestamps are wall-clock ms and
    event times are small ints — event-time watermark, not tick time."""
    import time as _time

    G.clear()

    class Feed(pw.io.python.ConnectorSubject):
        def run(self):
            for t_, v in [(1, 1), (2, 2), (20, 3), (3, 9)]:
                self.next(t=t_, v=v)
                self.commit()
                _time.sleep(0.01)

    src = pw.io.python.read(
        Feed(), schema=pw.schema_from_types(t=int, v=int),
        autocommit_duration_ms=None,
    )
    r = src.windowby(
        pw.this.t, window=pw.temporal.tumbling(duration=10),
        behavior=pw.temporal.common_behavior(cutoff=5),
    ).reduce(start=pw.this._pw_window_start, s=pw.reducers.sum(pw.this.v))
    acc = {}
    pw.io.subscribe(
        r,
        on_change=lambda key, row, time, is_addition: (
            acc.__setitem__(row["start"], row["s"]) if is_addition else None
        ),
    )
    pw.run()
    assert sorted(acc.items()) == [(0, 3), (20, 3)]


def test_interval_join_behavior_cutoff():
    """interval_join applies its behavior (it used to be silently
    ignored): a left row later than cutoff behind its side's event-time
    watermark never joins."""
    G.clear()
    l = T(
        """
        t | a | __time__
        1 | x | 2
        9 | z | 4
        2 | y | 6
        """
    )
    r = T("t | b\n1 | p\n2 | q\n9 | w")
    j = l.interval_join(
        r, l.t, r.t, pw.temporal.interval(0, 0),
        behavior=pw.temporal.common_behavior(cutoff=2),
    ).select(pw.this.a, pw.this.b)
    assert sorted(run_table(j)[0].values()) == [("x", "p"), ("z", "w")]


def test_interval_join_left_behavior_pads_respect_cutoff():
    """Rows dropped by the behavior must not resurface as outer pads
    (review: pads used to derive from the unwrapped side)."""
    G.clear()
    l = T("t | a | __time__\n1 | x | 2\n9 | z | 4\n2 | y | 6")
    r = T("t | b\n1 | p\n9 | w")
    j = l.interval_join_left(
        r, l.t, r.t, pw.temporal.interval(0, 0),
        behavior=pw.temporal.common_behavior(cutoff=2),
    ).select(pw.this.a, pw.this.b)
    assert sorted(run_table(j)[0].values()) == [("x", "p"), ("z", "w")]


def test_behavior_float_event_times():
    """Cutoffs work in the float time domain (review: int64 casts
    truncated float event times, granting up to a unit of extra
    lateness)."""
    G.clear()
    l = T("t | a | __time__\n1.0 | x | 2\n9.9 | z | 4\n9.0 | y | 6")
    r = T("t | b\n1.0 | p\n9.9 | w\n9.0 | q")
    j = l.interval_join(
        r, l.t, r.t, pw.temporal.interval(0.0, 0.0),
        behavior=pw.temporal.common_behavior(cutoff=0.5),
    ).select(pw.this.a, pw.this.b)
    assert sorted(run_table(j)[0].values()) == [("x", "p"), ("z", "w")]
