"""Unit tests for the exactly-once output plane (``io/delivery.py``):
RetryPolicy backoff, circuit breaker, DLQ routing, ack-cursor recovery
skip, the commit-boundary release protocol, the sink.write chaos gate,
and the recovery-floor math the executor uses to pick a snapshot."""

from __future__ import annotations

import json
import threading
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine.delta import Delta
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io.delivery import (
    CallableAdapter,
    DeadLetterQueue,
    DeliveryManager,
    DeliverySink,
    RetryPolicy,
    SinkRejectedError,
    _reset_stats_for_tests,
    _sanitize,
    sink_stats_snapshot,
)
from pathway_tpu.persistence.backends import MemoryBackend


@pytest.fixture(autouse=True)
def _clean():
    G.clear()
    _reset_stats_for_tests()
    yield
    G.clear()
    _reset_stats_for_tests()


def _batch(t: int, vals: list[int]) -> Delta:
    return Delta(
        keys=np.arange(len(vals), dtype=np.uint64),
        data={"x": np.asarray(vals)},
        diffs=np.ones(len(vals), dtype=np.int64),
    )


def _sink(fn, tmp_path, *, backend=None, transactional=False,
          policy=None, queue_batches=8, name="t") -> DeliverySink:
    return DeliverySink(
        CallableAdapter(fn, name), name,
        policy=policy or RetryPolicy(first_delay_ms=1, jitter_ms=0,
                                     max_retries=2),
        backend=backend,
        transactional=transactional,
        dlq=DeadLetterQueue(str(tmp_path / "dlq")),
        queue_batches=queue_batches,
    )


# -- RetryPolicy ---------------------------------------------------------


def test_retry_policy_backoff_shape():
    p = RetryPolicy(first_delay_ms=100, backoff_factor=3.0, jitter_ms=0)
    assert p.delay_s(1) == pytest.approx(0.1)
    assert p.delay_s(2) == pytest.approx(0.3)
    assert p.delay_s(3) == pytest.approx(0.9)
    assert p.attempts() == 6  # max_retries=5 default


def test_retry_policy_jitter_bounded():
    import random

    p = RetryPolicy(first_delay_ms=10, jitter_ms=50)
    rng = random.Random(1)
    for _ in range(50):
        d = p.delay_s(1, rng)
        assert 0.01 <= d <= 0.06


def test_retry_policy_http_reexport():
    from pathway_tpu.io.http import RetryPolicy as HttpPolicy

    assert HttpPolicy is RetryPolicy


def test_sanitize():
    assert _sanitize("fs-/tmp/out file.csv") == "fs-_tmp_out_file.csv"
    assert _sanitize("///") == "sink"


# -- immediate-mode delivery: retries, DLQ, breaker ----------------------


def test_transient_failures_retry_then_deliver_once(tmp_path):
    calls = []

    def fn(batch):
        calls.append(batch.time)
        if len(calls) <= 2:
            raise ConnectionError("transient")

    s = _sink(fn, tmp_path)
    s.on_batch(2, _batch(2, [1]))
    assert s.drain(timeout=10)
    s.shutdown()
    assert calls == [2, 2, 2]  # two failures, one success — delivered once
    assert s.stats.retries_total == 2
    assert s.stats.delivered_total == 1


def test_reject_routes_rows_to_dlq_and_delivers_rest(tmp_path):
    delivered = []

    def fn(batch):
        vals = list(batch.delta.data["x"])
        if 13 in vals:
            raise SinkRejectedError("bad row", row_indices=[vals.index(13)])
        delivered.extend(vals)

    s = _sink(fn, tmp_path, name="rj")
    s.on_batch(2, _batch(2, [7, 13, 9]))
    assert s.drain(timeout=10)
    s.shutdown()
    assert sorted(delivered) == [7, 9]
    assert s.stats.dlq_total == 1
    entries = [
        json.loads(line)
        for line in open(tmp_path / "dlq" / "rj.jsonl")
    ]
    assert len(entries) == 1
    assert entries[0]["row"]["x"] == 13
    assert entries[0]["row"]["diff"] == 1
    assert "bad row" in entries[0]["error"]
    assert entries[0]["stamp"][2] == 2  # boundary_seq = tick time


def test_whole_batch_reject_is_fully_dead_lettered_and_acked(tmp_path):
    def fn(batch):
        raise SinkRejectedError("all bad")

    s = _sink(fn, tmp_path, name="allbad")
    s.on_batch(4, _batch(4, [1, 2]))
    assert s.drain(timeout=10)
    s.shutdown()
    assert s.stats.dlq_total == 2
    assert s.acked_time == 4  # accounted for: recovery must not re-deliver


def test_breaker_opens_and_recovers(tmp_path):
    down = threading.Event()
    down.set()
    delivered = []

    def fn(batch):
        if down.is_set():
            raise ConnectionError("down")
        delivered.append(batch.time)

    s = _sink(fn, tmp_path, name="brk",
              policy=RetryPolicy(first_delay_ms=1, jitter_ms=0,
                                 max_retries=0))
    s._breaker.cooldown_s = 0.02
    s._breaker.threshold = 2
    s.on_batch(2, _batch(2, [1]))
    deadline = time.monotonic() + 10
    while s.stats.breaker_open == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert s.stats.breaker_open == 1
    assert s.stats.breaker_opens_total >= 1
    down.clear()
    assert s.drain(timeout=10)
    s.shutdown()
    assert delivered == [2]
    assert s.stats.breaker_open == 0


def test_timeout_watchdog_turns_hang_into_retry(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_SINK_TIMEOUT_S", "0.1")
    calls = []

    def fn(batch):
        calls.append(1)
        if len(calls) == 1:
            time.sleep(30)  # wedged external client
        return None

    s = _sink(fn, tmp_path, name="hang")
    assert s.timeout_s == pytest.approx(0.1)
    s.on_batch(2, _batch(2, [1]))
    assert s.drain(timeout=15)
    s.shutdown()
    assert len(calls) == 2
    assert s.stats.delivered_total == 1


# -- ack cursor / recovery skip ------------------------------------------


def test_ack_cursor_persists_and_skips_replayed_batches(tmp_path):
    backend = MemoryBackend()
    delivered = []

    def fn(batch):
        delivered.append(batch.time)

    s = _sink(fn, tmp_path, backend=backend, transactional=True, name="ack")
    # initial cursor is stamped at construction: floor -1, nothing acked
    doc = json.loads(backend.get_value("delivery/ack"))
    assert doc["acked_time"] == -1
    s.on_batch(2, _batch(2, [1]))
    s.on_batch(4, _batch(4, [2]))
    s.release(4)
    assert s.drain(timeout=10, bump_to=6)
    s.shutdown()
    assert delivered == [2, 4]
    doc = json.loads(backend.get_value("delivery/ack"))
    assert doc["acked_time"] == 6  # heartbeat bump to the commit tick
    assert doc["worker"] == 0

    # "restarted" sink over the same backend: replayed batches at or
    # below the cursor are skipped, fresh ones deliver
    delivered2 = []
    s2 = _sink(lambda b: delivered2.append(b.time), tmp_path,
               backend=backend, transactional=True, name="ack")
    assert s2.recovery_floor() == 6
    s2.on_batch(2, _batch(2, [1]))   # replay — skipped
    s2.on_batch(4, _batch(4, [2]))   # replay — skipped
    s2.on_batch(8, _batch(8, [3]))   # fresh
    s2.release_all()
    assert s2.drain(timeout=10)
    s2.shutdown()
    assert delivered2 == [8]


def test_transactional_batches_wait_for_release(tmp_path):
    backend = MemoryBackend()
    delivered = []
    s = _sink(lambda b: delivered.append(b.time), tmp_path,
              backend=backend, transactional=True, name="rel")
    s.on_batch(2, _batch(2, [1]))
    s.on_batch(4, _batch(4, [2]))
    time.sleep(0.1)
    assert delivered == []  # input not committed yet — nothing delivered
    s.release(2)
    assert s.drain(timeout=10)
    assert delivered == [2]
    s.release(4)
    assert s.drain(timeout=10)
    s.shutdown()
    assert delivered == [2, 4]


def test_manager_commit_protocol_and_floor(tmp_path):
    backend = MemoryBackend()
    mgr = DeliveryManager(worker_id=0)
    delivered = []
    s = _sink(lambda b: delivered.append(b.time), tmp_path,
              backend=backend, transactional=True, name="mgr")
    mgr.add(s)
    assert mgr.recovery_floor() == -1
    s.on_batch(2, _batch(2, [1]))
    mgr.pre_commit_barrier()  # nothing released yet — no-op
    mgr.on_commit(2)
    assert delivered == [2]
    assert mgr.recovery_floor() == 2
    s.on_batch(4, _batch(4, [2]))
    mgr.on_commit(4)
    assert mgr.recovery_floor() == 4
    mgr.finish()
    assert delivered == [2, 4]


def test_manager_want_early_commit(tmp_path):
    mgr = DeliveryManager(worker_id=0)
    s = _sink(lambda b: None, tmp_path, backend=MemoryBackend(),
              transactional=True, queue_batches=2, name="early")
    mgr.add(s)
    assert not mgr.want_early_commit()
    s.on_batch(2, _batch(2, [1]))
    s.on_batch(4, _batch(4, [1]))
    assert mgr.want_early_commit()
    mgr.on_commit(4)
    assert not mgr.want_early_commit()
    mgr.finish()


# -- sink.write chaos gate ----------------------------------------------


def _armed(plan_doc):
    from pathway_tpu.chaos import injector as inj
    from pathway_tpu.chaos.plan import FaultPlan

    return inj.arm(FaultPlan.from_dict(plan_doc), run=0)


def test_chaos_fail_nth_is_retried_exactly_once(tmp_path):
    from pathway_tpu.chaos import injector as inj

    _armed({"seed": 1, "faults": [
        {"site": "sink.write", "action": "fail", "nth": 1},
    ]})
    try:
        calls = []
        s = _sink(lambda b: calls.append(b.time), tmp_path, name="cf")
        s.on_batch(2, _batch(2, [1]))
        assert s.drain(timeout=10)
        s.shutdown()
        assert calls == [2]
        assert s.stats.retries_total == 1
        assert s.stats.chaos_injections_total == 1
    finally:
        inj.disarm()


def test_chaos_reject_dead_letters_first_row(tmp_path):
    from pathway_tpu.chaos import injector as inj

    _armed({"seed": 1, "faults": [
        {"site": "sink.write", "action": "reject", "nth": 1,
         "key_prefix": "cr"},
    ]})
    try:
        delivered = []
        s = _sink(lambda b: delivered.extend(b.delta.data["x"]),
                  tmp_path, name="cr")
        s.on_batch(2, _batch(2, [5, 6]))
        assert s.drain(timeout=10)
        s.shutdown()
        assert sorted(delivered) == [6]
        assert s.stats.dlq_total == 1
    finally:
        inj.disarm()


def test_chaos_torn_with_rollback_never_duplicates(tmp_path):
    """fs-adapter-style rollback: the torn half-batch is undone before
    the retry, so the delivered file carries each row exactly once."""
    from pathway_tpu.chaos import injector as inj

    _armed({"seed": 1, "faults": [
        {"site": "sink.write", "action": "torn", "nth": 1},
    ]})
    try:
        lines: list[int] = []

        def fn(batch):
            # fs-style: append rows, return the post-write position as
            # the resume token (acked by the delivery layer on success)
            lines.extend(int(v) for v in batch.delta.data["x"])
            return len(lines)

        def rollback(resume_token=None):
            del lines[int(resume_token or 0):]

        adapter = CallableAdapter(fn, "torn")
        adapter.rollback = rollback
        s = DeliverySink(
            adapter, "torn",
            policy=RetryPolicy(first_delay_ms=1, jitter_ms=0, max_retries=2),
            dlq=DeadLetterQueue(str(tmp_path / "dlq")),
        )
        s.on_batch(2, _batch(2, [1, 2, 3, 4]))
        assert s.drain(timeout=10)
        s.shutdown()
        assert lines == [1, 2, 3, 4]
    finally:
        inj.disarm()


# -- review-hardening regressions ----------------------------------------


def test_end_time_batch_skips_when_already_acked(tmp_path):
    """A kill after the END_TIME flush batch acked must not re-deliver
    the regenerated END batch on restart."""
    backend = MemoryBackend()
    END = 1 << 62
    delivered = []
    s = _sink(lambda b: delivered.append(b.time), tmp_path,
              backend=backend, transactional=True, name="endt")
    s.on_batch(END, _batch(END, [1]))
    s.release_all()
    assert s.drain(timeout=10)
    s.shutdown()
    assert delivered == [END]
    s2 = _sink(lambda b: delivered.append(("dup", b.time)), tmp_path,
               backend=backend, transactional=True, name="endt")
    assert s2.acked_time == END
    s2.on_batch(END, _batch(END, [1]))  # regenerated on restart — skipped
    s2.release_all()
    assert s2.drain(timeout=10)
    s2.shutdown()
    assert delivered == [END]


def test_on_end_drain_timeout_raises_not_drops(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_SINK_DRAIN_TIMEOUT_S", "0.2")

    def fn(batch):
        raise ConnectionError("down forever")

    s = _sink(fn, tmp_path, name="stuck",
              policy=RetryPolicy(first_delay_ms=1, jitter_ms=0,
                                 max_retries=0))
    s._breaker.cooldown_s = 0.01
    s.on_batch(2, _batch(2, [1]))
    with pytest.raises(RuntimeError, match="failed to drain"):
        s.on_end()


def test_duplicate_sink_names(tmp_path):
    t = pw.debug.table_from_rows(pw.schema_from_types(a=int), [(1,)])
    # DERIVED defaults de-collide with a deterministic suffix (two csv
    # writes to files sharing a basename stay valid)
    pw.io.csv.write(t, str(tmp_path / "a" / "out.csv"))
    pw.io.csv.write(t, str(tmp_path / "b" / "out.csv"))
    names = [s["delivery"]["name"] for s in G.sinks]
    assert names == ["fs-out.csv", "fs-out.csv-2"]
    # EXPLICIT duplicate names are refused (shared cursor = skipped rows)
    pw.io.csv.write(t, str(tmp_path / "c" / "out.csv"), name="mine")
    with pytest.raises(ValueError, match="already registered"):
        pw.io.csv.write(t, str(tmp_path / "d" / "out.csv"), name="mine")


def test_chaos_hang_is_cut_by_timeout_watchdog(tmp_path, monkeypatch):
    """The hang action runs INSIDE the watchdog: with a timeout set, a
    hung write turns into a retry instead of wedging the writer."""
    from pathway_tpu.chaos import injector as inj

    monkeypatch.setenv("PATHWAY_SINK_TIMEOUT_S", "0.1")
    _armed({"seed": 1, "faults": [
        {"site": "sink.write", "action": "hang", "nth": 1},
    ]})
    try:
        delivered = []
        s = _sink(lambda b: delivered.append(b.time), tmp_path, name="chang")
        s.on_batch(2, _batch(2, [1]))
        assert s.drain(timeout=15), "writer wedged on the chaos hang"
        s.shutdown()
        assert delivered == [2]
        assert s.stats.retries_total >= 1
    finally:
        inj.disarm()


def test_rescale_carries_delivery_cursors(tmp_path):
    """A rescale must carry the sink ack cursors into the new epoch —
    dropping them resets the recovery floor and re-delivers the replayed
    tail (duplicate external output)."""
    import json as _json
    import time as _time_mod

    from pathway_tpu.persistence import Backend, Config
    from pathway_tpu.persistence.backends import FilesystemBackend
    from pathway_tpu.rescale import rescale

    out = tmp_path / "out.jsonl"
    store = tmp_path / "store"

    class S(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(12):
                self.next(x=i)
                self.commit()
                _time_mod.sleep(0.01)

    t = pw.io.python.read(
        S(), schema=pw.schema_from_types(x=int), name="src",
        autocommit_ms=None,
    )
    pw.io.jsonlines.write(t, str(out), name="resc-out")
    cfg = Config.simple_config(
        Backend.filesystem(str(store)), snapshot_interval_ms=10
    )
    pw.run(persistence_config=cfg)
    root = FilesystemBackend(str(store))
    before = [k for k in root.list_keys() if "delivery/resc-out" in k]
    assert before, "run never wrote an ack cursor"
    acked = _json.loads(root.get_value(before[0]))["acked_time"]
    assert acked > 0
    rescale(root, 2)
    after = [k for k in root.list_keys() if "delivery/resc-out" in k]
    assert after, "rescale dropped the delivery ack cursor"
    assert all(k.startswith("epoch-1/") for k in after), after
    carried = _json.loads(root.get_value(after[0]))["acked_time"]
    assert carried == acked


def test_top_merged_sinks_prefer_live_over_muted_zeros():
    from pathway_tpu.observability.top import render_frame

    doc = {
        "process_id": 0,
        "workers": {},
        "sinks": {
            "0": {"out": {"delivered_rows_total": 42.0, "queue_depth": 1.0}},
            "1": {"out": {"delivered_rows_total": 0.0, "queue_depth": 0.0}},
        },
    }
    frame = render_frame(doc, now=0.0)
    assert "sink out: 42 row(s) delivered" in frame


def test_drain_interrupted_by_stop_never_bumps_cursor(tmp_path):
    """A shutdown racing a drain must not advance the durable cursor past
    undelivered batches — recovery would skip them (lost rows)."""
    backend = MemoryBackend()
    hold = threading.Event()

    def fn(batch):
        hold.wait(10)  # sink wedged until released

    s = _sink(fn, tmp_path, backend=backend, transactional=True, name="intr")
    s.on_batch(2, _batch(2, [1]))
    s.release_all()
    done: list[bool] = []

    def drainer():
        done.append(s.drain(timeout=None, bump_to=99))

    th = threading.Thread(target=drainer, daemon=True)
    th.start()
    time.sleep(0.2)
    s._stop.set()  # teardown races the drain
    th.join(timeout=10)
    hold.set()
    s.shutdown()
    assert done == [False]
    assert s.acked_time < 99  # no heartbeat past the undelivered batch
    doc = json.loads(backend.get_value("delivery/intr"))
    assert doc["acked_time"] < 99


def test_kill_between_first_commit_and_drain_loses_nothing(tmp_path):
    """The one reachable floor-below-all-snapshots window: die after the
    FIRST metadata commit's snapshot write but before the post-commit
    sink release/drain. Recovery must replay the input log from scratch
    (restore nothing) so the never-released output still delivers."""
    import subprocess
    import sys as _sys
    import textwrap

    prog = tmp_path / "prog.py"
    prog.write_text(textwrap.dedent("""
        import os, sys, time
        import pathway_tpu as pw
        from pathway_tpu.persistence import Backend, Config

        out, pstate = sys.argv[1], sys.argv[2]
        if os.environ.get("DIE_AT_FIRST_RELEASE") == "1":
            from pathway_tpu.io.delivery import DeliveryManager

            def dying_on_commit(self, up_to_time):
                # the metadata commit (snapshot included) just landed;
                # die before any batch releases or acks
                os._exit(17)

            DeliveryManager.on_commit = dying_on_commit

        class S(pw.io.python.ConnectorSubject):
            def run(self):
                for i in range(10):
                    self.next(x=i)
                    self.commit()
                    time.sleep(0.01)

        t = pw.io.python.read(
            S(), schema=pw.schema_from_types(x=int), name="src",
            autocommit_ms=None,
        )
        pw.io.jsonlines.write(t, out, name="out")
        cfg = Config.simple_config(
            Backend.filesystem(pstate), snapshot_interval_ms=20
        )
        pw.run(persistence_config=cfg)
    """))
    out = tmp_path / "o.jsonl"
    env = {
        **__import__("os").environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": __import__("os").path.dirname(
            __import__("os").path.dirname(
                __import__("os").path.abspath(__file__)
            )
        ),
        "PATHWAY_THREADS": "1",
        "PATHWAY_SINK_DLQ_DIR": str(tmp_path / "dlq"),
    }
    p1 = subprocess.run(
        [_sys.executable, str(prog), str(out), str(tmp_path / "ps")],
        env={**env, "DIE_AT_FIRST_RELEASE": "1"},
        capture_output=True, timeout=120,
    )
    assert p1.returncode == 17, p1.stderr.decode(errors="replace")
    assert not out.exists() or not out.read_text().strip()
    p2 = subprocess.run(
        [_sys.executable, str(prog), str(out), str(tmp_path / "ps")],
        env=env, capture_output=True, timeout=120,
    )
    assert p2.returncode == 0, p2.stderr.decode(errors="replace")
    rows = [json.loads(line)["x"] for line in out.open()]
    assert sorted(rows) == list(range(10)), rows  # nothing lost, no dupes


def test_cursor_transient_read_error_propagates(tmp_path):
    """A transient backend error while loading the cursor must surface —
    overwriting a good cursor with -1 would re-deliver the whole tail."""

    class FlakyBackend(MemoryBackend):
        def get_value(self, key):
            raise OSError("EIO")

    with pytest.raises(OSError, match="EIO"):
        _sink(lambda b: None, tmp_path, backend=FlakyBackend(),
              transactional=True, name="flaky-cur")


def test_cursor_corrupt_blob_not_overwritten(tmp_path):
    backend = MemoryBackend()
    backend.put_value("delivery/corr", b"\xff not json")
    s = _sink(lambda b: None, tmp_path, backend=backend,
              transactional=True, name="corr")
    assert s.acked_time == -1  # conservative floor in memory
    # the evidence blob survives until the next real ack rewrites it
    assert backend.get_value("delivery/corr") == b"\xff not json"
    s.shutdown()


def test_timeout_resets_adapter_before_retry(tmp_path, monkeypatch):
    """A watchdog-abandoned write leaves a zombie thread inside the
    adapter: the delivery layer must reset the adapter (on_timeout +
    reopen) so the retry never shares live handles with the zombie."""
    monkeypatch.setenv("PATHWAY_SINK_TIMEOUT_S", "0.1")
    events = []
    calls = [0]

    def fn(batch):
        calls[0] += 1
        if calls[0] == 1:
            time.sleep(5)  # zombie
        events.append(("write", batch.time))

    adapter = CallableAdapter(fn, "tz")
    adapter.open = lambda tok: events.append(("open", tok))
    adapter.on_timeout = lambda: events.append(("on_timeout",))
    s = DeliverySink(
        adapter, "tz",
        policy=RetryPolicy(first_delay_ms=1, jitter_ms=0, max_retries=2),
        dlq=DeadLetterQueue(str(tmp_path / "dlq")),
    )
    s.on_batch(2, _batch(2, [1]))
    assert s.drain(timeout=15)
    s.shutdown()
    assert ("on_timeout",) in events
    # reopened (with the last acked token, None here) before the retry
    reset_ix = events.index(("on_timeout",))
    assert ("open", None) in events[reset_ix:]
    assert events[-1] == ("write", 2)


def test_fs_adapter_on_timeout_reopen_keeps_file_exact(tmp_path):
    from pathway_tpu.io.fs import _FsSinkAdapter

    path = tmp_path / "o.csv"
    a = _FsSinkAdapter(str(path), "csv", ["x"])
    a.open(None)
    tok = a.write_batch(SinkBatchStub(2, [1, 2]))
    a.on_timeout()  # zombie cutoff: handles closed
    a.open(tok)  # delivery reopens from the last acked token
    a.write_batch(SinkBatchStub(4, [3]))
    a.close()
    lines = path.read_text().strip().splitlines()
    assert lines == ["x,time,diff", "1,2,1", "2,2,1", "3,4,1"]


class SinkBatchStub:
    def __init__(self, t, vals):
        from pathway_tpu.io.delivery import SinkBatch

        self.time = t
        self.delta = _batch(t, vals)

    def __len__(self):
        return len(self.delta)


# -- stats plumbing ------------------------------------------------------


def test_sink_stats_snapshot_surface(tmp_path):
    s = _sink(lambda b: None, tmp_path, name="stats")
    s.on_batch(2, _batch(2, [1, 2]))
    assert s.drain(timeout=10)
    s.shutdown()
    snap = sink_stats_snapshot()
    assert snap["stats"]["delivered_total"] == 1
    assert snap["stats"]["delivered_rows_total"] == 2
    assert snap["stats"]["acked_time"] == 2


def test_fatal_writer_failure_surfaces_on_engine_thread(tmp_path):
    s = _sink(lambda b: None, tmp_path, name="fatal")
    s._failure = RuntimeError("writer died")
    with pytest.raises(RuntimeError, match="delivery failed"):
        s.on_batch(2, _batch(2, [1]))


# -- end-to-end through pw.run (non-persisted immediate mode) ------------


def test_pw_run_static_table_through_delivery(tmp_path):
    out = tmp_path / "out.csv"
    t = pw.debug.table_from_rows(
        pw.schema_from_types(a=int, b=str), [(1, "x"), (2, "y")]
    )
    pw.io.csv.write(t, str(out), name="e2e")
    pw.run()
    lines = out.read_text().strip().splitlines()
    assert lines[0] == "a,b,time,diff"
    assert len(lines) == 3
    snap = sink_stats_snapshot()
    assert snap["e2e"]["delivered_rows_total"] == 2
