"""Behavioral coverage for Table surfaces not exercised elsewhere:
restrict/having/ix_ref/with_universe_of/with_id_from/rename_by_dict/
cast_to_types, universe promises, join aliases, pw.Json, declare_type,
schema_from_dict, iterate_universe (reference behaviors:
``python/pathway/internals/table.py`` + ``tests/test_common.py``)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
    run_table,
)


@pytest.fixture(autouse=True)
def _fresh_graph():
    G.clear()
    yield
    G.clear()


def rows_of(table):
    state, _names = run_table(table)
    from pathway_tpu.testing import _norm_row

    # repr-keyed sort: rows may contain None/mixed types
    return sorted((_norm_row(row) for row in state.values()), key=repr)


def test_restrict_to_subset_universe():
    t = T(
        """
        a | b
        1 | x
        2 | y
        3 | z
        """
    )
    small = t.filter(pw.this.a >= 2)
    # restrict needs a proven subset relation — filter provides it
    restricted = t.restrict(small)
    assert rows_of(restricted) == [(2, "y"), (3, "z")]


def test_restrict_refuses_unprovable_universe():
    t = T("a\n1\n2")
    other = T("b\n5")  # unrelated universe
    with pytest.raises(ValueError, match="provable subset"):
        t.restrict(other)


def test_with_universe_of_refuses_unprovable():
    # same-length unindexed tables share a universe by the reference's
    # ordinal-id rule, so use different key material to stay unprovable
    t = T("a\n1\n2")
    other = T("b\n5\n6\n7")
    with pytest.raises(ValueError, match="provably equal"):
        t.with_universe_of(other)


def test_same_length_static_tables_share_universe():
    # the reference's static-tables cache (debug/__init__.py:384-401): the
    # Nth unindexed row always gets the same id, so equal-length tables are
    # cross-selectable without promises
    t = T("a\n1\n2")
    other = T("b\n5\n6")
    res = t.select(pw.this.a, b=other.b)
    assert rows_of(res) == [(1, 5), (2, 6)]


def test_having_filters_to_existing_keys():
    data = T(
        """
        k | v
        a | 1
        b | 2
        c | 3
        """
    ).with_id_from(pw.this.k)
    queries = T(
        """
        k
        a
        c
        d
        """
    )
    ptr = queries.select(p=data.pointer_from(queries.k))
    present = data.having(ptr.p).select(pw.this.k, pw.this.v)
    assert rows_of(present) == [("a", 1), ("c", 3)]


def test_ix_ref_and_optional():
    data = T(
        """
        k | v
        a | 10
        b | 20
        """
    ).with_id_from(pw.this.k)
    q = T(
        """
        k
        a
        b
        """
    )
    got = data.ix_ref(q.k, context=q).select(pw.this.v)
    assert rows_of(got) == [(10,), (20,)]
    q2 = T(
        """
        k
        a
        z
        """
    )
    opt = data.ix_ref(q2.k, context=q2, optional=True).select(pw.this.v)
    assert rows_of(opt) == sorted([(10,), (None,)], key=repr)


def test_with_universe_of_swaps_keys():
    base = T(
        """
        a
        1
        2
        """
    )
    derived = base.select(b=pw.this.a * 10)
    back = derived.with_universe_of(base)
    joined = base + back  # same universe → columns can be zipped
    assert rows_of(joined) == [(1, 10), (2, 20)]


def test_rename_by_dict_and_swap():
    t = T(
        """
        a | b
        1 | 2
        """
    )
    r = t.rename_by_dict({"a": "b", "b": "a"})
    assert set(r.column_names()) == {"a", "b"}
    assert rows_of(r.select(pw.this.a, pw.this.b)) == [(2, 1)]


def test_cast_to_types():
    t = T(
        """
        a | b
        1 | 2
        """
    )
    c = t.cast_to_types(a=float)
    (row,) = rows_of(c)
    assert row == (1.0, 2) and isinstance(row[0], float)


def test_join_aliases_match_modes():
    left = T(
        """
        k | x
        a | 1
        b | 2
        """
    )
    right = T(
        """
        k | y
        b | 20
        c | 30
        """
    )
    inner = left.join_inner(right, left.k == right.k).select(
        pw.left.k, pw.this.x, pw.this.y
    )
    assert rows_of(inner) == [("b", 2, 20)]
    outer = left.join_outer(right, left.k == right.k).select(
        x=pw.left.x, y=pw.right.y
    )
    assert rows_of(outer) == sorted(
        [(None, 30), (1, None), (2, 20)], key=repr
    )


def test_promise_universes_are_equal_allows_zip():
    def make():
        a = T(
            """
            k | x
            p | 1
            q | 2
            """
        ).with_id_from(pw.this.k)
        b = T(
            """
            k | y
            p | 5
            q | 6
            """
        ).with_id_from(pw.this.k)
        return a.without(pw.this.k), b.without(pw.this.k)

    # the keys DO match (same with_id_from args) but equality is
    # unprovable without a promise
    a, b = make()
    with pytest.raises(Exception):
        run_table(a + b)
    G.clear()
    a, b = make()
    a.promise_universes_are_equal(b)
    assert rows_of(a + b) == [(1, 5), (2, 6)]


def test_promise_disjoint_allows_concat():
    # explicit distinct ids: unindexed same-length tables would now REALLY
    # collide (ordinal ids), exactly as in the reference
    a = T(
        """
          | x
        1 | 1
        """
    )
    b = T(
        """
          | x
        2 | 2
        """
    )
    a.promise_universes_are_disjoint(b)
    c = a.concat(b)
    assert rows_of(c) == [(1,), (2,)]


def test_with_id_from_is_deterministic_and_joinable():
    t1 = T(
        """
        k | v
        a | 1
        b | 2
        """
    ).with_id_from(pw.this.k)
    t2 = T(
        """
        k | w
        a | 9
        b | 8
        """
    ).with_id_from(pw.this.k)
    t1.promise_universes_are_equal(t2)
    z = t1 + t2.without(pw.this.k)
    assert rows_of(z.select(pw.this.k, pw.this.v, pw.this.w)) == [
        ("a", 1, 9),
        ("b", 2, 8),
    ]


def test_json_values_flow_through():
    j = pw.Json({"a": [1, 2], "b": {"c": "x"}})
    t = T(
        """
        i
        1
        """
    ).select(doc=j)
    got = t.select(
        first=pw.this.doc["a"][0],
        nested=pw.this.doc["b"]["c"].as_str(),
    )
    assert rows_of(got) == [(1, "x")] or rows_of(got) == [
        (pw.Json(1), "x")
    ]


def test_json_accessors_are_strict():
    t = T("i\n1").select(doc=pw.Json({"s": "x", "n": 3, "f": 1.5, "b": True}))
    got = t.select(
        a=pw.this.doc["s"].as_int(),   # mismatch -> None
        b=pw.this.doc["n"].as_int(),
        c=pw.this.doc["f"].as_float(),
        d=pw.this.doc["n"].as_float(),  # int widens to float
        e=pw.this.doc["b"].as_bool(),
        f=pw.this.doc["n"].as_bool(),   # mismatch -> None
        g=pw.this.doc["s"].as_str(),
        h=pw.this.doc["n"].as_str(),    # mismatch -> None
    )
    assert rows_of(got) == [(None, 3, 1.5, 3.0, True, None, "x", None)]


def test_having_refuses_this_placeholder():
    t = T("a\n1")
    with pytest.raises(TypeError, match="concrete table"):
        t.having(pw.this.a)


def test_fuzzy_match_mutual_best_is_intersection():
    # weights: (l1,r1) strong, (l1,r2) medium, (l2,r2) weak —
    # best-for-r2 is (l1,r2) which is NOT best-for-l1, so the only
    # mutually-best pair is (l1,r1); a subset-promise restrict would
    # have mis-declared the universe here (review finding)
    from pathway_tpu.stdlib.ml import fuzzy_match

    # l0="alpha beta gamma" matches r0 strongly (3 shared) and r1 weakly
    # (beta only); nothing else matches r1. best-for-l0 = r0, but
    # best-for-r1 = l0 — that pair is NOT mutual and must be cut, so
    # exactly one pair survives. The old restrict-based cut promised a
    # false subset here (review finding).
    left = pw.debug.table_from_rows(
        pw.schema_from_types(v=str),
        [("alpha beta gamma",), ("zeta",)],
    )
    right = pw.debug.table_from_rows(
        pw.schema_from_types(v=str),
        [("alpha beta gamma",), ("beta epsilon",)],
    )
    m = fuzzy_match(left.v, right.v)
    got = rows_of(m.select(pw.this.weight))
    assert len(got) == 1


def test_declare_type_changes_dtype():
    t = T(
        """
        a
        1
        """
    )
    s = t.select(b=pw.declare_type(float, pw.this.a))
    assert "float" in str(s.schema.typehints()["b"]).lower() or s.schema is not None


def test_schema_from_dict_and_types_roundtrip():
    sch = pw.schema_from_dict({"a": int, "b": str})
    assert set(sch.column_names()) == {"a", "b"}
    sch2 = pw.schema_from_types(x=float)
    assert sch2.column_names() == ["x"]


def test_iterate_universe_fixpoint():
    # collatz-style shrink: keep halving even numbers until all odd
    def step(t):
        return t.select(
            v=pw.if_else(pw.this.v % 2 == 0, pw.this.v // 2, pw.this.v)
        )

    t = T(
        """
        v
        8
        3
        12
        """
    )
    out = pw.iterate(step, t=t)
    assert rows_of(out) == [(1,), (3,), (3,)]


def test_groupby_reduce_on_renamed_columns():
    t = T(
        """
        g | v
        a | 1
        a | 2
        b | 3
        """
    ).rename_by_dict({"g": "grp"})
    r = t.groupby(pw.this.grp).reduce(
        pw.this.grp, total=pw.reducers.sum(pw.this.v)
    )
    assert rows_of(r) == [("a", 3), ("b", 3)]


def test_update_cells_requires_subset_and_updates():
    base = T(
        """
        k | v
        a | 1
        b | 2
        """
    ).with_id_from(pw.this.k)
    patch = T(
        """
        k | v
        b | 20
        """
    ).with_id_from(pw.this.k)
    patch.promise_universe_is_subset_of(base)
    upd = base.update_cells(patch)
    assert rows_of(upd) == [("a", 1), ("b", 20)]


def test_assert_table_equality_helpers():
    a = T(
        """
        x
        1
        2
        """
    )
    b = T(
        """
        x
        1
        2
        """
    )
    assert_table_equality_wo_index(a, b)
    with pytest.raises(AssertionError):
        assert_table_equality_wo_index(a, T("x\n1\n3"))
