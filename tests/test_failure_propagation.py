"""Fast failure propagation in the TCP cluster mesh.

Satellite coverage for ISSUE 2: a dead peer must fail every blocked
collective in milliseconds (notify_all on the `_broken` mark), never
wait out the collective timeout; timeouts are env-tunable; mesh
establishment names the unreachable peer.
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

from pathway_tpu.parallel.cluster import ClusterComm


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _mesh(n: int, threads_per_process: int = 1) -> dict[int, ClusterComm]:
    port = _free_port()
    comms: dict[int, ClusterComm] = {}

    def make(pid: int) -> None:
        comms[pid] = ClusterComm(
            process_id=pid, n_processes=n,
            threads_per_process=threads_per_process, first_port=port,
        )

    makers = [threading.Thread(target=make, args=(p,)) for p in range(n)]
    for m in makers:
        m.start()
    for m in makers:
        m.join(30)
    assert set(comms) == set(range(n))
    return comms


def test_peer_death_unblocks_collectives_within_a_second():
    """Worker 0 blocks in an allgather; process 1 dies (sockets torn).
    The blocked collective must raise in < 1s — not at the 600s timeout —
    and the error must name the failed peer."""
    comms = _mesh(2)
    outcome: dict = {}
    entered = threading.Event()

    def blocked() -> None:
        t0 = time.monotonic()
        entered.set()
        try:
            comms[0].allgather("never-completes", 0, "x")
            outcome["result"] = "completed"
        except RuntimeError as e:
            outcome["error"] = str(e)
            outcome["elapsed"] = time.monotonic() - t0

    th = threading.Thread(target=blocked, daemon=True)
    th.start()
    assert entered.wait(5)
    time.sleep(0.1)  # let the allgather actually block
    # simulate process 1 dying: its sockets close, comm0's reader sees EOF
    comms[1]._shutdown_sockets()
    th.join(5)
    assert not th.is_alive(), "collective still blocked after peer death"
    assert "error" in outcome, outcome
    assert outcome["elapsed"] < 1.0, (
        f"propagation took {outcome['elapsed']:.2f}s (acceptance: < 1s)"
    )
    assert "peer worker failed" in outcome["error"]
    assert "process 1" in outcome["error"], outcome["error"]
    comms[0].close()


def test_break_wakes_all_blocked_collectives_at_once():
    """Several workers blocked in distinct collectives all unwind on one
    `_broken` mark (the notify_all contract), each within the deadline."""
    comms = _mesh(2, threads_per_process=2)
    errors: list[tuple[int, float]] = []
    lock = threading.Lock()

    def blocked(wid: int) -> None:
        t0 = time.monotonic()
        try:
            comms[0].allgather(("tag", wid), wid, wid)
        except RuntimeError:
            with lock:
                errors.append((wid, time.monotonic() - t0))

    ts = [
        threading.Thread(target=blocked, args=(w,), daemon=True)
        for w in (0, 1)  # both local workers of process 0
    ]
    for t in ts:
        t.start()
    time.sleep(0.15)
    comms[1]._shutdown_sockets()
    for t in ts:
        t.join(5)
    assert not any(t.is_alive() for t in ts), "a collective stayed blocked"
    assert sorted(w for w, _ in errors) == [0, 1]
    assert all(dt < 1.0 for _, dt in errors), errors
    comms[0].close()


def test_collective_timeout_env_knob(monkeypatch):
    """PATHWAY_COLLECTIVE_TIMEOUT_S bounds a silent stall (no peer death,
    just a missing contribution) and the error names the missing workers."""
    monkeypatch.setenv("PATHWAY_COLLECTIVE_TIMEOUT_S", "0.3")
    comms = _mesh(2)
    assert comms[0].collective_timeout_s == 0.3
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="timed out") as ei:
        # process 1 never contributes: a stall, not a death
        comms[0].allgather("lonely", 0, "x")
    assert time.monotonic() - t0 < 5.0
    assert "workers [1]" in str(ei.value)
    for c in comms.values():
        c.close()


def test_connect_timeout_env_knob_names_unreachable_peer(monkeypatch):
    """Mesh establishment: an unreachable peer fails fast (tunable) and
    the error names the peer process and its address."""
    monkeypatch.setenv("PATHWAY_CONNECT_TIMEOUT_S", "0.5")
    port = _free_port()  # nothing listens here
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="peer process 0") as ei:
        ClusterComm(
            process_id=1, n_processes=2, threads_per_process=1,
            first_port=port,
        )
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, f"connect retry ran {elapsed:.1f}s past its budget"
    assert f"127.0.0.1:{port}" in str(ei.value)


def test_sever_fault_partitions_the_mesh():
    """A chaos 'sever' on the link tears the socket; both sides propagate
    the failure instead of hanging."""
    from pathway_tpu import chaos

    chaos.arm(chaos.FaultPlan.from_dict({
        "faults": [{"site": "comm.send", "process": 0, "peer": 1,
                    "nth": 1, "action": "sever"}],
    }), run=0)
    try:
        comms = _mesh(2)
        results: dict[int, str] = {}

        def gather(pid: int) -> None:
            try:
                comms[pid].allgather("t", pid, pid)
                results[pid] = "ok"
            except RuntimeError:
                results[pid] = "failed"

        ts = [
            threading.Thread(target=gather, args=(p,), daemon=True)
            for p in (0, 1)
        ]
        t0 = time.monotonic()
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        assert not any(t.is_alive() for t in ts)
        # process 0's first frame to 1 severed the link; 1 never receives
        # 0's contribution, so its side always fails
        assert results[1] == "failed"
        # 0's in-flight gather may legitimately complete when 1's
        # contribution raced ahead of the sever — but the partition must
        # surface on 0's side by the next collective (its reader's EOF
        # flips the broken mark and wakes any blocked wait)
        if results[0] == "ok":
            with pytest.raises(RuntimeError, match="peer worker failed"):
                comms[0].allgather("t2", 0, 0)
        assert time.monotonic() - t0 < 5.0
        for c in comms.values():
            c.close()
    finally:
        chaos.disarm()
