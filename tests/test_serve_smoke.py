"""Tier-1 wrapper around scripts/serve_smoke.py (like test_chaos_smoke):
the shard-loss serving contract end to end — a serve.query fault plan
silences shard 1, generation 0 keeps answering fast degraded 200s with
zero client timeouts while the shard is dark, the harness SIGKILLs the
silenced shard's process, `spawn --supervise` relaunches, and the
fault-free generation serves the exact full top-k again."""

import os
import sys

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
    ),
)


def test_serve_smoke(tmp_path):
    from serve_smoke import FULL_TOPK, run_smoke

    result = run_smoke(workdir=str(tmp_path))
    assert result["generations"] == [0, 1]
    assert result["gen0_degraded"] >= 2
    assert result["timeouts"] == 0
    assert sorted(result["gen1_full"]["body"]["hits"]) == sorted(FULL_TOPK)
