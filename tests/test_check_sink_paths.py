"""Tier-1 wiring for ``scripts/check_sink_paths.py``: every io/ sink
write entrypoint routes through the delivery layer, and the checker
itself catches a naked write."""

from __future__ import annotations

import os
import sys
import textwrap

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

import check_sink_paths  # noqa: E402


def test_no_naked_sink_writes():
    bad = check_sink_paths.check_all()
    assert not bad, (
        "io/ sinks bypass the delivery layer (retries/acks/DLQ):\n"
        + "\n".join(p for ps in bad.values() for p in ps)
    )


def test_checker_catches_naked_subscribe(tmp_path):
    mod = tmp_path / "naked.py"
    mod.write_text(textwrap.dedent("""
        def write(table, target):
            from . import subscribe
            subscribe(table, on_change=lambda **kw: None)
    """))
    problems = check_sink_paths.check_module(str(mod))
    assert len(problems) == 1
    assert "subscribe" in problems[0]


def test_checker_accepts_deliver_and_delegation(tmp_path):
    mod = tmp_path / "fs.py"
    mod.write_text(textwrap.dedent("""
        def write(table, target):
            from .delivery import deliver
            deliver(table, lambda: None, name="x")
    """))
    assert check_sink_paths.check_module(str(mod)) == []
    wrapper = tmp_path / "csv.py"
    wrapper.write_text(textwrap.dedent("""
        from . import fs
        def write(table, target, **kw):
            fs.write(table, target, format="csv", **kw)
    """))
    assert check_sink_paths.check_module(str(wrapper)) == []
