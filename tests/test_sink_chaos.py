"""Client-sink resilience under ``sink.write`` chaos, against fake
clients: mongodb, postgres and elasticsearch each prove

- transient fail ×2 → delivered exactly once (the shared RetryPolicy
  redelivers, the batch lands one time in the external system);
- reject-nth → the poison row lands in the DLQ with its original
  content and error, the rest of the batch still delivers, and nothing
  is silently dropped.
"""

from __future__ import annotations

import json
import sys
import types

import pytest

import pathway_tpu as pw
from pathway_tpu.chaos import injector as inj
from pathway_tpu.chaos.plan import FaultPlan
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io.delivery import _reset_stats_for_tests


@pytest.fixture(autouse=True)
def _clean(monkeypatch, tmp_path):
    monkeypatch.setenv("PATHWAY_SINK_DLQ_DIR", str(tmp_path / "dlq"))
    monkeypatch.setenv("PATHWAY_SINK_RETRY_FIRST_DELAY_MS", "1")
    monkeypatch.setenv("PATHWAY_SINK_RETRY_JITTER_MS", "0")
    G.clear()
    _reset_stats_for_tests()
    inj.disarm()
    yield
    inj.disarm()
    G.clear()
    _reset_stats_for_tests()


def _arm(faults):
    inj.arm(FaultPlan.from_dict({"seed": 5, "faults": faults}), run=0)


def _fail_twice(sink_prefix):
    return [
        {"site": "sink.write", "action": "fail", "nth": 1,
         "key_prefix": sink_prefix},
        {"site": "sink.write", "action": "fail", "nth": 2,
         "key_prefix": sink_prefix},
    ]


def _reject_first(sink_prefix):
    return [
        {"site": "sink.write", "action": "reject", "nth": 1,
         "key_prefix": sink_prefix},
    ]


def _dlq_entries(tmp_path, sink_name):
    path = tmp_path / "dlq" / f"{sink_name}.jsonl"
    assert path.exists(), f"no DLQ file at {path}"
    return [json.loads(line) for line in path.open()]


def _table(rows=3):
    return pw.debug.table_from_rows(
        pw.schema_from_types(x=int, label=str),
        [(i, f"row-{i}") for i in range(rows)],
    )


# -- mongodb -------------------------------------------------------------


class _FakeCollection:
    def __init__(self):
        self.insert_many_calls: list[list[dict]] = []

    def insert_many(self, docs):
        self.insert_many_calls.append([dict(d) for d in docs])


class _FakeMongoClient:
    instances: list["_FakeMongoClient"] = []

    def __init__(self, connection_string):
        self._dbs: dict = {}
        _FakeMongoClient.instances.append(self)

    def __getitem__(self, name):
        return self._dbs.setdefault(name, _FakeMongoDb())


class _FakeMongoDb:
    def __init__(self):
        self._colls: dict = {}

    def __getitem__(self, name):
        return self._colls.setdefault(name, _FakeCollection())


@pytest.fixture
def fake_pymongo(monkeypatch):
    mod = types.ModuleType("pymongo")
    mod.MongoClient = _FakeMongoClient
    _FakeMongoClient.instances = []
    monkeypatch.setitem(sys.modules, "pymongo", mod)
    yield mod


def _mongo_docs():
    coll = _FakeMongoClient.instances[-1]["db"]["events"]
    return [d for call in coll.insert_many_calls for d in call]


def test_mongodb_transient_fail_twice_delivered_once(fake_pymongo):
    _arm(_fail_twice("mongodb"))
    pw.io.mongodb.write(_table(), "mongodb://fake", "db", "events")
    pw.run()
    docs = _mongo_docs()
    assert sorted(d["x"] for d in docs) == [0, 1, 2]  # once each, no dupes


def test_mongodb_reject_goes_to_dlq(fake_pymongo, tmp_path):
    _arm(_reject_first("mongo-sink"))
    pw.io.mongodb.write(_table(), "mongodb://fake", "db", "events",
                        name="mongo-sink")
    pw.run()
    docs = _mongo_docs()
    entries = _dlq_entries(tmp_path, "mongo-sink")
    assert len(entries) == 1
    dead = entries[0]["row"]
    assert "reject" in entries[0]["error"]
    # no silent drop: delivered ∪ DLQ covers every input row exactly once
    assert sorted([d["x"] for d in docs] + [dead["x"]]) == [0, 1, 2]
    from pathway_tpu.io.delivery import sink_stats_snapshot

    assert sink_stats_snapshot()["mongo-sink"]["dlq_total"] == 1


# -- postgres ------------------------------------------------------------


class _FakePgCursor:
    def __init__(self, conn):
        self._conn = conn

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def execute(self, sql, params=None):
        self._conn._staged.append((sql, list(params or [])))

    def executemany(self, sql, rows):
        for r in rows:
            self._conn._staged.append((sql, list(r)))


class _FakePgConn:
    instances: list["_FakePgConn"] = []

    def __init__(self):
        self._staged: list = []
        self.committed: list = []
        self.rollbacks = 0
        _FakePgConn.instances.append(self)

    def cursor(self):
        return _FakePgCursor(self)

    def commit(self):
        self.committed.extend(self._staged)
        self._staged = []

    def rollback(self):
        self.rollbacks += 1
        self._staged = []

    def close(self):
        pass


@pytest.fixture
def fake_psycopg(monkeypatch):
    mod = types.ModuleType("psycopg")
    mod.connect = lambda **kw: _FakePgConn()
    _FakePgConn.instances = []
    monkeypatch.setitem(sys.modules, "psycopg", mod)
    yield mod


def test_postgres_transient_fail_twice_delivered_once(fake_psycopg):
    _arm(_fail_twice("postgres"))
    pw.io.postgres.write(_table(), {}, "tbl")
    pw.run()
    conn = _FakePgConn.instances[-1]
    xs = sorted(p[0] for _sql, p in conn.committed)
    assert xs == [0, 1, 2]  # one committed transaction, no dupes


def test_postgres_reject_goes_to_dlq(fake_psycopg, tmp_path):
    _arm(_reject_first("pg-sink"))
    pw.io.postgres.write(_table(), {}, "tbl", name="pg-sink")
    pw.run()
    conn = _FakePgConn.instances[-1]
    entries = _dlq_entries(tmp_path, "pg-sink")
    assert len(entries) == 1
    xs = sorted(p[0] for _sql, p in conn.committed)
    assert sorted(xs + [entries[0]["row"]["x"]]) == [0, 1, 2]
    assert entries[0]["row"]["label"].startswith("row-")


def test_postgres_write_snapshot_retries_rollback_server_side(fake_psycopg):
    """A torn attempt must roll the SQL transaction back before the
    retry: committed rows appear exactly once."""
    _arm([{"site": "sink.write", "action": "torn", "nth": 1,
           "key_prefix": "postgres"}])
    pw.io.postgres.write_snapshot(_table(), {}, "tbl", ["x"])
    pw.run()
    conn = _FakePgConn.instances[-1]
    assert conn.rollbacks >= 1
    upserts = [p for sql, p in conn.committed if "INSERT" in sql]
    assert sorted(p[0] for p in upserts) == [0, 1, 2]


# -- elasticsearch -------------------------------------------------------


class _FakeEs:
    instances: list["_FakeEs"] = []

    def __init__(self, **kwargs):
        self.indexed: list[tuple[str, dict]] = []
        _FakeEs.instances.append(self)

    def index(self, index, document):
        self.indexed.append((index, dict(document)))


@pytest.fixture
def fake_elasticsearch(monkeypatch):
    mod = types.ModuleType("elasticsearch")
    mod.Elasticsearch = _FakeEs
    _FakeEs.instances = []
    monkeypatch.setitem(sys.modules, "elasticsearch", mod)
    yield mod


def test_elasticsearch_transient_fail_twice_delivered_once(
    fake_elasticsearch,
):
    _arm(_fail_twice("elasticsearch"))
    pw.io.elasticsearch.write(_table(), host="http://x", index_name="idx")
    pw.run()
    es = _FakeEs.instances[-1]
    assert sorted(d["x"] for _i, d in es.indexed) == [0, 1, 2]
    assert all(i == "idx" for i, _d in es.indexed)


def test_elasticsearch_reject_goes_to_dlq(fake_elasticsearch, tmp_path):
    _arm(_reject_first("es-sink"))
    pw.io.elasticsearch.write(
        _table(), host="http://x", index_name="idx", name="es-sink"
    )
    pw.run()
    es = _FakeEs.instances[-1]
    entries = _dlq_entries(tmp_path, "es-sink")
    assert len(entries) == 1
    got = sorted(
        [d["x"] for _i, d in es.indexed] + [entries[0]["row"]["x"]]
    )
    assert got == [0, 1, 2]
