"""stdlib.graphs: bellman_ford / pagerank / louvain — exercises pw.iterate
(reference test model: python/pathway/tests + stdlib/graphs)."""

import math

import pathway_tpu as pw
from pathway_tpu.stdlib.graphs import Graph, WeightedGraph
from pathway_tpu.stdlib.graphs.bellman_ford import bellman_ford
from pathway_tpu.stdlib.graphs.louvain_communities import (
    exact_modularity,
    louvain_level,
)
from pathway_tpu.stdlib.graphs.pagerank import pagerank
from pathway_tpu.testing import T, run_table


def _vertices_edges():
    vertices = pw.debug.table_from_markdown(
        """
        name | is_source
        a    | True
        b    | False
        c    | False
        d    | False
        """,
        id_from="name",
    )
    raw = pw.debug.table_from_markdown(
        """
        un | vn | dist
        a  | b  | 1.0
        b  | c  | 2.0
        a  | c  | 10.0
        """
    )
    edges = raw.select(
        u=vertices.pointer_from(raw.un),
        v=vertices.pointer_from(raw.vn),
        dist=raw.dist,
    )
    return vertices, edges


def test_bellman_ford():
    vertices, edges = _vertices_edges()
    res = bellman_ford(vertices, edges)
    named = vertices.join(res, vertices.id == res.id).select(
        vertices.name, d=res.dist_from_source
    )
    rows, _ = run_table(named)
    by_name = {r[0]: r[1] for r in rows.values()}
    assert by_name["a"] == 0.0
    assert by_name["b"] == 1.0
    assert by_name["c"] == 3.0
    assert math.isinf(by_name["d"])


def test_pagerank_sums_and_orders():
    edges_raw = T(
        """
        un | vn
        a  | b
        c  | b
        b  | a
        """
    )
    edges = edges_raw.select(
        u=edges_raw.pointer_from(edges_raw.un),
        v=edges_raw.pointer_from(edges_raw.vn),
    )
    ranks = pagerank(edges, steps=30)
    # tie ranks back to vertex names via the vertex pointer
    uv = edges_raw.select(name=edges_raw.un, vid=edges_raw.pointer_from(edges_raw.un))
    vv = edges_raw.select(name=edges_raw.vn, vid=edges_raw.pointer_from(edges_raw.vn))
    verts = uv.concat_reindex(vv).groupby(pw.this.name).reduce(
        pw.this.name, vid=pw.reducers.unique(pw.this.vid)
    )
    named = verts.join(ranks, verts.vid == pw.right.id).select(
        verts.name, rank=pw.right.rank
    )
    rows, _ = run_table(named)
    by_name = {r[0]: r[1] for r in rows.values()}
    assert len(by_name) == 3
    # b receives from two vertices -> highest; c receives nothing -> lowest
    assert by_name["b"] > by_name["a"] > by_name["c"]


def test_louvain_two_cliques():
    # two triangles joined by a single weak edge -> two communities
    e = T(
        """
        a | b | w
        1 | 2 | 1.0
        2 | 3 | 1.0
        1 | 3 | 1.0
        4 | 5 | 1.0
        5 | 6 | 1.0
        4 | 6 | 1.0
        3 | 4 | 0.1
        """
    )
    we = e.select(
        u=e.pointer_from(e.a), v=e.pointer_from(e.b), weight=e.w
    )
    allv = e.select(x=e.a).concat_reindex(e.select(x=e.b))
    verts = allv.groupby(id=allv.pointer_from(allv.x)).reduce()
    G = WeightedGraph.from_vertices_and_weighted_edges(verts, we)
    clustering = louvain_level(G)
    rows, _ = run_table(clustering)
    assert len(rows) == 6
    clusters = set(c for (c,) in rows.values())
    assert len(clusters) == 2

    mod = exact_modularity(G, clustering)
    mrows, _ = run_table(mod)
    (q,) = list(mrows.values())[0]
    assert q > 0.3  # strongly clustered


def test_graph_contraction():
    e = T(
        """
        a | b
        1 | 2
        2 | 3
        """
    )
    edges = e.select(u=e.pointer_from(e.a), v=e.pointer_from(e.b))
    allv = e.select(x=e.a).concat_reindex(e.select(x=e.b))
    verts = allv.groupby(id=allv.pointer_from(allv.x)).reduce()
    # cluster 1 and 2 together (map both to vertex-1's pointer)
    base = T(
        """
        x | y
        1 | 1
        2 | 1
        """
    )
    cl = base.select(c=base.pointer_from(base.y)).with_id(
        base.pointer_from(base.x)
    )
    g = Graph(V=verts, E=edges).contracted_to_multi_graph(cl)
    rows, _ = run_table(g.E)
    assert len(rows) == 2  # edges 1->2 becomes self-loop, 2->3 crosses
    g2 = Graph(V=verts, E=edges).contracted_to_multi_graph(cl).without_self_loops()
    rows2, _ = run_table(g2.E)
    assert len(rows2) == 1
