"""The r2 vectorized hot paths: dense groupby arena, columnar sort-merge
join, batched connector/sink lanes, narrow-dtype key hashing.

Semantics must be identical to the general per-row paths (reference
reduce.rs / differential join_core) — these tests drive the specific
machinery: retraction correctness, arena demotion, run compaction,
row/batch emission equivalence.
"""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.engine import keys as K
from pathway_tpu.engine.delta import Delta
from pathway_tpu.engine.operators import GroupByReduce, Join, StaticSource, _SortedSide
from pathway_tpu.engine.reducers import make_reducer
from pathway_tpu.engine.slotmap import SlotMap
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def _col(vals):
    from pathway_tpu.engine.delta import column_of_values

    return column_of_values(list(vals))


def _mkdelta(words, diffs=None, extra=None):
    n = len(words)
    data = {"w": _col(words)}
    if extra:
        for k, v in extra.items():
            data[k] = _col(v)
    keys = K.hash_values([(i,) for i in range(n)], salt=123)
    return Delta(keys=keys, data=data,
                 diffs=None if diffs is None else np.asarray(diffs, np.int64))


def _drain(node, deltas):
    """Apply delta ticks; return final consolidated {group: row} state."""
    state = {}
    for t, d in enumerate(deltas):
        out = node.process(t, [d])
        if out is None:
            continue
        for key, row, diff in out.iter_rows():
            cur = state.get(key, (None, 0))
            if diff > 0:
                state[key] = (row, cur[1] + diff)
            else:
                state[key] = (cur[0] if cur[1] + diff > 0 else None, cur[1] + diff)
        state = {k: v for k, v in state.items() if v[1] != 0}
    return {v[0][0]: v[0] for v in state.values()}


def test_dense_groupby_count_sum_with_retractions():
    src = StaticSource(np.array([], np.uint64), {"w": _col([]), "x": _col([])})
    node = GroupByReduce(
        src, ["w"],
        [("c", make_reducer("count"), []), ("s", make_reducer("sum"), ["x"])],
    )
    assert node._dense
    d1 = _mkdelta(["a", "b", "a"], extra={"x": [1, 10, 2]})
    d2 = _mkdelta(["a", "b", "a"], diffs=[-1, 1, -1], extra={"x": [1, 5, 2]})
    final = _drain(node, [d1, d2])
    assert node._dense  # stayed on the arena path
    assert final == {"a": None, "b": ("b", 2, 15)} or final == {"b": ("b", 2, 15)}


def test_dense_groupby_group_vanishes_and_revives():
    src = StaticSource(np.array([], np.uint64), {"w": _col([])})
    node = GroupByReduce(src, ["w"], [("c", make_reducer("count"), [])])
    d1 = _mkdelta(["a", "a"])
    d2 = _mkdelta(["a", "a"], diffs=[-1, -1])
    d3 = _mkdelta(["a"])
    final = _drain(node, [d1, d2, d3])
    assert final == {"a": ("a", 1)}


def test_dense_groupby_matches_general_path():
    """Same input stream through arena and general paths — same output."""
    rng = np.random.default_rng(0)
    words = [f"g{i}" for i in rng.integers(0, 50, 500)]
    xs = rng.integers(-5, 100, 500).tolist()
    deltas = [
        _mkdelta(words[i : i + 100], extra={"x": xs[i : i + 100]})
        for i in range(0, 500, 100)
    ]

    def build():
        src = StaticSource(np.array([], np.uint64), {"w": _col([]), "x": _col([])})
        return GroupByReduce(
            src, ["w"],
            [("c", make_reducer("count"), []), ("s", make_reducer("sum"), ["x"])],
        )

    dense = build()
    general = build()
    general._dense = False
    out_d = _drain(dense, deltas)
    out_g = _drain(general, deltas)
    assert out_d == out_g


def test_dense_groupby_demotes_on_object_column():
    src = StaticSource(np.array([], np.uint64), {"w": _col([]), "x": _col([])})
    node = GroupByReduce(src, ["w"], [("s", make_reducer("sum"), ["x"])])
    d1 = _mkdelta(["a", "a"], extra={"x": [1, 2]})
    node.process(0, [d1])
    assert node._dense
    # ndarray-valued sum column → object dtype → demote, keep correctness
    d2 = _mkdelta(["b", "b"], extra={"x": [np.array([1.0, 2.0]), np.array([3.0, 4.0])]})
    out = node.process(1, [d2])
    assert not node._dense
    rows = {row[0]: row for _, row, diff in out.iter_rows() if diff > 0}
    assert np.allclose(rows["b"][1], [4.0, 6.0])
    # state carried over from the arena epoch
    d3 = _mkdelta(["a"], extra={"x": [10]})
    out3 = node.process(2, [d3])
    rows3 = {row[0]: (row, diff) for _, row, diff in out3.iter_rows()}
    assert rows3["a"][0][1] == 13 and rows3["a"][1] in (1,)


def test_sorted_side_probe_and_compaction():
    side = _SortedSide(1)
    jks = np.array([3, 1, 3], np.uint64)
    keys = np.array([100, 101, 102], np.uint64)
    side.apply(jks, keys, [_col(["x", "y", "z"])], np.array([1, 1, 1], np.int64))
    # retract one of the jk=3 rows
    side.apply(np.array([3], np.uint64), np.array([100], np.uint64),
               [_col(["x"])], np.array([-1], np.int64))
    matches = []
    for q_idx, rkeys, cols, counts in side.probe(np.array([3], np.uint64)):
        for i in range(len(rkeys)):
            matches.append((int(rkeys[i]), cols[0][i], int(counts[i])))
    # both runs yield; net multiplicity of key 100 is 0
    net = {}
    for k, v, c in matches:
        net[k] = net.get(k, 0) + c
    assert net == {100: 0, 102: 1}
    for _ in range(10):  # force compaction
        side.apply(np.array([7], np.uint64), np.array([200], np.uint64),
                   [_col(["q"])], np.array([1], np.int64))
    assert len(side._runs) <= side.MAX_RUNS
    # the cancelled (jk=3, key=100) pair is physically gone post-compaction
    assert not any(100 in r[1] for r in side._runs)
    net2 = {}
    for q_idx, rkeys, cols, counts in side.probe(np.array([3], np.uint64)):
        for i in range(len(rkeys)):
            net2[int(rkeys[i])] = net2.get(int(rkeys[i]), 0) + int(counts[i])
    assert net2 == {102: 1}  # cancelled pair dropped at compaction


def test_columnar_inner_join_incremental_retraction():
    left = pw.debug.table_from_markdown("""
        | k | v
      1 | a | 1
      2 | b | 2
    """)
    right = pw.debug.table_from_markdown("""
        | k | w
      9 | a | 10
    """)
    res = left.join(right, left.k == right.k).select(left.v, right.w)
    df = pw.debug.table_to_pandas(res)
    assert sorted(zip(df["v"], df["w"])) == [(1, 10)]


def test_next_batch_and_rowwise_emission_equivalent_keys():
    """Columnar next_batch must produce the same engine keys as per-row
    next() for the same logical rows (mix_columns == hash_values parity)."""
    from pathway_tpu.io.python import ConnectorSubject, PythonSubjectSource, _Batch

    class S(ConnectorSubject):
        def run(self):
            pass

    s1 = S()
    src1 = PythonSubjectSource(s1, ["a", "b"], {}, None, autocommit_ms=None)
    s1.next(a="x", b=1)
    s1.next(a="y", b=2)
    s1.commit()
    (d_row,) = src1.poll()

    s2 = S()
    src2 = PythonSubjectSource(s2, ["a", "b"], {}, None, autocommit_ms=None)
    s2.next_batch({"a": ["x", "y"], "b": [1, 2]})
    s2.commit()
    (d_batch,) = src2.poll()

    assert d_row.keys.tolist() == d_batch.keys.tolist()
    assert d_row.data["a"].tolist() == d_batch.data["a"].tolist()
    assert src1.offset_state() == src2.offset_state()


def test_batch_seek_skips_prefix():
    from pathway_tpu.io.python import ConnectorSubject, PythonSubjectSource

    class S(ConnectorSubject):
        def run(self):
            pass

    s = S()
    src = PythonSubjectSource(s, ["a"], {}, None, autocommit_ms=None)
    src.seek({"rows": 3})
    s.next_batch({"a": [1, 2]})
    s.commit()
    s.next_batch({"a": [3, 4, 5]})
    s.commit()
    deltas = src.poll()
    got = [v for d in deltas for v in d.data["a"].tolist()]
    assert got == [4, 5]
    assert src.offset_state() == {"rows": 5}


def test_on_batch_subscribe_receives_consolidated_columns():
    t = pw.debug.table_from_markdown("""
        | w
      1 | a
      2 | a
      3 | b
    """)
    counts = t.groupby(pw.this.w).reduce(pw.this.w, c=pw.reducers.count())
    seen = []
    pw.io.subscribe(counts, on_batch=lambda time, b: seen.append(
        (sorted(zip(b.data["w"].tolist(), b.data["c"].tolist(), b.diffs.tolist())))
    ))
    pw.run()
    assert seen == [[("a", 2, 1), ("b", 1, 1)]]


def test_narrow_dtype_hash_matches_wide():
    vals = np.array([0, 1, -5, 1000], np.int32)
    wide = np.array([0, 1, -5, 1000], np.int64)
    assert K.hash_column(vals).tolist() == K.hash_column(wide).tolist()
    f32 = np.array([1.5, -2.0], np.float32)
    f64 = np.array([1.5, -2.0], np.float64)
    assert K.hash_column(f32).tolist() == K.hash_column(f64).tolist()


def test_slotmap_python_fallback_matches_native():
    m = SlotMap()
    keys = np.array([9, 9, 4, 2, 4], np.uint64)
    slots, n_new = m.lookup_or_insert(keys)
    assert slots.tolist() == [0, 0, 1, 2, 1] and n_new == 3
    m2 = SlotMap()
    m2._table = None
    m2._dict = {}
    slots2, n_new2 = m2.lookup_or_insert(keys)
    assert slots2.tolist() == slots.tolist() and n_new2 == n_new
    assert m.lookup(np.array([4, 77], np.uint64)).tolist() == [1, -1]
    assert m2.lookup(np.array([4, 77], np.uint64)).tolist() == [1, -1]


def test_dense_groupby_arena_reclaims_dead_slots():
    src = StaticSource(np.array([], np.uint64), {"w": _col([])})
    node = GroupByReduce(src, ["w"], [("c", make_reducer("count"), [])])
    t = 0
    for wave in range(6):
        words = [f"k{wave}-{i}" for i in range(1000)]
        node.process(t, [_mkdelta(words)])
        node.process(t + 1, [_mkdelta(words, diffs=[-1] * len(words))])
        t += 2
    # 6000 distinct groups ever; all dead — the arena must have reclaimed
    assert len(node._slots) < 4000
    # correctness after reclamation: a revived key counts from scratch
    out = node.process(t, [_mkdelta(["k0-0"])])
    rows = {row[0]: (row, d) for _, row, d in out.iter_rows()}
    assert rows["k0-0"][0][1] == 1 and rows["k0-0"][1] == 1


def test_table_from_pandas_preserves_datetimes():
    import pandas as pd

    df = pd.DataFrame({
        "ts": pd.Series(["2024-01-01", "2024-06-15"]).astype("datetime64[ns]"),
        "x": [1, 2],
    })
    t = pw.debug.table_from_pandas(df)
    out = pw.debug.table_to_pandas(t)
    vals = sorted(out["ts"])
    assert vals[0] == pd.Timestamp("2024-01-01")
    assert not isinstance(vals[0], (int, np.integer))


def test_scalar_derivations_bit_identical_to_vectorized():
    """derive_scalar/derive_pair_scalar (plain-int splitmix) must match the
    numpy-vectorized derive/derive_pair bit for bit — per-row compute
    functions and columnar operators share one keyspace."""
    rng = np.random.default_rng(11)
    ks = rng.integers(0, 2**64, 300, dtype=np.uint64)
    rs = rng.integers(0, 2**64, 300, dtype=np.uint64)
    for salt in (0, 0xA50F, 0x5E55, 0x00AD_0000_0000_0001):
        vec = K.derive(ks, salt)
        assert [int(x) for x in vec] == [K.derive_scalar(int(k), salt) for k in ks]
    vec_pair = K.derive_pair(ks, rs)
    assert [int(x) for x in vec_pair] == [
        K.derive_pair_scalar(int(l), int(r)) for l, r in zip(ks, rs)
    ]
