"""Ported from
`/root/reference/python/pathway/tests/test_expression_repr.py`:
stable numbered-table expression reprs."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T


@pytest.fixture(autouse=True)
def _clean():
    G.clear()
    yield
    G.clear()


def _t():
    return T("pet | owner | age\n1 | Alice | 10")


def test_column_reference():
    # reference test_expression_repr.py:10
    t = _t()
    assert repr(t.pet) == "<table1>.pet"


def test_column_binary_op():
    # reference :20
    t = _t()
    for op in ("+", "-", "*", "/", "//", "**", "%",
               "==", "!=", "<", "<=", ">", ">="):
        expr = eval(f"t.pet {op} t.age", {"t": t})
        assert repr(expr) == f"(<table1>.pet {op} <table1>.age)", op


def test_2_args():
    # reference :42 — distinct tables number in appearance order
    t = _t()
    tt = t.copy()
    assert repr(t.pet + tt.age) == "(<table1>.pet + <table2>.age)"


def test_reducers():
    t = _t()
    assert (
        repr(pw.reducers.sum(t.age)) == "pathway.reducers.sum(<table1>.age)"
    )
