"""Ported from the reference's behavioral spec: iterate fixpoints, sort /
prev-next pointers, groupby instance, concat_unsafe collision, update_cells
edge cases.

Source: ``/root/reference/python/pathway/tests/test_common.py`` (third
block; porting contract as in ``tests/test_ported_common_1.py``; manifest
in ``PORTED_TESTS.md``).
"""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.testing import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
)


# -- iterate (test_common.py:1442-1658) --------------------------------------


def test_column_fixpoint():  # ref :1442 (collatz)
    def collatz_transformer(iterated):
        @pw.udf(deterministic=True)
        def collatz_step(x: float) -> float:
            if x == 1:
                return 1
            elif x % 2 == 0:
                return x / 2
            else:
                return 3 * x + 1

        return iterated.select(val=collatz_step(iterated.val))

    ret = pw.iterate(
        collatz_transformer,
        iterated=pw.debug.table_from_pandas(
            pd.DataFrame(
                index=range(1, 101), data={"val": np.arange(1.0, 101.0)}
            )
        ),
    )
    expected = pw.debug.table_from_pandas(
        pd.DataFrame(index=range(1, 101), data={"val": 1.0})
    )
    assert_table_equality(ret, expected)


def test_rows_fixpoint():  # ref :1468 (shrinking universe to empty)
    def min_id_remove(iterated: pw.Table):
        min_id_table = iterated.reduce(min_id=pw.reducers.min(iterated.id))
        return iterated.filter(iterated.id != min_id_table.ix_ref().min_id)

    ret = pw.iterate(
        min_id_remove,
        iterated=pw.iterate_universe(
            T(
                """
                    | foo
                1   | 1
                2   | 2
                3   | 3
                4   | 4
                5   | 5
                """
            )
        ),
    )
    assert len(pw.debug.table_to_pandas(ret)) == 0


def test_iteration_column_order():  # ref :1522
    def iteration_step(iterated):
        return iterated.select(
            bar=iterated.bar, foo=iterated.foo - iterated.foo
        )

    ret = pw.iterate(
        iteration_step,
        iterated=T(
            """
            foo | bar
            1   | 2
            """
        ),
    )
    assert_table_equality_wo_index(
        ret,
        T(
            """
            bar | foo
            2   | 0
            """
        ),
    )


def test_iterate_with_limit():  # ref :1571
    def double(t):
        return t.select(a=t.a * 2)

    ret = pw.iterate(double, iteration_limit=3, t=T("a\n1"))
    assert pw.debug.table_to_pandas(ret)["a"].tolist() == [8]


def test_iterate_with_wrong_limit():  # ref :1552
    def double(t):
        return t.select(a=t.a * 2)

    for limit in (0, -1):
        with pytest.raises(ValueError):
            pw.iterate(double, iteration_limit=limit, t=T("a\n1"))


# -- sort / prev-next (test_common.py:2579-2634) -----------------------------


def test_ix_sort_1():  # ref :2579
    data = T(
        """
        a | t
        0 | 1
        0 | 2
        0 | 3
        1 | 1
        1 | 2
        """
    )
    data_prev_next = data.sort(key=pw.this.t, instance=pw.this.a)
    data_prev = data.ix(data_prev_next.prev, optional=True)
    data_next = data.ix(data_prev_next.next, optional=True)
    result = data.select(
        pw.this.a, pw.this.t, prev_t=data_prev.t, next_t=data_next.t
    )
    df = pw.debug.table_to_pandas(result)

    def norm(v):
        return None if v is None or v != v else int(v)

    got = sorted(
        (int(a), int(t), norm(p), norm(n))
        for a, t, p, n in df[["a", "t", "prev_t", "next_t"]].values.tolist()
    )
    assert got == sorted([
        (0, 1, None, 2), (0, 2, 1, 3), (0, 3, 2, None),
        (1, 1, None, 2), (1, 2, 1, None),
    ])


# -- groupby instance (test_common.py:3981) ----------------------------------


def test_groupby_instance():  # ref :3981
    t = T(
        """
        instance | k | v
        0        | a | 1
        0        | a | 2
        0        | b | 3
        1        | a | 4
        1        | b | 5
        """
    )
    res = t.groupby(pw.this.k, instance=pw.this.instance).reduce(
        pw.this.k,
        s=pw.reducers.sum(pw.this.v),
    )
    df = pw.debug.table_to_pandas(res)
    got = sorted(map(tuple, df[["k", "s"]].values.tolist()))
    assert got == sorted([("a", 3), ("b", 3), ("a", 4), ("b", 5)])


# -- concat_unsafe collision / update_cells edges (test_common.py:956, 3507) --


def test_concat_unsafe_collision():  # ref :956
    t1 = T(
        """
          | v
        1 | a
        """
    )
    t2 = T(
        """
          | v
        1 | b
        """
    )
    pw.universes.promise_are_pairwise_disjoint(t1, t2)  # untrue promise
    res = pw.Table.concat(t1, t2)
    with pytest.raises(Exception):
        pw.debug.table_to_pandas(res)  # runtime key collision


def test_update_cells_0_rows():  # ref :3507
    old = T(
        """
          | a | b
        1 | 1 | x
        """
    )
    empty = old.filter(pw.this.a > 100).select(b=pw.this.b)
    res = old.update_cells(empty)
    assert_table_equality(
        res,
        T(
            """
              | a | b
            1 | 1 | x
            """
        ),
    )


def test_update_rows_0_rows():  # ref :3707
    old = T(
        """
          | a
        1 | 1
        """
    )
    empty = old.filter(pw.this.a > 100)
    res = old.update_rows(empty)
    assert_table_equality_wo_index(res, T("a\n1"))


# -- select with ix args (test_common.py:817, :3873) --------------------------


def test_select_column_ix_args():  # ref :817
    t_animals = T(
        """
          | epithet    | genus
        1 | upupa      | epops
        2 | acherontia | atropos
        3 | bubo       | scandiacus
        """
    )
    t_birds = T(
        """
          | ptr
        1 | 2
        2 | 3
        """
    )
    ret = t_birds.select(
        latin=t_animals.ix(t_animals.pointer_from(t_birds.ptr)).genus
    )
    assert sorted(pw.debug.table_to_pandas(ret)["latin"].tolist()) == [
        "atropos", "scandiacus",
    ]


# -- r4 review regressions ---------------------------------------------------


def test_sorted_optional_ix_sharded(monkeypatch):
    # None pointers must route through the sharded Exchange (the uint64
    # cast used to crash at -t 4 before the Join ever saw the row)
    monkeypatch.setenv("PATHWAY_THREADS", "4")
    data = T(
        """
        a | t
        0 | 1
        0 | 2
        1 | 5
        """
    )
    pn = data.sort(key=pw.this.t, instance=pw.this.a)
    prev = data.ix(pn.prev, optional=True)
    out = data.select(pw.this.t, p=prev.t)
    df = pw.debug.table_to_pandas(out)
    vals = sorted(
        (int(t), None if p is None or p != p else int(p))
        for t, p in df[["t", "p"]].values.tolist()
    )
    assert vals == [(1, None), (2, 1), (5, None)]


def test_window_self_join_via_copy():
    # the reference refuses joining a table with itself (interval joins:
    # test_errors_on_equal_tables); a COPY joins fine with direct-table
    # conditions and the ambiguity guard must not fire
    t = T(
        """
        k | t
        0 | 1
        0 | 2
        1 | 1
        """
    )
    t2 = t.copy()
    res = t.window_join(
        t2, t.t, t2.t, pw.temporal.tumbling(2), t.k == t2.k
    ).select(a=pw.left.t, b=pw.right.t, k=pw.left.k)
    df = pw.debug.table_to_pandas(res)
    got = sorted(map(tuple, df[["k", "a", "b"]].values.tolist()))
    assert (0, 1, 1) in got and (1, 1, 1) in got


def test_window_true_self_join_with_direct_refs_refused():
    t = T(
        """
        k | t
        0 | 1
        """
    )
    with pytest.raises(ValueError, match="pw.left/pw.right"):
        t.window_join(
            t, t.t, t.t, pw.temporal.tumbling(2), t.k == t.k
        ).select(a=pw.left.t)


def test_flatten_scalar_json_skipped():
    t = pw.debug.table_from_rows(
        pw.schema_from_types(data=pw.Json),
        [(pw.Json([1, 2]),), (pw.Json(42),)],
    )
    res = t.flatten(pw.this.data)
    vals = sorted(
        v.value if isinstance(v, pw.Json) else v
        for v in pw.debug.table_to_pandas(res)["data"].tolist()
    )
    assert vals == [1, 2]  # the scalar row skipped with a logged error
