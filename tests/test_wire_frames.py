"""Wire-codec coverage (ISSUE 5 satellite): property-style round-trips
across every engine dtype, zero-copy decode guarantees, and the
torn-frame contract — a reader facing corrupt bytes flips ``_broken``
with a named origin instead of deserializing garbage."""

from __future__ import annotations

import socket
import struct
import threading
import time

import numpy as np
import pytest

from pathway_tpu.engine.delta import Delta
from pathway_tpu.parallel import frames
from pathway_tpu.parallel.cluster import ClusterComm, _LEN


def _assemble(chunks) -> bytearray:
    return bytearray(b"".join(bytes(c) for c in chunks))


def _roundtrip(per_dst, channel=7, tick=42, src=1, ctx=None):
    chunks, nbytes = frames.encode_frame(channel, tick, src, per_dst, ctx)
    body = _assemble(chunks)
    assert len(body) == nbytes
    kind, ch, tk, sr, out, cx = frames.decode_frame(body)
    assert (kind, ch, tk, sr, cx) == ("x", channel, tick, src, ctx)
    return out


def _deltas_equal(a: Delta, b: Delta) -> None:
    assert np.array_equal(a.keys, b.keys)
    assert np.array_equal(a.diffs, b.diffs)
    assert list(a.data) == list(b.data)
    for c in a.data:
        assert a.data[c].dtype == b.data[c].dtype or (
            a.data[c].dtype == object and b.data[c].dtype == object
        ), c
        assert all(
            x == y or (x is None and y is None)
            for x, y in zip(a.data[c].tolist(), b.data[c].tolist())
        ), c


def _rng_delta(rng: np.random.Generator, n: int, cols: dict) -> Delta:
    data = {}
    for name, kind in cols.items():
        if kind == "int":
            data[name] = rng.integers(-(1 << 40), 1 << 40, n)
        elif kind == "float":
            data[name] = rng.standard_normal(n)
        elif kind == "bool":
            data[name] = rng.integers(0, 2, n).astype(bool)
        elif kind == "uint64":
            data[name] = rng.integers(0, 1 << 63, n).astype(np.uint64)
        elif kind == "str":
            data[name] = np.array(
                [f"s{int(v)}" for v in rng.integers(0, 50, n)], dtype=object
            )
        elif kind == "obj":
            vals = [None, "x", 3.5, (1, "t"), b"bytes"]
            col = np.empty(n, dtype=object)
            col[:] = [vals[int(v)] for v in rng.integers(0, len(vals), n)]
            data[name] = col
    diffs = rng.choice(np.array([-2, -1, 1, 1, 1, 3]), n).astype(np.int64)
    return Delta(
        keys=rng.integers(0, 1 << 63, n).astype(np.uint64),
        data=data,
        diffs=diffs,
    )


@pytest.mark.parametrize("seed", range(6))
def test_roundtrip_property_all_dtypes(seed):
    """Randomized column mixes over every engine dtype, including
    retractions (diff=-1) and empty frames, survive the wire intact."""
    rng = np.random.default_rng(seed)
    all_kinds = ["int", "float", "bool", "uint64", "str", "obj"]
    n_cols = int(rng.integers(1, len(all_kinds) + 1))
    cols = {
        f"c{i}": all_kinds[int(rng.integers(0, len(all_kinds)))]
        for i in range(n_cols)
    }
    n = int(rng.integers(0, 500))
    d = _rng_delta(rng, n, cols)
    out = _roundtrip({3: d}, ctx=("run-x", f"flow-{seed}"))
    _deltas_equal(d, out[3])


def test_roundtrip_datetime_columns():
    """datetime64/timedelta64 refuse the buffer protocol on encode —
    they ship via an int64 view and decode back under their real dtype."""
    d = Delta(
        keys=np.arange(3, dtype=np.uint64),
        data={
            "t": np.array(
                ["2026-01-01", "2026-06-02", "2026-08-03"],
                dtype="datetime64[ns]",
            ),
            "dt": np.array([1, 2, 3], dtype="timedelta64[ms]"),
        },
        diffs=np.ones(3, dtype=np.int64),
    )
    out = _roundtrip({0: d})[0]
    for c in ("t", "dt"):
        assert out.data[c].dtype == d.data[c].dtype
        assert np.array_equal(out.data[c], d.data[c])


def test_roundtrip_empty_frame_and_none_buckets():
    d = Delta.empty(["a", "b"])
    out = _roundtrip({0: d, 1: None})
    assert len(out[0]) == 0 and out[0].columns == ["a", "b"]
    assert out[1] is None


def test_roundtrip_mesh_host_cols_payload():
    """The (src, {name: col}) host-boundary payload of the mesh comm
    reuses the columnar codec (PT_COLS), not blanket pickling."""
    cols = {
        "s": np.array(["a", "b", "c"], dtype=object),
        "v": np.arange(3, dtype=np.int64),
    }
    out = _roundtrip({2: (5, cols)})
    src, got = out[2]
    assert src == 5
    assert got["s"].tolist() == ["a", "b", "c"]
    assert np.array_equal(got["v"], cols["v"])
    assert frames.decodable_payload((5, cols))


def test_dense_columns_decode_zero_copy_and_aligned():
    n = 1000
    d = Delta(
        keys=np.arange(n, dtype=np.uint64),
        data={"x": np.arange(n, dtype=np.int64),
              "f": np.linspace(0, 1, n)},
        diffs=np.ones(n, dtype=np.int64),
    )
    chunks, _ = frames.encode_frame(0, 1, 0, {0: d}, None)
    body = _assemble(chunks)
    out = frames.decode_frame(body)[4][0]
    for arr in (out.keys, out.diffs, out.data["x"], out.data["f"]):
        # aliases the recv buffer (no copy) at an 8-aligned offset
        assert arr.base is not None
        assert arr.__array_interface__["data"][0] % 8 == 0
    # writing through the view hits the shared buffer — ordinary arrays
    out.data["x"][0] = 7
    assert out.data["x"][0] == 7


def test_truncated_frame_raises_corrupt_frame():
    d = _rng_delta(np.random.default_rng(0), 64, {"a": "int", "s": "str"})
    chunks, nbytes = frames.encode_frame(1, 2, 0, {0: d}, None)
    body = _assemble(chunks)
    for cut in (0, 1, 7, len(body) // 3, len(body) - 1):
        with pytest.raises(frames.CorruptFrame):
            frames.decode_frame(body[:cut])
    # trailing garbage is also structural damage
    with pytest.raises(frames.CorruptFrame):
        frames.decode_frame(body + b"\x00" * 8)


def test_header_corruption_raises_corrupt_frame():
    d = _rng_delta(np.random.default_rng(1), 16, {"a": "float"})
    chunks, _ = frames.encode_frame(1, 2, 0, {0: d}, None)
    body = _assemble(chunks)
    # kind and version bytes are structural: any flip is detected
    for i in (0, 1):
        bad = bytearray(body)
        bad[i] ^= 0xA5
        with pytest.raises(frames.CorruptFrame):
            frames.decode_frame(bad)
    # the chaos 'corrupt' action mangles the leading header bytes — the
    # result must always be refused, whatever the frame held
    from pathway_tpu.parallel.cluster import _corrupt_chunks

    mangled = _corrupt_chunks([b"\x00" * 8] + chunks)
    with pytest.raises(frames.CorruptFrame):
        frames.decode_frame(_assemble(mangled[1:]))


# -- cluster integration ---------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _mesh(n: int) -> dict[int, ClusterComm]:
    port = _free_port()
    comms: dict[int, ClusterComm] = {}

    def make(pid: int) -> None:
        comms[pid] = ClusterComm(
            process_id=pid, n_processes=n, threads_per_process=1,
            first_port=port,
        )

    makers = [threading.Thread(target=make, args=(p,)) for p in range(n)]
    for m in makers:
        m.start()
    for m in makers:
        m.join(30)
    assert set(comms) == set(range(n))
    return comms


def test_cluster_exchange_delta_roundtrip_over_sockets():
    comms = _mesh(2)
    try:
        rng = np.random.default_rng(3)
        d0 = _rng_delta(rng, 200, {"a": "int", "s": "str", "f": "float"})
        d1 = _rng_delta(rng, 100, {"a": "int", "s": "str", "f": "float"})
        results: dict[int, list] = {}

        def worker(pid: int, d: Delta) -> None:
            buckets = [None, None]
            buckets[1 - pid] = d
            results[pid] = comms[pid].exchange(9, 0, pid, buckets)

        ts = [
            threading.Thread(target=worker, args=(p, d))
            for p, d in ((0, d0), (1, d1))
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        _deltas_equal(d1, results[0][0])
        _deltas_equal(d0, results[1][0])
        stats = comms[0].comm_stats()
        assert stats["bytes_total"] > 0
        assert stats["cluster_frames_sent"] >= 1
        assert "frames_coalesced_total" in stats
        assert "send_queue_depth" in stats
        assert stats["encode_seconds_total"] > 0
    finally:
        for c in comms.values():
            c.close()


def test_torn_wire_bytes_flip_broken_with_named_origin():
    """Raw garbage injected into the socket (a torn frame on the wire)
    must break the receiving process's collectives fast, naming the
    origin peer — never deserialize into operator state."""
    comms = _mesh(2)
    outcome: dict = {}

    def blocked() -> None:
        t0 = time.monotonic()
        try:
            comms[0].allgather("never", 0, "x")
            outcome["result"] = "completed"
        except RuntimeError as e:
            outcome["error"] = str(e)
            outcome["elapsed"] = time.monotonic() - t0

    th = threading.Thread(target=blocked, daemon=True)
    th.start()
    time.sleep(0.1)
    # a columnar-tagged frame whose body is garbage, sent from process 1
    garbage = bytes([frames.KIND_COLUMNAR]) + b"\xde\xad" * 16
    comms[1]._socks[0].sendall(_LEN.pack(len(garbage)) + garbage)
    th.join(5)
    assert not th.is_alive(), "collective still blocked after torn frame"
    assert "error" in outcome, outcome
    assert outcome["elapsed"] < 2.0
    assert comms[0]._broken is not None
    assert "corrupt frame from process 1" in comms[0]._broken
    for c in comms.values():
        c.close()


def test_chaos_corrupt_action_fires_on_pipelined_path():
    """The comm.send 'corrupt' fault mangles the frame on the wire; the
    peer's reader refuses it and propagates a named failure."""
    from pathway_tpu import chaos

    chaos.arm(chaos.FaultPlan.from_dict({
        "faults": [{"site": "comm.send", "process": 0, "peer": 1,
                    "nth": 1, "action": "corrupt"}],
    }), run=0)
    try:
        comms = _mesh(2)
        failed: dict = {}

        def gather1() -> None:
            try:
                comms[1].allgather("t", 1, 1)
                failed[1] = None
            except RuntimeError as e:
                failed[1] = str(e)

        th = threading.Thread(target=gather1, daemon=True)
        th.start()
        # p0 contributes: its first frame to p1 gets corrupted on the wire
        def gather0() -> None:
            try:
                comms[0].allgather("t", 0, 0)
            except RuntimeError:
                pass

        th0 = threading.Thread(target=gather0, daemon=True)
        th0.start()
        th.join(5)
        assert not th.is_alive()
        assert failed[1] is not None
        assert "corrupt frame from process 0" in failed[1]
        comms[0].abort()
        th0.join(5)
        for c in comms.values():
            c.close()
    finally:
        chaos.disarm()


def test_queue_frames_knob_and_backpressure(monkeypatch):
    monkeypatch.setenv("PATHWAY_COMM_QUEUE_FRAMES", "3")
    comms = _mesh(2)
    try:
        assert comms[0]._queue_frames == 3
        assert comms[1]._queue_frames == 3
    finally:
        for c in comms.values():
            c.close()


def test_localcomm_passes_frames_by_reference():
    """The in-process allocator never serializes: received payloads are
    the identical objects peers deposited."""
    from pathway_tpu.parallel.comm import LocalComm

    comm = LocalComm(2)
    d0 = Delta(keys=np.arange(3, dtype=np.uint64),
               data={"a": np.arange(3)}, diffs=np.ones(3, dtype=np.int64))
    d1 = Delta(keys=np.arange(2, dtype=np.uint64),
               data={"a": np.arange(2)}, diffs=np.ones(2, dtype=np.int64))
    results: dict[int, list] = {}

    def worker(wid: int, d: Delta) -> None:
        buckets = [None, None]
        buckets[1 - wid] = d
        results[wid] = comm.exchange(0, 0, wid, buckets)

    ts = [
        threading.Thread(target=worker, args=(w, d))
        for w, d in ((0, d0), (1, d1))
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(5)
    assert results[0][0] is d1  # identity, not equality
    assert results[1][0] is d0
