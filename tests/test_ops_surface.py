"""CLI, config, monitoring/metrics endpoint, yaml loader, universes
(reference: cli.py, internals/config.py, monitoring.py, yaml_loader.py)."""

from __future__ import annotations

import os
import subprocess
import sys
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def test_config_from_env(monkeypatch):
    monkeypatch.setenv("PATHWAY_THREADS", "4")
    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "1")
    monkeypatch.setenv("PATHWAY_IGNORE_ASSERTS", "true")
    cfg = pw.get_pathway_config()
    assert cfg.threads == 4 and cfg.processes == 2 and cfg.process_id == 1
    assert cfg.total_workers == 8
    assert cfg.ignore_asserts is True

    monkeypatch.setenv("PATHWAY_THREADS", "5")
    with pytest.raises(RuntimeError, match="too many workers"):
        pw.get_pathway_config()


def test_cli_spawn_sets_environment(tmp_path):
    prog = tmp_path / "prog.py"
    prog.write_text(
        "import os\n"
        "print(os.environ['PATHWAY_THREADS'], os.environ['PATHWAY_PROCESS_ID'])\n"
    )
    out = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "spawn", "-t", "2",
         sys.executable, str(prog)],
        capture_output=True, text=True, cwd="/root/repo",
        env={**os.environ, "PYTHONPATH": "/root/repo"},
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "2 0"


def test_cli_replay_sets_replay_env(tmp_path):
    prog = tmp_path / "prog.py"
    prog.write_text(
        "import os\n"
        "print(os.environ['PATHWAY_REPLAY_STORAGE'],"
        " os.environ['PATHWAY_SNAPSHOT_ACCESS'],"
        " os.environ['PATHWAY_PERSISTENCE_MODE'])\n"
    )
    out = subprocess.run(
        [sys.executable, "-m", "pathway_tpu.cli", "replay",
         "--record-path", "rec", "--mode", "speedrun",
         sys.executable, str(prog)],
        capture_output=True, text=True, cwd="/root/repo",
        env={**os.environ, "PYTHONPATH": "/root/repo"},
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "rec replay speedrun"


def test_metrics_endpoint(monkeypatch):
    """pw.run(with_http_server=True) serves OpenMetrics on 20000+pid
    (port overridable via PATHWAY_MONITORING_HTTP_PORT)."""
    import threading
    import time

    monkeypatch.setenv("PATHWAY_MONITORING_HTTP_PORT", "28471")

    import threading as _threading

    scrape_done = _threading.Event()

    class S(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(3):
                self.next(x=i)
                self.commit()
            # keep the engine (and its metrics server) alive until scraped
            scrape_done.wait(timeout=10)

    t = pw.io.python.read(S(), schema=pw.schema_from_types(x=int))
    total = t.reduce(s=pw.reducers.sum(pw.this.x))
    seen = threading.Event()
    scraped: list[str] = []

    def on_change(key, row, time, is_addition):
        if is_addition and int(row["s"]) == 3:
            seen.set()

    pw.io.subscribe(total, on_change=on_change)

    def scrape_and_stop():
        seen.wait(timeout=10)
        time.sleep(0.2)
        try:
            with urllib.request.urlopen(
                "http://127.0.0.1:28471/metrics", timeout=5
            ) as resp:
                scraped.append(resp.read().decode())
        finally:
            scrape_done.set()
            pw.request_stop()

    th = threading.Thread(target=scrape_and_stop, daemon=True)
    th.start()
    pw.run(with_http_server=True)
    th.join()
    assert scraped, "metrics endpoint unreachable"
    body = scraped[0]
    assert "pathway_engine_ticks" in body
    assert "pathway_input_rows 3" in body
    # sink deliveries count as output rows (sum updates reached subscribe)
    import re

    m = re.search(r"pathway_output_rows (\d+)", body)
    assert m and int(m.group(1)) > 0


def test_yaml_loader():
    doc = """
splitter: !pathway_tpu.xpacks.llm.splitters.TokenCountSplitter
  min_tokens: 2
  max_tokens: 7
limits:
  low: 1
  high: $splitter
"""
    objs = pw.load_yaml(doc)
    from pathway_tpu.xpacks.llm.splitters import TokenCountSplitter

    assert isinstance(objs["splitter"], TokenCountSplitter)
    assert objs["splitter"].max_tokens == 7
    assert objs["limits"]["high"] is objs["splitter"]


def test_universes_promises():
    a = pw.debug.table_from_rows(pw.schema_from_types(x=int), [(1,), (2,)])
    b = a.filter(pw.this.x > 0)
    # b ⊆ a already; promising equality allows mixing columns both ways
    pw.universes.promise_are_equal(a, b)
    res = a.select(y=pw.ColumnReference(b, "x"))
    assert sorted(pw.debug.table_to_pandas(res)["y"]) == [1, 2]


def test_yaml_pw_alias():
    objs = pw.load_yaml("s: !pw.xpacks.llm.splitters.NullSplitter\n")
    from pathway_tpu.xpacks.llm.splitters import NullSplitter

    assert isinstance(objs["s"], NullSplitter)


def test_yaml_schema_type_names_coerce(tmp_path):
    """String type names in YAML/JSON-loaded schemas resolve to real
    dtypes (reference schema.py:783: both int and "int" accepted), so
    csv reads under the yaml loader coerce numerics."""
    G.clear()
    csv = tmp_path / "in.csv"
    csv.write_text("a,b\n1,2\n3,4\n")
    cfg = pw.load_yaml(
        f"""
source: !pw.io.csv.read
  path: {csv}
  schema: !pw.schema_from_types
    a: int
    b: int
  mode: static
"""
    )
    acc = {}
    pw.io.subscribe(
        cfg["source"].groupby().reduce(s=pw.reducers.sum(pw.this.a)),
        on_change=lambda key, row, time, is_addition: acc.update(row),
    )
    pw.run()
    assert acc == {"s": 4}


def test_schema_from_dict_string_types():
    sch = pw.schema_from_dict({"a": "int", "b": {"dtype": "str"}})
    hints = sch.typehints()
    assert hints["a"] is int and hints["b"] is str
    # unknown strings degrade to ANY (unresolvable forward refs must not
    # crash schema definition)
    pw.schema_from_dict({"c": "np.ndarray"})
