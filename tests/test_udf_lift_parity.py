"""Lift-vs-fallback semantic parity matrix (PR 10 tentpole gate).

Every newly liftable UDF form — method chains, dict/tuple access,
conditionals, the builtin subset, probe-traced plans — must produce a
multiset-equal result to the FORCED per-row path (``PATHWAY_UDF_LIFT=off``
+ ``PATHWAY_UDF_TRACE=off``), including ``EngineError`` row-error
semantics and None propagation; impure UDFs must provably stay per-row;
the dtype-signature guard must re-trace on mixed-dtype streams; and the
refusal caches must evict their oldest half instead of cliff-clearing.
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
import pathway_tpu.debug as dbg
from pathway_tpu.internals import expression_compiler as ec
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def _column(table, name="c"):
    df = dbg.table_to_pandas(table)
    G.clear()
    return sorted(df[name].tolist(), key=repr)


def _both_ways(make_table, build, monkeypatch):
    """(fast-path result, forced per-row result) of the same pipeline."""
    fast = _column(build(make_table()))
    monkeypatch.setenv("PATHWAY_UDF_LIFT", "off")
    monkeypatch.setenv("PATHWAY_UDF_TRACE", "off")
    try:
        slow = _column(build(make_table()))
    finally:
        monkeypatch.delenv("PATHWAY_UDF_LIFT")
        monkeypatch.delenv("PATHWAY_UDF_TRACE")
    return fast, slow


def _assert_parity(make_table, fn, ret, monkeypatch, n_args=1):
    def build(t):
        args = [t.a] if n_args == 1 else [t.a, t.b]
        return t.select(c=pw.apply_with_type(fn, ret, *args))

    fast, slow = _both_ways(make_table, build, monkeypatch)
    assert fast == slow, (fast, slow)
    return fast


# ---- method-call chains --------------------------------------------------


def test_method_chain_lifts_and_matches(monkeypatch):
    before = ec.UDF_STATS["lifted_total"]
    out = _assert_parity(
        lambda: T("a\nFoo\nBAR\nbaz"),
        lambda s: s.lower() + "!", str, monkeypatch,
    )
    assert out == ["bar!", "baz!", "foo!"]
    assert ec.UDF_STATS["lifted_total"] > before  # fast path really lifted


def test_longer_method_chain(monkeypatch):
    out = _assert_parity(
        lambda: T("a\n xax \n byb "),
        lambda s: s.strip().replace("a", "o").title(), str, monkeypatch,
    )
    assert out == ["Byb", "Xox"]


def test_predicate_methods(monkeypatch):
    _assert_parity(
        lambda: T("a\nfoo\nbar"),
        lambda s: s.startswith("f"), bool, monkeypatch,
    )
    _assert_parity(
        lambda: T("a\nfoo\nbar"),
        lambda s: s.endswith("o") or s.find("r") >= 0, bool, monkeypatch,
    )


# ---- dict/tuple-style access ---------------------------------------------


def test_tuple_access(monkeypatch):
    def make():
        return dbg.table_from_rows(
            pw.schema_from_types(a=tuple), [((3, 4),), ((5, 6),)]
        )

    out = _assert_parity(make, lambda t: t[1] * 10, int, monkeypatch)
    assert out == [40, 60]


def test_json_dict_access(monkeypatch):
    from pathway_tpu.internals.json import Json

    def make():
        return dbg.table_from_rows(
            pw.schema_from_types(a=pw.Json if hasattr(pw, "Json") else dict),
            [(Json({"x": 2}),), (Json({"x": 5}),)],
        )

    def build(t):
        return t.select(c=pw.apply_with_type(lambda r: r["x"], pw.Json if hasattr(pw, "Json") else int, t.a))

    fast, slow = _both_ways(make, build, monkeypatch)
    assert fast == slow


# ---- the two long-refused corners: str.split + tz-aware timestamp -------


def test_split_lifts_with_python_list_semantics(monkeypatch):
    before = ec.UDF_STATS["lifted_total"]
    out = _assert_parity(
        lambda: T("a\nx,y,z\nq\na,,b"),
        lambda s: s.split(","), list, monkeypatch,
    )
    # exact Python semantics: a LIST (the engine used to return a tuple,
    # which kept this method off the lift table)
    assert sorted(out, key=repr) == sorted(
        [["x", "y", "z"], ["q"], ["a", "", "b"]], key=repr
    )
    assert all(isinstance(v, list) for v in out)
    assert ec.UDF_STATS["lifted_total"] > before


def test_split_whitespace_and_maxsplit(monkeypatch):
    def make():
        return dbg.table_from_rows(
            pw.schema_from_types(a=str),
            [("  foo  bar baz ",), ("one",)],
        )

    out = _assert_parity(make, lambda s: s.split(), list, monkeypatch)
    assert sorted(out, key=repr) == sorted(
        [["foo", "bar", "baz"], ["one"]], key=repr
    )
    out = _assert_parity(
        lambda: T("a\nx-y-z-w"),
        lambda s: s.split("-", 2), list, monkeypatch,
    )
    assert out == [["x", "y", "z-w"]]


def test_split_chained_with_len(monkeypatch):
    out = _assert_parity(
        lambda: T("a\nx,y,z\nq"),
        lambda s: len(s.split(",")), int, monkeypatch,
    )
    assert out == [1, 3]


def _dt_table():
    import datetime

    return dbg.table_from_rows(
        pw.schema_from_types(a=datetime.datetime),
        [
            (datetime.datetime(2023, 1, 1, 12, 30),),
            (datetime.datetime(1970, 1, 2),),
        ],
    )


def _aware_dt_table():
    import datetime
    from zoneinfo import ZoneInfo

    return dbg.table_from_rows(
        pw.schema_from_types(a=datetime.datetime),
        [
            (datetime.datetime(
                2023, 7, 1, 9, 0, tzinfo=ZoneInfo("Europe/Warsaw")
            ),),
            (datetime.datetime(
                2023, 1, 1, 9, 0, tzinfo=datetime.timezone.utc
            ),),
        ],
    )


def test_timestamp_lifts_tz_aware(monkeypatch):
    before = ec.UDF_STATS["lifted_total"]
    out = _assert_parity(
        _aware_dt_table, lambda d: d.timestamp(), float, monkeypatch,
    )
    import datetime
    from zoneinfo import ZoneInfo

    assert sorted(out) == sorted([
        datetime.datetime(
            2023, 7, 1, 9, 0, tzinfo=ZoneInfo("Europe/Warsaw")
        ).timestamp(),
        datetime.datetime(
            2023, 1, 1, 9, 0, tzinfo=datetime.timezone.utc
        ).timestamp(),
    ])
    assert ec.UDF_STATS["lifted_total"] > before


def test_timestamp_naive_matches_python_local_rule(monkeypatch):
    # Python interprets a NAIVE datetime in the local timezone; the lifted
    # kernel must reproduce exactly that (py.timestamp), not the
    # epoch-anchored dt.timestamp(unit=...) namespace rule
    out = _assert_parity(
        _dt_table, lambda d: d.timestamp(), float, monkeypatch,
    )
    import datetime

    assert sorted(out) == sorted([
        datetime.datetime(2023, 1, 1, 12, 30).timestamp(),
        datetime.datetime(1970, 1, 2).timestamp(),
    ])


def test_timestamp_arithmetic_chain(monkeypatch):
    out = _assert_parity(
        _dt_table, lambda d: d.timestamp() / 3600.0, float, monkeypatch,
    )
    assert len(out) == 2


# ---- conditionals ---------------------------------------------------------


def test_ternary(monkeypatch):
    out = _assert_parity(
        lambda: T("a\n-3\n0\n7"),
        lambda a: a if a > 0 else -a, int, monkeypatch,
    )
    assert out == [0, 3, 7]


def test_if_return_statements(monkeypatch):
    def grade(x: int) -> str:
        if x >= 90:
            return "A"
        if x >= 80:
            return "B"
        return "C"

    out = _assert_parity(
        lambda: T("a\n95\n85\n10"), grade, str, monkeypatch
    )
    assert out == ["A", "B", "C"]


def test_bool_ops_and_not(monkeypatch):
    _assert_parity(
        lambda: T("a | b\n1 | 0\n5 | 3\n0 | 0"),
        lambda a, b: a > 0 and b > 0, bool, monkeypatch, n_args=2,
    )
    _assert_parity(
        lambda: T("a | b\n1 | 0\n0 | 3\n0 | 0"),
        lambda a, b: a > 0 or b > 0, bool, monkeypatch, n_args=2,
    )
    _assert_parity(
        lambda: T("a\n0\n2"),
        lambda a: not a > 1, bool, monkeypatch,
    )


def test_conditional_with_division_error_semantics(monkeypatch):
    # the lifted if_else evaluates a//b eagerly: b==0 rows yield per-row
    # Error VALUES in the untaken branch, which where-selection discards
    # — exactly the per-row short-circuit result
    out = _assert_parity(
        lambda: T("a | b\n8 | 2\n9 | 0"),
        lambda a, b: a // b if b != 0 else -1, int, monkeypatch, n_args=2,
    )
    assert out == [-1, 4]


# ---- builtin subset -------------------------------------------------------


def test_builtins(monkeypatch):
    _assert_parity(
        lambda: T("a\nfoo\nquux"), lambda s: len(s) * 2, int, monkeypatch
    )
    _assert_parity(
        lambda: T("a\n-3\n4"), lambda a: abs(a) + 1, int, monkeypatch
    )
    _assert_parity(
        lambda: T("a\n3\n4"), lambda a: str(a) + "x", str, monkeypatch
    )
    _assert_parity(
        lambda: T("a\n3\n4"), lambda a: float(a) / 2, float, monkeypatch
    )
    _assert_parity(
        lambda: T("a | b\n3 | 7\n9 | 2"),
        lambda a, b: min(a, b) * 100 + max(a, b), int, monkeypatch,
        n_args=2,
    )


def test_round_builtin_matches_python(monkeypatch):
    # 1-arg round returns int (banker's rounding); 2-arg keeps float
    out = _assert_parity(
        lambda: T("a\n0.5\n1.5\n2.345"),
        lambda a: round(a), int, monkeypatch,
    )
    assert out == [0, 2, 2]
    _assert_parity(
        lambda: T("a\n2.345\n1.114"),
        lambda a: round(a, 1), float, monkeypatch,
    )


def test_fstring(monkeypatch):
    out = _assert_parity(
        lambda: T("a\n1\n2"), lambda a: f"v={a}!", str, monkeypatch
    )
    assert out == ["v=1!", "v=2!"]


# ---- error semantics ------------------------------------------------------


def test_error_rows_match_per_row_path(monkeypatch):
    def build(t):
        return t.select(c=pw.fill_error(
            pw.apply_with_type(
                lambda a, b: a // b + len(str(a)), int, t.a, t.b
            ),
            -1,
        ))

    fast, slow = _both_ways(
        lambda: T("a | b\n8 | 2\n9 | 0\n10 | 5"), build, monkeypatch
    )
    assert fast == slow == [-1, 2 + 2, 4 + 1]


# ---- None propagation -----------------------------------------------------


def test_none_propagation_optional_column(monkeypatch):
    # a None-guarded conditional lifts to if_else(is_none(x), ...) whose
    # per-row truthiness selection reproduces the per-row result exactly;
    # unguarded None-touching batches are kept per-row by the trace
    # signature guard
    def make():
        return dbg.table_from_rows(
            pw.schema_from_types(a=int | None), [(3,), (None,), (5,)]
        )

    out = _assert_parity(
        make, lambda x: 0 if x is None else x + 1, int, monkeypatch
    )
    assert out == [0, 4, 6]


# ---- probe-row tracing ----------------------------------------------------


def test_traced_plan_matches_per_row(monkeypatch):
    fn = eval("lambda a: abs(a) * 3 + 7")  # no source, LOAD_GLOBAL abs
    before = ec.UDF_STATS["traced_total"]
    out = _assert_parity(
        lambda: T("a\n1\n-2\n3"), fn, int, monkeypatch
    )
    assert out == [10, 13, 16]
    assert ec.UDF_STATS["traced_total"] > before


def test_traced_method_chain_matches_per_row(monkeypatch):
    fn = eval("lambda s: s.strip().upper()")
    out = _assert_parity(
        lambda: T("a\n x \n yo "), fn, str, monkeypatch
    )
    assert out == ["X", "YO"]


def test_dtype_signature_guard_retraces_on_mixed_stream(monkeypatch):
    # int batch then float batch through a source-less UDF: each dtype
    # signature gets its own traced plan (coalescing disabled so the two
    # commit windows stay separate batches)
    monkeypatch.setenv("PATHWAY_INGEST_COALESCE_WINDOWS", "0")
    fn = eval("lambda x: abs(x) * 3")

    def run_stream():
        G.clear()

        class Feed(pw.io.python.ConnectorSubject):
            def run(self):
                for v in (1, -2, 3):
                    self.next(x=v)
                self.commit()
                for v in (1.5, -2.5):
                    self.next(x=v)
                self.commit()

        t = pw.io.python.read(
            Feed(),
            schema=pw.schema_from_types(x=object),
            autocommit_duration_ms=None,
        )
        sel = t.select(c=pw.apply_with_type(fn, float, t.x))
        got = []
        pw.io.subscribe(
            sel,
            on_change=lambda key, row, time, is_addition: got.append(
                row["c"]
            ),
        )
        pw.run()
        G.clear()
        return sorted(got)

    before = ec.UDF_STATS["traced_total"]
    fast = run_stream()
    traced_delta = ec.UDF_STATS["traced_total"] - before
    monkeypatch.setenv("PATHWAY_UDF_TRACE", "off")
    monkeypatch.setenv("PATHWAY_UDF_LIFT", "off")
    slow = run_stream()
    assert fast == slow == sorted([3.0, 6.0, 9.0, 4.5, 7.5])
    assert traced_delta == 2  # one plan per dtype signature


def test_mixed_types_within_one_batch_stay_per_row(monkeypatch):
    fn = eval("lambda x: x * 2")
    # LOAD_GLOBAL-free, so defeat the static lift by schema: ANY column
    # with str+int in ONE batch — the signature guard must refuse a plan
    # and the per-row path must serve both types
    def make():
        return dbg.table_from_rows(
            pw.schema_from_types(a=object), [(3,), ("ab",)]
        )

    def build(t):
        return t.select(c=pw.apply_with_type(fn, object, t.a))

    fast, slow = _both_ways(make, build, monkeypatch)
    assert fast == slow == sorted([6, "abab"], key=repr)


# ---- review regressions ---------------------------------------------------


def test_wraps_decorated_udf_runs_the_wrapper():
    """functools.wraps unwinds getsource to the ORIGINAL body — the AST
    lifter must refuse, not silently compile the undecorated function."""
    import functools

    def base(x: int) -> int:
        return x + 1

    @functools.wraps(base)
    def doubled(*args, **kwargs):
        return base(*args, **kwargs) * 2

    t = T("a\n5\n7")
    out = _column(t.select(c=pw.apply_with_type(doubled, int, t.a)))
    assert out == [12, 16]  # (x+1)*2 — the wrapper's behavior, per row


def test_int_builtin_nan_matches_python(monkeypatch):
    # int(nan) must be a per-row Error (Python raises), never a silent
    # INT64_MIN from a dense astype
    def build(t):
        return t.select(c=pw.fill_error(
            pw.apply_with_type(lambda a: int(a), int, t.a), -7
        ))

    def make():
        return dbg.table_from_rows(
            pw.schema_from_types(a=float), [(2.5,), (float("nan"),)]
        )

    fast, slow = _both_ways(make, build, monkeypatch)
    assert fast == slow == [-7, 2]


def test_min_max_nan_matches_python(monkeypatch):
    # Python: min(nan, x) is nan, min(x, nan) is x (NaN compares False)
    import math

    def build(t):
        return t.select(c=pw.apply_with_type(
            lambda a: min(a, 1.0) + 0, float, t.a
        ))

    def make():
        return dbg.table_from_rows(
            pw.schema_from_types(a=float), [(0.5,), (float("nan"),), (2.0,)]
        )

    fast, slow = _both_ways(make, build, monkeypatch)
    assert [repr(v) for v in fast] == [repr(v) for v in slow]
    assert sum(1 for v in fast if isinstance(v, float) and math.isnan(v)) == 1


def test_get_on_non_dict_receiver_matches_per_row(monkeypatch):
    # tuple has no .get: per-row raises AttributeError into an Error row;
    # the lift/trace paths must NOT silently index the tuple
    def make():
        return dbg.table_from_rows(
            pw.schema_from_types(a=tuple), [((9, 8),)]
        )

    def build(t):
        return t.select(c=pw.fill_error(
            pw.apply_with_type(lambda r: r.get(0, -1), int, t.a), -99
        ))

    fast, slow = _both_ways(make, build, monkeypatch)
    assert fast == slow == [-99]


def test_get_on_dict_receiver_traces(monkeypatch):
    fn = eval("lambda r: r.get('x', -1)")  # source-less: tracer path

    def make():
        return dbg.table_from_rows(
            pw.schema_from_types(a=object), [({"x": 4},), ({"y": 9},)]
        )

    def build(t):
        return t.select(c=pw.apply_with_type(fn, int, t.a))

    fast, slow = _both_ways(make, build, monkeypatch)
    assert fast == slow == [-1, 4]


# ---- impure UDFs provably stay per-row ------------------------------------


def test_rng_udf_not_lifted(monkeypatch):
    import random

    def noisy(x):
        return x + random.random()

    t = T("a\n1\n2\n3")
    before = ec.UDF_STATS["perrow_rows_total"]
    out = t.select(c=pw.apply_with_type(noisy, float, t.a))
    vals = _column(out)
    assert ec.UDF_STATS["perrow_rows_total"] - before >= 3
    # three independent draws — a lifted/traced plan would have reused one
    fracs = {round(v % 1, 9) for v in vals}
    assert len(fracs) == 3


def test_closure_mutation_stays_per_row(monkeypatch):
    seen = []

    def note(x):
        seen.append(x)
        return x * 2

    t = T("a\n1\n2\n3")
    assert _column(t.select(c=pw.apply_with_type(note, int, t.a))) == [
        2, 4, 6,
    ]
    assert sorted(seen) == [1, 2, 3]  # once per ROW, not once per trace


# ---- refusal-cache eviction (satellite #1) --------------------------------


def test_evict_oldest_half_order():
    from pathway_tpu.internals.udf_lift import evict_oldest_half

    d = {i: None for i in range(100)}
    evict_oldest_half(d)
    assert list(d) == list(range(50, 100))


def test_lift_refused_eviction_keeps_codes_consistent(monkeypatch):
    saved = dict(ec._LIFT_REFUSED), set(ec._LIFT_REFUSED_CODES)
    try:
        ec._LIFT_REFUSED.clear()
        ec._LIFT_REFUSED_CODES.clear()
        fakes = [
            compile(f"lambda: {i}", "<fake>", "eval") for i in range(4096)
        ]
        for c in fakes:
            ec._LIFT_REFUSED[(c, (), ())] = None
            ec._LIFT_REFUSED_CODES.add(c)
        # a genuinely unliftable lambda pushes past the cap -> the OLDEST
        # half is evicted (no cliff) and CODES mirrors surviving keys
        cell = [7]
        t = T("a\n1")
        _column(t.select(c=pw.apply_with_type(
            lambda x: x + cell[0], int, t.a
        )))
        assert 1 <= len(ec._LIFT_REFUSED) <= 2049
        assert ec._LIFT_REFUSED_CODES == {k[0] for k in ec._LIFT_REFUSED}
        # the oldest fakes are gone, the newest survive
        assert (fakes[0], (), ()) not in ec._LIFT_REFUSED
        assert (fakes[-1], (), ()) in ec._LIFT_REFUSED
    finally:
        ec._LIFT_REFUSED.clear()
        ec._LIFT_REFUSED.update(saved[0])
        ec._LIFT_REFUSED_CODES.clear()
        ec._LIFT_REFUSED_CODES.update(saved[1])
