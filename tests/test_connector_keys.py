"""Row-key stability of the python connector (ISSUE 5 satellites).

A row's engine key must be a pure function of the row against its
DECLARED schema — never of which flush batch the row happened to ride
in. The advisor-high case: a float-declared column whose values are
python ints in one batch (column stays int64) and mixed int/float in
another (column promotes to float64) used to hash differently, so a
retraction could miss its row — ghost rows / negative multiplicities.
"""

from __future__ import annotations

import numpy as np

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.io.python import ConnectorSubject, PythonSubjectSource
from pathway_tpu.internals import dtype as dt


def _source(**dtypes):
    names = list(dtypes)
    return PythonSubjectSource(
        ConnectorSubject(), names, {}, None, autocommit_ms=None,
        dtypes={n: dt.wrap(t) for n, t in dtypes.items()},
    )


def test_key_independent_of_flush_batch_for_float_columns():
    src = _source(x=float)
    # batch A: the row {x: 1} flushed among ints -> int64 column
    d_int = src._make_delta([{"x": 1}, {"x": 2}])
    # batch B: the same row flushed next to a float -> float64 column
    d_mixed = src._make_delta([{"x": 1}, {"x": 2.5}])
    assert d_int.keys[0] == d_mixed.keys[0], (
        "row key depends on its flush batch"
    )
    # data itself is normalized to the declared dtype
    assert d_int.data["x"].dtype == np.float64
    assert d_mixed.data["x"].dtype == np.float64


def test_optional_float_object_column_normalized():
    from typing import Optional

    src = _source(x=Optional[float])
    d_a = src._make_delta([{"x": 1}, {"x": None}])
    d_b = src._make_delta([{"x": 1.0}, {"x": None}])
    assert d_a.keys[0] == d_b.keys[0]
    assert d_a.data["x"][0] == 1.0 and type(d_a.data["x"][0]) is not int


def test_batch_lane_matches_row_lane_keys():
    src = _source(x=float)
    d_rows = src._make_delta([{"x": 1}, {"x": 2}])
    from pathway_tpu.io.python import _Batch

    d_batch = src._make_batch_delta(_Batch({"x": [1, 2]}, None))
    assert np.array_equal(d_rows.keys, d_batch.keys)


def test_retraction_cancels_across_differently_typed_batches():
    """End-to-end regression: insert in an all-int batch, retract in a
    mixed batch — the multiset must come out empty (no ghost row, no
    negative multiplicity)."""
    G.clear()

    class Feed(ConnectorSubject):
        def run(self) -> None:
            self.next(x=1)
            self.next(x=2)
            self.commit()
            self._remove(x=1)
            self.next(x=2.5)  # forces float64 promotion of this batch
            self._remove(x=2)
            self.commit()

    t = pw.io.python.read(
        Feed(), schema=pw.schema_from_types(x=float),
        autocommit_duration_ms=None,
    )
    state: dict = {}

    def on_change(key, row, time, is_addition):
        state[key] = state.get(key, 0) + (1 if is_addition else -1)

    pw.io.subscribe(t, on_change=on_change)
    pw.run()
    G.clear()
    live = {k: v for k, v in state.items() if v != 0}
    assert all(v > 0 for v in state.values() if v), (
        f"negative multiplicity: {state}"
    )
    assert len(live) == 1, f"expected only x=2.5 to survive, got {live}"


def test_explicit_keys_do_not_register_derived_keys():
    """Entries carrying an explicit key must not register their unused
    derived key in the 128-bit conflation registry: a later legitimate
    derivation of the same content must still pass (advisor-low)."""
    from pathway_tpu.engine import keys as K

    src = _source(x=int)
    # explicit-keyed entry whose content would derive some 128-bit key
    d = src._make_delta([(1, {"x": 777_123}, 42)])
    assert d.keys[0] == 42
    # deriving the same content legitimately must neither collide nor
    # produce the explicit key
    derived = src._make_delta([{"x": 777_123}])
    assert derived.keys[0] != 42
    # mixed batch: explicit + derived — derived row keys registered and
    # stable vs an all-derived batch
    mixed = src._make_delta([(1, {"x": 5}, 99), {"x": 6}])
    pure = src._make_delta([{"x": 5}, {"x": 6}])
    assert mixed.keys[0] == 99
    assert mixed.keys[1] == pure.keys[1]
