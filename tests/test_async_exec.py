"""Frontier-driven asynchronous execution (PATHWAY_ASYNC_EXEC).

- mode selection: async is the sharded-streaming default, =0 restores
  the BSP tick loop, mesh exchange keeps BSP unless explicitly asked;
- parity: streaming sharded programs produce identical final multisets
  single-worker vs async vs the =0 escape hatch, fused AND unfused;
- exactly-once under async: the chaos smoke (SIGKILL mid-run + sup-
  ervised restart) and the sink smoke's kill scenario run with
  PATHWAY_ASYNC_EXEC=1 pinned explicitly;
- the TCP cluster transport (spawn -n 2) drains a streaming wordcount
  to exact counts through the async plane.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
from collections import Counter

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G

SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)


# -- mode selection ----------------------------------------------------------


def _executor_for(monkeypatch, n_workers=2, mesh=False):
    from pathway_tpu.engine.executor import Executor
    from pathway_tpu.parallel.comm import LocalComm, WorkerContext

    comm = LocalComm(n_workers)
    if mesh:
        comm.exchange_deltas = lambda *a, **k: []  # quacks like MeshComm
    ex = Executor.__new__(Executor)
    ex.ctx = WorkerContext(0, n_workers, comm)
    return ex


def test_async_is_default_for_sharded_streaming(monkeypatch):
    monkeypatch.delenv("PATHWAY_ASYNC_EXEC", raising=False)
    assert _executor_for(monkeypatch)._use_async()


def test_escape_hatch_restores_bsp(monkeypatch):
    monkeypatch.setenv("PATHWAY_ASYNC_EXEC", "0")
    assert not _executor_for(monkeypatch)._use_async()


def test_mesh_exchange_defaults_to_bsp_unless_asked(monkeypatch):
    monkeypatch.delenv("PATHWAY_ASYNC_EXEC", raising=False)
    assert not _executor_for(monkeypatch, mesh=True)._use_async()
    monkeypatch.setenv("PATHWAY_ASYNC_EXEC", "1")
    assert _executor_for(monkeypatch, mesh=True)._use_async()


# -- streaming parity: single vs async vs BSP escape hatch -------------------


def _run_streaming(build, monkeypatch, threads: int, async_exec: str,
                   fusion: str = "1") -> Counter:
    G.clear()
    acc: Counter = Counter()
    lock = threading.Lock()
    table = build()
    cols = table.column_names()

    def on_change(key, row, time, is_addition):
        with lock:
            acc[tuple(row[c] for c in cols)] += 1 if is_addition else -1

    pw.io.subscribe(table, on_change=on_change)
    monkeypatch.setenv("PATHWAY_THREADS", str(threads))
    monkeypatch.setenv("PATHWAY_ASYNC_EXEC", async_exec)
    monkeypatch.setenv("PATHWAY_FUSION", fusion)
    try:
        pw.run()
    finally:
        monkeypatch.setenv("PATHWAY_THREADS", "1")
        monkeypatch.delenv("PATHWAY_ASYNC_EXEC", raising=False)
        monkeypatch.delenv("PATHWAY_FUSION", raising=False)
        G.clear()
    assert all(v >= 0 for v in acc.values()), f"negative multiplicity: {acc}"
    return +acc


def _wordcount_prog():
    n, batch = 4_000, 250
    words = [f"w{i % 53}" for i in range(n)]

    class Feed(pw.io.python.ConnectorSubject):
        def run(self):
            for s in range(0, n, batch):
                self.next_batch({"word": words[s:s + batch]})
                self.commit()

    t = pw.io.python.read(
        Feed(), schema=pw.schema_from_types(word=str),
        autocommit_duration_ms=None,
    )
    return t.groupby(pw.this.word).reduce(
        pw.this.word, c=pw.reducers.count()
    )


def _join_retract_prog():
    # a streaming fact feed WITH retractions joined to a static dimension
    # table, grouped — drives ("column",) and ("mix",) exchange routes plus
    # negative diffs through the async data plane
    import pandas as pd

    right = pw.debug.table_from_pandas(
        pd.DataFrame({"rid": list(range(40)), "grp": [i % 5 for i in range(40)]})
    )

    class Facts(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(600):
                self.next(fid=i % 40, seq=i)
                if i % 7 == 3:
                    self._remove(fid=(i - 3) % 40, seq=i - 3)
                if i % 25 == 24:
                    self.commit()
            self.commit()

    facts = pw.io.python.read(
        Facts(), schema=pw.schema_from_types(fid=int, seq=int),
        autocommit_duration_ms=None,
    )
    joined = facts.join(right, facts.fid == right.rid).select(
        grp=right.grp, seq=facts.seq
    )
    return joined.groupby(pw.this.grp).reduce(
        pw.this.grp, n=pw.reducers.count(), s=pw.reducers.sum(pw.this.seq)
    )


@pytest.mark.parametrize("prog", [_wordcount_prog, _join_retract_prog])
@pytest.mark.parametrize("fusion", ["1", "0"])
def test_parity_async_vs_bsp_vs_single(monkeypatch, prog, fusion):
    single = _run_streaming(prog, monkeypatch, 1, "0", fusion)
    bsp = _run_streaming(prog, monkeypatch, 2, "0", fusion)
    a2 = _run_streaming(prog, monkeypatch, 2, "1", fusion)
    a4 = _run_streaming(prog, monkeypatch, 4, "1", fusion)
    assert bsp == single  # the escape hatch IS the old engine
    assert a2 == single
    assert a4 == single


# -- exactly-once under async (explicit PATHWAY_ASYNC_EXEC=1) ---------------


def test_chaos_smoke_async_pinned(tmp_path, monkeypatch):
    from chaos_smoke import EXPECTED, run_smoke

    monkeypatch.setenv("PATHWAY_ASYNC_EXEC", "1")
    result = run_smoke(workdir=str(tmp_path))
    assert result["final"] == EXPECTED
    assert result["generations"] == [0, 1]


def test_sink_kill_async_pinned(tmp_path, monkeypatch):
    import sink_smoke

    monkeypatch.setenv("PATHWAY_ASYNC_EXEC", "1")
    workdir = str(tmp_path)
    baseline = sink_smoke.scenario_clean(workdir)
    report = sink_smoke.scenario_kill(workdir, baseline)
    assert 0 < report["rows_before_kill"] < report["rows_total"]


# -- TCP cluster transport through the async plane ---------------------------


_CLUSTER_PROG = """
import json, os, sys
sys.path.insert(0, {repo!r})
from pathway_tpu.utils.jaxcfg import guard_cpu_platform
guard_cpu_platform()
import pathway_tpu as pw

n_rows, batch = 20_000, 1_000
words = [f"w{{i % 97}}" for i in range(n_rows)]


class Feed(pw.io.python.ConnectorSubject):
    def run(self):
        for s in range(0, n_rows, batch):
            self.next_batch({{"word": words[s:s + batch]}})
            self.commit()


t = pw.io.python.read(
    Feed(), schema=pw.schema_from_types(word=str),
    autocommit_duration_ms=None,
)
counts = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
from collections import Counter

net = Counter()


def on_change(key, row, time, is_addition):
    # multiset semantics: retract/insert pair order within one update
    # delta is not part of the engine contract — net multiplicities are
    net[(row["word"], int(row["c"]))] += 1 if is_addition else -1


pw.io.subscribe(counts, on_change=on_change)
pw.run()
if int(os.environ.get("PATHWAY_PROCESS_ID", "0")) == 0:
    final = {{w: c for (w, c), v in net.items() if v > 0}}
    with open(sys.argv[1], "w") as f:
        json.dump(final, f)
"""


@pytest.mark.slow
def test_cluster_n2_async(tmp_path):
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = tmp_path / "prog.py"
    out = tmp_path / "out.json"
    prog.write_text(_CLUSTER_PROG.format(repo=repo))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo,
        "PATHWAY_ASYNC_EXEC": "1",
    }
    r = subprocess.run(
        [
            sys.executable, "-m", "pathway_tpu.cli", "spawn",
            "-n", "2", "-t", "1", "--first-port", str(port),
            sys.executable, str(prog), str(out),
        ],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    acc = json.loads(out.read_text())
    expected = {f"w{i}": 20_000 // 97 + (1 if i < 20_000 % 97 else 0)
                for i in range(97)}
    assert acc == expected
