"""Commit-wave critical-path attribution (observability/critpath.py):
holding-worker election, stage-split math, the bounded wave history
ring, the process/cluster merges, the report renderer, and the staged
ingest->emit decomposition the executor feeds from the same stamps."""

from __future__ import annotations

import time

import pytest

from pathway_tpu.observability.critpath import (
    PHASES,
    WaveRecorder,
    attribute_holder,
    elect_holder,
    merge_process_waves,
    merge_worker_waves,
    render_report,
    stage_split,
)


def _phases(**kw) -> dict:
    out = {p: 0.0 for p in PHASES}
    out.update(kw)
    return out


def _wave(recorder, epoch, *, holder=2, dur=10.0, **phase_kw):
    order = [(recorder.worker_id, epoch, 0), (holder, epoch, 5)]
    return recorder.record_wave(
        epoch=epoch,
        T=epoch,
        t=1000.0 + epoch,
        duration_ms=dur,
        interval_ms=50.0,
        phases_ms=_phases(**phase_kw),
        settle_rounds=1,
        ready_order=order,
    )


# -- holding-worker election -------------------------------------------------


def test_elect_holder_last_arrival_wins():
    # worker 3 arrives in the last drain batch: it held the wave
    order = [(0, 10, 0), (1, 10, 1), (3, 10, 4), (2, 10, 2)]
    assert elect_holder(order) == 3


def test_elect_holder_tie_breaks_by_ready_clock_then_worker_id():
    # same arrival seq (one drain batch): the larger ready clock forced
    # T higher, so it held the wave longer
    assert elect_holder([(0, 10, 0), (1, 12, 3), (2, 15, 3)]) == 2
    # full tie: smaller worker id, so every worker elects the same one
    assert elect_holder([(0, 10, 0), (2, 15, 3), (1, 15, 3)]) == 1


def test_elect_holder_empty_order():
    assert elect_holder([]) is None


def test_attribute_holder_real_straggler_elected_by_arrival():
    # entry spread 80ms >= floor: the last frontier to arrive holds the
    # wave even though another worker burned more busy time
    order = [(0, 10, 100.0), (1, 10, 100.08)]
    holder, by = attribute_holder(
        order, busy_ms={0: 200.0, 1: 5.0}, floor_ms=25.0
    )
    assert (holder, by) == (1, "arrival")


def test_attribute_holder_jitter_falls_back_to_busy():
    # entries within 2ms of each other (timer-driven wave): arrival
    # order is scheduler noise, the busiest pipeline holds the wave
    order = [(0, 10, 100.001), (1, 10, 100.0)]
    holder, by = attribute_holder(
        order, busy_ms={0: 140.0, 1: 60.0}, floor_ms=25.0
    )
    assert (holder, by) == (0, "busy")


def test_attribute_holder_without_busy_data_keeps_arrival_verdict():
    order = [(0, 10, 100.001), (1, 10, 100.0)]
    assert attribute_holder(order, None, 25.0) == (0, "arrival")
    assert attribute_holder([], {0: 1.0}) == (None, "arrival")


# -- stage split -------------------------------------------------------------


def test_stage_split_names_largest_phase_and_shares_sum_to_one():
    critical, shares = stage_split(
        _phases(sweep=2.0, frontier_wait=6.0, settle=2.0)
    )
    assert critical == "frontier_wait"
    assert shares["frontier_wait"] == pytest.approx(0.6)
    assert sum(shares.values()) == pytest.approx(1.0)


def test_stage_split_ties_break_in_phase_order():
    # sweep precedes settle in PHASES: deterministic verdict on a tie
    critical, _ = stage_split(_phases(sweep=3.0, settle=3.0))
    assert critical == "sweep"


def test_stage_split_nothing_measured():
    critical, shares = stage_split(_phases())
    assert critical is None
    assert all(s == 0.0 for s in shares.values())


def test_stage_split_ignores_negative_phases():
    critical, shares = stage_split(_phases(settle=-5.0, release=1.0))
    assert critical == "release"
    assert shares["settle"] == 0.0


# -- per-worker recorder -----------------------------------------------------


def test_wave_recorder_ring_is_bounded_and_tallies_holders():
    rec = WaveRecorder(0, history=4)
    for ep in range(10):
        _wave(rec, ep, holder=ep % 2)
    assert len(rec.recent) == 4
    assert [d["epoch"] for d in rec.recent] == [6, 7, 8, 9]
    assert rec.held_total == {"0": 5, "1": 5}
    snap = rec.snapshot()
    assert snap["last"]["epoch"] == 9
    assert snap["worker"] == 0


def test_wave_recorder_document_shape():
    rec = WaveRecorder(1, history=8)
    doc = _wave(rec, 3, holder=2, dur=12.5, frontier_wait=9.0, sweep=3.0)
    assert doc["holder"] == 2
    assert doc["critical_stage"] == "frontier_wait"
    assert doc["duration_ms"] == 12.5
    assert set(doc["phases_ms"]) == set(PHASES)
    assert doc["ready_order"][-1] == (2, 3, 5)
    assert "fin" not in doc


def test_wave_recorder_marks_fin_wave():
    rec = WaveRecorder(0, history=2)
    doc = rec.record_wave(
        epoch=9, T=9, t=1.0, duration_ms=1.0, interval_ms=1.0,
        phases_ms=_phases(snapshot=1.0), settle_rounds=0,
        ready_order=[(0, 9, 0)], fin=True,
    )
    assert doc["fin"] is True


def test_wave_recorder_history_env_knob(monkeypatch):
    monkeypatch.setenv("PATHWAY_WAVE_HISTORY", "3")
    rec = WaveRecorder(0)
    assert rec.recent.maxlen == 3


# -- process merge (per-worker snapshots -> /query waves doc) ----------------


def _two_worker_snaps(holder_a=2, holder_b=2):
    a, b = WaveRecorder(0, history=8), WaveRecorder(2, history=8)
    _wave(a, 1, holder=holder_a, dur=10.0, frontier_wait=8.0)
    _wave(b, 1, holder=holder_b, dur=14.0, settle=12.0)
    return {"0": a.snapshot(), "2": b.snapshot()}


def test_merge_worker_waves_unanimous_holder_and_max_phases():
    doc = merge_worker_waves(_two_worker_snaps())
    assert doc["waves"] == 1
    wave = doc["recent"][0]
    assert wave["holder"] == 2 and wave["agreed"] is True
    # per-stage max over the workers' views; critical recomputed from it
    assert wave["critical_stage"] == "settle"
    assert wave["duration_ms"] == 14.0
    assert set(wave["workers"]) == {"0", "2"}
    assert doc["holder_share"] == {"2": 1.0}


def test_merge_worker_waves_disputed_holder_breaks_to_smaller_id():
    doc = merge_worker_waves(_two_worker_snaps(holder_a=3, holder_b=1))
    wave = doc["recent"][0]
    assert wave["agreed"] is False
    assert wave["holder"] == 1  # 1-1 vote: smaller worker id wins


def test_merge_worker_waves_skips_missing_snapshots():
    snaps = _two_worker_snaps()
    snaps["5"] = None
    doc = merge_worker_waves(snaps)
    assert doc["waves"] == 1


# -- cluster merge (process docs -> merged /query doc) -----------------------


def test_merge_process_waves_unions_workers_and_reelects():
    p0 = merge_worker_waves(_two_worker_snaps())
    c = WaveRecorder(4, history=8)
    _wave(c, 1, holder=4, dur=20.0, frontier_wait=18.0)
    _wave(c, 2, holder=4, dur=5.0, release=4.0)
    p1 = merge_worker_waves({"4": c.snapshot()})
    merged = merge_process_waves([p0, p1])
    assert merged["waves"] == 2
    wave1 = merged["recent"][0]
    assert set(wave1["workers"]) == {"0", "2", "4"}
    # 2 votes for w2, 1 for w4 over the union of verdicts
    assert wave1["holder"] == 2 and wave1["agreed"] is False
    # the slowest view's duration and split win
    assert wave1["duration_ms"] == 20.0
    assert merged["recent"][1]["holder"] == 4
    assert merged["held_total"] == {"2": 2, "4": 2}


def test_merge_process_waves_output_remerges():
    # the cluster doc has the same shape as a process doc, so merging
    # merges == merging the originals (re-merge associativity)
    p0 = merge_worker_waves(_two_worker_snaps())
    c = WaveRecorder(4, history=8)
    _wave(c, 1, holder=4, dur=20.0, frontier_wait=18.0)
    p1 = merge_worker_waves({"4": c.snapshot()})
    once = merge_process_waves([p0, p1])
    twice = merge_process_waves([merge_process_waves([p0]), p1])
    assert twice["recent"][0]["workers"] == once["recent"][0]["workers"]
    assert twice["recent"][0]["holder"] == once["recent"][0]["holder"]
    assert twice["held_total"] == once["held_total"]


def test_merge_process_waves_empty_inputs():
    doc = merge_process_waves([None, None])
    assert doc["waves"] == 0 and doc["last"] is None


# -- report ------------------------------------------------------------------


def test_render_report_ranks_slowest_and_names_holder():
    rec = WaveRecorder(0, history=16)
    for ep in range(6):
        _wave(rec, ep, holder=3, dur=float(ep), frontier_wait=float(ep))
    _wave(rec, 9, holder=1, dur=99.0, settle=90.0)
    doc = merge_worker_waves({"0": rec.snapshot()})
    report = render_report(doc, top_k=3)
    lines = report.splitlines()
    assert "wave 9" in lines[2] and "holder=w1" in lines[2]
    assert "critical=settle" in lines[2]
    assert len([ln for ln in lines if ln.startswith("  wave")]) == 3


def test_render_report_handles_empty_doc():
    assert "no commit waves" in render_report(None)
    assert "no commit waves" in render_report(merge_process_waves([]))


# -- staged ingest->emit decomposition (EngineStats.note_e2e) ----------------


def test_note_e2e_stages_sum_to_total_latency():
    from pathway_tpu.engine.executor import E2E_STAGES, EngineStats

    stats = EngineStats()
    now = time.time_ns()
    ingest = now - 100_000_000  # 100 ms ago
    stats.note_e2e(
        ingest, route_ns=10_000_000, dwell_ns=20_000_000,
        sweep_t0_wall_ns=now - 5_000_000,
    )
    assert stats.e2e_latency_hist._count == 1
    total = stats.e2e_latency_hist._sum
    staged = sum(stats.stage_hists[s]._sum for s in E2E_STAGES)
    assert staged == total
    assert stats.stage_hists["ingest_route"]._sum == 10_000_000
    assert stats.stage_hists["inbox_dwell"]._sum == 20_000_000
    assert stats.stage_hists["commit_deliver"]._sum >= 5_000_000


def test_note_e2e_clamps_stages_against_total():
    from pathway_tpu.engine.executor import E2E_STAGES, EngineStats

    stats = EngineStats()
    # claimed route latency exceeds the whole e2e: clamp, never negative
    stats.note_e2e(time.time_ns() - 1_000_000, route_ns=10_000_000_000)
    total = stats.e2e_latency_hist._sum
    staged = sum(stats.stage_hists[s]._sum for s in E2E_STAGES)
    assert staged == total
    assert all(stats.stage_hists[s]._sum >= 0 for s in E2E_STAGES)


def test_note_wave_folds_doc_into_counters():
    from pathway_tpu.engine.executor import EngineStats

    stats = EngineStats()
    rec = WaveRecorder(0, history=4)
    doc = _wave(rec, 1, holder=2, dur=10.0, frontier_wait=8.0, sweep=2.0)
    stats.note_wave(doc, 10_000_000)
    stats.note_wave(doc, 12_000_000)
    assert stats.waves_total == 2
    assert stats.wave_held_total == {"2": 2}
    assert stats.wave_stage_ns["frontier_wait"] == 16_000_000
    assert stats.wave_duration._count == 2


# -- offline trace view ------------------------------------------------------


def test_wave_spans_ranks_merged_trace_commit_spans():
    from pathway_tpu.observability.trace_merge import wave_spans

    doc = {
        "traceEvents": [
            {"name": "wave.commit", "ph": "X", "pid": 0, "ts": 10.0,
             "dur": 5000.0, "args": {"epoch": 1, "T": 1, "holder": 2,
                                     "critical": "settle"}},
            {"name": "wave.commit", "ph": "X", "pid": 1, "ts": 20.0,
             "dur": 9000.0, "args": {"epoch": 2, "T": 2, "holder": 3,
                                     "critical": "frontier_wait"}},
            {"name": "wave.settle", "ph": "X", "pid": 0, "ts": 11.0,
             "dur": 100.0, "args": {}},
            {"name": "process_name", "ph": "M", "pid": 0},
        ]
    }
    spans = wave_spans(doc, top_k=5)
    assert [s["epoch"] for s in spans] == [2, 1]
    assert spans[0]["holder"] == 3 and spans[0]["dur_ms"] == 9.0
    assert spans[0]["critical"] == "frontier_wait"
    assert wave_spans({"traceEvents": []}) == []
