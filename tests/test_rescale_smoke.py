"""Tier-1 wrapper around scripts/rescale_smoke.py (like test_chaos_smoke):
a 2-process persisted wordcount is SIGKILLed mid-stream, its state is
resharded to 3 workers (`pathway-tpu rescale`), a supervised 3-worker run
resumes to EXACT final counts — and a chaos SIGKILL during the rescale's
promotion leaves the old layout bootable, which `spawn --supervise
--elastic` then reshards in-process and still finishes exactly."""

import os
import sys

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
    ),
)


def test_rescale_smoke(tmp_path):
    from rescale_smoke import EXPECTED, run_smoke

    result = run_smoke(workdir=str(tmp_path))
    assert result["final"] == EXPECTED
    assert result["elastic_final"] == EXPECTED
    assert result["report"]["from"] == 2 and result["report"]["to"] == 3
