"""Spill-to-disk state tier (ISSUE 8 tentpole b, engine/spill.py).

- SpillStore: generation-versioned blobs; a torn/failed write (chaos
  ``state.spill`` site) leaves the previous generation readable and the
  caller's resident copy authoritative.
- _SortedSide: cold runs spill payload-only; probe/totals stay correct;
  pickling (= snapshots) materializes spilled runs.
- GroupByReduce: dense cold-prefix arena block + general cold-group
  buckets; fault-in on touch; snapshot materialization.
- StateBudget: sheds the largest holdings, survives failing stores.
"""

from __future__ import annotations

import pickle

import numpy as np
import pytest

from pathway_tpu import chaos
from pathway_tpu.engine import spill
from pathway_tpu.engine.operators import GroupByReduce, _SortedSide
from pathway_tpu.persistence.backends import FilesystemBackend, MemoryBackend


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    chaos.disarm()
    spill._reset_for_tests()
    yield
    chaos.disarm()
    spill._reset_for_tests()


def _arm_budget(monkeypatch, tmp_path, mb="0.01"):
    monkeypatch.setenv("PATHWAY_STATE_MEMORY_BUDGET_MB", str(mb))
    monkeypatch.setenv("PATHWAY_STATE_SPILL_DIR", str(tmp_path / "spill"))
    spill._reset_for_tests()
    budget = spill.get_budget()
    assert budget is not None
    return budget


# -- SpillStore ------------------------------------------------------------


def test_spillstore_roundtrip_and_generations():
    store = spill.SpillStore(MemoryBackend())
    h1 = store.put_blob("x", {"a": 1})
    assert store.get_blob(h1) == {"a": 1}
    h2 = store.put_blob("x", {"a": 2}, prev=h1)
    assert store.get_blob(h2) == {"a": 2}
    with pytest.raises(KeyError):
        store.get_blob(h1)  # previous generation deleted AFTER success
    c = spill.spill_counters()
    assert c["spill_events_total"] == 2 and c["load_events_total"] >= 2


def test_spillstore_chunks_large_blobs(monkeypatch):
    monkeypatch.setattr(spill, "CHUNK_BYTES", 1024)
    store = spill.SpillStore(MemoryBackend())
    payload = np.arange(2000, dtype=np.int64)  # 16KB > several chunks
    h = store.put_blob("big", payload)
    assert h["chunks"] > 1
    np.testing.assert_array_equal(store.get_blob(h), payload)


def test_chaos_fail_keeps_previous_generation():
    plan = chaos.FaultPlan.from_dict({
        "faults": [{"site": "state.spill", "action": "fail", "nth": 2}],
    })
    chaos.arm(plan)
    store = spill.SpillStore(MemoryBackend())
    h1 = store.put_blob("seg", [1, 2, 3])
    from pathway_tpu.chaos.injector import ChaosInjected

    with pytest.raises(ChaosInjected):
        store.put_blob("seg", [4, 5, 6], prev=h1)
    assert store.get_blob(h1) == [1, 2, 3]  # old generation intact


def test_chaos_torn_write_keeps_previous_generation():
    plan = chaos.FaultPlan.from_dict({
        "faults": [{"site": "state.spill", "action": "torn", "nth": 2}],
    })
    chaos.arm(plan)
    backend = MemoryBackend()
    store = spill.SpillStore(backend)
    h1 = store.put_blob("seg", list(range(100)))
    from pathway_tpu.chaos.injector import ChaosInjected

    with pytest.raises(ChaosInjected):
        store.put_blob("seg", list(range(200)), prev=h1)
    # the torn generation DID write garbage bytes somewhere — but the
    # handle protocol never exposed it, and the old blob still loads
    assert store.get_blob(h1) == list(range(100))


def test_chaos_key_prefix_selects_site():
    plan = chaos.FaultPlan.from_dict({
        "faults": [{
            "site": "state.spill", "action": "fail", "nth": 1,
            "key_prefix": "gb/",
        }],
    })
    chaos.arm(plan)
    store = spill.SpillStore(MemoryBackend())
    store.put_blob("join/run", [1])  # prefix mismatch: untouched
    from pathway_tpu.chaos.injector import ChaosInjected

    with pytest.raises(ChaosInjected):
        store.put_blob("gb/bucket/00", [2])


# -- _SortedSide spill -----------------------------------------------------


def _apply_batch(side, start, n, tag):
    jks = np.arange(start, start + n, dtype=np.uint64)
    keys = jks + np.uint64(1000)
    cols = [np.full(n, tag, dtype=np.int64)]
    side.apply(jks, keys, cols, np.ones(n, dtype=np.int64))


def _probe_all(side, qjks):
    hits = []
    for q_idx, keys, cols, counts in side.probe(qjks):
        for i in range(len(q_idx)):
            hits.append((int(qjks[q_idx[i]]), int(keys[i]), int(cols[0][i]),
                         int(counts[i])))
    return sorted(hits)


def test_sorted_side_spill_probe_totals_equal(monkeypatch, tmp_path):
    _arm_budget(monkeypatch, tmp_path)
    side = _SortedSide(1)
    ref = _SortedSide(1)
    for b, (s, n) in enumerate([(0, 500), (500, 300), (800, 50)]):
        _apply_batch(side, s, n, b)
        _apply_batch(ref, s, n, b)
    freed = side.spill(1 << 30)  # spill everything spillable
    assert freed > 0 and side._spilled and side.spilled_bytes() > 0
    assert len(side) == len(ref) == 850
    q = np.array([0, 123, 499, 700, 820, 9999], dtype=np.uint64)
    np.testing.assert_array_equal(side.totals(q), ref.totals(q))
    assert _probe_all(side, q) == _probe_all(ref, q)
    # spill/load moved real counters
    c = spill.spill_counters()
    assert c["spill_events_total"] > 0 and c["load_events_total"] > 0


def test_sorted_side_pickle_materializes_spilled_runs(monkeypatch, tmp_path):
    _arm_budget(monkeypatch, tmp_path)
    side = _SortedSide(1)
    _apply_batch(side, 0, 400, 7)
    side.spill(1 << 30)
    assert side._spilled
    clone = pickle.loads(pickle.dumps(side))
    assert not clone._spilled  # snapshot-format: fully resident
    assert len(clone) == 400
    q = np.array([5, 399], dtype=np.uint64)
    np.testing.assert_array_equal(clone.totals(q), side.totals(q))
    # the LIVE side still works after being snapshotted
    assert _probe_all(side, q) == _probe_all(clone, q)


def test_sorted_side_compaction_unspills(monkeypatch, tmp_path):
    _arm_budget(monkeypatch, tmp_path)
    side = _SortedSide(1)
    _apply_batch(side, 0, 512, 0)
    side.spill(1 << 30)
    # retract everything: the retraction batch + compaction must net out
    jks = np.arange(512, dtype=np.uint64)
    side.apply(jks, jks + np.uint64(1000),
               [np.zeros(512, dtype=np.int64)], np.full(512, -1, np.int64))
    side._compact()
    assert not side._spilled
    # values differ between insert (tag 0) and retract batches, so rows
    # do NOT cancel: both multiplicities survive, totals say net zero
    assert side.totals(jks).sum() == 0


def test_sorted_side_failed_spill_keeps_runs_resident(monkeypatch, tmp_path):
    budget = _arm_budget(monkeypatch, tmp_path, mb="0.001")
    plan = chaos.FaultPlan.from_dict({
        "faults": [{"site": "state.spill", "action": "fail", "prob": 1.0}],
    })
    chaos.arm(plan)
    side = _SortedSide(1)
    _apply_batch(side, 0, 300, 1)
    n_runs = len(side._runs)
    freed = budget.maybe_spill()  # swallows the chaos failure
    assert freed == 0
    assert len(side._runs) == n_runs and not side._spilled
    q = np.array([0, 299], dtype=np.uint64)
    assert side.totals(q).sum() == 2
    assert spill.spill_counters()["spill_errors_total"] >= 1


# -- GroupByReduce spill ---------------------------------------------------


def _dense_groupby():
    from pathway_tpu.engine.reducers import CountReducer, SumReducer

    class _Stub:
        node_id = 0
        column_names = ["k"]

        def __init__(self):
            self.inputs = []

    import pathway_tpu.engine.operators as ops

    src = ops.SourceNode.__new__(ops.SourceNode)
    src.node_id = 0
    src.column_names = ["k", "v"]
    src.inputs = []
    return GroupByReduce(
        src, ["k"], [("c", CountReducer(), []), ("s", SumReducer(), ["v"])]
    )


def _delta(gks, vals, diffs=None):
    from pathway_tpu.engine.delta import Delta

    n = len(gks)
    return Delta(
        keys=np.arange(n, dtype=np.uint64),
        data={
            "k": np.asarray(gks, dtype=np.int64),
            "v": np.asarray(vals, dtype=np.int64),
        },
        diffs=np.ones(n, np.int64) if diffs is None else np.asarray(diffs),
    )


def _collect(node, d, t=2):
    return node.process(t, [d])


def test_groupby_dense_arena_spills_and_faults_in(monkeypatch, tmp_path):
    _arm_budget(monkeypatch, tmp_path)
    g = _dense_groupby()
    assert g._dense
    # ticks over disjoint group ranges: early groups go cold
    for tick in range(6):
        gks = np.arange(tick * 200, (tick + 1) * 200)
        _collect(g, _delta(gks, gks * 10), t=2 + 2 * tick)
    before = g.spillable_bytes()
    freed = g.spill(1 << 30)
    assert freed > 0 and g._arena_base > 0
    assert g.spilled_bytes() > 0
    assert g.spillable_bytes() < before
    # touching an OLD group faults the cold block back in and the
    # retract/emit algebra stays exact
    out = _collect(g, _delta([5], [1]), t=99)
    assert g._arena_base == 0
    rows = {
        (int(k), int(c), int(s), int(d))
        for k, c, s, d in zip(
            out.data["k"], out.data["c"], out.data["s"], out.diffs
        )
    }
    assert (5, 1, 50, -1) in rows  # retract old aggregate for group 5
    assert (5, 2, 51, 1) in rows  # insert updated one


def test_groupby_dense_snapshot_materializes_cold_block(
    monkeypatch, tmp_path
):
    _arm_budget(monkeypatch, tmp_path)
    g = _dense_groupby()
    # > deque(maxlen=4) ticks over disjoint ranges so the recency
    # watermark rises above slot 0 and a cold prefix exists to spill
    for tick in range(6):
        gks = np.arange(tick * 100, (tick + 1) * 100)
        _collect(g, _delta(gks, gks), t=2 + 2 * tick)
    unspilled_snapshot = g.snapshot_state()
    g.spill(1 << 30)
    assert g._arena_base > 0
    snap = g.snapshot_state()
    a, b = unspilled_snapshot["arena"], snap["arena"]
    np.testing.assert_array_equal(a["_counts"], b["_counts"])
    np.testing.assert_array_equal(a["_gkey_by_slot"], b["_gkey_by_slot"])
    np.testing.assert_array_equal(a["_prev"][1], b["_prev"][1])
    # a fresh operator restored from the snapshot serves all groups with
    # NO spill dir dependency
    g2 = _dense_groupby()
    g2.restore_state(pickle.loads(pickle.dumps(snap)))
    out = _collect(g2, _delta([0], [7]), t=50)
    assert out is not None and len(out)


def test_groupby_general_cold_buckets(monkeypatch, tmp_path):
    _arm_budget(monkeypatch, tmp_path)
    from pathway_tpu.engine.reducers import MinReducer

    import pathway_tpu.engine.operators as ops

    src = ops.SourceNode.__new__(ops.SourceNode)
    src.node_id = 0
    src.column_names = ["k", "v"]
    src.inputs = []
    g = GroupByReduce(src, ["k"], [("m", MinReducer(), ["v"])])
    assert not g._dense
    # three disjoint batches: the first falls out of the 2-batch recency
    # window and becomes spillable
    _collect(g, _delta(np.arange(300), np.arange(300) + 5), t=2)
    _collect(g, _delta(np.arange(300, 600), np.arange(300)), t=4)
    _collect(g, _delta(np.arange(600, 700), np.arange(100)), t=6)
    n_resident = len(g._state)
    freed = g.spill(1 << 30)
    assert freed > 0 and g._cold_set
    assert len(g._state) < n_resident
    # cold groups materialize into snapshots
    snap = g.snapshot_state()
    assert len(snap["_state"]) == 700
    # touching cold groups faults them back in with exact accumulators
    out = _collect(g, _delta([10], [0]), t=60)
    rows = {
        (int(k), int(m), int(d))
        for k, m, d in zip(out.data["k"], out.data["m"], out.diffs)
    }
    assert (10, 15, -1) in rows and (10, 0, 1) in rows


# -- StateBudget -----------------------------------------------------------


class _FakeStore:
    def __init__(self, resident):
        self.resident = resident
        self.disk = 0

    def spillable_bytes(self):
        return self.resident

    def spilled_bytes(self):
        return self.disk

    def spill(self, want):
        moved = min(self.resident, want)
        self.resident -= moved
        self.disk += moved
        return moved


def test_budget_sheds_largest_first(monkeypatch, tmp_path):
    budget = spill.StateBudget(1000)
    small, big = _FakeStore(400), _FakeStore(5000)
    budget.register(small)
    budget.register(big)
    freed = budget.maybe_spill()
    assert freed >= 4400
    assert big.resident < 5000
    assert small.resident == 400  # big alone got under budget
    assert budget.maybe_spill() == 0  # already under budget


def test_budget_unspillable_warns_once(caplog):
    import logging

    class _Stuck(_FakeStore):
        def spill(self, want):
            return 0

    budget = spill.StateBudget(10)
    stuck = _Stuck(1000)  # strong ref: registration is a WeakSet
    budget.register(stuck)
    with caplog.at_level(logging.WARNING, logger="pathway_tpu.spill"):
        budget.maybe_spill()
        budget.maybe_spill()
    warnings = [
        r for r in caplog.records if "could spill" in r.getMessage()
    ]
    assert len(warnings) == 1


def test_budget_env_parsing(monkeypatch, tmp_path):
    monkeypatch.delenv("PATHWAY_STATE_MEMORY_BUDGET_MB", raising=False)
    spill._reset_for_tests()
    assert spill.get_budget() is None
    monkeypatch.setenv("PATHWAY_STATE_MEMORY_BUDGET_MB", "bogus")
    spill._reset_for_tests()
    assert spill.get_budget() is None  # logged, disabled — not a crash
    monkeypatch.setenv("PATHWAY_STATE_MEMORY_BUDGET_MB", "2.5")
    spill._reset_for_tests()
    assert spill.get_budget().budget_bytes == int(2.5 * (1 << 20))


def test_memory_snapshot_shape(monkeypatch, tmp_path):
    _arm_budget(monkeypatch, tmp_path)
    snap = spill.memory_snapshot()
    for key in (
        "rss_bytes", "state_budget_bytes", "state_resident_bytes",
        "state_spilled_bytes", "spill_events_total",
        "key_registry_entries", "key_registry_frozen",
        "key_registry_spilled_total",
    ):
        assert key in snap and isinstance(snap[key], (int, float))
    assert snap["rss_bytes"] > 0


def test_dead_pid_scratch_swept(monkeypatch, tmp_path):
    import os

    root = tmp_path / "spillroot"
    dead = root / "p999999999"  # no such pid
    dead.mkdir(parents=True)
    (dead / "junk").write_bytes(b"x")
    monkeypatch.setenv("PATHWAY_STATE_SPILL_DIR", str(root))
    got = spill._default_spill_root()
    assert got == str(root / f"p{os.getpid()}")
    assert not dead.exists()


# -- observability wiring (metrics / signals / top) ------------------------


def test_memory_gauges_on_metrics(monkeypatch, tmp_path):
    """RSS + state-budget + key-registry gauges render per process on
    /metrics (ISSUE 8 satellite: surface registry state everywhere)."""
    _arm_budget(monkeypatch, tmp_path)
    from pathway_tpu.observability.hub import ObservabilityHub

    hub = ObservabilityHub()
    body = hub.render_metrics()
    for name in (
        "pathway_process_rss_bytes",
        "pathway_state_budget_bytes",
        "pathway_state_resident_bytes",
        "pathway_state_spilled_bytes",
        "pathway_state_spill_events_total",
        "pathway_key_registry_entries",
        "pathway_key_registry_frozen",
        "pathway_key_registry_spilled_total",
    ):
        assert name in body, f"{name} missing from /metrics"
    assert 'process="0"' in body
    # counters typed as counters, gauges as gauges
    assert "# TYPE pathway_state_spill_events_total counter" in body
    assert "# TYPE pathway_process_rss_bytes gauge" in body


def test_memory_series_sampled_into_signals(monkeypatch, tmp_path):
    _arm_budget(monkeypatch, tmp_path)
    from pathway_tpu.observability.hub import ObservabilityHub
    from pathway_tpu.observability.timeseries import SignalsPlane

    hub = ObservabilityHub()
    plane = SignalsPlane(hub, sample_s=0.05, window_s=5)
    plane.sample_once(t=100.0)
    plane.sample_once(t=100.5)
    metrics = set(plane.signals.store.metrics(None))
    assert "mem.rss_bytes" in metrics
    assert "mem.state_budget_bytes" in metrics
    assert "mem.key_registry_entries" in metrics
    assert plane.signals.last("mem.rss_bytes", None) > 0


def test_top_renders_memory_line(monkeypatch, tmp_path):
    from pathway_tpu.observability.top import render_frame

    doc = {
        "process_id": 0,
        "workers": {},
        "memory": {
            "rss_bytes": 123_000_000.0,
            "state_budget_bytes": 1_000_000.0,
            "state_resident_bytes": 400_000.0,
            "state_spilled_bytes": 2_600_000.0,
            "spill_events_total": 7.0,
            "key_registry_entries": 5000.0,
            "key_registry_cold_entries": 1200.0,
            "key_registry_frozen": 0.0,
        },
    }
    frame = render_frame(doc, now=0.0)
    assert "mem p0: rss 123 MB" in frame
    assert "0.4/1.0 MB resident" in frame
    assert "2.6 MB spilled (7 spills)" in frame
    assert "registry 5000 key(s) (1200 cold)" in frame
    assert "FROZEN" not in frame
    doc["memory"]["key_registry_frozen"] = 1.0
    assert "FROZEN" in render_frame(doc, now=0.0)


def test_snapshot_document_carries_memory(monkeypatch, tmp_path):
    _arm_budget(monkeypatch, tmp_path)
    from pathway_tpu.observability.hub import ObservabilityHub

    doc = ObservabilityHub().snapshot_document()
    assert doc["memory"]["rss_bytes"] > 0
    assert "state_budget_bytes" in doc["memory"]
