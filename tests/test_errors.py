"""Value::Error semantics (reference ``src/engine/error.rs`` +
``python/pathway/tests/test_errors.py``): errors are per-row values that
flow through the dataflow without poisoning the stream — division by zero
makes an Error row (expression.rs:846,935), an Error in a reduced column
makes the group's aggregate Error until it retracts (reduce.rs:162-173),
and an Error grouping key skips the row with a log entry
(dataflow.rs:3026 ErrorInGroupby)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.error import ERROR_LOG
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T, run_table


@pytest.fixture(autouse=True)
def _fresh():
    G.clear()
    yield
    G.clear()


def rows(table):
    state, _ = run_table(table)
    return sorted(state.values(), key=repr)


def test_division_by_zero_is_error_row():
    t = T("a | b\n6 | 2\n5 | 0")
    out = t.select(d=pw.fill_error(pw.this.a // pw.this.b, -1))
    assert rows(out) == [(-1,), (3,)]


def test_mod_and_truediv_by_zero():
    t = T("a | b\n7 | 0\n7 | 2")
    out = t.select(
        m=pw.fill_error(pw.this.a % pw.this.b, -1),
        q=pw.fill_error(pw.this.a / pw.this.b, -1.0),
    )
    assert rows(out) == [(-1, -1.0), (1, 3.5)]


def test_unwrap_refuses_error():
    t = T("a | b\n5 | 0")
    out = t.select(d=pw.unwrap(pw.this.a // pw.this.b))
    with pytest.raises(Exception):
        run_table(out)


def test_error_in_reduced_column_makes_group_error():
    t = T("g | v\na | 1\na | 0\nb | 2")
    s = t.select(g=pw.this.g, inv=10 // pw.this.v)
    # _skip_errors=False: propagate (the engine reduce.rs error_count
    # contract); the reference groupby DEFAULT skips error cells
    r = s.groupby(pw.this.g, _skip_errors=False).reduce(
        pw.this.g,
        s=pw.reducers.sum(pw.this.inv),
        c=pw.reducers.count(),
    )
    rec = r.select(pw.this.g, s=pw.fill_error(pw.this.s, -999), c=pw.this.c)
    # count still counts the error row; only the sum turns Error
    assert rows(rec) == [("a", -999, 2), ("b", 5, 1)]


def test_error_retraction_recovers_group():
    t = T(
        """
        g | v | __time__ | __diff__
        a | 1 | 2        | 1
        a | 0 | 2        | 1
        b | 2 | 2        | 1
        a | 0 | 4        | -1
        """
    )
    s = t.select(g=pw.this.g, inv=10 // pw.this.v)
    r = s.groupby(pw.this.g, _skip_errors=False).reduce(
        pw.this.g, s=pw.reducers.sum(pw.this.inv)
    )
    rec = r.select(pw.this.g, s=pw.fill_error(pw.this.s, -999))
    # after the zero row retracts, group a's sum is clean again
    assert rows(rec) == [("a", 10), ("b", 5)]


def test_error_group_key_skips_row_and_logs():
    before = ERROR_LOG.total
    t = T("k | v\n2 | 10\n0 | 20")
    s = t.select(gk=pw.this.v // pw.this.k, v=pw.this.v)
    r = s.groupby(pw.this.gk).reduce(pw.this.gk, c=pw.reducers.count())
    assert rows(r) == [(5, 1)]
    assert ERROR_LOG.total > before
    assert any("grouping columns" in m for m, _ in ERROR_LOG.entries())


def test_error_in_min_max_reducers():
    t = T("g | v\na | 4\na | 0\nb | 3")
    s = t.select(g=pw.this.g, inv=12 // pw.this.v)
    r = s.groupby(pw.this.g, _skip_errors=False).reduce(
        pw.this.g,
        lo=pw.fill_error(pw.reducers.min(pw.this.inv), -1),
        hi=pw.fill_error(pw.reducers.max(pw.this.inv), -1),
    )
    assert rows(r) == [("a", -1, -1), ("b", 4, 4)]


def test_error_join_key_drops_row():
    l = T("k | x\n1 | 10\n0 | 20")
    r2 = T("k | y\n10 | 2")
    lk = l.select(kk=10 // pw.this.k, x=pw.this.x)
    j = lk.join(r2, lk.kk == r2.k).select(pw.this.x, pw.this.y)
    assert rows(j) == [(10, 2)]


def test_errors_propagate_through_expressions():
    t = T("a | b\n5 | 0")
    out = t.select(d=pw.fill_error((pw.this.a // pw.this.b) + 100, -1))
    assert rows(out) == [(-1,)]


def test_division_by_zero_on_optional_column():
    # optional (object-dtype) denominators hit the per-row path; a zero
    # must become an Error row there too, not a batch ZeroDivisionError
    t = pw.debug.table_from_rows(
        pw.schema_from_types(a=int, b=int | None),
        [(6, 2), (5, 0), (4, None)],
    )
    out = t.select(
        d=pw.fill_error(pw.this.a // pw.this.b, -1),
    )
    assert rows(out) == [(-1,), (3,), (None,)]


def test_error_flows_through_dense_downstream_ops():
    # the division's static dtype stays INT, so the downstream * and +
    # run on a statically-dense column that carries an Error at runtime —
    # they must pass it through per-row, not crash batch-wide
    t = T("a | b | c\n8 | 0 | 2\n9 | 3 | 3")
    out = t.select(d=pw.fill_error((pw.this.a // pw.this.b) * pw.this.c + 1, -1))
    assert rows(out) == [(-1,), (10,)]


def test_errors_seen_gate_scoped_to_live_errors():
    # r3 ADVICE: the gate is a live-object count, not a sticky process
    # latch — it stays on while any Error value is alive (even after the
    # log clears) and recovers the fast path once they are collected
    import gc

    from pathway_tpu.engine import error as err_mod

    base = err_mod.live_errors()
    e = err_mod.Error.silent("held")
    ERROR_LOG.clear()
    assert err_mod.errors_seen()  # clearing the log must not reset the gate
    assert err_mod.live_errors() == base + 1
    del e
    gc.collect()
    # __del__ defers its decrement (GC-reentrancy-safe, ADVICE r4);
    # live_errors() applies pending decrements without waiting for the
    # next _incr to drain them
    assert err_mod.live_errors() == base


def test_error_pickle_roundtrip_sets_latch():
    import pickle

    from pathway_tpu.engine.error import Error

    e = pickle.loads(pickle.dumps(Error("boom", "test")))
    assert e.message == "boom"
    assert repr(e) == "Error"


def test_stuck_error_group_does_not_spam_log():
    # a group stuck in error re-derives its aggregate on every later
    # update; only the original row errors may log (review finding)
    t = T(
        """
        g | v | __time__ | __diff__
        a | 0 | 2        | 1
        a | 5 | 4        | 1
        a | 6 | 6        | 1
        a | 7 | 8        | 1
        """
    )
    before = ERROR_LOG.total
    s = t.select(g=pw.this.g, inv=10 // pw.this.v)
    r = s.groupby(pw.this.g, _skip_errors=False).reduce(pw.this.g, s=pw.reducers.sum(pw.this.inv))
    rec = r.select(pw.this.g, s=pw.fill_error(pw.this.s, -999))
    assert rows(rec) == [("a", -999)]
    # one zero-division row error (possibly re-derived once per batch
    # retry) — NOT one entry per later clean update
    assert ERROR_LOG.total - before <= 3


def test_zero_denominator_constant():
    t = T("a\n5\n6")
    out = t.select(d=pw.fill_error(pw.this.a // 0, -1))
    assert rows(out) == [(-1,), (-1,)]


def test_error_keys_on_both_sides_never_match():
    # two Error join keys must not match each other (Error == nothing)
    l = T("k | x\n1 | 10\n0 | 20")
    r2 = T("k | y\n1 | 2\n0 | 3")
    lk = l.select(kk=10 // pw.this.k, x=pw.this.x)
    rk = r2.select(kk=10 // pw.this.k, y=pw.this.y)
    j = lk.join(rk, lk.kk == rk.kk).select(pw.this.x, pw.this.y)
    assert rows(j) == [(10, 2)]
    assert any("join condition" in m for m, _ in ERROR_LOG.entries())


def test_error_filter_condition_skips_row():
    t = T("a | b\n6 | 2\n5 | 0")
    f = t.filter((pw.this.a // pw.this.b) == 3)
    assert rows(f) == [(6, 2)]
    assert any("filter condition" in m for m, _ in ERROR_LOG.entries())


def test_error_join_key_retraction_consistent():
    # insert then retract a row with an Error key: state stays clean and
    # the live rows still join (the sentinel is deterministic)
    l = T(
        """
        k | x | __time__ | __diff__
        1 | 10 | 2       | 1
        0 | 20 | 2       | 1
        0 | 20 | 4       | -1
        """
    )
    r2 = T("k | y\n10 | 7")
    lk = l.select(kk=10 // pw.this.k, x=pw.this.x)
    j = lk.join(r2, lk.kk == r2.k).select(pw.this.x, pw.this.y)
    assert rows(j) == [(10, 7)]


def test_optional_ix_after_errors_latched():
    # an unrelated error latches errors_seen(); a later optional-pointer
    # ix join (object key column holding None) must still work
    t0 = T("a\n0")
    assert rows(t0.select(e=pw.fill_error(10 // pw.this.a, -1))) == [(-1,)]
    G.clear()
    src = T("k | v\na | 1\nb | 2").with_id_from(pw.this.k)
    q = T("k\na\nz")
    ptr = q.select(p=src.pointer_from(q.k))
    r = src.ix(ptr.p, optional=True, context=ptr).select(pw.this.v)
    assert rows(r) == sorted([(1,), (None,)], key=repr)
