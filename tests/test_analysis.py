"""Static analyzer (pathway_tpu/analysis): seeded-defect matrix.

Every shipped diagnostic is held to BOTH directions: one pipeline seeded
with the defect (the diagnostic fires) and one clean counterpart (it
stays quiet). Plus the fingerprint contract: stable across two compiles
of the same script, different when the graph changes.
"""

from __future__ import annotations

import datetime

import pytest

import pathway_tpu as pw
import pathway_tpu.debug as dbg
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.persistence import Backend, Config
from pathway_tpu.testing import T


@pytest.fixture(autouse=True)
def _clean_graph(monkeypatch):
    # the unbounded-state pass downgrades on a set spill budget — tests
    # must not inherit one from the environment
    monkeypatch.delenv("PATHWAY_STATE_MEMORY_BUDGET_MB", raising=False)
    monkeypatch.delenv("PATHWAY_SINK_DLQ_DIR", raising=False)
    monkeypatch.delenv("PATHWAY_LINT_WORKERS", raising=False)
    G.clear()
    yield
    G.clear()


class _Stream(pw.io.python.ConnectorSubject):
    """A never-ending-source stand-in (RealtimeSource post-lowering)."""

    def run(self):  # pragma: no cover - never polled by the analyzer
        pass


def _stream_table(**cols):
    cols = cols or {"word": str}
    return pw.io.python.read(
        _Stream(), schema=pw.schema_from_types(**cols), name="s"
    )


def _ids(report):
    return [d.id for d in report.diagnostics]


# ---------------------------------------------------------------------------
# unbounded-state
# ---------------------------------------------------------------------------


def test_unbounded_state_fires_on_streaming_groupby():
    t = _stream_table()
    t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
    # reduce() alone registers no sink; subscribe to pull it into the graph
    pw.io.subscribe(
        t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count()),
        on_change=lambda **kw: None,
    )
    report = pw.analyze()
    found = report.by_id("unbounded-state")
    assert found and found[0].severity == "warning"
    assert "GroupByReduce" in found[0].message
    assert "PATHWAY_STATE_MEMORY_BUDGET_MB" in (found[0].mitigation or "")


def test_unbounded_state_fires_on_streaming_join():
    left = _stream_table()
    right = T("word | label\nfoo | a")
    res = left.join(right, left.word == right.word).select(
        pw.left.word, pw.right.label
    )
    pw.io.subscribe(res, on_change=lambda **kw: None)
    report = pw.analyze()
    assert any(
        "Join" in d.message for d in report.by_id("unbounded-state")
    )


def test_unbounded_state_fires_on_streaming_deduplicate():
    t = _stream_table(word=str, n=int)
    res = t.deduplicate(
        value=pw.this.n, instance=pw.this.word,
        acceptor=lambda new, old: new > old,
    )
    pw.io.subscribe(res, on_change=lambda **kw: None)
    assert any(
        "Deduplicate" in d.message
        for d in pw.analyze().by_id("unbounded-state")
    )


def test_unbounded_state_quiet_on_static_source():
    t = T("word\nfoo\nbar")
    res = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
    pw.io.subscribe(res, on_change=lambda **kw: None)
    assert not pw.analyze().by_id("unbounded-state")


def test_unbounded_state_quiet_behind_forget_after():
    from pathway_tpu.stdlib.temporal._shared import apply_behavior_nodes

    t = _stream_table(word=str, t=int)
    # keep_results=False lowers a ForgetAfter(forget_state=True): rows
    # retract once the watermark passes them — bounded downstream state
    bounded = apply_behavior_nodes(t, None, pw.this.t, "t", False)
    res = bounded.groupby(pw.this.word).reduce(
        pw.this.word, c=pw.reducers.count()
    )
    pw.io.subscribe(res, on_change=lambda **kw: None)
    assert not pw.analyze().by_id("unbounded-state")


def test_unbounded_state_downgrades_to_info_with_spill_budget(monkeypatch):
    monkeypatch.setenv("PATHWAY_STATE_MEMORY_BUDGET_MB", "64")
    t = _stream_table()
    res = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
    pw.io.subscribe(res, on_change=lambda **kw: None)
    found = pw.analyze().by_id("unbounded-state")
    assert found and all(d.severity == "info" for d in found)


# ---------------------------------------------------------------------------
# nondeterministic-udf
# ---------------------------------------------------------------------------


def _rng_udf(x):
    import random

    return x + random.random()


def _time_udf(x):
    import time

    return x + time.time()


def test_nondeterministic_udf_fires_when_persisted():
    t = T("a\n1\n2")
    res = t.select(c=pw.apply_with_type(_rng_udf, float, pw.this.a))
    pw.io.subscribe(res, on_change=lambda **kw: None)
    cfg = Config.simple_config(Backend.memory("lint-nondet"))
    found = pw.analyze(persistence_config=cfg).by_id("nondeterministic-udf")
    assert found and found[0].severity == "error"
    assert "random" in found[0].message


def test_nondeterministic_time_udf_fires_for_exactly_once_sinks(tmp_path):
    t = T("a\n1\n2")
    res = t.select(c=pw.apply_with_type(_time_udf, float, pw.this.a))
    pw.io.csv.write(res, tmp_path / "out.csv")
    report = pw.analyze()  # transactional sink present, no persistence
    found = report.by_id("nondeterministic-udf")
    assert found and "time" in found[0].message


def test_nondeterministic_udf_quiet_without_persistence_or_sinks():
    t = T("a\n1\n2")
    res = t.select(c=pw.apply_with_type(_rng_udf, float, pw.this.a))
    pw.io.subscribe(res, on_change=lambda **kw: None)
    assert not pw.analyze().by_id("nondeterministic-udf")


def test_deterministic_uuid_parsing_quiet_but_uuid4_fires():
    def parse(s):
        import uuid

        return uuid.UUID(int=s).hex  # pure parsing: replays identically

    def mint(s):
        import uuid

        return uuid.uuid4().hex  # entropy: replay diverges

    cfg = Config.simple_config(Backend.memory("lint-uuid"))
    t = T("a\n1\n2")
    res = t.select(c=pw.apply_with_type(parse, str, pw.this.a))
    pw.io.subscribe(res, on_change=lambda **kw: None)
    assert not pw.analyze(persistence_config=cfg).by_id(
        "nondeterministic-udf"
    )
    G.clear()
    t = T("a\n1\n2")
    res = t.select(c=pw.apply_with_type(mint, str, pw.this.a))
    pw.io.subscribe(res, on_change=lambda **kw: None)
    found = pw.analyze(persistence_config=cfg).by_id("nondeterministic-udf")
    assert found and "uuid4" in found[0].message


def test_pure_udf_quiet_when_persisted():
    t = T("a\n1\n2")
    res = t.select(c=pw.apply_with_type(lambda x: x * 2, int, pw.this.a))
    pw.io.subscribe(res, on_change=lambda **kw: None)
    cfg = Config.simple_config(Backend.memory("lint-pure"))
    assert not pw.analyze(persistence_config=cfg).by_id(
        "nondeterministic-udf"
    )


# ---------------------------------------------------------------------------
# perrow-udf (dispatch tax)
# ---------------------------------------------------------------------------

_LOOKUP = {1: "one", 2: "two"}


def test_perrow_udf_fires_with_refusal_reason():
    t = T("a\n1\n2")
    res = t.select(
        c=pw.apply_with_type(lambda x: _LOOKUP[x], str, pw.this.a)
    )
    pw.io.subscribe(res, on_change=lambda **kw: None)
    found = pw.analyze().by_id("perrow-udf")
    assert found, "global-lookup UDF must be flagged as per-row"
    # the exact refusal reason from the lift ladder is surfaced
    assert "_LOOKUP" in found[0].message or "LOAD_GLOBAL" in found[0].message


def test_lifted_udf_quiet():
    t = T("a\n1\n2")
    res = t.select(c=pw.apply_with_type(lambda x: x * 2 + 1, int, pw.this.a))
    pw.io.subscribe(res, on_change=lambda **kw: None)
    assert not pw.analyze().by_id("perrow-udf")


def test_traceable_udf_quiet():
    # refused by the static lift (eval has no source) but traceable at
    # runtime: not a dispatch-tax finding
    fn = eval("lambda x: x * 3")
    t = T("a\n1\n2")
    res = t.select(c=pw.apply_with_type(fn, int, pw.this.a))
    pw.io.subscribe(res, on_change=lambda **kw: None)
    assert not pw.analyze().by_id("perrow-udf")


# ---------------------------------------------------------------------------
# fusion-chain
# ---------------------------------------------------------------------------


def test_fusion_chain_reported_for_pure_select_filter_select():
    t = T("a\n1\n2\n3")
    res = (
        t.select(b=pw.this.a * 2)
        .filter(pw.this.b > 2)
        .select(c=pw.this.b + 1)
    )
    pw.io.subscribe(res, on_change=lambda **kw: None)
    found = pw.analyze().by_id("fusion-chain")
    assert found and all(d.severity == "info" for d in found)
    assert any("Filter" in d.message for d in found)


def test_fusion_chain_absent_for_single_node():
    t = T("a\n1\n2")
    res = t.select(b=pw.this.a * 2)
    pw.io.subscribe(res, on_change=lambda **kw: None)
    assert not pw.analyze().by_id("fusion-chain")


# ---------------------------------------------------------------------------
# shard-skew
# ---------------------------------------------------------------------------


def test_shard_skew_fires_on_bool_key_at_four_workers():
    t = T("a\n1\n2\n3")
    flagged = t.select(flag=pw.this.a > 1, a=pw.this.a)
    res = flagged.groupby(pw.this.flag).reduce(
        pw.this.flag, c=pw.reducers.count()
    )
    pw.io.subscribe(res, on_change=lambda **kw: None)
    found = pw.analyze(n_workers=4).by_id("shard-skew")
    assert found and "2 distinct" in found[0].message


def test_shard_skew_quiet_on_string_key():
    t = T("word\nfoo\nbar")
    res = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
    pw.io.subscribe(res, on_change=lambda **kw: None)
    assert not pw.analyze(n_workers=4).by_id("shard-skew")


def test_shard_skew_quiet_single_worker():
    t = T("a\n1\n2")
    flagged = t.select(flag=pw.this.a > 1)
    res = flagged.groupby(pw.this.flag).reduce(
        pw.this.flag, c=pw.reducers.count()
    )
    pw.io.subscribe(res, on_change=lambda **kw: None)
    assert not pw.analyze(n_workers=1).by_id("shard-skew")


def test_shard_skew_fires_on_bool_join_key():
    t = T("a\n1\n2")
    l = t.select(flag=pw.this.a > 1, a=pw.this.a)
    r = t.select(flag=pw.this.a > 0, b=pw.this.a)
    res = l.join(r, l.flag == r.flag).select(pw.left.a, pw.right.b)
    pw.io.subscribe(res, on_change=lambda **kw: None)
    found = pw.analyze(n_workers=4).by_id("shard-skew")
    assert any("Join" in d.message for d in found)


# ---------------------------------------------------------------------------
# sink misconfiguration
# ---------------------------------------------------------------------------


def test_sink_no_persistence_fires_and_clears(tmp_path):
    t = T("a\n1")
    pw.io.csv.write(t, tmp_path / "out.csv")
    assert pw.analyze().by_id("sink-no-persistence")
    cfg = Config.simple_config(Backend.memory("lint-sinks"))
    assert not pw.analyze(persistence_config=cfg).by_id(
        "sink-no-persistence"
    )


def test_sink_name_collision_on_shared_basename(tmp_path):
    t = T("a\n1")
    (tmp_path / "x").mkdir()
    (tmp_path / "y").mkdir()
    pw.io.csv.write(t, tmp_path / "x" / "out.csv")
    pw.io.csv.write(t, tmp_path / "y" / "out.csv")
    found = pw.analyze().by_id("sink-name-collision")
    assert found and "registration" in found[0].message


def test_sink_name_collision_quiet_with_explicit_names(tmp_path):
    t = T("a\n1")
    (tmp_path / "x").mkdir()
    (tmp_path / "y").mkdir()
    pw.io.csv.write(t, tmp_path / "x" / "out.csv", name="first")
    pw.io.csv.write(t, tmp_path / "y" / "out.csv", name="second")
    assert not pw.analyze().by_id("sink-name-collision")


def test_dlq_collision_with_persistence_root(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_SINK_DLQ_DIR", str(tmp_path / "store"))
    t = T("a\n1")
    pw.io.csv.write(t, tmp_path / "out.csv")
    cfg = Config.simple_config(Backend.filesystem(str(tmp_path / "store")))
    found = pw.analyze(persistence_config=cfg).by_id("dlq-collision")
    assert found and "persistence root" in found[0].message


def test_dlq_collision_quiet_with_distinct_dirs(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_SINK_DLQ_DIR", str(tmp_path / "dlq"))
    t = T("a\n1")
    pw.io.csv.write(t, tmp_path / "out.csv")
    cfg = Config.simple_config(Backend.filesystem(str(tmp_path / "store")))
    assert not pw.analyze(persistence_config=cfg).by_id("dlq-collision")


# ---------------------------------------------------------------------------
# operator fingerprints
# ---------------------------------------------------------------------------


def _fp_pipeline(extra_filter: bool = False):
    G.clear()
    t = T("word | n\nfoo | 1\nbar | 2")
    res = t.groupby(pw.this.word).reduce(
        pw.this.word, s=pw.reducers.sum(pw.this.n)
    )
    if extra_filter:
        res = res.filter(pw.this.s > 0)
    pw.io.subscribe(res, on_change=lambda **kw: None)
    report = pw.analyze()
    G.clear()
    return report.fingerprints


def test_fingerprints_stable_across_two_compiles():
    first = _fp_pipeline()
    second = _fp_pipeline()
    assert first == second
    assert first, "fingerprints must not be empty"


def test_fingerprints_change_when_graph_changes():
    base = _fp_pipeline()
    changed = _fp_pipeline(extra_filter=True)
    assert base != changed
    # the untouched upstream prefix keeps its identity
    shared = set(base) & set(changed)
    assert any(base[k] == changed[k] for k in shared)


@pytest.mark.slow
def test_fingerprints_stable_across_processes():
    """The graph-migration contract: the SAME script fingerprints
    identically in two different interpreters, even under different
    hash randomization (set-literal constants in UDF bytecode repr in
    hash order — the canonicalizer must neutralize that)."""
    import json
    import os
    import subprocess
    import sys

    script = (
        "import os; os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "import json\n"
        "import pathway_tpu as pw\n"
        "from pathway_tpu.testing import T\n"
        "t = T('a\\nalpha\\nbeta')\n"
        "res = t.select(c=pw.apply_with_type(\n"
        "    lambda s: s in {'alpha', 'beta', 'gamma', 'delta'},\n"
        "    bool, pw.this.a))\n"
        "pw.io.subscribe(res, on_change=lambda **kw: None)\n"
        "print(json.dumps(pw.analyze().fingerprints))\n"
    )

    def run(seed):
        env = {**os.environ, "PYTHONHASHSEED": seed, "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable, "-c", script], env=env,
            capture_output=True, text=True, timeout=240,
        )
        assert out.returncode == 0, out.stderr
        return json.loads(out.stdout.strip().splitlines()[-1])

    assert run("1") == run("42")


def test_fingerprints_distinguish_expression_change():
    def build(mult):
        G.clear()
        t = T("a\n1\n2")
        res = t.select(b=pw.this.a * mult)
        pw.io.subscribe(res, on_change=lambda **kw: None)
        report = pw.analyze()
        G.clear()
        return report.fingerprints

    assert build(2) != build(3)


# ---------------------------------------------------------------------------
# report surface
# ---------------------------------------------------------------------------


def test_report_json_and_exit_codes(tmp_path):
    t = _stream_table()
    res = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
    pw.io.subscribe(res, on_change=lambda **kw: None)
    report = pw.analyze()
    doc = report.to_dict()
    assert doc["summary"]["warning"] >= 1
    assert report.exit_code() == 1
    assert report.exit_code(fail_on="error") == 0
    assert report.exit_code(fail_on="never") == 0
    assert all("id" in d and "severity" in d for d in doc["diagnostics"])


def test_analyze_counts_operators():
    t = T("a\n1")
    res = t.select(b=pw.this.a + 1)
    pw.io.subscribe(res, on_change=lambda **kw: None)
    report = pw.analyze()
    assert report.stats["operators"] >= 2
    assert report.stats["plain_sinks"] == 1
