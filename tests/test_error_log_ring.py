"""Error-log retention (ISSUE 5 satellite): the process error log is a
ring buffer with a monotonic base index — live ``pw.global_error_log()``
tables keep receiving rows past 1000 lifetime entries instead of
freezing at the cap."""

from __future__ import annotations

import pytest

from pathway_tpu.engine.error import _ErrorLog


def test_ring_retains_newest_past_cap():
    log = _ErrorLog(max_kept=10, max_logged=0)
    for i in range(25):
        log.record(f"e{i}", "ctx")
    assert log.total == 25
    kept = [m for m, _ in log.entries()]
    assert kept == [f"e{i}" for i in range(15, 25)]
    assert log.next_index == 25


def test_entries_since_tracks_lifetime_indices():
    log = _ErrorLog(max_kept=5, max_logged=0)
    for i in range(3):
        log.record(f"e{i}", "c")
    start, new, nxt = log.entries_since(0)
    assert (start, nxt) == (0, 3)
    assert [m for m, _, _ in new] == ["e0", "e1", "e2"]
    # poll again: nothing new
    start, new, nxt = log.entries_since(nxt)
    assert new == [] and nxt == 3
    # fall behind more than the cap: the window reports the gap honestly
    for i in range(3, 20):
        log.record(f"e{i}", "c")
    start, new, nxt = log.entries_since(3)
    assert start == 15  # e3..e14 fell off the ring
    assert [m for m, _, _ in new] == [f"e{i}" for i in range(15, 20)]
    assert nxt == 20


def test_error_log_table_polls_past_the_cap():
    """The live error-log source keeps emitting after 1000+ lifetime
    entries (used to freeze: entries stopped being appended at the cap)."""
    from pathway_tpu.engine.error import ERROR_LOG
    from pathway_tpu.internals.error_log_table import _ErrorLogSource

    ERROR_LOG.clear()
    try:
        src = _ErrorLogSource(["message", "context"])
        total_seen = 0
        # three waves, far past the 1000-entry retention cap
        for wave in range(3):
            for i in range(600):
                ERROR_LOG.record(f"w{wave}-{i}", "t")
            deltas = src.poll()
            rows = sum(len(d) for d in deltas)
            total_seen += rows
            assert rows == 600, (
                f"wave {wave}: poll returned {rows} of 600 entries"
            )
            assert src.is_finished()
        assert total_seen == 1800
        # keys are collision-free across the whole lifetime
    finally:
        ERROR_LOG.clear()


def test_lagging_poller_skips_evicted_entries_without_crashing():
    from pathway_tpu.engine.error import ERROR_LOG
    from pathway_tpu.internals.error_log_table import _ErrorLogSource

    ERROR_LOG.clear()
    try:
        src = _ErrorLogSource(["message", "context"])
        for i in range(2500):  # cap is 1000: oldest 1500 evicted
            ERROR_LOG.record(f"m{i}", "t")
        deltas = src.poll()
        rows = sum(len(d) for d in deltas)
        assert rows == 1000  # the retained window, newest entries
        msgs = [m for d in deltas for m in d.data["message"].tolist()]
        assert msgs[0] == "m1500" and msgs[-1] == "m2499"
        assert src.is_finished()
    finally:
        ERROR_LOG.clear()
