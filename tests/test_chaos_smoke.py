"""Tier-1 wrapper around scripts/chaos_smoke.py (like test_obs_smoke):
the supervised crash-recovery loop — fault plan SIGKILLs worker 1
mid-run, `spawn --supervise` restarts from the last common snapshot, and
the final groupby counts are exact."""

import os
import sys

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
    ),
)


def test_chaos_smoke(tmp_path):
    from chaos_smoke import EXPECTED, run_smoke

    result = run_smoke(workdir=str(tmp_path))
    assert result["final"] == EXPECTED
    assert result["generations"] == [0, 1]


def test_chaos_smoke_profiler_survives_crash_loop(tmp_path):
    # monitoring server + sampling profiler armed: the supervised
    # crash-recovery loop must still converge (no wedged teardown), the
    # crashed generation's bundle must carry profile.top deposits, and
    # the restarted generation must re-arm a fresh sampler
    from chaos_smoke import EXPECTED, run_profiler_chaos_smoke

    result = run_profiler_chaos_smoke(workdir=str(tmp_path))
    assert result["final"] == EXPECTED
    assert result["generations"] == [0, 1]
    assert result["profiler"]["gen0_deposits"] >= 1
    assert result["profiler"]["gen1_deposits"] >= 1
