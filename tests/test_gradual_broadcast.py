"""_gradual_broadcast (reference ``gradual_broadcast.rs:65`` +
``tests/test_gradual_broadcast.py``): a threshold ladder splits keys
between ``lower`` and ``upper`` apx values proportionally to
(value-lower)/(upper-lower), and a moving threshold flips only the
crossed keys."""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def _rows(table):
    cap = pw.internals.graph_runner.GraphRunner().run_tables(table)[0]
    names = table.column_names()
    return {
        tuple(r)[names.index("val")]: tuple(r)[names.index("apx_value")]
        for _, r in cap.state.iter_items()
    }


def _tab(n=200):
    return T("\n".join(["val"] + [str(10 * (i + 1)) for i in range(n)]))


def test_split_fraction_tracks_value():
    tab = _tab()
    for value, want in ((20.5, 0.0), (25.5, 0.5), (30.5, 1.0)):
        G.clear()
        tab = _tab()
        thr = T(f"lower | value | upper\n20.5 | {value} | 30.5")
        ext = tab._gradual_broadcast(thr, thr.lower, thr.value, thr.upper)
        got = _rows(ext)
        assert len(got) == 200
        frac_upper = sum(1 for v in got.values() if v == 30.5) / len(got)
        assert abs(frac_upper - want) <= 0.1, (value, frac_upper)
        assert set(got.values()) <= {20.5, 30.5}


def test_value_at_lower_gives_no_upper():
    tab = _tab(50)
    thr = T("lower | value | upper\n10.0 | 10.0 | 20.0")
    ext = tab._gradual_broadcast(thr, thr.lower, thr.value, thr.upper)
    assert set(_rows(ext).values()) == {10.0}


def test_monotone_flips_only_crossed_band():
    """A threshold sweep emits changes ONLY for keys in the crossed band —
    the whole point of the operator (vs. rejoining the threshold row, which
    would re-emit every key on every move)."""
    from pathway_tpu.engine.delta import Delta, rows_to_columns
    from pathway_tpu.engine.operators import GradualBroadcast, StaticSource

    keys = np.arange(1, 301, dtype=np.uint64) * 7919
    main = StaticSource(keys, {"x": np.arange(300)})
    thr_src = StaticSource(np.array([1], dtype=np.uint64), {
        "__l": np.array([0.0]), "__v": np.array([0.0]), "__u": np.array([1.0]),
    })
    node = GradualBroadcast(main, thr_src, ("__l", "__v", "__u"))

    def thr_delta(old_v, new_v):
        rows, diffs = [], []
        if old_v is not None:
            rows.append((0.0, old_v, 1.0))
            diffs.append(-1)
        rows.append((0.0, new_v, 1.0))
        diffs.append(1)
        return Delta(
            keys=np.array([1] * len(rows), dtype=np.uint64),
            data=rows_to_columns(rows, ["__l", "__v", "__u"]),
            diffs=np.array(diffs, dtype=np.int64),
        )

    main_delta = Delta(keys=keys, data={"x": np.arange(300)})
    out0 = node.process(0, [main_delta, thr_delta(None, 0.3)])
    ups0 = sum(1 for _, r, d in out0.iter_rows() if d > 0 and r[0] == 1.0)
    assert abs(ups0 / 300 - 0.3) < 0.1

    # sweep 0.3 -> 0.5: only the band's keys change
    out1 = node.process(2, [None, thr_delta(0.3, 0.5)])
    changes = list(out1.iter_rows())
    n_flipped = sum(1 for _, r, d in changes if d > 0)
    assert 0 < n_flipped < 120  # ~20% of 300, not all 300
    assert all(r[0] in (0.0, 1.0) for _, r, _ in changes)
    ups_total = ups0 + sum(
        (1 if d > 0 else -1) for _, r, d in changes if r[0] == 1.0
    )
    assert abs(ups_total / 300 - 0.5) < 0.1

    # sweep back down retracts exactly the same band
    out2 = node.process(4, [None, thr_delta(0.5, 0.3)])
    back = sum(1 for _, r, d in out2.iter_rows() if d > 0 and r[0] == 0.0)
    assert back == n_flipped


def test_same_tick_row_update_keeps_key_tracked():
    """(retract old row, insert new row) of one key in one tick must net to
    zero apx output and keep the key in operator state (review r3)."""
    from pathway_tpu.engine.delta import Delta, rows_to_columns
    from pathway_tpu.engine.operators import GradualBroadcast, StaticSource

    main = StaticSource(np.array([], dtype=np.uint64), {"x": np.array([])})
    thr_src = StaticSource(np.array([1], dtype=np.uint64), {
        "__l": np.array([0.0]), "__v": np.array([1.0]), "__u": np.array([1.0]),
    })
    node = GradualBroadcast(main, thr_src, ("__l", "__v", "__u"))
    thr = Delta(
        keys=np.array([1], dtype=np.uint64),
        data=rows_to_columns([(0.0, 1.0, 1.0)], ["__l", "__v", "__u"]),
    )
    node.process(0, [None, thr])
    node.process(2, [Delta(keys=np.array([55], dtype=np.uint64),
                           data={"x": np.array([1])}), None])
    update = Delta(
        keys=np.array([55, 55], dtype=np.uint64),
        data={"x": np.array([1, 2])},
        diffs=np.array([-1, 1], dtype=np.int64),
    )
    out = node.process(4, [update, None])
    assert out is None or len(out) == 0  # net zero: apx row unchanged
    assert list(node._keys) == [55]  # key still tracked
    # and it still participates in later threshold sweeps
    move = Delta(
        keys=np.array([1, 1], dtype=np.uint64),
        data=rows_to_columns(
            [(0.0, 1.0, 1.0), (0.0, 0.0, 1.0)], ["__l", "__v", "__u"]
        ),
        diffs=np.array([-1, 1], dtype=np.int64),
    )
    out2 = node.process(6, [None, move])
    assert out2 is not None and len(out2) == 2  # flips upper -> lower


def test_key_insert_and_retract_under_threshold():
    from pathway_tpu.engine.delta import Delta, rows_to_columns
    from pathway_tpu.engine.operators import GradualBroadcast, StaticSource

    main = StaticSource(np.array([], dtype=np.uint64), {"x": np.array([])})
    thr_src = StaticSource(np.array([1], dtype=np.uint64), {
        "__l": np.array([0.0]), "__v": np.array([1.0]), "__u": np.array([1.0]),
    })
    node = GradualBroadcast(main, thr_src, ("__l", "__v", "__u"))
    thr = Delta(
        keys=np.array([1], dtype=np.uint64),
        data=rows_to_columns([(0.0, 1.0, 1.0)], ["__l", "__v", "__u"]),
    )
    node.process(0, [None, thr])
    add = Delta(keys=np.array([55], dtype=np.uint64), data={"x": np.array([1])})
    (row,) = list(node.process(2, [add, None]).iter_rows())
    assert row[1] == (1.0,) and row[2] == 1  # value==upper -> all upper
    drop = Delta(
        keys=np.array([55], dtype=np.uint64), data={"x": np.array([1])},
        diffs=np.array([-1], dtype=np.int64),
    )
    (row,) = list(node.process(4, [drop, None]).iter_rows())
    assert row[2] == -1
