"""Tier-1 wiring for the exactly-once output-plane smoke
(``scripts/sink_smoke.py``): seeded flaky-sink and SIGKILL-mid-delivery
runs are multiset-equal to a clean run with zero duplicate deliveries;
a sink outage degrades to bounded buffering + backpressure and drains on
recovery; seeded poison rows land in the dead-letter queue."""

from __future__ import annotations

import collections
import os
import sys

import pytest

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ),
)

import sink_smoke  # noqa: E402


@pytest.fixture(scope="module")
def smoke_dir(tmp_path_factory) -> str:
    return str(tmp_path_factory.mktemp("sink-smoke"))


@pytest.fixture(scope="module")
def baseline(smoke_dir) -> collections.Counter:
    # one clean run shared by every scenario: the multiset ground truth
    return sink_smoke.scenario_clean(smoke_dir)


def test_outage_backpressure_and_drain():
    report = sink_smoke.scenario_outage()
    assert report["max_depth"] <= 4
    assert report["retries"] > 0


def test_clean_and_flaky_multiset_equal(smoke_dir, baseline):
    report = sink_smoke.scenario_flaky(smoke_dir, baseline)
    assert report["retries"] > 0


def test_sigkill_mid_delivery_no_double_deliver(smoke_dir, baseline):
    report = sink_smoke.scenario_kill(smoke_dir, baseline)
    assert 0 < report["rows_before_kill"] < report["rows_total"]


def test_dlq_captures_poison_rows(smoke_dir, baseline):
    report = sink_smoke.scenario_dlq(smoke_dir, baseline)
    assert report["dlq_rows"] >= 1


@pytest.mark.slow
def test_sharded_delivery_multiset_equal(smoke_dir, baseline):
    report = sink_smoke.scenario_sharded(smoke_dir, baseline)
    assert report["rows"] == sum(baseline.values())
