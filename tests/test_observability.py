"""End-to-end engine observability (pathway_tpu/observability/): log-bucket
histograms, OpenMetrics rendering + label escaping, /healthz and /readyz
probe semantics (startup → steady state → wedged fault), cluster roll-up,
latency-staleness companion gauge, and the periodic OTLP flusher.

Reference being reproduced: the engine telemetry pair
(src/engine/telemetry.rs:47-156, src/engine/http_server.rs:21-60)."""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.engine.executor import EngineStats
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.observability import (
    LogHistogram,
    ObservabilityHub,
    health_status,
    merge_snapshots,
    parse_exposition,
    quantile_from_snapshot,
    ready_status,
    render_snapshots,
    stats_snapshot,
)


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def _get(url: str):
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# -- histogram primitive -----------------------------------------------------


def test_histogram_observe_and_quantiles():
    h = LogHistogram()
    for v in [100, 200, 400, 800, 100_000, 1_000_000]:
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 6
    assert snap["sum"] == 100 + 200 + 400 + 800 + 100_000 + 1_000_000
    # p50 lands in the low-hundreds bucket, p99 near the max bucket
    assert h.quantile(0.5) < 1000
    assert h.quantile(0.99) > 500_000
    pcts = h.percentiles()
    assert pcts["p50"] <= pcts["p95"] <= pcts["p99"]


def test_histogram_merge_is_exact():
    a, b = LogHistogram(), LogHistogram()
    for v in [10, 20, 30]:
        a.observe(v)
    for v in [40, 50]:
        b.observe(v)
    merged = merge_snapshots([a.snapshot(), b.snapshot()])
    assert merged["count"] == 5
    assert merged["sum"] == 150
    one = LogHistogram()
    for v in [10, 20, 30, 40, 50]:
        one.observe(v)
    assert merged["counts"] == one.snapshot()["counts"]


def test_histogram_edge_values():
    h = LogHistogram()
    h.observe(0)
    h.observe(-5)  # clamped, not a crash
    h.observe(1 << 100)  # clamped into the top bucket
    snap = h.snapshot()
    assert snap["count"] == 3
    assert quantile_from_snapshot(snap, 0.01) == 0.0


# -- exposition rendering ----------------------------------------------------


def _stats_with_activity() -> EngineStats:
    s = EngineStats()
    s.ticks = 4
    s.input_rows = 10
    s.output_rows = 7
    s.rows_total = 17
    s.rows_by_node = {'Rowwise#3': 10}
    s.tick_duration.observe(2_000_000)
    s.tick_duration.observe(4_000_000)
    return s


def test_label_escaping_openmetrics():
    s = _stats_with_activity()
    s.rows_by_node = {'Op"quote\\back\nline#9': 3}
    from pathway_tpu.engine.http_server import _render_metrics

    body = _render_metrics(s)
    assert 'operator="Op\\"quote\\\\back\\nline#9"' in body
    # round-trips through the parser back to the original label
    series = parse_exposition(body)
    ops = {
        dict(labels).get("operator")
        for (name, labels) in series
        if name == "pathway_operator_rows_total"
    }
    assert ops == {'Op"quote\\back\nline#9'}


def test_single_worker_renders_unlabeled():
    # the seed's single-process format: existing scrapers match
    # bare `pathway_input_rows N`
    body = render_snapshots([stats_snapshot(_stats_with_activity())])
    assert "pathway_input_rows 10" in body
    assert 'worker="' not in body


def test_multi_worker_renders_labels_and_frontier_lag():
    a, b = _stats_with_activity(), _stats_with_activity()
    a.last_time = 5000
    b.last_time = 2000
    body = render_snapshots(
        [stats_snapshot(a, 0), stats_snapshot(b, 1)],
        comm_stats={"0": {"cluster_inbox_depth": 2.0}},
    )
    series = parse_exposition(body)
    assert series[("pathway_frontier_lag_ms", (("worker", "0"),))] == 0
    assert series[("pathway_frontier_lag_ms", (("worker", "1"),))] == 3000
    assert series[
        ("pathway_comm_cluster_inbox_depth", (("process", "0"),))
    ] == 2.0
    assert series[("pathway_cluster_workers", ())] == 2


def test_histogram_rendering_monotone_and_consistent():
    body = render_snapshots([stats_snapshot(_stats_with_activity())])
    series = parse_exposition(body)
    pts = sorted(
        (float("inf") if dict(l)["le"] == "+Inf" else float(dict(l)["le"]), v)
        for (n, l) in series
        if n == "pathway_tick_duration_seconds_bucket"
        for v in [series[(n, l)]]
    )
    counts = [v for _, v in pts]
    assert counts == sorted(counts)
    assert pts[-1][1] == series[("pathway_tick_duration_seconds_count", ())]
    assert series[("pathway_tick_duration_seconds_sum", ())] == pytest.approx(
        0.006
    )


# -- latency staleness companion ---------------------------------------------


def test_latency_age_gauge_tracks_staleness():
    s = EngineStats()
    wall_ms = int(time.time() * 1000)
    s.note_tick(wall_ms + 2)  # wall-clock commit → latency gauge updates
    assert s.latency_ms is not None
    s.latency_updated_at -= 7.5  # simulate 7.5s with no further commits
    snap = stats_snapshot(s)
    assert snap["latency_age_s"] == pytest.approx(7.5, abs=0.5)
    body = render_snapshots([snap])
    series = parse_exposition(body)
    assert series[
        ("pathway_output_latency_age_seconds", ())
    ] == pytest.approx(7.5, abs=0.5)
    # histogram companion recorded the commit latency too
    assert snap["latency_hist"]["count"] == 1


# -- probe semantics ---------------------------------------------------------


def test_probe_lifecycle_startup_steady_wedged():
    s = EngineStats()
    # startup: sources not yet connected, no ticks
    ok, detail = ready_status([s])
    assert not ok and "sources not connected" in detail["reasons"]
    s.sources_connected = True
    ok, detail = ready_status([s])
    assert not ok and "first frontier not advanced" in detail["reasons"]
    assert health_status([s], wedge_timeout_s=30)[0]  # alive while starting
    # steady state
    s.note_tick(10)
    assert ready_status([s])[0]
    assert health_status([s], wedge_timeout_s=30)[0]
    # wedged fault: heartbeat goes stale while unfinished
    s.last_heartbeat -= 120
    ok, detail = health_status([s], wedge_timeout_s=30)
    assert not ok and detail["status"] == "wedged"
    # a finished run can never be wedged
    s.finished = True
    assert health_status([s], wedge_timeout_s=30)[0]


def test_probe_endpoints_serve_status_codes():
    from pathway_tpu.engine.http_server import start_http_server

    s = EngineStats()
    hub = ObservabilityHub(wedge_timeout_s=30)
    hub.register_worker(0, s)
    server, _ = start_http_server(hub, port=0)
    port = server.server_address[1]
    try:
        assert _get(f"http://127.0.0.1:{port}/healthz")[0] == 200
        code, body = _get(f"http://127.0.0.1:{port}/readyz")
        assert code == 503 and "starting" in body
        s.sources_connected = True
        s.note_tick(3)
        assert _get(f"http://127.0.0.1:{port}/readyz")[0] == 200
        # inject the wedge fault
        s.last_heartbeat -= 300
        code, body = _get(f"http://127.0.0.1:{port}/healthz")
        assert code == 503 and "wedged" in body
        # /snapshot serves the raw JSON document
        code, body = _get(f"http://127.0.0.1:{port}/snapshot")
        assert code == 200
        doc = json.loads(body)
        assert doc["workers"][0]["ticks"] == 1
    finally:
        server.shutdown()
        server.server_close()


def test_probes_through_live_streaming_run(monkeypatch):
    """startup → steady state → wedged-executor fault against a real
    engine run. The wedge is genuine: a subscriber callback blocks inside
    a tick, so the executor thread stops heartbeating mid-sweep and
    /healthz must flip to 503 once the (shortened) wedge timeout lapses,
    then recover when the callback unblocks."""
    release = threading.Event()
    seen = threading.Event()
    go_poison = threading.Event()
    wedge = threading.Event()  # set → next on_change blocks
    unwedge = threading.Event()
    results: dict = {}

    class S(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(3):
                self.next(x=i)
                self.commit()
            go_poison.wait(timeout=20)
            self.next(x=100)
            self.commit()
            release.wait(timeout=20)

    t = pw.io.python.read(S(), schema=pw.schema_from_types(x=int))
    out = t.reduce(s=pw.reducers.sum(pw.this.x))

    def on_change(**kw):
        seen.set()
        if wedge.is_set():
            unwedge.wait(timeout=20)  # executor thread blocked mid-tick

    pw.io.subscribe(out, on_change=on_change)

    from pathway_tpu.internals.run import _current

    def probe():
        try:
            assert seen.wait(timeout=15)
            time.sleep(0.2)
            server = _current["runner"]._http_server_for_tests
            port = server.server_address[1]
            results["readyz"] = _get(f"http://127.0.0.1:{port}/readyz")
            results["healthz"] = _get(f"http://127.0.0.1:{port}/healthz")
            results["metrics"] = _get(f"http://127.0.0.1:{port}/metrics")
            # inject the wedge: the poison row's callback blocks the
            # executor inside its tick, past the 0.5s wedge timeout
            wedge.set()
            go_poison.set()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                results["wedged"] = _get(f"http://127.0.0.1:{port}/healthz")
                if results["wedged"][0] == 503:
                    break
                time.sleep(0.2)
            wedge.clear()
            unwedge.set()
            time.sleep(0.3)  # executor resumes heartbeating
            results["recovered"] = _get(f"http://127.0.0.1:{port}/healthz")
        finally:
            release.set()
            unwedge.set()
            pw.request_stop()

    th = threading.Thread(target=probe, daemon=True)
    th.start()

    monkeypatch.setenv("PATHWAY_MONITORING_HTTP_PORT", "0")  # ephemeral
    monkeypatch.setenv("PATHWAY_HEALTH_WEDGE_S", "0.5")
    pw.run(with_http_server=True)
    th.join(timeout=30)
    assert results["readyz"][0] == 200
    assert results["healthz"][0] == 200
    assert results["wedged"][0] == 503, results["wedged"]
    assert results["recovered"][0] == 200, results["recovered"]
    series = parse_exposition(results["metrics"][1])
    assert series[("pathway_input_rows", ())] == 3
    assert any(
        n == "pathway_tick_duration_seconds_bucket" for n, _ in series
    )


# -- cluster roll-up ---------------------------------------------------------


def test_cluster_rollup_scrapes_peer_processes():
    """Two hubs simulate two processes: process 1 serves /snapshot,
    process 0 scrapes it and renders the merged per-worker view."""
    from pathway_tpu.engine.http_server import start_http_server

    peer_stats = _stats_with_activity()
    peer_hub = ObservabilityHub(process_id=1, n_processes=2)
    peer_hub.register_worker(1, peer_stats)
    peer_server, _ = start_http_server(peer_hub, port=0)
    peer_port = peer_server.server_address[1]
    try:
        hub0 = ObservabilityHub(
            process_id=0,
            n_processes=2,
            peer_http=[("127.0.0.1", peer_port)],
        )
        hub0.register_worker(0, _stats_with_activity())
        body = hub0.render_metrics()
        series = parse_exposition(body)
        workers = {
            dict(l)["worker"]
            for (n, l) in series
            if n == "pathway_engine_ticks"
        }
        assert workers == {"0", "1"}
        assert series[("pathway_cluster_workers", ())] == 2
        # remote worker's histogram merged in with its label
        assert any(
            n == "pathway_tick_duration_seconds_bucket"
            and dict(l).get("worker") == "1"
            for (n, l) in series
        )
    finally:
        peer_server.shutdown()
        peer_server.server_close()


def test_cluster_rollup_tolerates_dead_peer():
    hub0 = ObservabilityHub(
        process_id=0, n_processes=2, peer_http=[("127.0.0.1", 1)]
    )
    hub0.register_worker(0, _stats_with_activity())
    body = hub0.render_metrics()  # must not raise
    series = parse_exposition(body)
    assert series[("pathway_cluster_scrape_errors", ())] >= 1
    assert series[("pathway_cluster_workers", ())] == 1


def test_sharded_threads_run_serves_merged_metrics():
    """A real PATHWAY_THREADS=2 run: /metrics carries worker=0 and
    worker=1 series including exchange backpressure counters."""
    import os

    release = threading.Event()
    seen = threading.Event()
    results: dict = {}

    class S(pw.io.python.ConnectorSubject):
        def run(self):
            for i in range(6):
                self.next(x=i)
                self.commit()
            release.wait(timeout=15)

    t = pw.io.python.read(S(), schema=pw.schema_from_types(x=int))
    # groupby forces Exchange nodes between the workers
    out = t.groupby(pw.this.x % 2).reduce(s=pw.reducers.sum(pw.this.x))
    pw.io.subscribe(out, on_change=lambda **kw: seen.set())

    port = 29137
    saved = {
        k: os.environ.get(k)
        for k in ("PATHWAY_THREADS", "PATHWAY_MONITORING_HTTP_PORT")
    }
    os.environ["PATHWAY_THREADS"] = "2"
    os.environ["PATHWAY_MONITORING_HTTP_PORT"] = str(port)

    def scrape():
        try:
            assert seen.wait(timeout=15)
            time.sleep(0.3)
            results["metrics"] = _get(f"http://127.0.0.1:{port}/metrics")
            results["readyz"] = _get(f"http://127.0.0.1:{port}/readyz")
        finally:
            release.set()
            pw.request_stop()

    th = threading.Thread(target=scrape, daemon=True)
    th.start()
    try:
        pw.run(with_http_server=True)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    th.join(timeout=15)
    series = parse_exposition(results["metrics"][1])
    workers = {
        dict(l)["worker"] for (n, l) in series if n == "pathway_engine_ticks"
    }
    assert workers == {"0", "1"}
    assert results["readyz"][0] == 200
    exch = [
        v for (n, l), v in series.items()
        if n == "pathway_exchange_batches_total"
    ]
    assert exch and all(v > 0 for v in exch)


# -- dashboard NONE regression ------------------------------------------------


def test_dashboard_none_level_is_noop(monkeypatch):
    import pathway_tpu.internals.monitoring as mon

    spawned = []
    monkeypatch.setattr(
        mon.threading,
        "Thread",
        lambda *a, **kw: spawned.append(1) or (_ for _ in ()).throw(
            AssertionError("NONE must not spawn a refresh thread")
        ),
    )
    stop = mon.start_dashboard(EngineStats(), mon.MonitoringLevel.NONE)
    stop()  # no-op stop returned immediately
    assert spawned == []
