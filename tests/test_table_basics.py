"""Core Table API: select/filter/expressions — mirrors the reference's
``test_common.py`` style (markdown tables + equality asserts)."""

import pytest

import pathway_tpu as pw
from pathway_tpu.testing import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
)


def test_static_table_roundtrip():
    t = T(
        """
        a | b
        1 | 2
        3 | 4
        """
    )
    assert t.column_names() == ["a", "b"]
    assert_table_equality(t, t)


def test_select_arithmetic():
    t = T(
        """
        a | b
        1 | 2
        3 | 4
        """
    )
    res = t.select(c=pw.this.a + pw.this.b)
    expected = T(
        """
        c
        3
        7
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_select_keeps_ids():
    t = T(
        """
        id | a
        1  | 10
        2  | 20
        """
    )
    res = t.select(b=pw.this.a * 2)
    expected = T(
        """
        id | b
        1  | 20
        2  | 40
        """
    )
    assert_table_equality(res, expected)


def test_filter():
    t = T(
        """
        id | a
        1  | 10
        2  | 25
        3  | 30
        """
    )
    res = t.filter(pw.this.a > 15)
    expected = T(
        """
        id | a
        2  | 25
        3  | 30
        """
    )
    assert_table_equality(res, expected)


def test_division_produces_float():
    t = T(
        """
        a | b
        6 | 3
        7 | 2
        """
    )
    res = t.select(q=pw.this.a / pw.this.b)
    expected = T(
        """
        q
        2.0
        3.5
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_comparison_and_bool_ops():
    t = T(
        """
        a | b
        1 | 2
        5 | 2
        3 | 3
        """
    )
    res = t.select(lt=pw.this.a < pw.this.b, both=(pw.this.a > 0) & (pw.this.b > 2))
    expected = T(
        """
        lt    | both
        True  | False
        False | False
        False | True
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_string_concat():
    t = T(
        """
        a     | b
        hello | world
        foo   | bar
        """
    )
    res = t.select(c=pw.this.a + pw.this.b)
    expected = T(
        """
        c
        helloworld
        foobar
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_if_else():
    t = T(
        """
        a
        1
        5
        3
        """
    )
    res = t.select(x=pw.if_else(pw.this.a > 2, pw.this.a * 10, pw.this.a))
    expected = T(
        """
        x
        1
        50
        30
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_with_columns():
    t = T(
        """
        id | a | b
        1  | 1 | 2
        2  | 3 | 4
        """
    )
    res = t.with_columns(c=pw.this.a + pw.this.b)
    expected = T(
        """
        id | a | b | c
        1  | 1 | 2 | 3
        2  | 3 | 4 | 7
        """
    )
    assert_table_equality(res, expected)


def test_rename_and_without():
    t = T(
        """
        id | a | b
        1  | 1 | 2
        """
    )
    res = t.rename_columns(c=pw.this.a).without("b")
    expected = T(
        """
        id | c
        1  | 1
        """
    )
    assert_table_equality(res, expected)


def test_apply_udf():
    t = T(
        """
        a
        1
        2
        """
    )

    @pw.udf
    def double(x: int) -> int:
        return 2 * x

    res = t.select(b=double(pw.this.a))
    expected = T(
        """
        b
        2
        4
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_apply_builtin():
    t = T(
        """
        a
        -1
        2
        """
    )
    res = t.select(b=pw.apply_with_type(abs, int, pw.this.a))
    expected = T(
        """
        b
        1
        2
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_optional_and_coalesce():
    t = T(
        """
        a
        1
        None
        3
        """
    )
    res = t.select(b=pw.coalesce(pw.this.a, 0))
    expected = T(
        """
        b
        1
        0
        3
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_is_none_filter():
    t = T(
        """
        a
        1
        None
        3
        """
    )
    res = t.filter(pw.this.a.is_not_none()).select(b=pw.unwrap(pw.this.a) + 1)
    expected = T(
        """
        b
        2
        4
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_str_namespace():
    t = T(
        """
        s
        Hello
        World
        """
    )
    res = t.select(
        lower=pw.this.s.str.lower(),
        n=pw.this.s.str.len(),
        sw=pw.this.s.str.startswith("He"),
    )
    expected = T(
        """
        lower | n | sw
        hello | 5 | True
        world | 5 | False
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_schema_class():
    class MySchema(pw.Schema):
        a: int
        b: str

    assert MySchema.column_names() == ["a", "b"]
    t = T(
        """
        a | b
        1 | x
        """,
        schema=MySchema,
    )
    pw.assert_table_has_schema(t, MySchema)


def test_foreign_column_same_universe():
    t = T(
        """
        id | a
        1  | 10
        2  | 20
        """
    )
    t2 = t.select(b=pw.this.a + 1)
    res = t2.select(c=t.a + pw.this.b)
    expected = T(
        """
        id | c
        1  | 21
        2  | 41
        """
    )
    assert_table_equality(res, expected)


def test_cast():
    t = T(
        """
        a
        1
        2
        """
    )
    res = t.select(f=pw.cast(float, pw.this.a), s=pw.cast(str, pw.this.a))
    expected = T(
        """
        f   | s
        1.0 | 1
        2.0 | 2
        """,
        schema=pw.schema_from_types(f=float, s=str),
    )
    assert_table_equality_wo_index(res, expected)


def test_make_tuple_and_get():
    t = T(
        """
        a | b
        1 | 2
        """
    )
    res = t.select(t=pw.make_tuple(pw.this.a, pw.this.b)).select(
        x=pw.this.t[0], y=pw.this.t.get(5, default=-1)
    )
    expected = T(
        """
        x | y
        1 | -1
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_compute_and_print(capsys):
    t = T(
        """
        a
        1
        """
    )
    pw.debug.compute_and_print(t)
    out = capsys.readouterr().out
    assert "a" in out and "1" in out


def test_row_error_values_and_fill_error():
    """Per-row UDF failures become Error values (reference Value::Error):
    the stream survives, fill_error recovers, unwrap refuses."""
    from pathway_tpu.engine.error import ERROR_LOG

    ERROR_LOG.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(x=int), [(1,), (0,), (4,)]
    )
    res = t.select(
        x=pw.this.x,
        inv=pw.apply(lambda v: 10 // v, pw.this.x),
    )
    recovered = res.select(
        x=pw.this.x,
        inv=pw.fill_error(pw.this.inv, -1),
    )
    df = pw.debug.table_to_pandas(recovered).sort_values("x")
    assert list(df["inv"]) == [-1, 10, 2]  # x=0 recovered to -1
    assert ERROR_LOG.total == 1
    [(msg, ctx)] = ERROR_LOG.entries()
    # a pure-operator lambda is AST-lifted into the columnar compiler, whose
    # div-by-zero message is the native binop's; the per-row interpreter
    # (untraceable lambdas) reports the exception class instead
    assert "ZeroDivisionError" in msg or "division by zero" in msg

    # raw (unrecovered) error renders as Error and never equals anything
    from pathway_tpu.internals.parse_graph import G as _G

    _G.clear()
    t = pw.debug.table_from_rows(pw.schema_from_types(x=int), [(0,)])
    res = t.select(inv=pw.apply(lambda v: 10 // v, pw.this.x))
    [val] = pw.debug.table_to_pandas(res)["inv"].tolist()
    assert repr(val) == "Error"

    # unwrap refuses error values
    _G.clear()
    t = pw.debug.table_from_rows(pw.schema_from_types(x=int), [(0,)])
    res = t.select(inv=pw.unwrap(pw.apply(lambda v: 10 // v, pw.this.x)))
    with pytest.raises(Exception, match="Error found in column"):
        pw.debug.table_to_pandas(res)


def test_error_values_propagate_through_expressions():
    t = pw.debug.table_from_rows(pw.schema_from_types(x=int), [(1,), (0,)])
    res = t.select(
        x=pw.this.x,
        y=pw.apply(lambda v: 10 // v, pw.this.x) + 1,  # binop over Error row
    )
    df = pw.debug.table_to_pandas(res).sort_values("x")
    vals = list(df["y"])
    assert repr(vals[0]) == "Error"  # x=0 row: error propagated, not crashed
    assert vals[1] == 11
    # an Error never equals anything, including itself
    assert (vals[0] == vals[0]) is False
