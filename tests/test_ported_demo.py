"""Ported from `/root/reference/python/pathway/tests/test_demo.py`:
pw.demo stream generators + csv replay."""

from __future__ import annotations

import pathlib

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T, assert_table_equality_wo_index


@pytest.fixture(autouse=True)
def _clean():
    G.clear()
    yield
    G.clear()


def test_generate_custom_stream():
    # reference test_demo.py:11
    value_functions = {
        "number": lambda x: x + 1,
        "name": lambda x: f"Person_{x}",
        "age": lambda x: 20 + x,
    }

    class InputSchema(pw.Schema):
        number: int
        name: str
        age: int

    table = pw.demo.generate_custom_stream(
        value_functions, schema=InputSchema, nb_rows=5, input_rate=1000
    )
    expected = T(
        """
        number | name | age
        1 | Person_0 | 20
        2 | Person_1 | 21
        3 | Person_2 | 22
        4 | Person_3 | 23
        5 | Person_4 | 24
        """
    )
    assert_table_equality_wo_index(table, expected)


@pytest.mark.parametrize("offset", [0, 10, -10])
def test_generate_range_stream(offset):
    # reference test_demo.py:39/:55/:71
    table = pw.demo.range_stream(nb_rows=5, offset=offset, input_rate=1000)
    expected = T(
        "value\n" + "\n".join(str(float(i + offset)) for i in range(5))
    )
    expected = expected.select(value=pw.cast(float, pw.this.value))
    assert_table_equality_wo_index(table, expected)


def test_generate_noisy_linear_stream():
    # reference test_demo.py:87
    table = pw.demo.noisy_linear_stream(nb_rows=5, input_rate=1000)
    expected = T("x\n0.0\n1.0\n2.0\n3.0\n4.0")
    expected = expected.select(x=pw.cast(float, pw.this.x))
    assert_table_equality_wo_index(table.select(pw.this.x), expected)


def test_demo_replay(tmp_path: pathlib.Path):
    # reference test_demo.py:105
    data = "number\n1\n2\n3\n4\n5\n"
    input_path = tmp_path / "in.csv"
    input_path.write_text(data)

    class InputSchema(pw.Schema):
        number: int

    table = pw.demo.replay_csv(
        str(input_path), schema=InputSchema, input_rate=1000
    )
    expected = T("number\n1\n2\n3\n4\n5")
    assert_table_equality_wo_index(table, expected)
