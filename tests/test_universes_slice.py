"""Universe solver, TableSlice, and concat disjointness enforcement
(reference ``internals/universe_solver.py`` / ``table_slice.py`` /
``Table._concat``)."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.universe_solver import UniverseSolver
from pathway_tpu.testing import T, assert_table_equality


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def test_solver_subset_transitivity_and_equality():
    s = UniverseSolver()
    a, b, c, d = "A", "B", "C", "D"
    s.register_as_subset(a, b)
    s.register_as_subset(b, c)
    assert s.query_is_subset(a, c)  # transitive closure
    assert not s.query_is_subset(c, a)
    s.register_as_equal(c, d)
    assert s.query_is_subset(a, d)
    assert s.query_are_equal(c, d)
    assert not s.query_are_equal(a, c)


def test_solver_disjointness_propagates_to_subsets():
    s = UniverseSolver()
    s.register_as_disjoint("L", "R")
    s.register_as_subset("l1", "L")
    s.register_as_subset("r1", "R")
    assert s.query_are_disjoint("l1", "r1")  # subsets of disjoint sets
    assert not s.query_are_disjoint("l1", "L")


def test_solver_intersection_difference():
    s = UniverseSolver()
    s.register_as_intersection("I", "A", "B")
    assert s.query_is_subset("I", "A") and s.query_is_subset("I", "B")
    s.register_as_difference("D", "A", "B")
    assert s.query_is_subset("D", "A")
    assert s.query_are_disjoint("D", "B")
    assert s.query_are_disjoint("D", "I")  # D∩B=∅ and I⊆B
    s.register_as_union("U", "A", "C")
    assert s.query_is_subset("A", "U")


def test_filter_universe_is_subset_and_usable_in_select():
    t = T(
        """
        a | b
        1 | 10
        2 | 20
        3 | 30
        """
    )
    f = t.filter(pw.this.a > 1)
    assert f._universe.is_subset_of(t._universe)
    # a filtered table can reference the parent's columns directly
    res = f.select(pw.this.a, big=t.b)
    cap = pw.internals.graph_runner.GraphRunner().run_tables(res)[0]
    assert sorted(tuple(r) for _, r in cap.state.iter_items()) == [
        (2, 20), (3, 30),
    ]


def test_concat_requires_disjointness_proof_or_promise():
    t1 = T("id | a\n1 | 1")
    t2 = T("id | a\n2 | 2")
    with pytest.raises(ValueError, match="might collide"):
        t1.concat(t2)
    pw.universes.promise_are_pairwise_disjoint(t1, t2)
    res = t1.concat(t2)
    assert_table_equality(res, T("id | a\n1 | 1\n2 | 2"))


def test_concat_of_difference_and_intersection_is_provably_disjoint():
    t = T(
        """
        id | a
        1  | 1
        2  | 2
        3  | 3
        """
    )
    sub = T(
        """
        id | a
        2  | 20
        3  | 30
        """
    ).promise_universe_is_subset_of(t)
    inter = t.intersect(sub)
    diff = t.difference(sub)
    # no promise needed: difference ∩ intersection = ∅ by construction
    res = diff.concat(inter)
    assert_table_equality(res, t)


def test_concat_runtime_collision_detection():
    """A false disjointness promise is caught by the engine, not silently
    merged."""
    t1 = T("id | a\n1 | 1\n7 | 7")
    t2 = T("id | a\n7 | 70")
    pw.universes.promise_are_pairwise_disjoint(t1, t2)
    res = t1.concat(t2)
    with pytest.raises(ValueError, match="live in more than one input"):
        pw.internals.graph_runner.GraphRunner().run_tables(res)


def test_concat_key_migration_within_tick_not_flagged():
    """A row moving between promised-disjoint partitions delivers -1 on one
    input and +1 on the other in the same tick — disjoint at every tick
    boundary, so the runtime check must not trip (either port order)."""
    from pathway_tpu.engine.delta import Delta, rows_to_columns
    from pathway_tpu.engine.operators import Concat, StaticSource
    import numpy as np

    def delta(key, diff):
        return Delta(
            keys=np.array([key], dtype=np.uint64),
            data=rows_to_columns([(1,)], ["a"]),
            diffs=np.array([diff], dtype=np.int64),
        )

    src = StaticSource(np.array([], dtype=np.uint64), {"a": np.array([])})
    node = Concat([src, src])
    node.process(0, [None, delta(7, 1)])  # key lives on port 1
    out = node.process(2, [delta(7, 1), delta(7, -1)])  # migrates to port 0
    assert out is not None and len(out) == 2
    out = node.process(4, [delta(7, -1), delta(7, 1)])  # and back
    assert out is not None
    with pytest.raises(ValueError, match="live in more than one"):
        node.process(6, [delta(7, 1), None])  # a REAL collision still trips


def test_proven_concat_is_stateless_passthrough():
    """Structurally-proven disjointness (difference ⊔ intersection) skips
    the runtime liveness state; promised-only keeps it."""
    from pathway_tpu.engine.operators import Concat
    from pathway_tpu.internals.graph_runner import GraphRunner

    t = T("id | a\n1 | 1\n2 | 2")
    sub = T("id | a\n2 | 20").promise_universe_is_subset_of(t)
    proven = t.difference(sub).concat(t.intersect(sub))
    r = GraphRunner()
    r.lower(proven)
    proven_nodes = [n for n in r._nodes if isinstance(n, Concat)]
    assert proven_nodes and all(not n._verify for n in proven_nodes)

    t1 = T("id | a\n1 | 1")
    t2 = T("id | a\n9 | 9")
    pw.universes.promise_are_pairwise_disjoint(t1, t2)
    r2 = GraphRunner()
    r2.lower(t1.concat(t2))
    promised_nodes = [n for n in r2._nodes if isinstance(n, Concat)]
    assert promised_nodes and all(n._verify for n in promised_nodes)


def test_self_outer_interval_join_pads_do_not_collide():
    """Rows unmatched on both sides of a self interval join pad with the
    same source key — the side-salted pad rekeying keeps the concat
    disjoint."""
    t = T(
        """
        t | v
        0 | 10
        100 | 20
        """
    )
    res = t.interval_join_outer(
        t, pw.left.t, pw.right.t, pw.temporal.interval(1, 2)
    ).select(lv=pw.left.v, rv=pw.right.v)
    cap = pw.internals.graph_runner.GraphRunner().run_tables(res)[0]
    rows = sorted(
        (tuple(r) for _, r in cap.state.iter_items()),
        key=lambda r: (r[0] is None, r),
    )
    # every row unmatched: 2 left pads + 2 right pads
    assert rows == [(10, None), (20, None), (None, 10), (None, 20)]


def test_table_slice_surface():
    t = T(
        """
        age | owner | pet
        10  | Alice | dog
        9   | Bob   | cat
        """
    )
    s = t.slice
    assert sorted(s.keys()) == ["age", "owner", "pet"]
    assert s["age"].name == "age"
    assert s.owner.name == "owner"
    renamed = s.without("age").with_suffix("_col")
    assert sorted(renamed.keys()) == ["owner_col", "pet_col"]
    with pytest.raises(KeyError):
        s.without("missing")
    with pytest.raises(ValueError, match="method name"):
        s.select  # column named like a Table method
    # unpacks into select with the slice's names
    res = t.select(*renamed)
    assert sorted(res.column_names()) == ["owner_col", "pet_col"]
    cap = pw.internals.graph_runner.GraphRunner().run_tables(res)[0]
    names = res.column_names()
    rows = sorted(
        tuple(r[names.index(c)] for c in ["owner_col", "pet_col"])
        for _, r in cap.state.iter_items()
    )
    assert rows == [("Alice", "dog"), ("Bob", "cat")]


def test_table_slice_rename_dict_and_getitem_list():
    t = T("a | b\n1 | 2")
    s = t.slice.rename({"a": "x"})
    assert sorted(s.keys()) == ["b", "x"]
    sub = t.slice[["a", "b"]]
    assert sorted(sub.keys()) == ["a", "b"]
    assert s.slice is s
