"""Graph-version upgrades: fingerprint stability, plan classification,
severity exit codes, and a single-process end-to-end apply.

The chaos-proof multi-process story (kill at every migration phase, old
version bootable, supervised resume with exactly-once output) lives in
``scripts/upgrade_smoke.py`` / ``tests/test_upgrade_smoke.py``; this file
covers the pure layers underneath it.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from pathway_tpu.upgrade import (
    UpgradeError,
    classify,
    load_new_graph,
    plan_exit_code,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _script(tmp_path, name, body):
    path = tmp_path / name
    path.write_text(textwrap.dedent(body))
    return str(path)


#: a minimal persisted wordcount; placeholders let variants rename
#: variables or tweak structure without touching anything else
_BASE = """
import sys
import pathway_tpu as pw

class S(pw.io.python.ConnectorSubject):
    def run(self):
        pass

{table} = pw.io.python.read(
    S(), schema=pw.schema_from_types(word=str), name="words",
    autocommit_ms=None,
)
{select} = {table}.select(
    word=pw.this.word,
    loud=pw.apply_with_type(lambda {param}: {param}.upper(), str, pw.this.word),
)
{counts} = {select}.groupby(pw.this.word{gb_extra}).reduce(
    pw.this.word, c=pw.reducers.count()
){named}
pw.io.subscribe({counts}, on_change=lambda **kw: None)
pw.run()
"""


def _variant(tmp_path, name, *, table="t", select="shouted", counts="counts",
             param="w", gb_extra="", named=""):
    return _script(
        tmp_path, name,
        _BASE.format(table=table, select=select, counts=counts, param=param,
                     gb_extra=gb_extra, named=named),
    )


def _load(script):
    doc = load_new_graph(script)
    assert doc.get("crash") is None, doc.get("crash")
    return doc


# -- fingerprint stability under pure renames ------------------------------


def test_pure_rename_keeps_fingerprints(tmp_path):
    """Identical structure + renamed Python variables (including lambda
    parameters) must produce bit-identical fingerprints — otherwise every
    cosmetic refactor orphans the persisted store."""
    a = _load(_variant(tmp_path, "a.py"))
    b = _load(
        _variant(tmp_path, "b.py", table="rows", select="yelled",
                 counts="tallies", param="token")
    )
    assert [e["fingerprint"] for e in a["stateful"]] == [
        e["fingerprint"] for e in b["stateful"]
    ]
    assert [s["fingerprint"] for s in a["sources"]] == [
        s["fingerprint"] for s in b["sources"]
    ]
    plan = classify(a, b)
    assert plan["carried"] == len(a["stateful"])
    assert plan["remapped"] == plan["new"] == plan["dropped"] == 0
    assert plan["errors"] == [] and plan["warnings"] == []
    assert plan_exit_code(plan) == 0


def test_structural_tweak_moves_fingerprint(tmp_path):
    """The complement: an actual structural change (groupby error
    semantics) must move the fingerprint, or drifted code would silently
    reuse incompatible state."""
    a = _load(_variant(tmp_path, "a.py"))
    c = _load(_variant(tmp_path, "c.py", gb_extra=", _skip_errors=False"))
    assert [e["fingerprint"] for e in a["stateful"]] != [
        e["fingerprint"] for e in c["stateful"]
    ]


def test_named_pin_survives_structural_tweak(tmp_path):
    """`.named()` is the remap hook: same pinned name + drifted signature
    classifies as remapped (state rewritten through split/merge), not as
    a drop+new pair."""
    old = _load(_variant(tmp_path, "old.py", named='.named("tally")'))
    new = _load(
        _variant(tmp_path, "new.py", param="token",
                 gb_extra=", _skip_errors=False", named='.named("tally")')
    )
    assert [e["name"] for e in old["stateful"]] == ["tally"]
    plan = classify(old, new)
    ops = [e for e in plan["operators"] if e["verb"] == "remapped"]
    assert len(ops) == 1 and ops[0]["name"] == "tally"
    assert ops[0]["old_rank"] == old["stateful"][0]["rank"]
    assert plan["dropped"] == 0 and plan["errors"] == []


def test_named_pin_same_signature_is_carried(tmp_path):
    """A pinned name whose signature did NOT drift (only upstream
    changed) is carried verbatim — remap machinery stays out of the way."""
    old = _load(_variant(tmp_path, "old.py", named='.named("tally")'))
    new = _load(
        _script(
            tmp_path, "new.py",
            _BASE.format(
                table="t", select="shouted", counts="counts", param="w",
                gb_extra="", named='.named("tally")',
            ).replace("w.upper()", "w.lower()"),
        )
    )
    # guard: the upstream tweak actually moved the groupby's fingerprint
    plan = classify(old, new)
    [op] = plan["operators"]
    assert op["verb"] == "carried"
    assert op["detail"] is None or "pinned" in op["detail"]


# -- classification and exit codes over synthetic manifests ----------------


def _op(rank, cls="GroupByReduce", fp="aa", name=None, sig="s0",
        reshard="keyed"):
    return {"rank": rank, "cls": cls, "fingerprint": fp, "name": name,
            "signature": sig, "reshard": reshard}


def test_classify_dropped_stateful_is_an_error():
    old = {"stateful": [_op(0, fp="dead")], "sources": []}
    new = {"stateful": [], "sources": []}
    plan = classify(old, new)
    assert plan["dropped"] == 1
    assert len(plan["errors"]) == 1
    assert "DROPPED" in plan["errors"][0]
    assert "GroupByReduce" in plan["errors"][0]
    assert plan_exit_code(plan) == 2


def test_classify_allow_drop_downgrades_to_warning():
    old = {"stateful": [_op(0, fp="dead")], "sources": []}
    new = {"stateful": [], "sources": []}
    plan = classify(old, new, allow_drop=True)
    assert plan["dropped"] == 1 and plan["errors"] == []
    assert len(plan["warnings"]) == 1
    assert plan_exit_code(plan) == 1


def test_classify_pinned_name_cross_class_refused():
    old = {"stateful": [_op(0, cls="GroupByReduce", name="x")],
           "sources": []}
    new = {"stateful": [_op(0, cls="Deduplicate", fp="bb", name="x",
                            sig="s1")],
           "sources": []}
    plan = classify(old, new)
    assert any("cannot migrate across operator classes" in e
               for e in plan["errors"])
    # the old op is also unmatched -> dropped without --allow-drop
    assert plan["dropped"] == 1
    assert plan_exit_code(plan) == 2


def test_classify_gone_source_warns():
    old = {"stateful": [], "sources": [{"pid": "words", "cls": "X",
                                        "fingerprint": "ff"}]}
    new = {"stateful": [], "sources": []}
    plan = classify(old, new)
    assert any("words" in w for w in plan["warnings"])
    assert plan_exit_code(plan) == 1


def test_classify_duplicate_fingerprints_pair_one_to_one():
    """Two structurally identical operators must match 1:1, not both onto
    the same old snapshot."""
    old = {"stateful": [_op(0), _op(1)], "sources": []}
    new = {"stateful": [_op(0), _op(1)], "sources": []}
    plan = classify(old, new)
    assert plan["carried"] == 2 and plan["dropped"] == 0
    assert sorted(e["old_rank"] for e in plan["operators"]) == [0, 1]


# -- end-to-end: persisted run -> apply -> boot (single process) -----------


_RUN = """
import json, sys
import pathway_tpu as pw
from pathway_tpu.persistence import Backend, Config

out_path = sys.argv[1] if len(sys.argv) > 1 else "/dev/null"
pstate = sys.argv[2] if len(sys.argv) > 2 else "pstate-scratch"
WORDS = ["foo", "bar", "foo", "baz"] * 3

class S(pw.io.python.ConnectorSubject):
    def run(self):
        for w in WORDS:
            self.next(word=w)
            self.commit()

t = pw.io.python.read(S(), schema=pw.schema_from_types(word=str),
                      name="words", autocommit_ms=None)
counts = t.groupby(pw.this.word).reduce(pw.this.word,
                                        c=pw.reducers.count())
f = open(out_path, "a")
pw.io.subscribe(counts, on_change=lambda key, row, time, is_addition:
                (f.write(json.dumps([row["word"], int(row["c"]),
                                     bool(is_addition)]) + chr(10)),
                 f.flush()))
cfg = Config.simple_config(Backend.filesystem(pstate),
                           snapshot_interval_ms=10)
pw.run(persistence_config=cfg)
"""

#: same pipeline plus a second (new) reducer over the same groupby chain
_RUN_V2 = _RUN.replace(
    'pw.io.subscribe(counts',
    'lens = t.groupby(pw.this.word).reduce(pw.this.word,'
    ' total_len=pw.reducers.sum(pw.apply_with_type(len, int,'
    ' pw.this.word)))\n'
    'pw.io.subscribe(lens, on_change=lambda **kw: None)\n'
    'pw.io.subscribe(counts',
)


def test_apply_end_to_end(tmp_path):
    from pathway_tpu.persistence import Backend
    from pathway_tpu.upgrade import apply_upgrade, plan_upgrade

    old = _script(tmp_path, "old.py", _RUN)
    new = _script(tmp_path, "new.py", _RUN_V2)
    pstate = str(tmp_path / "pstate")
    out = str(tmp_path / "events.jsonl")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT}
    env.pop("PATHWAY_FAULT_PLAN", None)
    proc = subprocess.run(
        [sys.executable, old, out, pstate], env=env, timeout=180,
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

    spec = Backend.filesystem(pstate)
    plan, crash = plan_upgrade(spec, new, script_args=("/dev/null",))
    assert crash is None
    assert plan["carried"] == 1 and plan["new"] == 1
    assert plan["dropped"] == 0 and plan["errors"] == []

    report = apply_upgrade(spec, new, script_args=("/dev/null",))
    assert report["epoch"] == plan["epoch"] + 1
    marker = json.loads((tmp_path / "pstate" / "cluster").read_text())
    assert marker["epoch"] == report["epoch"]
    # staging fully swept, the new epoch's layout present
    assert not list((tmp_path / "pstate" / "upgrade-tmp").rglob("*")) or all(
        p.is_dir()
        for p in (tmp_path / "pstate" / "upgrade-tmp").rglob("*")
    )
    assert (tmp_path / "pstate" / f"epoch-{report['epoch']}").is_dir()

    # re-apply is a noop: same manifest, no epoch churn
    again = apply_upgrade(spec, new, script_args=("/dev/null",))
    assert again.get("noop") is True
    assert json.loads(
        (tmp_path / "pstate" / "cluster").read_text()
    )["epoch"] == report["epoch"]

    # the upgraded store boots under the NEW script with zero duplicate
    # deliveries (stream already fully consumed -> nothing re-emitted)
    before = (tmp_path / "events.jsonl").read_text()
    proc = subprocess.run(
        [sys.executable, new, out, pstate], env=env, timeout=180,
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert (tmp_path / "events.jsonl").read_text() == before


def test_plan_on_unbooted_store_raises(tmp_path):
    from pathway_tpu.persistence import Backend
    from pathway_tpu.upgrade import plan_upgrade

    script = _script(tmp_path, "new.py", _RUN)
    store = tmp_path / "empty"
    store.mkdir()
    with pytest.raises(UpgradeError):
        plan_upgrade(Backend.filesystem(str(store)), script)


def test_crashing_script_reports_exit_3(tmp_path):
    bad = _script(tmp_path, "bad.py", "raise RuntimeError('boom')\n")
    doc = load_new_graph(bad)
    assert doc.get("crash") is not None
    assert "boom" in str(doc["crash"])
