"""Ported from
`/root/reference/python/pathway/tests/test_async_transformer.py`:
AsyncTransformer contract — successful/failed split, schema mismatch,
instance grouping, per-key instance change consistency."""

from __future__ import annotations

import asyncio
import random
from typing import Any

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T, assert_table_equality_wo_index


@pytest.fixture(autouse=True)
def _clean():
    G.clear()
    yield
    G.clear()


class OutputSchema(pw.Schema):
    ret: int


def test_simple():
    # reference test_async_transformer.py:34
    class TestAsyncTransformer(pw.AsyncTransformer, output_schema=OutputSchema):
        async def invoke(self, value: int) -> dict[str, Any]:
            await asyncio.sleep(random.uniform(0, 0.05))
            return dict(ret=value + 1)

    input_table = T("value\n1\n2\n3")
    result = TestAsyncTransformer(input_table=input_table).successful
    assert_table_equality_wo_index(result, T("ret\n2\n3\n4"))


def test_idempotency():
    # reference test_async_transformer.py:113 — state cleared between runs
    class TestAsyncTransformer(pw.AsyncTransformer, output_schema=OutputSchema):
        async def invoke(self, value: int) -> dict[str, Any]:
            return dict(ret=value + 1)

    input_table = T("value\n1\n2\n3")
    result = TestAsyncTransformer(input_table=input_table).successful
    expected = T("ret\n2\n3\n4")
    assert_table_equality_wo_index(result, expected)
    assert_table_equality_wo_index(result, expected)


def test_filter_failures():
    # reference test_async_transformer.py:150
    class TestAsyncTransformer(pw.AsyncTransformer, output_schema=OutputSchema):
        async def invoke(self, value: int) -> dict[str, Any]:
            if value == 2:
                raise Exception
            return dict(ret=value + 1)

    input_table = T("value\n1\n2\n3")
    result = TestAsyncTransformer(input_table=input_table).successful
    assert_table_equality_wo_index(result, T("ret\n2\n4"))


def test_assert_schema_error():
    # reference test_async_transformer.py:188 — wrong keys = failed row
    class TestAsyncTransformer(pw.AsyncTransformer, output_schema=OutputSchema):
        async def invoke(self, value: int) -> dict[str, Any]:
            return dict(foo=value + 1)

    input_table = T("value\n1\n2")
    result = TestAsyncTransformer(input_table=input_table).successful
    assert_table_equality_wo_index(result, pw.Table.empty(ret=int))


def test_failed():
    # reference test_async_transformer.py:470
    class OutputSchemaF(pw.Schema):
        ret: float

    class TestAsyncTransformer(pw.AsyncTransformer, output_schema=OutputSchemaF):
        async def invoke(self, value: float) -> dict[str, Any]:
            if value == 1.1:
                raise ValueError("incorrect value")
            return dict(ret=value)

    input_table = T("value\n1.3\n1.1")
    failed = TestAsyncTransformer(input_table=input_table).failed
    from pathway_tpu.internals.graph_runner import GraphRunner

    cap = GraphRunner().run_tables(failed)[0]
    rows = [r for _, r in cap.state.iter_items()]
    assert len(rows) == 1  # exactly the raising row, ret is null


def test_with_instance_groups_complete_together():
    # reference test_async_transformer.py:264 — all rows of an instance
    # land in one consistent batch
    class OutputSchemaF(pw.Schema):
        ret: float

    class TestAsyncTransformer(pw.AsyncTransformer, output_schema=OutputSchemaF):
        async def invoke(self, value: float, instance: int) -> dict[str, Any]:
            await asyncio.sleep(value * 0.05)
            return dict(ret=value)

    input_table = T(
        """
        value | instance
         0.3  |     1
         0.1  |     1
         0.0  |     2
         0.5  |     2
        """
    )
    result = TestAsyncTransformer(
        input_table=input_table, instance=pw.this.instance
    ).successful
    assert_table_equality_wo_index(
        result, T("ret\n0.3\n0.1\n0.0\n0.5"), check_types=False
    )
