"""Native C keyspace kernel: bit-parity with the pure-Python path.

Parity is load-bearing: persisted snapshots store keys, so the two
implementations must agree on every value class or recovery would
mis-route rows after an environment change.
"""

from __future__ import annotations

import hashlib

import numpy as np
import pytest

from pathway_tpu.engine import keys as K
from pathway_tpu.native import get_native

native = get_native()

pytestmark = pytest.mark.skipif(
    native is None, reason="no C compiler available to build the native module"
)

CORPUS_ROWS = [
    (),
    (None,),
    (True, False),
    (0, 1, -1, 2**62, -(2**62), 123456789),
    (0.0, -0.0, 1.5, float("inf"), -2.75e300),
    ("", "hello", "héllo wörld", "x" * 1000),
    (b"", b"raw\x00bytes", b"y" * 500),
    (("nested", 1), ("deep", ("er", 2.5), None)),
    (np.int64(42), np.float64(2.5), np.bool_(True)),
    (np.array([1.0, 2.0, 3.0]),),
    ({"a": 1},),  # falls back to repr hashing, must still agree
]


def test_blake2b8_matches_hashlib():
    for data in [b"", b"a", b"hello world", b"z" * 127, b"z" * 128, b"z" * 129,
                 b"q" * 1000]:
        expected = int.from_bytes(
            hashlib.blake2b(data, digest_size=8).digest(), "little"
        )
        assert native.blake2b8(data) == expected, f"len={len(data)}"


def test_splitmix_matches_python():
    for x in [0, 1, 0xDEADBEEF, 2**64 - 1, 0x9E3779B97F4A7C15]:
        assert native.splitmix64(x) == int(K._splitmix(np.uint64(x)))


def test_hash_rows_parity():
    for salt in (0, 7, 0xC0):
        py = K._hash_values_py(CORPUS_ROWS, salt)
        out = np.empty(len(CORPUS_ROWS), dtype=np.uint64)
        native.hash_rows(CORPUS_ROWS, salt, K._hash_scalar, out)
        assert list(out) == list(py)


def test_hash_values_uses_native_and_agrees():
    rows = [("word", i, float(i) / 3) for i in range(1000)]
    assert list(K.hash_values(rows)) == list(K._hash_values_py(rows))


def test_native_speedup_on_string_rows():
    import time

    rows = [(f"token-{i}", f"text {i % 97}", i) for i in range(20000)]
    t0 = time.perf_counter()
    out = np.empty(len(rows), dtype=np.uint64)
    native.hash_rows(rows, 0, K._hash_scalar, out)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    K._hash_values_py(rows)
    t_py = time.perf_counter() - t0
    # native should be dramatically faster; 3x is a conservative floor
    assert t_native * 3 < t_py, (t_native, t_py)

