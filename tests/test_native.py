"""Native C keyspace kernel: bit-parity with the pure-Python path.

Parity is load-bearing: persisted snapshots store keys, so the two
implementations must agree on every value class or recovery would
mis-route rows after an environment change.
"""

from __future__ import annotations

import hashlib

import numpy as np
import pytest

from pathway_tpu.engine import keys as K
from pathway_tpu.native import get_native

native = get_native()

pytestmark = pytest.mark.skipif(
    native is None, reason="no C compiler available to build the native module"
)

CORPUS_ROWS = [
    (),
    (None,),
    (True, False),
    (0, 1, -1, 2**62, -(2**62), 123456789),
    (0.0, -0.0, 1.5, float("inf"), -2.75e300),
    ("", "hello", "héllo wörld", "x" * 1000),
    (b"", b"raw\x00bytes", b"y" * 500),
    (("nested", 1), ("deep", ("er", 2.5), None)),
    (np.int64(42), np.float64(2.5), np.bool_(True)),
    (np.array([1.0, 2.0, 3.0]),),
    ({"a": 1},),  # falls back to repr hashing, must still agree
]


def test_blake2b8_matches_hashlib():
    for data in [b"", b"a", b"hello world", b"z" * 127, b"z" * 128, b"z" * 129,
                 b"q" * 1000]:
        expected = int.from_bytes(
            hashlib.blake2b(data, digest_size=8).digest(), "little"
        )
        assert native.blake2b8(data) == expected, f"len={len(data)}"


def test_splitmix_matches_python():
    for x in [0, 1, 0xDEADBEEF, 2**64 - 1, 0x9E3779B97F4A7C15]:
        assert native.splitmix64(x) == int(K._splitmix(np.uint64(x)))


def test_hash_rows_parity():
    for salt in (0, 7, 0xC0):
        py = K._hash_values_py(CORPUS_ROWS, salt)
        out = np.empty(len(CORPUS_ROWS), dtype=np.uint64)
        native.hash_rows(CORPUS_ROWS, salt, K._hash_scalar, out)
        assert list(out) == list(py)


def test_hash_values_uses_native_and_agrees():
    rows = [("word", i, float(i) / 3) for i in range(1000)]
    assert list(K.hash_values(rows)) == list(K._hash_values_py(rows))


def test_native_speedup_on_string_rows():
    import time

    rows = [(f"token-{i}", f"text {i % 97}", i) for i in range(20000)]
    t0 = time.perf_counter()
    out = np.empty(len(rows), dtype=np.uint64)
    native.hash_rows(rows, 0, K._hash_scalar, out)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    K._hash_values_py(rows)
    t_py = time.perf_counter() - t0
    # native should be dramatically faster; 3x is a conservative floor
    assert t_native * 3 < t_py, (t_native, t_py)



# -- 128-bit keyspace: HI lane parity + conflation detection -----------------


def test_blake2b16hi_matches_hashlib():
    for data in (b"", b"hello", b"x" * 1000, "héllo".encode()):
        exp = int.from_bytes(
            hashlib.blake2b(data, digest_size=16).digest()[8:16], "little"
        )
        assert native.blake2b16hi(data) == exp


def test_splitmix2_matches_python():
    for x in (0, 1, 2**63, 0xDEADBEEF, 2**64 - 1):
        assert native.splitmix64_2(x) == K._splitmix2_int(x)
        assert native.splitmix64_2(x) == int(K._splitmix2(np.uint64(x)))


def test_hash_scalars2_parity_with_python():
    flat = [v for row in CORPUS_ROWS for v in row]
    lo = np.empty(len(flat), dtype=np.uint64)
    hi = np.empty(len(flat), dtype=np.uint64)
    native.hash_scalars2(flat, K._hash_scalar, K._hash_scalar_hi, None, lo, hi)
    for i, v in enumerate(flat):
        assert int(lo[i]) == K._hash_scalar(v) & ((1 << 64) - 1), v
        assert int(hi[i]) == K._hash_scalar_hi(v), v


def test_hash_rows2_lo_lane_bit_identical_to_hash_rows():
    # the LO lane is the persisted engine keyspace: widening must not
    # change a single existing key
    lo = np.empty(len(CORPUS_ROWS), dtype=np.uint64)
    hi = np.empty(len(CORPUS_ROWS), dtype=np.uint64)
    native.hash_rows2(
        CORPUS_ROWS, 7, 7, K._hash_scalar, K._hash_scalar_hi, None, lo, hi
    )
    old = np.empty(len(CORPUS_ROWS), dtype=np.uint64)
    native.hash_rows(CORPUS_ROWS, 7, K._hash_scalar, old)
    assert list(lo) == list(old)
    assert list(lo) == list(K._hash_values_py(CORPUS_ROWS, 7))


def test_hi_lane_independent_of_lo_lane():
    # if HI were a function of LO, lane collisions would always agree on
    # HI and detection could never fire; check the lanes decorrelate
    vals = [f"s{i}" for i in range(64)] + list(range(64))
    lo = np.empty(len(vals), dtype=np.uint64)
    hi = np.empty(len(vals), dtype=np.uint64)
    native.hash_scalars2(vals, K._hash_scalar, K._hash_scalar_hi, None, lo, hi)
    assert len(set(map(int, lo))) == len(vals)
    assert len(set(map(int, hi))) == len(vals)
    assert not np.any(lo == hi)


def test_string_memo_bit_identical():
    vals = ["alpha", "beta", "alpha", "beta", "alpha"] * 10
    memo: dict = {}
    lo_m = np.empty(len(vals), dtype=np.uint64)
    hi_m = np.empty(len(vals), dtype=np.uint64)
    native.hash_scalars2(vals, K._hash_scalar, K._hash_scalar_hi, memo, lo_m, hi_m)
    lo = np.empty(len(vals), dtype=np.uint64)
    hi = np.empty(len(vals), dtype=np.uint64)
    native.hash_scalars2(vals, K._hash_scalar, K._hash_scalar_hi, None, lo, hi)
    assert list(lo_m) == list(lo) and list(hi_m) == list(hi)
    assert set(memo) == {"alpha", "beta"}
    out_m = np.empty(len(vals), dtype=np.uint64)
    lomemo: dict = {}
    native.hash_scalars(vals, K._hash_scalar, out_m, lomemo)
    assert list(out_m) == list(lo)


def test_key_registry_detects_lane_collision():
    reg = native.KeyRegistry(1000)
    lo = np.array([10, 20, 30], dtype=np.uint64)
    hi = np.array([1, 2, 3], dtype=np.uint64)
    assert reg.register(lo, hi) == -1
    assert reg.register(lo, hi) == -1  # re-registering same keys is fine
    clash_lo = np.array([20], dtype=np.uint64)
    clash_hi = np.array([99], dtype=np.uint64)
    assert reg.register(clash_lo, clash_hi) == 0
    assert reg.stats()[0] == 3


def test_key_registry_freezes_at_cap():
    reg = native.KeyRegistry(4)
    lo = np.arange(100, 110, dtype=np.uint64)
    hi = np.arange(200, 210, dtype=np.uint64)
    assert reg.register(lo, hi) == -1
    size, frozen = reg.stats()
    assert frozen == 1 and size <= 8
    # frozen: registered prefix still detects, unregistered keys pass
    assert reg.register(np.array([100], np.uint64), np.array([5], np.uint64)) == 0


def test_register_keys_raises_key_collision_error():
    import pathway_tpu.engine.keys as keys_mod

    saved = keys_mod._REGISTRY
    keys_mod._REGISTRY = None
    try:
        keys_mod._get_registry()
        keys_mod._register_keys(
            np.array([77], dtype=np.uint64), np.array([1], dtype=np.uint64)
        )
        with pytest.raises(K.KeyCollisionError, match="collision"):
            keys_mod._register_keys(
                np.array([77], dtype=np.uint64), np.array([2], dtype=np.uint64)
            )
    finally:
        keys_mod._REGISTRY = saved


def test_py_key_registry_matches_native_semantics():
    pyreg = K._PyKeyRegistry(1000)
    lo = np.array([10, 20], dtype=np.uint64)
    hi = np.array([1, 2], dtype=np.uint64)
    assert pyreg.register(lo, hi) == -1
    assert pyreg.register(np.array([20], np.uint64), np.array([9], np.uint64)) == 0


def test_mix_columns_registers_and_detects_synthetic_conflation(monkeypatch):
    # two different value columns whose LO lanes collide (forced via a
    # stubbed LO hash) must fail the run instead of conflating rows
    import pathway_tpu.engine.keys as keys_mod

    saved = keys_mod._REGISTRY
    keys_mod._REGISTRY = None
    try:
        keys_mod._get_registry()
        a = keys_mod.mix_columns([np.array(["x1"], dtype=object)], 1)
        # same LO fold can only repeat with the same values -> no error
        keys_mod.mix_columns([np.array(["x1"], dtype=object)], 1)
        # now register a forged pair with the same LO but different HI
        with pytest.raises(K.KeyCollisionError):
            keys_mod._register_keys(
                np.asarray(a, dtype=np.uint64),
                np.array([0xBAD], dtype=np.uint64),
            )
    finally:
        keys_mod._REGISTRY = saved
