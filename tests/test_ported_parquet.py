"""Ported from `/root/reference/python/pathway/tests/test_parquet.py`."""

from __future__ import annotations

import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.testing import T, assert_table_equality_wo_index


@pytest.fixture(autouse=True)
def _clean():
    G.clear()
    yield
    G.clear()


def test_write_parquet(tmp_path):
    # reference test_parquet.py:9
    path = tmp_path / "t.parquet"
    tab = T("a | b\n2 | 3\n5 | 6")
    pw.debug.table_to_parquet(tab, path)
    df = pd.read_parquet(path)
    t2 = pw.debug.table_from_pandas(df, id_from=None, unsafe_trusted_ids=False)
    assert_table_equality_wo_index(t2, tab)


def test_read_parquet(tmp_path):
    # reference test_parquet.py:29
    path = tmp_path / "t.parquet"
    tab = T("a | b\n2 | 3\n5 | 6")
    df = pw.debug.table_to_pandas(tab, include_id=False).reset_index(drop=True)
    df.to_parquet(path)
    t2 = pw.debug.table_from_parquet(path, id_from=None, unsafe_trusted_ids=False)
    assert_table_equality_wo_index(t2, tab)
