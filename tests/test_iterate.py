"""pw.iterate fixpoint semantics — mirrors reference iterate tests
(test_common.py iterate cases; engine dataflow.rs:3737 nested scopes)."""

import pytest

import pathway_tpu as pw
from pathway_tpu.testing import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
)


def test_iterate_single_table_fixpoint():
    t = T(
        """
        a
        1
        3
        50
        """
    )

    def double_small(t):
        return t.select(a=pw.if_else(t.a < 100, t.a * 2, t.a))

    res = pw.iterate(double_small, t=t)
    expected = T(
        """
        a
        128
        192
        100
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_iterate_preserves_keys():
    t = T(
        """
        a
        1
        2
        """
    )
    res = pw.iterate(lambda t: t.select(a=pw.if_else(t.a < 8, t.a * 2, t.a)), t=t)
    joined = t.join(res, t.id == res.id, how=pw.JoinMode.INNER).select(
        orig=t.a, final=res.a
    )
    expected = T(
        """
        orig | final
        1    | 8
        2    | 8
        """
    )
    assert_table_equality_wo_index(joined, expected)


def test_iterate_iteration_limit():
    t = T(
        """
        a
        1
        """
    )
    res = pw.iterate(
        lambda t: t.select(a=t.a * 2), iteration_limit=3, t=t
    )
    expected = T(
        """
        a
        8
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_iterate_bad_limit():
    t = T("a\n1")
    with pytest.raises(ValueError):
        pw.iterate(lambda t: t, iteration_limit=0, t=t)


def test_iterate_dict_with_constant_input():
    # propagate min over a chain: value[i] <- min(value[i], value[prev[i]])
    values = pw.debug.table_from_markdown(
        """
        i | v
        1 | 10
        2 | 5
        3 | 7
        """,
        id_from="i",
    )
    edges = T(
        """
        u | w
        1 | 2
        2 | 3
        3 | 1
        """
    )

    def step(values, edges):
        # for each edge u->w, candidate value for w is values[u]
        cand = edges.select(
            dst=values.pointer_from(edges.w), cv=values.ix(values.pointer_from(edges.u)).v
        )
        best = cand.groupby(id=cand.dst).reduce(m=pw.reducers.min(cand.cv))
        cand_m = pw.coalesce(best.ix(values.id, optional=True).m, values.v)
        improved = values.select(
            values.i, v=pw.if_else(cand_m < values.v, cand_m, values.v)
        )
        return dict(values=improved)

    res = pw.iterate(step, values=values, edges=edges)["values"]
    expected = T(
        """
        i | v
        1 | 5
        2 | 5
        3 | 5
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_iterate_incremental_update():
    # when the input changes at a later time, the fixpoint is recomputed and
    # the output is updated with diffs (engine Iterate re-runs on change)
    t = pw.debug.table_from_markdown(
        """
        a | __time__ | __diff__
        1 |     2    |    1
        4 |     2    |    1
        1 |     4    |   -1
        3 |     4    |    1
        """
    )
    res = pw.iterate(lambda t: t.select(a=pw.if_else(t.a < 10, t.a * 2, t.a)), t=t)
    expected = T(
        """
        a
        12
        16
        """
    )
    assert_table_equality_wo_index(res, expected)
