"""Multi-device sharding tests on the virtual 8-device CPU mesh: sharded
KNN (all-gather merge), bucketed all-to-all record exchange, and the full
distributed pipeline step."""

import jax
import numpy as np
import pytest

from pathway_tpu.internals.jax_compat import (
    shard_map_available,
    shard_map_unavailable_reason,
)
from pathway_tpu.parallel.mesh import data_model_mesh, make_mesh

pytestmark = [
    pytest.mark.skipif(
        len(jax.devices()) < 8, reason="needs 8 virtual devices"
    ),
    # explicit env-capability skip, not a blind xfail: the shim resolves
    # jax.shard_map OR jax.experimental.shard_map.shard_map — only a jax
    # with NEITHER (named in the reason) skips these
    pytest.mark.skipif(
        not shard_map_available(), reason=shard_map_unavailable_reason()
    ),
]


def test_sharded_knn_matches_single_device():
    from pathway_tpu.ops.knn import ShardedKnnIndex, knn_search

    mesh = make_mesh({"data": 8})
    rng = np.random.default_rng(0)
    docs = rng.standard_normal((256, 32)).astype(np.float32)
    docs /= np.linalg.norm(docs, axis=1, keepdims=True)
    queries = rng.standard_normal((5, 32)).astype(np.float32)
    queries /= np.linalg.norm(queries, axis=1, keepdims=True)

    idx = ShardedKnnIndex(dim=32, capacity=256, mesh=mesh)
    idx.add(docs)
    s_sharded, i_sharded = idx.query(queries, k=7)
    s_ref, i_ref = knn_search(queries, docs, k=7)
    # same neighbor sets (scores in bf16 → compare ids)
    for a, b in zip(i_sharded, i_ref):
        assert set(a.tolist()) == set(b.tolist())


def test_knn_capacity_padding_never_returned():
    from pathway_tpu.ops.knn import ShardedKnnIndex

    idx = ShardedKnnIndex(dim=8, capacity=64)
    # docs anti-correlated with the query → negative scores, below the
    # zero-score padding rows if masking were broken
    q = np.ones((1, 8), dtype=np.float32) / np.sqrt(8)
    docs = -np.eye(8, dtype=np.float32)[:5]
    idx.add(docs)
    s, i = idx.query(q, k=5)
    assert set(i[0].tolist()) <= set(range(5))
    assert np.all(np.isfinite(s))


def test_knn_sharded_k_clamp():
    from pathway_tpu.ops.knn import ShardedKnnIndex

    mesh = make_mesh({"data": 8})
    idx = ShardedKnnIndex(dim=8, capacity=16, mesh=mesh)  # 2 rows/shard
    v = np.random.default_rng(1).standard_normal((6, 8)).astype(np.float32)
    idx.add(v)
    s, i = idx.query(v[:2], k=5)  # k clamped to 2
    assert s.shape[1] == 2


def test_bucketed_all_to_all_roundtrip():
    import jax.numpy as jnp

    from pathway_tpu.parallel.exchange import bucketed_all_to_all

    mesh = make_mesh({"data": 8})
    n_shards = 8
    cap_in = 4  # per device
    d = 3
    rng = np.random.default_rng(0)
    # row value encodes (source_device, slot); dest = value-derived shard
    vals = np.zeros((n_shards * cap_in, d), np.float32)
    dest = np.zeros((n_shards * cap_in,), np.int32)
    for dev in range(n_shards):
        for slot in range(cap_in):
            r = dev * cap_in + slot
            vals[r] = [dev, slot, dev * 10 + slot]
            dest[r] = (dev * 3 + slot) % n_shards
    cap_out = n_shards * cap_in  # generous per-device capacity
    out_vals, out_valid = bucketed_all_to_all(
        mesh, "data", jnp.asarray(vals), jnp.asarray(dest), cap_out
    )
    out_vals = np.asarray(out_vals).reshape(n_shards, cap_out, d)
    out_valid = np.asarray(out_valid).reshape(n_shards, cap_out)
    # every row must arrive exactly once, on its destination shard
    arrived = {}
    for shard in range(n_shards):
        for j in range(cap_out):
            if out_valid[shard, j]:
                dev, slot, tag = out_vals[shard, j]
                key = (int(dev), int(slot))
                assert key not in arrived, f"duplicate arrival {key}"
                arrived[key] = shard
                expected = (int(dev) * 3 + int(slot)) % n_shards
                assert shard == expected, (key, shard, expected)
    assert len(arrived) == n_shards * cap_in


def test_pipeline_step_runs():
    from pathway_tpu.models.pipeline import run_one_step

    mesh = data_model_mesh(8)
    loss, scores, ids = run_one_step(mesh)
    assert np.isfinite(loss)
    assert scores.shape == ids.shape


def test_embedder_deterministic():
    from pathway_tpu.models.embedder import Embedder, EmbedderConfig

    cfg = EmbedderConfig(vocab_size=512, dim=32, n_layers=1, n_heads=2, max_len=16)
    e1 = Embedder(cfg, seed=0)
    e2 = Embedder(cfg, seed=0)
    v1 = e1.embed_texts(["hello world", "foo bar baz"], max_len=16)
    v2 = e2.embed_texts(["hello world", "foo bar baz"], max_len=16)
    np.testing.assert_allclose(v1, v2, rtol=1e-5)
    norms = np.linalg.norm(v1, axis=1)
    np.testing.assert_allclose(norms, 1.0, rtol=1e-4)
