"""Tier-1 wrapper around scripts/upgrade_smoke.py (like test_rescale_smoke):
a 2-process persisted wordcount is SIGKILLed mid-stream, its state is
migrated to a NEW code version (`pathway-tpu upgrade` / `spawn
--upgrade-to`) — Rowwise renames carry, the pinned groupby remaps, an
added reducer backfills — and the supervised resume converges to EXACT
final counts with zero duplicate deliveries; chaos faults at every
migration phase leave the old version bootable."""

import os
import sys

sys.path.insert(
    0,
    os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
    ),
)


def test_upgrade_smoke(tmp_path):
    from upgrade_smoke import EXPECTED, EXPECTED_LENS, run_smoke

    result = run_smoke(workdir=str(tmp_path))
    assert result["final"] == EXPECTED
    assert result["lens_final"] == EXPECTED_LENS
    assert result["old_boot_final"] == EXPECTED
    assert result["new_boot_final"] == EXPECTED
    assert result["plan"]["dropped"] == 0
