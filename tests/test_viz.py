"""pw.viz live-mirror machinery (reference stdlib/viz/plotting.py); the
Bokeh/Panel render layer is gated, the data path is tested here."""

from __future__ import annotations

import threading

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.stdlib.viz import LiveTableSource, plot, show, table_viz


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def test_live_source_mirrors_stream_with_retractions():
    class S(pw.io.python.ConnectorSubject):
        def run(self):
            for w in ("a", "b", "a", "c", "a"):
                self.next(word=w)
                self.commit()

    t = pw.io.python.read(
        S(), schema=pw.schema_from_types(word=str), autocommit_duration_ms=None
    )
    counts = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
    updates = []
    src = plot(counts, plotting_function=lambda cds: None, sorting_col="word")
    assert isinstance(src, LiveTableSource)  # no bokeh/panel installed
    src.on_update(lambda cols: updates.append(cols))
    pw.run()
    # final mirror: counts with retractions applied, sorted by word
    assert src.columns() == {"word": ["a", "b", "c"], "c": [3, 1, 1]}
    assert len(src) == 3
    assert updates, "listeners fire on every applied tick"
    assert updates[-1] == src.columns()


def test_table_viz_and_show_gating():
    t = pw.debug.table_from_markdown("a\n1")
    src = table_viz(t)
    assert isinstance(src, LiveTableSource)
    with pytest.raises(ImportError, match="panel"):
        show(object())
    with pytest.raises(ValueError, match="sorting_col"):
        table_viz(t, sorting_col="missing")


def test_live_source_ndarray_cells():
    """Array-valued cells (embedding columns) survive retraction matching."""
    import numpy as np

    class S(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k="a", v=np.ones(3))
            self.commit()
            self.next(k="a", v=np.zeros(3))  # same key, new array row
            self.commit()

    t = pw.io.python.read(
        S(), schema=pw.schema_from_types(k=str, v=np.ndarray),
        autocommit_duration_ms=None,
    )
    latest = t.groupby(pw.this.k).reduce(
        pw.this.k, v=pw.reducers.latest(pw.this.v)
    )
    src = table_viz(latest)
    pw.run()
    cols = src.columns()
    assert cols["k"] == ["a"] and np.allclose(cols["v"][0], 0.0)
