"""pw.viz live-mirror machinery (reference stdlib/viz/plotting.py); the
Bokeh/Panel render layer is gated, the data path is tested here."""

from __future__ import annotations

import threading

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G
from pathway_tpu.stdlib.viz import LiveTableSource, plot, show, table_viz


@pytest.fixture(autouse=True)
def _clean_graph():
    G.clear()
    yield
    G.clear()


def test_live_source_mirrors_stream_with_retractions():
    class S(pw.io.python.ConnectorSubject):
        def run(self):
            for w in ("a", "b", "a", "c", "a"):
                self.next(word=w)
                self.commit()

    t = pw.io.python.read(
        S(), schema=pw.schema_from_types(word=str), autocommit_duration_ms=None
    )
    counts = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
    updates = []
    src = plot(counts, plotting_function=lambda cds: None, sorting_col="word")
    assert isinstance(src, LiveTableSource)  # no bokeh/panel installed
    src.on_update(lambda cols, appended: updates.append(cols))
    pw.run()
    # final mirror: counts with retractions applied, sorted by word
    assert src.columns() == {"word": ["a", "b", "c"], "c": [3, 1, 1]}
    assert len(src) == 3
    assert updates, "listeners fire on every applied tick"
    assert updates[-1] == src.columns()


def test_table_viz_and_show_gating():
    t = pw.debug.table_from_markdown("a\n1")
    src = table_viz(t)
    assert isinstance(src, LiveTableSource)
    with pytest.raises(ImportError, match="panel"):
        show(object())
    with pytest.raises(ValueError, match="sorting_col"):
        table_viz(t, sorting_col="missing")


def test_live_source_ndarray_cells():
    """Array-valued cells (embedding columns) survive retraction matching."""
    import numpy as np

    class S(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k="a", v=np.ones(3))
            self.commit()
            self.next(k="a", v=np.zeros(3))  # same key, new array row
            self.commit()

    t = pw.io.python.read(
        S(), schema=pw.schema_from_types(k=str, v=np.ndarray),
        autocommit_duration_ms=None,
    )
    latest = t.groupby(pw.this.k).reduce(
        pw.this.k, v=pw.reducers.latest(pw.this.v)
    )
    src = table_viz(latest)
    pw.run()
    cols = src.columns()
    assert cols["k"] == ["a"] and np.allclose(cols["v"][0], 0.0)


def test_live_source_incremental_append_hints():
    """Append-only ticks surface the new rows as the incremental channel
    (what the Bokeh layer feeds ColumnDataSource.stream, reference
    plotting.py:99); retraction ticks surface None (full swap)."""

    class S(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k="a", v=1)
            self.commit()
            self.next(k="b", v=2)
            self.next(k="c", v=3)
            self.commit()

    t = pw.io.python.read(
        S(), schema=pw.schema_from_types(k=str, v=int),
        autocommit_duration_ms=None,
    )
    src = LiveTableSource(t)  # unsorted: append hints allowed
    events = []
    src.on_update(lambda cols, appended: events.append((cols, appended)))
    pw.run()
    appends = [a for _, a in events if a is not None]
    assert appends == [
        {"k": ["a"], "v": [1]},
        {"k": ["b", "c"], "v": [2, 3]},
    ]
    assert src.columns()["k"] == ["a", "b", "c"]


def test_live_source_update_tick_disables_append_hint():
    class S(pw.io.python.ConnectorSubject):
        def run(self):
            for w in ("a", "a"):  # second row bumps the count: -1/+1 tick
                self.next(word=w)
                self.commit()

    t = pw.io.python.read(
        S(), schema=pw.schema_from_types(word=str), autocommit_duration_ms=None
    )
    counts = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
    src = LiveTableSource(counts)
    events = []
    src.on_update(lambda cols, appended: events.append(appended))
    pw.run()
    assert events[0] == {"word": ["a"], "c": [1]}  # first tick is an append
    assert events[1] is None  # count update retracts: full-swap tick
    assert src.columns() == {"word": ["a"], "c": [2]}


def test_sorted_mirror_never_hints_append():
    class S(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k="b")
            self.commit()
            self.next(k="a")
            self.commit()

    t = pw.io.python.read(
        S(), schema=pw.schema_from_types(k=str), autocommit_duration_ms=None
    )
    src = LiveTableSource(t, sorting_col="k")
    events = []
    src.on_update(lambda cols, appended: events.append(appended))
    pw.run()
    # a sorted mirror re-orders on every tick: appends can't stream
    assert events == [None, None]
    assert src.columns()["k"] == ["a", "b"]


def test_table_plot_show_methods_and_repr_html():
    t = pw.debug.table_from_markdown("a | b\n1 | x\n2 | y")
    src = t.plot(lambda cds: None)
    assert isinstance(src, LiveTableSource)
    src2 = t.show()
    assert isinstance(src2, LiveTableSource)
    html = t._repr_html_()
    assert "<table" in html and "x" in html

    class S(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(a=1)

    G.clear()
    live = pw.io.python.read(S(), schema=pw.schema_from_types(a=int))
    assert "pw.run()" in live._repr_html_()
