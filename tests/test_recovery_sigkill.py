"""Real crash recovery: SIGKILL the engine mid-stream, restart, verify
exactly-once-ish output.

Mirrors the reference's wordcount fault-injection harness
(``integration_tests/wordcount/base.py:319``
``run_pw_program_suddenly_terminate`` + ``test_recovery.py``): the kill is a
hard SIGKILL landing wherever the engine happens to be — mid-tick, between a
snapshot chunk write and its metadata commit, anywhere — not a cooperative
stop between commits. Recovery must restore from the last complete snapshot
and re-read everything after it, so the *final* counts are exact even though
the callback stream is at-least-once across the crash window.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

_PROGRAM = """
import json, sys, time

import pathway_tpu as pw
from pathway_tpu.persistence import Backend, Config

out_path, pstate = sys.argv[1], sys.argv[2]
WORDS = ["foo", "bar", "foo", "baz"] * 5  # foo:10 bar:5 baz:5


class S(pw.io.python.ConnectorSubject):
    def run(self):
        for w in WORDS:
            self.next(word=w)
            self.commit()
            time.sleep(0.03)


t = pw.io.python.read(
    S(), schema=pw.schema_from_types(word=str), name="words",
    autocommit_ms=None,
)
counts = t.groupby(pw.this.word).reduce(pw.this.word, c=pw.reducers.count())
f = open(out_path, "a")


def on_change(key, row, time, is_addition):
    f.write(json.dumps([row["word"], int(row["c"]), bool(is_addition)]) + "\\n")
    f.flush()


pw.io.subscribe(counts, on_change=on_change)
cfg = Config.simple_config(Backend.filesystem(pstate), snapshot_interval_ms=20)
pw.run(persistence_config=cfg)
"""


def _events(path) -> list[tuple[str, int, bool]]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            try:  # the SIGKILL may tear the last line mid-write
                w, c, add = json.loads(line)
                out.append((w, int(c), bool(add)))
            except (json.JSONDecodeError, ValueError):
                pass
    return out


def test_sigkill_mid_run_recovery(tmp_path):
    prog = tmp_path / "prog.py"
    prog.write_text(textwrap.dedent(_PROGRAM))
    out = tmp_path / "events.jsonl"
    pstate = tmp_path / "pstate"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": repo_root,
        "PATHWAY_THREADS": "1",
    }

    p = subprocess.Popen(
        [sys.executable, str(prog), str(out), str(pstate)], env=env
    )
    try:
        # wait for some output to be live (and some snapshots committed),
        # then SIGKILL while the stream is still mid-flight
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            adds = [e for e in _events(out) if e[2]]
            if len(adds) >= 6:
                break
            if p.poll() is not None:
                raise AssertionError("program finished before the kill")
            time.sleep(0.02)
        else:
            raise AssertionError(f"no progress before kill: {_events(out)}")
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()

    # the crash must have left persisted state behind (snapshot interval is
    # 20ms against a ~600ms stream)
    persisted = [
        os.path.join(dp, f) for dp, _, fs in os.walk(pstate) for f in fs
    ]
    assert any("meta" in pth for pth in persisted), persisted
    killed_finals = {}
    for w, c, add in _events(out):
        if add:
            killed_finals[w] = c
    assert killed_finals, "kill landed before any output"
    assert killed_finals != {"foo": 10, "bar": 5, "baz": 5}, (
        "kill landed after the stream completed — not a mid-run crash"
    )

    # restart over the same persisted state; runs to natural completion
    subprocess.run(
        [sys.executable, str(prog), str(out), str(pstate)],
        env=env, check=True, timeout=120,
    )

    final: dict[str, int] = {}
    for w, c, add in _events(out):
        if add:
            final[w] = c
    assert final == {"foo": 10, "bar": 5, "baz": 5}, final
