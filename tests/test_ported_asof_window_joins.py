"""Ported from the reference's asof-join and window-join suites.

Sources: ``/root/reference/python/pathway/tests/temporal/test_asof_joins.py``
and ``.../test_window_joins.py`` (VERDICT r4 item 7). Porting contract as in
``tests/test_ported_common_1.py``; manifest in ``PORTED_TESTS.md``.
Reference expected tables are re-expressed as (key, left value, right value)
triples selected through ``pw.left`` / ``pw.right`` — this framework's
AsofJoinResult does not expose the reference's synthesized ``pw.this.t`` /
``pw.this.instance`` columns (idiom delta recorded in the manifest).
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.temporal._asof_join import Direction
from pathway_tpu.testing import T


def _t1():
    return T(
        """
            | K | val |  t
        1   | 0 | 1   |  1
        2   | 0 | 2   |  4
        3   | 0 | 3   |  5
        4   | 0 | 4   |  6
        5   | 0 | 5   |  7
        6   | 0 | 6   |  11
        7   | 0 | 7   |  12
        8   | 1 | 8   |  5
        9   | 1 | 9   |  7
        """
    )


def _t2():
    return T(
        """
             | K | val | t
        21   | 1 | 7  | 2
        22   | 1 | 3  | 8
        23   | 0 | 0  | 2
        24   | 0 | 6  | 3
        25   | 0 | 2  | 7
        26   | 0 | 3  | 8
        27   | 0 | 9  | 9
        28   | 0 | 7  | 13
        29   | 0 | 4  | 14
        """
    )


def _triples(res, cols=("k", "t", "v")):
    df = pw.debug.table_to_pandas(res)
    return sorted(map(tuple, df[list(cols)].values.tolist()))


def test_asof_left():  # ref :17 (expected table re-keyed by K, 2t)
    res = _t1().asof_join(
        _t2(),
        pw.left.t * 2,
        pw.right.t * 2,
        pw.left.K == pw.right.K,
        how=pw.JoinMode.LEFT,
        defaults={_t2().val: -1},
    ).select(
        k=pw.left.K,
        t=pw.left.t * 2,
        v=pw.coalesce(pw.right.val, -1),
    )
    # reference expected: (instance, t, val_right) rows at :60-73
    assert _triples(res) == sorted([
        (0, 2, -1), (0, 8, 6), (0, 10, 6), (0, 12, 6), (0, 14, 2),
        (0, 22, 9), (0, 24, 9), (1, 10, 7), (1, 14, 7),
    ])


def test_asof_left_forward():  # ref :153
    res = _t1().asof_join(
        _t2(),
        pw.left.t * 2,
        pw.right.t * 2,
        pw.left.K == pw.right.K,
        how=pw.JoinMode.LEFT,
        direction=Direction.FORWARD,
        defaults={_t2().val: 100},
    ).select(
        k=pw.left.K,
        t=pw.left.t * 2,
        v=pw.coalesce(pw.right.val, 100),
    )
    # reference expected at :200-212 (without the t=40 row — _t1 here has
    # no K=1,t=20 row; that row exists only in the forward variant's input)
    assert _triples(res) == sorted([
        (0, 2, 0), (0, 8, 2), (0, 10, 2), (0, 12, 2), (0, 14, 2),
        (0, 22, 7), (0, 24, 7), (1, 10, 3), (1, 14, 3),
    ])


def test_asof_left_nearest():  # ref :218
    res = _t1().asof_join(
        _t2(),
        pw.left.t,
        pw.right.t,
        pw.left.K == pw.right.K,
        how=pw.JoinMode.LEFT,
        direction=Direction.NEAREST,
    ).select(k=pw.left.K, t=pw.left.t, v=pw.right.val)
    got = {(k, t): v for k, t, v in _triples(res)}
    # nearest by |t_l - t_r| per K: spot-check the reference's semantics
    assert got[(0, 1)] == 0  # t=1: nearest right is t=2 (val 0)
    assert got[(0, 7)] == 2  # exact match t=7 (val 2)
    assert got[(0, 12)] == 7  # t=12: nearest is t=13 (val 7)
    assert got[(1, 7)] == 3  # K=1 t=7: nearest of {2,8} is 8 (val 3)


def test_asof_multiple_keys():  # ref :267
    t1 = T(
        """
          | K | L | v | t
        1 | 0 | a | 1 | 3
        2 | 0 | b | 2 | 3
        3 | 1 | a | 3 | 3
        """
    )
    t2 = T(
        """
           | K | L | w | t
        11 | 0 | a | 7 | 1
        12 | 0 | b | 8 | 2
        13 | 1 | a | 9 | 2
        14 | 0 | a | 5 | 9
        """
    )
    res = t1.asof_join(
        t2, pw.left.t, pw.right.t,
        pw.left.K == pw.right.K, pw.left.L == pw.right.L,
        how=pw.JoinMode.LEFT,
    ).select(k=pw.left.K, t=pw.left.v, v=pw.right.w)
    assert _triples(res) == sorted([(0, 1, 7), (0, 2, 8), (1, 3, 9)])


def test_asof_join_eq_direction():  # ref :616 (BACKWARD includes equal t)
    t1 = T(
        """
          | v | t
        1 | 1 | 5
        """
    )
    t2 = T(
        """
           | w | t
        11 | 9 | 5
        """
    )
    res = t1.asof_join(
        t2, pw.left.t, pw.right.t, how=pw.JoinMode.LEFT
    ).select(k=0, t=pw.left.t, v=pw.right.w)
    assert _triples(res) == [(0, 5, 9)]


# -- window joins (test_window_joins.py) -------------------------------------


def test_window_join_tumbling_1():  # ref :25, tumbling(1), INNER
    t1 = T(
        """
          | a | t
        0 | 1 | -2
        1 | 2 | 1
        2 | 3 | 2
        3 | 4 | 3
        4 | 5 | 7
        5 | 6 | 13
        """
    )
    t2 = T(
        """
          | b | t
        0 | 1 | 2
        1 | 2 | 5
        2 | 3 | 6
        3 | 4 | 7
        4 | 5 | 14
        """
    )
    res = t1.window_join(
        t2, t1.t, t2.t, pw.temporal.tumbling(1)
    ).select(a=pw.left.a, b=pw.right.b)
    df = pw.debug.table_to_pandas(res)
    got = sorted(map(tuple, df[["a", "b"]].values.tolist()))
    assert got == sorted([(3, 1), (5, 4)])


def test_window_join_tumbling_2():  # ref :25, tumbling(2), INNER
    t1 = T(
        """
          | a | t
        0 | 1 | -2
        1 | 2 | 1
        2 | 3 | 2
        3 | 4 | 3
        4 | 5 | 7
        5 | 6 | 13
        """
    )
    t2 = T(
        """
          | b | t
        0 | 1 | 2
        1 | 2 | 5
        2 | 3 | 6
        3 | 4 | 7
        4 | 5 | 14
        """
    )
    res = t1.window_join(
        t2, t1.t, t2.t, pw.temporal.tumbling(2)
    ).select(a=pw.left.a, b=pw.right.b)
    df = pw.debug.table_to_pandas(res)
    got = sorted(map(tuple, df[["a", "b"]].values.tolist()))
    assert got == sorted([(3, 1), (4, 1), (5, 3), (5, 4)])


def test_window_join_sharded():  # ref :177 (on= equality condition)
    t1 = T(
        """
          | k | a | t
        0 | 0 | 1 | 1
        1 | 0 | 2 | 5
        2 | 1 | 3 | 1
        """
    )
    t2 = T(
        """
          | k | b | t
        0 | 0 | 7 | 1
        1 | 1 | 8 | 1
        2 | 1 | 9 | 5
        """
    )
    res = t1.window_join(
        t2, t1.t, t2.t, pw.temporal.tumbling(2), t1.k == t2.k
    ).select(k=pw.left.k, a=pw.left.a, b=pw.right.b)
    df = pw.debug.table_to_pandas(res)
    got = sorted(map(tuple, df[["k", "a", "b"]].values.tolist()))
    assert got == sorted([(0, 1, 7), (1, 3, 8)])


def test_window_join_left_pads():  # ref :25 LEFT branch shape
    t1 = T(
        """
          | a | t
        0 | 1 | 0
        1 | 2 | 10
        """
    )
    t2 = T(
        """
          | b | t
        0 | 5 | 0
        """
    )
    res = t1.window_join_left(
        t2, t1.t, t2.t, pw.temporal.tumbling(2)
    ).select(a=pw.left.a, b=pw.right.b)
    df = pw.debug.table_to_pandas(res)
    got = sorted(
        (int(a), None if b is None or b != b else int(b))
        for a, b in df[["a", "b"]].values.tolist()
    )
    assert got == [(1, 5), (2, None)]
