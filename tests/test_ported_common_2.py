"""Ported from the reference's behavioral spec: apply / groupby / reducers /
join cases.

Source: ``/root/reference/python/pathway/tests/test_common.py`` (second
block; see ``tests/test_ported_common_1.py`` for the porting contract and
``PORTED_TESTS.md`` for the manifest).
"""

from __future__ import annotations

import functools

import numpy as np
import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.testing import (
    T,
    assert_table_equality,
    assert_table_equality_wo_index,
)


# -- apply (test_common.py:1659-1825) ---------------------------------------


def test_apply():  # ref :1659
    a = T(
        """
        foo
        1
        2
        3
        """
    )

    def inc(x: int) -> int:
        return x + 1

    result = a.select(ret=pw.apply(inc, a.foo))
    assert_table_equality(
        result,
        T(
            """
            ret
            2
            3
            4
            """
        ),
    )


def test_apply_inspect_wrapped_signature():  # ref :1687
    a = T(
        """
        foo
        1
        2
        3
        """
    )

    def decorator(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            return func(*args, **kwargs)

        return wrapper

    @decorator
    def inc(x: int) -> int:
        return x + 1

    result = a.select(ret=pw.apply(inc, a.foo))
    assert_table_equality(
        result,
        T(
            """
            ret
            2
            3
            4
            """
        ),
    )


def test_apply_consts():  # ref :1723
    a = T(
        """
        foo
        1
        2
        3
        """
    )

    def inc(x: int) -> int:
        return x + 1

    result = a.select(ret=pw.apply(inc, 1))
    assert_table_equality(
        result,
        T(
            """
            ret
            2
            2
            2
            """
        ),
    )


def test_apply_more_args():  # ref :1751
    a = T(
        """
        foo
        1
        2
        3
        """
    )
    b = T(
        """
        bar
        2
        -1
        4
        """
    )

    def add(x: int, y: int) -> int:
        return x + y

    result = a.select(ret=pw.apply(add, x=a.foo, y=b.bar))
    assert_table_equality(
        result,
        T(
            """
            ret
            3
            1
            7
            """
        ),
    )


# -- groupby & reducers (test_common.py:2663-3292) ---------------------------


def test_groupby_simplest():  # ref :2663
    left = T(
        """
        pet  |  owner  | age
        dog  | Alice   | 10
        dog  | Bob     | 9
        cat  | Alice   | 8
        dog  | Bob     | 7
        """
    )
    left_res = left.groupby(left.pet).reduce(left.pet)
    assert_table_equality_wo_index(
        left_res,
        T(
            """
            pet
            dog
            cat
            """
        ),
    )


def test_groupby_singlecol():  # ref :2688
    left = T(
        """
        pet  |  owner  | age
        dog  | Alice   | 10
        dog  | Bob     | 9
        cat  | Alice   | 8
        dog  | Bob     | 7
        """
    )
    left_res = left.groupby(left.pet).reduce(
        left.pet, ageagg=pw.reducers.sum(left.age)
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
            pet  | ageagg
            dog  | 26
            cat  | 8
            """
        ),
    )


def test_groupby_int_sum():  # ref :2713
    left = T(
        """
        owner   | val
        Alice   | 1
        Alice   | -1
        Bob     | 0
        Bob     | 0
        Charlie | 1
        Charlie | 0
        Dee     | 5
        Dee     | 5
        """
    )
    left_res = left.groupby(left.owner).reduce(
        left.owner, val=pw.reducers.sum(left.val)
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
            owner   | val
            Alice   | 0
            Bob     | 0
            Charlie | 1
            Dee     | 10
            """
        ),
    )


def test_groupby_filter_singlecol():  # ref :2746
    left = T(
        """
        pet  |  owner  | age
        dog  | Alice   | 10
        dog  | Bob     | 9
        cat  | Alice   | 8
        dog  | Bob     | 7
        cat  | Alice   | 6
        dog  | Bob     | 5
        """
    )
    left_res = (
        left.filter(left.age > 6)
        .groupby(pw.this.pet)
        .reduce(pw.this.pet, ageagg=pw.reducers.sum(pw.this.age))
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
            pet  | ageagg
            dog  | 26
            cat  | 8
            """
        ),
    )


def test_groupby_reducer_on_expression():  # ref :2829
    left = T(
        """
        pet  |  owner  | age
        dog  | Alice   | 10
        dog  | Bob     | 9
        cat  | Alice   | 8
        dog  | Bob     | 7
        """
    )
    left_res = left.groupby(left.pet).reduce(
        left.pet, ageagg=pw.reducers.sum(left.age + left.age)
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
            pet  | ageagg
            dog  | 52
            cat  | 16
            """
        ),
    )


def test_groupby_expression_on_reducers():  # ref :2856
    left = T(
        """
        pet  |  owner  | age
        dog  | Alice   | 10
        dog  | Bob     | 9
        cat  | Alice   | 8
        dog  | Bob     | 7
        """
    )
    left_res = left.groupby(left.pet).reduce(
        left.pet, ageagg=pw.reducers.sum(left.age) + pw.reducers.count()
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
            pet  | ageagg
            dog  | 29
            cat  | 9
            """
        ),
    )


def test_groupby_mutlicol():  # ref :2905
    left = T(
        """
        pet  |  owner  | age
        dog  | Alice   | 10
        dog  | Bob     | 9
        cat  | Alice   | 8
        dog  | Alice   | 7
        """
    )
    left_res = left.groupby(left.pet, left.owner).reduce(
        left.pet, left.owner, ageagg=pw.reducers.sum(left.age)
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
            pet | owner | ageagg
            dog | Alice | 17
            dog | Bob   | 9
            cat | Alice | 8
            """
        ),
    )


def test_avg_reducer():  # ref :3113
    t1 = T(
        """
        owner   | age
        Alice   | 10
        Bob     | 5
        Alice   | 20
        Bob     | 10
        """
    )
    res = t1.groupby(pw.this.owner).reduce(
        pw.this.owner, avg=pw.reducers.avg(pw.this.age)
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            owner  | avg
            Alice  | 15
            Bob    | 7.5
            """
        ),
    )


def test_earliest_and_latest_reducer():  # ref :3239
    t = T(
        """
        t | v | __time__
        1 | 1 |     2
        2 | 2 |     2
        1 | 3 |     4
        2 | 4 |     6
        1 | 5 |     8
        """
    )
    res = t.groupby(pw.this.t).reduce(
        pw.this.t,
        earliest=pw.reducers.earliest(pw.this.v),
        latest=pw.reducers.latest(pw.this.v),
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            t | earliest | latest
            1 | 1        | 5
            2 | 2        | 4
            """
        ),
    )


# -- joins (test_common.py:1994-2390) ----------------------------------------


def test_join():  # ref :2111
    t1 = T(
        """
            | pet | owner | age
        1   |   1 | Alice |  10
        2   |   1 |   Bob |   9
        3   |   2 | Alice |   8
        """
    )
    t2 = T(
        """
            | pet | owner | age | size
        11  |   3 | Alice |  10 |    M
        12  |   1 |   Bob |   9 |    L
        13  |   1 |   Tom |   8 |   XL
        """
    )
    res = t1.join(t2, t1.pet == t2.pet, t1.owner == t2.owner).select(
        owner_name=t2.owner, age=t1.age
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            owner_name | age
            Bob        |   9
            """
        ),
    )


def test_join_default():  # ref :2246
    t1 = T(
        """
            | pet | owner | age
        1   |   1 | Alice |  10
        2   |   1 |   Bob |   9
        3   |   2 | Alice |   8
        """
    )
    t2 = T(
        """
            | pet | owner | age | size
        11  |   3 | Alice |  10 |    M
        12  |   1 |   Bob |   9 |    L
        13  |   1 |   Tom |   8 |   XL
        """
    )
    res = t1.join(t2, t1.pet == t2.pet).select(
        owner_name=t2.owner, age=t1.age
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
            owner_name  | age
            Bob         | 10
            Tom         | 10
            Bob         |  9
            Tom         |  9
            """
        ),
    )


def test_join_self():  # ref :2282
    inp = T(
        """
        foo   | bar
        1     | 1
        1     | 2
        1     | 3
        """
    )
    with pytest.raises(Exception):
        res = inp.join(inp, inp.foo == inp.bar)
        pw.debug.table_to_pandas(res.select(x=pw.left.foo))


def test_join_select_no_columns():  # ref :2295
    left = T(
        """
           | a
        1  | 1
        2  | 2
        """
    )
    right = T(
        """
           | b
        1  | foo
        2  | bar
        """
    )
    ret = left.join(right, left.id == right.id).select().select(col=42)
    assert_table_equality_wo_index(
        ret,
        T(
            """
                | col
            1   | 42
            2   | 42
            """
        ),
    )


def test_cross_join():  # ref :2324
    t1 = T(
        """
            | v
        1   | 1
        2   | 2
        """
    )
    t2 = T(
        """
            | w
        11  | a
        12  | b
        """
    )
    res = t1.join(t2).select(pw.left.v, pw.right.w)
    assert sorted(
        map(tuple, pw.debug.table_to_pandas(res)[["v", "w"]].values.tolist())
    ) == [(1, "a"), (1, "b"), (2, "a"), (2, "b")]


def test_empty_join():  # ref :1994
    left = T(
        """
        a | b
        1 | x
        """
    )
    right = T(
        """
        c | d
        2 | y
        """
    )
    res = left.join(right, left.a == right.c).select(left.b, right.d)
    assert len(pw.debug.table_to_pandas(res)) == 0


# -- ix (test_common.py:2390-2662) -------------------------------------------


def test_ix():  # ref :2390
    t_animals = T(
        """
          | epithet    | genus
        1 | upupa      | epops
        2 | acherontia | atropos
        3 | bubo       | scandiacus
        4 | dynastes   | hercules
        """
    )
    t_birds = T(
        """
          | desc
        2 | hoopoe
        4 | owl
        """
    )
    ret = t_birds.select(
        t_birds.desc, latin=t_animals.ix(t_birds.id).genus
    )
    assert_table_equality(
        ret,
        T(
            """
              | desc   | latin
            2 | hoopoe | atropos
            4 | owl    | hercules
            """
        ),
    )


def test_ix_missing_key():  # ref :2480
    t = T(
        """
          | v
        1 | a
        """
    )
    q = T(
        """
          | p
        1 | 5
        """
    )
    ptr = q.select(p=t.pointer_from(q.p))
    with pytest.raises(Exception):
        res = t.ix(ptr.p, context=ptr).select(pw.this.v)
        pw.debug.table_to_pandas(res)


def test_groupby_ix_this():  # ref :2635
    # argmin + row lookup. IDIOM DELTA (PORTED_TESTS.md): the reference's
    # in-reduce `table.ix(argmin, context=pw.this)` is expressed here as the
    # equivalent two-phase reduce-then-ix over the argmin pointer.
    table = T(
        """
        name    | age
        Charlie | 18
        Alice   | 18
        Bob     | 18
        David   | 19
        Erin    | 19
        Frank   | 20
        """
    )
    red = table.groupby(table.age).reduce(
        table.age, lo=pw.reducers.argmin(table.age)
    )
    res = red.select(red.age, min_name=table.ix(red.lo).name)
    df = pw.debug.table_to_pandas(res).sort_values("age")
    assert df["age"].tolist() == [18, 19, 20]
    assert df["min_name"].tolist()[2] == "Frank"
    assert set(df["min_name"].tolist()) <= {
        "Charlie", "Alice", "Bob", "David", "Erin", "Frank"
    }


# -- r4 review regressions ---------------------------------------------------


def test_strict_ix_tolerates_late_arriving_indexed_row():
    # a probe arriving a commit BEFORE its indexed row must not crash the
    # stream; the strict missing-key check fires only at end-of-stream
    from pathway_tpu.internals.parse_graph import G as _G

    _G.clear()

    class Dims(pw.io.python.ConnectorSubject):
        def run(self):
            import time as _t

            _t.sleep(0.15)  # dim row arrives AFTER the probe's commit
            self.next(k="a", v=1)
            self.commit()

    class Probes(pw.io.python.ConnectorSubject):
        def run(self):
            self.next(k="a")
            self.commit()

    dims_raw = pw.io.python.read(
        Dims(), schema=pw.schema_from_types(k=str, v=int),
        autocommit_duration_ms=None,
    )
    dims = dims_raw.with_id_from(pw.this.k)
    probes = pw.io.python.read(
        Probes(), schema=pw.schema_from_types(k=str),
        autocommit_duration_ms=None,
    )
    ptr = probes.select(p=dims.pointer_from(probes.k))
    res = dims.ix(ptr.p, context=ptr).select(pw.this.v)
    got = []
    pw.io.subscribe(
        res, on_change=lambda key, row, time, is_addition: got.append(row["v"])
    )
    pw.run()
    assert got == [1]


def test_strict_ix_raises_at_stream_end_for_missing_key():
    t = T(
        """
          | v
        1 | a
        """
    )
    q = T(
        """
          | p
        1 | 5
        """
    )
    ptr = q.select(p=t.pointer_from(q.p))
    with pytest.raises(KeyError):
        res = t.ix(ptr.p, context=ptr).select(pw.this.v)
        pw.debug.table_to_pandas(res)


def test_apply_is_none_branch_not_lifted():
    # `a is None` folds to False on the expression placeholder with no
    # blocked call — the bytecode gate must reject identity tests so the
    # None branch executes per row
    t = pw.debug.table_from_rows(
        pw.schema_from_types(a=int), [(None,), (5,)]
    )
    res = t.select(
        c=pw.apply_with_type(lambda a: 0 if a is None else a, int, pw.this.a)
    )
    assert sorted(pw.debug.table_to_pandas(res)["c"].tolist()) == [0, 5]


def test_update_types_does_not_cast_values():
    t = pw.debug.table_from_rows(pw.schema_from_types(a=int), [(1,), (2,)])
    res = t.update_types(a=float)
    vals = sorted(pw.debug.table_to_pandas(res)["a"].tolist())
    assert vals == [1, 2]  # values untouched; only the declared type moved
    assert "FLOAT" in repr(res.schema.dtypes()["a"]).upper() or str(
        res.schema.dtypes()["a"]
    ).lower().find("float") >= 0


def test_join_select_left_wildcard_without():
    a = T(
        """
        k | x | y
        1 | 2 | 3
        """
    )
    b = T(
        """
        k | z
        1 | 9
        """
    )
    res = a.join(b, a.k == b.k).select(*pw.left.without(pw.left.x), b.z)
    df = pw.debug.table_to_pandas(res)
    assert sorted(df.columns.tolist()) == ["k", "y", "z"]
    assert df[["k", "y", "z"]].values.tolist() == [[1, 3, 9]]
