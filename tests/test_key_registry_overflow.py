"""Key registry past PATHWAY_KEY_REGISTRY_CAP (ISSUE 8 tentpole a).

Scaled-down cap: the two-tier registry must keep FULL 128-bit conflation
detection through the spilled cold tier, refuse loudly when no spill path
is configured, and freeze open ONLY under the explicit
``PATHWAY_KEY_REGISTRY_OVERFLOW=allow`` escape hatch — for both the
native C and the pure-python hot tables.
"""

from __future__ import annotations

import numpy as np
import pytest

import pathway_tpu.engine.keys as K
from pathway_tpu.native import native_available


def _fresh_registry(monkeypatch, tmp_path, cap, *, overflow=None,
                    spill=True, force_py=False):
    monkeypatch.setattr(K, "_REGISTRY", None)
    monkeypatch.setenv("PATHWAY_KEY_REGISTRY_CAP", str(cap))
    if overflow is not None:
        monkeypatch.setenv("PATHWAY_KEY_REGISTRY_OVERFLOW", overflow)
    else:
        monkeypatch.delenv("PATHWAY_KEY_REGISTRY_OVERFLOW", raising=False)
    if spill:
        monkeypatch.setenv(
            "PATHWAY_KEY_REGISTRY_SPILL_DIR", str(tmp_path / "kreg")
        )
    else:
        monkeypatch.delenv("PATHWAY_KEY_REGISTRY_SPILL_DIR", raising=False)
        monkeypatch.delenv("PATHWAY_STATE_SPILL_DIR", raising=False)
    if force_py:
        import pathway_tpu.native as native_mod

        monkeypatch.setattr(native_mod, "_cached", None)
        monkeypatch.setattr(native_mod, "_tried", True)
    reg = K._get_registry()
    assert isinstance(reg, K._TwoTierRegistry)
    if force_py:
        assert isinstance(reg._hot, K._PyKeyRegistry)
    return reg


def _pairs(start, n):
    lo = np.arange(start, start + n, dtype=np.uint64)
    hi = lo + np.uint64(10_000_000)
    return lo, hi


_BOTH = pytest.mark.parametrize(
    "force_py",
    [
        pytest.param(True, id="python"),
        pytest.param(
            False,
            id="native",
            marks=pytest.mark.skipif(
                not native_available(), reason="no C compiler for native.c"
            ),
        ),
    ],
)


@_BOTH
def test_detection_survives_past_cap_via_cold_tier(
    monkeypatch, tmp_path, force_py
):
    cap = 64
    reg = _fresh_registry(monkeypatch, tmp_path, cap, force_py=force_py)
    lo, hi = _pairs(0, 1000)  # ~16x the cap
    assert reg.register(lo, hi) == -1
    st = reg.detailed_stats()
    assert st["entries"] == 1000
    assert st["cold_entries"] > 0
    assert st["spilled_total"] == st["cold_entries"]
    assert st["mode"] == "spill"
    assert st["frozen"] == 0  # spill mode is NOT a frozen registry

    # re-registering the same pairs (replay) is clean — hot AND cold
    assert reg.register(lo, hi) == -1

    # a forged conflation against a COLD key (same LO, different HI)
    # must be detected, exactly as it would below the cap
    cold_lo = np.array([900], dtype=np.uint64)
    assert reg.register(cold_lo, cold_lo + np.uint64(1)) == 0
    # ... and against a hot key too
    hot_lo = np.array([1], dtype=np.uint64)
    assert reg.register(hot_lo, hot_lo) == 0


@_BOTH
def test_cold_tier_detects_after_writeback_flush(
    monkeypatch, tmp_path, force_py
):
    cap = 32
    reg = _fresh_registry(monkeypatch, tmp_path, cap, force_py=force_py)
    lo, hi = _pairs(0, 400)
    assert reg.register(lo, hi) == -1
    # force the write-behind batches to disk, then drop the bucket cache:
    # probes must come back from the spilled files, not resident dicts
    cold = reg._cold
    assert cold is not None
    cold.flush()
    assert cold._pending_n == 0
    cold._cache.clear()
    assert reg.register(lo, hi) == -1  # replay reads disk buckets
    bad = np.array([399], dtype=np.uint64)
    assert reg.register(bad, bad) == 0  # conflation via a disk bucket


@_BOTH
def test_cap_hit_without_spill_path_is_a_hard_error(
    monkeypatch, tmp_path, force_py
):
    reg = _fresh_registry(
        monkeypatch, tmp_path, 16, spill=False, force_py=force_py
    )
    lo, hi = _pairs(0, 16)
    assert reg.register(lo, hi) == -1
    over_lo, over_hi = _pairs(100, 8)
    with pytest.raises(K.KeyRegistryOverflowError, match="OVERFLOW=allow"):
        reg.register(over_lo, over_hi)
    # keys already registered keep working after the refusal
    assert reg.register(lo, hi) == -1


@_BOTH
def test_overflow_allow_restores_freeze_open_loudly(
    monkeypatch, tmp_path, force_py, caplog
):
    import logging

    reg = _fresh_registry(
        monkeypatch, tmp_path, 16, overflow="allow", spill=False,
        force_py=force_py,
    )
    lo, hi = _pairs(0, 16)
    assert reg.register(lo, hi) == -1
    with caplog.at_level(logging.WARNING, logger="pathway_tpu.keys"):
        over_lo, over_hi = _pairs(100, 8)
        assert reg.register(over_lo, over_hi) == -1  # passes unchecked
    assert any("FROZEN" in r.message for r in caplog.records)
    st = reg.detailed_stats()
    assert st["frozen"] == 1
    assert st["mode"] == "allow"
    # frozen-open: a conflation among NEW keys is NOT detected (the
    # documented 64-bit degradation the operator explicitly accepted)...
    assert reg.register(over_lo, over_hi + np.uint64(1)) == -1
    # ...but the registered prefix still detects
    assert reg.register(lo[:1], hi[:1] + np.uint64(1)) == 0


@_BOTH
def test_explicit_error_mode_beats_configured_spill_dir(
    monkeypatch, tmp_path, force_py
):
    reg = _fresh_registry(
        monkeypatch, tmp_path, 16, overflow="error", force_py=force_py
    )
    lo, hi = _pairs(0, 24)
    with pytest.raises(K.KeyRegistryOverflowError):
        reg.register(lo, hi)


def test_register_keys_entry_point_spills(monkeypatch, tmp_path):
    """The real `_register_keys` path (mix_columns & co) rides the
    two-tier registry transparently."""
    _fresh_registry(monkeypatch, tmp_path, 32)
    lo, hi = _pairs(0, 200)
    K._register_keys(lo, hi)  # no error
    with pytest.raises(K.KeyCollisionError):
        K._register_keys(
            np.array([150], np.uint64), np.array([3], np.uint64)
        )
    st = K.registry_stats()
    assert st["entries"] == 200
    assert st["cold_entries"] > 0


def test_cap_hit_emits_flight_recorder_event(monkeypatch, tmp_path):
    from pathway_tpu.observability import flightrecorder as fr

    monkeypatch.setenv("PATHWAY_FLIGHT_DIR", str(tmp_path / "flight"))
    try:
        reg = _fresh_registry(monkeypatch, tmp_path, 16)
        lo, hi = _pairs(0, 64)
        assert reg.register(lo, hi) == -1
        rec = fr.get_recorder()
        assert rec is not None
        rec.close()
        doc = fr.harvest(rec.path)
        hits = [r for r in doc["records"] if r["kind"] == "keyreg.cap_hit"]
        assert hits and hits[0]["mode"] == "spill"
        assert hits[0]["cap"] == 16
    finally:
        if fr._active is not None:
            fr._active.close()
        fr._active = None
        fr._env_sig = None


def test_registry_stats_unarmed_is_cheap(monkeypatch):
    monkeypatch.setattr(K, "_REGISTRY", None)
    st = K.registry_stats()
    assert st["mode"] == "unarmed"
    assert K._REGISTRY is None  # stats did not instantiate the registry
