"""Ported from the reference's interval-join boundary suite.

Source: ``/root/reference/python/pathway/tests/temporal/test_interval_joins.py``
(VERDICT r4 item 7). Porting contract as in ``tests/test_ported_common_1.py``;
manifest in ``PORTED_TESTS.md``.
"""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.temporal import interval
from pathway_tpu.testing import T, assert_table_equality_wo_index


def _t1():
    return T(
        """
          | a | t
        0 | 1 | -1
        1 | 2 | 0
        2 | 3 | 2
        3 | 4 | 3
        4 | 5 | 7
        5 | 6 | 13
        """
    )


def _t2():
    return T(
        """
          | b | t
        0 | 1 | 2
        1 | 2 | 5
        2 | 3 | 6
        3 | 4 | 10
        4 | 5 | 15
        """
    )


def _pairs(res):
    df = pw.debug.table_to_pandas(res)
    out = [
        (None if v is None or v != v else int(v),
         None if w is None or w != w else int(w))
        for v, w in df[["a", "b"]].values.tolist()
    ]
    return sorted(out, key=repr)


def _sorted(pairs):
    return sorted(pairs, key=repr)


# ref :21 test_interval_join_time_only, max_time_difference=1
def test_interval_join_inner_pm1():
    res = _t1().interval_join_inner(
        _t2(), pw.left.t, pw.right.t, interval(-1, 1)
    ).select(pw.left.a, pw.right.b)
    assert _pairs(res) == _sorted([(3, 1), (4, 1), (5, 3)])


def test_interval_join_left_pm1():  # ref :21 LEFT branch
    res = _t1().interval_join_left(
        _t2(), pw.left.t, pw.right.t, interval(-1, 1)
    ).select(pw.left.a, pw.right.b)
    assert _pairs(res) == _sorted(
        [(3, 1), (4, 1), (5, 3), (1, None), (2, None), (6, None)]
    )


def test_interval_join_right_pm1():  # ref :21 RIGHT branch
    res = _t1().interval_join_right(
        _t2(), pw.left.t, pw.right.t, interval(-1, 1)
    ).select(pw.left.a, pw.right.b)
    assert _pairs(res) == _sorted(
        [(3, 1), (4, 1), (5, 3), (None, 2), (None, 4), (None, 5)]
    )


def test_interval_join_outer_pm1():  # ref :21 OUTER branch
    res = _t1().interval_join_outer(
        _t2(), pw.left.t, pw.right.t, interval(-1, 1)
    ).select(pw.left.a, pw.right.b)
    assert _pairs(res) == _sorted(
        [(3, 1), (4, 1), (5, 3),
         (1, None), (2, None), (6, None),
         (None, 2), (None, 4), (None, 5)]
    )


def test_interval_join_inner_pm2():  # ref :21, max_time_difference=2
    res = _t1().interval_join_inner(
        _t2(), pw.left.t, pw.right.t, interval(-2, 2)
    ).select(pw.left.a, pw.right.b)
    assert _pairs(res) == _sorted(
        [(2, 1), (3, 1), (4, 1), (4, 2), (5, 2), (5, 3), (6, 5)]
    )


def test_interval_join_empty_interval():  # ref :148
    # interval(0, 0): only exact time matches
    t1 = T(
        """
          | a | t
        0 | 1 | 1
        1 | 2 | 5
        2 | 3 | 7
        """
    )
    t2 = T(
        """
          | b | t
        0 | 1 | 1
        1 | 2 | 6
        2 | 3 | 7
        """
    )
    res = t1.interval_join_inner(
        t2, pw.left.t, pw.right.t, interval(0, 0)
    ).select(pw.left.a, pw.right.b)
    assert _pairs(res) == _sorted([(1, 1), (3, 3)])


def test_interval_join_empty_interval_shifted():  # ref :217
    # interval(1, 1): right exactly 1 later
    t1 = T(
        """
          | a | t
        0 | 1 | 1
        1 | 2 | 5
        2 | 3 | 7
        """
    )
    t2 = T(
        """
          | b | t
        0 | 1 | 2
        1 | 2 | 5
        2 | 3 | 8
        """
    )
    res = t1.interval_join_inner(
        t2, pw.left.t, pw.right.t, interval(1, 1)
    ).select(pw.left.a, pw.right.b)
    assert _pairs(res) == _sorted([(1, 1), (3, 3)])


def test_interval_join_negative_time_errors():  # ref :286
    # lower_bound > upper_bound is refused at build time
    with pytest.raises(ValueError):
        _t1().interval_join_inner(
            _t2(), pw.left.t, pw.right.t, interval(2, -2)
        )


def test_interval_join_non_symmetric():  # ref :335, bounds=(-2, 0)
    res = _t1().interval_join_inner(
        _t2(), pw.left.t, pw.right.t, interval(-2, 0)
    ).select(pw.left.a, pw.right.b)
    # pairs with t_right in [t_left-2, t_left] (reference :359 filter)
    assert _pairs(res) == _sorted([(3, 1), (4, 1), (5, 2), (5, 3)])


def test_interval_join_float():  # ref :619, max_time_difference=0.7
    t1 = T(
        """
          | a | t
        0 | 1 | 0.0
        1 | 2 | 3.0
        """
    )
    t2 = T(
        """
          | b | t
        0 | 1 | 0.5
        1 | 2 | 2.0
        2 | 3 | 3.6
        """
    )
    res = t1.interval_join_inner(
        t2, pw.left.t, pw.right.t, interval(-0.7, 0.7)
    ).select(pw.left.a, pw.right.b)
    assert _pairs(res) == _sorted([(1, 1), (2, 3)])


def test_interval_join_sharded():  # ref :392 (on= equality condition)
    t1 = T(
        """
          | k | a | t
        0 | 0 | 1 | 2
        1 | 0 | 2 | 7
        2 | 1 | 3 | 2
        """
    )
    t2 = T(
        """
          | k | b | t
        0 | 0 | 1 | 2
        1 | 1 | 2 | 2
        2 | 1 | 3 | 8
        """
    )
    res = t1.interval_join_inner(
        t2, pw.left.t, pw.right.t, interval(-1, 1), pw.left.k == pw.right.k
    ).select(pw.left.k, pw.left.a, pw.right.b)
    df = pw.debug.table_to_pandas(res)
    got = sorted(map(tuple, df[["k", "a", "b"]].values.tolist()))
    assert got == sorted([(0, 1, 1), (1, 3, 2)])


def test_interval_join_expressions():  # ref :902
    # non-time expressions in select over the joined pair
    t1 = T(
        """
          | a | t
        0 | 2 | 1
        1 | 4 | 5
        """
    )
    t2 = T(
        """
          | b | t
        0 | 3 | 1
        1 | 5 | 5
        """
    )
    res = t1.interval_join_inner(
        t2, pw.left.t, pw.right.t, interval(0, 0)
    ).select(s=pw.left.a + pw.right.b, p=pw.left.a * pw.right.b)
    df = pw.debug.table_to_pandas(res)
    assert sorted(map(tuple, df[["s", "p"]].values.tolist())) == [
        (5, 6), (9, 20)
    ]


def test_interval_join_coalesce():  # ref :1049
    t1 = T(
        """
          | a | t
        0 | 1 | 1
        1 | 2 | 7
        """
    )
    t2 = T(
        """
          | b | t
        0 | 8 | 1
        """
    )
    res = t1.interval_join_left(
        t2, pw.left.t, pw.right.t, interval(0, 0)
    ).select(
        pw.left.a,
        v=pw.coalesce(pw.right.b, -1),
    )
    df = pw.debug.table_to_pandas(res)
    assert sorted(map(tuple, df[["a", "v"]].values.tolist())) == [
        (1, 8), (2, -1)
    ]


def test_non_overlapping_times():  # ref :727
    t1 = T(
        """
          | a | t
        0 | 1 | 0
        """
    )
    t2 = T(
        """
          | b | t
        0 | 1 | 100
        """
    )
    inner = t1.interval_join_inner(
        t2, pw.left.t, pw.right.t, interval(-1, 1)
    ).select(pw.left.a, pw.right.b)
    assert len(pw.debug.table_to_pandas(inner)) == 0
    outer = t1.interval_join_outer(
        t2, pw.left.t, pw.right.t, interval(-1, 1)
    ).select(pw.left.a, pw.right.b)
    assert _pairs(outer) == _sorted([(1, None), (None, 1)])
